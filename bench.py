"""Benchmark suite: one JSON line per BASELINE.md measurement config, on one
TPU chip.

Configs (BASELINE.md "measurement configs"):
  - llama_420m  : Llama decoder pretraining, seq 2048, bf16, flash attention
                  (the round-2 headline metric; keep MFU >= 0.507)
  - resnet50    : ImageNet-shape conv training, images/sec
  - bert_base   : MLM+NSP pretraining step, seq 512, DP-shape attention
  - qwen2_moe   : sparse MoE decoder step (grouped-GEMM dispatch, one chip)
  - lenet_mnist : BASELINE config 1, single-device correctness reference
                  (correctness-only metric: step time sits below the relay
                  jitter floor, so img/s is noise on this rig)
  - llama8b_shape: 2 Llama-3-8B-config decoder layers + 128k-vocab fused CE,
                  seq 4096 bf16 remat — north-star-shape MFU on one chip
  - llama_decode: serving decode — compiled prefill + one-program lax.scan
                  token loop; steady-state decode tokens/s at batch 1 and 8
  - llama_longctx: the flagship at seq 16384 with remat — long-context;
                  10-step windows (extra.iters) since each step is ~0.8 s
  - llama_longctx_32k (OPT-IN, run by name): same at seq 32768
  - llama_decode_int8 / llama_serving_int8: the quantized-serving arms —
                  int8 KV cache + int8 weight streaming (SERVING.md
                  "Quantized KV & weights"); MBU against *necessary* int8
                  bytes, bytes_ratio_vs_bf16 is the bandwidth headroom

Each line: {"metric", "value", "unit", "vs_baseline", "extra"}. The primary
(first) line is llama_420m — vs_baseline remains MFU/0.40 against the
BASELINE.json north-star target. Other configs report their own MFU-based
vs_baseline against the same 0.40 target (BASELINE.md publishes no absolute
reference numbers — "to measure").

Protocol (round 4): every config is fed THROUGH its input pipeline inside
the timed loop (llama: native pack_sequences over variable-length docs;
others: DataLoader over synthetic datasets) and timed over 3 windows of 30
steps; extra carries {pipeline, runs, spread}. 30-step windows amortize
the relay's fixed ~100 ms sync round-trip to ~3 ms/step (10-step windows
read ~7% slow on fast configs). Device batches are pre-staged and cycled
because the relay moves ~12 MB/s (see _time_windows docstring).

Chip peak FLOP/s is detected from device_kind (VERDICT r2: was hardcoded
v5e); unknown kinds fall back to v5e with a note in extra.

Pass config names as argv to run a subset: `python bench.py llama_420m`.

Driver contract: the LAST stdout line is always one JSON object
``{"bench_summary": {config: {value, mfu, spread}}}`` covering every
selected config (value null for failed ones) — emitted before the
failure SystemExit so a partial run still reports what it measured.
``--dry`` skips all device work (and the jax import) and emits only the
summary skeleton; the CI smoke test asserts the contract against it.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

# nominal bf16 dense peak FLOP/s by TPU generation (public spec sheets)
_PEAKS = {
    "v4": 275e12,
    "v5e": 197e12, "v5litepod": 197e12, "v5lite": 197e12,
    "v5p": 459e12,
    "v6e": 918e12, "trillium": 918e12,
}


def _detect_peak(dev) -> tuple[float, str]:
    kind = getattr(dev, "device_kind", "").lower().replace(" ", "")
    for key, peak in _PEAKS.items():
        if key in kind:
            return peak, key
    return 197e12, f"unknown({kind})->v5e-fallback"


_RUNS = 3  # timed windows per config (reported in extra.runs)

# latency SLOs the serving configs score goodput_at_slo against
# (SERVING.md "Tracing & SLOs"): requests/s that finished normally AND
# met both budgets — TTFT from arrival, p99 of the request's own
# inter-token gaps. The prefix config gets the tighter TTFT budget its
# cache exists to deliver.
_SERVING_SLOS = {
    "llama_serving": {"ttft_p99_s": 2.0, "itl_p99_s": 0.25},
    "llama_serving_prefix": {"ttft_p99_s": 1.0, "itl_p99_s": 0.25},
    # int8 arm: same workload and SLOs as llama_serving — quantization
    # must not be allowed to hide behind looser targets
    "llama_serving_int8": {"ttft_p99_s": 2.0, "itl_p99_s": 0.25},
    # fleet arm: a replica is killed mid-run, so failed-over requests
    # pay re-prefill + replay inside one inter-token gap — the looser
    # ITL budget is the failover price the SLO explicitly allows
    "llama_serving_fleet": {"ttft_p99_s": 2.0, "itl_p99_s": 1.0},
    # failover A/B (full vs bounded replay): same kill, same budgets as
    # the fleet arm — snapshots must win on replay work, not on SLOs
    "llama_serving_failover": {"ttft_p99_s": 2.0, "itl_p99_s": 1.0},
    # partition A/B (clean vs lossy wire): retransmissions and a healed
    # partition stretch inter-token gaps — the fleet ITL budget prices
    # the lease ejection + replay, same as any other failover
    "llama_serving_partition": {"ttft_p99_s": 2.0, "itl_p99_s": 1.0},
    # multi-host A/B (loopback vs real localhost TCP): the socket wire
    # adds a per-step frame round-trip to every inter-token gap — the
    # fleet ITL budget prices it, and both arms score against the same
    # targets so the framing overhead shows up in goodput, not excuses
    "llama_serving_multihost": {"ttft_p99_s": 2.0, "itl_p99_s": 1.0},
    # chunked-prefill A/B: long prompts land mid-decode, so the OFF
    # arm's itl_p99 carries the head-of-line stall chunking removes; a
    # tight ITL SLO makes goodput_at_slo sensitive to exactly that
    "llama_serving_chunked": {"ttft_p99_s": 4.0, "itl_p99_s": 0.25},
    # speculative arm: same workload/SLOs as llama_serving — drafting
    # must not be allowed to trade latency SLOs for throughput. itl is
    # per-EMITTED-token, so accepted multi-token steps help, not hurt
    "llama_serving_spec": {"ttft_p99_s": 2.0, "itl_p99_s": 0.25},
    # tiered arm: prefix-cache SLOs — the host tier's job is to keep
    # the hit path (and its TTFT) alive under pool pressure
    "llama_serving_tiered": {"ttft_p99_s": 1.0, "itl_p99_s": 0.25},
    # overload A/B: generous TTFT bound (the trace deliberately floods
    # the queue — what matters is the COLD tenants' p99 against it and
    # the goodput delta between the FCFS and fair+brownout arms)
    "llama_serving_fairness": {"ttft_p99_s": 4.0, "itl_p99_s": 0.5},
    # tensor-parallel A/B: same workload and SLOs as llama_serving —
    # the mesh must not hide behind looser targets; both arms report
    # goodput against the identical budget
    "llama_serving_tp": {"ttft_p99_s": 2.0, "itl_p99_s": 0.25},
    # pp arm: same workload and SLOs as llama_serving_tp — staging the
    # decoder must not be allowed to hide behind looser targets
    "llama_serving_pp": {"ttft_p99_s": 2.0, "itl_p99_s": 0.25},
    # disaggregated prefill/decode A/B: the long-prompt trace makes
    # TTFT prefill-dominated (chunked 10x prompts take seconds on the
    # bench chip), so the TTFT budget is generous — the SLO that the
    # split exists to protect is ITL: decode replicas never run prefill
    # chunks, so inter-token gaps must stay flat as prompts grow
    "llama_serving_disagg": {"ttft_p99_s": 8.0, "itl_p99_s": 1.0},
    # multi-tenant LoRA arm: same workload and SLOs as llama_serving —
    # paging adapters through the slot pool must not hide behind looser
    # targets; the A/B vs the single-adapter arm prices the churn
    "llama_serving_lora": {"ttft_p99_s": 2.0, "itl_p99_s": 0.25},
}


def _time_windows(step_fn, feed, iters=30, runs=_RUNS):
    """Median step time over `runs` timed windows of `iters` steps, the
    input pipeline IN the measured loop: every step calls ``feed()``, which
    performs the host-side pipeline work (DataLoader iteration / sequence
    packing) and returns the device batch for the step (VERDICT r3 missing
    #6 — one repeated in-memory batch hides host-bound regressions).

    Device feeds cycle a small set of PRE-STAGED device batches instead of
    shipping each host batch: this bench chip sits behind a relay that
    moves ~12 MB/s (measured), vs GB/s host-to-HBM on a production TPU
    host — per-step transfer here would time the tunnel, not the
    framework. Host pipeline cost lands in the window the way it does in
    production: llama's pack_sequences runs serially per step; the
    DataLoader configs pop the buffer-reader thread's queue, so their
    host cost only shows when the pipeline cannot keep up with the
    device step (queue starvation).

    Returns (median_dt, spread, last_loss) with spread = (max-min)/median
    over the window means.
    """
    loss = step_fn(*feed())
    _ = float(np.asarray(loss).ravel()[0])  # compile + warmup
    times = []
    for _ in range(runs):
        t0 = time.perf_counter()
        for _ in range(iters):
            loss = step_fn(*feed())
        lossv = float(np.asarray(loss).ravel()[0])
        times.append((time.perf_counter() - t0) / iters)
    assert np.isfinite(lossv), lossv
    med = sorted(times)[len(times) // 2]
    spread = (max(times) - min(times)) / med
    return med, spread, lossv


def _staged_feed(host_iter, staged):
    """feed() closure: drive the host pipeline one batch per call, return
    the next staged device batch (see _time_windows on why transfer is
    staged). ``feed.close()`` releases the pipeline (drains an in-flight
    DataLoader epoch so its prefetcher thread exits instead of pinning the
    dataset in memory for the rest of the multi-config bench process)."""
    it = iter(host_iter)
    k = [0]

    def feed():
        next(it)  # host pipeline work, in the timed loop
        k[0] += 1
        return staged[k[0] % len(staged)]

    def close():
        for obj in (host_iter, it):
            if hasattr(obj, "close"):
                obj.close()
                break
    feed.close = close
    return feed


class _LoaderCycle:
    """Endless epochs over a DataLoader. The loader's buffer-reader thread
    has no stop signal — it runs until its epoch drains — so close()
    consumes the in-flight epoch's tail to let the thread exit."""

    def __init__(self, loader):
        self.loader = loader
        self.it = iter(loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.it)
        except StopIteration:
            self.it = iter(self.loader)
            return next(self.it)

    def close(self):
        for _ in self.it:
            pass


class _SynthImages:
    """Pre-generated image shards served as whole batches (IterableDataset
    protocol): one vectorized fancy-index per batch instead of 128
    per-item copies + stack — per-item collate of 77 MB fp32 batches
    cannot keep up with a ~60 ms device step (the 30-step windows surfaced
    exactly that host-bound starvation), while production image pipelines
    read pre-batched/pre-decoded shards at memcpy speed."""

    def __init__(self, n, batch, batches_per_epoch=64):
        r = np.random.default_rng(1)
        self.x = r.standard_normal((n, 3, 224, 224)).astype(np.float32)
        self.y = r.integers(0, 1000, (n,)).astype(np.int64)
        self.batch = batch
        self.batches_per_epoch = batches_per_epoch
        self._rng = np.random.default_rng(2)

    def __iter__(self):
        for _ in range(self.batches_per_epoch):
            idx = self._rng.integers(0, len(self.y), self.batch)
            yield self.x[idx], self.y[idx]


def _llama_flagship(seq, recompute):
    """Shared flagship construction for the llama configs: returns
    (cfg, model, n_params, step, flops_per_token)."""
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    pt.seed(0)
    cfg = LlamaConfig(vocab_size=32000, hidden_size=2048, intermediate_size=5632,
                      num_hidden_layers=8, num_attention_heads=16,
                      num_key_value_heads=8, max_position_embeddings=seq,
                      dtype="bfloat16", mp_axis=None, fsdp_axis=None,
                      recompute=recompute)
    model = LlamaForCausalLM(cfg)
    n_params = model.num_params()
    opt = pt.optimizer.AdamW(learning_rate=1e-4, parameters=model)
    step = pt.jit.TrainStep(model, opt,
                            lambda logits, labels: model.loss(logits, labels))
    fpt = 6.0 * n_params + 12.0 * cfg.num_hidden_layers * seq * cfg.hidden_size
    return cfg, model, n_params, step, fpt


def bench_llama(peak, peak_kind):
    import jax.numpy as jnp

    batch, seq = 4, 2048  # sweep 2026-07: fastest no-remat point on v5e
    cfg, model, n_params, step, flops_per_token = _llama_flagship(
        seq, recompute=False)
    rng = np.random.default_rng(0)
    # input pipeline: variable-length documents packed into fixed rows via
    # the native packer (io/native_loader.pack_sequences), batch rows per
    # host step
    from paddle_tpu.io.native_loader import pack_sequences
    docs = [rng.integers(0, cfg.vocab_size, rng.integers(128, seq + 1))
            .astype(np.int32) for _ in range(256)]

    def host_batches():
        i = 0
        while True:
            chunk = [docs[(i + j) % len(docs)] for j in range(batch * 2)]
            i += batch * 2
            rows, _ = pack_sequences(chunk, seq)
            for r0 in range(0, len(rows) - batch + 1, batch):
                yield rows[r0:r0 + batch]

    staged = [(a := jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)),
                                jnp.int32), a) for _ in range(4)]
    pipe = _staged_feed(host_batches(), staged)
    try:
        dt, spread, lossv = _time_windows(step, pipe)
    finally:
        pipe.close()
    tokens_per_sec = batch * seq / dt
    mfu = flops_per_token * tokens_per_sec / peak
    return {
        "metric": "llama_420m_seq2048_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {"mfu": round(mfu, 4), "step_ms": round(dt * 1000, 2),
                  "params": n_params, "loss": round(lossv, 4),
                  "batch": batch, "seq": seq, "peak": peak_kind,
                  "pipeline": True, "runs": _RUNS, "spread": round(spread, 4)},
    }


def bench_resnet50(peak, peak_kind, batch=128):  # 128 ~20% > 64/256 (sweep)
    import jax.numpy as jnp

    import paddle_tpu as pt
    import paddle_tpu.nn.functional as F
    from paddle_tpu.vision.models import resnet50

    pt.seed(0)
    model = resnet50(num_classes=1000)
    # AMP O2: bf16 conv/fc params + bf16 input, fp32 batch norms, fp32
    # master weights in the optimizer (reference bench: DP+AMP, SURVEY A.2)
    model = pt.amp.decorate(model, level="O2")
    opt = pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                parameters=model)
    step = pt.jit.TrainStep(model, opt,
                            lambda out, y: F.cross_entropy(out, y))
    rng = np.random.default_rng(0)
    # input pipeline: pre-batched image shards through the DataLoader's
    # buffer-reader thread (see _SynthImages) — a host-bound pipeline
    # surfaces as queue starvation in the timed window
    from paddle_tpu.io import DataLoader, IterableDataset

    class _Shards(_SynthImages, IterableDataset):
        pass

    # each dataset item IS a batch: batch_size=1 + unwrap collate
    loader = DataLoader(_Shards(8 * batch, batch), batch_size=1,
                        collate_fn=lambda items: items[0], to_device=False)
    staged = [(jnp.asarray(rng.standard_normal((batch, 3, 224, 224)),
                           jnp.bfloat16),
               jnp.asarray(rng.integers(0, 1000, (batch,)), jnp.int32))
              for _ in range(2)]
    pipe = _staged_feed(_LoaderCycle(loader), staged)
    try:
        dt, spread, lossv = _time_windows(step, pipe)
    finally:
        pipe.close()
    images_per_sec = batch / dt
    # ResNet-50 @224 is 4.09 GMACs = 8.18 GFLOP forward per image (the
    # widely quoted "4.09 GFLOPs" counts multiply-accumulates; summing the
    # actual conv inventory — tools/profile_resnet_convs.py — gives
    # ~8.5e9/img incl. projections). Round-3 artifacts used 4.09e9 and so
    # UNDERcounted MFU 2x. train ≈ 3x fwd (bwd ~2x).
    mfu = 3 * 8.18e9 * images_per_sec / peak
    # honest chip ceiling (PROFILE_resnet50.md round 5): ~50 ms/step at
    # batch 128 — XLA conv-custom-call core at 46% of peak + BN already
    # below its standalone bandwidth floor. Report how close the step sits
    # so a regression reads as at_ceiling_frac dropping, not as "MFU low".
    ceiling_ms = 50.0 * batch / 128
    return {
        "metric": "resnet50_224_images_per_sec_per_chip",
        "value": round(images_per_sec, 1),
        "unit": "images/s",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {"mfu": round(mfu, 4), "step_ms": round(dt * 1000, 2),
                  "ceiling_step_ms": round(ceiling_ms, 2),
                  "at_ceiling_frac": round(ceiling_ms / (dt * 1000), 4),
                  "loss": round(lossv, 4), "batch": batch, "peak": peak_kind,
                  "pipeline": True, "runs": _RUNS, "spread": round(spread, 4)},
    }


def bench_bert(peak, peak_kind, batch=32):
    import jax.numpy as jnp

    import paddle_tpu as pt
    from paddle_tpu.models.bert import BertConfig, BertForPreTraining

    pt.seed(0)
    seq = 512
    cfg = BertConfig(dtype="bfloat16", hidden_dropout_prob=0.0,
                     attention_probs_dropout_prob=0.0)
    model = BertForPreTraining(cfg)
    n_params = model.num_params() if hasattr(model, "num_params") else int(sum(
        np.prod(v.shape) for v in model.state_dict().values()))
    opt = pt.optimizer.AdamW(learning_rate=1e-4, parameters=model)

    def loss_fn(outputs, labels):
        mlm_logits, nsp_logits = outputs
        mlm_labels, nsp_labels = labels
        return model.loss(mlm_logits, nsp_logits, mlm_labels, nsp_labels)

    step = pt.jit.TrainStep(model, opt, loss_fn)
    rng = np.random.default_rng(0)
    from paddle_tpu.io import DataLoader, Dataset

    class SynthMLM(Dataset):
        # 16 batches/epoch: epoch restarts respawn the buffer-reader
        # thread; keep that churn out of the 10-step timed windows
        def __init__(self):
            r = np.random.default_rng(1)
            self.ids = r.integers(0, cfg.vocab_size,
                                  (16 * batch, seq)).astype(np.int32)
            self.nsp = r.integers(0, 2, (16 * batch,)).astype(np.int32)

        def __len__(self):
            return 16 * batch

        def __getitem__(self, i):
            return self.ids[i], self.ids[(i + 1) % len(self.ids)], self.nsp[i]

    loader = DataLoader(SynthMLM(), batch_size=batch, shuffle=True,
                        drop_last=True, to_device=False)

    def stage():
        ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)),
                          jnp.int32)
        mlm = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)),
                          jnp.int32)
        nsp = jnp.asarray(rng.integers(0, 2, (batch,)), jnp.int32)
        return (ids, (mlm, nsp))

    staged = [stage() for _ in range(4)]
    pipe = _staged_feed(_LoaderCycle(loader), staged)
    try:
        dt, spread, lossv = _time_windows(step, pipe)
    finally:
        pipe.close()
    tokens_per_sec = batch * seq / dt
    mfu = 6.0 * n_params * tokens_per_sec / peak
    return {
        "metric": "bert_base_seq512_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {"mfu": round(mfu, 4), "step_ms": round(dt * 1000, 2),
                  "params": n_params, "loss": round(lossv, 4),
                  "batch": batch, "seq": seq, "peak": peak_kind,
                  "pipeline": True, "runs": _RUNS, "spread": round(spread, 4)},
    }


def bench_qwen2_moe(peak, peak_kind, batch=8,  # sweep r4: 8 > 4/16 (bf16)
                    ep_dispatch="grouped"):
    import jax.numpy as jnp

    import paddle_tpu as pt
    from paddle_tpu.models.qwen2_moe import Qwen2MoeConfig, Qwen2MoeForCausalLM

    pt.seed(0)
    seq = 1024
    cfg = Qwen2MoeConfig(vocab_size=32000, hidden_size=1024,
                         intermediate_size=2816, moe_intermediate_size=704,
                         shared_expert_intermediate_size=2816,
                         num_hidden_layers=8, num_attention_heads=16,
                         num_key_value_heads=8, num_experts=16,
                         num_experts_per_tok=2, max_position_embeddings=seq,
                         dtype="bfloat16", mp_axis=None, fsdp_axis=None,
                         ep_axis=None, ep_dispatch=ep_dispatch)
    model = Qwen2MoeForCausalLM(cfg)
    n_params = int(sum(np.prod(v.shape)
                       for v in model.state_dict().values()))
    # active params per token: dense stack + shared expert + top-k routed
    cfg2 = cfg
    routed_per_layer = 3 * cfg2.hidden_size * cfg2.moe_intermediate_size
    n_active = n_params - cfg2.num_hidden_layers * (
        cfg2.num_experts - cfg2.num_experts_per_tok) * routed_per_layer
    opt = pt.optimizer.AdamW(learning_rate=1e-4, parameters=model)
    step = pt.jit.TrainStep(model, opt,
                            lambda logits, labels: model.loss(logits, labels))
    rng = np.random.default_rng(0)
    from paddle_tpu.io import DataLoader, Dataset

    class SynthTokens(Dataset):
        # 16 batches/epoch: see SynthMLM note on buffer-reader churn
        def __init__(self):
            r = np.random.default_rng(1)
            self.ids = r.integers(0, cfg.vocab_size,
                                  (16 * batch, seq)).astype(np.int32)

        def __len__(self):
            return 16 * batch

        def __getitem__(self, i):
            return self.ids[i]

    loader = DataLoader(SynthTokens(), batch_size=batch, shuffle=True,
                        drop_last=True, to_device=False)
    staged = [(a := jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)),
                                jnp.int32), a) for _ in range(4)]
    pipe = _staged_feed(_LoaderCycle(loader), staged)
    try:
        dt, spread, lossv = _time_windows(step, pipe)
    finally:
        pipe.close()
    tokens_per_sec = batch * seq / dt
    mfu = 6.0 * n_active * tokens_per_sec / peak
    suffix = "" if ep_dispatch == "grouped" else f"_{ep_dispatch}"
    return {
        "metric": f"qwen2_moe_16e_seq1024_tokens_per_sec_per_chip{suffix}",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {"mfu_active": round(mfu, 4), "step_ms": round(dt * 1000, 2),
                  "params_total": n_params, "params_active": int(n_active),
                  "loss": round(lossv, 4), "batch": batch, "seq": seq,
                  "experts": cfg.num_experts, "dispatch": ep_dispatch,
                  "peak": peak_kind,
                  "pipeline": True, "runs": _RUNS, "spread": round(spread, 4)},
    }


def bench_lenet(peak, peak_kind, batch=256):
    """BASELINE config 1: MNIST LeNet — the single-device correctness
    reference. Reports images/s and asserts the loss actually falls over
    the measured windows (the other configs only check finiteness)."""
    import jax.numpy as jnp

    import paddle_tpu as pt
    import paddle_tpu.nn.functional as F
    from paddle_tpu.vision.models import LeNet

    pt.seed(0)
    model = LeNet(num_classes=10)
    opt = pt.optimizer.Adam(learning_rate=1e-3, parameters=model)
    step = pt.jit.TrainStep(model, opt, lambda o, y: F.cross_entropy(o, y))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((batch, 1, 28, 28)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, (batch,)), jnp.int32)
    first = float(np.asarray(step(x, y)).ravel()[0])  # compile + step 0
    # 100-step windows: at ~10 ms/step the default 30-step window is
    # dominated by relay sync jitter (spread read >1)
    dt, spread, lossv = _time_windows(step, lambda: (x, y), iters=100)
    # no assert: a did-not-train run must still EMIT the value-0.0 line
    # (the driver reads vs_baseline, not a traceback)
    images_per_sec = batch / dt
    # correctness-only metric (VERDICT r4 weak #3): the ~3.6 ms steps sit
    # below the relay's sync jitter floor, so img/s is NOISE on this rig
    # (spread ~0.36 even at 100-step windows) — report did-it-train as the
    # value and keep the unreliable throughput in extra, labeled.
    return {
        "metric": "lenet_mnist_correctness",
        "value": 1.0 if lossv < first else 0.0,
        "unit": "loss_fell",
        "vs_baseline": 1.0 if lossv < first else 0.0,
        "extra": {"step_ms": round(dt * 1000, 3), "loss0": round(first, 4),
                  "loss": round(lossv, 4), "batch": batch,
                  "images_per_sec_unreliable": round(images_per_sec, 1),
                  "throughput_note": "relay sync jitter >> step time; "
                                     "img/s not a framework measurement",
                  "peak": peak_kind, "pipeline": False, "runs": _RUNS,
                  "spread": round(spread, 4)},
    }


def bench_llama_longctx(peak, peak_kind, batch=1, seq=16384):
    """Long-context (SURVEY §5.7; default at 16k since round 5 — VERDICT r4
    weak #5 wanted the number in the driver artifact): the same Llama
    flagship at long seq on ONE chip — Pallas flash attention (no O(S^2)
    materialization) + per-layer remat. 10-step windows (each step is
    ~0.8 s, so 10 already amortize the relay sync; extra.iters records the
    deviation from the default 30). seq-32k stays opt-in:
    ``python bench.py llama_longctx_32k``."""
    import jax.numpy as jnp

    cfg, model, n_params, step, flops_per_token = _llama_flagship(
        seq, recompute=True)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)),
                      jnp.int32)
    dt, spread, lossv = _time_windows(step, lambda: (ids, ids), iters=10)
    tokens_per_sec = batch * seq / dt
    mfu = flops_per_token * tokens_per_sec / peak
    return {
        "metric": f"llama_420m_seq{seq}_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {"mfu": round(mfu, 4), "step_ms": round(dt * 1000, 2),
                  "params": n_params, "loss": round(lossv, 4),
                  "batch": batch, "seq": seq, "peak": peak_kind,
                  "recompute": True, "pipeline": False, "runs": _RUNS,
                  "iters": 10, "spread": round(spread, 4)},
    }


def bench_llama_decode(peak, peak_kind, prefill_len=2048, new_tokens=256,
                       kv_int8=False):
    """Serving/decode throughput (VERDICT r4 missing #3): the flagship's
    compiled prefill program and the one-program lax.scan decode loop
    (models/llama.py decode_programs — parity: AnalysisPredictor +
    FusedMultiTransformer KV-cache decode, fused_transformer.py:994).
    Reports steady-state decode tokens/s at batch 8 as the headline value;
    batch 1 and prefill tokens/s land in extra. Decode is HBM-bound: the
    model-bandwidth utilisation (MBU = bytes-of-weights+cache per token /
    HBM bandwidth) is the honest efficiency number, reported per batch.

    ``kv_int8=True`` is the quantized-serving arm (``llama_decode_int8``,
    SERVING.md "Quantized KV & weights"): int8 weight streaming
    (quantize_for_serving — decode matmuls read int8 codes + per-channel
    scales, dequantized in the matmul epilogue) AND an int8 KV cache
    (codes + per-row fp32 absmax scales). MBU is then computed against
    these *necessary* int8 bytes — the smaller denominator is the whole
    point: the same achieved bandwidth serves ~2x the tokens."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    pt.seed(0)
    seq = prefill_len + new_tokens
    cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                      intermediate_size=5632, num_hidden_layers=8,
                      num_attention_heads=16, num_key_value_heads=8,
                      max_position_embeddings=seq, dtype="bfloat16",
                      mp_axis=None, fsdp_axis=None)
    model = LlamaForCausalLM(cfg)
    model.eval()
    n_params = model.num_params()
    if kv_int8:
        from paddle_tpu.quantization import (quantize_for_serving,
                                             serving_state_bytes)
        quantize_for_serving(model, inplace=True)
        weight_bytes = float(serving_state_bytes(model))
    else:
        weight_bytes = 2.0 * n_params
    state = model.state_dict(include_non_persistable_buffer=True)
    rng = np.random.default_rng(0)
    # HBM bandwidth by generation (public specs), for MBU — keyed by the
    # SAME aliases _detect_peak can return (_PEAKS keys)
    hbm_bw = {"v4": 1.2e12,
              "v5e": 0.82e12, "v5litepod": 0.82e12, "v5lite": 0.82e12,
              "v5p": 2.77e12,
              "v6e": 1.64e12, "trillium": 1.64e12,
              }.get(peak_kind.split("(")[0], 0.82e12)
    per_batch = {}
    for batch in (1, 8):
        prefill, decode, _ = model.decode_programs(batch, prefill_len,
                                                   new_tokens, seq)
        ids = jnp.asarray(rng.integers(0, cfg.vocab_size,
                                       (batch, prefill_len)), jnp.int32)
        caches0 = model.init_kv_caches(batch, seq,
                                       dtype="int8" if kv_int8 else None)
        keys = jax.random.split(jax.random.key(0), new_tokens)

        def run_prefill():
            tok, caches = prefill(state, ids, caches0, keys[0])
            return tok

        # prefill timing: whole-prompt forward, 10 iters/window
        t = _time_windows(lambda: run_prefill(), lambda: (), iters=10)
        dt_pre, spread_pre = t[0], t[1]
        tok0, caches1 = prefill(state, ids, caches0, keys[0])

        # decode timing: one call = new_tokens-1 fused steps in one program
        t = _time_windows(lambda: decode(state, tok0, caches1, keys[1:]),
                          lambda: (), iters=3)
        dt_dec, spread_dec = t[0], t[1]
        tok_s_decode = batch * (new_tokens - 1) / dt_dec
        ms_per_tok = dt_dec / (new_tokens - 1) * 1000
        # bytes touched per decode step: all weights + the KV cache read
        # up to the mean filled length + new KV write (negligible). int8
        # KV: codes (kvh*d bytes) + fp32 absmax scales (kvh*4) per
        # token per layer per K/V; bf16: kvh*d*2
        kv_tok = (cfg.num_key_value_heads * (cfg.head_dim + 4) if kv_int8
                  else cfg.num_key_value_heads * cfg.head_dim * 2)
        cache_bytes = (2 * cfg.num_hidden_layers * batch
                       * (prefill_len + new_tokens / 2) * kv_tok)
        cache_bf16 = (2 * cfg.num_hidden_layers * batch
                      * (prefill_len + new_tokens / 2)
                      * cfg.num_key_value_heads * cfg.head_dim * 2)
        mbu = (weight_bytes + cache_bytes) / (dt_dec / (new_tokens - 1)) \
            / hbm_bw
        per_batch[batch] = {
            "step_bytes": round(weight_bytes + cache_bytes),
            "bytes_ratio_vs_bf16": round(
                (2.0 * n_params + cache_bf16)
                / (weight_bytes + cache_bytes), 4),
            "decode_tokens_per_sec": round(tok_s_decode, 1),
            "decode_ms_per_token": round(ms_per_tok, 3),
            "prefill_tokens_per_sec": round(batch * prefill_len / dt_pre, 1),
            "prefill_ms": round(dt_pre * 1000, 2),
            "mbu": round(mbu, 4),
            "spread_prefill": round(spread_pre, 4),
            "spread_decode": round(spread_dec, 4),
        }
    headline = per_batch[8]["decode_tokens_per_sec"]
    sfx = "_int8" if kv_int8 else ""
    return {
        "metric": f"llama_420m_decode{sfx}_tokens_per_sec_batch8",
        "value": headline,
        "unit": "tokens/s",
        # no absolute serving baseline published; report MBU-vs-ideal as
        # the honest ratio (1.0 = every decode step at HBM speed)
        "vs_baseline": per_batch[8]["mbu"],
        "extra": {"params": n_params, "prefill_len": prefill_len,
                  "new_tokens": new_tokens, "batches": per_batch,
                  "kv_int8": kv_int8,
                  "bytes_ratio_vs_bf16": per_batch[8]["bytes_ratio_vs_bf16"],
                  "peak": peak_kind, "hbm_bw": hbm_bw,
                  "mbu_note": "MBU vs the SPEC bandwidth; this chip's "
                              "measured streaming ceiling is ~600 GB/s "
                              "(PROFILE_resnet50.md), against which the "
                              "batch-8 decode is ~bandwidth-bound",
                  "pipeline": False, "runs": _RUNS,
                  "spread": per_batch[8]["spread_decode"]},
    }


def _make_tracer(trace_path):
    """Tracer for the serving configs when ``--trace PATH`` was given
    (None otherwise — tracing stays off and the engine holds the no-op
    NULL_TRACER)."""
    if trace_path is None:
        return None
    from paddle_tpu.observability import Tracer
    return Tracer()


def _dump_trace(tracer, trace_path, name):
    """Write the config's Chrome trace next to ``trace_path`` with the
    config name spliced in before the extension (two serving configs in
    one run must not clobber each other); returns the written path."""
    if tracer is None:
        return None
    import os
    root, ext = os.path.splitext(trace_path)
    return tracer.dump_chrome_trace(f"{root}.{name}{ext or '.json'}")


def bench_llama_serving(peak, peak_kind, n_requests=12, max_new_tokens=64,
                        trace_path=None, quantized=False):
    """Continuous-batching serving throughput (SERVING.md): the paged
    KV-pool engine (paddle_tpu.serving) driven by a staggered-arrival
    trace — 2 requests queued at t=0, then one more every 4 engine steps,
    ragged prompt lengths in [64, 256). Headline value is end-to-end
    generated tokens/s; TTFT p50/p99 and TPOT land in extra (and in the
    bench_summary cell — the driver's serving SLO view). Programs are
    warmed on a throwaway trace first so compile time doesn't pollute
    TTFT; the measured trace reuses the same engine (decode stays ONE
    compiled program throughout — asserted, it is the design contract).

    ``quantized=True`` is the int8 arm (``llama_serving_int8``,
    SERVING.md "Quantized KV & weights"): the engine's paged pool stores
    int8 KV codes + per-row fp32 absmax scales and the decode matmuls
    stream int8 weights (quantize_for_serving). The weights-only MBU
    floor is computed against the *necessary* int8 bytes
    (serving_state_bytes) — smaller denominator, same achieved
    bandwidth, ~2x the tokens."""
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import ServingEngine, ServingMetrics

    name = "llama_serving_int8" if quantized else "llama_serving"
    pt.seed(0)
    cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                      intermediate_size=5632, num_hidden_layers=8,
                      num_attention_heads=16, num_key_value_heads=8,
                      max_position_embeddings=4096, dtype="bfloat16",
                      mp_axis=None, fsdp_axis=None)
    model = LlamaForCausalLM(cfg)
    model.eval()
    n_params = model.num_params()
    if quantized:
        from paddle_tpu.quantization import (quantize_for_serving,
                                             serving_state_bytes)
        quantize_for_serving(model, inplace=True)
        weight_bytes = float(serving_state_bytes(model))
    else:
        weight_bytes = 2.0 * n_params
    rng = np.random.default_rng(0)
    lens = [int(x) for x in rng.integers(64, 256, n_requests)]
    prompts = [rng.integers(0, cfg.vocab_size, n).astype(np.int32)
               for n in lens]
    tracer = _make_tracer(trace_path)
    eng = ServingEngine(model, num_pages=512, page_size=16, max_slots=8,
                        max_pages_per_slot=32, tracer=tracer,
                        kv_quant=quantized)
    # warm BOTH step-shape programs (decode + mixed) with one all-slots-
    # inactive dispatch each — prompts of any length reuse them (chunks
    # are array values, not shapes), so no per-length warm sweep remains
    eng.warm_programs()
    eng.metrics = ServingMetrics()  # compile time stays out of the trace
    eng.metrics.set_kv_quant(quantized)  # re-arm after the reset
    eng.metrics.set_slo(**_SERVING_SLOS[name])

    added = 2
    for p in prompts[:2]:
        eng.add_request(p, max_new_tokens)
    steps = 0
    while eng.scheduler.has_work() or added < n_requests:
        eng.step()
        steps += 1
        if added < n_requests and steps % 4 == 0:
            eng.add_request(prompts[added], max_new_tokens)
            added += 1
    m = eng.metrics.summary()
    assert eng.decode_program_count() == 1, "serving decode retraced"
    hbm_bw = {"v4": 1.2e12,
              "v5e": 0.82e12, "v5litepod": 0.82e12, "v5lite": 0.82e12,
              "v5p": 2.77e12,
              "v6e": 1.64e12, "trillium": 1.64e12,
              }.get(peak_kind.split("(")[0], 0.82e12)
    # weights-only traffic floor: every engine step streams the weights
    # once regardless of slot occupancy (KV traffic excluded — honest
    # lower bound on bandwidth utilisation). int8 arm: the necessary
    # bytes are the int8 codes + scales, about half the bf16 stream
    wall = max(m["wall_s"], 1e-9)
    mbu = steps * weight_bytes / wall / hbm_bw
    # necessary-bytes-per-decode-step decomposition at full occupancy
    # (PERF.md): weights once + the 8 slots' mean live context of KV.
    # The ratio vs the bf16 arm is the bandwidth headroom int8 buys.
    kv_tok = eng.pool.kv_bytes_per_token()
    kv_tok_bf16 = (2 * cfg.num_hidden_layers * cfg.num_key_value_heads
                   * cfg.head_dim * 2)
    mean_ctx = sum(lens) / len(lens) + max_new_tokens / 2
    step_bytes = weight_bytes + 8 * mean_ctx * kv_tok
    step_bytes_bf16 = 2.0 * n_params + 8 * mean_ctx * kv_tok_bf16
    trace_out = _dump_trace(tracer, trace_path, name)
    return {
        "metric": f"llama_420m_{'serving_int8' if quantized else 'serving'}"
                  f"_tokens_per_sec",
        "value": round(m["tokens_per_s"], 1),
        "unit": "tokens/s",
        "vs_baseline": round(mbu, 4),
        "extra": {"params": n_params, "n_requests": n_requests,
                  "kv_quant": int(quantized),
                  "kv_quant_err_bound": round(m["kv_quant_err_bound"], 6),
                  "kv_bytes_per_token": kv_tok,
                  "step_bytes": round(step_bytes),
                  "bytes_ratio_vs_bf16": round(step_bytes_bf16
                                               / step_bytes, 4),
                  "max_new_tokens": max_new_tokens,
                  "prompt_lens": lens, "engine_steps": steps,
                  "ttft_p50": round(m["ttft_p50_s"], 4),
                  "ttft_p99": round(m["ttft_p99_s"], 4),
                  "tpot": round(m["tpot_mean_s"], 5),
                  "itl_p99": round(m["itl_p99_s"], 5),
                  "preemptions": m["preemptions"],
                  "rejected": m["rejected"],
                  "timed_out": m["timed_out"],
                  "quarantined": m["quarantined"],
                  "queue_wait_p99": round(m["queue_wait_p99_s"], 4),
                  "kv_util_peak": round(m["kv_util_peak"], 4),
                  "queue_depth_max": m["queue_depth_max"],
                  "goodput_at_slo": round(m["goodput_at_slo"], 4),
                  "slo": _SERVING_SLOS[name],
                  "retraces": eng.decode_program_count() - 1,
                  "trace": trace_out,
                  "mbu_weights_only": round(mbu, 4),
                  "peak": peak_kind, "hbm_bw": hbm_bw,
                  "pipeline": False, "runs": _RUNS,
                  "spread": None},
    }


def bench_llama_serving_prefix(peak, peak_kind, n_requests=12,
                               max_new_tokens=64, prefix_len=384,
                               trace_path=None):
    """Prefix-cache serving throughput (SERVING.md "Prefix caching"):
    same engine/model/arrival shape as bench_llama_serving, but every
    request shares a ``prefix_len``-token system prompt followed by a
    short ragged user suffix in [16, 64) — the chat-serving workload the
    prefix cache targets. The first request prefills and registers the
    shared pages; the staggered followers map them and prefill only
    their suffix, so TTFT collapses toward a single small-bucket prefill
    and ``cache_hit_rate`` (fraction of prefill context tokens served
    from cached pages) lands in the bench_summary cell next to
    ttft_p50/p99. Decode stays ONE compiled program (asserted) — the
    cached-prefix offset is a traced argument, never a bucket axis."""
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import ServingEngine, ServingMetrics

    pt.seed(0)
    cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                      intermediate_size=5632, num_hidden_layers=8,
                      num_attention_heads=16, num_key_value_heads=8,
                      max_position_embeddings=4096, dtype="bfloat16",
                      mp_axis=None, fsdp_axis=None)
    model = LlamaForCausalLM(cfg)
    model.eval()
    n_params = model.num_params()
    rng = np.random.default_rng(0)
    system = rng.integers(0, cfg.vocab_size, prefix_len).astype(np.int32)
    sfx_lens = [int(x) for x in rng.integers(16, 64, n_requests)]
    prompts = [np.concatenate(
        [system, rng.integers(0, cfg.vocab_size, n).astype(np.int32)])
        for n in sfx_lens]
    lens = [len(p) for p in prompts]
    tracer = _make_tracer(trace_path)
    eng = ServingEngine(model, num_pages=512, page_size=16, max_slots=8,
                        max_pages_per_slot=48, tracer=tracer)
    # warm both step-shape programs with scratch-page dispatches: writes
    # nothing into the pool and registers nothing, so the measured trace
    # starts with a cold prefix index for its own system prompt
    eng.warm_programs()
    eng.metrics = ServingMetrics()  # compile time stays out of the trace
    eng.metrics.set_slo(**_SERVING_SLOS["llama_serving_prefix"])

    added = 2
    for p in prompts[:2]:
        eng.add_request(p, max_new_tokens)
    steps = 0
    while eng.scheduler.has_work() or added < n_requests:
        eng.step()
        steps += 1
        if added < n_requests and steps % 4 == 0:
            eng.add_request(prompts[added], max_new_tokens)
            added += 1
    m = eng.metrics.summary()
    assert eng.decode_program_count() == 1, "serving decode retraced"
    hbm_bw = {"v4": 1.2e12,
              "v5e": 0.82e12, "v5litepod": 0.82e12, "v5lite": 0.82e12,
              "v5p": 2.77e12,
              "v6e": 1.64e12, "trillium": 1.64e12,
              }.get(peak_kind.split("(")[0], 0.82e12)
    wall = max(m["wall_s"], 1e-9)
    mbu = steps * 2.0 * n_params / wall / hbm_bw
    trace_out = _dump_trace(tracer, trace_path, "llama_serving_prefix")
    return {
        "metric": "llama_420m_serving_prefix_tokens_per_sec",
        "value": round(m["tokens_per_s"], 1),
        "unit": "tokens/s",
        "vs_baseline": round(mbu, 4),
        "extra": {"params": n_params, "n_requests": n_requests,
                  "max_new_tokens": max_new_tokens,
                  "prefix_len": prefix_len, "prompt_lens": lens,
                  "engine_steps": steps,
                  "cache_hit_rate": round(m["cache_hit_rate"], 4),
                  "prefill_tokens": m["prefill_tokens"],
                  "prefill_cached_tokens": m["prefill_cached_tokens"],
                  "prefix_hits": m.get("prefix_hits", 0),
                  "prefix_evictions": m.get("prefix_evictions", 0),
                  "ttft_p50": round(m["ttft_p50_s"], 4),
                  "ttft_p99": round(m["ttft_p99_s"], 4),
                  "tpot": round(m["tpot_mean_s"], 5),
                  "itl_p99": round(m["itl_p99_s"], 5),
                  "preemptions": m["preemptions"],
                  "rejected": m["rejected"],
                  "timed_out": m["timed_out"],
                  "quarantined": m["quarantined"],
                  "kv_util_peak": round(m["kv_util_peak"], 4),
                  "goodput_at_slo": round(m["goodput_at_slo"], 4),
                  "slo": _SERVING_SLOS["llama_serving_prefix"],
                  "retraces": eng.decode_program_count() - 1,
                  "trace": trace_out,
                  "mbu_weights_only": round(mbu, 4),
                  "peak": peak_kind, "hbm_bw": hbm_bw,
                  "pipeline": False, "runs": _RUNS,
                  "spread": None},
    }


def bench_llama_serving_chunked(peak, peak_kind, n_short=10, n_long=2,
                                max_new_tokens=48, long_len=768,
                                budget=128, trace_path=None):
    """Chunked-prefill serving A/B (SERVING.md "Chunked prefill & mixed
    steps"): a decode-heavy short-request stream with LONG prompts
    landing mid-trace, run twice on the same model — chunked OFF (the
    legacy whole-prompt admission prefill: a long arrival stalls every
    decoding slot for its entire prompt) and chunked ON (the prompt
    streams through the mixed program in budget-sized chunks alongside
    the decode rows, so decoders keep emitting every step). Headline
    value is the chunked arm's tokens/s; the A/B evidence the driver
    wants is ``itl_p99`` and ``goodput_at_slo`` for BOTH arms in the
    bench_summary cell — head-of-line blocking shows up as the OFF
    arm's inter-token p99, which is exactly what chunking removes.
    Greedy streams are asserted token-exact between the arms (chunk
    boundaries are scheduling, never semantics), and both arms assert
    zero retraces across the decode + mixed program pair."""
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import ServingEngine, ServingMetrics

    name = "llama_serving_chunked"
    pt.seed(0)
    cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                      intermediate_size=5632, num_hidden_layers=8,
                      num_attention_heads=16, num_key_value_heads=8,
                      max_position_embeddings=4096, dtype="bfloat16",
                      mp_axis=None, fsdp_axis=None)
    model = LlamaForCausalLM(cfg)
    model.eval()
    n_params = model.num_params()
    rng = np.random.default_rng(0)
    short_lens = [int(x) for x in rng.integers(48, 96, n_short)]
    shorts = [rng.integers(0, cfg.vocab_size, n).astype(np.int32)
              for n in short_lens]
    longs = [rng.integers(0, cfg.vocab_size, long_len).astype(np.int32)
             for _ in range(n_long)]
    long_steps = [6 + 10 * i for i in range(n_long)]  # land mid-decode
    tracer = _make_tracer(trace_path)

    def run_arm(chunked):
        eng = ServingEngine(model, num_pages=512, page_size=16,
                            max_slots=8, max_pages_per_slot=64,
                            prefill_token_budget=budget,
                            tracer=tracer if chunked else None,
                            chunked=chunked, prefill_chunk=64)
        eng.warm_programs()
        eng.metrics = ServingMetrics()  # compile stays out of the trace
        eng.metrics.set_chunked(chunked)  # re-arm after the reset
        eng.metrics.set_slo(**_SERVING_SLOS[name])

        added, added_long = 2, 0
        rids = [eng.add_request(p, max_new_tokens) for p in shorts[:2]]
        steps = 0
        while (eng.scheduler.has_work() or added < n_short
               or added_long < n_long):
            eng.step()
            steps += 1
            if added < n_short and steps % 3 == 0:
                rids.append(eng.add_request(shorts[added],
                                            max_new_tokens))
                added += 1
            if added_long < n_long and steps >= long_steps[added_long]:
                # a long prompt arrives while every slot is decoding
                rids.append(eng.add_request(longs[added_long], 8))
                added_long += 1
        outs = [list(eng.request(r).tokens) for r in rids]
        m = eng.metrics.summary()
        retraces = sum(n - 1 for n in eng.step_program_counts().values())
        assert retraces == 0, "serving step program retraced"
        return eng, m, steps, outs

    _, m0, steps0, outs0 = run_arm(False)
    eng, m, steps, outs = run_arm(True)
    # the tentpole's determinism contract, priced into the headline:
    # chunked streams are token-exact vs whole-prompt prefill
    assert outs == outs0, "chunked arm diverged from whole-prompt arm"
    hbm_bw = {"v4": 1.2e12,
              "v5e": 0.82e12, "v5litepod": 0.82e12, "v5lite": 0.82e12,
              "v5p": 2.77e12,
              "v6e": 1.64e12, "trillium": 1.64e12,
              }.get(peak_kind.split("(")[0], 0.82e12)
    wall = max(m["wall_s"], 1e-9)
    mbu = steps * 2.0 * n_params / wall / hbm_bw
    trace_out = _dump_trace(tracer, trace_path, name)
    return {
        "metric": "llama_420m_serving_chunked_tokens_per_sec",
        "value": round(m["tokens_per_s"], 1),
        "unit": "tokens/s",
        "vs_baseline": round(m["tokens_per_s"]
                             / max(m0["tokens_per_s"], 1e-9), 4),
        "extra": {"params": n_params,
                  "n_short": n_short, "n_long": n_long,
                  "short_lens": short_lens, "long_len": long_len,
                  "prefill_chunk": 64, "prefill_token_budget": budget,
                  "max_new_tokens": max_new_tokens,
                  "engine_steps": steps,
                  "engine_steps_baseline": steps0,
                  "tokens_per_s_baseline": round(m0["tokens_per_s"], 1),
                  "mixed_steps": m["mixed_steps"],
                  "chunk_tokens_total": m["chunk_tokens_total"],
                  "chunks_dispatched": m["chunks_dispatched_total"],
                  "ttft_p50": round(m["ttft_p50_s"], 4),
                  "ttft_p99": round(m["ttft_p99_s"], 4),
                  "tpot": round(m["tpot_mean_s"], 5),
                  "itl_p99": round(m["itl_p99_s"], 5),
                  "itl_p99_baseline": round(m0["itl_p99_s"], 5),
                  "itl_p99_ratio": round(
                      m0["itl_p99_s"] / max(m["itl_p99_s"], 1e-9), 4),
                  "preemptions": m["preemptions"],
                  "rejected": m["rejected"],
                  "timed_out": m["timed_out"],
                  "quarantined": m["quarantined"],
                  "kv_util_peak": round(m["kv_util_peak"], 4),
                  "goodput_at_slo": round(m["goodput_at_slo"], 4),
                  "goodput_at_slo_baseline": round(
                      m0["goodput_at_slo"], 4),
                  "slo": _SERVING_SLOS[name],
                  "retraces": sum(
                      n - 1
                      for n in eng.step_program_counts().values()),
                  "trace": trace_out,
                  "mbu_weights_only": round(mbu, 4),
                  "peak": peak_kind, "hbm_bw": hbm_bw,
                  "pipeline": False, "runs": _RUNS,
                  "spread": None},
    }


def bench_llama_serving_spec(peak, peak_kind, n_requests=12,
                             max_new_tokens=64, prefix_len=256,
                             spec_k=4, trace_path=None):
    """Speculative-decoding serving A/B (SERVING.md "Speculative
    decoding"): the shared-system-prompt staggered trace run twice on
    the same model — spec-off (plain decode) then spec-on (n-gram
    prompt-lookup draft + one fixed-shape ``[max_slots, k]`` verify
    program). Headline value is the spec-on tokens/s; the baseline
    arm's tokens/s and the speedup land in extra alongside
    ``accept_rate`` / ``draft_hit_rate`` (the knobs that explain the
    speedup: every accepted draft token is one decode step's weight
    stream the engine did not pay for). Greedy output is asserted
    token-exact between the arms — speculation changes how many tokens
    a step emits, never which — and both per-step-shape programs are
    asserted compiled-once (both programs are warmed by
    ``warm_programs()`` — verify rows ride the mixed program — so
    mid-trace compiles stay out of TTFT)."""
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import (ServingEngine, ServingMetrics,
                                    SpeculativeConfig)

    name = "llama_serving_spec"
    pt.seed(0)
    cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                      intermediate_size=5632, num_hidden_layers=8,
                      num_attention_heads=16, num_key_value_heads=8,
                      max_position_embeddings=4096, dtype="bfloat16",
                      mp_axis=None, fsdp_axis=None)
    model = LlamaForCausalLM(cfg)
    model.eval()
    n_params = model.num_params()
    rng = np.random.default_rng(0)
    system = rng.integers(0, cfg.vocab_size, prefix_len).astype(np.int32)
    sfx_lens = [int(x) for x in rng.integers(16, 64, n_requests)]
    prompts = [np.concatenate(
        [system, rng.integers(0, cfg.vocab_size, n).astype(np.int32)])
        for n in sfx_lens]
    lens = [len(p) for p in prompts]
    tracer = _make_tracer(trace_path)

    def run_arm(spec_on):
        eng = ServingEngine(model, num_pages=512, page_size=16,
                            max_slots=8, max_pages_per_slot=48,
                            tracer=tracer if spec_on else None,
                            speculative=(SpeculativeConfig(k=spec_k)
                                         if spec_on else None))
        # verify rows share the mixed program with prefill chunks, so
        # one warm dispatch per step shape covers spec-on and -off alike
        # (no propose-always warm drafter needed anymore)
        eng.warm_programs()
        eng.metrics = ServingMetrics()  # compile stays out of the trace
        eng.metrics.set_spec(spec_on)   # re-arm after the reset
        eng.metrics.set_slo(**_SERVING_SLOS[name])

        added = 2
        rids = [eng.add_request(p, max_new_tokens) for p in prompts[:2]]
        steps = 0
        while eng.scheduler.has_work() or added < n_requests:
            eng.step()
            steps += 1
            if added < n_requests and steps % 4 == 0:
                rids.append(eng.add_request(prompts[added],
                                            max_new_tokens))
                added += 1
        outs = [list(eng.request(r).tokens) for r in rids]
        m = eng.metrics.summary()
        retraces = sum(n - 1 for n in eng.step_program_counts().values())
        assert retraces == 0, "serving step program retraced"
        return eng, m, steps, outs

    _, m0, steps0, outs0 = run_arm(False)
    eng, m, steps, outs = run_arm(True)
    # the determinism contract, priced into the headline number: the
    # speculative arm's greedy streams are token-exact vs plain decode
    assert outs == outs0, "speculative arm diverged from plain decode"
    hbm_bw = {"v4": 1.2e12,
              "v5e": 0.82e12, "v5litepod": 0.82e12, "v5lite": 0.82e12,
              "v5p": 2.77e12,
              "v6e": 1.64e12, "trillium": 1.64e12,
              }.get(peak_kind.split("(")[0], 0.82e12)
    wall = max(m["wall_s"], 1e-9)
    mbu = steps * 2.0 * n_params / wall / hbm_bw
    trace_out = _dump_trace(tracer, trace_path, name)
    return {
        "metric": "llama_420m_serving_spec_tokens_per_sec",
        "value": round(m["tokens_per_s"], 1),
        "unit": "tokens/s",
        "vs_baseline": round(m["tokens_per_s"]
                             / max(m0["tokens_per_s"], 1e-9), 4),
        "extra": {"params": n_params, "n_requests": n_requests,
                  "max_new_tokens": max_new_tokens,
                  "prefix_len": prefix_len, "prompt_lens": lens,
                  "spec_k": spec_k,
                  "engine_steps": steps,
                  "engine_steps_baseline": steps0,
                  "tokens_per_s_baseline": round(m0["tokens_per_s"], 1),
                  "speedup_vs_decode": round(
                      m["tokens_per_s"] / max(m0["tokens_per_s"], 1e-9),
                      4),
                  "accept_rate": round(m["spec_accept_rate"], 4),
                  "draft_hit_rate": round(m["spec_draft_hit_rate"], 4),
                  "spec_draft_tokens": m["spec_draft_tokens_total"],
                  "spec_accepted_tokens": m["spec_accepted_tokens_total"],
                  "ttft_p50": round(m["ttft_p50_s"], 4),
                  "ttft_p99": round(m["ttft_p99_s"], 4),
                  "tpot": round(m["tpot_mean_s"], 5),
                  "itl_p99": round(m["itl_p99_s"], 5),
                  "preemptions": m["preemptions"],
                  "rejected": m["rejected"],
                  "timed_out": m["timed_out"],
                  "quarantined": m["quarantined"],
                  "kv_util_peak": round(m["kv_util_peak"], 4),
                  "goodput_at_slo": round(m["goodput_at_slo"], 4),
                  "slo": _SERVING_SLOS[name],
                  "retraces": sum(
                      n - 1
                      for n in eng.step_program_counts().values()),
                  "trace": trace_out,
                  "mbu_weights_only": round(mbu, 4),
                  "peak": peak_kind, "hbm_bw": hbm_bw,
                  "pipeline": False, "runs": _RUNS,
                  "spread": None},
    }


def bench_llama_serving_fleet(peak, peak_kind, n_requests=12,
                              max_new_tokens=64, kill_step=20,
                              trace_path=None):
    """Fault-tolerant fleet serving (SERVING.md "Engine fleet &
    failover"): the same 420M model and staggered-arrival trace as
    bench_llama_serving, but behind a 2-replica ``FleetRouter`` — and
    one replica is KILLED mid-run (router step ``kill_step``). Its
    in-flight requests fail over to the survivor, replay their already
    streamed positions (suppressed by the exactly-once dedup) and then
    finish; the headline tokens/s is the CLIENT-visible stream, so the
    replay overhead is priced in. failovers / replayed_tokens / shed
    land in the bench_summary cell — the driver's evidence that failover
    happened and what it cost against the serving SLOs."""
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import (FleetMetrics, FleetRouter,
                                    ServingEngine, ServingMetrics)

    name = "llama_serving_fleet"
    pt.seed(0)
    cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                      intermediate_size=5632, num_hidden_layers=8,
                      num_attention_heads=16, num_key_value_heads=8,
                      max_position_embeddings=4096, dtype="bfloat16",
                      mp_axis=None, fsdp_axis=None)
    model = LlamaForCausalLM(cfg)
    model.eval()
    n_params = model.num_params()
    weight_bytes = 2.0 * n_params
    rng = np.random.default_rng(0)
    lens = [int(x) for x in rng.integers(64, 256, n_requests)]
    prompts = [rng.integers(0, cfg.vocab_size, n).astype(np.int32)
               for n in lens]
    tracer = _make_tracer(trace_path)
    engines = [ServingEngine(model, num_pages=256, page_size=16,
                             max_slots=8, max_pages_per_slot=32,
                             tracer=tracer)
               for _ in range(2)]
    # both replicas share the model, so the compiled decode/mixed
    # programs are shared too — warm them once through replica 0, plus
    # one tiny run on replica 1 so its own step path is exercised
    engines[0].warm_programs()
    engines[1].add_request(prompts[0], 2)
    engines[1].run_to_completion(max_steps=100)
    warm_steps = [e.stats()["steps"] for e in engines]

    router = FleetRouter(engines, tracer=tracer)
    router.metrics = ServingMetrics()  # compile time stays out of the trace
    router.metrics.set_slo(**_SERVING_SLOS[name])
    router.fleet_metrics = FleetMetrics()

    added = 2
    for p in prompts[:2]:
        router.submit(p, max_new_tokens)
    steps = 0
    killed = False
    while router.has_work() or added < n_requests:
        router.step()
        steps += 1
        if not killed and steps == kill_step:
            router.kill_replica(1)  # chaos: replica 1 dies mid-decode
            killed = True
        if added < n_requests and steps % 4 == 0:
            router.submit(prompts[added], max_new_tokens)
            added += 1
    m = router.metrics.summary()
    fleet = router.fleet_metrics.summary()
    survivors = [e for e, rep in zip(engines, router._replicas)
                 if rep.state != "dead"]
    for e in survivors:
        assert e.decode_program_count() == 1, "serving decode retraced"
    engine_steps = sum(e.stats()["steps"] - w
                       for e, w in zip(engines, warm_steps))
    hbm_bw = {"v4": 1.2e12,
              "v5e": 0.82e12, "v5litepod": 0.82e12, "v5lite": 0.82e12,
              "v5p": 2.77e12,
              "v6e": 1.64e12, "trillium": 1.64e12,
              }.get(peak_kind.split("(")[0], 0.82e12)
    # weights-only floor across BOTH replicas' engine steps: every step
    # on every live replica streams the (shared) weights once
    wall = max(m["wall_s"], 1e-9)
    mbu = engine_steps * weight_bytes / wall / hbm_bw
    trace_out = _dump_trace(tracer, trace_path, name)
    return {
        "metric": "llama_420m_serving_fleet_tokens_per_sec",
        "value": round(m["tokens_per_s"], 1),
        "unit": "tokens/s",
        "vs_baseline": round(mbu, 4),
        "extra": {"params": n_params, "n_requests": n_requests,
                  "max_new_tokens": max_new_tokens,
                  "prompt_lens": lens,
                  "replicas": 2, "kill_step": kill_step,
                  "replicas_ejected": 2 - router.replicas_live(),
                  "router_steps": steps, "engine_steps": engine_steps,
                  "failovers": fleet["failovers"],
                  "replayed_requests": fleet["replayed_requests"],
                  "replayed_tokens": fleet["replayed_tokens"],
                  "shed": fleet["shed"],
                  "breaker_opens": fleet["breaker_opens"],
                  "ttft_p50": round(m["ttft_p50_s"], 4),
                  "ttft_p99": round(m["ttft_p99_s"], 4),
                  "tpot": round(m["tpot_mean_s"], 5),
                  "itl_p99": round(m["itl_p99_s"], 5),
                  "preemptions": m["preemptions"],
                  "rejected": m["rejected"],
                  "goodput_at_slo": round(m["goodput_at_slo"], 4),
                  "slo": _SERVING_SLOS[name],
                  "retraces": sum(e.decode_program_count() - 1
                                  for e in survivors),
                  "trace": trace_out,
                  "mbu_weights_only": round(mbu, 4),
                  "peak": peak_kind, "hbm_bw": hbm_bw,
                  "pipeline": False, "runs": _RUNS,
                  "spread": None},
    }


def bench_llama_serving_failover(peak, peak_kind, n_requests=12,
                                 max_new_tokens=64, kill_step=20,
                                 snapshot_interval=4, trace_path=None):
    """Bounded-replay failover A/B (RESILIENCE.md "Serving recovery
    playbook"): the same 420M model, staggered trace and mid-run replica
    kill as bench_llama_serving_fleet, run twice. Arm A has no snapshot
    store, so every failed-over request replays its FULL already-emitted
    stream on the survivor; arm B's replicas share a ``SnapshotStore``
    (capture every ``snapshot_interval`` engine steps), so failover
    restores each request's KV from its latest verified snapshot and
    replays only the tokens emitted since. Both arms see the identical
    trace and must produce bitwise-identical client streams (asserted) —
    the cell's evidence is the replay-work delta:
    ``replayed_tokens_full`` vs ``recovery_replayed_tokens`` +
    ``recovery_restored_tokens``, with ``goodput_at_slo`` for both arms
    so the saved recompute is priced against the same SLOs."""
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import (FleetMetrics, FleetRouter,
                                    ServingEngine, ServingMetrics,
                                    SnapshotStore)

    name = "llama_serving_failover"
    pt.seed(0)
    cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                      intermediate_size=5632, num_hidden_layers=8,
                      num_attention_heads=16, num_key_value_heads=8,
                      max_position_embeddings=4096, dtype="bfloat16",
                      mp_axis=None, fsdp_axis=None)
    model = LlamaForCausalLM(cfg)
    model.eval()
    n_params = model.num_params()
    weight_bytes = 2.0 * n_params
    rng = np.random.default_rng(0)
    lens = [int(x) for x in rng.integers(64, 256, n_requests)]
    prompts = [rng.integers(0, cfg.vocab_size, n).astype(np.int32)
               for n in lens]
    tracer = _make_tracer(trace_path)

    def _arm(bounded):
        # replicas share the model, so compiled programs are shared
        # across arms too — arm A pays the compiles, arm B reuses them
        store = SnapshotStore() if bounded else None
        kw = ({"snapshot_store": store,
               "snapshot_interval": snapshot_interval} if bounded else {})
        arm_tracer = tracer if bounded else None
        engines = [ServingEngine(model, num_pages=256, page_size=16,
                                 max_slots=8, max_pages_per_slot=32,
                                 tracer=arm_tracer, **kw)
                   for _ in range(2)]
        engines[0].warm_programs()
        engines[1].add_request(prompts[0], 2)
        engines[1].run_to_completion(max_steps=100)
        warm_steps = [e.stats()["steps"] for e in engines]
        router = FleetRouter(engines, tracer=arm_tracer)
        router.metrics = ServingMetrics()  # compile time stays out
        router.metrics.set_slo(**_SERVING_SLOS[name])
        router.fleet_metrics = FleetMetrics()
        added = 2
        for p in prompts[:2]:
            router.submit(p, max_new_tokens)
        steps = 0
        killed = False
        out = {}
        while router.has_work() or added < n_requests:
            for ev in router.step():
                if ev.get("token") is not None:
                    out.setdefault(ev["rid"], []).append(ev["token"])
            steps += 1
            if not killed and steps == kill_step:
                router.kill_replica(1)  # the same chaos in both arms
                killed = True
            if added < n_requests and steps % 4 == 0:
                router.submit(prompts[added], max_new_tokens)
                added += 1
        survivors = [e for e, rep in zip(engines, router._replicas)
                     if rep.state != "dead"]
        for e in survivors:
            assert e.decode_program_count() == 1, "serving decode retraced"
            e.audit_pool()
        engine_steps = sum(e.stats()["steps"] - w
                           for e, w in zip(engines, warm_steps))
        return {"m": router.metrics.summary(),
                "fleet": router.fleet_metrics.summary(),
                "out": out, "steps": steps, "engine_steps": engine_steps,
                "retraces": sum(e.decode_program_count() - 1
                                for e in survivors),
                "ejected": 2 - router.replicas_live()}

    full = _arm(bounded=False)
    bnd = _arm(bounded=True)
    # the whole point of bounded replay: the client streams are the SAME
    assert bnd["out"] == full["out"], \
        "bounded-replay arm diverged from full-replay arm"
    m, fleet = bnd["m"], bnd["fleet"]
    m0, fleet0 = full["m"], full["fleet"]
    hbm_bw = {"v4": 1.2e12,
              "v5e": 0.82e12, "v5litepod": 0.82e12, "v5lite": 0.82e12,
              "v5p": 2.77e12,
              "v6e": 1.64e12, "trillium": 1.64e12,
              }.get(peak_kind.split("(")[0], 0.82e12)
    wall = max(m["wall_s"], 1e-9)
    mbu = bnd["engine_steps"] * weight_bytes / wall / hbm_bw
    trace_out = _dump_trace(tracer, trace_path, name)
    return {
        "metric": "llama_420m_serving_failover_tokens_per_sec",
        "value": round(m["tokens_per_s"], 1),
        "unit": "tokens/s",
        "vs_baseline": round(m["tokens_per_s"]
                             / max(m0["tokens_per_s"], 1e-9), 4),
        "extra": {"params": n_params, "n_requests": n_requests,
                  "max_new_tokens": max_new_tokens,
                  "prompt_lens": lens,
                  "replicas": 2, "kill_step": kill_step,
                  "snapshot_interval": snapshot_interval,
                  "replicas_ejected": bnd["ejected"],
                  "router_steps": bnd["steps"],
                  "engine_steps": bnd["engine_steps"],
                  "failovers": fleet["failovers"],
                  # the A/B evidence: replay work in each arm
                  "replayed_tokens": fleet["replayed_tokens"],
                  "replayed_tokens_full": fleet0["replayed_tokens"],
                  "snapshot_restores": fleet["snapshot_restores"],
                  "snapshot_fallbacks": fleet["snapshot_fallbacks"],
                  "recovery_restored_tokens":
                      fleet["recovery_restored_tokens"],
                  "recovery_replayed_tokens":
                      fleet["recovery_replayed_tokens"],
                  "token_exact": True,
                  "shed": fleet["shed"],
                  "ttft_p50": round(m["ttft_p50_s"], 4),
                  "ttft_p99": round(m["ttft_p99_s"], 4),
                  "tpot": round(m["tpot_mean_s"], 5),
                  "itl_p99": round(m["itl_p99_s"], 5),
                  "goodput_at_slo": round(m["goodput_at_slo"], 4),
                  "goodput_at_slo_full": round(m0["goodput_at_slo"], 4),
                  "tokens_per_s_full": round(m0["tokens_per_s"], 1),
                  "slo": _SERVING_SLOS[name],
                  "retraces": bnd["retraces"] + full["retraces"],
                  "trace": trace_out,
                  "mbu_weights_only": round(mbu, 4),
                  "peak": peak_kind, "hbm_bw": hbm_bw,
                  "pipeline": False, "runs": _RUNS,
                  "spread": None},
    }


def bench_llama_serving_partition(peak, peak_kind, n_requests=12,
                                  max_new_tokens=48, partition_step=12,
                                  trace_path=None):
    """Clean-vs-lossy wire A/B (SERVING.md "Fleet transport &
    membership"): the same 420M model and staggered trace served by a
    3-replica FleetRouter twice. Arm A runs on the default
    ``LoopbackTransport`` (lossless, synchronous). Arm B routes every
    router<->replica message through a seeded ``ChaosTransport`` —
    drops, duplicates, deterministic reordering — and two-way
    partitions replica 2 at ``partition_step`` until its lease expires,
    the router ejects it and replays its requests on the survivors; the
    partition then heals and the zombie's held traffic must be fenced.
    Both arms must produce bitwise-identical client streams (asserted —
    the exactly-once contract priced by this cell), so the evidence is
    what the lossy wire cost: ``failovers``, ``stale_epoch_discarded``,
    ``duplicates_suppressed``, transport drop volume, and
    ``goodput_at_slo`` for both arms."""
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import (ChaosTransport, FleetMetrics,
                                    FleetRouter, ServingEngine,
                                    ServingMetrics)

    name = "llama_serving_partition"
    pt.seed(0)
    cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                      intermediate_size=5632, num_hidden_layers=8,
                      num_attention_heads=16, num_key_value_heads=8,
                      max_position_embeddings=4096, dtype="bfloat16",
                      mp_axis=None, fsdp_axis=None)
    model = LlamaForCausalLM(cfg)
    model.eval()
    n_params = model.num_params()
    weight_bytes = 2.0 * n_params
    rng = np.random.default_rng(0)
    lens = [int(x) for x in rng.integers(64, 256, n_requests)]
    prompts = [rng.integers(0, cfg.vocab_size, n).astype(np.int32)
               for n in lens]
    tracer = _make_tracer(trace_path)

    def _arm(lossy):
        wire = None
        if lossy:
            wire = ChaosTransport(seed=42, drop_p=0.05, dup_p=0.15,
                                  reorder=True)
            wire.partition("router", "replica:2", two_way=True,
                           start=partition_step)
        arm_tracer = tracer if lossy else None
        engines = [ServingEngine(model, num_pages=256, page_size=16,
                                 max_slots=8, max_pages_per_slot=32,
                                 tracer=arm_tracer) for _ in range(3)]
        engines[0].warm_programs()
        engines[1].add_request(prompts[0], 2)
        engines[1].run_to_completion(max_steps=100)
        warm_steps = [e.stats()["steps"] for e in engines]
        router = FleetRouter(engines, tracer=arm_tracer, transport=wire,
                             lease_steps=6)
        router.metrics = ServingMetrics()  # compile time stays out
        router.metrics.set_slo(**_SERVING_SLOS[name])
        router.fleet_metrics = FleetMetrics()
        added = 2
        for p in prompts[:2]:
            router.submit(p, max_new_tokens)
        steps = 0
        out = {}
        while router.has_work() or added < n_requests:
            for ev in router.step():
                if ev.get("token") is not None:
                    out.setdefault(ev["rid"], []).append(ev["token"])
            steps += 1
            if added < n_requests and steps % 4 == 0:
                router.submit(prompts[added], max_new_tokens)
                added += 1
            assert steps < 5000, "fleet hung on the lossy wire"
        if lossy:
            wire.heal()      # the zombie's held traffic arrives ...
            for ev in router.step():  # ... and must be fenced, not
                if ev.get("token") is not None:   # re-emitted
                    out.setdefault(ev["rid"], []).append(ev["token"])
            steps += 1
        survivors = [e for e, rep in zip(engines, router._replicas)
                     if rep.state != "dead"]
        for e in survivors:
            assert e.decode_program_count() == 1, "serving decode retraced"
            e.audit_pool()
        engine_steps = sum(e.stats()["steps"] - w
                           for e, w in zip(engines, warm_steps))
        return {"m": router.metrics.summary(),
                "fleet": router.fleet_metrics.summary(),
                "wire": dict(router.transport.stats()),
                "out": out, "steps": steps, "engine_steps": engine_steps,
                "retraces": sum(e.decode_program_count() - 1
                                for e in survivors),
                "ejected": 3 - router.replicas_live()}

    clean = _arm(lossy=False)
    lossy = _arm(lossy=True)
    # the exactly-once contract: the lossy wire may cost latency and
    # replay work, never tokens — streams identical to the clean arm
    assert lossy["out"] == clean["out"], \
        "lossy-wire arm diverged from the clean arm"
    m, fleet, wire = lossy["m"], lossy["fleet"], lossy["wire"]
    m0, fleet0 = clean["m"], clean["fleet"]
    assert wire["corrupt_dropped"] == wire["corrupt_injected"]
    assert fleet["lease_expirations"] >= 1, "the partition never expired"
    hbm_bw = {"v4": 1.2e12,
              "v5e": 0.82e12, "v5litepod": 0.82e12, "v5lite": 0.82e12,
              "v5p": 2.77e12,
              "v6e": 1.64e12, "trillium": 1.64e12,
              }.get(peak_kind.split("(")[0], 0.82e12)
    wall = max(m["wall_s"], 1e-9)
    mbu = lossy["engine_steps"] * weight_bytes / wall / hbm_bw
    trace_out = _dump_trace(tracer, trace_path, name)
    return {
        "metric": "llama_420m_serving_partition_tokens_per_sec",
        "value": round(m["tokens_per_s"], 1),
        "unit": "tokens/s",
        "vs_baseline": round(m["tokens_per_s"]
                             / max(m0["tokens_per_s"], 1e-9), 4),
        "extra": {"params": n_params, "n_requests": n_requests,
                  "max_new_tokens": max_new_tokens,
                  "prompt_lens": lens,
                  "replicas": 3, "partition_step": partition_step,
                  "replicas_ejected": lossy["ejected"],
                  "router_steps": lossy["steps"],
                  "engine_steps": lossy["engine_steps"],
                  # the A/B evidence: what the lossy wire cost
                  "failovers": fleet["failovers"],
                  "failovers_clean": fleet0["failovers"],
                  "stale_epoch_discarded": fleet["stale_epoch_discarded"],
                  "lease_expirations": fleet["lease_expirations"],
                  "duplicates_suppressed": fleet["duplicates_suppressed"],
                  "replayed_tokens": fleet["replayed_tokens"],
                  "transport_dropped": wire["dropped"],
                  "transport_duplicated": wire["duplicated"],
                  "transport_held": wire["held"],
                  "token_exact": True,
                  "shed": fleet["shed"],
                  "ttft_p50": round(m["ttft_p50_s"], 4),
                  "ttft_p99": round(m["ttft_p99_s"], 4),
                  "tpot": round(m["tpot_mean_s"], 5),
                  "itl_p99": round(m["itl_p99_s"], 5),
                  "goodput_at_slo": round(m["goodput_at_slo"], 4),
                  "goodput_at_slo_clean": round(m0["goodput_at_slo"], 4),
                  "tokens_per_s_clean": round(m0["tokens_per_s"], 1),
                  "slo": _SERVING_SLOS[name],
                  "retraces": lossy["retraces"] + clean["retraces"],
                  "trace": trace_out,
                  "mbu_weights_only": round(mbu, 4),
                  "peak": peak_kind, "hbm_bw": hbm_bw,
                  "pipeline": False, "runs": _RUNS,
                  "spread": None},
    }


def bench_llama_serving_multihost(peak, peak_kind, n_requests=12,
                                  max_new_tokens=48, trace_path=None):
    """Loopback-vs-socket wire A/B (SERVING.md "Multi-host serving"):
    the same 420M model and staggered trace served by a 2-replica
    FleetRouter twice. Arm A is the default in-process
    ``LoopbackTransport``. Arm B puts every router<->replica message on
    a REAL localhost TCP socket — length-prefixed frames through
    ``SocketTransport``, each replica's ``EngineServer`` behind its own
    dialed connection, exactly the wire ``spawn_fleet`` replicas speak
    (the engines stay in-process so the chip is allocated once; the
    process boundary itself is priced by tools/profile_serving.py
    --multihost). Both arms must produce bitwise-identical client
    streams (asserted), so the evidence is what the socket costs:
    frame/byte volume, reconnects (0 on a healthy wire),
    lease_expirations (0 — framing latency must never masquerade as
    membership churn), and ``goodput_at_slo`` for both arms."""
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import (FleetMetrics, FleetRouter,
                                    ServingEngine, ServingMetrics,
                                    SocketTransport)
    from paddle_tpu.serving.transport import EngineServer

    name = "llama_serving_multihost"
    pt.seed(0)
    cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                      intermediate_size=5632, num_hidden_layers=8,
                      num_attention_heads=16, num_key_value_heads=8,
                      max_position_embeddings=4096, dtype="bfloat16",
                      mp_axis=None, fsdp_axis=None)
    model = LlamaForCausalLM(cfg)
    model.eval()
    n_params = model.num_params()
    weight_bytes = 2.0 * n_params
    rng = np.random.default_rng(0)
    lens = [int(x) for x in rng.integers(64, 256, n_requests)]
    prompts = [rng.integers(0, cfg.vocab_size, n).astype(np.int32)
               for n in lens]
    tracer = _make_tracer(trace_path)

    class _RemoteFront:
        """Engine-shaped stand-in the router holds on the socket arm —
        the real EngineServer answers from the far end of the wire."""
        is_remote = True
        snapshot_store = None
        flight_recorder = None
        pool = None

        def __init__(self, idx):
            self.idx = idx

    def _arm(socket_wire):
        arm_tracer = tracer if socket_wire else None
        engines = [ServingEngine(model, num_pages=256, page_size=16,
                                 max_slots=8, max_pages_per_slot=32,
                                 tracer=arm_tracer) for _ in range(2)]
        for e in engines:
            e.warm_programs()
        warm_steps = [e.stats()["steps"] for e in engines]
        reps = []
        if socket_wire:
            wire = SocketTransport("router", listen=("127.0.0.1", 0),
                                   poll_s=0.0005, query_timeout_s=0.01)
            for i, e in enumerate(engines):
                tr = SocketTransport(
                    f"replica:{i}", connect={"router": wire.listen_addr},
                    poll_s=0.0005)
                reps.append((tr, EngineServer(i, e, tr)))
            want = {f"replica:{i}" for i in range(2)}
            deadline = time.monotonic() + 30
            while set(wire.peers()) != want:
                for tr, _ in reps:
                    tr.pump()
                wire.pump()
                assert time.monotonic() < deadline, "fleet never formed"
            router = FleetRouter([_RemoteFront(i) for i in range(2)],
                                 transport=wire, tracer=arm_tracer,
                                 lease_steps=60)
        else:
            router = FleetRouter(engines, tracer=arm_tracer,
                                 lease_steps=60)
        router.metrics = ServingMetrics()  # compile time stays out
        router.metrics.set_slo(**_SERVING_SLOS[name])
        router.fleet_metrics = FleetMetrics()
        added = 2
        for p in prompts[:2]:
            router.submit(p, max_new_tokens)
        steps = 0
        out = {}
        while router.has_work() or added < n_requests:
            for ev in router.step():
                if ev.get("token") is not None:
                    out.setdefault(ev["rid"], []).append(ev["token"])
            for tr, _ in reps:
                tr.pump()
            steps += 1
            if added < n_requests and steps % 4 == 0:
                router.submit(prompts[added], max_new_tokens)
                added += 1
            assert steps < 20000, "multi-host fleet hung"
        for e in engines:
            assert e.decode_program_count() == 1, "serving decode retraced"
            e.audit_pool()
        engine_steps = sum(e.stats()["steps"] - w
                           for e, w in zip(engines, warm_steps))
        res = {"m": router.metrics.summary(),
               "fleet": router.fleet_metrics.summary(),
               "wire": dict(router.transport.stats()),
               "out": out, "steps": steps, "engine_steps": engine_steps,
               "retraces": sum(e.decode_program_count() - 1
                               for e in engines)}
        if socket_wire:
            for tr, _ in reps:
                tr.close()
            wire.close()
        return res

    loop = _arm(socket_wire=False)
    sock = _arm(socket_wire=True)
    # the framing contract: the socket wire may cost syscalls and
    # latency, never tokens — streams identical to the loopback arm
    assert sock["out"] == loop["out"], \
        "socket arm diverged from the loopback arm"
    assert len(sock["out"]) == n_requests
    m, fleet, wire = sock["m"], sock["fleet"], sock["wire"]
    m0 = loop["m"]
    assert wire["corrupt_dropped"] == 0, "a damaged frame was injected?"
    assert fleet["lease_expirations"] == 0, \
        "socket latency expired a lease on a healthy wire"
    hbm_bw = {"v4": 1.2e12,
              "v5e": 0.82e12, "v5litepod": 0.82e12, "v5lite": 0.82e12,
              "v5p": 2.77e12,
              "v6e": 1.64e12, "trillium": 1.64e12,
              }.get(peak_kind.split("(")[0], 0.82e12)
    wall = max(m["wall_s"], 1e-9)
    mbu = sock["engine_steps"] * weight_bytes / wall / hbm_bw
    trace_out = _dump_trace(tracer, trace_path, name)
    return {
        "metric": "llama_420m_serving_multihost_tokens_per_sec",
        "value": round(m["tokens_per_s"], 1),
        "unit": "tokens/s",
        "vs_baseline": round(m["tokens_per_s"]
                             / max(m0["tokens_per_s"], 1e-9), 4),
        "extra": {"params": n_params, "n_requests": n_requests,
                  "max_new_tokens": max_new_tokens,
                  "prompt_lens": lens,
                  "replicas": 2,
                  "router_steps": sock["steps"],
                  "engine_steps": sock["engine_steps"],
                  # the A/B evidence: what the socket wire cost
                  "frames_sent": wire["socket_frames_sent"],
                  "frames_recv": wire["socket_frames_recv"],
                  "frame_bytes_sent": wire["socket_bytes_sent"],
                  "frame_bytes_recv": wire["socket_bytes_recv"],
                  "socket_reconnects": wire["socket_reconnects"],
                  "lease_expirations": fleet["lease_expirations"],
                  "duplicates_suppressed": fleet["duplicates_suppressed"],
                  "token_exact": True,
                  "ttft_p50": round(m["ttft_p50_s"], 4),
                  "ttft_p99": round(m["ttft_p99_s"], 4),
                  "tpot": round(m["tpot_mean_s"], 5),
                  "itl_p99": round(m["itl_p99_s"], 5),
                  "goodput_at_slo": round(m["goodput_at_slo"], 4),
                  "goodput_at_slo_loopback": round(
                      m0["goodput_at_slo"], 4),
                  "tokens_per_s_loopback": round(m0["tokens_per_s"], 1),
                  "slo": _SERVING_SLOS[name],
                  "retraces": sock["retraces"] + loop["retraces"],
                  "trace": trace_out,
                  "mbu_weights_only": round(mbu, 4),
                  "peak": peak_kind, "hbm_bw": hbm_bw,
                  "pipeline": False, "runs": _RUNS,
                  "spread": None},
    }


def bench_llama_serving_tiered(peak, peak_kind, n_requests=12,
                               max_new_tokens=48, trace_path=None):
    """Tiered-KV serving A/B (SERVING.md "KV tiering & traffic
    harness"): a seeded Poisson multi-tenant :class:`Workload` (Zipf
    tenant popularity over 3 shared system prompts, mixed suffix
    lengths) replayed on a pool deliberately sized to hold ~1.3 tenants'
    pages, so returning tenants force LRU evictions. Arm A runs with no
    host tier (evicted = recompute); arm B attaches a :class:`HostTier`
    so evictions demote to host RAM and hits restore. Both arms see the
    IDENTICAL trace (the workload is a value) and each arm replays it
    twice on one engine — epoch 1 warms the compiled programs and the
    prefix index, epoch 2 is measured — so the goodput_at_slo and
    HBM/host/miss hit-rate deltas in the bench_summary cell are
    attributable to the tier alone. Decode stays ONE compiled program
    per arm: restores are admission-time ``device_put``s, never a new
    step shape."""
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import (HostTier, ServingEngine,
                                    ServingMetrics, make_workload)

    name = "llama_serving_tiered"
    pt.seed(0)
    cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                      intermediate_size=5632, num_hidden_layers=8,
                      num_attention_heads=16, num_key_value_heads=8,
                      max_position_embeddings=4096, dtype="bfloat16",
                      mp_axis=None, fsdp_axis=None)
    model = LlamaForCausalLM(cfg)
    model.eval()
    n_params = model.num_params()
    wl = make_workload(seed=0, n_requests=n_requests, arrival="poisson",
                       rate=0.5, tenants=3, zipf_alpha=1.2,
                       system_len=(160, 224),
                       prompt_mix=((0.7, 16, 48), (0.3, 48, 96)),
                       max_new=(max_new_tokens, max_new_tokens),
                       vocab_size=cfg.vocab_size)
    tracer = _make_tracer(trace_path)
    arms = {}
    for arm, tier in (("notier", None), ("tiered", HostTier())):
        eng = ServingEngine(model, num_pages=40, page_size=16,
                            max_slots=4, tracer=tracer, host_tier=tier)
        wl.replay(eng, max_steps=4000, rid_prefix="warm-")
        eng.metrics = ServingMetrics()  # compile time stays off the clock
        eng.metrics.set_slo(**_SERVING_SLOS[name])
        eng.metrics.set_host_tier(tier is not None)
        out = wl.replay(eng, max_steps=4000, rid_prefix="run-")
        m = eng.metrics.summary()
        assert eng.decode_program_count() == 1, "serving decode retraced"
        arms[arm] = (eng, m, out)
    eng, m, out = arms["tiered"]
    assert eng.pool.host_tier.counters["restored_pages"] > 0, \
        "tiered arm never restored — pool no longer under pressure"
    m0 = arms["notier"][1]
    hbm_bw = {"v4": 1.2e12,
              "v5e": 0.82e12, "v5litepod": 0.82e12, "v5lite": 0.82e12,
              "v5p": 2.77e12,
              "v6e": 1.64e12, "trillium": 1.64e12,
              }.get(peak_kind.split("(")[0], 0.82e12)
    wall = max(m["wall_s"], 1e-9)
    mbu = out["steps"] * 2.0 * n_params / wall / hbm_bw
    trace_out = _dump_trace(tracer, trace_path, name)
    wstats = wl.stats()
    return {
        "metric": "llama_420m_serving_tiered_tokens_per_sec",
        "value": round(m["tokens_per_s"], 1),
        "unit": "tokens/s",
        "vs_baseline": round(mbu, 4),
        "extra": {"params": n_params, "workload": wstats,
                  "max_new_tokens": max_new_tokens,
                  "engine_steps": out["steps"],
                  "submitted": out["submitted"], "shed": out["shed"],
                  "cache_hit_rate": round(m["cache_hit_rate"], 4),
                  "cache_hit_rate_notier": round(m0["cache_hit_rate"], 4),
                  "tier_hbm_hit_rate": round(m["tier_hbm_hit_rate"], 4),
                  "tier_host_hit_rate": round(m["tier_host_hit_rate"], 4),
                  "tier_miss_rate": round(m["tier_miss_rate"], 4),
                  "spilled_pages": m["spilled_pages"],
                  "restored_pages": m["restored_pages"],
                  "spilled_bytes": m["spilled_bytes"],
                  "restored_bytes": m["restored_bytes"],
                  "host_pool_bytes": m["host_pool_bytes"],
                  "prefill_restored_tokens": m["prefill_restored_tokens"],
                  "ttft_p50": round(m["ttft_p50_s"], 4),
                  "ttft_p99": round(m["ttft_p99_s"], 4),
                  "tpot": round(m["tpot_mean_s"], 5),
                  "itl_p99": round(m["itl_p99_s"], 5),
                  "preemptions": m["preemptions"],
                  "rejected": m["rejected"],
                  "goodput_at_slo": round(m["goodput_at_slo"], 4),
                  "goodput_at_slo_notier": round(m0["goodput_at_slo"], 4),
                  "tokens_per_s_notier": round(m0["tokens_per_s"], 1),
                  "slo": _SERVING_SLOS[name],
                  "retraces": eng.decode_program_count() - 1,
                  "trace": trace_out,
                  "mbu_weights_only": round(mbu, 4),
                  "peak": peak_kind, "hbm_bw": hbm_bw,
                  "pipeline": False, "runs": _RUNS,
                  "spread": None},
    }


class _StreamRecorder:
    """Replay target that wraps an engine and keeps each request's
    emitted tokens — the tensor-parallel A/B asserts the two arms'
    streams bitwise identical, which ``Workload.replay``'s summary dict
    alone cannot show."""

    def __init__(self, eng):
        self.eng = eng
        self.scheduler = eng.scheduler     # replay's has_work probe
        self.tokens = {}

    def add_request(self, *args, **kw):
        return self.eng.add_request(*args, **kw)

    def step(self):
        events = self.eng.step()
        for ev in events:
            if ev.get("token") is not None:
                self.tokens.setdefault(ev["rid"], []).append(ev["token"])
        return events


def bench_llama_serving_disagg(peak, peak_kind, n_requests=10,
                               prompt_scale=10.0, trace_path=None):
    """Disaggregated prefill/decode serving A/B (SERVING.md
    "Disaggregated serving"): the seeded long-prompt Workload replayed
    at prompt_scale 1x and 10x, each scale served by a colocated
    2-replica fleet (both replicas interleave prefill chunks with
    decode rows) and by the same fleet with ``placement="disagg"`` (one
    prefill specialist, one decode specialist, KV handed off over the
    wire). Loopback transport steps replicas sequentially in-process,
    so each arm is timed on a VIRTUAL PARALLEL CLOCK: per router step
    the measured clock advances by the slowest replica's engine-step
    wall time — the latency a fleet of parallel machines pays. The A/B
    evidence the driver wants is itl_p99 for both arms at both scales:
    colocated inter-token gaps stretch with the 10x prompts (every
    decode step shares a program dispatch with someone's prefill
    chunk), disagg gaps track the decode-only step and stay flat
    (itl_p99_ratio_10x). Streams are asserted bitwise identical between
    the arms at each scale — the handoff relocates KV, it never changes
    the math — and both arms assert zero program retraces."""
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import (FleetMetrics, FleetRouter,
                                    ServingEngine, ServingMetrics,
                                    long_prompt_workload)

    name = "llama_serving_disagg"
    pt.seed(0)
    cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                      intermediate_size=5632, num_hidden_layers=8,
                      num_attention_heads=16, num_key_value_heads=8,
                      max_position_embeddings=4096, dtype="bfloat16",
                      mp_axis=None, fsdp_axis=None)
    model = LlamaForCausalLM(cfg)
    model.eval()
    n_params = model.num_params()
    weight_bytes = 2.0 * n_params
    tracer = _make_tracer(trace_path)

    def run_arm(scale, disagg):
        wl = long_prompt_workload(seed=0, n_requests=n_requests,
                                  prompt_scale=scale)
        engines = [ServingEngine(model, num_pages=512, page_size=16,
                                 max_slots=8, max_pages_per_slot=64,
                                 chunked=True, prefill_chunk=64,
                                 prefill_token_budget=128,
                                 tracer=tracer if disagg else None)
                   for _ in range(2)]
        # warm both replicas so the measured replay pays no compiles;
        # the disagg prefill specialist (replica 0) warms mixed only —
        # warming decode there would void the phase-split contract
        engines[0].warm_programs(decode=not disagg)
        engines[1].warm_programs()
        engines[1].add_request(np.arange(1, 9, dtype=np.int32), 2)
        engines[1].run_to_completion(max_steps=100)
        warm_steps = [e.stats()["steps"] for e in engines]
        # virtual parallel clock: real replicas are separate machines,
        # but the loopback wire steps them back-to-back in one process —
        # per router step, advance measured time by the SLOWEST replica
        # step, the wall time a parallel fleet would pay for that step
        vt = [0.0]
        durs: list = []
        for e in engines:
            def timed(_orig=e.step):
                t0 = time.perf_counter()
                ev = _orig()
                durs.append(time.perf_counter() - t0)
                return ev
            e.step = timed
        router = FleetRouter(
            engines, placement="disagg" if disagg else "affinity",
            tracer=tracer if disagg else None)
        router.metrics = ServingMetrics(clock=lambda: vt[0])
        router.metrics.set_slo(**_SERVING_SLOS[name])
        router.fleet_metrics = FleetMetrics()

        class _Rec:  # replay target: route submits, tick the clock
            def submit(self, *args, **kw):
                return router.submit(*args, **kw)

            def has_work(self):
                return router.has_work()

            def step(self):
                durs.clear()
                router.step()
                vt[0] += max(durs, default=0.0)

        res = wl.replay(_Rec(), max_steps=20000)
        outs = {rid: list(router.request(rid).tokens)
                for rid in res["rids"]}
        m = router.metrics.summary()
        fleet = router.fleet_metrics.summary()
        retraces = sum(max(0, n - 1) for e in engines
                       for n in e.step_program_counts().values())
        assert retraces == 0, "serving step program retraced"
        engine_steps = sum(e.stats()["steps"] - w
                           for e, w in zip(engines, warm_steps))
        return {"outs": outs, "m": m, "fleet": fleet,
                "router_steps": res["steps"], "shed": res["shed"],
                "engine_steps": engine_steps}

    arms = {}
    for scale in (1.0, float(prompt_scale)):
        for disagg in (False, True):
            arms[(scale, disagg)] = run_arm(scale, disagg)
        # the tentpole's determinism contract, priced into the headline:
        # the handoff arm's streams are bitwise the colocated arm's
        assert arms[(scale, True)]["outs"] == arms[(scale, False)]["outs"], \
            f"disagg arm diverged from colocated at {scale}x"

    hi = float(prompt_scale)
    dis, col = arms[(hi, True)], arms[(hi, False)]
    dis1, col1 = arms[(1.0, True)], arms[(1.0, False)]
    m, m0 = dis["m"], col["m"]
    fleet = dis["fleet"]

    def ratio(a, b):
        return round(a / max(b, 1e-9), 4)

    hbm_bw = {"v4": 1.2e12,
              "v5e": 0.82e12, "v5litepod": 0.82e12, "v5lite": 0.82e12,
              "v5p": 2.77e12,
              "v6e": 1.64e12, "trillium": 1.64e12,
              }.get(peak_kind.split("(")[0], 0.82e12)
    # fleet-aggregate weights floor over the PARALLEL wall: both
    # replicas stream the shared weights concurrently, so this can
    # legitimately exceed a single chip's ratio
    wall = max(m["wall_s"], 1e-9)
    mbu = dis["engine_steps"] * weight_bytes / wall / hbm_bw
    trace_out = _dump_trace(tracer, trace_path, name)
    return {
        "metric": "llama_420m_serving_disagg_tokens_per_sec",
        "value": round(m["tokens_per_s"], 1),
        "unit": "tokens/s",
        "vs_baseline": ratio(m["tokens_per_s"], m0["tokens_per_s"]),
        "extra": {"params": n_params, "n_requests": n_requests,
                  "prompt_scale": hi, "replicas": 2,
                  "ttft_p50": round(m["ttft_p50_s"], 4),
                  "ttft_p99": round(m["ttft_p99_s"], 4),
                  "ttft_p99_colocated": round(m0["ttft_p99_s"], 4),
                  # p50 spans can read 0.0: a short prompt prefills
                  # inside ONE router step and the virtual parallel
                  # clock only ticks between steps — the long-prompt
                  # tail lives in the p99 columns
                  "ttft_queue_p50": round(
                      m.get("ttft_queue_wait_p50_s", 0.0), 4),
                  "ttft_prefill_p50": round(
                      m.get("ttft_prefill_p50_s", 0.0), 4),
                  "ttft_prefill_p99": round(
                      m.get("ttft_prefill_p99_s", 0.0), 4),
                  "ttft_handoff_p50": round(
                      m.get("ttft_handoff_p50_s", 0.0), 4),
                  "ttft_handoff_p99": round(
                      m.get("ttft_handoff_p99_s", 0.0), 4),
                  "tpot": round(m["tpot_mean_s"], 5),
                  "itl_p99": round(m["itl_p99_s"], 5),
                  "itl_p99_colocated": round(m0["itl_p99_s"], 5),
                  "itl_p99_1x": round(dis1["m"]["itl_p99_s"], 5),
                  "itl_p99_colocated_1x":
                      round(col1["m"]["itl_p99_s"], 5),
                  "itl_p99_ratio_10x":
                      ratio(m["itl_p99_s"], dis1["m"]["itl_p99_s"]),
                  "itl_p99_colocated_ratio_10x":
                      ratio(m0["itl_p99_s"], col1["m"]["itl_p99_s"]),
                  "goodput_at_slo": round(m["goodput_at_slo"], 4),
                  "goodput_at_slo_colocated":
                      round(m0["goodput_at_slo"], 4),
                  "handoff_prefills": fleet.get("handoff_prefills", 0),
                  "handoff_pulls": fleet.get("handoff_pulls", 0),
                  "handoff_bytes": fleet.get("handoff_bytes", 0),
                  "handoff_recomputes":
                      fleet.get("handoff_recomputes", 0),
                  "handoff_commits": fleet.get("handoff_commits", 0),
                  "rerolls": fleet.get("rerolls", 0),
                  "shed": dis["shed"] + col["shed"],
                  "router_steps": dis["router_steps"],
                  "engine_steps": dis["engine_steps"],
                  "slo": _SERVING_SLOS[name],
                  "retraces": 0,
                  "trace": trace_out,
                  "mbu_weights_only": round(mbu, 4),
                  "peak": peak_kind, "hbm_bw": hbm_bw,
                  "pipeline": False, "runs": _RUNS,
                  "spread": None},
    }


def bench_llama_serving_tp(peak, peak_kind, n_requests=12,
                           max_new_tokens=48, trace_path=None):
    """Tensor-parallel serving A/B (SERVING.md "Tensor-parallel
    serving"): ONE seeded staggered Workload trace served by a tp=1
    engine and by a tp=2 engine whose two step programs each run as one
    shard_map over the mp mesh (KV pool sharded on the kv-head dim,
    Megatron column/row weight layout, one psum per block). The arms'
    per-request token streams are asserted BITWISE IDENTICAL — sharding
    relocates math, it never changes it — so every delta in the summary
    (tokens/s, goodput_at_slo, per-shard KV bytes) is attributable to
    the mesh alone. Each arm replays the trace twice on one engine:
    epoch 1 warms the two compiled programs, epoch 2 is measured.
    Needs >= 2 devices (TPU slice, or CPU with
    ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` exported
    before the first jax import)."""
    import jax

    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import (ServingEngine, ServingMetrics,
                                    make_workload)

    name = "llama_serving_tp"
    if jax.device_count() < 2:
        raise RuntimeError(
            "llama_serving_tp needs >= 2 devices for the tp=2 arm; on "
            "CPU export XLA_FLAGS=--xla_force_host_platform_device_count"
            "=8 before running bench.py (jax is already initialized by "
            "the time this config runs, so the flag cannot be set here)")
    pt.seed(0)
    cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                      intermediate_size=5632, num_hidden_layers=8,
                      num_attention_heads=16, num_key_value_heads=8,
                      max_position_embeddings=4096, dtype="bfloat16",
                      mp_axis="mp", fsdp_axis=None)
    model = LlamaForCausalLM(cfg)
    model.eval()
    n_params = model.num_params()
    wl = make_workload(seed=0, n_requests=n_requests, arrival="poisson",
                       rate=0.5, tenants=3, zipf_alpha=1.2,
                       system_len=(96, 160),
                       prompt_mix=((0.7, 16, 48), (0.3, 48, 96)),
                       max_new=(max_new_tokens, max_new_tokens),
                       vocab_size=cfg.vocab_size)
    tracer = _make_tracer(trace_path)
    arms = {}
    for arm, deg in (("tp1", 1), ("tp2", 2)):
        eng = ServingEngine(model, num_pages=64, page_size=16,
                            max_slots=4, tracer=tracer, tp=deg)
        rec = _StreamRecorder(eng)
        wl.replay(rec, max_steps=4000, rid_prefix="warm-")
        eng.metrics = ServingMetrics()  # compile time stays off the clock
        eng.metrics.set_slo(**_SERVING_SLOS[name])
        eng.metrics.set_tp(deg, eng.pool.kv_bytes_per_token_shard())
        out = wl.replay(rec, max_steps=4000, rid_prefix="run-")
        m = eng.metrics.summary()
        assert eng.step_program_counts() == {"decode": 1, "mixed": 1}, \
            f"tp={deg} step retraced"
        streams = {r: t for r, t in rec.tokens.items()
                   if r.startswith("run-")}
        arms[arm] = (eng, m, out, streams)
    assert arms["tp1"][3] == arms["tp2"][3], \
        "tp=2 streams diverged from tp=1 — TP must be bitwise"
    eng, m, out, _ = arms["tp2"]
    m0 = arms["tp1"][1]
    hbm_bw = {"v4": 1.2e12,
              "v5e": 0.82e12, "v5litepod": 0.82e12, "v5lite": 0.82e12,
              "v5p": 2.77e12,
              "v6e": 1.64e12, "trillium": 1.64e12,
              }.get(peak_kind.split("(")[0], 0.82e12)
    wall = max(m["wall_s"], 1e-9)
    mbu = out["steps"] * 2.0 * n_params / wall / hbm_bw
    trace_out = _dump_trace(tracer, trace_path, name)
    return {
        "metric": "llama_420m_serving_tp_tokens_per_sec",
        "value": round(m["tokens_per_s"], 1),
        "unit": "tokens/s",
        "vs_baseline": round(mbu, 4),
        "extra": {"params": n_params, "workload": wl.stats(),
                  "max_new_tokens": max_new_tokens,
                  "engine_steps": out["steps"],
                  "submitted": out["submitted"], "shed": out["shed"],
                  "tp_degree": 2,
                  "tp_shard_kv_bytes_per_token":
                      eng.pool.kv_bytes_per_token_shard(),
                  "kv_bytes_per_token": eng.pool.kv_bytes_per_token(),
                  "ttft_p50": round(m["ttft_p50_s"], 4),
                  "ttft_p99": round(m["ttft_p99_s"], 4),
                  "tpot": round(m["tpot_mean_s"], 5),
                  "itl_p99": round(m["itl_p99_s"], 5),
                  "preemptions": m["preemptions"],
                  "rejected": m["rejected"],
                  "goodput_at_slo": round(m["goodput_at_slo"], 4),
                  "goodput_at_slo_tp1": round(m0["goodput_at_slo"], 4),
                  "tokens_per_s_tp1": round(m0["tokens_per_s"], 1),
                  "bitwise_parity": True,
                  "slo": _SERVING_SLOS[name],
                  "retraces": eng.decode_program_count() - 1,
                  "trace": trace_out,
                  "mbu_weights_only": round(mbu, 4),
                  "peak": peak_kind, "hbm_bw": hbm_bw,
                  "pipeline": False, "runs": _RUNS,
                  "spread": None},
    }


def bench_llama_serving_pp(peak, peak_kind, n_requests=12,
                           max_new_tokens=48, trace_path=None):
    """Pipeline-parallel serving A/B (SERVING.md "Pipeline-parallel
    serving"): ONE seeded staggered Workload trace served by a tp=2
    engine and by a pp=2 x tp=2 engine that stages the decoder along
    the stacked-layer axis (embed + first half on stage 0, lm_head +
    last half on stage 1), carves the KV pool per stage, and hands
    activations between stages with one ppermute ring INSIDE each of
    the two compiled step programs. The arms' per-request token streams
    are asserted BITWISE IDENTICAL — staging relocates layers, it never
    changes the math — so every delta in the summary is attributable to
    the pipeline alone. On the loopback harness both stages of the one
    shard_map program run back-to-back in-process, so each arm is timed
    on the VIRTUAL PARALLEL CLOCK (PR 16 precedent): the measured clock
    advances by each engine step's wall time, compile time off the
    clock (epoch 1 warms, epoch 2 is measured). The headline pipeline
    evidence: per-chip KV bytes exactly 1/pp of the tp-only shard, and
    the microbatched mixed step's pipeline_bubble_frac
    ``(pp-1)/(waves+pp-1)`` strictly below the unwaved ``(pp-1)/pp``.
    Needs >= 4 devices (TPU slice, or CPU with
    ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` exported
    before the first jax import)."""
    import jax

    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import (ServingEngine, ServingMetrics,
                                    make_workload)

    name = "llama_serving_pp"
    if jax.device_count() < 4:
        raise RuntimeError(
            "llama_serving_pp needs >= 4 devices for the pp=2 x tp=2 "
            "arm; on CPU export XLA_FLAGS=--xla_force_host_platform_"
            "device_count=8 before running bench.py (jax is already "
            "initialized by the time this config runs, so the flag "
            "cannot be set here)")
    pt.seed(0)
    cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                      intermediate_size=5632, num_hidden_layers=8,
                      num_attention_heads=16, num_key_value_heads=8,
                      max_position_embeddings=4096, dtype="bfloat16",
                      mp_axis="mp", fsdp_axis=None)
    model = LlamaForCausalLM(cfg)
    model.eval()
    n_params = model.num_params()
    wl = make_workload(seed=0, n_requests=n_requests, arrival="poisson",
                       rate=0.5, tenants=3, zipf_alpha=1.2,
                       system_len=(96, 160),
                       prompt_mix=((0.7, 16, 48), (0.3, 48, 96)),
                       max_new=(max_new_tokens, max_new_tokens),
                       vocab_size=cfg.vocab_size)
    tracer = _make_tracer(trace_path)
    arms = {}
    for arm, pp in (("tp2", 1), ("pp2", 2)):
        eng = ServingEngine(model, num_pages=64, page_size=16,
                            max_slots=4, tracer=tracer, tp=2, pp=pp)
        # virtual parallel clock: a real pp x tp slice runs the one
        # compiled step across 2 x pp chips at once, but the loopback
        # harness executes every fake device in one process — score the
        # metrics on accumulated engine-step wall time so both arms pay
        # exactly their step cost, nothing else
        vt = [0.0]

        def timed(_orig=eng.step):
            t0 = time.perf_counter()
            ev = _orig()
            vt[0] += time.perf_counter() - t0
            return ev

        eng.step = timed
        rec = _StreamRecorder(eng)
        wl.replay(rec, max_steps=4000, rid_prefix="warm-")
        vt[0] = 0.0                     # compile time stays off the clock
        eng.metrics = ServingMetrics(clock=lambda _vt=vt: _vt[0])
        eng.metrics.set_slo(**_SERVING_SLOS[name])
        eng.metrics.set_tp(2, eng.pool.kv_bytes_per_token_shard())
        eng.metrics.set_pp(eng.pp, eng._pp_waves,
                           eng.pipeline_bubble_frac())
        out = wl.replay(rec, max_steps=4000, rid_prefix="run-")
        m = eng.metrics.summary()
        assert eng.step_program_counts() == {"decode": 1, "mixed": 1}, \
            f"pp={pp} step retraced"
        streams = {r: t for r, t in rec.tokens.items()
                   if r.startswith("run-")}
        arms[arm] = (eng, m, out, streams)
    assert arms["tp2"][3] == arms["pp2"][3], \
        "pp=2 streams diverged from tp-only — staging must be bitwise"
    eng, m, out, _ = arms["pp2"]
    m0 = arms["tp2"][1]
    # the two headline pipeline claims, priced into the summary
    shard_pp = eng.pool.kv_bytes_per_token_shard()
    shard_tp = arms["tp2"][0].pool.kv_bytes_per_token_shard()
    assert shard_pp * eng.pp == shard_tp, \
        "per-chip KV bytes must be exactly 1/pp of the tp-only shard"
    bubble = eng.pipeline_bubble_frac()
    bubble_unwaved = eng.pipeline_bubble_frac(waves=1)
    assert bubble < bubble_unwaved, \
        "microbatched bubble fraction must beat the unwaved schedule"
    hbm_bw = {"v4": 1.2e12,
              "v5e": 0.82e12, "v5litepod": 0.82e12, "v5lite": 0.82e12,
              "v5p": 2.77e12,
              "v6e": 1.64e12, "trillium": 1.64e12,
              }.get(peak_kind.split("(")[0], 0.82e12)
    wall = max(m["wall_s"], 1e-9)
    mbu = out["steps"] * 2.0 * n_params / wall / hbm_bw
    trace_out = _dump_trace(tracer, trace_path, name)
    return {
        "metric": "llama_420m_serving_pp_tokens_per_sec",
        "value": round(m["tokens_per_s"], 1),
        "unit": "tokens/s",
        "vs_baseline": round(mbu, 4),
        "extra": {"params": n_params, "workload": wl.stats(),
                  "max_new_tokens": max_new_tokens,
                  "engine_steps": out["steps"],
                  "submitted": out["submitted"], "shed": out["shed"],
                  "pp_degree": eng.pp, "tp_degree": 2,
                  "pp_waves": eng._pp_waves,
                  "pipeline_bubble_frac": round(bubble, 4),
                  "pipeline_bubble_frac_unwaved":
                      round(bubble_unwaved, 4),
                  "pp_stage_layers":
                      cfg.num_hidden_layers // eng.pp,
                  "tp_shard_kv_bytes_per_token": shard_pp,
                  "tp_shard_kv_bytes_per_token_tponly": shard_tp,
                  "kv_bytes_per_token": eng.pool.kv_bytes_per_token(),
                  "ttft_p50": round(m["ttft_p50_s"], 4),
                  "ttft_p99": round(m["ttft_p99_s"], 4),
                  "tpot": round(m["tpot_mean_s"], 5),
                  "itl_p99": round(m["itl_p99_s"], 5),
                  "preemptions": m["preemptions"],
                  "rejected": m["rejected"],
                  "goodput_at_slo": round(m["goodput_at_slo"], 4),
                  "goodput_at_slo_tponly":
                      round(m0["goodput_at_slo"], 4),
                  "tokens_per_s_tponly": round(m0["tokens_per_s"], 1),
                  "bitwise_parity": True,
                  "slo": _SERVING_SLOS[name],
                  "retraces": eng.decode_program_count() - 1,
                  "trace": trace_out,
                  "mbu_weights_only": round(mbu, 4),
                  "peak": peak_kind, "hbm_bw": hbm_bw,
                  "pipeline": True, "runs": _RUNS,
                  "spread": None},
    }


def bench_llama8b_shape(peak, peak_kind, batch=1, seq=4096, layers=2):
    """North-star-SHAPE evidence (VERDICT r4 missing #1): ``layers``
    llama_3_8b-config decoder layers (hidden 4096, ffn 14336, GQA 32/8,
    models/llama.py llama_3_8b) + the fused hard-label CE head over the
    full 128256 vocab, fwd+bwd+AdamW at seq 4096 bf16 with per-layer
    remat, on ONE chip. MFU physics at 8B shapes differs from the 420M
    proxy (bigger matmuls, relatively costlier 128k-vocab softmax and
    GQA-8 attention); this config measures exactly those shapes. The
    embedding is tied so the 525M-param vocab matrix is stored once
    (fits HBM next to fp32 AdamW moments); FLOPs/token = 6*N + 12*L*s*h
    counts the head matmul through the tied matrix."""
    import jax.numpy as jnp

    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    pt.seed(0)
    cfg = LlamaConfig(vocab_size=128256, hidden_size=4096,
                      intermediate_size=14336, num_hidden_layers=layers,
                      num_attention_heads=32, num_key_value_heads=8,
                      max_position_embeddings=seq, rope_theta=500000.0,
                      tie_word_embeddings=True, dtype="bfloat16",
                      mp_axis=None, fsdp_axis=None, recompute=True)
    model = LlamaForCausalLM(cfg)
    n_params = model.num_params()
    opt = pt.optimizer.AdamW(learning_rate=1e-4, parameters=model)
    step = pt.jit.TrainStep(model, opt,
                            lambda logits, labels: model.loss(logits, labels))
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)),
                      jnp.int32)
    dt, spread, lossv = _time_windows(step, lambda: (ids, ids), iters=10)
    tokens_per_sec = batch * seq / dt
    flops_per_token = 6.0 * n_params \
        + 12.0 * layers * seq * cfg.hidden_size
    mfu = flops_per_token * tokens_per_sec / peak
    return {
        "metric": f"llama8b_shape_{layers}layer_seq{seq}_tokens_per_sec",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {"mfu": round(mfu, 4), "step_ms": round(dt * 1000, 2),
                  "params": n_params, "loss": round(lossv, 4),
                  "batch": batch, "seq": seq, "layers": layers,
                  "hidden": cfg.hidden_size, "vocab": cfg.vocab_size,
                  "gqa": "32/8", "recompute": True, "tied": True,
                  "peak": peak_kind, "pipeline": False, "runs": _RUNS,
                  "iters": 10, "spread": round(spread, 4)},
    }


def bench_llama_serving_fairness(peak, peak_kind, n_requests=40,
                                 trace_path=None):
    """Overload-control A/B (SERVING.md "Overload control & tenant
    fairness"): the canonical hot-tenant flood — ``overload_workload``,
    where low-priority tenant 0 carries ~2/3 of a bursty trace and the
    cold tenants are the interactive SLO classes — replayed twice on
    the same model: FCFS (the legacy global queue: the flood buries
    every cold arrival behind the hot backlog) vs fair scheduling +
    the brownout ladder (weighted virtual-token-counter admission,
    budget-shrink/drafter-off/priority-shed degradation). The evidence
    the driver wants is the COLD tenants' worst p99 TTFT and aggregate
    ``goodput_at_slo`` for BOTH arms in the bench_summary cell —
    fairness bounds the former without moving the latter backwards.
    Streams finished in both arms are asserted token-exact (scheduling
    is invisible in the tokens) and both arms assert zero retraces:
    every brownout level is host-side scalar churn, never a shape."""
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import (BrownoutConfig, ServingEngine,
                                    ServingMetrics, overload_workload)

    name = "llama_serving_fairness"
    pt.seed(0)
    cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                      intermediate_size=5632, num_hidden_layers=8,
                      num_attention_heads=16, num_key_value_heads=8,
                      max_position_embeddings=4096, dtype="bfloat16",
                      mp_axis=None, fsdp_axis=None)
    model = LlamaForCausalLM(cfg)
    model.eval()
    n_params = model.num_params()
    wl = overload_workload(seed=0, n_requests=n_requests, rate=2.0,
                           zipf_alpha=1.6, vocab_size=cfg.vocab_size)
    tracer = _make_tracer(trace_path)
    arms = {}
    for arm in ("fcfs", "fair"):
        kw = {}
        if arm == "fair":
            kw = dict(fair_scheduling=True,
                      brownout=BrownoutConfig(high_queue=10, low_queue=4,
                                              dwell_steps=2))
        eng = ServingEngine(model, num_pages=256, page_size=16,
                            max_slots=8, max_pages_per_slot=16,
                            prefill_token_budget=128,
                            tracer=tracer if arm == "fair" else None,
                            **kw)
        wl.replay(eng, max_steps=4000, rid_prefix="warm-")
        eng.metrics = ServingMetrics()  # compile time stays off the clock
        eng.metrics.set_fair(arm == "fair")
        eng.metrics.set_brownout(arm == "fair")
        eng.metrics.set_slo(**_SERVING_SLOS[name])
        rec = _StreamRecorder(eng)
        out = wl.replay(rec, max_steps=4000, rid_prefix="run-")
        m = eng.metrics.summary()
        retraces = sum(n - 1 for n in eng.step_program_counts().values())
        assert retraces == 0, "serving step program retraced"
        arms[arm] = (eng, m, out, rec.tokens)
    eng, m, out, toks = arms["fair"]
    eng0, m0, out0, toks0 = arms["fcfs"]
    # the fairness contract, priced into the headline: a request
    # finished in BOTH arms decoded the identical stream — admission
    # order and brownout levels are scheduling, never semantics
    both = sorted(set(toks) & set(toks0))
    assert both, "no request finished in both arms"
    for rid in both:
        assert toks[rid] == toks0[rid], f"{rid} diverged across arms"

    def cold_p99(metrics):
        per = metrics.per_tenant()
        vals = [v["ttft_p99_s"] for t, v in per.items()
                if t != 0 and v["finished"] > 0]
        return max(vals) if vals else 0.0

    hbm_bw = {"v4": 1.2e12,
              "v5e": 0.82e12, "v5litepod": 0.82e12, "v5lite": 0.82e12,
              "v5p": 2.77e12,
              "v6e": 1.64e12, "trillium": 1.64e12,
              }.get(peak_kind.split("(")[0], 0.82e12)
    wall = max(m["wall_s"], 1e-9)
    mbu = out["steps"] * 2.0 * n_params / wall / hbm_bw
    trace_out = _dump_trace(tracer, trace_path, name)
    return {
        "metric": "llama_420m_serving_fairness_tokens_per_sec",
        "value": round(m["tokens_per_s"], 1),
        "unit": "tokens/s",
        "vs_baseline": round(m["tokens_per_s"]
                             / max(m0["tokens_per_s"], 1e-9), 4),
        "extra": {"params": n_params, "workload": wl.stats(),
                  "engine_steps": out["steps"],
                  "engine_steps_fcfs": out0["steps"],
                  "submitted": out["submitted"],
                  "tokens_per_s_fcfs": round(m0["tokens_per_s"], 1),
                  "ttft_p50": round(m["ttft_p50_s"], 4),
                  "ttft_p99": round(m["ttft_p99_s"], 4),
                  "tpot": round(m["tpot_mean_s"], 5),
                  "itl_p99": round(m["itl_p99_s"], 5),
                  "cold_ttft_p99": round(cold_p99(eng.metrics), 4),
                  "cold_ttft_p99_fcfs": round(cold_p99(eng0.metrics), 4),
                  "per_tenant": {t: {"finished": v["finished"],
                                     "ttft_p99_s": round(
                                         v["ttft_p99_s"], 4),
                                     "shed": v["shed"]}
                                 for t, v in
                                 eng.metrics.per_tenant().items()},
                  "shed": m["shed"],
                  "shed_by_priority": eng.metrics.shed_by_priority(),
                  "brownout_transitions": m["brownout_transitions"],
                  "brownout_level1_steps": m["brownout_level1_steps"],
                  "brownout_level2_steps": m["brownout_level2_steps"],
                  "brownout_level3_steps": m["brownout_level3_steps"],
                  "goodput_at_slo": round(m["goodput_at_slo"], 4),
                  "goodput_at_slo_fcfs": round(m0["goodput_at_slo"], 4),
                  "slo": _SERVING_SLOS[name],
                  "retraces": sum(
                      n - 1
                      for n in eng.step_program_counts().values()),
                  "trace": trace_out,
                  "mbu_weights_only": round(mbu, 4),
                  "peak": peak_kind, "hbm_bw": hbm_bw,
                  "pipeline": False, "runs": _RUNS,
                  "spread": None},
    }


def bench_llama_serving_lora(peak, peak_kind, n_requests=24, n_adapters=32,
                             max_new_tokens=48, trace_path=None):
    """Multi-tenant LoRA serving A/B (SERVING.md "Multi-tenant LoRA
    serving"): one staggered-arrival ragged trace served three ways on
    identically-configured engines — no adapter pool at all ("base"),
    every request bound to ONE adapter ("single"), and every request
    drawing its adapter from a Zipf-popularity distribution over
    ``n_adapters`` tenants ("multi", the headline arm). The pool holds
    8 live slots against 32 registered adapters, so the multi arm pays
    real churn: misses page adapters in from host RAM, LRU evictions
    spill cold ones back, and the adapter-table value swaps every
    admission — while ``step_program_counts()`` must stay
    ``{"decode": 1, "mixed": 1}`` (asserted; the design contract).
    The bench_summary cell carries the adapter economics next to the
    usual serving SLO keys: adapter_hit_rate (Zipf should keep it
    high), lora_bytes_streamed (the HBM<->host bandwidth adapter churn
    cost), and multi_vs_single_ratio — the acceptance gate is multi
    tokens/s >= 0.8x the single-adapter arm."""
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import ServingEngine, ServingMetrics
    from paddle_tpu.serving.lora import LoRAAdapter

    name = "llama_serving_lora"
    pt.seed(0)
    cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                      intermediate_size=5632, num_hidden_layers=8,
                      num_attention_heads=16, num_key_value_heads=8,
                      max_position_embeddings=4096, dtype="bfloat16",
                      mp_axis=None, fsdp_axis=None)
    model = LlamaForCausalLM(cfg)
    model.eval()
    n_params = model.num_params()
    rng = np.random.default_rng(0)
    lens = [int(x) for x in rng.integers(64, 256, n_requests)]
    prompts = [rng.integers(0, cfg.vocab_size, n).astype(np.int32)
               for n in lens]
    adapters = [LoRAAdapter.random(f"tenant-{i}", cfg, rank=8, seed=i)
                for i in range(n_adapters)]
    # Zipf tenant popularity (alpha 1.2, same shape the tiered bench's
    # Workload uses): a few hot adapters dominate, the tail forces
    # misses + evictions
    w = 1.0 / np.arange(1, n_adapters + 1) ** 1.2
    zipf_draw = rng.choice(n_adapters, size=n_requests, p=w / w.sum())
    # plant the coldest tenants at the tail: together with the hot head
    # draws the trace touches more distinct adapters than the pool has
    # slots, so the multi arm's eviction churn is deterministic
    n_cold = min(8, n_adapters - 1, n_requests // 2)
    zipf_draw[-n_cold:] = np.arange(n_adapters - n_cold, n_adapters)
    tracer = _make_tracer(trace_path)

    def run_arm(arm):
        lora = (None if arm == "base"
                else {"max_live": 9, "max_rank": 8,
                      "host_tier": 1 << 30})
        eng = ServingEngine(model, num_pages=512, page_size=16,
                            max_slots=8, max_pages_per_slot=32,
                            tracer=tracer, lora=lora)
        hexes = ([] if arm == "base"
                 else [eng.register_adapter(a) for a in adapters])
        per_req = {"base": [None] * n_requests,
                   "single": [hexes[0] if hexes else None] * n_requests,
                   "multi": [hexes[k] if hexes else None
                             for k in zipf_draw]}[arm]
        eng.warm_programs()
        eng.metrics = ServingMetrics()  # compile time stays off the clock
        eng.metrics.set_lora(eng.adapters is not None)
        eng.metrics.set_slo(**_SERVING_SLOS[name])
        added = 2
        for p, a in zip(prompts[:2], per_req[:2]):
            eng.add_request(p, max_new_tokens, adapter=a)
        steps = 0
        while eng.scheduler.has_work() or added < n_requests:
            eng.step()
            steps += 1
            if added < n_requests and steps % 4 == 0:
                eng.add_request(prompts[added], max_new_tokens,
                                adapter=per_req[added])
                added += 1
        m = eng.metrics.summary()
        counts = eng.step_program_counts()
        assert counts["decode"] == 1 and counts["mixed"] <= 1, \
            f"{arm} arm retraced: {counts}"
        return eng, m, steps

    arms = {arm: run_arm(arm) for arm in ("base", "single", "multi")}
    eng, m, steps = arms["multi"]
    m_base, m_single = arms["base"][1], arms["single"][1]
    lst = eng.adapters.stats()
    assert lst["adapter_evictions"] > 0, \
        "multi arm never evicted — pool no longer under adapter pressure"
    hbm_bw = {"v4": 1.2e12,
              "v5e": 0.82e12, "v5litepod": 0.82e12, "v5lite": 0.82e12,
              "v5p": 2.77e12,
              "v6e": 1.64e12, "trillium": 1.64e12,
              }.get(peak_kind.split("(")[0], 0.82e12)
    wall = max(m["wall_s"], 1e-9)
    mbu = steps * 2.0 * n_params / wall / hbm_bw
    trace_out = _dump_trace(tracer, trace_path, name)
    return {
        "metric": "llama_420m_serving_lora_tokens_per_sec",
        "value": round(m["tokens_per_s"], 1),
        "unit": "tokens/s",
        "vs_baseline": round(mbu, 4),
        "extra": {"params": n_params, "n_requests": n_requests,
                  "n_adapters": n_adapters,
                  "max_new_tokens": max_new_tokens,
                  "prompt_lens": lens, "engine_steps": steps,
                  "adapter_hit_rate": round(lst["adapter_hit_rate"], 4),
                  "adapter_loads": lst["adapter_loads"],
                  "adapter_evictions": lst["adapter_evictions"],
                  "adapter_spills": lst["adapter_spills"],
                  "lora_bytes_streamed": lst["lora_bytes_streamed"],
                  "lora_bytes_per_slot": lst["bytes_per_slot"],
                  "tokens_per_s_base": round(m_base["tokens_per_s"], 1),
                  "tokens_per_s_single":
                      round(m_single["tokens_per_s"], 1),
                  "multi_vs_single_ratio":
                      round(m["tokens_per_s"]
                            / max(m_single["tokens_per_s"], 1e-9), 4),
                  "ttft_p50": round(m["ttft_p50_s"], 4),
                  "ttft_p99": round(m["ttft_p99_s"], 4),
                  "tpot": round(m["tpot_mean_s"], 5),
                  "itl_p99": round(m["itl_p99_s"], 5),
                  "preemptions": m["preemptions"],
                  "rejected": m["rejected"],
                  "goodput_at_slo": round(m["goodput_at_slo"], 4),
                  "goodput_at_slo_base":
                      round(m_base["goodput_at_slo"], 4),
                  "goodput_at_slo_single":
                      round(m_single["goodput_at_slo"], 4),
                  "slo": _SERVING_SLOS[name],
                  "retraces": sum(
                      max(n - 1, 0)
                      for n in eng.step_program_counts().values()),
                  "trace": trace_out,
                  "mbu_weights_only": round(mbu, 4),
                  "peak": peak_kind, "hbm_bw": hbm_bw,
                  "pipeline": False, "runs": _RUNS,
                  "spread": None},
    }


_CONFIGS = {
    "llama_420m": bench_llama,
    "resnet50": bench_resnet50,
    "bert_base": bench_bert,
    "qwen2_moe": bench_qwen2_moe,
    "lenet_mnist": bench_lenet,
    # round-5 additions to the driver artifact (VERDICT r4 next #1/#3/#6):
    "llama8b_shape": bench_llama8b_shape,
    "llama_decode": bench_llama_decode,
    "llama_longctx": bench_llama_longctx,
    # continuous-batching serving over the paged KV pool (SERVING.md)
    "llama_serving": bench_llama_serving,
    # shared-system-prompt serving: prefix-cache hit path (SERVING.md
    # "Prefix caching") — TTFT/hit-rate evidence for the cache
    "llama_serving_prefix": bench_llama_serving_prefix,
    # int8 quantized serving (SERVING.md "Quantized KV & weights"): the
    # same decode/serving workloads with int8 KV + int8 weight streaming;
    # MBU denominators are the *necessary* int8 bytes
    "llama_decode_int8": lambda peak, kind, **kw: bench_llama_decode(
        peak, kind, kv_int8=True, **kw),
    "llama_serving_int8": lambda peak, kind, **kw: bench_llama_serving(
        peak, kind, quantized=True, **kw),
    # 2-replica FleetRouter with a mid-run replica kill (SERVING.md
    # "Engine fleet & failover"): client-visible tokens/s with the
    # failover replay priced in, plus failovers/replays/shed evidence
    "llama_serving_fleet": bench_llama_serving_fleet,
    # bounded-replay failover A/B (RESILIENCE.md "Serving recovery
    # playbook"): the fleet kill run twice — no snapshots (full replay)
    # vs a shared SnapshotStore (restore KV, replay only the delta);
    # bitwise-identical client streams by assertion, replay-work +
    # goodput_at_slo evidence for both arms
    "llama_serving_failover": bench_llama_serving_failover,
    # clean-vs-lossy wire A/B (SERVING.md "Fleet transport &
    # membership"): loopback vs seeded chaos transport with a healed
    # mid-run partition and a lease ejection; bitwise-identical client
    # streams by assertion, failover/fencing/goodput evidence for both
    # arms
    "llama_serving_partition": bench_llama_serving_partition,
    # loopback-vs-socket wire A/B (SERVING.md "Multi-host serving"):
    # the same trace over the in-process wire and over real localhost
    # TCP framing; bitwise-identical client streams by assertion,
    # frame/byte volume + zero reconnects/lease churn + goodput for
    # both arms
    "llama_serving_multihost": bench_llama_serving_multihost,
    # chunked-prefill A/B (SERVING.md "Chunked prefill & mixed steps"):
    # whole-prompt vs chunk-streamed prefill on a long-prompt +
    # decode-heavy trace; itl_p99/goodput for both arms, token-exact
    "llama_serving_chunked": bench_llama_serving_chunked,
    # speculative decoding A/B (SERVING.md "Speculative decoding"):
    # n-gram draft verified through the mixed step vs plain decode
    # on the same shared-system-prompt trace; token-exact by assertion
    "llama_serving_spec": bench_llama_serving_spec,
    # host-RAM KV tiering A/B on a Poisson multi-tenant Workload
    # (SERVING.md "KV tiering & traffic harness"): spill-off vs spill-on
    # under forced pool pressure; goodput_at_slo + tier hit rates
    "llama_serving_tiered": bench_llama_serving_tiered,
    # overload-control A/B (SERVING.md "Overload control & tenant
    # fairness"): FCFS vs fair-scheduling + brownout ladder on the
    # canonical hot-tenant flood; cold-tenant p99 TTFT + goodput for
    # both arms, streams finished in both asserted token-exact
    "llama_serving_fairness": bench_llama_serving_fairness,
    # tensor-parallel serving A/B (SERVING.md "Tensor-parallel
    # serving"): tp=1 vs tp=2 on one seeded trace, streams asserted
    # bitwise identical; per-shard KV bytes + goodput for both arms.
    # Needs >= 2 devices (CPU: XLA_FLAGS=--xla_force_host_platform_
    # device_count=8 exported before launch)
    "llama_serving_tp": bench_llama_serving_tp,
    # pipeline-parallel serving A/B (SERVING.md "Pipeline-parallel
    # serving"): tp=2 vs pp=2 x tp=2 on one seeded trace, virtual
    # parallel clock, streams asserted bitwise identical; per-chip KV
    # bytes (exactly 1/pp), microbatched vs unwaved bubble fraction +
    # goodput for both arms. Needs >= 4 devices (CPU: XLA_FLAGS=
    # --xla_force_host_platform_device_count=8 exported before launch)
    "llama_serving_pp": bench_llama_serving_pp,
    # disaggregated prefill/decode A/B (SERVING.md "Disaggregated
    # serving"): colocated vs phase-specialized 2-replica fleet on the
    # long-prompt trace at 1x and 10x prompt length, virtual parallel
    # clock; itl_p99 flatness + handoff counters + goodput for both
    # arms, streams asserted bitwise identical per scale
    "llama_serving_disagg": bench_llama_serving_disagg,
    # multi-tenant LoRA A/B (SERVING.md "Multi-tenant LoRA serving"):
    # base-only vs single-adapter vs Zipf-popular 32-adapter arms on
    # one staggered trace; adapter hit rate + streamed bytes + the
    # multi/single throughput ratio (acceptance: >= 0.8), programs
    # pinned at {decode: 1, mixed: 1} through the churn
    "llama_serving_lora": bench_llama_serving_lora,
}

# configs whose bench_summary cell carries extra keys beyond
# {value, mfu, spread} — mirrored as nulls in --dry skeleton mode so the
# driver sees a stable schema either way
_SUMMARY_EXTRA_KEYS = {
    "llama_serving": ("ttft_p50", "ttft_p99", "tpot",
                      "rejected", "timed_out", "quarantined",
                      "goodput_at_slo", "retraces"),
    "llama_serving_prefix": ("ttft_p50", "ttft_p99", "tpot",
                             "cache_hit_rate", "prefix_hits",
                             "prefix_evictions",
                             "goodput_at_slo", "retraces"),
    "llama_decode_int8": ("bytes_ratio_vs_bf16",),
    "llama_serving_int8": ("ttft_p50", "ttft_p99", "tpot",
                           "rejected", "timed_out", "quarantined",
                           "goodput_at_slo", "retraces",
                           "kv_quant_err_bound", "bytes_ratio_vs_bf16"),
    "llama_serving_fleet": ("ttft_p50", "ttft_p99", "tpot",
                            "failovers", "replayed_tokens", "shed",
                            "replicas_ejected",
                            "goodput_at_slo", "retraces"),
    "llama_serving_failover": ("ttft_p50", "ttft_p99", "tpot",
                               "failovers",
                               "replayed_tokens", "replayed_tokens_full",
                               "snapshot_restores", "snapshot_fallbacks",
                               "recovery_restored_tokens",
                               "recovery_replayed_tokens",
                               "goodput_at_slo", "goodput_at_slo_full",
                               "retraces"),
    "llama_serving_partition": ("ttft_p50", "ttft_p99", "tpot",
                                "failovers", "failovers_clean",
                                "stale_epoch_discarded",
                                "lease_expirations",
                                "duplicates_suppressed",
                                "transport_dropped",
                                "goodput_at_slo", "goodput_at_slo_clean",
                                "retraces"),
    "llama_serving_multihost": ("ttft_p50", "ttft_p99", "tpot",
                                "frames_sent", "frames_recv",
                                "frame_bytes_sent", "frame_bytes_recv",
                                "socket_reconnects",
                                "lease_expirations",
                                "goodput_at_slo",
                                "goodput_at_slo_loopback",
                                "tokens_per_s_loopback",
                                "retraces"),
    "llama_serving_chunked": ("ttft_p50", "ttft_p99", "tpot",
                              "itl_p99", "itl_p99_baseline",
                              "itl_p99_ratio",
                              "goodput_at_slo",
                              "goodput_at_slo_baseline",
                              "chunk_tokens_total", "retraces"),
    "llama_serving_spec": ("ttft_p50", "ttft_p99", "tpot",
                           "accept_rate", "draft_hit_rate",
                           "speedup_vs_decode",
                           "goodput_at_slo", "retraces"),
    "llama_serving_tiered": ("ttft_p50", "ttft_p99", "tpot",
                             "cache_hit_rate", "tier_hbm_hit_rate",
                             "tier_host_hit_rate", "tier_miss_rate",
                             "spilled_pages", "restored_pages", "shed",
                             "goodput_at_slo", "goodput_at_slo_notier",
                             "retraces"),
    "llama_serving_fairness": ("ttft_p50", "ttft_p99", "tpot",
                               "cold_ttft_p99", "cold_ttft_p99_fcfs",
                               "shed", "brownout_transitions",
                               "goodput_at_slo", "goodput_at_slo_fcfs",
                               "retraces"),
    "llama_serving_tp": ("ttft_p50", "ttft_p99", "tpot",
                         "tp_degree", "tp_shard_kv_bytes_per_token",
                         "kv_bytes_per_token",
                         "tokens_per_s_tp1",
                         "goodput_at_slo", "goodput_at_slo_tp1",
                         "retraces"),
    "llama_serving_pp": ("ttft_p50", "ttft_p99", "tpot",
                         "pp_degree", "pp_waves",
                         "pipeline_bubble_frac",
                         "pipeline_bubble_frac_unwaved",
                         "tp_shard_kv_bytes_per_token",
                         "tp_shard_kv_bytes_per_token_tponly",
                         "kv_bytes_per_token",
                         "tokens_per_s_tponly",
                         "goodput_at_slo", "goodput_at_slo_tponly",
                         "retraces"),
    "llama_serving_disagg": ("ttft_p50", "ttft_p99",
                             "ttft_p99_colocated", "tpot",
                             "itl_p99", "itl_p99_colocated",
                             "itl_p99_ratio_10x",
                             "itl_p99_colocated_ratio_10x",
                             "handoff_pulls", "handoff_bytes",
                             "handoff_recomputes",
                             "goodput_at_slo",
                             "goodput_at_slo_colocated", "retraces"),
    "llama_serving_lora": ("ttft_p50", "ttft_p99", "tpot",
                           "n_adapters", "adapter_hit_rate",
                           "adapter_loads", "adapter_evictions",
                           "lora_bytes_streamed",
                           "tokens_per_s_base", "tokens_per_s_single",
                           "multi_vs_single_ratio",
                           "goodput_at_slo", "goodput_at_slo_base",
                           "retraces"),
}

# opt-in configs (not in the default driver run — kept out to bound its
# wall time; run by name)
_EXTRA_CONFIGS = {
    "llama_longctx_32k": lambda peak, kind: bench_llama_longctx(
        peak, kind, seq=32768),
    # A/B arm for the fused Pallas MoE dispatch (PERF.md): same model and
    # shapes as qwen2_moe, dispatch="fused"
    "qwen2_moe_fused": lambda peak, kind: bench_qwen2_moe(
        peak, kind, ep_dispatch="fused"),
}


def _summary_entry(result, name=None):
    """Compact per-config summary cell: {value, mfu, spread} plus any
    config-specific keys (_SUMMARY_EXTRA_KEYS — e.g. serving's
    ttft_p50/ttft_p99/tpot). ``mfu`` takes whichever efficiency ratio the
    config reports (mfu, mfu_active, decode's batch-8 MBU, or serving's
    weights-only MBU); null when the config failed."""
    ex = result.get("extra") or {}
    mfu = ex.get("mfu", ex.get("mfu_active"))
    if mfu is None:
        mfu = ((ex.get("batches") or {}).get(8) or {}).get("mbu")
    if mfu is None:
        mfu = ex.get("mbu_weights_only")
    entry = {"value": result.get("value"), "mfu": mfu,
             "spread": ex.get("spread")}
    for k in _SUMMARY_EXTRA_KEYS.get(name, ()):
        entry[k] = ex.get(k)
    return entry


def main():
    argv = list(sys.argv[1:])
    # --trace PATH: dump a Chrome trace (Perfetto-loadable) of each
    # serving config's engine run. PATH gets the config name spliced in
    # before the extension. Parsed (and removed) BEFORE the config-name
    # filter below — PATH itself does not start with "-".
    trace_path = None
    if "--trace" in argv:
        i = argv.index("--trace")
        if i + 1 >= len(argv):
            raise SystemExit("--trace requires a PATH argument")
        trace_path = argv[i + 1]
        del argv[i:i + 2]
    args = [a for a in argv if not a.startswith("-")]
    dry = "--dry" in argv
    all_configs = {**_CONFIGS, **_EXTRA_CONFIGS}
    unknown = [a for a in args if a not in all_configs]
    if unknown:
        raise SystemExit(f"unknown bench config(s) {unknown}; "
                         f"choose from {list(all_configs)}")
    names = args or list(_CONFIGS)
    summary = {}
    if dry:
        # parse/skeleton mode (CI smoke test): no jax import, no device
        # work — emit only the final summary line with every selected
        # config present, values null
        for name in names:
            summary[name] = {"value": None, "mfu": None, "spread": None,
                             **{k: None
                                for k in _SUMMARY_EXTRA_KEYS.get(name, ())}}
        print(json.dumps({"bench_summary": summary, "dry": True}),
              flush=True)
        return

    import jax

    dev = jax.devices()[0]
    peak, peak_kind = _detect_peak(dev)
    failed = []

    def _release_hbm():
        # release the finished config's HBM before the next one: the big
        # configs (llama8b_shape needs ~14 GB for fp32 AdamW moments) OOM
        # if earlier configs' params/opt-states/compiled executables
        # linger — locals die on return, but jit caches pin buffers until
        # cleared
        import gc
        gc.collect()
        try:
            jax.clear_caches()
        except Exception:
            pass
        gc.collect()

    for name in names:
        # one retry per config: the tunneled chip's relay occasionally
        # drops a connection mid-run ("response body closed") — transient;
        # the cleanup between attempts also clears OOM-class leftovers.
        # Only the exceptions' reprs are kept: holding the exception
        # object would pin its traceback's frames, whose locals are the
        # very params/opt-state jax Arrays the retry needs freed.
        errs = []
        kwargs = ({"trace_path": trace_path}
                  if trace_path is not None and name in _SERVING_SLOS
                  else {})
        for attempt in (0, 1):
            try:
                result = all_configs[name](peak, peak_kind, **kwargs)
                if errs:
                    # a success on the retry must not hide that the config
                    # was flaky: surface the first attempt's failure on the
                    # success line (round-5 advisor finding)
                    result.setdefault("extra", {})["retried_after"] = errs[0]
                print(json.dumps(result), flush=True)
                summary[name] = _summary_entry(result, name)
                errs = []
                break
            except Exception as e:
                errs.append(repr(e)[:300])
            finally:
                # the except block's implicit `del e` ran before this, so
                # gc here can actually collect the frame cycle + buffers
                _release_hbm()
        if errs:  # one config failing must not kill the others
            failed.append(name)
            summary[name] = {"value": None, "mfu": None, "spread": None,
                             **{k: None
                                for k in _SUMMARY_EXTRA_KEYS.get(name, ())}}
            print(json.dumps({"metric": name, "value": None, "unit": "error",
                              "vs_baseline": 0.0,
                              "extra": {"error": errs[-1],
                                        "error_first_attempt": errs[0],
                                        "attempts": len(errs)}}),
                  flush=True)
    # driver contract: LAST stdout line = one-object summary of ALL
    # selected configs (before the failure exit, so partial runs report)
    print(json.dumps({"bench_summary": summary}), flush=True)
    if failed:  # ...but the run must still report failure to the driver
        raise SystemExit(f"bench config(s) failed: {failed}")


if __name__ == "__main__":
    main()
