"""Benchmark: Llama decoder pretraining throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Config: a ~420M-param Llama (hidden 2048, 8 layers) at seq 2048, bf16 params
and compute, fused train step (forward+backward+AdamW in one XLA program with
buffer donation), flash-attention Pallas kernel on the causal path, fused
Pallas RMS-norm. Batch 4 with NO activation recompute — measured fastest on
this chip (sweep 2026-07: b4/no-remat 25.7k tok/s vs b8/remat 22.1k, b6/
no-remat 24.1k; b8/no-remat exceeds compile memory). MFU against the v5e
nominal bf16 peak (197 TFLOP/s); vs_baseline is MFU / 0.40 (the BASELINE.md
north-star target).
"""

from __future__ import annotations

import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    pt.seed(0)
    batch, seq = 4, 2048
    cfg = LlamaConfig(vocab_size=32000, hidden_size=2048, intermediate_size=5632,
                      num_hidden_layers=8, num_attention_heads=16,
                      num_key_value_heads=8, max_position_embeddings=seq,
                      dtype="bfloat16", mp_axis=None, fsdp_axis=None,
                      recompute=False)
    model = LlamaForCausalLM(cfg)
    n_params = model.num_params()
    opt = pt.optimizer.AdamW(learning_rate=1e-4, parameters=model)
    step = pt.jit.TrainStep(model, opt,
                            lambda logits, labels: model.loss(logits, labels))
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)), jnp.int32)

    # warmup / compile
    loss = step(ids, ids)
    _ = float(loss)

    iters = 10
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(ids, ids)
    lossv = float(loss)  # forces completion of the chain
    dt = (time.perf_counter() - t0) / iters

    tokens_per_sec = batch * seq / dt
    # 6ND for fwd+bwd (attention FLOPs add ~12*L*h*s^2*d ≈ included via 6ND
    # underestimate; report the standard 6ND MFU)
    flops_per_token = 6.0 * n_params
    attn_flops = 12.0 * cfg.num_hidden_layers * seq * cfg.hidden_size
    model_flops = (flops_per_token + attn_flops) * tokens_per_sec
    peak = 197e12  # v5e nominal bf16
    mfu = model_flops / peak
    assert np.isfinite(lossv)
    print(json.dumps({
        "metric": "llama_420m_seq2048_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {"mfu": round(mfu, 4), "step_ms": round(dt * 1000, 2),
                  "params": n_params, "loss": round(lossv, 4),
                  "batch": batch, "seq": seq},
    }))


if __name__ == "__main__":
    main()
