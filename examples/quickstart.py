"""Quickstart: define a model, train it in one compiled step, save/load.

The paddle-style workflow on TPU: the whole training step (forward +
backward + optimizer) compiles into ONE XLA program via jit.TrainStep.
Runs on CPU too (this script forces CPU so it works anywhere):

    python examples/quickstart.py
"""
import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as pt
import paddle_tpu.nn.functional as F
from paddle_tpu import nn


def main():
    pt.seed(0)
    model = nn.Sequential(
        nn.Linear(28 * 28, 256), nn.ReLU(),
        nn.Linear(256, 64), nn.ReLU(),
        nn.Linear(64, 10),
    )
    opt = pt.optimizer.AdamW(learning_rate=1e-3, parameters=model)
    step = pt.jit.TrainStep(model, opt, lambda out, y: F.cross_entropy(out, y))

    rng = np.random.default_rng(0)
    x = rng.standard_normal((256, 28 * 28)).astype("float32")
    y = rng.integers(0, 10, 256).astype("int64")

    for epoch in range(5):
        loss = float(step(x, y))
        print(f"epoch {epoch}: loss {loss:.4f}")

    # checkpoint roundtrip (paddle API)
    import tempfile
    ckpt = os.path.join(tempfile.mkdtemp(), "quickstart.pdparams")
    pt.save(model.state_dict(), ckpt)
    model.set_state_dict(pt.load(ckpt))

    # eval
    model.eval()
    pred = np.asarray(model(x)).argmax(-1)
    print(f"train accuracy after 5 steps: {(pred == y).mean():.2f}")
    return loss


if __name__ == "__main__":
    main()
