"""Post-training quantization to an int8 deployment artifact.

PTQ flow: wrap -> calibrate -> convert (real int8 weights + fp32 scales,
dequantized on use) -> jit.save a source-free artifact -> reload.

    python examples/quantize_deploy.py
"""
import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.quantization import PTQ, QuantizedConv2D, QuantizedLinear


def main():
    pt.seed(0)
    model = nn.Sequential(
        nn.Conv2D(1, 8, 3, padding=1), nn.ReLU(), nn.MaxPool2D(2, 2),
        nn.Flatten(), nn.Linear(8 * 14 * 14, 10))
    x = jnp.asarray(np.random.default_rng(0).standard_normal((8, 1, 28, 28)),
                    jnp.float32)
    fp_out = np.asarray(model(x))

    ptq = PTQ()
    quanted = ptq.quantize(model)        # insert observers
    ptq.sample(quanted, x)               # calibrate
    deploy = ptq.convert(quanted)        # real int8 artifact

    qlayers = [s for s in deploy.sublayers()
               if isinstance(s, (QuantizedLinear, QuantizedConv2D))]
    for q in qlayers:
        print(f"{type(q).__name__}: weight {q.weight_q.dtype}"
              f"{tuple(q.weight_q.shape)}, scales {tuple(q.weight_scale.shape)}")
    int8_out = np.asarray(deploy(x))
    print(f"max |int8 - fp| output delta: {np.abs(int8_out - fp_out).max():.4f}")

    import tempfile
    path = os.path.join(tempfile.mkdtemp(), "example_int8")
    pt.jit.save(deploy, path,
                input_spec=[pt.jit.InputSpec((8, 1, 28, 28), "float32")])
    reloaded = pt.jit.load(path)
    np.testing.assert_allclose(np.asarray(reloaded(x)), int8_out,
                               rtol=2e-5, atol=2e-5)
    print(f"saved + reloaded source-free artifact at {path}")
    return float(np.abs(int8_out - fp_out).max())


if __name__ == "__main__":
    main()
