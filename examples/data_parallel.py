"""Hybrid-parallel training on a device mesh (runs on an 8-CPU virtual
mesh — the same code targets TPU pods).

The fleet workflow: declare degrees in DistributedStrategy, let GSPMD
shard parameters and insert collectives. Column/Row-parallel layers are
just weight shardings (Linear(weight_spec=...)).

    python examples/data_parallel.py
"""
import os
import sys

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8").strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as pt
import paddle_tpu.distributed as dist
import paddle_tpu.nn.functional as F
from paddle_tpu import nn
from paddle_tpu.core import mesh as mesh_lib


def main():
    strategy = dist.fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4}
    dist.fleet.init(strategy=strategy)
    mesh = dist.fleet.fleet_mesh()
    print(f"mesh: {dict(mesh.shape)} over {len(jax.devices())} devices")

    pt.seed(0)
    with mesh_lib.use_mesh(mesh):
        model = nn.Sequential(
            # column-parallel: output features sharded over 'mp'
            nn.Linear(64, 256, weight_spec=(None, "mp")), nn.ReLU(),
            # row-parallel: input features sharded; GSPMD inserts the
            # allreduce the reference codes by hand in mp_layers.py
            nn.Linear(256, 16, weight_spec=("mp", None)),
        )
        model = dist.fleet.distributed_model(model)
        opt = pt.optimizer.AdamW(learning_rate=1e-3, parameters=model)
        step = pt.jit.TrainStep(model, opt,
                                lambda out, y: F.cross_entropy(out, y))
        rng = np.random.default_rng(0)
        x = rng.standard_normal((64, 64)).astype("float32")
        y = rng.integers(0, 16, 64).astype("int64")
        for i in range(5):
            loss = float(step(x, y))
            print(f"step {i}: loss {loss:.4f}")
    return loss


if __name__ == "__main__":
    main()
