"""Serving: batched generation with compiled prefill + one-program decode.

The serving workflow (parity: the reference's AnalysisPredictor +
FusedMultiTransformer KV-cache decode): ``model.generate`` runs ONE jitted
prefill over the prompt and the WHOLE token loop as ONE jitted ``lax.scan``
over a fixed-size KV cache — two compiled programs total, cached on the
model per (batch, prompt_len, new_tokens) signature, so a serving loop
never retraces. Greedy and nucleus (top-p) sampling both ride the same
programs.

Runs on CPU as-is:

    python examples/serve_generate.py
"""
import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as pt
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny


def main():
    pt.seed(0)
    # the test-scale Llama config so the example runs in seconds on CPU;
    # the same code path serves llama_3_8b on a chip
    cfg = llama_tiny(mp_axis=None, fsdp_axis=None)
    model = LlamaForCausalLM(cfg)
    model.eval()

    rng = np.random.default_rng(0)
    prompts = rng.integers(0, cfg.vocab_size, (2, 12))  # [batch, prompt_len]

    # greedy: deterministic continuation
    out = model.generate(prompts, max_new_tokens=16)
    print("greedy      :", np.asarray(out)[0, 12:].tolist())

    # the second call with the same signature reuses the compiled
    # prefill + scan-decode programs (no retrace) — the serving pattern
    out2 = model.generate(prompts, max_new_tokens=16)
    assert np.array_equal(np.asarray(out), np.asarray(out2))
    assert len(model._decode_prog_cache) == 1  # one signature, one entry

    # nucleus sampling: seeded, reproducible
    s1 = model.generate(prompts, max_new_tokens=16, do_sample=True,
                        top_p=0.9, temperature=0.8, seed=7)
    s2 = model.generate(prompts, max_new_tokens=16, do_sample=True,
                        top_p=0.9, temperature=0.8, seed=7)
    assert np.array_equal(np.asarray(s1), np.asarray(s2))
    print("sampled     :", np.asarray(s1)[0, 12:].tolist())

    # token-by-token debugging path (identical greedy tokens)
    dbg = model.generate(prompts, max_new_tokens=16, jit_loop=False)
    assert np.array_equal(np.asarray(out), np.asarray(dbg))
    print("eager-loop  : identical to scan decode")
    # program economy: greedy reuses ONE (prefill, decode) pair across its
    # two calls; the sampled signature adds its own pair; the eager loop
    # adds its per-token step program
    print(f"ok: {len(model._decode_prog_cache)} cached signatures "
          f"served 10 sequences (5 calls x batch 2)")


if __name__ == "__main__":
    main()
