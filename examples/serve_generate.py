"""Serving: batched generation with compiled prefill + one-program decode.

The serving workflow (parity: the reference's AnalysisPredictor +
FusedMultiTransformer KV-cache decode): ``model.generate`` runs ONE jitted
prefill over the prompt and the WHOLE token loop as ONE jitted ``lax.scan``
over a fixed-size KV cache — two compiled programs total, cached on the
model per (batch, prompt_len, new_tokens) signature, so a serving loop
never retraces. Greedy and nucleus (top-p) sampling both ride the same
programs.

Runs on CPU as-is:

    python examples/serve_generate.py
"""
import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as pt
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny


def main():
    pt.seed(0)
    # the test-scale Llama config so the example runs in seconds on CPU;
    # the same code path serves llama_3_8b on a chip
    cfg = llama_tiny(mp_axis=None, fsdp_axis=None)
    model = LlamaForCausalLM(cfg)
    model.eval()

    rng = np.random.default_rng(0)
    prompts = rng.integers(0, cfg.vocab_size, (2, 12))  # [batch, prompt_len]

    # greedy: deterministic continuation
    out = model.generate(prompts, max_new_tokens=16)
    print("greedy      :", np.asarray(out)[0, 12:].tolist())

    # the second call with the same signature reuses the compiled
    # prefill + scan-decode programs (no retrace) — the serving pattern
    out2 = model.generate(prompts, max_new_tokens=16)
    assert np.array_equal(np.asarray(out), np.asarray(out2))
    assert model.decode_cache_stats()["signatures"] == 1  # one entry

    # nucleus sampling: seeded, reproducible
    s1 = model.generate(prompts, max_new_tokens=16, do_sample=True,
                        top_p=0.9, temperature=0.8, seed=7)
    s2 = model.generate(prompts, max_new_tokens=16, do_sample=True,
                        top_p=0.9, temperature=0.8, seed=7)
    assert np.array_equal(np.asarray(s1), np.asarray(s2))
    print("sampled     :", np.asarray(s1)[0, 12:].tolist())

    # token-by-token debugging path (identical greedy tokens)
    dbg = model.generate(prompts, max_new_tokens=16, jit_loop=False)
    assert np.array_equal(np.asarray(out), np.asarray(dbg))
    print("eager-loop  : identical to scan decode")
    # program economy: greedy reuses ONE (prefill, decode) pair across its
    # two calls; the sampled signature adds its own pair; the eager loop
    # adds its per-token step program — all visible through the PUBLIC
    # decode_cache_stats() accessor (never poke private model attributes)
    stats = model.decode_cache_stats()
    print(f"ok: {stats['signatures']} cached signatures "
          f"(capacity {stats['capacity']}) served 10 sequences")

    # --- continuous batching: ragged prompts, one paged KV pool ---------
    # generate() pads a fixed batch to the longest prompt; the serving
    # engine (SERVING.md) instead shares a paged pool with iteration-level
    # scheduling — and its greedy tokens are bitwise identical to
    # per-request generate()
    from paddle_tpu.serving import ServingEngine
    eng = ServingEngine(model, num_pages=64, page_size=4, max_slots=4)
    ragged = [list(rng.integers(0, cfg.vocab_size, n)) for n in (5, 12, 9)]
    rids = [eng.add_request(p, max_new_tokens=8) for p in ragged]
    results = eng.run_to_completion()
    for p, rid in zip(ragged, rids):
        ref = np.asarray(model.generate(np.asarray([p]),
                                        max_new_tokens=8))[0, len(p):]
        assert results[rid] == ref.tolist()
    assert eng.decode_program_count() == 1  # churn never retraced decode
    print("engine      :", results[rids[0]],
          f"(3 ragged requests, decode stayed 1 program, "
          f"{eng.metrics.summary()['tokens_generated']} tokens)")

    # --- automatic prefix caching: shared system prompt -----------------
    # the chat-serving workload (SERVING.md "Prefix caching"): every
    # request repeats the same long system prompt. The first prefill
    # registers its pages in the pool's content-hash index; the rest map
    # them and prefill only their own suffix — same bitwise tokens, a
    # fraction of the prefill work, visible as cache_hit_rate
    eng2 = ServingEngine(model, num_pages=64, page_size=4, max_slots=4,
                         max_pages_per_slot=16)
    system = list(rng.integers(0, cfg.vocab_size, 24))
    users = [list(rng.integers(0, cfg.vocab_size, n)) for n in (4, 7, 3)]
    rid0 = eng2.add_request(system + users[0], max_new_tokens=8)
    eng2.step()  # first request prefills + registers the shared pages
    more = [eng2.add_request(system + u, max_new_tokens=8)
            for u in users[1:]]
    shared_res = eng2.run_to_completion()
    for u, rid in zip(users, [rid0] + more):
        p = system + u
        ref = np.asarray(model.generate(np.asarray([p]),
                                        max_new_tokens=8))[0, len(p):]
        assert shared_res[rid] == ref.tolist()  # cache hits change nothing
    m = eng2.metrics.summary()
    print(f"prefix cache: hit_rate={m['cache_hit_rate']:.2f} "
          f"({m['prefill_cached_tokens']}/{m['prefill_tokens']} prefill "
          f"tokens served from cached pages, {m['prefix_hits']} hits, "
          f"tokens bitwise identical to cold generate())")

    # --- int8 quantized serving: KV cache + weight streaming ------------
    # decode is bandwidth-bound: every step re-reads the weights and the
    # whole KV cache. kv_quant=True stores pages as int8 codes + per-row
    # fp32 absmax scales (~half the KV bytes); quantize_for_serving swaps
    # decode matmuls to int8 weights dequantized in the matmul epilogue
    # (SERVING.md "Quantized KV & weights"). Greedy tokens match the fp
    # cache on this workload — the error model bounds per-element dequant
    # error at scale/2, and the A/B harness (tools/profile_serving.py
    # --kv-int8) checks >=99% token agreement on bigger traces.
    from paddle_tpu.quantization import quantize_for_serving, \
        serving_state_bytes
    eng3 = ServingEngine(model, num_pages=64, page_size=4, max_slots=4,
                         kv_quant=True)
    rids3 = [eng3.add_request(p, max_new_tokens=8) for p in ragged[:2]]
    res3 = eng3.run_to_completion()
    assert all(res3[r3] == results[r] for r3, r in zip(rids3, rids[:2]))
    assert eng3.decode_program_count() == 1
    qm = eng3.metrics.summary()
    qmodel = quantize_for_serving(model)
    fp_b, q_b = serving_state_bytes(model), serving_state_bytes(qmodel)
    print(f"int8 serving: tokens identical to fp cache, "
          f"kv {eng3.pool.kv_bytes_per_token()}B/token vs "
          f"{eng.pool.kv_bytes_per_token()}B fp, err_bound="
          f"{qm['kv_quant_err_bound']:.4f}, weights {fp_b/1e6:.1f}MB -> "
          f"{q_b/1e6:.1f}MB")

    # --- tiered KV cache: spill to host RAM, restore on hit -------------
    # when the HBM pool LRU-evicts a cached page, host_tier=True demotes
    # its bytes to a bounded host-RAM pool instead of losing them; a
    # later request whose prefix walks into the tier restores the pages
    # bit-exactly at admission time (SERVING.md "KV tiering & traffic
    # harness"). Pool sized so two alternating tenants cannot coexist:
    # every tenant switch evicts the other tenant's pages, every return
    # restores them — and the tokens STILL match cold generate()
    from paddle_tpu.serving import HostTier
    eng4 = ServingEngine(model, num_pages=14, page_size=4, max_slots=1,
                         host_tier=True)
    systems = [list(rng.integers(0, cfg.vocab_size, 24)) for _ in range(2)]
    for i in range(4):
        p = systems[i % 2] + list(rng.integers(0, cfg.vocab_size, 6))
        rid = eng4.add_request(p, max_new_tokens=6)
        ref = np.asarray(model.generate(np.asarray([p]),
                                        max_new_tokens=6))[0, len(p):]
        assert eng4.run_to_completion()[rid] == ref.tolist()
    assert eng4.decode_program_count() == 1  # restores are host-side
    ps = eng4.pool.stats()       # host-tier breakdown rides pool.stats()
    tm = eng4.metrics.summary()
    assert ps["restored_pages"] > 0
    print(f"tiered kv   : hit_rate={tm['cache_hit_rate']:.2f} "
          f"(hbm={tm['tier_hbm_hit_rate']:.2f} "
          f"host={tm['tier_host_hit_rate']:.2f}), spilled "
          f"{ps['spilled_pages']} pages / restored {ps['restored_pages']} "
          f"({ps['host_pool_bytes']}B in host pool), tokens bitwise "
          f"identical through the host round-trip")

    # HostTier(max_bytes=...) bounds the host pool; Workload/make_workload
    # (paddle_tpu.serving.workload) builds the seeded Poisson multi-tenant
    # traces the bench + profiler replay against it — see
    # tools/profile_serving.py --tiered and bench.py llama_serving_tiered
    _ = HostTier


if __name__ == "__main__":
    main()
