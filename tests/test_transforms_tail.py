"""Color + geometric transforms (parity: python/paddle/vision/transforms/
{transforms,functional}.py — ColorJitter family, rotate/affine/
perspective, RandomResizedCrop, RandomErasing)."""

import numpy as np
import pytest

import paddle_tpu.vision.transforms as T

RNG = np.random.default_rng(2)


def _img(c=3, h=8, w=8):
    return RNG.uniform(0, 1, (c, h, w)).astype(np.float32)


def test_adjust_brightness_contrast_identity_and_scale():
    img = _img()
    np.testing.assert_allclose(T.adjust_brightness(img, 1.0), img)
    np.testing.assert_allclose(T.adjust_brightness(img, 2.0), img * 2)
    np.testing.assert_allclose(T.adjust_contrast(img, 1.0), img, rtol=1e-6)
    # contrast 0 collapses to the gray mean
    flat = T.adjust_contrast(img, 0.0)
    assert np.ptp(flat) < 1e-6


def test_adjust_saturation_and_grayscale():
    img = _img()
    np.testing.assert_allclose(T.adjust_saturation(img, 1.0), img,
                               rtol=1e-6)
    gray = T.adjust_saturation(img, 0.0)
    # fully desaturated: all channels equal
    np.testing.assert_allclose(gray[0], gray[1], rtol=1e-5)
    g1 = T.to_grayscale(img)
    assert g1.shape == (1, 8, 8)
    g3 = T.to_grayscale(img, 3)
    assert g3.shape == (3, 8, 8)
    np.testing.assert_allclose(g3[0], g1[0])


def test_adjust_hue_identity_and_full_cycle():
    img = _img()
    np.testing.assert_allclose(T.adjust_hue(img, 0.0), img, atol=1e-5)
    # +0.5 then +0.5 wraps the hue circle back to the original
    back = T.adjust_hue(T.adjust_hue(img, 0.5), 0.5)
    np.testing.assert_allclose(back, img, atol=1e-4)
    with pytest.raises(ValueError):
        T.adjust_hue(img, 0.7)


def test_rotate_90_matches_numpy():
    img = _img(1, 6, 6)
    out = T.rotate(img, 90.0)
    # 90-degree CCW rotation about the center equals np.rot90 on (H, W)
    ref = np.rot90(img[0]).copy()
    np.testing.assert_allclose(out[0], ref, atol=1e-4)


def test_rotate_zero_and_affine_identity():
    img = _img()
    np.testing.assert_allclose(T.rotate(img, 0.0), img, atol=1e-5)
    np.testing.assert_allclose(T.affine(img, 0.0), img, atol=1e-5)


def test_affine_translate_shifts():
    img = _img(1, 8, 8)
    out = T.affine(img, 0.0, translate=(2, 0))
    np.testing.assert_allclose(out[0, :, 2:], img[0, :, :-2], atol=1e-4)


def test_perspective_identity_corners():
    img = _img()
    pts = [(0, 0), (7, 0), (7, 7), (0, 7)]
    np.testing.assert_allclose(T.perspective(img, pts, pts), img, atol=1e-4)


def test_erase_and_random_erasing():
    img = _img()
    out = T.erase(img, 2, 3, 2, 2, 0.0)
    assert np.abs(out[:, 2:4, 3:5]).sum() == 0
    assert np.abs(out[:, :2]).sum() > 0
    np.random.seed(0)
    er = T.RandomErasing(prob=1.0)(img)
    assert er.shape == img.shape
    assert not np.allclose(er, img)


def test_random_resized_crop_shape():
    np.random.seed(0)
    out = T.RandomResizedCrop(4)(_img(3, 16, 16))
    assert out.shape == (3, 4, 4)


def test_color_jitter_and_random_transforms_shapes():
    np.random.seed(1)
    img = _img()
    for t in (T.ColorJitter(0.4, 0.4, 0.4, 0.1), T.Grayscale(3),
              T.RandomRotation(30), T.RandomPerspective(prob=1.0),
              T.RandomAffine(10, translate=(0.1, 0.1), scale=(0.9, 1.1))):
        out = t(img)
        assert out.shape == img.shape, type(t).__name__


def test_crop_center_crop_pad_functions():
    img = _img(3, 8, 10)
    assert T.crop(img, 1, 2, 4, 5).shape == (3, 4, 5)
    assert T.center_crop(img, 6).shape == (3, 6, 6)
    assert T.pad(img, 2).shape == (3, 12, 14)


def test_review_regressions_transforms():
    img = _img()
    # per-channel erase value
    out = T.erase(img, 1, 1, 3, 4, np.array([0.1, 0.2, 0.3], np.float32))
    np.testing.assert_allclose(out[:, 1:4, 1:5],
                               np.broadcast_to(
                                   np.array([0.1, 0.2, 0.3],
                                            np.float32)[:, None, None],
                                   (3, 3, 4)))
    # tuple ranges accepted by the jitter family
    np.random.seed(0)
    T.ColorJitter(brightness=(0.5, 1.5), contrast=(0.8, 1.2),
                  saturation=(0.9, 1.1), hue=(-0.1, 0.1))(img)
    # sequence shear is applied (result differs from shear=None)
    np.random.seed(3)
    a = T.RandomAffine(0, shear=[10, 10])(img)
    assert not np.allclose(a, img)
    # expand-rotate fills the expansion band with `fill`
    big = T.rotate(np.full((1, 6, 6), 100.0, np.float32), 45.0,
                   expand=True, fill=50.0)
    corners = [big[0, 0, 0], big[0, 0, -1], big[0, -1, 0], big[0, -1, -1]]
    for c in corners:
        assert abs(c - 50.0) < 1.0, corners


def test_per_channel_fill_and_array_erase_value():
    img = _img()
    out = T.rotate(img, 30.0, fill=[1.0, 2.0, 3.0])
    assert out.shape == img.shape
    # a corner rotated out of frame reads the per-channel fill
    np.testing.assert_allclose(out[:, 0, 0], [1.0, 2.0, 3.0], atol=0.2)
    big = T.rotate(img, 45.0, expand=True, fill=[1.0, 2.0, 3.0])
    np.testing.assert_allclose(big[:, 0, 0], [1.0, 2.0, 3.0], atol=0.2)
    np.random.seed(0)
    er = T.RandomErasing(prob=1.0, value=np.array([0.5, 0.6, 0.7],
                                                  np.float32))(img)
    assert er.shape == img.shape
