"""Custom-op toolchain (parity: utils/cpp_extension + PD_BUILD_OP,
test model: test/custom_op/ — register an op, check forward, backward,
sharding-rule dispatch, and contract-suite enrollment)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu.core import mesh as mesh_lib
from paddle_tpu.core.registry import all_ops
from paddle_tpu.utils.custom_op import CustomOpBuilder, register_custom_op

RNG = np.random.default_rng(0)


def _make_sscale(name):
    def fwd(x, alpha):
        return jnp.tanh(x) * alpha

    def bwd(res, g):
        x, alpha = res
        t = jnp.tanh(x)
        return g * alpha * (1 - t * t), jnp.sum(g * t)

    return register_custom_op(
        name, fwd, bwd=bwd,
        ref=lambda x, a: np.tanh(x) * a,
        make_inputs=lambda rng: (
            rng.standard_normal((4, 8)).astype(np.float32), np.float32(1.7)),
        grad_ref=True,
        sharding_rule=lambda mesh, x, a: ((P("dp"), P()), P("dp")))


def test_custom_op_forward_and_enrollment():
    op = _make_sscale("sscale_t1")
    x = RNG.standard_normal((4, 8)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(op(x, np.float32(2.0))),
                               np.tanh(x) * 2.0, rtol=1e-6)
    info = all_ops()["sscale_t1"]
    assert info.ref is not None and info.category == "custom"
    # the enrolled row passes its own contract
    xs = info.make_inputs(np.random.default_rng(0))
    np.testing.assert_allclose(np.asarray(info.fn_call(*xs)),
                               info.ref(*xs), rtol=1e-5, atol=1e-6)


def test_custom_op_custom_vjp_used():
    op = _make_sscale("sscale_t2")
    x = jnp.asarray(RNG.standard_normal((4, 8)), jnp.float32)
    g = jax.grad(lambda x: jnp.sum(op(x, jnp.float32(1.5))))(x)
    t = np.tanh(np.asarray(x))
    np.testing.assert_allclose(np.asarray(g), 1.5 * (1 - t * t),
                               rtol=1e-5, atol=1e-6)
    ga = jax.grad(lambda a: jnp.sum(op(x, a)))(jnp.float32(1.5))
    np.testing.assert_allclose(float(ga), float(np.sum(t)), rtol=1e-5)


def test_custom_op_sharding_rule_dispatch():
    """With a mesh active, the op must run through its shard_map rule and
    still produce the correct global result on dp-sharded input."""
    op = _make_sscale("sscale_t3")
    mesh = Mesh(np.array(jax.devices()).reshape(8), ("dp",))
    x = RNG.standard_normal((16, 8)).astype(np.float32)
    with mesh_lib.use_mesh(mesh):
        out = op(jnp.asarray(x), jnp.float32(1.2))
    np.testing.assert_allclose(np.asarray(out), np.tanh(x) * 1.2,
                               rtol=1e-5, atol=1e-6)


def test_builder_fluent_api():
    op = (CustomOpBuilder("sscale_t4")
          .forward(lambda x: jnp.square(x))
          .backward(lambda res, g: (2.0 * res[0] * g,))
          .reference(lambda x: x ** 2,
                     lambda rng: (rng.standard_normal((3, 3))
                                  .astype(np.float32),), grad_ref=True)
          .build())
    x = jnp.asarray(RNG.standard_normal((3, 3)), jnp.float32)
    np.testing.assert_allclose(np.asarray(op(x)), np.asarray(x) ** 2,
                               rtol=1e-6)
    g = jax.grad(lambda x: jnp.sum(op(x)))(x)
    np.testing.assert_allclose(np.asarray(g), 2 * np.asarray(x), rtol=1e-6)


def test_cpp_extension_host_build():
    """The one legitimate native path: build + dlopen a host C++ helper."""
    import ctypes
    from paddle_tpu.utils import cpp_extension
    lib = cpp_extension.load_inline(
        "t_addmul", "extern \"C\" double addmul(double a, double b) "
        "{ return a * b + 1.0; }")
    lib.addmul.restype = ctypes.c_double
    lib.addmul.argtypes = [ctypes.c_double, ctypes.c_double]
    assert lib.addmul(3.0, 4.0) == 13.0


def test_cuda_extension_raises_actionable():
    from paddle_tpu.utils import cpp_extension
    try:
        cpp_extension.CUDAExtension(["x.cu"])
        raise AssertionError("should have raised")
    except RuntimeError as e:
        assert "register_custom_op" in str(e)
