"""Remaining API families (parity rows: sparse, quantization, audio, text,
vision model zoo, device memory stats, multiprocess DataLoader, sharding
offload — SURVEY §2.6 rows 41/43 and §2.3 memory stats)."""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn

RNG = np.random.default_rng(0)


# ---------------- sparse ----------------

def test_sparse_coo_roundtrip_and_ops():
    dense = np.array([[0, 1, 0], [2, 0, 0], [0, 0, 3]], np.float32)
    s = pt.sparse.to_sparse_coo(dense)
    assert pt.sparse.is_sparse_coo(s)
    assert int(pt.sparse.nnz(s)) == 3
    np.testing.assert_allclose(np.asarray(pt.sparse.to_dense(s)), dense)
    np.testing.assert_allclose(
        np.asarray(pt.sparse.to_dense(pt.sparse.add(s, s))), dense * 2)
    np.testing.assert_allclose(
        np.asarray(pt.sparse.to_dense(pt.sparse.relu(
            pt.sparse.to_sparse_coo(-dense)))), np.zeros_like(dense))
    y = RNG.standard_normal((3, 4)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(pt.sparse.matmul(s, y)), dense @ y,
                               rtol=1e-5, atol=1e-5)
    csr = pt.sparse.to_sparse_csr(dense)
    assert pt.sparse.is_sparse_csr(csr)
    np.testing.assert_allclose(np.asarray(pt.sparse.to_dense(csr)), dense)


def test_sparse_masked_matmul():
    x = RNG.standard_normal((4, 5)).astype(np.float32)
    y = RNG.standard_normal((5, 4)).astype(np.float32)
    mask = (RNG.uniform(size=(4, 4)) > 0.5).astype(np.float32)
    out = pt.sparse.masked_matmul(x, y, mask)
    np.testing.assert_allclose(np.asarray(pt.sparse.to_dense(out)),
                               (x @ y) * (mask != 0), rtol=1e-4, atol=1e-5)


def test_sparse_coo_creation_api():
    s = pt.sparse.sparse_coo_tensor([[0, 1], [1, 0]], [5.0, 6.0],
                                    shape=(2, 2))
    np.testing.assert_allclose(np.asarray(pt.sparse.to_dense(s)),
                               [[0, 5], [6, 0]])


# ---------------- quantization ----------------

def test_qat_close_to_fp_and_trainable():
    pt.seed(0)
    net = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))
    q = pt.quantization.QAT().quantize(net)
    x = jnp.asarray(RNG.standard_normal((16, 8)), jnp.float32)
    ref = np.asarray(net(x))
    got = np.asarray(q(x))
    assert np.abs(got - ref).max() < 0.2  # int8 simulation error bound
    # STE: gradients flow through fake quant
    import paddle_tpu.nn.functional as F
    opt = pt.optimizer.Adam(learning_rate=1e-2, parameters=q)
    step = pt.jit.TrainStep(q, opt, lambda o, y: F.cross_entropy(o, y))
    y = RNG.integers(0, 4, 16)
    losses = [float(step(np.asarray(x), y)) for _ in range(6)]
    assert losses[-1] < losses[0]


def test_ptq_observer_flow():
    pt.seed(1)
    net = nn.Sequential(nn.Linear(8, 4))
    ptq = pt.quantization.PTQ()
    m = ptq.quantize(net)
    for _ in range(3):
        ptq.sample(m, jnp.asarray(RNG.standard_normal((4, 8)), jnp.float32))
    frozen = ptq.convert(m)
    out = frozen(jnp.asarray(RNG.standard_normal((2, 8)), jnp.float32))
    assert np.isfinite(np.asarray(out)).all()


def test_quant_dequant_grid():
    x = jnp.asarray([-1.0, -0.5, 0.0, 0.5, 1.0])
    out = np.asarray(pt.quantization.quant_dequant(x, jnp.float32(1.0)))
    np.testing.assert_allclose(out, np.asarray(x), atol=1.0 / 127)
    g = jax.grad(lambda x: jnp.sum(
        pt.quantization.quant_dequant(x, jnp.float32(1.0))))(x)
    np.testing.assert_allclose(np.asarray(g), 1.0)  # STE


# ---------------- audio ----------------

def test_audio_mel_pipeline():
    import paddle_tpu.audio as A
    wav = np.sin(2 * np.pi * 440 * np.arange(8000) / 8000).astype(np.float32)
    spec = A.Spectrogram(n_fft=256, hop_length=128)(wav[None])
    assert spec.shape[1] == 129
    mel = A.MelSpectrogram(sr=8000, n_fft=256, hop_length=128, n_mels=40,
                           f_min=0.0)(wav[None])
    assert mel.shape[1] == 40
    # 440 Hz must dominate the spectrum row nearest 440 Hz
    sp = np.asarray(spec[0])
    peak_bin = sp.mean(-1).argmax()
    assert abs(peak_bin * 8000 / 256 - 440) < 100
    mfcc = A.MFCC(sr=8000, n_mfcc=13, n_mels=40, n_fft=256,
                  hop_length=128)(wav[None])
    assert mfcc.shape[1] == 13 and np.isfinite(np.asarray(mfcc)).all()


def test_audio_functional_contracts():
    import paddle_tpu.audio.functional as AF
    np.testing.assert_allclose(float(AF.hz_to_mel(1000.0)), 15.0, rtol=1e-5)
    np.testing.assert_allclose(
        float(AF.mel_to_hz(AF.hz_to_mel(3000.0))), 3000.0, rtol=1e-4)
    fb = AF.compute_fbank_matrix(16000, 512, 64)
    assert fb.shape == (64, 257)
    assert float(jnp.min(fb)) >= 0
    w = AF.get_window("hann", 128)
    np.testing.assert_allclose(np.asarray(w),
                               np.hanning(129)[:128], atol=1e-5)


# ---------------- text ----------------

def test_viterbi_decode_matches_bruteforce():
    pot = RNG.standard_normal((2, 5, 4)).astype(np.float32)
    trans = RNG.standard_normal((4, 4)).astype(np.float32)
    scores, paths = pt.text.viterbi_decode(pot, trans,
                                           include_bos_eos_tag=False)
    for b in range(2):
        best, bestp = -1e9, None
        for p in itertools.product(range(4), repeat=5):
            sc = pot[b, 0, p[0]] + sum(
                trans[p[i - 1], p[i]] + pot[b, i, p[i]] for i in range(1, 5))
            if sc > best:
                best, bestp = sc, p
        assert abs(float(scores[b]) - best) < 1e-3
        assert tuple(np.asarray(paths[b])) == bestp
    dec = pt.text.ViterbiDecoder(trans, include_bos_eos_tag=False)
    s2, p2 = dec(pot)
    np.testing.assert_allclose(np.asarray(s2), np.asarray(scores))


# ---------------- vision zoo ----------------

@pytest.mark.parametrize("ctor,kw", [
    ("vgg11", dict(num_classes=7)),
    ("mobilenet_v1", dict(scale=0.25, num_classes=7)),
    ("mobilenet_v2", dict(scale=0.25, num_classes=7)),
    ("alexnet", dict(num_classes=7)),
    ("squeezenet1_1", dict(num_classes=7)),
])
def test_vision_model_zoo_forward(ctor, kw):
    from paddle_tpu.vision import models as M
    pt.seed(0)
    m = getattr(M, ctor)(**kw)
    m.eval()
    x = jnp.asarray(RNG.standard_normal((2, 3, 64, 64)), jnp.float32)
    out = m(x)
    assert out.shape == (2, 7)
    assert np.isfinite(np.asarray(out)).all()


# ---------------- device / memory stats ----------------

def test_device_memory_stats():
    x = jnp.zeros((256, 256))
    x.block_until_ready()
    assert pt.device.memory_allocated() >= 0
    assert pt.device.max_memory_allocated() >= pt.device.memory_allocated() - 1
    props = pt.device.get_device_properties()
    assert props.platform in ("cpu", "tpu")
    pt.device.cuda.synchronize()  # name-compat shim
    ev1, ev2 = pt.device.Event(), pt.device.Event()
    ev1.record()
    ev2.record()
    assert ev1.elapsed_time(ev2) >= 0


# ---------------- multiprocess DataLoader ----------------

def test_dataloader_multiprocess_workers():
    from paddle_tpu.io.dataset import TensorDataset
    from paddle_tpu.io.dataloader import DataLoader
    x = np.arange(64, dtype=np.float32).reshape(32, 2)
    y = np.arange(32, dtype=np.int64)
    ds = TensorDataset([x, y])
    loader = DataLoader(ds, batch_size=8, num_workers=2, shuffle=False,
                        to_device=False, use_buffer_reader=False)
    batches = list(loader)
    assert len(batches) == 4
    np.testing.assert_allclose(np.asarray(batches[0][0]), x[:8])
    np.testing.assert_allclose(np.asarray(batches[3][1]), y[24:])
    # second epoch reuses the worker pool
    batches2 = list(loader)
    np.testing.assert_allclose(np.asarray(batches2[0][0]), x[:8])
    loader._mp_pool.shutdown()


# ---------------- sharding offload ----------------

def test_group_sharded_offload_places_state_on_host():
    from jax.sharding import Mesh
    from paddle_tpu.core import mesh as mesh_lib
    from paddle_tpu.distributed.sharding import group_sharded_parallel
    import paddle_tpu.nn.functional as F
    pt.seed(2)
    mesh = Mesh(np.array(jax.devices()).reshape(1, 8), ("dp", "fsdp"))
    with mesh_lib.use_mesh(mesh):
        net = nn.Sequential(nn.Linear(64, 4096), nn.ReLU(),
                            nn.Linear(4096, 8))
        opt = pt.optimizer.AdamW(learning_rate=1e-3, parameters=net)
        net, opt, _ = group_sharded_parallel(net, opt, level="os_g",
                                             offload=True,
                                             segment_size=1024)
        state = opt.init_state(net.param_dict())
        kinds = {getattr(v.sharding, "memory_kind", None)
                 for slot in opt.slots
                 for v in state[slot].values()
                 if hasattr(v, "sharding")}
        # TPU/GPU PJRT name the host space "pinned_host"; the jax CPU
        # backend names it "unpinned_host" — either proves the slots
        # were parked in host memory, which is what offload promises
        assert kinds & {"pinned_host", "unpinned_host"}, kinds


# ---------------- geometric / onnx / launch auto-tuner ----------------

def test_geometric_send_u_recv():
    x = np.arange(12, dtype=np.float32).reshape(4, 3)
    src = np.array([0, 1, 2, 3, 0])
    dst = np.array([1, 1, 0, 0, 3])
    out = pt.geometric.send_u_recv(x, src, dst, "sum")
    want = np.zeros((4, 3), np.float32)
    for s, d in zip(src, dst):
        want[d] += x[s]
    np.testing.assert_allclose(np.asarray(out), want)
    out_mean = pt.geometric.send_u_recv(x, src, dst, "mean")
    cnt = np.bincount(dst, minlength=4)[:, None]
    np.testing.assert_allclose(np.asarray(out_mean),
                               want / np.maximum(cnt, 1), rtol=1e-6)
    out_max = pt.geometric.send_u_recv(x, src, dst, "max")
    assert np.asarray(out_max)[2].sum() == 0  # empty segment zeroed


def test_geometric_edge_ops_and_segments():
    x = np.arange(8, dtype=np.float32).reshape(4, 2)
    e = np.ones((3, 2), np.float32)
    src = np.array([0, 1, 2])
    dst = np.array([1, 2, 3])
    out = pt.geometric.send_ue_recv(x, e, src, dst, "add", "sum")
    assert out.shape == (4, 2)
    uv = pt.geometric.send_uv(x, x, src, dst, "mul")
    np.testing.assert_allclose(np.asarray(uv), np.asarray(x)[src] * np.asarray(x)[dst])
    seg = pt.geometric.segment_mean(x, np.array([0, 0, 1, 1]))
    np.testing.assert_allclose(np.asarray(seg),
                               [x[:2].mean(0), x[2:].mean(0)], rtol=1e-6)


def test_onnx_export_is_stablehlo(tmp_path):
    from paddle_tpu import nn
    pt.seed(0)
    net = nn.Sequential(nn.Linear(4, 2))
    net.eval()
    pt.onnx.export(net, str(tmp_path / "m"),
                   input_spec=[pt.jit.InputSpec([2, 4])])
    loaded = pt.jit.load(str(tmp_path / "m"))
    x = RNG.standard_normal((2, 4)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(loaded(x)), np.asarray(net(x)),
                               rtol=1e-5, atol=1e-6)
    with pytest.raises(NotImplementedError):
        pt.onnx.export(net, str(tmp_path / "m.onnx"),
                       input_spec=[pt.jit.InputSpec([2, 4])])


def test_launch_auto_tuner_exports_env(tmp_path):
    import json
    import subprocess
    import sys
    import os as _os
    spec = {"n_params": 25_000_000, "num_layers": 4, "hidden": 512,
            "seq_len": 512, "vocab": 32000, "global_batch": 64,
            "n_devices": 8}
    cfg = tmp_path / "tune.json"
    cfg.write_text(json.dumps(spec))
    script = tmp_path / "w.py"
    script.write_text(
        "import os, json\n"
        "print(json.dumps({k: v for k, v in os.environ.items()\n"
        "                  if k.startswith('PADDLE_AUTO_')}))\n")
    repo = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    code = (f"import sys; sys.path.insert(0, {repo!r});\n"
            f"from paddle_tpu.distributed.launch.main import launch\n"
            f"sys.exit(launch(['--nproc_per_node', '1', '--auto_tuner_json',"
            f" {str(cfg)!r}, {str(script)!r}]))")
    proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-1500:]
    env = json.loads(proc.stdout.strip().splitlines()[-1])
    assert "PADDLE_AUTO_DP_DEGREE" in env
    degs = [int(env[f"PADDLE_AUTO_{a}_DEGREE"])
            for a in ("DP", "FSDP", "MP", "PP", "SEP")]
    assert np.prod(degs) == 8
    assert "[auto_tuner] selected" in proc.stderr


def test_strings_family():
    s = ["Hello", "WORLD", "MiXeD"]
    np.testing.assert_array_equal(pt.strings.lower(s),
                                  ["hello", "world", "mixed"])
    np.testing.assert_array_equal(pt.strings.upper(s),
                                  ["HELLO", "WORLD", "MIXED"])
    np.testing.assert_array_equal(pt.strings.length(s), [5, 5, 5])
    t, lens = pt.strings.to_tensor(["ab", "xyz"])
    assert t.shape == (2, 3) and t.dtype == np.uint8
    assert pt.strings.to_strings(t, lens) == ["ab", "xyz"]
    # unicode roundtrip
    t2, l2 = pt.strings.to_tensor(["héllo", "日本"])
    assert pt.strings.to_strings(t2, l2) == ["héllo", "日本"]


def test_static_compat_surface(tmp_path):
    """paddle.static shims map onto the jit path (SURVEY jit-everything
    collapse); InputSpec/save/load_inference_model work end-to-end."""
    import paddle_tpu as pt
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    with pytest.raises(ValueError, match="STATIC"):
        pt.static.InputSpec(shape=[None, 4]).to_sds()
    spec = [pt.static.InputSpec(shape=[3, 4], dtype="float32")]
    prefix = str(tmp_path / "inf")
    pt.static.save_inference_model(prefix, spec, net)
    prog = pt.static.load_inference_model(prefix)
    x = np.random.default_rng(0).standard_normal((3, 4)).astype("float32")
    np.testing.assert_allclose(np.asarray(prog(x)), np.asarray(net(x)),
                               rtol=2e-5, atol=1e-5)
    with pytest.raises(NotImplementedError):
        pt.static.default_main_program().global_block()
    with pt.static.name_scope("block"):
        pass
    assert pt.version.full_version.startswith("3.")
