"""Detection/vision ops (parity: python/paddle/vision/ops.py +
test/legacy_test/test_{roi_align,nms,box_coder,yolo_box}_op.py)."""

import numpy as np
import pytest

from paddle_tpu.vision import ops

RNG = np.random.default_rng(11)


# ---------------- roi family ----------------

def test_roi_align_matches_manual_bilinear():
    # 1x1 output over an axis-aligned box centers on known coordinates
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    boxes = np.array([[0.0, 0.0, 2.0, 2.0]], np.float32)
    out = ops.roi_align(x, boxes, [1], output_size=1, sampling_ratio=1,
                        aligned=False)
    # single sample at bin center (1.0, 1.0) -> value x[1,1] = 5
    np.testing.assert_allclose(np.asarray(out), [[[[5.0]]]], atol=1e-5)


def test_roi_align_is_differentiable():
    import jax
    import jax.numpy as jnp
    x = jnp.asarray(RNG.standard_normal((1, 2, 8, 8)), jnp.float32)
    boxes = jnp.asarray([[1.0, 1.0, 6.0, 6.0]], jnp.float32)
    g = jax.grad(lambda x_: ops.roi_align(
        x_, boxes, [1], output_size=2).sum())(x)
    assert np.isfinite(np.asarray(g)).all()
    assert float(jnp.abs(g).sum()) > 0


def test_roi_pool_max_semantics():
    x = np.zeros((1, 1, 4, 4), np.float32)
    x[0, 0, 0, 0] = 7.0
    x[0, 0, 3, 3] = 9.0
    boxes = np.array([[0.0, 0.0, 3.0, 3.0]], np.float32)
    out = np.asarray(ops.roi_pool(x, boxes, [1], output_size=2))
    assert out[0, 0, 0, 0] == 7.0  # top-left bin max
    assert out[0, 0, 1, 1] == 9.0  # bottom-right bin max


def test_psroi_pool_reads_position_channels():
    # C = out_c(1) * 2*2; bin (i,j) must read channel i*2+j only
    x = np.zeros((1, 4, 4, 4), np.float32)
    for c in range(4):
        x[0, c] = c + 1
    boxes = np.array([[0.0, 0.0, 4.0, 4.0]], np.float32)
    out = np.asarray(ops.psroi_pool(x, boxes, [1], output_size=2))
    np.testing.assert_allclose(out[0, 0], [[1, 2], [3, 4]], atol=1e-5)


def test_roi_batch_routing():
    # two images; second box must read the second image's features
    x = np.stack([np.zeros((1, 4, 4), np.float32),
                  np.full((1, 4, 4), 3.0, np.float32)])
    boxes = np.array([[0, 0, 3, 3], [0, 0, 3, 3]], np.float32)
    out = np.asarray(ops.roi_align(x, boxes, [1, 1], output_size=1))
    assert abs(out[0, 0, 0, 0]) < 1e-6
    assert abs(out[1, 0, 0, 0] - 3.0) < 1e-5


# ---------------- deformable conv ----------------

def test_deform_conv2d_zero_offset_equals_conv2d():
    import jax.numpy as jnp
    import paddle_tpu.nn.functional as F
    x = RNG.standard_normal((2, 3, 8, 8)).astype(np.float32)
    w = RNG.standard_normal((4, 3, 3, 3)).astype(np.float32)
    off = np.zeros((2, 2 * 9, 6, 6), np.float32)
    out = ops.deform_conv2d(x, off, w)
    ref = F.conv2d(jnp.asarray(x), jnp.asarray(w), stride=1, padding=0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4,
                               atol=1e-4)


def test_deform_conv2d_mask_scales_contribution():
    x = np.ones((1, 1, 5, 5), np.float32)
    w = np.ones((1, 1, 3, 3), np.float32)
    off = np.zeros((1, 18, 3, 3), np.float32)
    full = np.asarray(ops.deform_conv2d(x, off, w))
    half = np.asarray(ops.deform_conv2d(
        x, off, w, mask=np.full((1, 9, 3, 3), 0.5, np.float32)))
    np.testing.assert_allclose(half, full * 0.5, rtol=1e-5)


def test_deform_conv2d_layer_shape_and_integer_shift():
    # offset (0, 1) shifts sampling one column right: equals plain conv of
    # the shifted input
    import paddle_tpu as pt
    from paddle_tpu.vision.ops import DeformConv2D
    pt.seed(0)
    layer = DeformConv2D(2, 3, 3, padding=1)
    x = RNG.standard_normal((1, 2, 6, 6)).astype(np.float32)
    off0 = np.zeros((1, 18, 6, 6), np.float32)
    base = np.asarray(layer(x, off0))
    assert base.shape == (1, 3, 6, 6)
    xs = np.roll(x, -1, axis=3)
    off1 = np.zeros((1, 18, 6, 6), np.float32)
    off1[:, 1::2] = 1.0  # dx = +1 for every tap
    shifted = np.asarray(layer(x, off1))
    # interior columns (away from the roll wrap + zero padding border)
    np.testing.assert_allclose(shifted[..., 1:-2, 1:-2],
                               np.asarray(layer(xs, off0))[..., 1:-2, 1:-2],
                               rtol=2e-4, atol=2e-4)


# ---------------- boxes ----------------

def test_box_coder_encode_decode_roundtrip():
    prior = RNG.uniform(0, 8, (5, 2)).astype(np.float32)
    prior = np.concatenate([prior, prior + RNG.uniform(1, 4, (5, 2))
                            .astype(np.float32)], -1)
    target = RNG.uniform(0, 8, (3, 2)).astype(np.float32)
    target = np.concatenate([target, target + RNG.uniform(1, 4, (3, 2))
                             .astype(np.float32)], -1)
    var = [0.1, 0.1, 0.2, 0.2]
    enc = ops.box_coder(prior, var, target, "encode_center_size")
    assert enc.shape == (3, 5, 4)
    dec = ops.box_coder(prior, var, np.asarray(enc),
                        "decode_center_size", axis=0)
    # decoding its own encoding returns the target box against each prior
    for m in range(5):
        np.testing.assert_allclose(np.asarray(dec)[:, m], target, rtol=1e-3,
                                   atol=1e-3)


def test_prior_box_shapes_and_range():
    feat = np.zeros((1, 8, 4, 4))
    img = np.zeros((1, 3, 32, 32))
    boxes, var = ops.prior_box(feat, img, min_sizes=[8.0], max_sizes=[16.0],
                               aspect_ratios=[2.0], flip=True, clip=True)
    assert boxes.shape[:2] == (4, 4) and boxes.shape[-1] == 4
    assert var.shape == boxes.shape
    b = np.asarray(boxes)
    assert (b >= 0).all() and (b <= 1).all()
    # anchors: min(1) + ar 2 + ar 1/2 + max = 4
    assert boxes.shape[2] == 4


def test_nms_reference_example():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [20, 20, 30, 30]],
                     np.float32)
    scores = np.array([0.9, 0.8, 0.7], np.float32)
    kept = ops.nms(boxes, 0.5, scores)
    assert kept.tolist() == [0, 2]  # box 1 suppressed by box 0
    # categorized: different categories never suppress each other
    kept2 = ops.nms(boxes, 0.5, scores, np.array([0, 1, 0]), [0, 1])
    assert sorted(kept2.tolist()) == [0, 1, 2]
    kept3 = ops.nms(boxes, 0.5, scores, top_k=1)
    assert kept3.tolist() == [0]


def test_matrix_nms_decays_not_removes():
    boxes = np.array([[[0, 0, 10, 10], [1, 1, 11, 11], [20, 20, 30, 30]]],
                     np.float32)
    scores = np.array([[[0.9, 0.8, 0.7]]], np.float32)  # one fg class
    out, idx, num = ops.matrix_nms(boxes, scores, score_threshold=0.1,
                                   post_threshold=0.0, nms_top_k=-1,
                                   keep_top_k=-1, background_label=-1,
                                   return_index=True)
    assert num.tolist() == [3]  # decayed, not dropped
    assert out.shape == (3, 6)
    # the overlapped box's decayed score is strictly below its raw score
    decayed = {int(i): s for i, s in zip(idx[:, 0], out[:, 1])}
    assert decayed[1] < 0.8 - 1e-6
    assert abs(decayed[0] - 0.9) < 1e-6  # top box undecayed


def test_generate_proposals_filters_and_clips():
    N, A, H, W = 1, 2, 4, 4
    scores = RNG.uniform(size=(N, A, H, W)).astype(np.float32)
    deltas = RNG.standard_normal((N, A * 4, H, W)).astype(np.float32) * 0.1
    anchors = RNG.uniform(0, 28, (H * W * A, 2)).astype(np.float32)
    anchors = np.concatenate(
        [anchors, anchors + RNG.uniform(2, 6, (H * W * A, 2))
         .astype(np.float32)], -1)
    var = np.ones_like(anchors)
    rois, rscores, num = ops.generate_proposals(
        scores, deltas, np.array([[32.0, 32.0]]), anchors, var,
        post_nms_top_n=5, return_rois_num=True)
    assert num[0] == len(rois) <= 5
    assert (rois >= 0).all() and (rois <= 32).all()
    assert (rscores[:-1] >= rscores[1:]).all()  # sorted by score


def test_distribute_fpn_proposals_routes_by_scale():
    rois = np.array([[0, 0, 16, 16],      # small -> low level
                     [0, 0, 460, 460]], np.float32)  # >2x refer -> level 5
    multi, restore = ops.distribute_fpn_proposals(rois, 2, 5, 4, 224)
    sizes = [len(m) for m in multi]
    assert sum(sizes) == 2
    assert len(multi[0]) == 1 and len(multi[-1]) == 1
    # restore index maps concatenated outputs back to input order
    cat = np.concatenate([m for m in multi if len(m)])
    np.testing.assert_allclose(cat[restore[:, 0].argsort()][restore[:, 0]],
                               cat)


# ---------------- yolo ----------------

def test_yolo_box_decode_properties():
    N, na, cls, H, W = 1, 2, 3, 4, 4
    x = RNG.standard_normal((N, na * (5 + cls), H, W)).astype(np.float32)
    boxes, scores = ops.yolo_box(x, np.array([[128, 128]]),
                                 anchors=[10, 13, 16, 30], class_num=cls,
                                 conf_thresh=0.0, downsample_ratio=32)
    assert boxes.shape == (1, H * W * na, 4)
    assert scores.shape == (1, H * W * na, cls)
    b = np.asarray(boxes)
    assert (b >= 0).all() and (b <= 127).all()  # clipped to image
    s = np.asarray(scores)
    assert (s >= 0).all() and (s <= 1).all()
    # high threshold zeroes everything
    b2, s2 = ops.yolo_box(x, np.array([[128, 128]]),
                          anchors=[10, 13, 16, 30], class_num=cls,
                          conf_thresh=1.1, downsample_ratio=32)
    assert np.abs(np.asarray(s2)).sum() == 0


def test_yolo_loss_trains_toward_gt():
    import jax
    import jax.numpy as jnp
    N, cls, H, W = 1, 2, 4, 4
    anchors = [10, 13, 16, 30, 33, 23]
    mask = [0, 1, 2]
    na = len(mask)
    gt_box = np.array([[[0.5, 0.5, 0.3, 0.4]]], np.float32)
    gt_label = np.array([[1]], np.int64)
    pt_x = jnp.asarray(RNG.standard_normal(
        (N, na * (5 + cls), H, W)) * 0.1, jnp.float32)
    loss_fn = lambda x_: ops.yolo_loss(
        x_, gt_box, gt_label, anchors, mask, cls, ignore_thresh=0.7,
        downsample_ratio=32).sum()
    l0 = float(loss_fn(pt_x))
    assert np.isfinite(l0) and l0 > 0
    # a few gradient steps reduce the loss
    g = jax.grad(loss_fn)
    x_cur = pt_x
    for _ in range(20):
        x_cur = x_cur - 0.1 * g(x_cur)
    assert float(loss_fn(x_cur)) < l0


# ---------------- misc ----------------

def test_read_file_decode_jpeg_roundtrip(tmp_path):
    from PIL import Image
    img = (RNG.uniform(0, 255, (10, 12, 3))).astype(np.uint8)
    p = tmp_path / "t.jpg"
    Image.fromarray(img).save(p, quality=95)
    data = ops.read_file(str(p))
    assert data.dtype == np.uint8
    out = np.asarray(ops.decode_jpeg(data, mode="rgb"))
    assert out.shape == (3, 10, 12)
    assert abs(out.astype(float).mean() - img.mean()) < 10  # lossy jpeg


def test_conv_norm_activation_block():
    import paddle_tpu as pt
    from paddle_tpu import nn
    pt.seed(0)
    block = ops.ConvNormActivation(3, 8, kernel_size=3, stride=2,
                                   activation_layer=nn.ReLU6)
    x = RNG.standard_normal((2, 3, 16, 16)).astype(np.float32)
    out = np.asarray(block(x))
    assert out.shape == (2, 8, 8, 8)
    assert (out >= 0).all() and (out <= 6).all()


def test_yolo_loss_padded_gt_rows_do_not_clobber_targets():
    # padded (all-zero) GT rows must not alter the loss of a real GT that
    # happens to land in grid cell (0,0) with anchor 0 (review regression)
    anchors = [10, 13, 16, 30, 33, 23]
    mask = [0, 1, 2]
    cls = 2
    x = RNG.standard_normal((1, 3 * (5 + cls), 4, 4)).astype(np.float32)
    gt1 = np.array([[[0.05, 0.05, 0.08, 0.1]]], np.float32)  # cell (0,0)
    lbl1 = np.array([[1]], np.int64)
    gt2 = np.concatenate([gt1, np.zeros((1, 3, 4), np.float32)], axis=1)
    lbl2 = np.concatenate([lbl1, np.zeros((1, 3), np.int64)], axis=1)
    l1 = float(np.asarray(ops.yolo_loss(x, gt1, lbl1, anchors, mask, cls,
                                        0.7, 32)).sum())
    l2 = float(np.asarray(ops.yolo_loss(x, gt2, lbl2, anchors, mask, cls,
                                        0.7, 32)).sum())
    np.testing.assert_allclose(l1, l2, rtol=1e-5)


def test_roi_align_boundary_clamp_semantics():
    # reference kernel: samples in (-1, 0) clamp to pixel 0 at FULL
    # weight; box [0,0,1,1] aligned on a 4x4 ramp gives exactly 0.625
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    boxes = np.array([[0.0, 0.0, 1.0, 1.0]], np.float32)
    out = ops.roi_align(x, boxes, [1], output_size=1, aligned=True)
    np.testing.assert_allclose(float(np.asarray(out)[0, 0, 0, 0]), 0.625,
                               atol=1e-5)


def test_yolo_loss_gt_score_weights_positive_terms():
    anchors = [10, 13, 16, 30, 33, 23]
    mask = [0, 1, 2]
    cls = 2
    x = RNG.standard_normal((1, 3 * (5 + cls), 4, 4)).astype(np.float32)
    gt = np.array([[[0.5, 0.5, 0.3, 0.4]]], np.float32)
    lbl = np.array([[1]], np.int64)
    l_full = float(np.asarray(ops.yolo_loss(
        x, gt, lbl, anchors, mask, cls, 0.7, 32,
        gt_score=np.array([[1.0]], np.float32))).sum())
    l_none = float(np.asarray(ops.yolo_loss(
        x, gt, lbl, anchors, mask, cls, 0.7, 32)).sum())
    l_half = float(np.asarray(ops.yolo_loss(
        x, gt, lbl, anchors, mask, cls, 0.7, 32,
        gt_score=np.array([[0.5]], np.float32))).sum())
    np.testing.assert_allclose(l_full, l_none, rtol=1e-6)
    assert l_half < l_full  # down-weighted positives shrink the loss


def test_matrix_nms_normalized_flag_changes_iou():
    # pixel-space boxes: +1 offset raises IoU, decaying the overlap more
    boxes = np.array([[[0, 0, 4, 4], [2, 0, 6, 4], [20, 20, 24, 24]]],
                     np.float32)
    scores = np.array([[[0.9, 0.8, 0.7]]], np.float32)
    kw = dict(score_threshold=0.1, post_threshold=0.0, nms_top_k=-1,
              keep_top_k=-1, background_label=-1, return_index=True)
    out_n, idx_n, _ = ops.matrix_nms(boxes, scores, normalized=True, **kw)
    out_p, idx_p, _ = ops.matrix_nms(boxes, scores, normalized=False, **kw)
    dn = {int(i): s for i, s in zip(idx_n[:, 0], out_n[:, 1])}
    dp = {int(i): s for i, s in zip(idx_p[:, 0], out_p[:, 1])}
    assert dp[1] < dn[1]  # pixel-mode IoU is larger -> stronger decay
