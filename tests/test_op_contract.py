"""OpTest-style numeric contract suite (parity model:
test/legacy_test/op_test.py:418 check_output/check_grad).

Every registered op carrying a numpy reference is checked against it on
random inputs, and ops marked grad_ref get a finite-difference gradient
check of jax.grad — the same contract the reference holds PHI kernels to,
applied to our XLA lowerings.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.core.registry import all_ops

RNG = np.random.default_rng(0)


def _gen_inputs(info):
    shapes = info.test_shapes or ((4, 8),)
    if info.category == "elementwise" and len(shapes) == 1:
        shapes = shapes * _arity(info)
    return [RNG.standard_normal(s).astype(np.float32) + 0.5 for s in shapes]


def _arity(info):
    import inspect
    sig = inspect.signature(info.fn)
    n = 0
    for p in sig.parameters.values():
        if p.default is inspect.Parameter.empty and p.kind in (
                p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
            n += 1
    return max(n, 1)


CASES = [(name, info) for name, info in sorted(all_ops().items()) if info.ref is not None]
STAT_CASES = [(n, i) for n, i in sorted(all_ops().items())
              if i.extra.get("check") is not None]


def test_contract_inventory_breadth():
    """The registry must enumerate the whole public op surface — the
    single-source-of-truth promise (ops.yaml parity): >= 200 rows under
    contract, spanning every tensor-API family."""
    ops = all_ops()
    covered = [n for n, i in ops.items()
               if i.ref is not None or i.extra.get("check")]
    assert len(covered) >= 200, f"only {len(covered)} ops under contract"
    cats = {ops[n].category for n in covered}
    assert {"elementwise", "contract", "random"} <= cats


def _inputs_for(name, info):
    if info.make_inputs is not None:
        import zlib
        rng = np.random.default_rng(zlib.crc32(name.encode()))  # stable seed
        return list(info.make_inputs(rng))
    xs = _gen_inputs(info)
    if name in ("sqrt", "log", "log2", "log10", "log1p", "rsqrt"):
        xs = [np.abs(x) + 0.1 for x in xs]
    if name in ("asin", "acos", "atanh"):
        xs = [np.clip(x, -0.9, 0.9) for x in xs]
    if name == "acosh":
        xs = [np.abs(x) + 1.1 for x in xs]
    if name in ("gcd", "lcm"):
        xs = [np.abs(x * 10).astype(np.int32) + 1 for x in xs]
    if name in ("bitwise_left_shift", "bitwise_right_shift"):
        xs = [np.abs(x * 10).astype(np.int32) % 8 for x in xs]
    return xs


def _compare_trees(got, want, rtol, atol):
    gl = jax.tree.leaves(got)
    wl = jax.tree.leaves(
        want if isinstance(want, (tuple, list)) else (want,))
    assert len(gl) == len(wl), f"output arity {len(gl)} != ref {len(wl)}"
    for g, w in zip(gl, wl):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=rtol, atol=atol)


@pytest.mark.parametrize("name,info", CASES, ids=[c[0] for c in CASES])
def test_forward_matches_numpy(name, info):
    xs = _inputs_for(name, info)
    call = info.fn_call or info.fn
    got = call(*xs)
    want = info.ref(*xs)
    if isinstance(got, jax.Array) and not isinstance(want, (tuple, list)):
        np.testing.assert_allclose(np.asarray(got), want, rtol=5e-4, atol=1e-4)
    else:
        _compare_trees(got, want, rtol=5e-4, atol=1e-4)


@pytest.mark.parametrize("name,info", STAT_CASES, ids=[c[0] for c in STAT_CASES])
def test_random_op_statistics(name, info):
    """Sampling ops: shape/dtype/moment contracts (the reference tests these
    the same way — e.g. test_poisson_op.py checks sample moments)."""
    out = (info.fn_call or info.fn)()
    info.extra["check"](out)


GRAD_CASES = [(n, i) for n, i in CASES if i.grad_ref]


@pytest.mark.parametrize("name,info", GRAD_CASES, ids=[c[0] for c in GRAD_CASES])
def test_grad_matches_numeric(name, info):
    if name in ("gcd", "lcm", "bitwise_left_shift", "bitwise_right_shift"):
        pytest.skip("integer op")
    xs = _inputs_for(name, info)
    if info.make_inputs is None:
        if name in ("sqrt", "log", "log2", "log10", "log1p", "rsqrt"):
            xs = [np.abs(x) + 0.5 for x in xs]
        if name in ("asin", "acos", "atanh"):
            xs = [np.clip(x, -0.8, 0.8) for x in xs]
        if name == "acosh":
            xs = [np.abs(x) + 1.5 for x in xs]
    call = info.fn_call or info.fn

    def scalar_fn(*args):
        return jnp.sum(jnp.concatenate(
            [jnp.ravel(l).astype(jnp.float32) for l in jax.tree.leaves(
                call(*args))]))

    g = jax.grad(scalar_fn)(*[jnp.asarray(x) for x in xs])
    # central differences on a few elements of the first input
    eps = 1e-2 if name in ("det",) else 1e-3
    it = np.nditer(xs[0], flags=["multi_index"])
    flat_checks = 0
    while not it.finished and flat_checks < 8:
        idx = it.multi_index
        xp = [x.copy() for x in xs]
        xm = [x.copy() for x in xs]
        xp[0][idx] += eps
        xm[0][idx] -= eps
        num = (float(scalar_fn(*xp)) - float(scalar_fn(*xm))) / (2 * eps)
        np.testing.assert_allclose(np.asarray(g)[idx], num, rtol=5e-2,
                                   atol=5e-3)
        flat_checks += 1
        it.iternext()


BF16_CASES = [(n, i) for n, i in CASES
              if i.category == "elementwise" and i.grad_ref
              and n not in ("tan",)]  # poles blow past bf16 tolerance


@pytest.mark.parametrize("name,info", BF16_CASES, ids=[c[0] for c in BF16_CASES])
def test_forward_bfloat16(name, info):
    """bf16 dtype pass (the MXU-native dtype): loose tolerance vs the fp32
    numpy reference — parity with OpTest's bf16 place/dtype matrix."""
    xs = _inputs_for(name, info)
    xs16 = [jnp.asarray(x, jnp.bfloat16) if x.dtype == np.float32 else x
            for x in xs]
    got = np.asarray((info.fn_call or info.fn)(*xs16), np.float32)
    want = np.asarray(info.ref(*xs), np.float32)
    np.testing.assert_allclose(got, want, rtol=5e-2, atol=5e-2)


def test_matmul_against_numpy():
    a = RNG.standard_normal((3, 4, 8)).astype(np.float32)
    b = RNG.standard_normal((3, 8, 5)).astype(np.float32)
    # FLAGS_matmul_precision routes to lax Precision (default on this backend
    # allows reduced-precision passes, like the MXU on TPU)
    with pt.core.flags.flag_guard(matmul_precision="highest"):
        np.testing.assert_allclose(np.asarray(pt.matmul(a, b)), a @ b,
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(pt.matmul(a, b.swapaxes(-1, -2), transpose_y=True)), a @ b,
            rtol=1e-5, atol=1e-5)
    # default precision still within bf16-class error
    np.testing.assert_allclose(np.asarray(pt.matmul(a, b)), a @ b, rtol=3e-2, atol=3e-2)


def test_reduction_semantics():
    x = RNG.standard_normal((4, 5, 6)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(pt.sum(x, axis=[0, 2])), x.sum((0, 2)), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(pt.mean(x, axis=1, keepdim=True)),
                               x.mean(1, keepdims=True), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(pt.std(x, unbiased=False)), x.std(), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(pt.logsumexp(x, axis=-1)),
                               np.log(np.exp(x).sum(-1)), rtol=1e-4)


def test_manipulation_semantics():
    x = RNG.standard_normal((4, 6)).astype(np.float32)
    assert pt.reshape(x, [2, 12]).shape == (2, 12)
    assert pt.transpose(x, [1, 0]).shape == (6, 4)
    parts = pt.split(x, [2, -1], axis=1)
    assert parts[0].shape == (4, 2) and parts[1].shape == (4, 4)
    assert pt.concat(parts, axis=1).shape == (4, 6)
    g = pt.gather(x, np.array([0, 2]), axis=0)
    np.testing.assert_allclose(np.asarray(g), x[[0, 2]])
    vals, idx = pt.topk(x, 3, axis=1)
    np.testing.assert_allclose(np.asarray(vals), np.sort(x, 1)[:, ::-1][:, :3], rtol=1e-6)


def test_scatter_put_along_axis():
    x = np.zeros((4, 5), np.float32)
    idx = np.array([[0], [1], [2], [3]])
    out = pt.put_along_axis(x, idx, 1.0, axis=1)
    np.testing.assert_allclose(np.asarray(out).sum(), 4.0)
    s = pt.scatter(np.zeros((5, 3), np.float32), np.array([1, 3]),
                   np.ones((2, 3), np.float32))
    assert float(np.asarray(s).sum()) == 6.0


def test_linalg_ops():
    a = RNG.standard_normal((5, 5)).astype(np.float32)
    spd = a @ a.T + 5 * np.eye(5, dtype=np.float32)
    L = np.asarray(pt.cholesky(spd))
    np.testing.assert_allclose(L @ L.T, spd, rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(np.asarray(pt.inv(spd)) @ spd, np.eye(5),
                               rtol=1e-2, atol=1e-3)
    np.testing.assert_allclose(float(pt.det(np.eye(3, dtype=np.float32) * 2)), 8.0,
                               rtol=1e-5)
    b = RNG.standard_normal((5, 2)).astype(np.float32)
    x = np.asarray(pt.solve(spd, b))
    np.testing.assert_allclose(spd @ x, b, rtol=1e-3, atol=1e-3)


def test_dtype_promotion():
    assert pt.promote_types("float16", "float32") == jnp.float32
    assert pt.promote_types("int32", "float16") == jnp.float16
    assert pt.promote_types("bfloat16", "float16") == jnp.float32


def test_check_nan_inf_flag():
    pt.set_flags({"check_nan_inf": True})
    try:
        with pytest.raises(FloatingPointError):
            pt.log(np.array([-1.0], np.float32))

    finally:
        pt.set_flags({"check_nan_inf": False})
