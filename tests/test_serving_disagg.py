"""paddle_tpu.serving.fleet — disaggregated prefill/decode serving.

The disagg contracts (SERVING.md "Disaggregated serving"):

1. BITWISE — ``placement="disagg"`` relocates the decode phase to a
   different replica via the KV handoff; it never changes the math.
   Every stream is bitwise identical to single-engine ``generate()``
   and to the colocated fleet, including the first token (emitted from
   the decode side with the same sampling key the prefill replica
   would have used).
2. PHASE SPLIT — a prefill-role replica only ever compiles/runs the
   mixed program (``step_program_counts() == {"decode": 0, "mixed":
   1}``); the decode replica owns the whole decode phase.
3. DEGRADE, NEVER CORRUPT — a dropped offer, a corrupt payload (caught
   by the per-page digest gate), a timed-out handoff, or a replica
   killed mid-handoff all degrade to a full recompute somewhere; the
   client stream stays bitwise and exactly-once throughout, and the
   pool invariants survive (``audit_pool``).
4. ELASTIC — roles re-roll on sustained imbalance and an extinct role
   is restored immediately; only idle replicas flip.

The ``fleet.handoff`` chaos site (ctx path = rid) drops/delays/
corrupts the offer in flight; kill chaos goes through
``kill_replica`` like the fleet suite.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.distributed import fault
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.observability import parse_prometheus, render_fleet_prometheus
from paddle_tpu.serving import FleetRouter, ServingEngine
from paddle_tpu.serving.fleet import DEAD

RNG = np.random.default_rng(7)


@pytest.fixture(scope="module")
def model():
    pt.seed(123)
    m = LlamaForCausalLM(llama_tiny(dtype="float32",
                                    mp_axis=None, fsdp_axis=None))
    m.eval()
    return m


@pytest.fixture
def fault_free(monkeypatch):
    """No FaultPlan leaks out of a chaos test; no rank env leaks in."""
    fault.deactivate()
    monkeypatch.delenv("PADDLE_TRAINER_ID", raising=False)
    monkeypatch.delenv("PROCESS_ID", raising=False)
    monkeypatch.delenv("PADDLE_RESTART_EPOCH", raising=False)
    yield
    fault.deactivate()


def _reference(model, prompt, max_new, **kw):
    out = model.generate(jnp.asarray([prompt]), max_new_tokens=max_new, **kw)
    return np.asarray(out)[0, len(prompt):].tolist()


def _mk_engine(model, **kw):
    cfg = dict(num_pages=64, page_size=16, max_slots=4)
    cfg.update(kw)
    return ServingEngine(model, **cfg)


def _roles(router):
    return [h["role"] for h in router.stats()["replica_health"]]


def _run_exactly_once(router, rids, max_steps=400, events=None):
    """Drain the router collecting client events; assert each stream
    was delivered exactly once (event tokens == the record, no dup, no
    gap) and return {rid: tokens}. ``events`` seeds the collection
    with client events a test already drove manually (warm-up steps
    before a kill) — they are part of the exactly-once stream and must
    not be dropped."""
    events = list(events or [])
    while router.has_work():
        events.extend(router.step())
        assert router.stats()["steps"] < max_steps, "router hang"
    seen = {rid: [] for rid in rids}
    for ev in events:
        if ev.get("token") is not None:
            seen[ev["rid"]].append(ev["token"])
    out = {}
    for rid in rids:
        rec = router.request(rid)
        assert rec.finished
        assert seen[rid] == rec.tokens      # no dup, no gap
        out[rid] = rec.tokens
    return out


# ---------------------------------------------------------------------------
# placement validation + role wiring (fast)
# ---------------------------------------------------------------------------

class TestDisaggPlacement:
    def test_unknown_placement_rejected(self, model):
        with pytest.raises(ValueError):
            FleetRouter([_mk_engine(model), _mk_engine(model)],
                        placement="sideways")

    def test_disagg_needs_two_replicas(self, model):
        with pytest.raises(ValueError):
            FleetRouter([_mk_engine(model)], placement="disagg")

    def test_roles_assigned_and_exported(self, model):
        router = FleetRouter([_mk_engine(model) for _ in range(3)],
                             placement="disagg", disagg_prefill_frac=0.5)
        assert _roles(router) == ["prefill", "prefill", "decode"]
        st = router.stats()
        assert st["placement"] == "disagg"
        assert st["handoff_offers_held"] == 0
        series = parse_prometheus(render_fleet_prometheus(router))
        assert series['paddle_serving_fleet_replica_prefill'
                      '{replica="0"}'] == 1.0
        assert series['paddle_serving_fleet_replica_prefill'
                      '{replica="2"}'] == 0.0

    def test_colocated_default_has_no_roles(self, model):
        router = FleetRouter([_mk_engine(model), _mk_engine(model)])
        assert _roles(router) == ["colocated", "colocated"]
        series = parse_prometheus(render_fleet_prometheus(router))
        assert series['paddle_serving_fleet_replica_prefill'
                      '{replica="0"}'] == 0.0


# ---------------------------------------------------------------------------
# happy path: bitwise streams, phase split, counters (tier-1, real model)
# ---------------------------------------------------------------------------

class TestDisaggHappyPath:
    def test_streams_bitwise_and_phase_split(self, model, fault_free):
        prompts = [RNG.integers(1, 500, size=int(n)).tolist()
                   for n in (5, 9, 7, 12)]
        refs = [_reference(model, p, 6) for p in prompts]
        engines = [_mk_engine(model), _mk_engine(model)]
        router = FleetRouter(engines, placement="disagg")
        assert _roles(router) == ["prefill", "decode"]
        rids = [router.submit(p, 6) for p in prompts]
        out = _run_exactly_once(router, rids)
        for rid, ref in zip(rids, refs):
            assert out[rid] == ref
        # phase split: the prefill specialist NEVER compiled decode —
        # its entire life is mixed-step prompt chunks
        assert engines[0].step_program_counts() == {"decode": 0,
                                                    "mixed": 1}
        assert engines[1].decode_program_count() == 1
        c = router.fleet_metrics.counters
        assert c.get("handoff_prefills") == 4
        assert c.get("handoff_offers") == 4
        assert c.get("handoff_pulls") == 4
        assert c.get("handoff_commits") == 4
        assert c.get("handoff_bytes", 0) > 0
        assert c.get("handoff_recomputes", 0) == 0
        for e in engines:
            e.audit_pool()
        # TTFT decomposes into queue-wait / prefill / handoff
        m = router.metrics.summary()
        assert m["ttft_prefill_p50_s"] > 0.0
        assert m["ttft_handoff_p50_s"] > 0.0
        # counters + per-replica roles land on the Prometheus page
        series = parse_prometheus(render_fleet_prometheus(router))
        assert series["paddle_serving_fleet_handoff_pulls_total"] == 4.0
        assert series["paddle_serving_fleet_handoff_bytes_total"] > 0

    def test_matches_colocated_fleet(self, model, fault_free):
        prompts = [RNG.integers(1, 500, size=int(n)).tolist()
                   for n in (6, 11, 8)]

        def run(placement):
            router = FleetRouter([_mk_engine(model), _mk_engine(model)],
                                 placement=placement)
            rids = [router.submit(p, 5, rid=f"r{i}")
                    for i, p in enumerate(prompts)]
            return router.run_to_completion(max_steps=300), rids

        colo, rids = run("affinity")
        disagg, _ = run("disagg")
        assert all(disagg[r] == colo[r] for r in rids)


# ---------------------------------------------------------------------------
# elastic re-rolling
# ---------------------------------------------------------------------------

class TestDisaggReroll:
    def test_extinct_prefill_role_restored(self, model, fault_free):
        """Kill the ONLY prefill specialist, then submit a second wave
        that still owes its prefill: the sweep must promote a drained
        decode replica to restore the role (an extinct role is
        restored as soon as an idle donor exists), and the new wave
        flows prefill -> handoff -> decode on the re-rolled fleet."""
        prompts = [RNG.integers(1, 500, size=int(n)).tolist()
                   for n in (5, 8, 6, 7, 9)]
        refs = [_reference(model, p, 5) for p in prompts]
        router = FleetRouter([_mk_engine(model) for _ in range(3)],
                             placement="disagg", disagg_prefill_frac=0.34,
                             reroll_interval=1)
        assert _roles(router) == ["prefill", "decode", "decode"]
        rids = [router.submit(p, 5) for p in prompts[:3]]
        pre = []
        guard = 0
        c = router.fleet_metrics.counters
        while c.get("handoff_prefills", 0) < 3:   # wave 1 past prefill
            pre.extend(router.step())
            guard += 1
            assert guard < 100
        router.kill_replica(0)          # the ONLY prefill specialist dies
        rids += [router.submit(p, 5) for p in prompts[3:]]  # owe prefill
        out = _run_exactly_once(router, rids, events=pre)
        for rid, ref in zip(rids, refs):
            assert out[rid] == ref
        # an idle decode replica was re-rolled to restore the role
        assert router.fleet_metrics.counters.get("rerolls", 0) >= 1
        live_roles = [h["role"] for h in router.stats()["replica_health"]
                      if h["state"] != DEAD]
        assert "prefill" in live_roles
        assert "decode" in live_roles


# ---------------------------------------------------------------------------
# chaos: the handoff fallback ladder + kill-during-handoff (slow/faults)
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestDisaggChaos:
    def _prompts_and_refs(self, model, n=4, max_new=5):
        prompts = [RNG.integers(1, 500, size=int(RNG.integers(4, 12)))
                   .tolist() for _ in range(n)]
        return prompts, [_reference(model, p, max_new) for p in prompts]

    @pytest.mark.faults
    def test_offer_dropped_recomputes(self, model, fault_free):
        prompts, refs = self._prompts_and_refs(model)
        fault.activate(fault.FaultPlan([
            fault.FaultSpec(site="fleet.handoff", action="drop",
                            match=r"^fleet-req-0$"),
        ]))
        router = FleetRouter([_mk_engine(model), _mk_engine(model)],
                             placement="disagg")
        rids = [router.submit(p, 5) for p in prompts]
        out = _run_exactly_once(router, rids)
        for rid, ref in zip(rids, refs):
            assert out[rid] == ref
        c = router.fleet_metrics.counters
        assert c.get("handoff_recomputes") == 1
        assert c.get("handoff_offers") == 3     # the dropped one never lands
        for e in router.engines:
            e.audit_pool()

    @pytest.mark.faults
    def test_offer_corrupt_caught_by_digest_gate(self, model, fault_free):
        prompts, refs = self._prompts_and_refs(model)
        fault.activate(fault.FaultPlan([
            fault.FaultSpec(site="fleet.handoff", action="corrupt",
                            match=r"^fleet-req-1$"),
        ]))
        router = FleetRouter([_mk_engine(model), _mk_engine(model)],
                             placement="disagg")
        rids = [router.submit(p, 5) for p in prompts]
        out = _run_exactly_once(router, rids)
        for rid, ref in zip(rids, refs):
            assert out[rid] == ref
        # the decode replica's per-page digest gate refused the payload
        # and recomputed from the prompt — corruption NEVER lands
        assert router.fleet_metrics.counters.get("handoff_corrupt", 0) >= 1
        for e in router.engines:
            e.audit_pool()

    @pytest.mark.faults
    def test_offer_delayed_within_budget_still_pulls(self, model,
                                                     fault_free):
        prompts, refs = self._prompts_and_refs(model)
        fault.activate(fault.FaultPlan([
            fault.FaultSpec(site="fleet.handoff", action="delay", arg=3,
                            once=False),
        ]))
        router = FleetRouter([_mk_engine(model), _mk_engine(model)],
                             placement="disagg")
        rids = [router.submit(p, 5) for p in prompts]
        out = _run_exactly_once(router, rids)
        for rid, ref in zip(rids, refs):
            assert out[rid] == ref
        c = router.fleet_metrics.counters
        assert c.get("handoff_pulls") == 4
        assert c.get("handoff_recomputes", 0) == 0

    @pytest.mark.faults
    def test_offer_delayed_past_timeout_recomputes(self, model,
                                                   fault_free):
        prompts, refs = self._prompts_and_refs(model)
        fault.activate(fault.FaultPlan([
            fault.FaultSpec(site="fleet.handoff", action="delay", arg=40,
                            match=r"^fleet-req-2$"),
        ]))
        router = FleetRouter([_mk_engine(model), _mk_engine(model)],
                             placement="disagg", handoff_timeout_steps=8)
        rids = [router.submit(p, 5) for p in prompts]
        out = _run_exactly_once(router, rids)
        for rid, ref in zip(rids, refs):
            assert out[rid] == ref
        c = router.fleet_metrics.counters
        assert c.get("handoff_timeouts") == 1
        assert c.get("handoff_recomputes") == 1

    def test_kill_prefill_during_handoff_sweep(self, model, fault_free):
        """Kill the prefill specialist at every early router step: the
        offer is either recomputed (died before publishing) or already
        router-held (pull proceeds) — bitwise + exactly-once always."""
        prompts = [RNG.integers(1, 500, size=int(n)).tolist()
                   for n in (5, 9, 7, 12)]
        refs = [_reference(model, p, 5) for p in prompts]
        for kill_step in range(1, 7):
            router = FleetRouter([_mk_engine(model) for _ in range(3)],
                                 placement="disagg",
                                 disagg_prefill_frac=0.34,
                                 reroll_interval=1)
            rids = [router.submit(p, 5) for p in prompts]
            pre = []
            for _ in range(kill_step):
                pre.extend(router.step())
            router.kill_replica(0)      # the prefill specialist
            out = _run_exactly_once(router, rids, max_steps=500,
                                    events=pre)
            for rid, ref in zip(rids, refs):
                assert out[rid] == ref, f"kill_step={kill_step}"
            for h in router.stats()["replica_health"]:
                if h["state"] != DEAD:
                    eng = router.engines[h["replica"]]
                    # chaos must not retrace either program
                    assert all(n <= 1 for n
                               in eng.step_program_counts().values())
                    eng.audit_pool()

    def test_kill_decode_after_pull(self, model, fault_free):
        """The decode replica dies AFTER pulling: the router still
        holds its own offer reference until the record finishes, so
        the replacement re-pulls instead of recomputing the prompt."""
        prompts = [RNG.integers(1, 500, size=int(n)).tolist()
                   for n in (5, 9, 7, 12)]
        refs = [_reference(model, p, 5) for p in prompts]
        router = FleetRouter([_mk_engine(model) for _ in range(3)],
                             placement="disagg", disagg_prefill_frac=0.34,
                             reroll_interval=1)
        rids = [router.submit(p, 5) for p in prompts]
        pre = []
        guard = 0
        while router.fleet_metrics.counters.get("handoff_pulls", 0) < 1:
            pre.extend(router.step())
            guard += 1
            assert guard < 100
        victims = [h["replica"] for h in router.stats()["replica_health"]
                   if h["role"] == "decode" and h["live"]]
        router.kill_replica(victims[0] if victims else 1)
        out = _run_exactly_once(router, rids, max_steps=500, events=pre)
        for rid, ref in zip(rids, refs):
            assert out[rid] == ref
        assert router.fleet_metrics.counters.get("failovers", 0) >= 1
        for h in router.stats()["replica_health"]:
            if h["state"] != DEAD:
                router.engines[h["replica"]].audit_pool()
