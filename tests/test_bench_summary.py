"""CI smoke test for the bench driver contract: the LAST stdout line of
bench.py is a single JSON object ``{"bench_summary": {config: {value,
mfu, spread}}}`` carrying every default config. Runs bench.py --dry in a
subprocess — dry mode skips the jax import and all device work, so this
stays in the fast (-m 'not slow') tier."""

import json
import subprocess
import sys
from pathlib import Path

_REPO = Path(__file__).resolve().parents[1]

_DEFAULT_CONFIGS = {
    "llama_420m", "resnet50", "bert_base", "qwen2_moe", "lenet_mnist",
    "llama8b_shape", "llama_decode", "llama_longctx", "llama_serving",
    "llama_serving_prefix",
}


def _run_dry(*argv):
    return subprocess.run(
        [sys.executable, str(_REPO / "bench.py"), "--dry", *argv],
        capture_output=True, text=True, timeout=120, cwd=_REPO)


def test_dry_summary_line_has_all_default_configs():
    out = _run_dry()
    assert out.returncode == 0, out.stderr
    lines = [ln for ln in out.stdout.splitlines() if ln.strip()]
    assert lines, "bench.py --dry printed nothing"
    last = json.loads(lines[-1])
    summary = last["bench_summary"]
    assert _DEFAULT_CONFIGS <= set(summary), (
        f"missing configs: {_DEFAULT_CONFIGS - set(summary)}")
    for name, cell in summary.items():
        assert set(cell) >= {"value", "mfu", "spread"}, (name, cell)


def test_dry_subset_and_unknown_config():
    out = _run_dry("qwen2_moe", "qwen2_moe_fused")
    assert out.returncode == 0, out.stderr
    last = json.loads(out.stdout.splitlines()[-1])
    assert set(last["bench_summary"]) == {"qwen2_moe", "qwen2_moe_fused"}
    bad = _run_dry("not_a_config")
    assert bad.returncode != 0


def test_summary_entry_picks_the_configs_efficiency_ratio():
    sys.path.insert(0, str(_REPO))
    try:
        import bench
    finally:
        sys.path.pop(0)
    dense = {"value": 1.0, "extra": {"mfu": 0.5, "spread": 0.01}}
    moe = {"value": 2.0, "extra": {"mfu_active": 0.3, "spread": 0.02}}
    decode = {"value": 3.0, "extra": {"batches": {8: {"mbu": 0.7}},
                                      "spread": 0.03}}
    err = {"metric": "x", "value": None, "extra": {"error": "boom"}}
    assert bench._summary_entry(dense) == {
        "value": 1.0, "mfu": 0.5, "spread": 0.01}
    assert bench._summary_entry(moe) == {
        "value": 2.0, "mfu": 0.3, "spread": 0.02}
    assert bench._summary_entry(decode) == {
        "value": 3.0, "mfu": 0.7, "spread": 0.03}
    assert bench._summary_entry(err) == {
        "value": None, "mfu": None, "spread": None}
    serving = {"value": 4.0, "extra": {"mbu_weights_only": 0.2,
                                       "ttft_p50": 0.1, "ttft_p99": 0.4,
                                       "tpot": 0.02, "rejected": 1,
                                       "timed_out": 2, "quarantined": 0,
                                       "spread": None}}
    assert bench._summary_entry(serving, "llama_serving") == {
        "value": 4.0, "mfu": 0.2, "spread": None,
        "ttft_p50": 0.1, "ttft_p99": 0.4, "tpot": 0.02,
        "rejected": 1, "timed_out": 2, "quarantined": 0}


def test_dry_serving_cell_carries_latency_and_failure_keys():
    out = _run_dry("llama_serving")
    assert out.returncode == 0, out.stderr
    last = json.loads(out.stdout.splitlines()[-1])
    cell = last["bench_summary"]["llama_serving"]
    assert set(cell) >= {"value", "mfu", "spread",
                         "ttft_p50", "ttft_p99", "tpot",
                         "rejected", "timed_out", "quarantined"}, cell


def test_dry_serving_prefix_cell_carries_cache_keys():
    out = _run_dry("llama_serving_prefix")
    assert out.returncode == 0, out.stderr
    last = json.loads(out.stdout.splitlines()[-1])
    cell = last["bench_summary"]["llama_serving_prefix"]
    assert set(cell) >= {"value", "mfu", "spread",
                         "ttft_p50", "ttft_p99", "tpot",
                         "cache_hit_rate", "prefix_hits",
                         "prefix_evictions"}, cell
    assert all(v is None for v in cell.values()), cell
