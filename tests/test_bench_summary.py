"""CI smoke test for the bench driver contract: the LAST stdout line of
bench.py is a single JSON object ``{"bench_summary": {config: {value,
mfu, spread}}}`` carrying every default config. Runs bench.py --dry in a
subprocess — dry mode skips the jax import and all device work, so this
stays in the fast (-m 'not slow') tier."""

import json
import subprocess
import sys
from pathlib import Path

_REPO = Path(__file__).resolve().parents[1]

_DEFAULT_CONFIGS = {
    "llama_420m", "resnet50", "bert_base", "qwen2_moe", "lenet_mnist",
    "llama8b_shape", "llama_decode", "llama_longctx", "llama_serving",
    "llama_serving_prefix", "llama_decode_int8", "llama_serving_int8",
    "llama_serving_fleet", "llama_serving_spec", "llama_serving_tiered",
    "llama_serving_chunked", "llama_serving_failover",
    "llama_serving_partition", "llama_serving_multihost",
    "llama_serving_tp", "llama_serving_pp", "llama_serving_fairness",
    "llama_serving_disagg", "llama_serving_lora",
}


def _run_dry(*argv):
    return subprocess.run(
        [sys.executable, str(_REPO / "bench.py"), "--dry", *argv],
        capture_output=True, text=True, timeout=120, cwd=_REPO)


def test_dry_summary_line_has_all_default_configs():
    out = _run_dry()
    assert out.returncode == 0, out.stderr
    lines = [ln for ln in out.stdout.splitlines() if ln.strip()]
    assert lines, "bench.py --dry printed nothing"
    last = json.loads(lines[-1])
    summary = last["bench_summary"]
    assert _DEFAULT_CONFIGS <= set(summary), (
        f"missing configs: {_DEFAULT_CONFIGS - set(summary)}")
    for name, cell in summary.items():
        assert set(cell) >= {"value", "mfu", "spread"}, (name, cell)


def test_dry_subset_and_unknown_config():
    out = _run_dry("qwen2_moe", "qwen2_moe_fused")
    assert out.returncode == 0, out.stderr
    last = json.loads(out.stdout.splitlines()[-1])
    assert set(last["bench_summary"]) == {"qwen2_moe", "qwen2_moe_fused"}
    bad = _run_dry("not_a_config")
    assert bad.returncode != 0


def test_summary_entry_picks_the_configs_efficiency_ratio():
    sys.path.insert(0, str(_REPO))
    try:
        import bench
    finally:
        sys.path.pop(0)
    dense = {"value": 1.0, "extra": {"mfu": 0.5, "spread": 0.01}}
    moe = {"value": 2.0, "extra": {"mfu_active": 0.3, "spread": 0.02}}
    decode = {"value": 3.0, "extra": {"batches": {8: {"mbu": 0.7}},
                                      "spread": 0.03}}
    err = {"metric": "x", "value": None, "extra": {"error": "boom"}}
    assert bench._summary_entry(dense) == {
        "value": 1.0, "mfu": 0.5, "spread": 0.01}
    assert bench._summary_entry(moe) == {
        "value": 2.0, "mfu": 0.3, "spread": 0.02}
    assert bench._summary_entry(decode) == {
        "value": 3.0, "mfu": 0.7, "spread": 0.03}
    assert bench._summary_entry(err) == {
        "value": None, "mfu": None, "spread": None}
    serving = {"value": 4.0, "extra": {"mbu_weights_only": 0.2,
                                       "ttft_p50": 0.1, "ttft_p99": 0.4,
                                       "tpot": 0.02, "rejected": 1,
                                       "timed_out": 2, "quarantined": 0,
                                       "goodput_at_slo": 1.5, "retraces": 0,
                                       "spread": None}}
    assert bench._summary_entry(serving, "llama_serving") == {
        "value": 4.0, "mfu": 0.2, "spread": None,
        "ttft_p50": 0.1, "ttft_p99": 0.4, "tpot": 0.02,
        "rejected": 1, "timed_out": 2, "quarantined": 0,
        "goodput_at_slo": 1.5, "retraces": 0}


def test_dry_serving_cell_carries_latency_and_failure_keys():
    out = _run_dry("llama_serving")
    assert out.returncode == 0, out.stderr
    last = json.loads(out.stdout.splitlines()[-1])
    cell = last["bench_summary"]["llama_serving"]
    assert set(cell) >= {"value", "mfu", "spread",
                         "ttft_p50", "ttft_p99", "tpot",
                         "rejected", "timed_out", "quarantined",
                         "goodput_at_slo", "retraces"}, cell


def test_dry_serving_prefix_cell_carries_cache_keys():
    out = _run_dry("llama_serving_prefix")
    assert out.returncode == 0, out.stderr
    last = json.loads(out.stdout.splitlines()[-1])
    cell = last["bench_summary"]["llama_serving_prefix"]
    assert set(cell) >= {"value", "mfu", "spread",
                         "ttft_p50", "ttft_p99", "tpot",
                         "cache_hit_rate", "prefix_hits",
                         "prefix_evictions",
                         "goodput_at_slo", "retraces"}, cell
    assert all(v is None for v in cell.values()), cell


def test_dry_int8_cells_carry_quant_keys():
    # the quantized-serving arms (SERVING.md "Quantized KV & weights"):
    # the decode cell reports the bytes ratio vs bf16, the serving cell
    # additionally the quantization error bound gauge
    out = _run_dry("llama_decode_int8", "llama_serving_int8")
    assert out.returncode == 0, out.stderr
    last = json.loads(out.stdout.splitlines()[-1])
    dec = last["bench_summary"]["llama_decode_int8"]
    assert set(dec) >= {"value", "mfu", "spread",
                        "bytes_ratio_vs_bf16"}, dec
    srv = last["bench_summary"]["llama_serving_int8"]
    assert set(srv) >= {"value", "mfu", "spread",
                        "ttft_p50", "ttft_p99", "tpot",
                        "rejected", "timed_out", "quarantined",
                        "goodput_at_slo", "retraces",
                        "kv_quant_err_bound", "bytes_ratio_vs_bf16"}, srv
    assert all(v is None for v in srv.values()), srv


def test_dry_fleet_cell_carries_failover_keys():
    # the fleet arm (SERVING.md "Engine fleet & failover"): the cell must
    # surface the failover evidence — how many requests failed over, how
    # many replayed tokens the exactly-once dedup suppressed, and whether
    # anything was shed — next to the usual serving SLO keys
    out = _run_dry("llama_serving_fleet")
    assert out.returncode == 0, out.stderr
    last = json.loads(out.stdout.splitlines()[-1])
    cell = last["bench_summary"]["llama_serving_fleet"]
    assert set(cell) >= {"value", "mfu", "spread",
                         "ttft_p50", "ttft_p99", "tpot",
                         "failovers", "replayed_tokens", "shed",
                         "replicas_ejected",
                         "goodput_at_slo", "retraces"}, cell
    assert all(v is None for v in cell.values()), cell


def test_dry_failover_cell_carries_replay_ab_keys():
    # the bounded-replay A/B (RESILIENCE.md "Serving recovery
    # playbook"): the cell must surface the replay-work evidence for
    # BOTH arms — the full-replay arm's replayed_tokens vs the snapshot
    # arm's restored/replayed split and its restore/fallback counts —
    # plus goodput_at_slo for both arms, next to the usual serving keys
    out = _run_dry("llama_serving_failover")
    assert out.returncode == 0, out.stderr
    last = json.loads(out.stdout.splitlines()[-1])
    cell = last["bench_summary"]["llama_serving_failover"]
    assert set(cell) >= {"value", "mfu", "spread",
                         "ttft_p50", "ttft_p99", "tpot",
                         "failovers",
                         "replayed_tokens", "replayed_tokens_full",
                         "snapshot_restores", "snapshot_fallbacks",
                         "recovery_restored_tokens",
                         "recovery_replayed_tokens",
                         "goodput_at_slo", "goodput_at_slo_full",
                         "retraces"}, cell
    assert all(v is None for v in cell.values()), cell


def test_dry_partition_cell_carries_lossy_wire_ab_keys():
    # the clean-vs-lossy wire A/B (SERVING.md "Fleet transport &
    # membership"): the cell must surface what the lossy wire cost —
    # failovers in each arm, the fencing + dedup counters that prove
    # the exactly-once contract did real work, the transport drop
    # volume, and goodput_at_slo for BOTH arms — next to the usual
    # serving keys
    out = _run_dry("llama_serving_partition")
    assert out.returncode == 0, out.stderr
    last = json.loads(out.stdout.splitlines()[-1])
    cell = last["bench_summary"]["llama_serving_partition"]
    assert set(cell) >= {"value", "mfu", "spread",
                         "ttft_p50", "ttft_p99", "tpot",
                         "failovers", "failovers_clean",
                         "stale_epoch_discarded", "lease_expirations",
                         "duplicates_suppressed", "transport_dropped",
                         "goodput_at_slo", "goodput_at_slo_clean",
                         "retraces"}, cell
    assert all(v is None for v in cell.values()), cell


def test_dry_multihost_cell_carries_socket_ab_keys():
    # the loopback-vs-socket A/B (SERVING.md "Multi-host serving"): the
    # cell must surface what the real TCP wire cost — frame/byte
    # volume, reconnects and lease churn (both 0 on a healthy wire),
    # and goodput_at_slo for BOTH arms — next to the usual serving keys
    out = _run_dry("llama_serving_multihost")
    assert out.returncode == 0, out.stderr
    last = json.loads(out.stdout.splitlines()[-1])
    cell = last["bench_summary"]["llama_serving_multihost"]
    assert set(cell) >= {"value", "mfu", "spread",
                         "ttft_p50", "ttft_p99", "tpot",
                         "frames_sent", "frames_recv",
                         "frame_bytes_sent", "frame_bytes_recv",
                         "socket_reconnects", "lease_expirations",
                         "goodput_at_slo", "goodput_at_slo_loopback",
                         "tokens_per_s_loopback", "retraces"}, cell
    assert all(v is None for v in cell.values()), cell


def test_dry_serving_chunked_cell_carries_ab_keys():
    # the chunked-prefill arm (SERVING.md "Chunked prefill & mixed
    # steps"): the cell must surface the A/B evidence — itl_p99 and
    # goodput_at_slo for BOTH arms (head-of-line blocking shows up as
    # the OFF arm's inter-token p99) plus the chunk volume — next to
    # the usual serving SLO keys
    out = _run_dry("llama_serving_chunked")
    assert out.returncode == 0, out.stderr
    last = json.loads(out.stdout.splitlines()[-1])
    cell = last["bench_summary"]["llama_serving_chunked"]
    assert set(cell) >= {"value", "mfu", "spread",
                         "ttft_p50", "ttft_p99", "tpot",
                         "itl_p99", "itl_p99_baseline", "itl_p99_ratio",
                         "goodput_at_slo", "goodput_at_slo_baseline",
                         "chunk_tokens_total", "retraces"}, cell
    assert all(v is None for v in cell.values()), cell


def test_dry_serving_spec_cell_carries_acceptance_keys():
    # the speculative arm (SERVING.md "Speculative decoding"): the cell
    # must surface the draft-economics evidence — accept rate, how often
    # the n-gram drafter had anything to propose, and the measured
    # speedup vs the plain-decode arm of the same run — next to the
    # usual serving SLO keys
    out = _run_dry("llama_serving_spec")
    assert out.returncode == 0, out.stderr
    last = json.loads(out.stdout.splitlines()[-1])
    cell = last["bench_summary"]["llama_serving_spec"]
    assert set(cell) >= {"value", "mfu", "spread",
                         "ttft_p50", "ttft_p99", "tpot",
                         "accept_rate", "draft_hit_rate",
                         "speedup_vs_decode",
                         "goodput_at_slo", "retraces"}, cell
    assert all(v is None for v in cell.values()), cell


def test_dry_serving_tiered_cell_carries_tier_keys():
    # the tiered arm (SERVING.md "KV tiering & traffic harness"): the
    # cell must surface the A/B evidence — the HBM/host/miss hit-rate
    # breakdown, spill/restore volume, what the traffic harness shed,
    # and goodput_at_slo for BOTH arms — next to the usual serving keys
    out = _run_dry("llama_serving_tiered")
    assert out.returncode == 0, out.stderr
    last = json.loads(out.stdout.splitlines()[-1])
    cell = last["bench_summary"]["llama_serving_tiered"]
    assert set(cell) >= {"value", "mfu", "spread",
                         "ttft_p50", "ttft_p99", "tpot",
                         "cache_hit_rate", "tier_hbm_hit_rate",
                         "tier_host_hit_rate", "tier_miss_rate",
                         "spilled_pages", "restored_pages", "shed",
                         "goodput_at_slo", "goodput_at_slo_notier",
                         "retraces"}, cell
    assert all(v is None for v in cell.values()), cell


def test_dry_serving_tp_cell_carries_tp_keys():
    # the tensor-parallel arm (SERVING.md "Tensor-parallel serving"):
    # the cell must surface the A/B evidence — tp degree, per-shard vs
    # total KV bytes per token, and tokens/s + goodput_at_slo for BOTH
    # arms — next to the usual serving keys
    out = _run_dry("llama_serving_tp")
    assert out.returncode == 0, out.stderr
    last = json.loads(out.stdout.splitlines()[-1])
    cell = last["bench_summary"]["llama_serving_tp"]
    assert set(cell) >= {"value", "mfu", "spread",
                         "ttft_p50", "ttft_p99", "tpot",
                         "tp_degree", "tp_shard_kv_bytes_per_token",
                         "kv_bytes_per_token", "tokens_per_s_tp1",
                         "goodput_at_slo", "goodput_at_slo_tp1",
                         "retraces"}, cell
    assert all(v is None for v in cell.values()), cell


def test_dry_serving_pp_cell_carries_pipeline_keys():
    # the pipeline-parallel arm (SERVING.md "Pipeline-parallel
    # serving"): the cell must surface the A/B evidence — pp degree and
    # wave count, the microbatched vs unwaved bubble fraction, per-chip
    # KV bytes for the staged vs tp-only pool (the ~1/pp saving), and
    # tokens/s + goodput_at_slo for BOTH arms — next to the usual
    # serving keys
    out = _run_dry("llama_serving_pp")
    assert out.returncode == 0, out.stderr
    last = json.loads(out.stdout.splitlines()[-1])
    cell = last["bench_summary"]["llama_serving_pp"]
    assert set(cell) >= {"value", "mfu", "spread",
                         "ttft_p50", "ttft_p99", "tpot",
                         "pp_degree", "pp_waves",
                         "pipeline_bubble_frac",
                         "pipeline_bubble_frac_unwaved",
                         "tp_shard_kv_bytes_per_token",
                         "tp_shard_kv_bytes_per_token_tponly",
                         "kv_bytes_per_token", "tokens_per_s_tponly",
                         "goodput_at_slo", "goodput_at_slo_tponly",
                         "retraces"}, cell
    assert all(v is None for v in cell.values()), cell


def test_dry_serving_fairness_cell_carries_overload_ab_keys():
    # the overload-control arm (SERVING.md "Overload control & tenant
    # fairness"): the cell must surface the A/B evidence — the cold
    # tenants' worst p99 TTFT under FCFS vs fair+brownout, what the
    # ladder shed, how often it moved, and goodput_at_slo for BOTH
    # arms — next to the usual serving keys
    out = _run_dry("llama_serving_fairness")
    assert out.returncode == 0, out.stderr
    last = json.loads(out.stdout.splitlines()[-1])
    cell = last["bench_summary"]["llama_serving_fairness"]
    assert set(cell) >= {"value", "mfu", "spread",
                         "ttft_p50", "ttft_p99", "tpot",
                         "cold_ttft_p99", "cold_ttft_p99_fcfs",
                         "shed", "brownout_transitions",
                         "goodput_at_slo", "goodput_at_slo_fcfs",
                         "retraces"}, cell
    assert all(v is None for v in cell.values()), cell


def test_dry_serving_disagg_cell_carries_handoff_ab_keys():
    # the disaggregated arm (SERVING.md "Disaggregated serving"): the
    # cell must surface the A/B evidence — itl_p99 for both arms plus
    # each arm's 10x-prompt flatness ratio (the split's whole point),
    # the handoff volume/fallback counters, and goodput_at_slo for
    # BOTH arms — next to the usual serving keys
    out = _run_dry("llama_serving_disagg")
    assert out.returncode == 0, out.stderr
    last = json.loads(out.stdout.splitlines()[-1])
    cell = last["bench_summary"]["llama_serving_disagg"]
    assert set(cell) >= {"value", "mfu", "spread",
                         "ttft_p50", "ttft_p99", "ttft_p99_colocated",
                         "tpot",
                         "itl_p99", "itl_p99_colocated",
                         "itl_p99_ratio_10x",
                         "itl_p99_colocated_ratio_10x",
                         "handoff_pulls", "handoff_bytes",
                         "handoff_recomputes",
                         "goodput_at_slo", "goodput_at_slo_colocated",
                         "retraces"}, cell
    assert all(v is None for v in cell.values()), cell


def test_dry_serving_lora_cell_carries_adapter_keys():
    # the multi-tenant LoRA arm (SERVING.md "Multi-tenant LoRA
    # serving"): the cell must surface the adapter economics — hit
    # rate, load/eviction churn, bytes streamed host<->HBM — plus the
    # base and single-adapter arms' throughput and the multi/single
    # ratio the acceptance gate reads, next to the usual serving keys
    out = _run_dry("llama_serving_lora")
    assert out.returncode == 0, out.stderr
    last = json.loads(out.stdout.splitlines()[-1])
    cell = last["bench_summary"]["llama_serving_lora"]
    assert set(cell) >= {"value", "mfu", "spread",
                         "ttft_p50", "ttft_p99", "tpot",
                         "n_adapters", "adapter_hit_rate",
                         "adapter_loads", "adapter_evictions",
                         "lora_bytes_streamed",
                         "tokens_per_s_base", "tokens_per_s_single",
                         "multi_vs_single_ratio",
                         "goodput_at_slo", "goodput_at_slo_base",
                         "retraces"}, cell
    assert all(v is None for v in cell.values()), cell


def test_dry_trace_flag_path_not_eaten_as_config_name():
    # --trace PATH: PATH does not start with "-", so the flag must be
    # stripped before the positional config-name filter sees argv
    out = _run_dry("--trace", "serve.trace.json", "llama_serving")
    assert out.returncode == 0, out.stderr
    last = json.loads(out.stdout.splitlines()[-1])
    assert set(last["bench_summary"]) == {"llama_serving"}
    bad = _run_dry("llama_serving", "--trace")
    assert bad.returncode != 0, "--trace without PATH must fail"


def test_metrics_endpoint_serves_parseable_prometheus_text():
    """Tier-1-safe /metrics smoke: a MetricsServer on an ephemeral port
    fed by an explicit render callable (no engine, no jax) must serve
    text every strict Prometheus parser accepts, plus /healthz JSON."""
    import urllib.request

    from paddle_tpu.observability import (MetricsServer, parse_prometheus,
                                          render_prometheus)

    text_src = render_prometheus(
        {"tokens_per_s": 12.5, "ttft_p99_s": 0.25, "goodput_at_slo": 3.0,
         "note": "non-numeric values are skipped"},
        {"in_use": 7, "utilization": 0.5},
        {"compiles": 2})
    srv = MetricsServer(render=lambda: text_src,
                        health=lambda: {"status": "ok"})
    port = srv.start()
    try:
        assert port != 0
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/plain")
            body = r.read().decode()
        metrics = parse_prometheus(body)  # raises on any malformed line
        assert metrics["paddle_serving_tokens_per_seconds"] == 12.5
        assert metrics["paddle_serving_ttft_p99_seconds"] == 0.25
        assert metrics["paddle_serving_goodput_at_slo"] == 3.0
        assert metrics["paddle_serving_pool_in_use"] == 7
        assert metrics["paddle_serving_trace_compiles_total"] == 2
        assert "paddle_serving_note" not in metrics
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
            assert json.loads(r.read().decode()) == {"status": "ok"}
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/nope", timeout=10) as r:
            raise AssertionError("unknown path must 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404
    finally:
        srv.stop()
