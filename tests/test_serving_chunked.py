"""Chunked prefill + mixed prefill/decode steps (SERVING.md "Chunked
prefill & mixed steps").

The chunked contracts:

1. BITWISE PARITY — emitted streams with chunking on are bitwise
   identical to ``generate()`` and to the unchunked arm, for every
   chunk size, composed with prefix caching, int8 KV, speculative
   verify and preemption/recompute. Chunk boundaries are data, never
   semantics.
2. O(1) PROGRAMS — ``step_program_counts() == {"decode": 1, "mixed": 1}``
   under churn, mixed prefill/decode steps, varying chunk sizes and
   mid-prompt preemption: the pow2 suffix-bucket prefill family is gone
   and ``stats()["prefill_programs"]`` reads the ONE mixed program.
3. BUDGET METERING — per-step prefill chunk tokens never exceed the
   prefill token budget (minus the verify reserve), FCFS over
   prefilling slots, with the oldest slot always advancing.
4. FINAL-CHUNK REGISTRATION — prefix pages commit on the final chunk
   only: a request preempted mid-prompt registers nothing and leaks no
   COW refs (first-writer-wins preserved).
"""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.distributed import fault
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.observability import Tracer
from paddle_tpu.serving import (SamplingParams, ServingEngine,
                                SpeculativeConfig, WorkloadSpec,
                                heavy_tail_workload, make_workload)

RNG = np.random.default_rng(31)

# one long prompt (several chunks at chunk=8) + short companions
P_LONG = RNG.integers(0, 512, 29).tolist()
P_A = RNG.integers(0, 512, 5).tolist()
P_B = RNG.integers(0, 512, 7).tolist()
MAX_NEW = 8


@pytest.fixture(scope="module")
def model():
    pt.seed(123)
    m = LlamaForCausalLM(llama_tiny(dtype="float32",
                                    mp_axis=None, fsdp_axis=None))
    m.eval()
    return m


@pytest.fixture(scope="module")
def refs(model):
    return {id_: _reference(model, p, MAX_NEW)
            for id_, p in (("long", P_LONG), ("a", P_A), ("b", P_B))}


@pytest.fixture
def fault_free():
    fault.deactivate()
    yield
    fault.deactivate()


def _reference(model, prompt, max_new, **kw):
    out = model.generate(jnp.asarray([prompt]), max_new_tokens=max_new, **kw)
    return np.asarray(out)[0, len(prompt):].tolist()


def _engine(model, **kw):
    kw.setdefault("num_pages", 64)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_slots", 4)
    kw.setdefault("max_pages_per_slot", 16)
    return ServingEngine(model, **kw)


class TestChunkedParity:
    @pytest.mark.parametrize("chunk", [1, 4, 8, 64])
    def test_chunk_size_never_changes_the_stream(self, model, refs, chunk):
        eng = _engine(model, chunked=True, prefill_chunk=chunk)
        rids = [eng.add_request(p, MAX_NEW)
                for p in (P_LONG, P_A, P_B)]
        res = eng.run_to_completion(max_steps=400)
        for rid, ref in zip(rids, (refs["long"], refs["a"], refs["b"])):
            assert res[rid] == ref, f"chunk={chunk}"
        assert eng.step_program_counts() == {"decode": 1, "mixed": 1}

    def test_decode_interleaves_with_chunks(self, model, refs):
        """The tentpole behavior: while the long prompt streams through
        in budget-sized chunks, an already-decoding request keeps
        emitting EVERY step instead of stalling behind the prefill."""
        eng = _engine(model, chunked=True, prefill_chunk=4,
                      prefill_token_budget=4)
        rid_a = eng.add_request(P_A, MAX_NEW)
        eng.step()                      # a's prompt (5 toks > budget 4)
        eng.step()                      # ... finishes chunking, emits
        assert len(eng.request(rid_a).tokens) == 1
        rid_l = eng.add_request(P_LONG, MAX_NEW)
        emitted = []
        for _ in range(6):              # long prompt: 29 toks / 4 per step
            n0 = len(eng.request(rid_a).tokens)
            eng.step()
            emitted.append(len(eng.request(rid_a).tokens) - n0)
            assert eng.request(rid_l).prefilling or \
                eng.request(rid_l).tokens
        # a decoded on every one of those mixed steps
        assert all(n == 1 for n in emitted)
        res = eng.run_to_completion(max_steps=200)
        assert res[rid_a] == refs["a"]
        assert res[rid_l] == refs["long"]

    @pytest.mark.slow
    def test_parity_composed_with_prefix_cache_and_int8(self, model):
        shared = RNG.integers(0, 512, 18).tolist()
        prompts = [shared + RNG.integers(0, 512, n).tolist()
                   for n in (3, 5)]
        for kv_quant in (False, True):
            # int8 reference is generate(kv_dtype="int8") — the quant
            # parity contract from test_serving_quant
            kw = {"kv_dtype": "int8"} if kv_quant else {}
            refs_ = [_reference(model, p, 6, **kw) for p in prompts]
            eng = _engine(model, chunked=True, prefill_chunk=8,
                          kv_quant=kv_quant)
            rid0 = eng.add_request(prompts[0], 6)
            eng.step()  # registration commits on the final chunk...
            eng.step()
            eng.step()
            rid1 = eng.add_request(prompts[1], 6)
            res = eng.run_to_completion(max_steps=200)
            assert res[rid0] == refs_[0], f"kv_quant={kv_quant}"
            assert res[rid1] == refs_[1], f"kv_quant={kv_quant}"
            # ...so the second arrival shares the full shared pages
            assert eng.metrics.summary()["prefix_hits"] >= 1

    def test_parity_composed_with_speculation(self, model, refs):
        eng = _engine(model, chunked=True, prefill_chunk=8,
                      speculative=SpeculativeConfig(k=4))
        rids = [eng.add_request(p, MAX_NEW) for p in (P_LONG, P_A)]
        res = eng.run_to_completion(max_steps=400)
        assert res[rids[0]] == refs["long"]
        assert res[rids[1]] == refs["a"]
        # spec verify rides the SAME mixed program as the chunks
        assert eng.step_program_counts() == {"decode": 1, "mixed": 1}
        assert eng.verify_program_count() == 1

    @pytest.mark.slow
    def test_sampled_stream_parity_across_chunk_sizes(self, model):
        sp = SamplingParams(do_sample=True, top_p=0.9, temperature=0.8,
                            seed=17)
        outs = []
        for chunk in (4, 64):
            eng = _engine(model, chunked=True, prefill_chunk=chunk)
            rid = eng.add_request(P_LONG, MAX_NEW,
                                  sampling=SamplingParams(**sp.__dict__))
            outs.append(eng.run_to_completion(max_steps=200)[rid])
        assert outs[0] == outs[1]

    @pytest.mark.slow
    def test_unchunked_arm_matches_chunked_arm(self, model, refs):
        outs = []
        for chunked in (False, True):
            eng = _engine(model, chunked=chunked, prefill_chunk=8)
            rids = [eng.add_request(p, MAX_NEW) for p in (P_LONG, P_B)]
            res = eng.run_to_completion(max_steps=400)
            outs.append([res[r] for r in rids])
        assert outs[0] == outs[1] == [refs["long"], refs["b"]]


class TestChunkedPrograms:
    @pytest.mark.slow
    def test_o1_programs_over_churn_epochs_with_preemption(self, model,
                                                           fault_free):
        """3 churn epochs on a page-starved engine (mid-prompt
        preemption guaranteed by an injected alloc storm): program
        counts stay {"decode": 1, "mixed": 1} throughout and streams
        replay bitwise after recompute."""
        fault.activate(fault.FaultPlan([
            fault.FaultSpec(site="serving.alloc", action="raise",
                            prob=0.35, once=False),
        ], seed=9))
        eng = _engine(model, num_pages=20, max_slots=2,
                      max_pages_per_slot=12, chunked=True,
                      prefill_chunk=4)
        for epoch in range(3):
            prompts = [RNG.integers(0, 512, n).tolist()
                       for n in (17 + epoch, 6)]
            refs_ = [_reference(model, p, 6) for p in prompts]
            rids = [eng.add_request(p, 6) for p in prompts]
            res = eng.run_to_completion(max_steps=500)
            for rid, ref in zip(rids, refs_):
                assert res[rid] == ref, f"epoch {epoch}"
            assert eng.step_program_counts() == \
                {"decode": 1, "mixed": 1}, f"retraced in epoch {epoch}"
        assert eng.scheduler.num_preemptions > 0
        assert eng.stats()["prefill_programs"] == 1

    def test_warm_programs_compiles_both_shapes(self, model):
        eng = _engine(model, chunked=True, prefill_chunk=8)
        assert eng.step_program_counts() == {"decode": 0, "mixed": 0}
        eng.warm_programs()
        assert eng.step_program_counts() == {"decode": 1, "mixed": 1}
        eng.warm_programs()  # idempotent
        assert eng.step_program_counts() == {"decode": 1, "mixed": 1}
        # the warm dispatch wrote nothing but scratch
        assert eng.pool.num_in_use == 0

    def test_retrace_sentinel_names_the_mixed_program(self, model):
        tr = Tracer()
        eng = _engine(model, chunked=True, prefill_chunk=8, tracer=tr)
        rid = eng.add_request(P_LONG, 4)
        eng.run_to_completion(max_steps=200)
        progs = {e["args"]["program"] for e in tr.events
                 if e["name"] == "compile"}
        assert progs <= {"decode", "mixed"}
        assert "mixed" in progs
        chunks = [e for e in tr.events if e["name"] == "chunk"]
        assert len(chunks) >= 1
        assert all(e["track"] == rid for e in chunks)


class TestChunkBudget:
    def test_chunk_tokens_metered_by_budget(self, model):
        """Per-step chunk tokens never exceed the prefill budget, and a
        long prompt takes ceil(len/budget) steps to materialize."""
        eng = _engine(model, chunked=True, prefill_chunk=64,
                      prefill_token_budget=8)
        rid = eng.add_request(P_LONG, 4)   # 29 prompt tokens
        req = eng.request(rid)
        steps = 0
        while req.prefilling or not req.tokens:
            c0 = req.context_len
            eng.step()
            assert req.context_len - c0 <= 8
            steps += 1
            assert steps < 20
        assert steps == -(-29 // 8)  # 4 steps of <= 8 chunk tokens
        last = eng.metrics.summary()
        assert last["chunk_tokens_total"] == 29
        assert last["mixed_steps"] == 4

    def test_oldest_prefilling_slot_always_advances(self, model):
        """Zero/negative leftover budget (verify reserve can eat it
        all) still advances the oldest prefilling slot — the
        no-starvation guarantee behind the stall detector."""
        eng = _engine(model, chunked=True, prefill_chunk=4,
                      prefill_token_budget=1,
                      speculative=SpeculativeConfig(k=4))
        rid = eng.add_request(P_LONG, 2)
        req = eng.request(rid)
        for _ in range(40):
            if not req.prefilling and req.tokens:
                break
            c0 = req.context_len
            eng.step()
            assert req.context_len > c0 or req.tokens
        assert req.tokens  # progressed to emission despite budget 1

    def test_fcfs_no_queue_jumping(self, model):
        """Two prefilling slots: the younger one only chunks with
        leftover budget after the older one's chunk."""
        eng = _engine(model, chunked=True, prefill_chunk=8,
                      prefill_token_budget=8)
        r0 = eng.add_request(P_LONG, 2)
        eng.step()  # r0 chunks 8
        r1 = eng.add_request(RNG.integers(0, 512, 20).tolist(), 2)
        eng.step()  # r0 chunks 8 more; r1 gets nothing (budget gone)
        assert eng.request(r0).context_len == 16
        assert eng.request(r1).context_len == 0
        eng.run_to_completion(max_steps=100)
        assert len(eng.request(r0).tokens) == 2
        assert len(eng.request(r1).tokens) == 2


class TestFinalChunkRegistration:
    def test_mid_prompt_preemption_registers_nothing(self, model,
                                                     fault_free):
        """Satellite 1 regression: preempt a request BETWEEN chunks —
        no partial-prompt pages may enter the prefix index, no COW refs
        may leak, and the recompute still replays bitwise."""
        prompt = RNG.integers(0, 512, 24).tolist()
        ref = _reference(model, prompt, 6)
        eng = _engine(model, num_pages=16, max_slots=2,
                      max_pages_per_slot=10, chunked=True,
                      prefill_chunk=4, prefill_token_budget=4)
        rid = eng.add_request(prompt, 6)
        eng.step()  # one 4-token chunk in flight, 20 to go
        req = eng.request(rid)
        assert req.prefilling and req.context_len == 4
        # force a mid-prompt preemption through the scheduler's own path
        victim = eng.scheduler._preempt_youngest(eng.pool)
        assert victim is req and req.pages == []
        # nothing registered: the same prompt must miss the cache
        # entirely, and no COW copies may have been taken
        assert eng.pool.match_prefix(prompt).cached_tokens == 0
        assert eng.pool.counters["prefix_cow_copies"] == 0
        res = eng.run_to_completion(max_steps=300)
        assert res[rid] == ref

    def test_injected_chunk_failure_never_registers(self, model,
                                                    fault_free):
        fault.activate(fault.FaultPlan([
            fault.FaultSpec(site="serving.prefill", action="raise",
                            match=r"^doomed$"),
        ], seed=3))
        eng = _engine(model, chunked=True, prefill_chunk=4)
        prompt = RNG.integers(0, 512, 10).tolist()
        rid = eng.add_request(prompt, 4, rid="doomed")
        ok = eng.add_request(P_A, 4, rid="ok")
        res = eng.run_to_completion(max_steps=100)
        assert eng.request("doomed").finish_reason == "injected"
        assert res["doomed"] == []
        assert len(res["ok"]) == 4
        assert eng.pool.match_prefix(prompt).cached_tokens == 0

    @pytest.mark.slow
    def test_first_writer_wins_when_two_chunkers_share(self, model):
        """Two same-step requests over one shared prefix both chunk to
        completion in the same dispatches; both register at their final
        chunks and first-writer-wins keeps exactly one copy indexed."""
        shared = RNG.integers(0, 512, 16).tolist()
        prompts = [shared + RNG.integers(0, 512, n).tolist()
                   for n in (2, 3)]
        refs_ = [_reference(model, p, 4) for p in prompts]
        eng = _engine(model, chunked=True, prefill_chunk=8)
        rids = [eng.add_request(p, 4) for p in prompts]
        res = eng.run_to_completion(max_steps=100)
        for rid, ref in zip(rids, refs_):
            assert res[rid] == ref
        # a later arrival hits the one surviving copy
        rid2 = eng.add_request(shared + [7, 8, 9], 4)
        eng.step()
        assert eng.metrics.summary()["prefix_hits"] >= 1
        eng.run_to_completion(max_steps=100)


class TestChunkedMetrics:
    def test_mixed_batch_gauges(self, model):
        eng = _engine(model, chunked=True, prefill_chunk=4,
                      prefill_token_budget=4)
        rid_a = eng.add_request(P_A, MAX_NEW)
        eng.run_to_completion(max_steps=100)
        s = eng.metrics.summary()
        assert s["chunked_enabled"] == 1
        assert s["mixed_steps"] >= 1
        assert s["chunk_tokens_total"] == len(P_A)
        assert s["chunks_dispatched_total"] >= 2  # 5 tokens / 4-chunks
        for key in ("chunk_prefill_tokens_last", "chunk_decode_slots_last",
                    "chunks_in_flight"):
            assert key in s
        # unchunked arm reports the flag off but the same schema
        eng2 = _engine(model, chunked=False)
        s2 = eng2.metrics.summary()
        assert s2["chunked_enabled"] == 0
        assert s2["mixed_steps"] == 0

    def test_prometheus_exports_chunk_gauges(self, model):
        from paddle_tpu.observability import (parse_prometheus,
                                              render_prometheus)
        eng = _engine(model, chunked=True, prefill_chunk=4)
        eng.add_request(P_A, 4)
        eng.run_to_completion(max_steps=50)
        page = render_prometheus(eng.metrics.summary(), eng.pool.stats())
        parsed = parse_prometheus(page)
        assert parsed["paddle_serving_chunked_enabled"] == 1
        assert parsed["paddle_serving_chunk_tokens_total"] == len(P_A)
        assert "paddle_serving_mixed_steps" in parsed


class TestHeavyTailWorkload:
    def test_preset_is_deterministic_and_heavy_tailed(self):
        wl = heavy_tail_workload(seed=5, n_requests=64)
        wl2 = heavy_tail_workload(seed=5, n_requests=64)
        assert [(r.rid, r.prompt, r.max_new_tokens, r.arrival_step)
                for r in wl] == \
               [(r.rid, r.prompt, r.max_new_tokens, r.arrival_step)
                for r in wl2]
        plens = sorted(len(r.prompt) for r in wl)
        # heavy tail: the top decile dwarfs the median
        assert plens[-1] >= 48
        assert plens[len(plens) // 2] <= 30
        # a different seed draws a different trace
        other = heavy_tail_workload(seed=6, n_requests=64)
        assert [r.prompt for r in other] != [r.prompt for r in wl]

    def test_lognormal_spec_validation(self):
        with pytest.raises(ValueError):
            make_workload(WorkloadSpec(suffix_dist="pareto"))

    def test_replay_on_chunked_engine_drains(self, model, fault_free):
        wl = heavy_tail_workload(seed=2, n_requests=6,
                                 suffix_clip=(24, 40), max_new=(2, 4),
                                 light_max_new=(4, 8))
        eng = _engine(model, chunked=True, prefill_chunk=8)
        out = wl.replay(eng, max_steps=400)
        assert out["submitted"] + out["shed"] == 6
        assert eng.step_program_counts() == {"decode": 1, "mixed": 1}


# ---------------------------------------------------------------------------
# drain / failover arriving MID-CHUNK on a prefilling slot
# ---------------------------------------------------------------------------

class TestMidChunkDrain:
    def _mid_chunk(self, eng, rid):
        """Step until ``rid`` is mid-prompt: some chunks consumed, the
        final chunk not yet dispatched."""
        guard = 0
        while True:
            req = eng.request(rid)
            if req.prefilling and req.context_len > 0:
                return req
            eng.step()
            guard += 1
            assert guard < 50, "never observed a mid-chunk slot"

    def test_drain_mid_chunk_stops_at_boundary_registers_nothing(
            self, model, fault_free):
        """SIGTERM between chunk steps: the drain preempts the slot at
        the chunk boundary — zero tokens emitted for the partial
        prompt, NOTHING registered in the prefix index (final-chunk
        registration), and the outcome is retriable."""
        eng = _engine(model, chunked=True, prefill_chunk=8)
        rid = eng.add_request(P_LONG, MAX_NEW)
        eng.step()
        req = self._mid_chunk(eng, rid)
        assert 0 < req.context_len < len(P_LONG)
        report = eng.drain(timeout_s=0.0)
        assert report[rid]["finish_reason"] == "preempted"
        assert report[rid]["retriable"] is True
        assert report[rid]["tokens"] == []      # prefill never finished
        assert eng.pool.counters["prefix_pages_registered"] == 0
        assert eng.pool.num_in_use == 0         # partial pages released
        eng.audit_pool()

    def test_failover_mid_chunk_replays_bitwise_on_survivor(
            self, model, fault_free):
        """Replica killed while its slot is mid-chunk: the surviving
        replica replays from scratch and the client stream is bitwise
        the single-engine run — a half-prefilled prompt contributes
        nothing (no tokens, no registered pages) to the replay."""
        from paddle_tpu.serving import FleetRouter
        ref = _reference(model, P_LONG, MAX_NEW)
        engines = [_engine(model, chunked=True, prefill_chunk=8)
                   for _ in range(2)]
        router = FleetRouter(engines)
        rid = router.submit(P_LONG, MAX_NEW)
        guard = 0
        while router.request(rid).replica is None:
            router.step()
            guard += 1
            assert guard < 50
        victim = router.request(rid).replica
        veng = engines[victim]
        req = self._mid_chunk(veng, rid)
        assert 0 < req.context_len < len(P_LONG)
        router.kill_replica(victim)
        out = router.run_to_completion(max_steps=400)
        assert out[rid] == ref                  # bitwise, exactly-once
        assert router.request(rid).emitted == len(ref)
        # the victim registered nothing for its partial prompt
        assert veng.pool.counters["prefix_pages_registered"] == 0
        survivor = engines[1 - victim]
        assert all(v <= 1
                   for v in survivor.step_program_counts().values())
        survivor.audit_pool()
