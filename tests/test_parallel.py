"""Hybrid-parallel tests on the 8-device virtual CPU mesh
(parity model: test/collective/fleet/ hybrid tests — numeric equivalence of
parallel vs single-device execution, SURVEY §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.core import mesh as mesh_lib
import paddle_tpu.nn.functional as F

RNG = np.random.default_rng(11)


@pytest.fixture()
def hybrid_mesh():
    mesh = mesh_lib.make_mesh({"dp": 2, "pp": 1, "fsdp": 1, "sep": 1, "mp": 4})
    with mesh_lib.use_mesh(mesh):
        yield mesh


@pytest.fixture()
def sep_mesh():
    mesh = mesh_lib.make_mesh({"dp": 1, "pp": 1, "fsdp": 2, "sep": 4, "mp": 1})
    with mesh_lib.use_mesh(mesh):
        yield mesh


@pytest.fixture()
def pp_mesh():
    mesh = mesh_lib.make_mesh({"dp": 2, "pp": 4, "fsdp": 1, "sep": 1, "mp": 1})
    with mesh_lib.use_mesh(mesh):
        yield mesh


def test_column_row_parallel_match_dense(hybrid_mesh):
    from paddle_tpu.distributed.fleet.mp_layers import (ColumnParallelLinear,
                                                        RowParallelLinear)
    pt.seed(0)
    col = ColumnParallelLinear(16, 32, gather_output=False)
    row = RowParallelLinear(32, 16, input_is_parallel=True)
    x = jnp.asarray(RNG.standard_normal((4, 16)), jnp.float32)

    @jax.jit
    def tp_fwd(x, cw, cb, rw, rb):
        h = x @ cw + cb
        h = jax.nn.relu(h)
        return h @ rw + rb

    # dense reference
    want = jax.nn.relu(x @ col.weight + col.bias) @ row.weight + row.bias
    # run with mp-sharded weights
    cw = jax.device_put(col.weight, NamedSharding(hybrid_mesh, P(None, "mp")))
    rw = jax.device_put(row.weight, NamedSharding(hybrid_mesh, P("mp", None)))
    got = tp_fwd(x, cw, col.bias, rw, row.bias)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4,
                               atol=1e-5)


def test_fleet_tp_training_matches_single_device(hybrid_mesh):
    """TP-sharded training must produce the same losses as unsharded."""
    from paddle_tpu.distributed import fleet

    def build():
        pt.seed(42)
        return nn.Sequential(
            nn.Linear(16, 64, weight_spec=(None, "mp")), nn.ReLU(),
            nn.Linear(64, 4, weight_spec=("mp", None)))

    x = RNG.standard_normal((8, 16)).astype(np.float32)
    y = RNG.integers(0, 4, 8)

    def run(shard):
        model = build()
        if shard:
            from paddle_tpu.distributed.fleet.meta_parallel import \
                apply_hybrid_shardings
            apply_hybrid_shardings(model, hybrid_mesh, None)
        opt = pt.optimizer.Adam(learning_rate=1e-2, parameters=model)
        step = pt.jit.TrainStep(model, opt, lambda o, t: F.cross_entropy(o, t))
        return [float(step(x, y)) for _ in range(5)]

    dense = run(False)
    tp = run(True)
    np.testing.assert_allclose(dense, tp, rtol=1e-3)


def test_fsdp_sharding_and_zero_stages(sep_mesh):
    from paddle_tpu.distributed.sharding import group_sharded_parallel
    pt.seed(1)
    model = nn.Sequential(nn.Linear(256, 4096), nn.ReLU(), nn.Linear(4096, 8))
    opt = pt.optimizer.Adam(learning_rate=1e-3, parameters=model)
    model, opt, _ = group_sharded_parallel(model, opt, level="p_g_os",
                                           segment_size=4096)
    w = model.state_dict()["0.weight"]
    assert "fsdp" in str(w.sharding.spec)
    # training still works sharded
    x = RNG.standard_normal((4, 256)).astype(np.float32)
    y = RNG.integers(0, 8, 4)
    step = pt.jit.TrainStep(model, opt, lambda o, t: F.cross_entropy(o, t))
    l0 = float(step(x, y))
    l1 = float(step(x, y))
    assert np.isfinite(l0) and l1 < l0
    # stage-1: optimizer state sharded, params replicated
    model2 = nn.Sequential(nn.Linear(256, 4096), nn.ReLU(), nn.Linear(4096, 8))
    opt2 = pt.optimizer.Adam(learning_rate=1e-3, parameters=model2)
    model2, opt2, _ = group_sharded_parallel(model2, opt2, level="os",
                                             segment_size=4096)
    state = opt2.init_state(model2.param_dict())
    m1 = state["moment1"]["0.weight"]
    assert "fsdp" in str(m1.sharding.spec)


def test_pipeline_matches_sequential(pp_mesh):
    from paddle_tpu.distributed.pipeline import PipelineStagedLayers
    pt.seed(2)
    layers = [nn.Linear(16, 16) for _ in range(8)]
    staged = PipelineStagedLayers(layers, num_micro=4, axis="pp")
    x = jnp.asarray(RNG.standard_normal((8, 16)), jnp.float32)
    ref = x
    for l in layers:
        ref = l(ref)
    out = staged(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4,
                               atol=1e-5)
    # end-to-end grads through the pipeline
    from paddle_tpu.nn.module import functional_call
    state = staged.state_dict()

    def loss_fn(state, x):
        o, _ = functional_call(staged, state, x)
        return jnp.sum(o ** 2)

    g = jax.jit(jax.grad(loss_fn))(state, x)

    def ref_loss(ws, x):
        h = x
        for w, b in ws:
            h = h @ w + b
        return jnp.sum(h ** 2)

    gr = jax.grad(ref_loss)([(l.weight, l.bias) for l in layers], x)
    k = next(k for k in g if k.endswith("weight"))
    for li in (0, 3, 7):
        np.testing.assert_allclose(np.asarray(g[k][li]), np.asarray(gr[li][0]),
                                   rtol=1e-4, atol=1e-5)


def test_pipeline_trains_e2e(pp_mesh):
    from paddle_tpu.distributed.pipeline import PipelineStagedLayers
    pt.seed(3)

    class PPModel(nn.Layer):
        def __init__(self):
            super().__init__()
            self.embed = nn.Linear(8, 32)
            self.middle = PipelineStagedLayers(
                [nn.Linear(32, 32) for _ in range(4)], num_micro=2, axis="pp")
            self.head = nn.Linear(32, 3)

        def forward(self, x):
            return self.head(self.middle(F.relu(self.embed(x))))

    model = PPModel()
    opt = pt.optimizer.Adam(learning_rate=5e-3, parameters=model)
    step = pt.jit.TrainStep(model, opt, lambda o, t: F.cross_entropy(o, t))
    x = RNG.standard_normal((8, 8)).astype(np.float32)
    y = RNG.integers(0, 3, 8)
    losses = [float(step(x, y)) for _ in range(10)]
    assert losses[-1] < losses[0]


def test_ulysses_and_ring_match_reference(sep_mesh):
    from paddle_tpu.distributed.sequence_parallel import (ring_attention,
                                                          ulysses_attention)
    from paddle_tpu.nn.functional.attention import _xla_attention
    b, s, h, d = 2, 128, 4, 32
    q = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    ref = _xla_attention(q, k, v, is_causal=True)
    np.testing.assert_allclose(np.asarray(ulysses_attention(q, k, v, causal=True)),
                               np.asarray(ref), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ring_attention(q, k, v, causal=True)),
                               np.asarray(ref), rtol=1e-4, atol=1e-5)
    g1 = jax.grad(lambda q: jnp.sum(jnp.sin(ring_attention(q, k, v, causal=True))))(q)
    g2 = jax.grad(lambda q: jnp.sum(jnp.sin(_xla_attention(q, k, v, is_causal=True))))(q)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-3, atol=1e-4)


def test_async_checkpoint_save(tmp_path):
    """async_save snapshots to host and writes in the background; the files
    must load back identically after .result()."""
    from paddle_tpu.distributed.checkpoint import (load_state_dict,
                                                   save_state_dict)
    state = {"w": jnp.asarray(RNG.standard_normal((16, 8)), jnp.float32),
             "b": jnp.asarray(RNG.standard_normal((8,)), jnp.float32)}
    handle = save_state_dict(state, str(tmp_path / "ck"), async_save=True)
    handle.result(timeout=60)
    assert handle.done()
    dst = {"w": jnp.zeros((16, 8)), "b": jnp.zeros((8,))}
    out = load_state_dict(dst, str(tmp_path / "ck"))
    np.testing.assert_allclose(np.asarray(out["w"]), np.asarray(state["w"]))
    np.testing.assert_allclose(np.asarray(out["b"]), np.asarray(state["b"]))


def test_ring_attention_gqa(sep_mesh):
    """GQA ring: k/v travel at kv-head width, repeated per step — must match
    the pre-repeated full-head reference, values and grads."""
    from paddle_tpu.distributed.sequence_parallel import ring_attention
    from paddle_tpu.nn.functional.attention import _xla_attention
    b, s, h, kvh, d = 2, 128, 4, 2, 16
    q = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(RNG.standard_normal((b, s, kvh, d)), jnp.float32)
    v = jnp.asarray(RNG.standard_normal((b, s, kvh, d)), jnp.float32)
    kf = jnp.repeat(k, h // kvh, axis=2)
    vf = jnp.repeat(v, h // kvh, axis=2)
    ref = _xla_attention(q, kf, vf, is_causal=True)
    np.testing.assert_allclose(np.asarray(ring_attention(q, k, v, causal=True)),
                               np.asarray(ref), rtol=1e-4, atol=1e-5)
    gk = jax.grad(lambda k: jnp.sum(jnp.sin(
        ring_attention(q, k, v, causal=True))))(k)
    gk_ref = jax.grad(lambda k: jnp.sum(jnp.sin(_xla_attention(
        q, jnp.repeat(k, h // kvh, axis=2), vf, is_causal=True))))(k)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(gk_ref),
                               rtol=1e-3, atol=1e-4)


def test_moe_layer_and_gates(sep_mesh):
    from paddle_tpu.distributed.moe import MoELayer
    pt.seed(4)
    for gate in ("gshard", "switch"):
        moe = MoELayer(d_model=16, num_experts=4, d_hidden=32, gate=gate)
        x = jnp.asarray(RNG.standard_normal((2, 8, 16)), jnp.float32)
        y = moe(x)
        assert y.shape == x.shape
        assert float(moe.aux_loss) > 0
    # training decreases loss (includes aux via buffer read)
    moe = MoELayer(d_model=16, num_experts=4, d_hidden=32, gate="gshard")
    opt = pt.optimizer.Adam(learning_rate=1e-2, parameters=moe)
    t = jnp.asarray(RNG.standard_normal((2, 8, 16)), jnp.float32)
    x = jnp.asarray(RNG.standard_normal((2, 8, 16)), jnp.float32)
    step = pt.jit.TrainStep(moe, opt, lambda o, tt: F.mse_loss(o, tt))
    losses = [float(step(x, t)) for _ in range(8)]
    assert losses[-1] < losses[0]


def test_moe_capacity_drops_tokens():
    from paddle_tpu.distributed.moe import TopKGate
    pt.seed(5)
    gate = TopKGate(8, 2, top_k=1, capacity_factor=0.5)
    x = jnp.asarray(RNG.standard_normal((64, 8)), jnp.float32)
    dispatch, combine, aux = gate(x)
    # with capacity factor 0.5, at most 50%+eps of tokens can be dispatched
    assert float(jnp.sum(dispatch)) <= 64 * 0.75


def test_collectives_inside_shard_map(sep_mesh):
    from paddle_tpu import distributed as dist
    from paddle_tpu.core.compat import shard_map

    x = jnp.arange(8.0)

    def f(x):
        s = dist.all_reduce(x, group="sep")
        g = dist.all_gather(x, group="sep", axis=0)
        rs = dist.reduce_scatter(g, group="sep", axis=0)
        return s, g, rs

    s, g, rs = shard_map(f, mesh=sep_mesh,
                         in_specs=P("sep"), out_specs=(P("sep"), P(), P("sep")),
                         check_vma=False)(x)
    # all_reduce of per-device shards sums to full-array segments
    np.testing.assert_allclose(np.asarray(g), np.arange(8.0))
    # reduce_scatter sums the 4 replicated gathered copies, then scatters
    np.testing.assert_allclose(np.asarray(rs), 4 * np.arange(8.0))
    total = np.arange(8).reshape(4, 2).sum(0)
    np.testing.assert_allclose(np.asarray(s).reshape(4, 2),
                               np.tile(total, (4, 1)))


def test_dist_checkpoint_reshard_roundtrip(hybrid_mesh, tmp_path):
    from paddle_tpu.distributed.checkpoint import load_state_dict, save_state_dict
    w = jax.device_put(jnp.arange(64.0).reshape(8, 8),
                       NamedSharding(hybrid_mesh, P("mp", None)))
    save_state_dict({"w": w}, str(tmp_path / "ckpt"))
    tmpl = {"w": jax.device_put(jnp.zeros((8, 8)),
                                NamedSharding(hybrid_mesh, P(None, "mp")))}
    out = load_state_dict(tmpl, str(tmp_path / "ckpt"))
    np.testing.assert_allclose(np.asarray(out["w"]),
                               np.arange(64.0).reshape(8, 8))
    assert "mp" in str(out["w"].sharding.spec)


def test_dataparallel_wrapper(hybrid_mesh):
    from paddle_tpu.distributed import DataParallel
    m = nn.Linear(4, 4)
    dp = DataParallel(m)
    x = jnp.ones((2, 4))
    np.testing.assert_allclose(np.asarray(dp(x)), np.asarray(m(x)))
    with dp.no_sync():
        pass
    assert dp.state_dict().keys() == m.state_dict().keys()


def test_global_scatter_gather_roundtrip(sep_mesh):
    """Explicit EP all-to-all dispatch (parity: moe_utils.py
    global_scatter/global_gather): tokens routed to expert ranks, processed,
    and returned must equal applying each expert directly."""
    from paddle_tpu.core.compat import shard_map
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.distributed.moe import global_gather, global_scatter
    mesh = mesh_lib.current_mesh()
    Pdeg = mesh.shape["mp"]
    E, C, d = 2 * Pdeg, 3, 8   # 2 experts per rank
    x = jnp.asarray(RNG.standard_normal((E, C, d)), jnp.float32)
    scales = jnp.arange(1, E + 1, dtype=jnp.float32)  # expert e multiplies by e+1

    def body(x):
        inbox = global_scatter(x, None, None, axis="mp")   # [E/P, P*C, d]
        r = jax.lax.axis_index("mp")
        local_ids = r * (E // Pdeg) + jnp.arange(E // Pdeg)
        out = inbox * scales[local_ids][:, None, None]
        return global_gather(out, None, None, axis="mp")

    got = jax.jit(shard_map(body, mesh=mesh, in_specs=P(), out_specs=P(),
                            axis_names=frozenset({"mp"}),
                            check_vma=False))(x)
    want = x * scales[:, None, None]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)


def test_moe_alltoall_dispatch_matches_einsum(hybrid_mesh):
    """dispatch='alltoall' (explicit global_scatter/global_gather under
    shard_map over mp) must agree with the dense GSPMD einsum path when
    capacity is ample (eval mode => deterministic gating, no drops)."""
    from paddle_tpu.distributed.moe import MoELayer, TopKGate
    pt.seed(6)
    # eval_capacity_factor large enough that neither the global (einsum) nor
    # the per-rank (alltoall) capacity drops any token — otherwise the two
    # paths legitimately differ on which overflow tokens they drop.
    moe_e = MoELayer(d_model=16, num_experts=8, d_hidden=32,
                     gate=TopKGate(16, 8, top_k=2, eval_capacity_factor=16.0),
                     ep_axis="mp", dispatch="einsum")
    moe_a = MoELayer(d_model=16, num_experts=8, d_hidden=32,
                     gate=TopKGate(16, 8, top_k=2, eval_capacity_factor=16.0),
                     ep_axis="mp", dispatch="alltoall")
    moe_a.set_state_dict(moe_e.state_dict())
    moe_e.eval(); moe_a.eval()
    x = jnp.asarray(RNG.standard_normal((4, 8, 16)), jnp.float32)

    y_e = moe_e(x)
    # partial-manual shard_map needs an enclosing jit; read aux as a jit
    # OUTPUT (a bare buffer read after raw jit would see a leaked tracer —
    # TrainStep/functional_call handle this swap in real training code)
    y_a, aux_a = jax.jit(lambda v: (moe_a(v), moe_a.aux_loss))(x)
    np.testing.assert_allclose(np.asarray(y_e), np.asarray(y_a),
                               rtol=2e-4, atol=2e-4)
    assert np.isfinite(float(aux_a)) and float(aux_a) > 0


def test_moe_alltoall_trains_and_falls_back(sep_mesh):
    """Training step through the alltoall path converges; on a mesh without
    the ep axis >1 the layer falls back to the einsum path (sep_mesh has
    mp=1)."""
    from paddle_tpu.distributed.moe import MoELayer
    pt.seed(7)
    moe = MoELayer(d_model=16, num_experts=4, d_hidden=32, gate="switch",
                   ep_axis="mp", dispatch="alltoall")  # mp=1 -> fallback
    x = jnp.asarray(RNG.standard_normal((2, 8, 16)), jnp.float32)
    t = jnp.asarray(RNG.standard_normal((2, 8, 16)), jnp.float32)
    opt = pt.optimizer.Adam(learning_rate=1e-2, parameters=moe)
    step = pt.jit.TrainStep(moe, opt, lambda o, tt: F.mse_loss(o, tt))
    losses = [float(step(x, t)) for _ in range(8)]
    assert losses[-1] < losses[0]


def test_qwen2_moe_alltoall_trains(hybrid_mesh):
    """Flagship routed through explicit EP dispatch on an expert-sharded
    mesh: one train step, finite loss, grads flow to expert weights."""
    from paddle_tpu.models.qwen2_moe import Qwen2MoeConfig, Qwen2MoeForCausalLM
    pt.seed(8)
    cfg = Qwen2MoeConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                         moe_intermediate_size=16,
                         shared_expert_intermediate_size=64,
                         num_hidden_layers=2, num_attention_heads=4,
                         num_key_value_heads=2, num_experts=8,
                         num_experts_per_tok=2, max_position_embeddings=64,
                         mp_axis=None, fsdp_axis=None,
                         ep_axis="mp", ep_dispatch="alltoall")
    model = Qwen2MoeForCausalLM(cfg)
    opt = pt.optimizer.AdamW(learning_rate=1e-3, parameters=model)
    step = pt.jit.TrainStep(model, opt,
                            lambda logits, labels: model.loss(logits, labels))
    ids = np.asarray(RNG.integers(0, cfg.vocab_size, (4, 16)))
    l0 = float(step(ids, ids))
    l1 = float(step(ids, ids))
    assert np.isfinite(l0) and np.isfinite(l1)


def test_current_mesh_inside_jit_under_set_mesh():
    """Regression: current_mesh() from jitted code under jax.sharding.set_mesh
    (no use_mesh wrapper) must not crash at trace time — get_mesh() raises
    ValueError while tracing, so the abstract mesh is the fallback. Covers
    no_mesh_active() (gates fused norms / flash) and MoE sorted dispatch."""
    from paddle_tpu._mesh_gate import no_mesh_active
    mesh = mesh_lib.make_mesh({"dp": 2, "mp": 4})
    seen = {}

    @jax.jit
    def fwd(x):
        m = mesh_lib.current_mesh()
        seen["shape"] = dict(m.shape)
        seen["quiet"] = no_mesh_active()
        return x * 2

    from paddle_tpu.core.compat import set_mesh
    with set_mesh(mesh):
        out = fwd(jnp.ones((4, 4)))
    assert seen["shape"] == {"dp": 2, "mp": 4}
    assert seen["quiet"] is False
    np.testing.assert_allclose(np.asarray(out), 2.0)


def test_moe_sorted_dispatch_jitted_under_set_mesh():
    """The grouped MoE forward (default for Qwen2MoeConfig) calls
    current_mesh() from jitted code; under set_mesh it must trace and fall
    back to the dense path (multi-device mesh active)."""
    from paddle_tpu.distributed.moe import MoELayer
    pt.seed(3)
    layer = MoELayer(16, num_experts=4, d_hidden=32, dispatch="grouped")
    x = jnp.asarray(RNG.standard_normal((8, 16)), jnp.float32)
    mesh = mesh_lib.make_mesh({"dp": 2, "mp": 4})

    from paddle_tpu.core.compat import set_mesh
    fwd = jax.jit(lambda t: layer(t))
    with set_mesh(mesh):
        out = fwd(x)
    assert np.isfinite(np.asarray(out)).all()
