"""Fused grouped-GEMM MoE dispatch (ops/pallas/moe_grouped_gemm.py,
``dispatch="fused"``) vs the capacity-packed grouped path: same routing
decisions by construction (shared ``_top2_parts``), so outputs and
gradients must agree to fp tolerance — forward and backward, tight and
padded capacity, capacity-overflow drops, E not dividing T, bf16 and
fp32, tie-broken routing, and the ep=2 virtual-mesh all-to-all handoff.

Runs the real kernels in Pallas interpret mode on the CPU mesh; every
test asserts the fused path actually ENGAGES (applicability gate), so a
regression can't silently pass by falling back to the grouped path.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.nn.functional as F
from paddle_tpu.core import mesh as mesh_lib
from paddle_tpu.distributed.moe import (MoELayer, TopKGate, _top2_parts,
                                        moe_fused_compute,
                                        moe_grouped_compute)
from paddle_tpu.ops.pallas.moe_grouped_gemm import fused_dispatch_applicable

RNG = np.random.default_rng(20)


def _route(T, E, capfac, seed=0):
    """Deterministic top-2 routing (XLA chain, no second-expert rng) in
    the sparse form both compute paths consume."""
    r = np.random.default_rng(seed)
    logits = jnp.asarray(r.standard_normal((T, E)) * 1.5, jnp.float32)
    cap = max(4, int(capfac * T * 2 / E))
    g1, g2, w1, w2, k1, k2, p1, p2, aux = _top2_parts(
        logits, cap, second_policy="all")
    return (jnp.stack([g1, g2], 1), jnp.stack([w1, w2], 1),
            jnp.stack([p1, p2], 1), jnp.stack([k1, k2], 1), cap)


def _weights(E, D, H, dtype, seed=0):
    r = np.random.default_rng(seed)
    mk = lambda *s: jnp.asarray(r.standard_normal(s) * 0.05, dtype)
    return mk(E, D, H), mk(E, D, H), mk(E, H, D)


def _tols(dtype):
    # fp32: both paths accumulate in fp32 — 1e-4 is the ISSUE's contract,
    # observed ~1e-7. bf16: the packed path rounds its GEMM outputs to
    # bf16 where the kernel keeps fp32 through the epilogue.
    return dict(rtol=1e-4, atol=1e-5) if dtype == jnp.float32 \
        else dict(rtol=3e-2, atol=3e-3)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("capfac", [1.0, 1.25])
@pytest.mark.parametrize("T", [256, 250])  # 250: E does not divide T
def test_fused_matches_grouped_fwd(dtype, capfac, T):
    D, H, E = 128, 96, 4
    idx, w, pos, keep, cap = _route(T, E, capfac)
    w_in, w_gate, w_out = _weights(E, D, H, dtype)
    assert fused_dispatch_applicable(T, D, H, E, cap, dtype, F.silu, True)
    x = jnp.asarray(RNG.standard_normal((T, D)), dtype)
    got = moe_fused_compute(x, idx, w, pos, keep, cap, w_in, w_gate, w_out,
                            F.silu)
    want = moe_grouped_compute(x, idx, w, pos, keep, cap, w_in, w_gate,
                               w_out, F.silu)
    assert got.dtype == want.dtype == dtype
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32), **_tols(dtype))


@pytest.mark.parametrize("capfac", [1.0, 1.25])
def test_fused_matches_grouped_grads(capfac):
    """dX through the scatter-accumulating index map, d(combine weights),
    and dW through the grouped grid — all against the packed path."""
    T, D, H, E = 256, 128, 96, 4
    dtype = jnp.float32
    idx, w, pos, keep, cap = _route(T, E, capfac, seed=1)
    w_in, w_gate, w_out = _weights(E, D, H, dtype, seed=1)
    assert fused_dispatch_applicable(T, D, H, E, cap, dtype, F.silu, True)
    x = jnp.asarray(RNG.standard_normal((T, D)), dtype)
    ct = jnp.asarray(RNG.standard_normal((T, D)), dtype)

    def loss(fn, x, w, w_in, w_gate, w_out):
        return jnp.sum(fn(x, idx, w, pos, keep, cap, w_in, w_gate, w_out,
                          F.silu) * ct)

    gf = jax.grad(lambda *a: loss(moe_fused_compute, *a),
                  argnums=(0, 1, 2, 3, 4))(x, w, w_in, w_gate, w_out)
    gg = jax.grad(lambda *a: loss(moe_grouped_compute, *a),
                  argnums=(0, 1, 2, 3, 4))(x, w, w_in, w_gate, w_out)
    for name, a, b in zip(("dx", "dw_combine", "dw_in", "dw_gate", "dw_out"),
                          gf, gg):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5, err_msg=name)


def test_fused_grads_bf16():
    T, D, H, E = 256, 128, 64, 4
    idx, w, pos, keep, cap = _route(T, E, 1.25, seed=2)
    w_in, w_gate, w_out = _weights(E, D, H, jnp.bfloat16, seed=2)
    assert fused_dispatch_applicable(T, D, H, E, cap, jnp.bfloat16, F.silu,
                                     True)
    x = jnp.asarray(RNG.standard_normal((T, D)), jnp.bfloat16)

    def loss(fn, x, w_in):
        return jnp.sum((fn(x, idx, w, pos, keep, cap, w_in, w_gate, w_out,
                           F.silu).astype(jnp.float32)) ** 2)

    gf = jax.grad(lambda *a: loss(moe_fused_compute, *a),
                  argnums=(0, 1))(x, w_in)
    gg = jax.grad(lambda *a: loss(moe_grouped_compute, *a),
                  argnums=(0, 1))(x, w_in)
    for a, b in zip(gf, gg):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=5e-2, atol=5e-2)


def test_overflow_drops_match():
    """Tight capacity: dropped copies contribute exactly zero on both
    paths (the fused kernel's sentinel trash-row + zero gate weight must
    reproduce the packed path's drop semantics bit-for-bit in routing)."""
    T, D, H, E = 256, 128, 64, 4
    idx, w, pos, keep, cap = _route(T, E, 0.3, seed=3)
    assert int(jnp.sum(~keep)) > 0  # overflow actually happened
    w_in, w_gate, w_out = _weights(E, D, H, jnp.float32, seed=3)
    assert fused_dispatch_applicable(T, D, H, E, cap, jnp.float32, F.silu,
                                     True)
    x = jnp.asarray(RNG.standard_normal((T, D)), jnp.float32)
    got = moe_fused_compute(x, idx, w, pos, keep, cap, w_in, w_gate, w_out,
                            F.silu)
    want = moe_grouped_compute(x, idx, w, pos, keep, cap, w_in, w_gate,
                               w_out, F.silu)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)
    # a fully-dropped token must come out exactly zero from both
    dead = np.asarray(jnp.sum(keep, 1) == 0)
    if dead.any():
        assert np.abs(np.asarray(got)[dead]).max() == 0.0


def test_tie_cases_match():
    """Duplicate tokens and flat logits produce argmax ties and FCFS
    position contention; both paths must resolve them identically (shared
    routing) and dispatch identically (this test)."""
    T, D, H, E = 256, 128, 64, 4
    r = np.random.default_rng(5)
    base = r.standard_normal((T // 4, E))
    logits = jnp.asarray(np.concatenate([base] * 4), jnp.float32)
    logits = logits.at[:8].set(0.0)  # fully tied rows
    cap = max(4, int(1.0 * T * 2 / E))
    g1, g2, w1, w2, k1, k2, p1, p2, _ = _top2_parts(logits, cap,
                                                    second_policy="all")
    idx = jnp.stack([g1, g2], 1)
    w = jnp.stack([w1, w2], 1)
    pos = jnp.stack([p1, p2], 1)
    keep = jnp.stack([k1, k2], 1)
    w_in, w_gate, w_out = _weights(E, D, H, jnp.float32, seed=5)
    x = jnp.asarray(np.concatenate([r.standard_normal((T // 4, D))] * 4),
                    jnp.float32)
    got = moe_fused_compute(x, idx, w, pos, keep, cap, w_in, w_gate, w_out,
                            F.silu)
    want = moe_grouped_compute(x, idx, w, pos, keep, cap, w_in, w_gate,
                               w_out, F.silu)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


def test_ungated_relu_fused():
    """Kernel branch coverage: gated=False + relu activation."""
    T, D, H, E = 256, 128, 64, 4
    idx, w, pos, keep, cap = _route(T, E, 1.25, seed=6)
    w_in, _, w_out = _weights(E, D, H, jnp.float32, seed=6)
    assert fused_dispatch_applicable(T, D, H, E, cap, jnp.float32, F.relu,
                                     False)
    x = jnp.asarray(RNG.standard_normal((T, D)), jnp.float32)
    args = (x, idx, w, pos, keep, cap, w_in, None, w_out, F.relu)
    got = moe_fused_compute(*args)
    want = moe_grouped_compute(*args)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)
    gf = jax.grad(lambda x: jnp.sum(moe_fused_compute(
        x, *args[1:]) ** 2))(x)
    gg = jax.grad(lambda x: jnp.sum(moe_grouped_compute(
        x, *args[1:]) ** 2))(x)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gg),
                               rtol=1e-4, atol=1e-5)


def test_fused_layer_falls_back_off_kernel_shapes():
    """dispatch='fused' with D % 128 != 0 must take the grouped fallback
    and still match dispatch='grouped' exactly."""
    assert not fused_dispatch_applicable(64, 96, 32, 4, 32, jnp.float32,
                                         F.silu, True)
    outs = []
    for disp in ("fused", "grouped"):
        pt.seed(9)
        layer = MoELayer(96, num_experts=4, d_hidden=32, dispatch=disp)
        layer.eval()
        x = jnp.asarray(np.random.default_rng(9).standard_normal((64, 96)),
                        jnp.float32)
        outs.append(np.asarray(layer(x)))
    np.testing.assert_array_equal(outs[0], outs[1])


@pytest.fixture()
def ep2_mesh():
    mesh = mesh_lib.make_mesh({"dp": 2, "pp": 1, "fsdp": 1, "sep": 1,
                               "mp": 2})
    with mesh_lib.use_mesh(mesh):
        yield mesh


def test_ep2_fused_loss_matches_einsum(ep2_mesh):
    """dispatch='fused' under an ep=2 mesh hands off to the all-to-all
    path whose inbox feeds the fused kernel (identity arrangement); the
    step loss must match the dense GSPMD einsum path. Ample capacity so
    neither path drops (per-rank vs global overflow picks differ)."""
    from paddle_tpu.ops.pallas.moe_grouped_gemm import padded_capacity

    def build(disp):
        pt.seed(12)
        return MoELayer(d_model=128, num_experts=8, d_hidden=64,
                        gate=TopKGate(128, 8, top_k=2,
                                      eval_capacity_factor=16.0),
                        ep_axis="mp", dispatch=disp)

    moe_e = build("einsum")
    moe_a = build("alltoall")
    moe_f = build("fused")
    moe_a.set_state_dict(moe_e.state_dict())
    moe_f.set_state_dict(moe_e.state_dict())
    moe_e.eval(); moe_a.eval(); moe_f.eval()
    x = jnp.asarray(RNG.standard_normal((4, 8, 128)), jnp.float32)
    tgt = jnp.asarray(RNG.standard_normal((4, 8, 128)), jnp.float32)

    # the inbox the all-to-all hands the kernel must fit the kernel
    cap = moe_f.gate.capacity(x.shape[0] * x.shape[1] // 2)
    El, S = 8 // 2, 2 * cap
    assert fused_dispatch_applicable(El * S, 128, 64, El, S, jnp.float32,
                                     F.silu, True)
    assert padded_capacity(S) >= S

    def step(moe):
        def loss_fn(v):
            out = moe(v)
            return F.mse_loss(out, tgt) + moe.aux_loss, out
        (l, out), dx = jax.jit(
            lambda v: jax.value_and_grad(loss_fn, has_aux=True)(v))(x)
        return float(l), np.asarray(out), np.asarray(dx)

    le, oe, ge = step(moe_e)
    la, oa, ga = step(moe_a)
    lf, of, gf = step(moe_f)
    # vs einsum: outputs/grads agree (the aux term is computed per-rank
    # and pmean'd on the all-to-all paths vs globally on the dense path —
    # a documented, legitimate difference, so losses are compared only
    # between the two all-to-all variants)
    np.testing.assert_allclose(of, oe, rtol=2e-4, atol=2e-4)
    # vs alltoall (same routing, same aux semantics): the fused-inbox
    # handoff must be a drop-in for ExpertFFN.apply, loss and grad alike
    np.testing.assert_allclose(lf, la, rtol=1e-5)
    np.testing.assert_allclose(of, oa, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(gf, ga, rtol=1e-3, atol=1e-5)


def test_qwen2_moe_fused_dispatch_config():
    """The model config accepts ep_dispatch='fused' and its loss matches
    the grouped default (tiny config: kernel falls back — the point is
    the wiring, the kernel parity is covered above)."""
    from paddle_tpu.models.qwen2_moe import Qwen2MoeForCausalLM, \
        qwen2_moe_tiny

    losses = {}
    for disp in ("fused", "grouped"):
        cfg = qwen2_moe_tiny(mp_axis=None, fsdp_axis=None, ep_axis=None,
                             ep_dispatch=disp)
        pt.seed(0)
        m = Qwen2MoeForCausalLM(cfg)
        m.eval()
        ids = jnp.asarray(np.random.default_rng(0).integers(
            0, cfg.vocab_size, (2, 16)), jnp.int32)
        losses[disp] = float(m.loss(m(ids), ids))
    np.testing.assert_allclose(losses["fused"], losses["grouped"],
                               rtol=1e-5)
