"""Multi-process async checkpoint + elastic resume e2e (VERDICT r2 item 9;
parity: distributed/checkpoint/save_state_dict.py async path +
fleet/elastic/manager.py resume flow)."""

import os
import subprocess
import sys
import textwrap
import time

import jax
import jax.numpy as jnp
import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RNG = np.random.default_rng(5)


def test_async_save_two_rank_merge(tmp_path, monkeypatch):
    """Simulate two ranks in one process: each writes its piece async;
    the coordinator's writer thread must poll for the other rank's done
    marker and merge the metadata without any device barrier."""
    from paddle_tpu.distributed.checkpoint import (load_state_dict,
                                                   save_state_dict)
    from paddle_tpu.distributed.checkpoint import save_load as sl
    path = str(tmp_path / "ck")
    w = jnp.asarray(RNG.standard_normal((8, 4)), jnp.float32)
    b = jnp.asarray(RNG.standard_normal((4,)), jnp.float32)

    monkeypatch.setattr(sl.jax, "process_count", lambda: 2)
    # coordinator (rank 0) goes FIRST: its merge thread must wait for
    # rank 1's marker, proving the polling path
    monkeypatch.setattr(sl.jax, "process_index", lambda: 0)
    h0 = save_state_dict({"w": w}, path, async_save=True, async_timeout=30)
    time.sleep(0.2)
    assert not os.path.exists(os.path.join(path, "metadata.pkl"))
    # both "ranks" are this one process, so undo the per-process
    # bookkeeping rank 0 made (save-seq bump + in-flight handle) — in a
    # real job each process keeps its own
    sl._SAVE_SEQ[path] -= 1
    sl._INFLIGHT.pop(path)
    monkeypatch.setattr(sl.jax, "process_index", lambda: 1)
    h1 = save_state_dict({"b": b}, path, async_save=True, async_timeout=30)
    h1.result(timeout=30)
    h0.result(timeout=30)
    assert h0.done() and h1.done()
    assert os.path.exists(os.path.join(path, "metadata.pkl"))
    # markers and per-rank meta pieces are cleaned up by the merge
    assert not any(".done" in f or f.endswith(".meta.pkl")
                   for f in os.listdir(path))
    monkeypatch.setattr(sl.jax, "process_index", lambda: 0)
    monkeypatch.setattr(sl.jax, "process_count", lambda: 1)
    out = load_state_dict({"w": jnp.zeros((8, 4)), "b": jnp.zeros((4,))},
                          path)
    np.testing.assert_allclose(np.asarray(out["w"]), np.asarray(w))
    np.testing.assert_allclose(np.asarray(out["b"]), np.asarray(b))


def test_async_save_timeout_surfaces(tmp_path, monkeypatch):
    from paddle_tpu.distributed.checkpoint import save_state_dict
    from paddle_tpu.distributed.checkpoint import save_load as sl
    import pytest
    monkeypatch.setattr(sl.jax, "process_count", lambda: 2)
    monkeypatch.setattr(sl.jax, "process_index", lambda: 0)
    h = save_state_dict({"w": jnp.ones((2,))}, str(tmp_path / "ck"),
                        async_save=True, async_timeout=0.3)
    with pytest.raises(TimeoutError):  # rank 1 never shows up
        h.result(timeout=30)


def test_elastic_kill_relaunch_resume_loss_continuity(tmp_path):
    """The full VERDICT done-bar: a worker hard-crashes mid-train after an
    async checkpoint lands; the launcher gang-restarts; the relaunched
    worker resumes from the checkpoint and its first loss continues the
    pre-crash trajectory instead of restarting from scratch."""
    script = tmp_path / "train.py"
    ckpt_dir = tmp_path / "ckpts"
    ckpt_dir.mkdir()
    script.write_text(textwrap.dedent(f"""
        import os, sys
        sys.path.insert(0, {REPO!r})
        import numpy as np
        import jax; jax.config.update("jax_platforms", "cpu")
        import paddle_tpu as pt
        from paddle_tpu import nn
        from paddle_tpu.distributed.fleet.elastic import ElasticManager
        from paddle_tpu.distributed.checkpoint import (load_state_dict,
                                                       save_state_dict)

        epoch = int(os.environ["PADDLE_RESTART_EPOCH"])
        ckpt_dir = {str(ckpt_dir)!r}
        pt.seed(0)
        rng = np.random.default_rng(0)
        X = rng.standard_normal((32, 16)).astype("float32")
        Y = (X @ rng.standard_normal((16, 1)).astype("float32")).ravel()
        model = nn.Sequential(nn.Linear(16, 32), nn.Tanh(), nn.Linear(32, 1))
        opt = pt.optimizer.SGD(learning_rate=0.05, parameters=model)
        step = pt.jit.TrainStep(model, opt,
                                lambda out, y: ((out.ravel() - y) ** 2).mean(),
                                n_inputs=1)
        em = ElasticManager(checkpoint_dir=ckpt_dir)
        start = 0
        latest = em.latest_checkpoint()
        if latest:
            state = dict(model.state_dict())
            model.set_state_dict(load_state_dict(state, latest))
            start = int(latest.rsplit("_", 1)[1]) + 1
        for i in range(start, 8):
            loss = float(step(X, Y))
            with open(os.path.join(ckpt_dir, f"loss_e{{epoch}}.txt"),
                      "a") as f:
                f.write(f"{{i}} {{loss}}\\n")
            h = save_state_dict(dict(model.state_dict()),
                                os.path.join(ckpt_dir, f"step_{{i}}"),
                                async_save=True)
            h.result(timeout=60)
            if epoch == 0 and i == 3:
                os._exit(7)  # hard crash: no cleanup, no atexit
    """))
    code = textwrap.dedent(f"""
        import sys; sys.path.insert(0, {REPO!r})
        from paddle_tpu.distributed.launch.main import launch
        sys.exit(launch(["--nproc_per_node", "1", "--max_restarts", "2",
                         {str(script)!r}]))
    """)
    proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, timeout=300)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    e0 = [(int(a), float(b)) for a, b in
          (ln.split() for ln in
           (ckpt_dir / "loss_e0.txt").read_text().splitlines())]
    e1 = [(int(a), float(b)) for a, b in
          (ln.split() for ln in
           (ckpt_dir / "loss_e1.txt").read_text().splitlines())]
    assert [i for i, _ in e0] == [0, 1, 2, 3]
    assert [i for i, _ in e1] == [4, 5, 6, 7]  # resumed, not restarted
    fresh0, crash_last = e0[0][1], e0[-1][1]
    resume_first, final = e1[0][1], e1[-1][1]
    # continuity: the resumed loss carries on from the crash point, far
    # below a fresh start, and keeps improving
    assert resume_first < 0.5 * fresh0, (fresh0, resume_first)
    assert resume_first < crash_last * 1.5 + 1e-3
    assert final < resume_first
