"""RNN family tests: cell formulas vs numpy, stacked/bidirectional layers vs a
hand-rolled step loop, sequence-length masking, grads, and an e2e LSTM+CTC
step (pairing the new encoder with the already-shipped CTCLoss).

Mirrors the reference test strategy for test/rnn/test_rnn_nets.py (numpy cell
oracles + layer-vs-naive parity)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.nn.layer.rnn import concat_states, split_states


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def _np_params(cell):
    return (np.asarray(cell.weight_ih), np.asarray(cell.weight_hh),
            np.asarray(cell.bias_ih), np.asarray(cell.bias_hh))


def np_simple_rnn_step(cell, x, h, act=np.tanh):
    wi, wh, bi, bh = _np_params(cell)
    return act(x @ wi.T + bi + h @ wh.T + bh)


def np_lstm_step(cell, x, h, c):
    wi, wh, bi, bh = _np_params(cell)
    gates = x @ wi.T + bi + h @ wh.T + bh
    i, f, g, o = np.split(gates, 4, axis=-1)
    c_new = _sigmoid(f) * c + _sigmoid(i) * np.tanh(g)
    h_new = _sigmoid(o) * np.tanh(c_new)
    return h_new, c_new


def np_gru_step(cell, x, h):
    wi, wh, bi, bh = _np_params(cell)
    xg = x @ wi.T + bi
    hg = h @ wh.T + bh
    x_r, x_z, x_c = np.split(xg, 3, axis=-1)
    h_r, h_z, h_c = np.split(hg, 3, axis=-1)
    r = _sigmoid(x_r + h_r)
    z = _sigmoid(x_z + h_z)
    c = np.tanh(x_c + r * h_c)
    return z * h + (1 - z) * c


# ---------------------------------------------------------------------------
# cell formula oracles
# ---------------------------------------------------------------------------

def test_simple_rnn_cell_formula():
    paddle.seed(0)
    cell = nn.SimpleRNNCell(16, 32)
    x = np.random.RandomState(1).randn(4, 16).astype("float32")
    h = np.random.RandomState(2).randn(4, 32).astype("float32")
    y, h_new = cell(jnp.asarray(x), jnp.asarray(h))
    ref = np_simple_rnn_step(cell, x, h)
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-5, atol=1e-5)
    assert y is h_new  # output IS the new state


def test_simple_rnn_cell_relu_and_validation():
    cell = nn.SimpleRNNCell(8, 8, activation="relu")
    x = np.random.RandomState(0).randn(2, 8).astype("float32")
    y, _ = cell(jnp.asarray(x))  # default zero state
    ref = np_simple_rnn_step(cell, x, np.zeros((2, 8), "float32"),
                             act=lambda v: np.maximum(v, 0))
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-5, atol=1e-5)
    with pytest.raises(ValueError):
        nn.SimpleRNNCell(8, 8, activation="sigmoid")


def test_lstm_cell_formula():
    paddle.seed(0)
    cell = nn.LSTMCell(16, 32)
    rs = np.random.RandomState(3)
    x, h, c = (rs.randn(4, 16).astype("float32"),
               rs.randn(4, 32).astype("float32"),
               rs.randn(4, 32).astype("float32"))
    y, (h_new, c_new) = cell(jnp.asarray(x), (jnp.asarray(h), jnp.asarray(c)))
    rh, rc = np_lstm_step(cell, x, h, c)
    np.testing.assert_allclose(np.asarray(h_new), rh, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(c_new), rc, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(y), rh, rtol=1e-5, atol=1e-5)


def test_gru_cell_formula():
    paddle.seed(0)
    cell = nn.GRUCell(16, 32)
    rs = np.random.RandomState(4)
    x, h = rs.randn(4, 16).astype("float32"), rs.randn(4, 32).astype("float32")
    y, h_new = cell(jnp.asarray(x), jnp.asarray(h))
    ref = np_gru_step(cell, x, h)
    np.testing.assert_allclose(np.asarray(h_new), ref, rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# sequence-level recurrence vs hand-rolled loop
# ---------------------------------------------------------------------------

def _naive_rnn(cell, step, x, h0, reverse=False, lengths=None):
    """Hand-rolled per-timestep numpy loop with mask-freeze semantics."""
    B, T = x.shape[0], x.shape[1]
    h = h0
    outs = np.zeros((B, T, cell.hidden_size), "float32")
    ts = range(T - 1, -1, -1) if reverse else range(T)
    for b in range(B):
        L = T if lengths is None else int(lengths[b])
        hb = tuple(s[b:b + 1] for s in h) if isinstance(h, tuple) else h[b:b + 1]
        steps = (range(L - 1, -1, -1) if reverse else range(L))
        for t in steps:
            res = step(cell, x[b:b + 1, t], *(hb if isinstance(hb, tuple) else (hb,)))
            hb = res if isinstance(res, tuple) else res
            outs[b, t] = (hb[0] if isinstance(hb, tuple) else hb)[0]
        if isinstance(h, tuple):
            for comp, val in zip(h, hb):
                comp[b] = val[0]
        else:
            h[b] = hb[0]
    return outs, h


def test_rnn_layer_matches_naive_loop():
    paddle.seed(1)
    cell = nn.SimpleRNNCell(8, 12)
    layer = nn.RNN(cell)
    x = np.random.RandomState(5).randn(3, 7, 8).astype("float32")
    h0 = np.random.RandomState(6).randn(3, 12).astype("float32")
    out, hT = layer(jnp.asarray(x), jnp.asarray(h0))
    ref_out, ref_h = _naive_rnn(cell, np_simple_rnn_step, x, h0.copy())
    np.testing.assert_allclose(np.asarray(out), ref_out, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(hT), ref_h, rtol=1e-4, atol=1e-4)


def test_rnn_layer_sequence_length_masks_and_freezes():
    paddle.seed(1)
    cell = nn.GRUCell(8, 12)
    layer = nn.RNN(cell)
    x = np.random.RandomState(7).randn(3, 7, 8).astype("float32")
    lengths = np.array([7, 4, 1], dtype=np.int32)
    out, hT = layer(jnp.asarray(x), None, sequence_length=jnp.asarray(lengths))
    ref_out, ref_h = _naive_rnn(cell, lambda c, xi, hi: np_gru_step(c, xi, hi),
                                x, np.zeros((3, 12), "float32"), lengths=lengths)
    np.testing.assert_allclose(np.asarray(out), ref_out, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(hT), ref_h, rtol=1e-4, atol=1e-4)
    # padded positions are exactly zero
    assert np.all(np.asarray(out)[1, 4:] == 0)
    assert np.all(np.asarray(out)[2, 1:] == 0)


def test_reverse_rnn_reads_from_last_valid_step():
    paddle.seed(2)
    cell = nn.SimpleRNNCell(8, 12)
    layer = nn.RNN(cell, is_reverse=True)
    x = np.random.RandomState(8).randn(2, 5, 8).astype("float32")
    lengths = np.array([5, 3], dtype=np.int32)
    out, hT = layer(jnp.asarray(x), None, sequence_length=jnp.asarray(lengths))
    ref_out, ref_h = _naive_rnn(cell, np_simple_rnn_step, x,
                                np.zeros((2, 12), "float32"), reverse=True,
                                lengths=lengths)
    np.testing.assert_allclose(np.asarray(out), ref_out, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(hT), ref_h, rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# stacked / bidirectional nets
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("direction", ["forward", "bidirectional"])
def test_lstm_shapes_and_final_state_stack(direction):
    paddle.seed(3)
    D = 2 if direction == "bidirectional" else 1
    net = nn.LSTM(16, 32, num_layers=2, direction=direction)
    x = jnp.asarray(np.random.RandomState(9).randn(4, 23, 16).astype("float32"))
    out, (h, c) = net(x)
    assert out.shape == (4, 23, 32 * D)
    assert h.shape == (2 * D, 4, 32)
    assert c.shape == (2 * D, 4, 32)


def test_gru_time_major_matches_batch_major():
    paddle.seed(4)
    net_bm = nn.GRU(8, 16, num_layers=1)
    net_tm = nn.GRU(8, 16, num_layers=1, time_major=True)
    net_tm.set_state_dict(net_bm.state_dict())
    x = np.random.RandomState(10).randn(3, 6, 8).astype("float32")
    out_bm, h_bm = net_bm(jnp.asarray(x))
    out_tm, h_tm = net_tm(jnp.asarray(x.transpose(1, 0, 2)))
    np.testing.assert_allclose(np.asarray(out_bm),
                               np.asarray(out_tm).transpose(1, 0, 2),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(h_bm), np.asarray(h_tm),
                               rtol=1e-5, atol=1e-5)


def test_stacked_lstm_matches_two_manual_layers():
    paddle.seed(5)
    net = nn.LSTM(8, 16, num_layers=2)
    net.eval()  # dropout=0 anyway; be explicit
    layers = list(net)
    x = jnp.asarray(np.random.RandomState(11).randn(2, 5, 8).astype("float32"))
    out1, st1 = layers[0](x, None, None)
    out2, st2 = layers[1](out1, None, None)
    out, (h, c) = net(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out2), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(h[0]), np.asarray(st1[0]), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(h[1]), np.asarray(st2[0]), rtol=1e-6)


def test_birnn_concat_of_forward_and_reverse():
    paddle.seed(6)
    cfw, cbw = nn.LSTMCell(8, 12), nn.LSTMCell(8, 12)
    bi = nn.BiRNN(cfw, cbw)
    x = jnp.asarray(np.random.RandomState(12).randn(2, 5, 8).astype("float32"))
    out, (st_fw, st_bw) = bi(x)
    ofw, sfw = nn.RNN(cfw)(x)
    obw, sbw = nn.RNN(cbw, is_reverse=True)(x)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(jnp.concatenate([ofw, obw], -1)),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(st_fw[0]), np.asarray(sfw[0]), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(st_bw[1]), np.asarray(sbw[1]), rtol=1e-6)


def test_split_concat_states_roundtrip():
    rs = np.random.RandomState(13)
    h = jnp.asarray(rs.randn(4, 3, 8).astype("float32"))
    c = jnp.asarray(rs.randn(4, 3, 8).astype("float32"))
    per_layer = split_states((h, c), bidirectional=True, state_components=2)
    assert len(per_layer) == 2          # 2 layers x 2 directions
    assert len(per_layer[0]) == 2       # (fw, bw)
    assert len(per_layer[0][0]) == 2    # (h, c)
    h2, c2 = concat_states(per_layer, bidirectional=True, state_components=2)
    np.testing.assert_array_equal(np.asarray(h), np.asarray(h2))
    np.testing.assert_array_equal(np.asarray(c), np.asarray(c2))
    # single-component path
    per_layer = split_states(h, bidirectional=False, state_components=1)
    h3 = concat_states(per_layer, bidirectional=False, state_components=1)
    np.testing.assert_array_equal(np.asarray(h), np.asarray(h3))


# ---------------------------------------------------------------------------
# autodiff + jit + e2e
# ---------------------------------------------------------------------------

def test_lstm_grads_flow_and_jit():
    paddle.seed(7)
    net = nn.LSTM(8, 16, num_layers=2, direction="bidirectional")
    x = jnp.asarray(np.random.RandomState(14).randn(2, 6, 8).astype("float32"))
    params = {k: jnp.asarray(v) for k, v in paddle.nn.to_static_state(net).items()}

    @jax.jit
    def loss_fn(params, x):
        out, _ = paddle.nn.functional_call(net, params, x)[0]
        return jnp.mean(out ** 2)

    grads = jax.grad(loss_fn)(params, x)
    leaves = jax.tree_util.tree_leaves(grads)
    assert leaves and all(bool(jnp.any(g != 0)) for g in leaves)


def test_lstm_ctc_e2e_loss_decreases():
    """Speech-style e2e: BiLSTM encoder + CTC loss, a few SGD steps."""
    paddle.seed(8)

    class Enc(nn.Layer):
        def __init__(self):
            super().__init__()
            self.lstm = nn.LSTM(6, 24, direction="bidirectional")
            self.proj = nn.Linear(48, 5)

        def forward(self, x):
            out, _ = self.lstm(x)
            return self.proj(out)

    net = Enc()
    rs = np.random.RandomState(15)
    x = jnp.asarray(rs.randn(2, 12, 6).astype("float32"))
    labels = jnp.asarray(rs.randint(1, 5, (2, 4)).astype("int32"))
    in_len = jnp.full((2,), 12, "int32")
    lab_len = jnp.full((2,), 4, "int32")
    params = {k: jnp.asarray(v) for k, v in paddle.nn.to_static_state(net).items()}

    def loss_fn(params):
        logits, _ = paddle.nn.functional_call(net, params, x)
        logp = jax.nn.log_softmax(logits.transpose(1, 0, 2), -1)  # [T,B,C]
        return jnp.mean(paddle.nn.functional.ctc_loss(
            logp, labels, in_len, lab_len, blank=0))

    vg = jax.jit(jax.value_and_grad(loss_fn))
    l0, g = vg(params)
    for _ in range(8):
        l, g = vg(params)
        params = jax.tree_util.tree_map(lambda p, gr: p - 0.05 * gr, params, g)
    l_end, _ = vg(params)
    assert float(l_end) < float(l0)


def test_functional_rnn_entry_points_exported():
    from paddle_tpu.nn import functional as F
    assert callable(F.rnn) and callable(F.birnn)
