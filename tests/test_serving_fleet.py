"""paddle_tpu.serving.fleet — replicated serving with failover replay.

The fleet contracts (SERVING.md "Engine fleet & failover"):

1. EXACTLY-ONCE — kill/stall/drain a replica at ANY point of a stream
   and the client-visible token sequence is bitwise identical to an
   unfailed run: replay regenerates, the router's emitted/produced
   dedup suppresses, nothing duplicates and nothing is lost. The
   property sweep kills at every possible emitted count k.
2. CLASSIFIED OR EXACT — under chaos (kill + stall + poison, one
   replica each) every request either matches single-engine
   ``generate()`` bitwise or ends in a typed/classified outcome; the
   router never hangs (``run_to_completion(max_steps=...)`` is the
   tripwire) and ``decode_program_count() == 1`` on every survivor.
3. HEALTH — transient dispatch failures trip a consecutive-failure
   circuit breaker (OPEN -> deterministic bounded backoff ->
   HALF_OPEN probe -> CLOSED), a full global queue sheds with the
   retryable ``FleetOverloadedError``, and an all-dead fleet sheds its
   queue with classified ``finish_reason="shed"`` instead of spinning.

Router logic is exercised on scripted fake engines (fast, tier-1); the
real-model chaos acceptance runs llama_tiny replicas behind ``slow`` /
``faults`` markers.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.distributed import fault
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.observability import (FlightRecorder, Tracer,
                                      parse_prometheus,
                                      render_fleet_prometheus)
from paddle_tpu.serving import (EngineDrainingError, FleetOverloadedError,
                                FleetRouter, QueueFullError,
                                RequestTooLargeError, SamplingParams,
                                SchedulerStalledError, ServingEngine,
                                ServingError)
from paddle_tpu.serving.fleet import CLOSED, DEAD, HALF_OPEN, OPEN

RNG = np.random.default_rng(11)


@pytest.fixture(scope="module")
def model():
    pt.seed(123)
    m = LlamaForCausalLM(llama_tiny(dtype="float32",
                                    mp_axis=None, fsdp_axis=None))
    m.eval()
    return m


@pytest.fixture
def fault_free(monkeypatch):
    """No FaultPlan leaks out of a chaos test; no rank env leaks in."""
    fault.deactivate()
    monkeypatch.delenv("PADDLE_TRAINER_ID", raising=False)
    monkeypatch.delenv("PROCESS_ID", raising=False)
    monkeypatch.delenv("PADDLE_RESTART_EPOCH", raising=False)
    yield
    fault.deactivate()


def _reference(model, prompt, max_new, **kw):
    out = model.generate(jnp.asarray([prompt]), max_new_tokens=max_new, **kw)
    return np.asarray(out)[0, len(prompt):].tolist()


# ---------------------------------------------------------------------------
# scripted fake engine: the duck-typed surface the router depends on
# ---------------------------------------------------------------------------

class FakeScheduler:
    def __init__(self, max_queue_depth=None):
        self.waiting = []
        self.running = {}
        self.max_queue_depth = max_queue_depth

    @property
    def queue_depth(self):
        return len(self.waiting)

    def has_work(self):
        return bool(self.waiting or self.running)

    def live_requests(self):
        return list(self.waiting) + list(self.running.values())


class FakeReq:
    def __init__(self, rid, prompt, sampling):
        self.rid = rid
        self.prompt = prompt
        self.sampling = sampling
        self.produced = 0


class FakePool:
    """Just enough pool for affinity: a set of known prefixes."""

    def __init__(self, prefixes=()):
        self.cache_enabled = True
        self.fault_path = None
        self._prefixes = [list(p) for p in prefixes]

    def utilization(self):
        return 0.0

    def match_prefix(self, tokens):
        class M:
            cached_tokens = 0
        m = M()
        for p in self._prefixes:
            if list(tokens[:len(p)]) == p:
                m.cached_tokens = max(m.cached_tokens, len(p))
        return m


class FakeEngine:
    """Deterministic scripted engine: request [p0, ...] emits the stream
    p0*100, p0*100+1, ... — same tokens wherever (re)placed, which is
    exactly the determinism the real engine guarantees."""

    def __init__(self, max_slots=4, max_queue_depth=None, prefixes=(),
                 add_fails=0, stall_after=None):
        self.scheduler = FakeScheduler(max_queue_depth)
        self.pool = FakePool(prefixes)
        self._draining = False
        self.last_drain_events = []
        self.max_slots = max_slots
        self.add_fails = add_fails        # QueueFullError for first N adds
        self.stall_after = stall_after    # step() raises after N steps
        self.steps = 0
        self.flight_recorder = None

    def admission_check(self, prompt_len, max_new_tokens):
        if prompt_len + max_new_tokens > 10_000:
            raise RequestTooLargeError("scripted: never fits")

    def add_request(self, prompt, max_new_tokens, sampling=None,
                    eos_token_id=None, rid=None, deadline_s=None,
                    max_queue_wait_s=None):
        if self._draining:
            raise EngineDrainingError("draining")
        if self.add_fails > 0:
            self.add_fails -= 1
            raise QueueFullError("scripted queue full")
        r = FakeReq(rid, list(prompt), sampling)
        r.max_new = max_new_tokens
        if len(self.scheduler.running) < self.max_slots:
            slot = min(set(range(self.max_slots))
                       - set(self.scheduler.running))
            self.scheduler.running[slot] = r
        else:
            self.scheduler.waiting.append(r)
        return rid

    def step(self):
        self.steps += 1
        if self.stall_after is not None and self.steps > self.stall_after:
            raise SchedulerStalledError("scripted stall", {"step": self.steps})
        events = []
        while (self.scheduler.waiting
               and len(self.scheduler.running) < self.max_slots):
            slot = min(set(range(self.max_slots))
                       - set(self.scheduler.running))
            self.scheduler.running[slot] = self.scheduler.waiting.pop(0)
        for slot, r in sorted(self.scheduler.running.items()):
            tok = r.prompt[0] * 100 + r.produced
            r.produced += 1
            fin = r.produced >= r.max_new
            events.append({"rid": r.rid, "token": tok, "finished": fin,
                           "finish_reason": "length" if fin else None})
            if fin:
                del self.scheduler.running[slot]
        return events

    def drain(self, timeout_s=None):
        self._draining = True
        events = []
        for r in self.scheduler.waiting:
            events.append({"rid": r.rid, "token": None, "finished": True,
                           "finish_reason": "preempted"})
        self.scheduler.waiting.clear()
        while self.scheduler.running:
            events.extend(self.step())
        self.last_drain_events = events
        return {}

    def decode_program_count(self):
        return 1


def _expected(prompt, max_new):
    return [prompt[0] * 100 + i for i in range(max_new)]


# ---------------------------------------------------------------------------
# routing: admission, shedding, placement
# ---------------------------------------------------------------------------

class TestFleetRouting:
    def test_round_trip_two_replicas(self, fault_free):
        router = FleetRouter([FakeEngine(), FakeEngine()])
        r1 = router.submit([3], 4)
        r2 = router.submit([5], 4)
        out = router.run_to_completion(max_steps=50)
        assert out[r1] == _expected([3], 4)
        assert out[r2] == _expected([5], 4)
        assert router.request(r1).finish_reason == "length"
        assert not router.has_work()

    def test_global_queue_sheds_with_typed_error(self, fault_free):
        router = FleetRouter([FakeEngine()], max_queue_depth=2)
        router.submit([1], 2)
        router.submit([2], 2)
        with pytest.raises(FleetOverloadedError) as ei:
            router.submit([3], 2)
        assert ei.value.retryable is True
        assert router.fleet_metrics.counters["shed"] == 1
        assert router.metrics.counters["rejected_queue_full"] == 1

    def test_too_large_rejected_fleet_wide(self, fault_free):
        router = FleetRouter([FakeEngine(), FakeEngine()])
        with pytest.raises(RequestTooLargeError) as ei:
            router.submit([1], 20_000)
        assert ei.value.retryable is False
        assert router.metrics.counters["rejected_too_large"] == 1

    def test_draining_fleet_refuses_submission(self, fault_free):
        router = FleetRouter([FakeEngine()])
        router.drain()
        with pytest.raises(EngineDrainingError):
            router.submit([1], 2)

    def test_least_loaded_placement(self, fault_free):
        a, b = FakeEngine(max_slots=8), FakeEngine(max_slots=8)
        router = FleetRouter([a, b])
        for i in range(6):
            router.submit([i + 1], 4)
        router.step()
        # greedy least-loaded alternates 3/3
        assert len(a.scheduler.running) == 3
        assert len(b.scheduler.running) == 3

    def test_prefix_affinity_beats_emptier_replica(self, fault_free):
        cold = FakeEngine(max_slots=8)
        warm = FakeEngine(max_slots=8, prefixes=[[7, 7, 7]])
        router = FleetRouter([cold, warm])
        # load the warm replica so pure least-loaded would pick cold
        router.submit([1], 8)
        router.step()
        assert router.request("fleet-req-0").replica == 0
        rid = router.submit([7, 7, 7, 9], 4)
        router.step()
        assert router.request(rid).replica == 1  # affinity won

    def test_fleet_rid_uniqueness(self, fault_free):
        router = FleetRouter([FakeEngine()])
        router.submit([1], 2, rid="dup")
        with pytest.raises(ValueError, match="duplicate"):
            router.submit([2], 2, rid="dup")


# ---------------------------------------------------------------------------
# failover replay: the exactly-once property sweep
# ---------------------------------------------------------------------------

class TestFailoverReplay:
    def test_kill_at_every_emitted_count_stream_identical(self, fault_free):
        """THE exactly-once property: kill the serving replica at every
        possible client-visible token count k — the final stream must
        be bitwise identical to the unfailed run (no dup, no gap), with
        exactly k replayed-and-suppressed positions."""
        max_new = 8
        expected = _expected([7], max_new)
        for k in range(max_new):
            router = FleetRouter([FakeEngine(), FakeEngine()])
            rid = router.submit([7], max_new)
            guard = 0
            while router.request(rid).emitted < k:
                router.step()
                guard += 1
                assert guard < 50, "sweep runaway"
            # k=0: not dispatched yet — kill the replica placement WOULD
            # pick (dead-before-first-token is still a valid kill point)
            victim = router.request(rid).replica
            router.kill_replica(0 if victim is None else victim)
            out = router.run_to_completion(max_steps=100)
            assert out[rid] == expected, f"k={k}: {out[rid]}"
            assert router.request(rid).finish_reason == "length"
            assert router.fleet_metrics.counters["replayed_tokens"] == k
            assert router.fleet_metrics.counters["failovers"] == \
                (1 if victim is not None else 0)

    def test_chaos_kill_via_fault_site(self, fault_free):
        """fleet.replica_kill with match pinned to one replica index."""
        router = FleetRouter([FakeEngine(), FakeEngine()])
        fault.activate(fault.FaultPlan([
            fault.FaultSpec(site="fleet.replica_kill", action="raise",
                            step=2, match=r"^1$"),
        ]))
        rids = [router.submit([i + 1], 6) for i in range(4)]
        out = router.run_to_completion(max_steps=100)
        for i, rid in enumerate(rids):
            assert out[rid] == _expected([i + 1], 6)
        st = router.stats()
        assert st["replicas_ejected"] == 1
        assert st["replica_health"][1]["state"] == DEAD
        assert st["replica_health"][1]["dead_reason"] == "killed"
        assert st["fleet"]["failovers"] == 2  # replica 1 held 2 of the 4

    def test_stalled_replica_ejected_and_replayed(self, fault_free):
        router = FleetRouter([FakeEngine(stall_after=2), FakeEngine()])
        rids = [router.submit([i + 1], 6) for i in range(4)]
        out = router.run_to_completion(max_steps=100)
        for i, rid in enumerate(rids):
            assert out[rid] == _expected([i + 1], 6)
        st = router.stats()
        assert st["replicas_ejected"] == 1
        assert st["replica_health"][0]["dead_reason"] == "stalled"
        assert st["fleet"]["failovers"] >= 1

    def test_all_replicas_dead_sheds_classified(self, fault_free):
        router = FleetRouter([FakeEngine(), FakeEngine()])
        rid = router.submit([3], 4)
        router.kill_replica(0)
        router.kill_replica(1)
        out = router.run_to_completion(max_steps=10)   # must NOT hang
        assert out[rid] == []
        assert router.request(rid).finish_reason == "shed"
        assert router.fleet_metrics.counters["shed"] == 1
        assert not router.has_work()

    def test_replay_divergence_is_a_hard_error(self, fault_free):
        """A replica that replays DIFFERENT tokens breaks the
        determinism contract — the router must refuse to stream it."""

        class Liar(FakeEngine):
            def step(self):
                events = super().step()
                for ev in events:
                    if ev["token"] is not None:
                        ev["token"] += 1_000_000   # never matches
                return events

        router = FleetRouter([FakeEngine(), Liar()])
        rid = router.submit([5], 6)
        while router.request(rid).emitted < 2:
            router.step()
        assert router.request(rid).replica == 0   # least-loaded tie -> 0
        router.kill_replica(0)                    # replay lands on Liar
        with pytest.raises(RuntimeError, match="replay divergence"):
            router.run_to_completion(max_steps=50)


# ---------------------------------------------------------------------------
# health: circuit breaker, backoff, probing
# ---------------------------------------------------------------------------

class TestCircuitBreaker:
    def test_breaker_opens_then_probes_then_closes(self, fault_free):
        eng = FakeEngine(add_fails=3)      # first 3 dispatches bounce
        router = FleetRouter([eng], breaker_threshold=3,
                             breaker_backoff_steps=2,
                             breaker_backoff_max=4)
        rid = router.submit([4], 3)
        router.step()   # 1st failure
        router.step()   # 2nd failure
        router.step()   # 3rd failure -> OPEN
        st = router.stats()["replica_health"][0]
        assert st["state"] == OPEN
        assert router.fleet_metrics.counters["breaker_opens"] == 1
        assert st["backoff_remaining"] > 0
        out = router.run_to_completion(max_steps=50)
        assert out[rid] == _expected([4], 3)      # placed after the probe
        assert router.stats()["replica_health"][0]["state"] == CLOSED
        assert router.fleet_metrics.counters["probes"] >= 1

    def test_half_open_failure_reopens_with_longer_backoff(self, fault_free):
        eng = FakeEngine(add_fails=4)      # probe itself fails once
        router = FleetRouter([eng], breaker_threshold=3,
                             breaker_backoff_steps=2,
                             breaker_backoff_max=8)
        rid = router.submit([4], 3)
        deadline = 0
        while router.fleet_metrics.counters["breaker_opens"] < 2:
            router.step()
            deadline += 1
            assert deadline < 60
        assert router.stats()["replica_health"][0]["state"] == OPEN
        out = router.run_to_completion(max_steps=80)
        assert out[rid] == _expected([4], 3)

    def test_jitter_is_deterministic(self):
        a = FleetRouter._jitter(1, 2, 8)
        b = FleetRouter._jitter(1, 2, 8)
        assert a == b
        assert 0 <= a < 8

    def test_health_fault_site_counts_as_breaker_failure(self, fault_free):
        router = FleetRouter([FakeEngine(), FakeEngine()],
                             breaker_threshold=1, breaker_backoff_steps=2)
        fault.activate(fault.FaultPlan([
            fault.FaultSpec(site="fleet.health", action="raise",
                            step=0, match=r"^1$"),
        ]))
        rid = router.submit([6], 3)
        router.step()
        st = router.stats()["replica_health"]
        assert st[1]["state"] == OPEN          # injected probe failure
        assert st[0]["state"] == CLOSED
        assert router.request(rid).replica == 0
        out = router.run_to_completion(max_steps=50)
        assert out[rid] == _expected([6], 3)

    def test_open_replica_keeps_stepping_inflight_work(self, fault_free):
        """The breaker gates NEW placements only."""
        eng = FakeEngine(max_slots=8)
        router = FleetRouter([eng], breaker_threshold=1)
        rid = router.submit([2], 5)
        router.step()                           # placed + first token
        eng.add_fails = 5                       # now dispatches bounce
        router.submit([3], 5)                   # will open the breaker
        out = router.run_to_completion(max_steps=300)
        assert out[rid] == _expected([2], 5)    # in-flight work finished


# ---------------------------------------------------------------------------
# drain + preemption guard
# ---------------------------------------------------------------------------

class TestFleetDrain:
    def test_drain_classifies_queued_and_finishes_running(self, fault_free):
        eng = FakeEngine(max_slots=1)
        router = FleetRouter([eng])
        r1 = router.submit([4], 3)
        router.step()                  # r1 running (1 token)
        r2 = router.submit([5], 3)     # stays in the router queue: slot busy
        eng.add_fails = 99
        router.step()
        report = router.drain()
        assert report[r1]["finish_reason"] == "length"
        assert report[r1]["tokens"] == _expected([4], 3)
        assert report[r1]["retriable"] is False
        assert report[r2]["finish_reason"] == "preempted"
        assert report[r2]["retriable"] is True
        assert report[r2]["tokens"] == []

    def test_preemption_guard_composes(self, fault_free):
        router = FleetRouter([FakeEngine(), FakeEngine()])
        guard = router.attach_preemption_guard()
        try:
            r1 = router.submit([4], 6)
            events = []
            it = router.stream()
            events.append(next(it))
            guard.request()            # SIGTERM equivalent
            events.extend(it)
            terminal = [e for e in events if e["finished"]]
            assert terminal and all(
                e["finish_reason"] in ("preempted", "length", "stop")
                for e in terminal)
            rec = router.request(r1)
            assert rec.finished
            # nothing the client saw is lost on the preempted path
            assert rec.tokens == _expected([4], 6)[:len(rec.tokens)]
        finally:
            guard.uninstall()

    def test_drain_is_reported_in_stats(self, fault_free):
        router = FleetRouter([FakeEngine()])
        router.drain()
        assert router.stats()["draining"] is True


# ---------------------------------------------------------------------------
# retryable attributes (satellite: machine-readable error surface)
# ---------------------------------------------------------------------------

class TestRetryableSurface:
    @pytest.mark.parametrize("cls,flag", [
        (ServingError, False),
        (QueueFullError, True),
        (RequestTooLargeError, False),
        (SchedulerStalledError, True),
        (EngineDrainingError, True),
        (FleetOverloadedError, True),
    ])
    def test_retryable_class_attribute(self, cls, flag):
        assert cls.retryable is flag
        if cls is SchedulerStalledError:
            assert cls("x").retryable is flag
        elif cls is not ServingError:
            assert cls("x").retryable is flag

    def test_fleet_overloaded_is_serving_error(self):
        assert issubclass(FleetOverloadedError, ServingError)


# ---------------------------------------------------------------------------
# observability: per-replica labels, fleet gauges, parseability
# ---------------------------------------------------------------------------

class TestFleetExport:
    def test_render_fleet_prometheus_round_trips(self, fault_free):
        router = FleetRouter([FakeEngine(), FakeEngine()])
        rid = router.submit([3], 4)
        router.step()
        router.kill_replica(router.request(rid).replica)
        router.run_to_completion(max_steps=50)
        text = render_fleet_prometheus(router)
        parsed = parse_prometheus(text)   # strict: every line well-formed
        assert parsed["paddle_serving_fleet_replicas"] == 2
        assert parsed["paddle_serving_fleet_replicas_live"] == 1
        assert parsed["paddle_serving_fleet_replicas_ejected"] == 1
        assert parsed["paddle_serving_fleet_failovers_total"] == 1
        assert parsed["paddle_serving_fleet_replayed_tokens_total"] >= 1
        assert parsed["paddle_serving_fleet_shed_total"] == 0
        # per-replica series carry the replica label
        ups = {k: v for k, v in parsed.items()
               if k.startswith("paddle_serving_fleet_replica_up")}
        assert len(ups) == 2
        assert sum(ups.values()) == 1     # one dead, one alive
        assert 'paddle_serving_fleet_replica_queue_depth{replica="0"}' \
            in parsed
        # the client-visible summary rides along unlabeled
        assert parsed["paddle_serving_tokens_generated"] == 4

    def test_parse_accepts_labels_rejects_garbage(self):
        parsed = parse_prometheus(
            'metric_a{replica="0"} 1\nmetric_a{replica="1"} 2\n')
        assert parsed == {'metric_a{replica="0"}': 1.0,
                          'metric_a{replica="1"}': 2.0}
        with pytest.raises(ValueError, match="malformed"):
            parse_prometheus('metric_a{replica=0} 1\n')
        with pytest.raises(ValueError, match="malformed"):
            parse_prometheus('metric_a{replica="0" 1\n')

    def test_router_spans_land_on_fleet_track(self, fault_free):
        tr = Tracer()
        router = FleetRouter([FakeEngine(), FakeEngine()], tracer=tr)
        rid = router.submit([3], 3)
        router.step()
        router.kill_replica(router.request(rid).replica)
        router.run_to_completion(max_steps=50)
        names = {e["name"] for e in tr.events if e.get("track") == "fleet"}
        assert {"submit", "dispatch", "replica_eject", "failover",
                "finish"} <= names


# ---------------------------------------------------------------------------
# real-model acceptance: chaos under load (slow/faults)
# ---------------------------------------------------------------------------

def _mk_engine(model, recorder=None, **kw):
    cfg = dict(num_pages=64, page_size=16, max_slots=4)
    cfg.update(kw)
    return ServingEngine(model, flight_recorder=recorder, **cfg)


@pytest.mark.slow
class TestFleetRealModel:
    def test_kill_mid_stream_bitwise_parity(self, model, fault_free):
        prompts = [RNG.integers(1, 500, size=int(n)).tolist()
                   for n in (5, 9, 7, 12)]
        refs = [_reference(model, p, 8) for p in prompts]
        router = FleetRouter([_mk_engine(model), _mk_engine(model)])
        rids = [router.submit(p, 8) for p in prompts]
        for _ in range(3):
            router.step()
        router.kill_replica(router.request(rids[0]).replica)
        out = router.run_to_completion(max_steps=300)
        for rid, ref in zip(rids, refs):
            assert out[rid] == ref
        for h in router.stats()["replica_health"]:
            if h["state"] != DEAD:
                assert router.engines[h["replica"]] \
                    .decode_program_count() == 1

    def test_kill_at_every_k_real_engine(self, model, fault_free):
        """Real-engine version of the property sweep (short stream)."""
        prompt = RNG.integers(1, 500, size=6).tolist()
        max_new = 5
        ref = _reference(model, prompt, max_new)
        for k in range(max_new):
            router = FleetRouter([_mk_engine(model), _mk_engine(model)])
            rid = router.submit(prompt, max_new)
            guard = 0
            while router.request(rid).emitted < k:
                router.step()
                guard += 1
                assert guard < 50
            # a fresh request can emit 2 tokens in its first engine step
            # (prefill + decode) — assert against the count actually
            # delivered when the kill lands, not the loop target
            at_kill = router.request(rid).emitted
            victim = router.request(rid).replica
            router.kill_replica(0 if victim is None else victim)
            out = router.run_to_completion(max_steps=200)
            assert out[rid] == ref, f"k={k}"
            assert router.fleet_metrics.counters["replayed_tokens"] \
                == at_kill

    @pytest.mark.faults
    def test_chaos_acceptance_kill_stall_poison(self, model, fault_free,
                                                tmp_path):
        """ISSUE acceptance: 3 replicas, >= 24 requests, one replica
        killed, one stalled (pinned alloc storm), one request
        NaN-poisoned — every request is bitwise-exact or classified,
        zero dup/lost tokens, no hangs, 1 decode program per survivor."""
        n_req = 24
        max_new = 6
        prompts = [RNG.integers(1, 500, size=int(RNG.integers(4, 12)))
                   .tolist() for _ in range(n_req)]
        refs = [_reference(model, p, max_new) for p in prompts]
        recorders = [FlightRecorder(dump_dir=str(tmp_path))
                     for _ in range(3)]
        engines = [_mk_engine(model, recorder=recorders[i])
                   for i in range(3)]
        router = FleetRouter(engines, max_queue_depth=64)
        poisoned_rid = "fleet-req-5"
        fault.activate(fault.FaultPlan([
            # kill replica 1 mid-run
            fault.FaultSpec(site="fleet.replica_kill", action="raise",
                            step=4, match=r"^2$"),
            # permanent alloc storm pinned to replica 0 -> it stalls and
            # is ejected with its in-flight requests replayed elsewhere
            fault.FaultSpec(site="serving.alloc", action="raise",
                            once=False, match=r"^0$"),
            # NaN-poison one request's KV wherever it runs
            fault.FaultSpec(site="serving.decode", action="poison",
                            match=rf"^{poisoned_rid}$"),
        ]))
        rids = []
        events = []
        for i, p in enumerate(prompts):
            rids.append(router.submit(p, max_new))
            events.extend(router.step())    # staggered arrivals
        while router.has_work():
            events.extend(router.step())
            assert router.stats()["steps"] < 2000, "router hang"
        # exactly-once: the event stream carries each delivered token
        # once, and it equals the per-request record
        seen: dict[str, list] = {r: [] for r in rids}
        for ev in events:
            if ev["token"] is not None:
                seen[ev["rid"]].append(ev["token"])
        classified = 0
        for rid, ref in zip(rids, refs):
            rec = router.request(rid)
            assert rec.finished
            assert seen[rid] == rec.tokens      # no dup, no gap
            if rec.finish_reason in ("stop", "length"):
                assert rec.tokens == ref        # bitwise single-engine
            else:
                classified += 1
                assert rec.finish_reason in (
                    "nonfinite", "injected", "shed", "preempted",
                    "timeout", "preempted_limit")
        assert classified >= 1                  # the poisoned one
        assert router.request(poisoned_rid).finish_reason in (
            "nonfinite", "injected")
        st = router.stats()
        assert st["replicas_ejected"] == 2      # killed + stalled
        dead = {h["dead_reason"] for h in st["replica_health"]
                if h["state"] == DEAD}
        assert dead == {"killed", "stalled"}
        assert st["fleet"]["failovers"] >= 1
        # flight recorder dumped on every ejection
        for h in st["replica_health"]:
            if h["state"] == DEAD:
                assert h["flight_recorder"] is not None
        for h in st["replica_health"]:
            if h["state"] != DEAD:
                assert router.engines[h["replica"]] \
                    .decode_program_count() == 1
                # chaos left the pool's bookkeeping invariants intact
                router.engines[h["replica"]].audit_pool()
