"""Multi-host serving: SocketTransport + process-isolated replicas.

Contracts under test (SERVING.md "Multi-host serving"):

1. FRAMING — the length-prefixed wire format round-trips a Message
   (digests verbatim, snapshots included); damaged frames raise typed
   :class:`FrameProtocolError`, damaged BODIES survive framing and die
   at the existing digest gate — never a wrong byte delivered.
2. SOCKET FLEET PARITY — a FleetRouter driving EngineServers over real
   TCP loopback produces the exact streams the in-process loopback
   fleet pins, exactly-once, including across an abrupt connection
   death (lease expiry -> epoch fence -> replay: no NEW failover
   logic, the PR-15 machinery fires from socket-shaped silence).
3. FRAME CHAOS — byte corruption, mid-frame RSTs and stalls at the
   connection layer degrade to the same counters/fallbacks the
   message-level ChaosTransport pins (corrupt_injected ==
   corrupt_dropped; resets -> torn frames + reconnects; stalls ->
   half-open teardown), with streams bitwise intact.
4. FAULT SITES — ``fleet.transport.connect`` / ``fleet.transport.accept``
   make connection ESTABLISHMENT itself lossy, deterministically.
5. REAL PROCESSES (slow tier) — ``spawn_fleet`` children are genuine
   OS processes: SIGKILL one mid-stream and every client stream stays
   bitwise identical to a single-engine ``generate()`` run,
   exactly-once, snapshot-seeded when a fetched snapshot exists;
   SIGTERM drains via the preemption guard and exits 143.

Fast tier runs on scripted fake engines over real localhost TCP
(tier-1); the subprocess sweeps ride ``slow``/``faults`` markers.
Every test in this module carries a hard SIGALRM timeout — a wedged
socket loop fails loudly instead of eating the suite's budget.
"""

import os
import signal
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.distributed import fault
from paddle_tpu.observability import parse_prometheus, render_fleet_prometheus
from paddle_tpu.serving import (FleetRouter, FrameChaos, FrameDecoder,
                                LoopbackTransport, Message, SocketTransport)
from paddle_tpu.serving import replica_host
from paddle_tpu.serving.fleet import DEAD
from paddle_tpu.serving.replica_host import (RemoteEngineHandle, shutdown_fleet,
                                             spawn_fleet)
from paddle_tpu.serving.transport import EngineServer
from paddle_tpu.serving.transport_socket import (FT_HELLO, FT_MESSAGE,
                                                 FrameProtocolError, _frame,
                                                 decode_message,
                                                 encode_message)

from test_serving_transport import (FakeEngine, _collect_tokens, _expected,
                                    _submit_payload)

_FAST_TIMEOUT_S = 60
_SLOW_TIMEOUT_S = 300


@pytest.fixture(autouse=True)
def _hard_timeout(request):
    """Per-test wall-clock ceiling (CI hygiene): socket loops that wedge
    must fail THIS test, not stall the whole run."""
    budget = (_SLOW_TIMEOUT_S if request.node.get_closest_marker("slow")
              else _FAST_TIMEOUT_S)

    def _expired(signum, frame):
        raise TimeoutError(
            f"{request.node.nodeid} exceeded its {budget}s hard timeout")

    old = signal.signal(signal.SIGALRM, _expired)
    signal.alarm(budget)
    yield
    signal.alarm(0)
    signal.signal(signal.SIGALRM, old)


@pytest.fixture
def fault_free(monkeypatch):
    fault.deactivate()
    monkeypatch.delenv("PADDLE_TRAINER_ID", raising=False)
    monkeypatch.delenv("PROCESS_ID", raising=False)
    monkeypatch.delenv("PADDLE_RESTART_EPOCH", raising=False)
    monkeypatch.delenv("PADDLE_FAULT_PLAN", raising=False)
    yield
    fault.deactivate()


# ---------------------------------------------------------------------------
# framing: FrameDecoder + Message codec
# ---------------------------------------------------------------------------

class TestFrameDecoder:
    def test_byte_by_byte_reassembly(self):
        blob = (_frame(FT_HELLO, b"replica:0")
                + _frame(FT_MESSAGE, b"x" * 300))
        dec = FrameDecoder()
        frames = []
        for i in range(len(blob)):
            frames.extend(dec.feed(blob[i:i + 1]))
        assert frames == [(FT_HELLO, b"replica:0"),
                          (FT_MESSAGE, b"x" * 300)]
        assert dec.pending == 0

    def test_coalesced_and_split_arbitrarily(self):
        msgs = [_frame(FT_MESSAGE, bytes([i]) * i) for i in range(1, 6)]
        blob = b"".join(msgs)
        for cut in (1, 3, 7, len(blob)):
            dec = FrameDecoder()
            out = []
            for off in range(0, len(blob), cut):
                out.extend(dec.feed(blob[off:off + cut]))
            assert [p for _, p in out] == [bytes([i]) * i
                                           for i in range(1, 6)]

    def test_torn_frame_is_pending_not_delivered(self):
        f = _frame(FT_MESSAGE, b"abcdef")
        dec = FrameDecoder()
        assert dec.feed(f[:-2]) == []
        assert dec.pending > 0          # counted as torn on teardown

    def test_bad_magic_raises_typed(self):
        dec = FrameDecoder()
        with pytest.raises(FrameProtocolError):
            dec.feed(b"XY" + b"\x01\x00\x00\x00\x00")

    def test_unknown_frame_type_raises(self):
        dec = FrameDecoder()
        with pytest.raises(FrameProtocolError):
            dec.feed(_frame(FT_MESSAGE, b"")[:2] + b"\x7f\x00\x00\x00\x00")

    def test_oversize_length_raises_before_buffering(self):
        import struct
        hdr = struct.pack(">2sBI", b"PT", FT_MESSAGE, (1 << 30) + 1)
        with pytest.raises(FrameProtocolError):
            FrameDecoder().feed(hdr)


class TestMessageWire:
    def test_round_trip_verbatim(self):
        m = Message.make("SUBMIT", "router", "replica:1", epoch=3, seq=17,
                         rid="r9", payload=_submit_payload("r9", [5], 4))
        d = decode_message(encode_message(m))
        assert (d.kind, d.src, d.dst, d.epoch, d.seq, d.rid) \
            == (m.kind, m.src, m.dst, m.epoch, m.seq, m.rid)
        assert d.body == m.body and d.digest == m.digest
        assert d.verify() and d.payload() == m.payload()

    def test_snapshot_blobs_cross_bitwise(self):
        from paddle_tpu.serving.snapshot import RequestSnapshot
        snap = RequestSnapshot(
            rid="r1", prompt=[1, 2, 3], max_new_tokens=8,
            eos_token_id=None, temperature=1.0, top_p=1.0,
            do_sample=False, seed=0, arrival_seq=0, tokens=[7, 8],
            context_len=4, step=4, kv_tag="kv", page_size=4,
            payloads=[[np.arange(8, dtype=np.float32).reshape(4, 2)],
                      [np.ones((4, 2), np.float32)]]).seal()
        m = Message.make("KV_OFFER", "replica:0", "router", rid="r1",
                         payload={"rid": "r1"}, snaps=(snap,))
        d = decode_message(encode_message(m))
        assert len(d.snaps) == 1
        got = d.snaps[0]
        assert got.verify()             # digests traveled verbatim
        assert got.tokens == snap.tokens
        for a, b in zip(got.payloads[0] + got.payloads[1],
                        snap.payloads[0] + snap.payloads[1]):
            assert np.asarray(a).tobytes() == np.asarray(b).tobytes()

    def test_flipped_body_byte_fails_digest_not_framing(self):
        m = Message.make("STEP", "router", "replica:0",
                         payload={"router_step": 1, "ack": 0})
        wire = bytearray(encode_message(m))
        wire[-1] ^= 0xFF                # last body byte
        d = decode_message(bytes(wire))  # framing still parses...
        assert not d.verify()            # ...the digest gate catches it

    def test_garbage_payload_raises_typed(self):
        with pytest.raises(FrameProtocolError):
            decode_message(b"\x00\x00\x00\xffgarbage")
        with pytest.raises(FrameProtocolError):
            decode_message(b"\x00")


# ---------------------------------------------------------------------------
# in-process fleets over real localhost TCP (scripted engines)
# ---------------------------------------------------------------------------

class _FakeProc:
    """Just enough of subprocess.Popen for RemoteEngineHandle: the
    in-process 'replica process' whose fate the test scripts."""

    def __init__(self, pid=4242, returncode=None):
        self.pid = pid
        self.returncode = returncode

    def poll(self):
        return self.returncode


class _SocketFleet:
    """A FleetRouter over real TCP with in-process scripted replicas:
    each replica is a FakeEngine behind an EngineServer bound to its
    own SocketTransport dialing the router's listener."""

    def __init__(self, n=2, *, router_tr_kw=None, rep_tr_kw=None,
                 router_kw=None):
        tr_kw = dict(poll_s=0.0005, query_timeout_s=0.05)
        tr_kw.update(router_tr_kw or {})
        self.rt = SocketTransport("router", listen=("127.0.0.1", 0),
                                  **tr_kw)
        self.reps = []
        for i in range(n):
            rkw = dict(poll_s=0.0005)
            rkw.update(rep_tr_kw or {})
            tr = SocketTransport(f"replica:{i}",
                                 connect={"router": self.rt.listen_addr},
                                 **rkw)
            eng = FakeEngine()
            srv = EngineServer(i, eng, tr)
            self.reps.append((tr, eng, srv))
        self.dead = set()
        want = {f"replica:{i}" for i in range(n)}
        deadline = time.monotonic() + 15
        while set(self.rt.peers()) != want:
            self.pump_replicas()
            self.rt.pump()
            assert time.monotonic() < deadline, "socket fleet never formed"
        self.handles = [RemoteEngineHandle(i, _FakeProc(pid=4000 + i))
                        for i in range(n)]
        kw = dict(lease_steps=60)
        kw.update(router_kw or {})
        self.router = FleetRouter(self.handles, transport=self.rt, **kw)

    def pump_replicas(self):
        for i, (tr, _, _) in enumerate(self.reps):
            if i not in self.dead:
                tr.pump()

    def kill(self, idx, rc=-9):
        """SIGKILL semantics, in-process: the replica's sockets vanish
        and it goes silent forever."""
        self.dead.add(idx)
        self.reps[idx][0].close()
        self.handles[idx].proc.returncode = rc

    def drive(self, *, until_emitted=None, max_steps=20000):
        events, steps = [], 0
        while self.router.has_work():
            events.extend(self.router.step())
            self.pump_replicas()
            steps += 1
            assert steps < max_steps, "socket fleet hang"
            if until_emitted is not None:
                emitted = sum(len(r.tokens)
                              for r in self.router._records.values())
                if emitted >= until_emitted:
                    break
        return events

    def close(self):
        for i, (tr, _, _) in enumerate(self.reps):
            if i not in self.dead:
                tr.close()
        self.rt.close()

    def assert_exact(self, rids, events, prompts, max_new):
        seen = _collect_tokens(events)
        for rid, p in zip(rids, prompts):
            rec = self.router.request(rid)
            assert rec.finished and rec.finish_reason == "length", rid
            assert rec.tokens == _expected(list(p), max_new), rid
            assert seen.get(rid, []) == rec.tokens       # exactly-once


class TestSocketFleet:
    def test_parity_with_loopback_fleet(self, fault_free):
        prompts, max_new = [[p] for p in (2, 3, 5, 7, 9)], 6
        fleet = _SocketFleet(2)
        try:
            rids = [fleet.router.submit(list(p), max_new) for p in prompts]
            events = fleet.drive()
            fleet.assert_exact(rids, events, prompts, max_new)
            st = fleet.rt.stats()
            assert st["socket_frames_sent"] > 0
            assert st["socket_bytes_recv"] > 0
            assert fleet.rt.counters["corrupt_dropped"] == 0
            # same streams the default loopback fleet produces
            router = FleetRouter([FakeEngine(), FakeEngine()])
            lrids = [router.submit(list(p), max_new) for p in prompts]
            while router.has_work():
                router.step()
            for rid, lrid in zip(rids, lrids):
                assert (fleet.router.request(rid).tokens
                        == router.request(lrid).tokens)
        finally:
            fleet.close()

    def test_abrupt_connection_death_fails_over_exactly_once(
            self, fault_free):
        prompts, max_new = [[p] for p in (2, 3, 5, 7, 9, 11)], 8
        fleet = _SocketFleet(2, router_kw=dict(lease_steps=20))
        try:
            rids = [fleet.router.submit(list(p), max_new) for p in prompts]
            events = fleet.drive(until_emitted=6)
            # kill a replica that actually HOSTS a live request
            victim = next(fleet.router.request(r).replica for r in rids
                          if fleet.router.request(r).replica is not None
                          and not fleet.router.request(r).finished)
            fleet.kill(victim, rc=-signal.SIGKILL)
            events += fleet.drive()
            fleet.assert_exact(rids, events, prompts, max_new)
            h = fleet.router.health(victim)
            assert h["state"] == DEAD
            assert h["exit_status"] == "signal:SIGKILL"
            assert h["pid"] == 4000 + victim
            fm = fleet.router.fleet_metrics.counters
            assert fm["lease_expirations"] >= 1
            assert fm["failovers"] >= 1
            # the corpse's queued frames became honest drops, never
            # wrong bytes
            assert fleet.rt.counters["corrupt_dropped"] == 0
        finally:
            fleet.close()

    def test_health_and_prometheus_carry_pid_addr_exit(self, fault_free):
        fleet = _SocketFleet(2)
        try:
            rid = fleet.router.submit([3], 4)
            fleet.drive()
            assert fleet.router.request(rid).tokens == _expected([3], 4)
            for i in range(2):
                h = fleet.router.health(i)
                assert h["pid"] == 4000 + i
                assert h["addr"] == fleet.rt.peer_addr(f"replica:{i}")
                assert h["exit_status"] is None
            page = render_fleet_prometheus(fleet.router)
            series = parse_prometheus(page)      # strict: every line
            assert series['paddle_serving_fleet_replica_pid'
                          '{replica="0"}'] == 4000
            assert any(k.startswith("paddle_serving_fleet_replica_info")
                       for k in series)
            assert series["paddle_serving_fleet_transport_"
                          "socket_frames_sent_total"] > 0
        finally:
            fleet.close()

    def test_query_round_trips_over_the_wire(self, fault_free):
        fleet = _SocketFleet(1)
        try:
            stop = threading.Event()

            def pump():
                while not stop.is_set():
                    fleet.reps[0][0].pump()

            th = threading.Thread(target=pump, daemon=True)
            th.start()

            def ask(kind):
                # queries are advisory (timeout -> None); retry like the
                # router does, while the pump thread answers
                deadline = time.monotonic() + 10
                while time.monotonic() < deadline:
                    out = fleet.rt.query("replica:0", kind, {})
                    if out is not None:
                        return out
                    fleet.rt.pump()
                return None

            try:
                g = ask("gauges")
                ins = ask("introspect")
            finally:
                stop.set()
                th.join()
            assert g is not None and g["pid"] == os.getpid()
            assert ins is not None and ins["pid"] == os.getpid()
            # unknown peer degrades to None, never raises
            assert fleet.rt.query("replica:9", "gauges", {}) is None
        finally:
            fleet.close()


class TestDeferredStepMode:
    def test_step_burst_latches_to_one_engine_step(self, fault_free):
        t = LoopbackTransport()
        t.bind("router")
        eng = FakeEngine()
        srv = EngineServer(0, eng, t, step_mode="deferred")
        t.send(Message.make("SUBMIT", "router", "replica:0", epoch=1,
                            rid="r1", payload=_submit_payload("r1", [3], 4)))
        t.pump()
        for k in range(3):              # a burst of retransmitted STEPs
            t.send(Message.make("STEP", "router", "replica:0", epoch=1,
                                payload={"router_step": k, "ack": 0}))
            t.pump()
        assert eng.steps == 0           # latched, not executed
        assert srv.pending_step()
        srv.run_pending_step()
        assert eng.steps == 1           # the burst collapsed to ONE step
        assert not srv.pending_step()
        t.pump()
        results = [m for m in t.recv("router") if m.kind == "STEP_RESULTS"]
        assert results and results[-1].payload()["events"]

    def test_invalid_mode_rejected(self):
        t = LoopbackTransport()
        with pytest.raises(ValueError):
            EngineServer(0, FakeEngine(), t, step_mode="bogus")


# ---------------------------------------------------------------------------
# frame-layer chaos: corruption / resets / stalls / half-open
# ---------------------------------------------------------------------------

class TestFrameChaos:
    def test_corruption_caught_by_digest_gate_streams_bitwise(
            self, fault_free):
        prompts, max_new = [[p] for p in (2, 3, 5)], 6
        fleet = _SocketFleet(
            2, router_tr_kw=dict(chaos=FrameChaos(seed=7, corrupt_p=0.08)),
            router_kw=dict(lease_steps=400))
        try:
            rids = [fleet.router.submit(list(p), max_new) for p in prompts]
            events = fleet.drive()
            fleet.assert_exact(rids, events, prompts, max_new)
            injected = fleet.rt.counters["corrupt_injected"]
            caught = sum(tr.counters["corrupt_dropped"]
                         for tr, _, _ in fleet.reps)
            assert injected > 0
            assert caught == injected   # every flipped byte was caught
        finally:
            fleet.close()

    def test_mid_frame_resets_torn_then_reconnect_bitwise(
            self, fault_free):
        prompts, max_new = [[p] for p in (2, 3, 5)], 6
        fleet = _SocketFleet(
            2, router_tr_kw=dict(chaos=FrameChaos(seed=3, reset_p=0.03)),
            router_kw=dict(lease_steps=400))
        try:
            rids = [fleet.router.submit(list(p), max_new) for p in prompts]
            events = fleet.drive()
            fleet.assert_exact(rids, events, prompts, max_new)
            assert fleet.rt.counters["socket_resets"] >= 1
            rep_counts = [tr.counters for tr, _, _ in fleet.reps]
            assert sum(c["socket_torn_frames"] for c in rep_counts) >= 1
            assert sum(c["socket_reconnects"] for c in rep_counts) >= 1
            assert all(c["corrupt_dropped"] == 0 for c in rep_counts)
        finally:
            fleet.close()

    def test_backpressure_is_bounded_not_oom(self, fault_free):
        # a stalled link + a tiny outbound budget: the queue saturates,
        # stalls are counted, overflow becomes honest drops
        fleet = _SocketFleet(
            1, router_tr_kw=dict(
                outbound_limit=4,
                chaos=FrameChaos(seed=1, stall_p=1.0, stall_s=30.0)))
        try:
            for i in range(16):
                fleet.rt.send(Message.make(
                    "STEP", "router", "replica:0", epoch=1,
                    payload={"router_step": i, "ack": 0}))
                fleet.rt.pump()
            c = fleet.rt.counters
            assert c["socket_backpressure_stalls"] > 0
            assert c["dropped"] > 0
            assert len(fleet.rt._out["replica:0"]) <= 4
        finally:
            fleet.close()

    def test_half_open_link_detected_and_torn_down(self, fault_free):
        fleet = _SocketFleet(
            1, router_tr_kw=dict(ping_interval_s=0.01, half_open_s=0.05))
        try:
            # the replica goes silent but its socket stays open: only
            # the ping/pong probe can tell this from a healthy idle link
            deadline = time.monotonic() + 10
            while fleet.rt.counters["socket_half_open"] == 0:
                fleet.rt.pump()          # replica NOT pumped: no pongs
                assert time.monotonic() < deadline, "half-open undetected"
            assert "replica:0" not in fleet.rt.peers()
        finally:
            fleet.close()

    def test_send_to_gone_peer_is_honest_loss(self, fault_free):
        fleet = _SocketFleet(1)
        try:
            fleet.kill(0)
            drops0 = fleet.rt.counters["dropped"]
            # router side notices the EOF on its next sweep, then sends
            # land in the no-peer-no-dial branch
            deadline = time.monotonic() + 10
            while "replica:0" in fleet.rt.peers():
                fleet.rt.pump()
                assert time.monotonic() < deadline
            fleet.rt.send(Message.make("FENCE", "router", "replica:0",
                                       epoch=5, payload={"epoch": 5}))
            fleet.rt.pump()
            assert fleet.rt.counters["dropped"] > drops0
        finally:
            fleet.close()


# ---------------------------------------------------------------------------
# connection-establishment fault sites
# ---------------------------------------------------------------------------

class TestConnectionFaultSites:
    def test_connect_drop_backs_off_then_connects(self, fault_free):
        plan = fault.activate(fault.FaultPlan([fault.FaultSpec(
            site="fleet.transport.connect", action="drop",
            match="^router$", once=True)]))
        fleet = _SocketFleet(1, rep_tr_kw=dict(reconnect_base_s=0.005))
        try:
            assert len(plan._fired) == 1          # the first dial died
            rid = fleet.router.submit([3], 4)     # ...and nobody noticed
            fleet.drive()
            assert fleet.router.request(rid).tokens == _expected([3], 4)
        finally:
            fleet.close()

    def test_accept_raise_is_an_rst_then_redial(self, fault_free):
        fault.activate(fault.FaultPlan([fault.FaultSpec(
            site="fleet.transport.accept", action="raise", once=True)]))
        fleet = _SocketFleet(1, rep_tr_kw=dict(reconnect_base_s=0.005))
        try:
            # the listener RST the first attempt (counted as a reset on
            # the accept side), the dialer retried, the fleet formed
            assert fleet.rt.counters["socket_resets"] >= 1
            rid = fleet.router.submit([5], 4)
            fleet.drive()
            assert fleet.router.request(rid).tokens == _expected([5], 4)
        finally:
            fleet.close()

    def test_connect_delay_parks_the_dial(self, fault_free):
        fault.activate(fault.FaultPlan([fault.FaultSpec(
            site="fleet.transport.connect", action="delay", arg=0.2,
            match="^router$", once=True)]))
        t0 = time.monotonic()
        fleet = _SocketFleet(1)
        try:
            assert time.monotonic() - t0 >= 0.2   # the dial waited
            assert fleet.rt.peers() == ["replica:0"]
        finally:
            fleet.close()

    def test_plan_replays_from_env(self, fault_free, monkeypatch):
        # PADDLE_FAULT_PLAN is the cross-process arming path replica
        # hosts inherit: the same JSON must round-trip to the same plan
        plan = fault.FaultPlan([fault.FaultSpec(
            site="fleet.transport.connect", action="drop",
            match="^router$", once=True)], seed=5)
        clone = fault.FaultPlan.from_json(plan.to_json())
        assert [s.site for s in clone.specs] == ["fleet.transport.connect"]
        assert clone.specs[0].action == "drop" and clone.seed == 5


# ---------------------------------------------------------------------------
# slow tier: real OS processes (spawn, SIGKILL, SIGTERM)
# ---------------------------------------------------------------------------

def _reference_streams(spec, workload):
    """The single-engine ground truth: same seed, same config, same
    prompts through model.generate — what every fleet stream must match
    bitwise."""
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
    pt.seed(int(spec.get("seed", 0)))
    cfg = dict(spec.get("config") or {})
    cfg.setdefault("mp_axis", None)
    cfg.setdefault("fsdp_axis", None)
    model = LlamaForCausalLM(llama_tiny(**cfg))
    model.eval()
    refs = []
    for prompt, max_new in workload:
        out = model.generate(jnp.asarray([prompt]), max_new_tokens=max_new)
        refs.append(np.asarray(out)[0, len(prompt):].tolist())
    return refs


_SPEC = {"seed": 0, "snapshots": True,
         "engine": {"num_pages": 64, "page_size": 4, "max_slots": 4,
                    "snapshot_interval": 2}}
_WORKLOAD = [([1 + i, 7, 3], 12) for i in range(6)]


def _drive_fleet(router, *, stop=None, max_steps=40000):
    steps = 0
    while router.has_work():
        router.step()
        steps += 1
        assert steps < max_steps, "real-process fleet hang"
        if stop is not None and stop():
            break
    return steps


def _emitted(router, rids):
    return sum(len(router.request(r).tokens) for r in rids)


def _introspect(router, idx, tries=5):
    for _ in range(tries):
        out = router.transport.query(f"replica:{idx}", "introspect", {})
        if out is not None:
            return out
    return None


@pytest.mark.slow
@pytest.mark.faults
class TestRealProcessFleet:
    @pytest.mark.parametrize("kill_after", [6, 40])
    def test_sigkill_mid_stream_is_bitwise_exactly_once(self, fault_free,
                                                        kill_after):
        refs = _reference_streams(_SPEC, _WORKLOAD)
        router, handles = spawn_fleet(
            3, _SPEC, router_kwargs={"snapshot_fetch_interval": 2})
        try:
            rids = [router.submit(list(p), m) for p, m in _WORKLOAD]
            _drive_fleet(router,
                         stop=lambda: _emitted(router, rids) >= kill_after)
            victim = router.request(rids[0]).replica
            if victim is None or router.health(victim)["state"] == DEAD:
                victim = 1
            handles[victim].kill()       # real SIGKILL, real process
            handles[victim].wait(10)
            _drive_fleet(router)

            for rid, ref in zip(rids, refs):
                rec = router.request(rid)
                assert rec.finished and rec.finish_reason in ("length",
                                                              "stop")
                assert rec.tokens == ref, (
                    f"{rid}: fleet stream diverged from generate()")
            h = router.health(victim)
            assert h["state"] == DEAD
            assert h["exit_status"] == "signal:SIGKILL"
            assert h["pid"] == handles[victim].pid
            fm = router.fleet_metrics.counters
            assert fm["lease_expirations"] >= 1
            assert fm["failovers"] >= 1
            if kill_after >= 40:
                # killed late: fetched snapshots existed, so recovery
                # was snapshot-seeded — replay strictly shorter than
                # regenerating every token from scratch
                assert fm["snapshot_restores"] >= 1
                assert fm["recovery_restored_tokens"] > 0
            # survivors: pinned program set, clean page accounting
            for idx in range(3):
                if idx == victim:
                    continue
                ins = _introspect(router, idx)
                assert ins is not None, f"replica {idx} unreachable"
                assert ins["audit_ok"], ins.get("audit_error")
                counts = ins["step_program_counts"]
                assert set(counts) <= {"decode", "mixed", "prefill"}
                assert sum(counts.values()) <= 4
        finally:
            shutdown_fleet(router, handles)

    def test_sigterm_drains_and_exits_preempted(self, fault_free):
        refs = _reference_streams(_SPEC, _WORKLOAD[:4])
        router, handles = spawn_fleet(2, _SPEC)
        try:
            rids = [router.submit(list(p), m) for p, m in _WORKLOAD[:4]]
            _drive_fleet(router,
                         stop=lambda: _emitted(router, rids) >= 8)
            handles[0].terminate()       # SIGTERM -> guard -> drain
            rc = handles[0].wait(30)
            assert rc == 143             # EXIT_PREEMPTED
            assert handles[0].post_mortem() == "preempted:SIGTERM"
            _drive_fleet(router)
            for rid, ref in zip(rids, refs):
                rec = router.request(rid)
                assert rec.finished
                assert rec.finish_reason in ("length", "stop", "preempted")
                # NEVER wrong bytes: whatever was delivered is a prefix
                # of the ground-truth stream
                assert rec.tokens == ref[:len(rec.tokens)], rid
        finally:
            shutdown_fleet(router, handles)

    def test_spawn_failure_raises_and_leaves_no_orphans(self, fault_free):
        from paddle_tpu.serving import ReplicaSpawnError
        bad = {"seed": 0, "config": {"vocab_size": -1}}   # child dies
        with pytest.raises(ReplicaSpawnError):
            spawn_fleet(1, bad, spawn_timeout_s=60)
        assert replica_host.reap_orphans() == 0
