"""Export / inference path (parity: jit.save -> translated_layer loadable
without model source; AnalysisPredictor serving contract)."""

import os
import subprocess
import sys
import textwrap

import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt
from paddle_tpu import nn

RNG = np.random.default_rng(0)
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _make_model():
    pt.seed(11)
    return nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))


def test_save_load_same_logits(tmp_path):
    model = _make_model()
    model.eval()
    x = RNG.standard_normal((3, 16)).astype(np.float32)
    want = np.asarray(model(jnp.asarray(x)))
    prefix = str(tmp_path / "m")
    pt.jit.save(model, prefix, input_spec=[pt.jit.InputSpec([3, 16])])
    loaded = pt.jit.load(prefix)
    got = np.asarray(loaded(x))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    # StableHLO text is exposed for external/C++ consumers
    assert "stablehlo" in loaded.mlir_module() or "func.func" in loaded.mlir_module()


def test_load_runs_in_fresh_process_without_source(tmp_path):
    """The exported artifact must run in a NEW process that never imports
    the model-building code — the translated_layer contract."""
    model = _make_model()
    model.eval()
    x = RNG.standard_normal((2, 16)).astype(np.float32)
    want = np.asarray(model(jnp.asarray(x)))
    prefix = str(tmp_path / "m")
    pt.jit.save(model, prefix, input_spec=[pt.jit.InputSpec([2, 16])])
    np.save(tmp_path / "x.npy", x)
    code = textwrap.dedent(f"""
        import sys; sys.path.insert(0, {REPO!r})
        import jax; jax.config.update("jax_platforms", "cpu")
        import numpy as np
        from paddle_tpu.jit.save_load import load
        loaded = load({prefix!r})
        x = np.load({str(tmp_path / 'x.npy')!r})
        out = np.asarray(loaded(x))
        np.save({str(tmp_path / 'out.npy')!r}, out)
    """)
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr[-2000:]
    got = np.load(tmp_path / "out.npy")
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_predictor_api(tmp_path):
    model = _make_model()
    model.eval()
    x = RNG.standard_normal((2, 16)).astype(np.float32)
    want = np.asarray(model(jnp.asarray(x)))
    prefix = str(tmp_path / "m")
    pt.jit.save(model, prefix, input_spec=[pt.jit.InputSpec([2, 16])])
    config = pt.inference.Config(prefix + ".pdmodel")
    predictor = pt.inference.create_predictor(config)
    h = predictor.get_input_handle(predictor.get_input_names()[0])
    h.copy_from_cpu(x)
    outs = predictor.run()
    np.testing.assert_allclose(outs[0], want, rtol=1e-5, atol=1e-6)
    out_h = predictor.get_output_handle(predictor.get_output_names()[0])
    np.testing.assert_allclose(out_h.copy_to_cpu(), want, rtol=1e-5, atol=1e-6)


def test_predictor_missing_artifact_prefix(tmp_path):
    import pytest
    with pytest.raises(FileNotFoundError, match="missing"):
        pt.inference.create_predictor(
            pt.inference.Config(str(tmp_path / "no-such-model")))


def test_predictor_input_validation_errors(tmp_path):
    import pytest
    model = _make_model()
    model.eval()
    prefix = str(tmp_path / "m")
    pt.jit.save(model, prefix, input_spec=[pt.jit.InputSpec([2, 16])])
    predictor = pt.inference.create_predictor(pt.inference.Config(prefix))
    with pytest.raises(KeyError, match="unknown input name"):
        predictor.get_input_handle("input_9")
    with pytest.raises(ValueError, match="shape mismatch"):
        predictor.run([np.zeros((3, 16), np.float32)])
    with pytest.raises(TypeError, match="dtype mismatch"):
        predictor.run([np.zeros((2, 16), np.float64)])
    with pytest.raises(ValueError, match="inputs not set"):
        predictor.run()
    with pytest.raises(ValueError, match="takes 1 input"):
        predictor.run([np.zeros((2, 16), np.float32)] * 2)
    handle = predictor.get_input_handle("input_0")
    with pytest.raises(ValueError, match="shape mismatch"):
        handle.copy_from_cpu(np.zeros((5, 16), np.float32))
    with pytest.raises(KeyError, match="unknown output"):
        predictor.get_output_handle("output_42")
    # after the failures, a valid run still works
    handle.copy_from_cpu(np.zeros((2, 16), np.float32))
    assert predictor.run()[0].shape == (2, 4)


def test_save_llama_reload_same_logits(tmp_path):
    """Flagship export: save Llama, reload, same logits (verdict done-bar)."""
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    pt.seed(12)
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=96,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=32,
                      mp_axis=None, fsdp_axis=None)
    model = LlamaForCausalLM(cfg)
    model.eval()
    ids = RNG.integers(0, 64, (2, 16))
    want = np.asarray(model(jnp.asarray(ids)))
    prefix = str(tmp_path / "llama")
    pt.jit.save(model, prefix,
                input_spec=[pt.jit.InputSpec([2, 16], dtype="int64")])
    loaded = pt.jit.load(prefix)
    got = np.asarray(loaded(ids))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
