"""paddle_tpu.serving.speculative — n-gram draft + multi-token verify.

The speculative contracts (SERVING.md "Speculative decoding"):

1. BITWISE PARITY — the emitted stream with speculation on is bitwise
   identical to the non-speculative engine (greedy AND sampled), which
   is itself bitwise identical to standalone ``generate()``. The verify
   step samples every position under the engine's standard
   ``fold_in(PRNGKey(seed), token_index)`` contract and emits its OWN
   samples — drafts only decide how many tokens a step emits, never
   which. Holds across churn, preemption, prefix-cache hits and int8 KV.
2. O(1) PROGRAMS — the engine owns exactly two per-step-shape programs
   (``[max_slots]`` decode + the ``[max_slots, chunk]`` MIXED step that
   carries prefill chunks and verify rows alike), each pinned at 1
   compiled instance under churn and arbitrary accept patterns
   (``step_program_counts()``; asserted over 3 churn epochs).
3. EXACT ROLLBACK — rejected draft rows are zeroed in-program and an
   in-window stop rewinds the accepted-but-unused tail, so no
   speculative garbage survives beyond ``context_len``
   (masked-garbage-is-zero at token granularity).
4. FLEET REPLAY — accepted-token streams replay bitwise on failover:
   the router's per-position dedup counts accepted positions, not
   steps.

Most engine tests share ONE module-scoped speculative engine (``eng4``)
and swap the drafter per test (drafters are stateless host objects, and
the parity contract makes the emitted stream drafter-independent) — a
fresh ServingEngine means recompiling decode + mixed, which is the
dominant cost of this file. The shared engine doubles as a cross-test
churn assertion: ``step_program_counts()`` must still be exactly
``{"decode": 1, "mixed": 1}`` after EVERY workload below.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.distributed import fault
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.observability import Tracer, parse_prometheus, \
    render_prometheus
from paddle_tpu.serving import (DraftProposer, FleetRouter, KVCachePool,
                                NgramDrafter, Request, SamplingParams,
                                Scheduler, ServingEngine, ServingMetrics,
                                SpeculativeConfig)

RNG = np.random.default_rng(23)

# Fixed prompts shared across tests: every (prompt_len, max_new) pair is
# a distinct generate() compile, so tests reuse the same three lengths
# and the same MAX_NEW wherever the scenario allows.
P5, P9, P12 = (RNG.integers(0, 512, n).tolist() for n in (5, 9, 12))
MAX_NEW = 8
KSPEC = 4


@pytest.fixture(scope="module")
def model():
    pt.seed(123)
    m = LlamaForCausalLM(llama_tiny(dtype="float32",
                                    mp_axis=None, fsdp_axis=None))
    m.eval()
    return m


@pytest.fixture(scope="module")
def refs(model):
    return {5: _reference(model, P5, MAX_NEW),
            9: _reference(model, P9, MAX_NEW),
            12: _reference(model, P12, MAX_NEW)}


@pytest.fixture(scope="module")
def eng4(model):
    return _spec_engine(model)


@pytest.fixture
def fault_free():
    fault.deactivate()
    yield
    fault.deactivate()


def _reference(model, prompt, max_new, **kw):
    out = model.generate(jnp.asarray([prompt]), max_new_tokens=max_new, **kw)
    return np.asarray(out)[0, len(prompt):].tolist()


def _req(prompt, tokens=()):
    r = Request(rid="r", prompt=list(prompt), max_new_tokens=64)
    r.tokens = list(tokens)
    return r


class OracleDrafter(DraftProposer):
    """Proposes the TRUE future tokens from a reference stream — every
    draft accepts, so a request finishes in ~max_new/k verify steps.
    The inverse, ``WrongDrafter``, never matches."""

    def __init__(self, refs: dict[str, list[int]]):
        self.refs = refs

    def propose(self, req, k):
        ref = self.refs.get(req.rid)
        if ref is None:
            return []
        done = len(req.tokens)
        return ref[done:done + k]


class RepeatDrafter(DraftProposer):
    """Proposes the last context token k times — the cheapest real
    drafter (great on repetitive text). Here it guarantees every decode
    step goes through the mixed program regardless of prompt content,
    which pins the program-count assertions; parity is unaffected
    because the emitted stream never depends on the drafter."""

    def propose(self, req, k):
        ctx = req.tokens or req.prompt
        return [int(ctx[-1])] * k


class WrongDrafter(DraftProposer):
    """Proposes tokens guaranteed to be rejected (vocab-shifted oracle)."""

    def __init__(self, refs: dict[str, list[int]], vocab: int):
        self.refs = refs
        self.vocab = vocab

    def propose(self, req, k):
        ref = self.refs.get(req.rid, [])
        done = len(req.tokens)
        return [(t + 1) % self.vocab for t in ref[done:done + k]]


def _spec_engine(model, spec=True, **kw):
    cfg = dict(num_pages=64, page_size=4, max_slots=4, max_pages_per_slot=16)
    cfg.update(kw)
    if spec is True:
        spec = SpeculativeConfig(k=KSPEC, drafter=RepeatDrafter())
    return ServingEngine(model, speculative=spec, **cfg)


def _arm(eng, drafter=None):
    """Reset the shared engine for one test: fresh metrics (spec
    re-armed, as the bench harness does) + the test's drafter."""
    eng.metrics = ServingMetrics()
    eng.metrics.set_spec(True)
    eng._drafter = drafter if drafter is not None else RepeatDrafter()
    return eng


# ---------------------------------------------------------------------------
# drafter units (no model)
# ---------------------------------------------------------------------------

class TestNgramDrafter:
    def test_matches_longest_ngram_first(self):
        d = NgramDrafter(max_ngram=2, min_ngram=1)
        # trailing bigram (3, 4) recurs at position 1 -> continuation 5 6
        assert d.propose(_req([9, 3, 4, 5, 6, 3, 4]), 2) == [5, 6]

    def test_falls_back_to_shorter_ngram(self):
        d = NgramDrafter(max_ngram=3, min_ngram=1)
        # no trigram/bigram recurrence; unigram 4 recurs -> continuation
        assert d.propose(_req([4, 7, 8, 4]), 2) == [7, 8]

    def test_rightmost_occurrence_wins(self):
        d = NgramDrafter(max_ngram=1, min_ngram=1)
        # token 2 occurs at 0 (-> 5) and at 2 (-> 6): most recent wins
        assert d.propose(_req([2, 5, 2, 6, 2]), 1) == [6]

    def test_no_match_returns_empty(self):
        d = NgramDrafter()
        assert d.propose(_req([1, 2, 3, 4]), 4) == []
        assert d.propose(_req([1]), 4) == []
        assert d.propose(_req([1, 2, 1, 2]), 0) == []  # k = 0

    def test_draft_spans_prompt_and_generated_history(self):
        d = NgramDrafter(max_ngram=2, min_ngram=1)
        # the match crosses the prompt/tokens boundary
        assert d.propose(_req([8, 9, 1], tokens=[2, 8, 9]), 3) == [1, 2, 8]

    def test_caps_at_k(self):
        d = NgramDrafter(max_ngram=1, min_ngram=1)
        got = d.propose(_req([5, 1, 2, 3, 4, 5]), 2)
        assert got == [1, 2]

    def test_validation(self):
        with pytest.raises(ValueError):
            NgramDrafter(max_ngram=2, min_ngram=3)
        with pytest.raises(ValueError):
            SpeculativeConfig(k=1)

    def test_config_drafter_passthrough(self):
        d = NgramDrafter(max_ngram=5)
        assert SpeculativeConfig(k=3, drafter=d).make_drafter() is d
        assert isinstance(SpeculativeConfig(k=3).make_drafter(),
                          NgramDrafter)


# ---------------------------------------------------------------------------
# scheduler accounting
# ---------------------------------------------------------------------------

class TestSpecScheduler:
    def _pool(self, pages=16, ps=4):
        return KVCachePool(1, pages, ps, 2, 8)

    def test_verify_token_reserve(self):
        pool = self._pool()
        sched = Scheduler(max_slots=4, prefill_token_budget=32)
        assert sched.verify_token_reserve() == 0
        sched.spec_k = 4
        sched.add(Request(rid="a", prompt=[1, 2, 3], max_new_tokens=4))
        sched.admit(pool)
        assert sched.verify_token_reserve() == 3  # (k-1) per running slot

    def test_admit_charges_verify_rows_like_prefill(self):
        pool = self._pool()
        sched = Scheduler(max_slots=4, prefill_token_budget=8)
        sched.spec_k = 4
        for i in range(3):
            sched.add(Request(rid=f"r{i}", prompt=[1, 2, 3, 4],
                              max_new_tokens=4))
        admitted = sched.admit(pool)
        # r0: 4 prefill + 3 verify rows = 7 of 8; r1 (another 4) exceeds
        # the remaining budget — without the verify charge both fit
        assert [r.rid for r in admitted] == ["r0"]

    def test_ensure_decode_pages_covers_draft_writes(self):
        pool = self._pool(pages=16, ps=4)
        sched = Scheduler(max_slots=2)
        sched.add(Request(rid="a", prompt=[1, 2, 3], max_new_tokens=8))
        (req,) = sched.admit(pool)
        assert len(req.pages) == 1          # context_len 3 of page_size 4
        req.draft_tokens = [7, 7, 7]        # writes at positions 3..6
        sched.ensure_decode_pages(pool)
        assert len(req.pages) == 2          # position 6 needs page 2

    def test_release_clears_drafts(self):
        pool = self._pool()
        sched = Scheduler(max_slots=1)
        sched.add(Request(rid="a", prompt=[1, 2, 3], max_new_tokens=8))
        (req,) = sched.admit(pool)
        req.tokens = [5]
        req.draft_tokens = [7, 8]
        sched.finish(req, pool, "length")
        assert req.draft_tokens == []


# ---------------------------------------------------------------------------
# engine: bitwise parity + O(1) programs
# ---------------------------------------------------------------------------

class TestSpecParity:
    def test_greedy_equivalence_staggered_arrivals(self, eng4, refs):
        # First use of the shared engine: a drafter that never proposes
        # keeps every DECODE step on the 1-token program — the mixed
        # program compiles once for the prefill chunk and must not
        # retrace when real drafts arrive below.
        class NoDrafter(DraftProposer):
            def propose(self, req, k):
                return []

        eng = _arm(eng4, NoDrafter())
        rid0 = eng.add_request(P5, 4)
        assert eng.run_to_completion(max_steps=50)[rid0] == refs[5][:4]
        assert eng.step_program_counts() == {"decode": 1, "mixed": 1}

        eng = _arm(eng4)
        rids = [eng.add_request(P5, MAX_NEW), eng.add_request(P9, MAX_NEW)]
        eng.step()
        rids.append(eng.add_request(P12, MAX_NEW))
        res = eng.run_to_completion(max_steps=200)
        for rid, ref in zip(rids, (refs[5], refs[9], refs[12])):
            assert res[rid] == ref
        assert eng.step_program_counts() == {"decode": 1, "mixed": 1}

    def test_greedy_equivalence_through_preemption(self, model, refs):
        """Preemption parity — and, on the same fresh engine, the full
        observability surface: draft/verify/rollback trace events, the
        one-time verify compile instant, and the Prometheus roundtrip
        of the spec counters (a fresh engine is needed to witness the
        compile event, so this test carries both loads)."""
        tr = Tracer()
        eng = _spec_engine(model, num_pages=7, max_slots=2,
                           max_pages_per_slot=6, tracer=tr)
        rids = [eng.add_request(p, MAX_NEW) for p in (P9, P12)]
        res = eng.run_to_completion(max_steps=500)
        assert eng.scheduler.num_preemptions > 0
        for rid, ref in zip(rids, (refs[9], refs[12])):
            assert res[rid] == ref
        assert eng.step_program_counts() == {"decode": 1, "mixed": 1}
        names = {e["name"] for e in tr.events}
        assert {"draft", "mixed_dispatch", "rollback"} <= names
        # the mixed program announces its compile exactly once (at the
        # first prefill chunk; verify rides the same program)
        compiles = [e for e in tr.events if e["name"] == "compile"
                    and e["args"].get("program") == "mixed"]
        assert len(compiles) == 1
        assert "decode_retraces" not in tr.counters
        # chrome export round-trips the new events
        doc = tr.chrome_trace()
        chrome_names = {e.get("name") for e in doc["traceEvents"]}
        assert {"draft", "mixed_dispatch", "rollback"} <= chrome_names
        # the spec counters survive the Prometheus render/parse roundtrip
        page = render_prometheus(eng.metrics.summary(), eng.pool.stats(),
                                 eng.tracer.counters)
        parsed = parse_prometheus(page)
        for key in ("paddle_serving_spec_accept_rate",
                    "paddle_serving_spec_draft_tokens_total",
                    "paddle_serving_spec_accepted_tokens_total",
                    "paddle_serving_spec_enabled",
                    "paddle_serving_pool_rewound_tokens"):
            assert key in parsed, key
        assert parsed["paddle_serving_spec_enabled"] == 1

    def test_sampled_stream_parity(self, model, eng4):
        """Sampled requests draw the SAME stream with speculation on —
        the verify step uses the identical fold_in(seed, token_index)
        keys — so speculation composes with the sampling contract."""
        sps = [SamplingParams(do_sample=True, top_p=0.9, temperature=0.8,
                              seed=7 + i) for i in range(2)]
        outs = []
        for eng in (ServingEngine(model, num_pages=64, page_size=4,
                                  max_slots=4, max_pages_per_slot=16),
                    _arm(eng4)):
            rids = [eng.add_request(p, MAX_NEW, sampling=sp)
                    for p, sp in zip((P5, P9), sps)]
            res = eng.run_to_completion(max_steps=200)
            outs.append([res[r] for r in rids])
        assert outs[0] == outs[1]

    def test_int8_kv_parity(self, model):
        """Speculation composes with the int8 KV pool: quantize-at-write
        per verify row, dequantize in the shared core — same stream."""
        outs = []
        for spec in (None, SpeculativeConfig(k=3)):
            eng = _spec_engine(model, spec=spec, kv_quant=True)
            rids = [eng.add_request(p, 6) for p in (P9, P12)]
            res = eng.run_to_completion(max_steps=200)
            outs.append([res[r] for r in rids])
        assert outs[0] == outs[1]

    def test_prefix_hit_churn_epochs_o1_programs(self, model, eng4):
        """3 churn epochs over a shared system prompt (prefix-cache hits
        on re-arrivals) with varying draft outcomes: parity holds and
        BOTH per-step-shape programs stay at exactly 1 compiled
        instance — O(1) in k, independent of accept patterns."""
        system = list(P9)
        eng = _arm(eng4)
        for epoch in range(3):
            prompts = [system + RNG.integers(0, 512, n).tolist()
                       for n in (2, 3)]
            refs = [_reference(model, p, 6) for p in prompts]
            rids = [eng.add_request(p, 6) for p in prompts]
            res = eng.run_to_completion(max_steps=300)
            for rid, ref in zip(rids, refs):
                assert res[rid] == ref, f"epoch {epoch}"
            assert eng.step_program_counts() == \
                {"decode": 1, "mixed": 1}, f"retraced in epoch {epoch}"
        assert eng.metrics.summary()["cache_hit_rate"] > 0
        assert eng.stats()["step_programs"] == {"decode": 1, "mixed": 1}

    def test_ngram_drafter_end_to_end(self, model, eng4):
        """Default n-gram drafter on a repetitive prompt: the trailing
        pattern recurs, so drafts are proposed and the stream still
        matches generate() exactly."""
        prompt = [462, 138, 185, 450, 95, 32]  # greedy run self-repeats
        ref = _reference(model, prompt, 16)
        eng = _arm(eng4, NgramDrafter())
        rid = eng.add_request(prompt, 16)
        res = eng.run_to_completion(max_steps=100)
        assert res[rid] == ref
        assert eng.metrics.summary()["spec_draft_tokens_total"] > 0

    def test_oracle_drafter_full_accept_fewer_steps(self, eng4, refs):
        """A perfect drafter accepts everything: the stream is unchanged
        and the engine takes ~max_new/k verify steps instead of max_new
        decode steps."""
        eng = _arm(eng4, OracleDrafter({"fast": refs[9]}))
        s0 = eng.stats()["steps"]
        eng.add_request(P9, MAX_NEW, rid="fast")
        res = eng.run_to_completion(max_steps=50)
        assert res["fast"] == refs[9]
        s = eng.metrics.summary()
        assert s["spec_accept_rate"] == 1.0
        assert s["spec_draft_tokens_total"] == s["spec_accepted_tokens_total"]
        # prefill emits 1; the remaining 7 land in ceil(7/4) = 2 steps
        assert eng.stats()["steps"] - s0 <= 1 + 2

    def test_eos_inside_accept_window_truncates(self, eng4, refs):
        """eos landing mid-window stops the request AT the eos token even
        though later positions were accepted (exactly like sequential
        decode), and the unused tail is rewound."""
        ref = refs[9]
        eos = ref[2]
        k = ref.index(eos)
        eng = _arm(eng4, OracleDrafter({"e": ref}))
        rewound0 = eng.pool.counters["rewound_tokens"]
        eng.add_request(P9, MAX_NEW, eos_token_id=eos, rid="e")
        res = eng.run_to_completion(max_steps=50)
        assert res["e"] == ref[: k + 1]
        assert eng.request("e").finish_reason == "stop"
        if k + 1 < KSPEC:  # the stop landed inside the first accept window
            assert eng.pool.counters["rewound_tokens"] > rewound0


class TestSpecRollback:
    def test_rejected_rows_zeroed_all_rejected_still_exact(
            self, model, eng4, refs, fault_free):
        """A drafter that is always wrong: every step emits exactly one
        token (the stream stays exact), and after each verify step the
        rejected positions' KV is exactly zero — masked-garbage-is-zero
        at token granularity, proven by direct pool inspection."""
        ref = refs[9]
        eng = _arm(eng4, WrongDrafter({"w": ref}, model.config.vocab_size))
        eng.add_request(P9, MAX_NEW, rid="w")
        req = eng.request("w")
        eng.step()  # prefill + first token
        for _ in range(3):
            before = req.context_len
            eng.step()
            if req.done:
                break
            # every draft was rejected: exactly one token emitted, and
            # positions context_len .. before + k - 1 (the zapped draft
            # rows) must be exact zeros in every layer's pool
            assert req.context_len == before + 1
            ps = eng.page_size
            for p in range(req.context_len, before + KSPEC):
                if p // ps >= len(req.pages):
                    break
                page, off = req.pages[p // ps], p % ps
                for pk, pv in eng.pool.pools:
                    assert not np.asarray(pk[page, off]).any(), \
                        f"K garbage at position {p}"
                    assert not np.asarray(pv[page, off]).any(), \
                        f"V garbage at position {p}"
        assert eng.run_to_completion(max_steps=100)["w"] == ref
        s = eng.metrics.summary()
        assert s["spec_accept_rate"] == 0.0
        assert s["spec_draft_tokens_total"] > 0


# ---------------------------------------------------------------------------
# metrics + observability
# ---------------------------------------------------------------------------

class TestSpecObservability:
    def test_metrics_accounting_and_histogram(self):
        m = ServingMetrics()
        m.set_spec(True)
        m.on_spec_draft(3)
        m.on_spec_draft(0)
        m.on_spec_verify(3, 2)
        m.on_spec_verify(3, 0)
        m.on_spec_verify(1, 1)
        s = m.summary()
        assert s["spec_enabled"] == 1
        assert s["spec_draft_tokens_total"] == 7
        assert s["spec_accepted_tokens_total"] == 3
        assert s["spec_accept_rate"] == pytest.approx(3 / 7)
        assert s["spec_draft_hit_rate"] == pytest.approx(0.5)
        h = m.spec_accept_histogram()
        assert h[3] == {"steps": 2, "accepted_mean": 1.0,
                        "accept_rate": pytest.approx(1 / 3)}
        assert h[1]["accept_rate"] == 1.0


# ---------------------------------------------------------------------------
# fleet failover with speculation on
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestSpecFleet:
    def test_kill_mid_run_replays_accepted_positions_bitwise(
            self, model, refs, fault_free):
        """Kill a replica mid-run with speculation enabled on every
        replica: failover replay stays exactly-once and bitwise. The
        router's emitted/produced dedup counts accepted POSITIONS (a
        verify step can emit several per request per step), not steps."""
        prompts = [P5, P9, P12]
        expect = [refs[5], refs[9], refs[12]]

        def mk():
            return _spec_engine(model, num_pages=64, page_size=16,
                                max_slots=4, max_pages_per_slot=8)

        router = FleetRouter([mk(), mk()])
        rids = [router.submit(p, MAX_NEW) for p in prompts]
        events = [ev for _ in range(3) for ev in router.step()]
        victim = router.request(rids[0]).replica
        replayed = sum(r.emitted for r in router._records.values()
                       if r.replica == victim)
        router.kill_replica(victim)
        while router.has_work():
            events.extend(router.step())
            assert router.stats()["steps"] < 500, "router hang"
        seen = {r: [] for r in rids}
        for ev in events:
            if ev["token"] is not None:
                seen[ev["rid"]].append(ev["token"])
        for rid, ref in zip(rids, expect):
            rec = router.request(rid)
            assert rec.tokens == ref            # bitwise vs generate()
            assert seen[rid] == ref             # exactly-once delivery
        # every emitted-then-replayed POSITION was verified + suppressed
        assert router.fleet_metrics.counters["replayed_tokens"] == replayed
        st = router.stats()
        for h in st["replica_health"]:
            if h["state"] != "dead":
                e = router.engines[h["replica"]]
                assert e.step_program_counts() == {"decode": 1, "mixed": 1}
