"""Real int8 deployment conversion (VERDICT r3 missing #5 / weak #5).

Parity: quantization/qat.py:23 (convert -> deployable quantized model) and
observers/groupwise.py:23 (groupwise weight observer). convert() must emit
int8 weight ARTIFACTS (not eval-mode fake quant), honor quantable_types
(Conv2D!), survive a jit.save/load roundtrip, and stay within a bounded
accuracy delta of the fp model.
"""

import numpy as np

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.quantization import (GroupWiseWeightObserver, PTQ, QAT,
                                     QuantConfig, QuantedConv2D,
                                     QuantedLinear, QuantizedConv2D,
                                     QuantizedLinear, quantize_weight)

RNG = np.random.default_rng(0)


def _lenet():
    pt.seed(0)
    return nn.Sequential(
        nn.Conv2D(1, 6, 5, padding=2), nn.ReLU(), nn.MaxPool2D(2, 2),
        nn.Conv2D(6, 16, 5), nn.ReLU(), nn.MaxPool2D(2, 2),
        nn.Flatten(), nn.Linear(16 * 5 * 5, 120), nn.ReLU(),
        nn.Linear(120, 84), nn.ReLU(), nn.Linear(84, 10))


def test_qat_wraps_conv2d():
    m = QAT().quantize(_lenet())
    kinds = [type(sub).__name__ for sub in m.sublayers()]
    assert kinds.count("QuantedConv2D") == 2, kinds
    assert kinds.count("QuantedLinear") == 3, kinds
    # custom quantable_types restricts wrapping
    cfg = QuantConfig()
    cfg.add_type_config([nn.Linear])
    m2 = QAT(cfg).quantize(_lenet())
    kinds2 = [type(sub).__name__ for sub in m2.sublayers()]
    assert kinds2.count("QuantedConv2D") == 0
    assert kinds2.count("QuantedLinear") == 3


def test_convert_emits_int8_artifacts_and_bounded_delta():
    net = _lenet()
    x = jnp.asarray(RNG.standard_normal((8, 1, 28, 28)), jnp.float32)
    ref = np.asarray(net(x))

    ptq = PTQ()
    m = ptq.quantize(net)
    for _ in range(3):
        ptq.sample(m, x)
    deploy = ptq.convert(m)

    qlayers = [s for s in deploy.sublayers()
               if isinstance(s, (QuantizedLinear, QuantizedConv2D))]
    assert len(qlayers) == 5
    for q in qlayers:
        assert q.weight_q.dtype == jnp.int8, q.weight_q.dtype
        assert q.weight_scale.dtype == jnp.float32
    # per-out-channel scale shapes
    convs = [s for s in deploy.sublayers() if isinstance(s, QuantizedConv2D)]
    assert convs[0].weight_scale.shape == (6,)
    lins = [s for s in deploy.sublayers() if isinstance(s, QuantizedLinear)]
    assert lins[-1].weight_scale.shape == (10,)

    got = np.asarray(deploy(x))
    # weight-only int8 with per-channel scales: tight output delta
    assert np.abs(got - ref).max() < 0.15 * max(1.0, np.abs(ref).max()), \
        np.abs(got - ref).max()
    # classification agreement on the calibration batch
    assert (got.argmax(-1) == ref.argmax(-1)).mean() >= 0.75


def test_groupwise_observer_and_convert():
    obs = GroupWiseWeightObserver(group_size=4)
    w = jnp.asarray(RNG.standard_normal((16, 8)), jnp.float32)
    s = obs.scales(w)
    assert s.shape == (4, 8)
    np.testing.assert_allclose(
        np.asarray(s)[0], np.abs(np.asarray(w)[:4]).max(0), rtol=1e-6)

    q, scales = quantize_weight(w, group_size=4)
    assert q.dtype == jnp.int8 and scales.shape == (4, 8)
    # groupwise dequant is closer than per-tensor would be; check roundtrip
    gs = np.repeat(np.asarray(scales), 4, axis=0)
    deq = np.asarray(q, np.float32) * gs / 127.0
    assert np.abs(deq - np.asarray(w)).max() <= (gs.max() / 127.0) + 1e-6

    # e2e: convert with group_size on a Linear-only model
    pt.seed(1)
    net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    x = jnp.asarray(RNG.standard_normal((4, 16)), jnp.float32)
    ref = np.asarray(net(x))
    qat = QAT()
    m = qat.quantize(net)
    m(x)
    deploy = qat.convert(m, group_size=8)
    lins = [s for s in deploy.sublayers() if isinstance(s, QuantizedLinear)]
    assert lins[0].weight_scale.shape == (2, 32)  # 16/8 groups
    got = np.asarray(deploy(x))
    assert np.abs(got - ref).max() < 0.1 * max(1.0, np.abs(ref).max())


def test_converted_model_jit_save_load_roundtrip(tmp_path):
    net = _lenet()
    x = jnp.asarray(RNG.standard_normal((4, 1, 28, 28)), jnp.float32)
    ptq = PTQ()
    m = ptq.quantize(net)
    ptq.sample(m, x)
    deploy = ptq.convert(m)
    want = np.asarray(deploy(x))

    path = str(tmp_path / "lenet_int8")
    pt.jit.save(deploy, path, input_spec=[
        pt.jit.InputSpec((4, 1, 28, 28), "float32")])
    loaded = pt.jit.load(path)
    got = np.asarray(loaded(x))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_qat_conv_trainable_with_ste():
    """QAT Conv2D path trains (STE gradients flow through both quanters)."""
    import paddle_tpu.nn.functional as F
    pt.seed(2)
    net = nn.Sequential(nn.Conv2D(1, 4, 3, padding=1), nn.ReLU(),
                        nn.Flatten(), nn.Linear(4 * 8 * 8, 3))
    q = QAT().quantize(net)
    opt = pt.optimizer.Adam(learning_rate=5e-3, parameters=q)
    step = pt.jit.TrainStep(q, opt, lambda o, y: F.cross_entropy(o, y))
    X = RNG.standard_normal((16, 1, 8, 8)).astype("float32")
    Y = RNG.integers(0, 3, 16)
    losses = [float(step(X, Y)) for _ in range(8)]
    assert losses[-1] < losses[0], losses
