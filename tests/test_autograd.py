"""autograd extension points (parity: test/legacy_test/test_pylayer_op.py,
test_saved_tensors_hooks.py, tensor register_hook tests)."""

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt
from paddle_tpu.autograd import (PyLayer, register_param_grad_hook,
                                 clear_param_grad_hooks, saved_tensors_hooks)

RNG = np.random.default_rng(0)


class _Scale(PyLayer):
    @staticmethod
    def forward(ctx, x, alpha):
        ctx.save_for_backward(x)
        ctx.alpha = alpha
        return x * alpha

    @staticmethod
    def backward(ctx, g):
        (x,) = ctx.saved_tensor()
        return g * ctx.alpha


class _TanhCustom(PyLayer):
    """Custom backward that intentionally differs (x2 factor) to prove the
    custom path is taken, not jax's builtin rule."""

    @staticmethod
    def forward(ctx, x):
        y = jnp.tanh(x)
        ctx.save_for_backward(y)
        return y

    @staticmethod
    def backward(ctx, g):
        (y,) = ctx.saved_tensor()
        return 2.0 * g * (1 - y * y)


def test_pylayer_forward_and_custom_backward():
    x = jnp.asarray(RNG.standard_normal((4, 5)), jnp.float32)
    y = _Scale.apply(x, 3.0)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x) * 3.0, rtol=1e-6)
    g = jax.grad(lambda x: jnp.sum(_Scale.apply(x, 3.0)))(x)
    np.testing.assert_allclose(np.asarray(g), 3.0, rtol=1e-6)
    g2 = jax.grad(lambda x: jnp.sum(_TanhCustom.apply(x)))(x)
    t = np.tanh(np.asarray(x))
    np.testing.assert_allclose(np.asarray(g2), 2.0 * (1 - t * t), rtol=1e-5)


def test_pylayer_multi_tensor_inputs():
    class Mul(PyLayer):
        @staticmethod
        def forward(ctx, a, b):
            ctx.save_for_backward(a, b)
            return a * b

        @staticmethod
        def backward(ctx, g):
            a, b = ctx.saved_tensor()
            return g * b, g * a

    a = jnp.asarray(RNG.standard_normal(6), jnp.float32)
    b = jnp.asarray(RNG.standard_normal(6), jnp.float32)
    ga, gb = jax.grad(lambda a, b: jnp.sum(Mul.apply(a, b)),
                      argnums=(0, 1))(a, b)
    np.testing.assert_allclose(np.asarray(ga), np.asarray(b), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(a), rtol=1e-6)


def test_pylayer_inside_jit_and_layer():
    x = jnp.asarray(RNG.standard_normal((3, 3)), jnp.float32)
    out = jax.jit(lambda x: _Scale.apply(x, 2.0))(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x) * 2, rtol=1e-6)


def test_saved_tensors_hooks_pack_unpack():
    calls = {"pack": 0, "unpack": 0}

    def pack(t):
        calls["pack"] += 1
        return t.astype(jnp.bfloat16)  # compress saved activation

    def unpack(t):
        calls["unpack"] += 1
        return t.astype(jnp.float32)

    x = jnp.asarray(RNG.standard_normal(8), jnp.float32)
    with saved_tensors_hooks(pack, unpack):
        g = jax.grad(lambda x: jnp.sum(_TanhCustom.apply(x)))(x)
    assert calls["pack"] >= 1 and calls["unpack"] >= 1
    t = np.tanh(np.asarray(x), dtype=np.float32)
    np.testing.assert_allclose(np.asarray(g), 2.0 * (1 - t * t),
                               rtol=5e-2, atol=5e-2)  # bf16 saved


def test_param_grad_hook_in_train_step():
    """A registered hook that zeroes a param's grad freezes that param."""
    from paddle_tpu import nn
    import paddle_tpu.nn.functional as F
    pt.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 3))
    w0_before = np.asarray(net.param_dict()["0.weight"]).copy()
    w2_before = np.asarray(net.param_dict()["2.weight"]).copy()
    register_param_grad_hook("0.weight", lambda g: jnp.zeros_like(g))
    try:
        opt = pt.optimizer.SGD(learning_rate=0.1, parameters=net)
        step = pt.jit.TrainStep(net, opt, lambda o, y: F.cross_entropy(o, y))
        x = RNG.standard_normal((16, 8)).astype(np.float32)
        y = RNG.integers(0, 3, 16)
        for _ in range(3):
            step(x, y)
        np.testing.assert_allclose(np.asarray(net.param_dict()["0.weight"]),
                                   w0_before)  # frozen by hook
        assert not np.allclose(np.asarray(net.param_dict()["2.weight"]),
                               w2_before)  # others trained
    finally:
        clear_param_grad_hooks()


def test_no_grad():
    @pt.no_grad()
    def f(x):
        return x * 3.0

    x = jnp.asarray(RNG.standard_normal(4), jnp.float32)
    g = jax.grad(lambda x: jnp.sum(f(x)) + jnp.sum(x * 2.0))(x)
    np.testing.assert_allclose(np.asarray(g), 2.0, rtol=1e-6)


def test_functional_transforms():
    f = lambda x: jnp.sum(jnp.sin(x))  # noqa: E731
    x = jnp.asarray(RNG.standard_normal(4), jnp.float32)
    j = pt.autograd.jacobian(f, x)
    np.testing.assert_allclose(np.asarray(j), np.cos(np.asarray(x)),
                               rtol=1e-5)
    h = pt.autograd.hessian(f, x)
    np.testing.assert_allclose(np.asarray(h),
                               np.diag(-np.sin(np.asarray(x))), atol=1e-5)
