"""hapi Model train/eval/predict loop (parity: python/paddle/hapi/model.py
Model.fit :1750; test model: test/legacy_test/test_model.py pattern —
LeNet-style classifier end to end)."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn
import paddle_tpu.nn.functional as F
from paddle_tpu.io.dataset import TensorDataset
from paddle_tpu.metric import Accuracy


def _toy_classification(n=128, d=16, classes=4, seed=0):
    w = np.random.default_rng(42).standard_normal((d, classes))  # shared rule
    x = np.random.default_rng(seed).standard_normal((n, d)).astype(np.float32)
    y = (x @ w).argmax(-1).astype(np.int64)
    return TensorDataset([x, y])


def test_model_fit_evaluate_predict(tmp_path, capsys):
    pt.seed(0)
    net = nn.Sequential(nn.Linear(16, 64), nn.ReLU(), nn.Linear(64, 4))
    model = pt.Model(net)
    model.prepare(
        optimizer=pt.optimizer.Adam(learning_rate=1e-2, parameters=net),
        loss=lambda out, y: F.cross_entropy(out, y),
        metrics=Accuracy())
    train = _toy_classification(seed=0)
    val = _toy_classification(seed=1)
    hist = model.fit(train, val, batch_size=32, epochs=3, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0]
    logs = model.evaluate(val, batch_size=32, verbose=0)
    assert logs["acc"] > 0.5
    preds = model.predict(val, batch_size=32, stack_outputs=True)
    assert preds[0].shape == (128, 4)
    # save/load roundtrip restores weights + optimizer state
    model.save(str(tmp_path / "ck"))
    w0 = np.asarray(net.param_dict()["0.weight"])
    net.set_state_dict({"0.weight": np.zeros_like(w0)})
    model.load(str(tmp_path / "ck"))
    np.testing.assert_allclose(np.asarray(net.param_dict()["0.weight"]), w0)


def test_model_lenet_fit():
    """Verdict done-bar: LeNet Model.fit e2e."""
    from paddle_tpu.vision.models import LeNet
    pt.seed(1)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((64, 1, 28, 28)).astype(np.float32)
    y = rng.integers(0, 10, 64).astype(np.int64)
    net = LeNet()
    model = pt.Model(net)
    model.prepare(
        optimizer=pt.optimizer.Adam(learning_rate=5e-3, parameters=net),
        loss=lambda out, yy: F.cross_entropy(out, yy),
        metrics=Accuracy())
    hist = model.fit(TensorDataset([x, y]), batch_size=32, epochs=8, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0], hist["loss"]
    info = model.summary()
    assert info["total_params"] > 1000


def test_callbacks_early_stopping_and_history(tmp_path):
    pt.seed(2)
    net = nn.Sequential(nn.Linear(16, 4))
    model = pt.Model(net)
    model.prepare(
        optimizer=pt.optimizer.SGD(learning_rate=0.0, parameters=net),
        loss=lambda out, y: F.cross_entropy(out, y),
        metrics=Accuracy())
    train = _toy_classification(seed=0)
    es = pt.callbacks.EarlyStopping(monitor="eval_loss", patience=1,
                                    verbose=0, save_best_model=False)
    hist_path = str(tmp_path / "hist.jsonl")
    hl = pt.callbacks.HistoryLogger(hist_path)
    # lr=0 => no improvement => must stop after patience+1 evals
    model.fit(train, train, batch_size=64, epochs=10, verbose=0,
              callbacks=[es, hl])
    import json
    lines = [json.loads(l) for l in open(hist_path)]
    assert 2 <= len(lines) < 10
    assert "loss" in lines[0]


def test_model_checkpoint_callback(tmp_path):
    pt.seed(3)
    net = nn.Sequential(nn.Linear(16, 4))
    model = pt.Model(net)
    model.prepare(
        optimizer=pt.optimizer.SGD(learning_rate=1e-2, parameters=net),
        loss=lambda out, y: F.cross_entropy(out, y))
    train = _toy_classification(seed=0)
    ck = pt.callbacks.ModelCheckpoint(save_freq=1,
                                      save_dir=str(tmp_path / "ck"))
    model.fit(train, batch_size=64, epochs=2, verbose=0, callbacks=[ck])
    import os
    assert os.path.exists(tmp_path / "ck" / "0.pdparams")
    assert os.path.exists(tmp_path / "ck" / "final.pdparams")


def test_flops_and_standalone_summary(capsys):
    import paddle_tpu as pt
    net = nn.Sequential(nn.Conv2D(3, 8, 3, padding=1), nn.ReLU(),
                        nn.Flatten(), nn.Linear(8 * 8 * 8, 10))
    total = pt.flops(net, (1, 3, 8, 8))
    # conv: 512 out elems * (3*9 + 1 bias) = 14336; relu 512; fc 5130
    assert total == 14336 + 512 + 5130
    stats = pt.summary(net, (1, 3, 8, 8))
    out = capsys.readouterr().out
    assert "Conv2D" in out and "Total params" in out
    assert stats["total_params"] == 224 + 5130
    # custom op override
    total2 = pt.flops(net, (1, 3, 8, 8),
                      custom_ops={nn.Linear: lambda l, i, o: 7})
    assert total2 == 14336 + 512 + 7


def test_reduce_lr_on_plateau_callback():
    from paddle_tpu.hapi.callbacks import ReduceLROnPlateau
    import types
    cb = ReduceLROnPlateau(monitor="loss", factor=0.5, patience=2,
                           verbose=0)
    opt = pt.optimizer.SGD(learning_rate=1.0, parameters=[])
    cb.model = types.SimpleNamespace(_optimizer=opt)
    cb.on_train_begin()
    for loss in (1.0, 0.9, 0.95, 0.92):  # improves twice then stalls
        cb.on_eval_end({"loss": loss})
    assert abs(float(opt.get_lr()) - 0.5) < 1e-9  # halved once
    cb.on_eval_end({"loss": 0.91})
    cb.on_eval_end({"loss": 0.91})
    assert abs(float(opt.get_lr()) - 0.25) < 1e-9


def test_visualdl_callback_writes_scalars(tmp_path):
    from paddle_tpu.hapi.callbacks import VisualDL
    import json as _json
    cb = VisualDL(log_dir=str(tmp_path))
    cb.on_train_begin()
    cb.on_epoch_end(0, {"loss": 1.25})
    cb.on_eval_end({"acc": 0.5})
    cb.on_train_end()
    lines = [_json.loads(ln) for ln in
             (tmp_path / "vdl_scalars.jsonl").read_text().splitlines()]
    assert lines[0]["tag"] == "train" and lines[0]["loss"] == 1.25
    assert lines[1]["tag"] == "eval" and lines[1]["acc"] == 0.5


def test_wandb_callback_names_missing_package():
    from paddle_tpu.hapi.callbacks import WandbCallback
    with pytest.raises(ImportError, match="wandb"):
        WandbCallback(project="x")


def test_reduce_lr_cooldown_and_eval_only_flows(tmp_path):
    from paddle_tpu.hapi.callbacks import ReduceLROnPlateau, VisualDL
    import types
    cb = ReduceLROnPlateau(factor=0.5, patience=1, cooldown=5, verbose=0)
    opt = pt.optimizer.SGD(learning_rate=1.0, parameters=[])
    cb.model = types.SimpleNamespace(_optimizer=opt)
    cb.on_train_begin()
    for _ in range(4):
        cb.on_eval_end({"loss": 1.0})
    # cooldown suppresses further reductions: exactly ONE halving
    assert abs(float(opt.get_lr()) - 0.5) < 1e-9
    # evaluate-only (no on_train_begin) must not crash
    cb2 = ReduceLROnPlateau(verbose=0)
    cb2.model = types.SimpleNamespace(_optimizer=opt)
    cb2.on_eval_end({"loss": 1.0})
    v = VisualDL(log_dir=str(tmp_path))
    v.on_eval_end({"acc": 0.1})
    assert (tmp_path / "vdl_scalars.jsonl").exists()


def test_summary_reports_frozen_params(capsys):
    import paddle_tpu as pt
    from paddle_tpu.nn.module import Parameter
    net = nn.Sequential(nn.Linear(4, 8), nn.Linear(8, 2))
    first = list(net.children())[0]
    first.weight = Parameter(first.weight, trainable=False)  # freeze
    stats = pt.summary(net, (1, 4))
    capsys.readouterr()
    assert stats["total_params"] == 4 * 8 + 8 + 8 * 2 + 2
    assert stats["trainable_params"] == stats["total_params"] - 4 * 8


def test_flops_on_bare_leaf_layer():
    import paddle_tpu as pt
    assert pt.flops(nn.Linear(4, 8), (1, 4)) == 4 * 8 + 8


def test_reduce_lr_composes_with_schedule():
    from paddle_tpu.hapi.callbacks import ReduceLROnPlateau
    import types
    sched = pt.optimizer.lr.CosineAnnealingDecay(learning_rate=1.0,
                                                 T_max=100)
    opt = pt.optimizer.SGD(learning_rate=sched, parameters=[])
    cb = ReduceLROnPlateau(factor=0.5, patience=1, verbose=0)
    cb.model = types.SimpleNamespace(_optimizer=opt)
    cb.on_train_begin()
    cb.on_eval_end({"loss": 1.0})
    cb.on_eval_end({"loss": 1.0})  # plateau -> reduce
    # the schedule SHAPE survives at half amplitude
    l10 = float(opt._lr.lr_at(10))
    l50 = float(opt._lr.lr_at(50))
    ref10 = float(sched.lr_at(10))
    ref50 = float(sched.lr_at(50))
    np.testing.assert_allclose(l10, 0.5 * ref10, rtol=1e-6)
    np.testing.assert_allclose(l50, 0.5 * ref50, rtol=1e-6)
    assert l10 != l50  # still a schedule, not a constant
    # second reduction compounds (patience=1: next stalled eval reduces)
    cb.on_eval_end({"loss": 1.0})
    np.testing.assert_allclose(float(opt._lr.lr_at(10)), 0.25 * ref10,
                               rtol=1e-6)
