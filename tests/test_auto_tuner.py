"""Auto-tuner / planner (parity: distributed/auto_tuner/tuner.py:21 and the
static Engine planner role)."""

import numpy as np

from paddle_tpu.distributed.auto_tuner import (AutoTuner, HardwareSpec,
                                               ModelSpec, plan)


def _llama8b(batch=64):
    return ModelSpec(n_params=8_030_000_000, num_layers=32, hidden=4096,
                     seq_len=8192, vocab=128256, global_batch=batch)


def test_candidates_cover_factorizations():
    t = AutoTuner(_llama8b(), HardwareSpec(n_devices=8))
    cands = t.candidates()
    degs = {(c.dp, c.fsdp, c.mp, c.pp) for c in cands}
    assert (1, 8, 1, 1) in degs and (2, 2, 2, 1) in degs
    for c in cands:
        assert c.dp * c.fsdp * c.mp * c.pp * c.sep == 8


def test_prune_respects_divisibility():
    t = AutoTuner(_llama8b(batch=64), HardwareSpec(n_devices=8))
    pruned = t.prune(t.candidates())
    for c in pruned:
        assert 32 % c.pp == 0
        assert 4096 % c.mp == 0
        assert 64 % (c.dp * c.fsdp) == 0


def test_memory_model_rejects_single_chip_8b():
    """8B params + AdamW cannot sit on one 16GB chip unsharded — the memory
    model must say so."""
    t = AutoTuner(_llama8b(), HardwareSpec(n_devices=8))
    c = t.estimate(t.prune(t.candidates())[0].__class__(dp=8))
    assert not c.fits


def test_tune_returns_fitting_config():
    best = plan(_llama8b(), n_devices=64)
    assert best.fits
    d = best.degrees
    assert d["fsdp"] * d["mp"] * d["pp"] > 1  # must shard something
    assert np.isfinite(best.step_time)


def test_measure_hook_refines_ranking():
    t = AutoTuner(_llama8b(), HardwareSpec(n_devices=8))
    calls = []

    def fake_measure(c):
        calls.append(c.degrees)
        return float(c.mp)  # pretend mp hurts

    ranked = t.tune(top_k=3, measure=fake_measure)
    assert len(calls) == 3
    assert ranked[0].step_time <= ranked[1].step_time


def test_small_model_prefers_data_parallel():
    small = ModelSpec(n_params=25_000_000, num_layers=4, hidden=512,
                      seq_len=512, vocab=32000, global_batch=64)
    best = plan(small, n_devices=8)
    assert best.fits
    assert best.degrees["dp"] * best.degrees["fsdp"] >= 4  # mostly data parallel


def _tiny_spec():
    # small enough that an 8-device CPU-mesh trial compiles + runs in
    # seconds; num_heads=8 keeps every mp degree measurable
    return ModelSpec(n_params=250_000, num_layers=1, hidden=32, seq_len=32,
                     vocab=64, global_batch=8, num_heads=8)


def test_measured_trials_run_and_record(tmp_path):
    """The built-in measure phase really executes candidates on the
    ambient 8-device mesh and logs a recorder history (parity:
    auto_tuner/tuner.py:21 profile jobs + recorder.py history)."""
    t = AutoTuner(_tiny_spec(), HardwareSpec(n_devices=8))
    csv_path = tmp_path / "history.csv"
    ranked = t.tune(top_k=2, measure="auto", history_csv=str(csv_path))
    ok_rows = [r for r in t.recorder.rows if r["status"] == "ok"]
    assert ok_rows, t.recorder.rows
    for r in ok_rows:
        assert r["measured_time"] > 0
        assert np.isfinite(r["analytic_time"])
    assert csv_path.exists()
    header = csv_path.read_text().splitlines()[0]
    assert "measured_time" in header and "dp" in header
    # the winner among measured candidates carries a real (measured) time
    assert ranked[0].step_time == min(r["measured_time"] for r in ok_rows)


def test_measured_order_can_overturn_analytic(tmp_path):
    """A measurement that contradicts the analytic model must win the
    ranking — the whole point of the profile phase."""
    t = AutoTuner(_llama8b(), HardwareSpec(n_devices=8))
    analytic = t.tune(top_k=3)
    a_order = [c.degrees for c in analytic[:3]]

    def contrarian(c):
        # analytically-worst of the top-3 measures fastest
        return float(3 - a_order.index(c.degrees))

    ranked = t.tune(top_k=3, measure=contrarian)
    m_order = [c.degrees for c in ranked[:3]]
    assert m_order == a_order[::-1]  # fully inverted vs the analytic order
    assert [r["status"] for r in t.recorder.rows] == ["ok"] * 3


def test_unmeasurable_candidates_stay_in_contention():
    """A config the trial runner cannot execute (pp>1) must not be
    demoted wholesale: its analytic estimate is rescaled onto the
    measured time scale (median measured/analytic ratio) and competes."""
    t = AutoTuner(_llama8b(), HardwareSpec(n_devices=8))

    def measure(c):
        if c.pp > 1:
            raise RuntimeError("measured trials cover pp=1 configs")
        return 1.0  # all measurable configs tie at 1s

    ranked = t.tune(top_k=3, measure=measure)
    failed = [c for c in ranked[:3]
              if any(n.startswith("measure failed") for n in c.notes)]
    for c in failed:
        # calibrated, finite, and NOT forced behind the measured ones
        assert np.isfinite(c.step_time)
        assert any("calibration" in n for n in c.notes)


def test_measured_trial_pp2_runs_for_real():
    """Pipelined candidates (pp>1) run a real PipelineTrainStep trial on
    the 8-device mesh and land recorder rows with status=ok — the r3
    'measured trials cover pp=1 configs' limitation is gone."""
    from paddle_tpu.distributed.auto_tuner import Candidate

    spec = ModelSpec(n_params=250_000, num_layers=4, hidden=32, seq_len=32,
                     vocab=64, global_batch=8, num_heads=8)
    t = AutoTuner(spec, HardwareSpec(n_devices=8))
    c = t.estimate(Candidate(dp=2, fsdp=1, mp=2, pp=2, sep=1,
                             micro_batch=2))
    dt = t.measure_candidate(c)
    assert np.isfinite(dt) and dt > 0

    # a pruned pp=2 candidate measured through the recorder protocol
    t2 = AutoTuner(spec, HardwareSpec(n_devices=8))
    cands = [t2.estimate(x) for x in t2.prune(t2.candidates())]
    pp2 = [x for x in cands if x.pp == 2]
    assert pp2, "no pp=2 candidate survived pruning"
    from paddle_tpu.distributed.auto_tuner import TrialRecorder
    rec = TrialRecorder()
    rec.add(pp2[0].degrees, analytic_time=pp2[0].step_time,
            measured_time=t2.measure_candidate(pp2[0]), status="ok")
    row = rec.rows[0]
    assert row["pp"] == 2 and row["status"] == "ok"
    assert row["measured_time"] > 0
