"""Auto-tuner / planner (parity: distributed/auto_tuner/tuner.py:21 and the
static Engine planner role)."""

import numpy as np

from paddle_tpu.distributed.auto_tuner import (AutoTuner, HardwareSpec,
                                               ModelSpec, plan)


def _llama8b(batch=64):
    return ModelSpec(n_params=8_030_000_000, num_layers=32, hidden=4096,
                     seq_len=8192, vocab=128256, global_batch=batch)


def test_candidates_cover_factorizations():
    t = AutoTuner(_llama8b(), HardwareSpec(n_devices=8))
    cands = t.candidates()
    degs = {(c.dp, c.fsdp, c.mp, c.pp) for c in cands}
    assert (1, 8, 1, 1) in degs and (2, 2, 2, 1) in degs
    for c in cands:
        assert c.dp * c.fsdp * c.mp * c.pp * c.sep == 8


def test_prune_respects_divisibility():
    t = AutoTuner(_llama8b(batch=64), HardwareSpec(n_devices=8))
    pruned = t.prune(t.candidates())
    for c in pruned:
        assert 32 % c.pp == 0
        assert 4096 % c.mp == 0
        assert 64 % (c.dp * c.fsdp) == 0


def test_memory_model_rejects_single_chip_8b():
    """8B params + AdamW cannot sit on one 16GB chip unsharded — the memory
    model must say so."""
    t = AutoTuner(_llama8b(), HardwareSpec(n_devices=8))
    c = t.estimate(t.prune(t.candidates())[0].__class__(dp=8))
    assert not c.fits


def test_tune_returns_fitting_config():
    best = plan(_llama8b(), n_devices=64)
    assert best.fits
    d = best.degrees
    assert d["fsdp"] * d["mp"] * d["pp"] > 1  # must shard something
    assert np.isfinite(best.step_time)


def test_measure_hook_refines_ranking():
    t = AutoTuner(_llama8b(), HardwareSpec(n_devices=8))
    calls = []

    def fake_measure(c):
        calls.append(c.degrees)
        return float(c.mp)  # pretend mp hurts

    ranked = t.tune(top_k=3, measure=fake_measure)
    assert len(calls) == 3
    assert ranked[0].step_time <= ranked[1].step_time


def test_small_model_prefers_data_parallel():
    small = ModelSpec(n_params=25_000_000, num_layers=4, hidden=512,
                      seq_len=512, vocab=32000, global_batch=64)
    best = plan(small, n_devices=8)
    assert best.fits
    assert best.degrees["dp"] * best.degrees["fsdp"] >= 4  # mostly data parallel
