"""The examples/ scripts must stay runnable — they are the front door for
users switching from the reference."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.parametrize("name", ["quickstart", "data_parallel",
                                  "quantize_deploy", "serve_generate"])
def test_example_runs(name):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # each script sets its own device count
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", f"{name}.py")],
        capture_output=True, text=True, timeout=420, env=env)
    assert proc.returncode == 0, (proc.stdout[-1500:], proc.stderr[-1500:])


def test_examples_use_public_surfaces_only():
    """Examples are copy-paste templates: they must not poke private
    model attributes (the decode program cache has a public accessor,
    LlamaForCausalLM.decode_cache_stats)."""
    examples_dir = os.path.join(REPO, "examples")
    offenders = []
    for fn in sorted(os.listdir(examples_dir)):
        if not fn.endswith(".py"):
            continue
        with open(os.path.join(examples_dir, fn)) as f:
            src = f.read()
        if "_decode_prog_cache" in src:
            offenders.append(fn)
    assert not offenders, (
        f"examples poke the private decode program cache: {offenders}; "
        f"use model.decode_cache_stats() instead")
