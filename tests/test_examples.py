"""The examples/ scripts must stay runnable — they are the front door for
users switching from the reference."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.parametrize("name", ["quickstart", "data_parallel",
                                  "quantize_deploy", "serve_generate"])
def test_example_runs(name):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # each script sets its own device count
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", f"{name}.py")],
        capture_output=True, text=True, timeout=420, env=env)
    assert proc.returncode == 0, (proc.stdout[-1500:], proc.stderr[-1500:])
