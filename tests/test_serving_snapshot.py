"""paddle_tpu.serving.snapshot — crash-consistent serving snapshots.

The snapshot contracts (RESILIENCE.md "Serving recovery playbook"):

1. BOUNDED REPLAY — on replica ejection the router restores each live
   request's KV from its latest verified snapshot and replays only the
   delta since capture; client streams stay bitwise identical to a
   single-engine run and exactly-once, with ``recovery_replayed_tokens``
   strictly below the full-replay cost whenever a snapshot exists.
2. WARM RESTART — ``save_snapshot``/``restore`` persist through the
   stage -> COMMIT -> rename protocol; a SIGKILLed process restores and
   continues every in-flight stream bitwise. A torn (uncommitted) dir
   is never loaded.
3. NEVER WRONG TOKENS — a corrupt snapshot (bit rot, or the
   ``serving.snapshot``/``serving.snapshot_restore`` ``poison`` fault)
   is caught by the blake2b re-verify and falls back to full replay /
   recompute. Corruption can cost time, never correctness.
4. NO NEW PROGRAMS — capture is batched ``device_get``s outside every
   compiled program; ``step_program_counts()`` stays
   ``{"decode": 1, "mixed": 1}`` with snapshots on.

Chaos tests (deterministic FaultPlan replays) carry the ``faults``
marker, same as the serving/fleet suites. Every test audits the pool's
bookkeeping invariants on the way out (``KVCachePool.audit``).
"""

import os
import shutil

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.distributed import fault
from paddle_tpu.distributed.checkpoint.save_load import (
    COMMIT_MARKER, CheckpointCorruptionError)
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.serving import (FleetRouter, RequestSnapshot, ServingEngine,
                                SnapshotStore, load_engine_snapshot,
                                save_engine_snapshot)

RNG = np.random.default_rng(31)


@pytest.fixture(scope="module")
def model():
    pt.seed(123)
    m = LlamaForCausalLM(llama_tiny(dtype="float32",
                                    mp_axis=None, fsdp_axis=None))
    m.eval()
    return m


@pytest.fixture
def fault_free(monkeypatch):
    """No FaultPlan leaks out of a chaos test; no rank env leaks in."""
    fault.deactivate()
    monkeypatch.delenv("PADDLE_TRAINER_ID", raising=False)
    monkeypatch.delenv("PROCESS_ID", raising=False)
    monkeypatch.delenv("PADDLE_RESTART_EPOCH", raising=False)
    yield
    fault.deactivate()


def _reference(model, prompt, max_new, **kw):
    out = model.generate(jnp.asarray([prompt]), max_new_tokens=max_new, **kw)
    return np.asarray(out)[0, len(prompt):].tolist()


def _mk(model, **kw):
    cfg = dict(num_pages=64, page_size=4, max_slots=2,
               prefill_token_budget=64)
    cfg.update(kw)
    return ServingEngine(model, **cfg)


def _snap(rid="r0", tokens=(7, 8), payloads=None):
    s = RequestSnapshot(rid=rid, prompt=[1, 2, 3], max_new_tokens=8,
                        eos_token_id=None, temperature=1.0, top_p=1.0,
                        do_sample=False, seed=0, arrival_seq=0,
                        tokens=list(tokens),
                        context_len=3 + max(0, len(tokens) - 1),
                        step=4, kv_tag="kv", page_size=4,
                        payloads=[list(p) for p in (payloads or [])])
    return s.seal()


# ---------------------------------------------------------------------------
# snapshot value objects + store (no model)
# ---------------------------------------------------------------------------

class TestRequestSnapshot:
    def test_seal_verify_roundtrip(self):
        pay = [[np.arange(8, dtype=np.float32).reshape(4, 2)],
               [np.ones((4, 2), np.float32)]]
        s = _snap(payloads=pay)
        assert s.verify_meta() and s.verify_payloads() and s.verify()
        assert len(s.page_digests) == 2

    def test_meta_tamper_detected(self):
        s = _snap()
        s.tokens.append(9)
        assert not s.verify_meta() and not s.verify()

    def test_payload_tamper_detected(self):
        s = _snap(payloads=[[np.zeros((4, 2), np.float32)]])
        s.corrupt()
        assert s.verify_meta()          # identity bytes untouched
        assert not s.verify_payloads()

    def test_seq_materializes_prompt_plus_tokens(self):
        s = _snap(tokens=[7, 8, 9])
        # context_len = len(prompt) + len(tokens) - 1: the newest token
        # has not been written into KV yet
        assert s.seq() == [1, 2, 3, 7, 8]


class TestSnapshotStore:
    def test_put_get_drop_and_counters(self):
        st = SnapshotStore()
        s = _snap()
        st.put("r0", s)
        assert st.num_snapshots == 1
        assert st.get("r0") is s
        assert st.get("nope") is None
        st.drop("r0")
        st.drop("r0")                   # idempotent
        assert st.get("r0") is None
        c = st.stats()
        assert c["snapshot_requests"] == 1
        assert c["snapshot_hits"] == 1 and c["snapshot_misses"] == 2
        assert c["snapshot_live"] == 0

    def test_get_reverifies_and_evicts_corrupt(self):
        st = SnapshotStore()
        st.put("r0", _snap(payloads=[[np.zeros((4, 2), np.float32)]]))
        st.corrupt("r0")
        assert st.get("r0") is None     # digest re-verify caught it
        assert st.stats()["snapshot_corrupt_detected"] == 1
        assert st.num_snapshots == 0    # evicted, later gets are misses

    def test_zero_stats_matches_stats_keys(self):
        st = SnapshotStore()
        assert set(SnapshotStore.zero_stats()) == set(st.stats())
        assert all(v == 0 for v in SnapshotStore.zero_stats().values())


# ---------------------------------------------------------------------------
# pool audit (satellite): the invariant checker itself
# ---------------------------------------------------------------------------

class TestPoolAudit:
    def test_clean_engine_passes_and_reports(self, model, fault_free):
        eng = _mk(model)
        eng.add_request([1, 2, 3, 4, 5], 6, eos_token_id=None)
        eng.run_to_completion(max_steps=100)
        rep = eng.audit_pool()
        assert rep["pages"] == rep["free"] + rep["cached"] + rep["held"]

    def test_detects_refcount_leak(self, model, fault_free):
        eng = _mk(model)
        eng.add_request([1, 2, 3, 4, 5], 6, eos_token_id=None)
        eng.run_to_completion(max_steps=100)
        page = eng.pool._free[0]
        eng.pool._ref[page] = 1         # held AND free: conservation broken
        with pytest.raises(AssertionError, match="audit failed"):
            eng.audit_pool(check_device=False)

    def test_detects_index_registration_drift(self, model, fault_free):
        eng = _mk(model)
        eng.add_request([1, 2, 3, 4, 5, 6, 7, 8], 4, eos_token_id=None)
        eng.run_to_completion(max_steps=100)
        assert eng.pool._page_key, "expected cached registered pages"
        page = next(iter(eng.pool._page_key))
        del eng.pool._page_key[page]    # index still points at the page
        with pytest.raises(AssertionError, match="audit failed"):
            eng.audit_pool(check_device=False)


# ---------------------------------------------------------------------------
# periodic capture
# ---------------------------------------------------------------------------

class TestPeriodicCapture:
    def test_capture_counters_programs_and_metrics(self, model, fault_free):
        st = SnapshotStore()
        eng = _mk(model, snapshot_store=st, snapshot_interval=2)
        prompts = [list(RNG.integers(1, 500, 6)), list(RNG.integers(1, 500, 9))]
        refs = [_reference(model, p, 8) for p in prompts]
        rids = [eng.add_request(p, 8, eos_token_id=None) for p in prompts]
        out = eng.run_to_completion(max_steps=100)
        assert [out[r] for r in rids] == refs
        assert eng.step_program_counts() == {"decode": 1, "mixed": 1}
        stats = st.stats()
        assert stats["snapshots_captured"] >= 2
        assert stats["snapshot_requests"] >= 2
        assert stats["snapshot_live"] == 0      # finish drops snapshots
        summ = eng.metrics.summary()
        assert summ["snapshots_enabled"] == 1
        assert summ["snapshots_captured"] == stats["snapshots_captured"]
        eng.audit_pool()

    def test_interval_validation(self, model):
        with pytest.raises(ValueError):
            _mk(model, snapshot_store=SnapshotStore(), snapshot_interval=0)


# ---------------------------------------------------------------------------
# warm restart (save/restore through stage -> COMMIT -> rename)
# ---------------------------------------------------------------------------

class TestWarmRestart:
    def _run_partial(self, model, tmp_path, steps=6, **kw):
        prompts = [list(RNG.integers(1, 500, 7)),
                   list(RNG.integers(1, 500, 5))]
        eng = _mk(model, **kw)
        rids = [eng.add_request(p, 10, eos_token_id=None) for p in prompts]
        for _ in range(steps):
            eng.step()
        path = str(tmp_path / "snap")
        eng.save_snapshot(path)
        return eng, rids, path

    def test_save_restore_continues_bitwise(self, model, tmp_path,
                                            fault_free):
        eng, rids, path = self._run_partial(model, tmp_path)
        warm = _mk(model)
        assert warm.restore(path) == rids       # arrival order preserved
        out = warm.run_to_completion(max_steps=100)
        cont = eng.run_to_completion(max_steps=100)
        for r in rids:
            assert out[r] == cont[r]            # bitwise vs uninterrupted
        assert warm.metrics.counters["snapshot_restores"] == len(rids)
        assert warm.metrics.counters["snapshot_restore_corrupt"] == 0
        assert eng.metrics.counters["snapshot_saves"] == 1
        warm.audit_pool()
        eng.audit_pool()

    def test_save_restore_bitwise_int8(self, model, tmp_path, fault_free):
        eng, rids, path = self._run_partial(model, tmp_path, kv_quant=True)
        warm = _mk(model, kv_quant=True)
        warm.restore(path)
        out = warm.run_to_completion(max_steps=100)
        cont = eng.run_to_completion(max_steps=100)
        for r in rids:
            assert out[r] == cont[r]
        warm.audit_pool()

    def test_torn_dir_never_loaded(self, model, tmp_path, fault_free):
        _, _, path = self._run_partial(model, tmp_path)
        torn = str(tmp_path / "torn.tmp")
        shutil.copytree(path, torn)
        os.remove(os.path.join(torn, COMMIT_MARKER))
        with pytest.raises(CheckpointCorruptionError, match="uncommitted"):
            load_engine_snapshot(torn)
        with pytest.raises(CheckpointCorruptionError):
            _mk(model).restore(torn)

    def test_corrupt_payload_degrades_to_recompute(self, model, tmp_path,
                                                   fault_free):
        eng, rids, path = self._run_partial(model, tmp_path)
        pages = os.path.join(path, "pages.npz")
        data = bytearray(open(pages, "rb").read())
        # flip a byte inside the first member's array data (the member
        # name in the local header + ~70B npy header precede it)
        data[data.find(b"r0_p0_a0") + 200] ^= 0xFF
        open(pages, "wb").write(bytes(data))
        snaps, meta = load_engine_snapshot(path)
        assert meta["corrupt_payloads_dropped"] >= 1
        warm = _mk(model)
        warm.restore(path)
        out = warm.run_to_completion(max_steps=100)
        cont = eng.run_to_completion(max_steps=100)
        for r in rids:
            assert out[r] == cont[r]            # recompute path, bitwise
        warm.audit_pool()

    def test_save_roundtrip_preserves_dtypes_and_digests(self, tmp_path):
        # bfloat16 does not survive a naive np.savez round-trip — the
        # format stores raw uint8 views + dtype names instead
        pay = [[np.asarray(RNG.standard_normal((4, 2)),
                           jnp.bfloat16.dtype)],
               [np.asarray(RNG.integers(-127, 128, (4, 2)), np.int8),
                np.ones((4, 1), np.float32)]]
        s = _snap(payloads=pay)
        path = str(tmp_path / "s")
        save_engine_snapshot(path, [s], meta={"k": 1})
        loaded, meta = load_engine_snapshot(path)
        assert meta["k"] == 1 and meta["corrupt_payloads_dropped"] == 0
        l = loaded[0]
        assert l.verify()
        for p0, p1 in zip(pay, l.payloads):
            for a0, a1 in zip(p0, p1):
                assert a1.dtype == a0.dtype and a1.shape == a0.shape
                assert np.array_equal(np.asarray(a0), np.asarray(a1))

    def test_drain_snapshot_fast_path(self, model, tmp_path, fault_free):
        eng, rids, _ = self._run_partial(model, tmp_path, steps=4)
        path = str(tmp_path / "drain_snap")
        partial = {r: list(eng.request(r).tokens) for r in rids}
        report = eng.drain(snapshot_path=path)
        # fast path: no decode-to-finish — everything preempted at once
        assert report and all(o["finish_reason"] == "preempted"
                              and o["retriable"]
                              for o in report.values())
        warm = _mk(model)
        warm.restore(path)
        out = warm.run_to_completion(max_steps=100)
        ref_eng = _mk(model)
        prompts = {r: list(eng.request(r).prompt) for r in rids}
        refs = {}
        for r in rids:
            rr = ref_eng.add_request(prompts[r], 10, eos_token_id=None)
            refs[r] = rr
        full = ref_eng.run_to_completion(max_steps=100)
        for r in rids:
            assert out[r] == full[refs[r]]      # continuation == one life
            assert out[r][: len(partial[r])] == partial[r]
        warm.audit_pool()


# ---------------------------------------------------------------------------
# bounded-replay failover
# ---------------------------------------------------------------------------

def _fleet(model, store, n=2, **kw):
    return FleetRouter([_mk(model, snapshot_store=store,
                            snapshot_interval=2, **kw) for _ in range(n)])


class TestBoundedReplayFailover:
    def _sweep(self, model, ks, max_new=8):
        prompt = list(RNG.integers(1, 500, 6))
        ref = _reference(model, prompt, max_new)
        for k in ks:
            store = SnapshotStore()
            router = _fleet(model, store)
            rid = router.submit(prompt, max_new)
            guard = 0
            while router.request(rid).emitted < k:
                router.step()
                guard += 1
                assert guard < 100
            at_kill = router.request(rid).emitted
            victim = router.request(rid).replica
            router.kill_replica(0 if victim is None else victim)
            out = router.run_to_completion(max_steps=300)
            assert out[rid] == ref, f"k={k}"    # bitwise + exactly-once
            fm = router.fleet_metrics.counters
            if fm["snapshot_restores"]:
                # bounded: strictly cheaper than replaying the full stream
                assert fm["recovery_replayed_tokens"] < at_kill
                assert (fm["recovery_restored_tokens"]
                        + fm["recovery_replayed_tokens"]) == at_kill
            else:
                assert fm["snapshot_fallbacks"] == 1
                assert fm["recovery_replayed_tokens"] == at_kill
            for eng in router.engines:
                if eng.stats()["steps"]:
                    # the ejected replica may have died before its first
                    # decode-only step — the contract is "never >1"
                    assert all(v <= 1 for v in
                               eng.step_program_counts().values())
                    eng.audit_pool()

    def test_kill_after_snapshot_is_bounded_and_bitwise(self, model,
                                                        fault_free):
        self._sweep(model, ks=(3, 4))

    @pytest.mark.slow
    def test_kill_at_every_emitted_count_sweep(self, model, fault_free):
        self._sweep(model, ks=range(1, 8))

    def test_recovery_latency_observed(self, model, fault_free):
        store = SnapshotStore()
        router = _fleet(model, store)
        rid = router.submit(list(RNG.integers(1, 500, 6)), 8)
        while router.request(rid).emitted < 3:
            router.step()
        router.kill_replica(router.request(rid).replica)
        router.run_to_completion(max_steps=300)
        fs = router.fleet_metrics.summary()
        assert fs["recovery_ttfrt_p50_s"] >= 0.0
        assert fs["snapshot_restores"] + fs["snapshot_fallbacks"] >= 1

    def test_snapshot_ahead_of_emitted_is_unusable(self, model, fault_free):
        """A snapshot holding tokens the client has not been shown yet
        must not seed the replay — those tokens would never be emitted."""
        store = SnapshotStore()
        router = _fleet(model, store)

        class Rec:
            rid = "r0"
            emitted = 1
            tokens = [5, 6, 7]
        store.put("r0", _snap(rid="r0", tokens=[5, 6]))     # 2 > emitted
        assert router._usable_snapshot(Rec()) is None
        store.put("r0", _snap(rid="r0", tokens=[9]))        # diverged
        assert router._usable_snapshot(Rec()) is None
        store.put("r0", _snap(rid="r0", tokens=[5]))        # usable prefix
        assert router._usable_snapshot(Rec()) is not None


# ---------------------------------------------------------------------------
# chaos: the serving.snapshot / serving.snapshot_restore fault sites
# ---------------------------------------------------------------------------

@pytest.mark.faults
class TestSnapshotChaos:
    def _kill_run(self, model, store, prompt, max_new, k=3):
        router = _fleet(model, store)
        rid = router.submit(prompt, max_new)
        guard = 0
        while router.request(rid).emitted < k:
            router.step()
            guard += 1
            assert guard < 100
        router.kill_replica(router.request(rid).replica)
        out = router.run_to_completion(max_steps=300)
        return router, rid, out

    def test_capture_raise_drops_that_snapshot(self, model, fault_free):
        prompt = list(RNG.integers(1, 500, 6))
        ref = _reference(model, prompt, 8)
        fault.activate(fault.FaultPlan([
            fault.FaultSpec(site="serving.snapshot", action="raise",
                            once=False),
        ]))
        store = SnapshotStore()
        router, rid, out = self._kill_run(model, store, prompt, 8)
        assert out[rid] == ref                  # full replay, bitwise
        assert store.counters["snapshot_failed"] >= 1
        fm = router.fleet_metrics.counters
        assert fm["snapshot_restores"] == 0
        assert fm["snapshot_fallbacks"] == 1
        for eng in router.engines:
            if eng.stats()["steps"]:
                eng.audit_pool()

    def test_capture_poison_caught_by_reverify(self, model, fault_free):
        """Poisoned at capture (digest NOT updated) — the failover-side
        ``get`` re-verifies, evicts, and falls back to full replay:
        zero wrong tokens."""
        prompt = list(RNG.integers(1, 500, 6))
        ref = _reference(model, prompt, 8)
        fault.activate(fault.FaultPlan([
            fault.FaultSpec(site="serving.snapshot", action="poison",
                            once=False),
        ]))
        store = SnapshotStore()
        router, rid, out = self._kill_run(model, store, prompt, 8)
        assert out[rid] == ref
        assert store.counters["snapshot_corrupt_detected"] >= 1
        fm = router.fleet_metrics.counters
        assert fm["snapshot_restores"] == 0
        assert fm["snapshot_fallbacks"] == 1
        for eng in router.engines:
            if eng.stats()["steps"]:
                assert eng.step_program_counts()["decode"] == 1
                eng.audit_pool()

    def test_restore_raise_recomputes_kv_still_bounded(self, model,
                                                       fault_free):
        """The restore site failing skips KV injection only — the replay
        is still seeded from snapshot tokens (bounded), KV recomputes."""
        prompt = list(RNG.integers(1, 500, 6))
        ref = _reference(model, prompt, 8)
        fault.activate(fault.FaultPlan([
            fault.FaultSpec(site="serving.snapshot_restore",
                            action="raise"),
        ]))
        store = SnapshotStore()
        router, rid, out = self._kill_run(model, store, prompt, 8)
        assert out[rid] == ref
        failed = sum(e.metrics.counters["snapshot_restore_failed"]
                     for e in router.engines)
        assert failed == 1
        assert router.fleet_metrics.counters["snapshot_restores"] == 1
        for eng in router.engines:
            if eng.stats()["steps"]:
                eng.audit_pool()

    def test_restore_poison_caught_zero_wrong_tokens(self, model,
                                                     fault_free):
        prompt = list(RNG.integers(1, 500, 6))
        ref = _reference(model, prompt, 8)
        fault.activate(fault.FaultPlan([
            fault.FaultSpec(site="serving.snapshot_restore",
                            action="poison"),
        ]))
        store = SnapshotStore()
        router, rid, out = self._kill_run(model, store, prompt, 8)
        assert out[rid] == ref
        corrupt = sum(e.metrics.counters["snapshot_restore_corrupt"]
                      for e in router.engines)
        assert corrupt == 1
        for eng in router.engines:
            if eng.stats()["steps"]:
                assert eng.step_program_counts()["decode"] == 1
                eng.audit_pool()
