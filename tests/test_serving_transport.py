"""paddle_tpu.serving.transport — the partition-tolerant fleet wire.

Contracts under test (SERVING.md "Fleet transport & membership"):

1. LOOPBACK PARITY — with the default LoopbackTransport the fleet
   behaves bitwise like the pre-transport in-process router: same
   streams, same step-by-step event lists, zero transport losses.
2. DELIVERY SEMANTICS — a seeded ChaosTransport deterministically
   drops, duplicates, delays, reorders, corrupts and partitions; the
   receiver side turns at-least-once delivery back into exactly-once
   (seq dedup, digest re-verify, idempotent command handlers).
3. FENCING — a zombie replica returning from a partition after its
   lease expired cannot ack stale work or double-emit: its traffic is
   counted (``stale_epoch_discarded`` / ``fenced_dropped``) and
   dropped, and client streams stay exactly-once and bitwise.
4. FAULT SITES — ``fleet.transport.send`` / ``fleet.transport.recv``
   make even the loopback wire lossy for one message kind of one
   request, and the stream still survives bitwise.

Router/transport logic runs on scripted fake engines (fast, tier-1);
the real-model kill-during-partition sweep runs llama_tiny replicas
behind ``slow``/``faults`` markers.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.distributed import fault
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.observability import parse_prometheus, render_fleet_prometheus
from paddle_tpu.serving import (ChaosTransport, EngineDrainingError,
                                EngineServer, FleetRouter,
                                LoopbackTransport, Message, QueueFullError,
                                RequestTooLargeError, SamplingParams,
                                SchedulerStalledError, ServingEngine,
                                deterministic_jitter)
from paddle_tpu.serving.fleet import DEAD

RNG = np.random.default_rng(23)


@pytest.fixture(scope="module")
def model():
    pt.seed(123)
    m = LlamaForCausalLM(llama_tiny(dtype="float32",
                                    mp_axis=None, fsdp_axis=None))
    m.eval()
    return m


@pytest.fixture
def fault_free(monkeypatch):
    fault.deactivate()
    monkeypatch.delenv("PADDLE_TRAINER_ID", raising=False)
    monkeypatch.delenv("PROCESS_ID", raising=False)
    monkeypatch.delenv("PADDLE_RESTART_EPOCH", raising=False)
    yield
    fault.deactivate()


def _reference(model, prompt, max_new, **kw):
    out = model.generate(jnp.asarray([prompt]), max_new_tokens=max_new, **kw)
    return np.asarray(out)[0, len(prompt):].tolist()


# ---------------------------------------------------------------------------
# scripted fake engine (the same duck-typed surface test_serving_fleet pins)
# ---------------------------------------------------------------------------

class FakeScheduler:
    def __init__(self, max_queue_depth=None):
        self.waiting = []
        self.running = {}
        self.max_queue_depth = max_queue_depth

    @property
    def queue_depth(self):
        return len(self.waiting)

    def has_work(self):
        return bool(self.waiting or self.running)

    def live_requests(self):
        return list(self.waiting) + list(self.running.values())


class FakeReq:
    def __init__(self, rid, prompt):
        self.rid = rid
        self.prompt = prompt
        self.produced = 0


class FakeEngine:
    """Deterministic scripted engine: request [p0, ...] emits the stream
    p0*100, p0*100+1, ... — same tokens wherever (re)placed."""

    def __init__(self, max_slots=4, max_queue_depth=None, add_fails=0,
                 stall_after=None):
        self.scheduler = FakeScheduler(max_queue_depth)
        self.pool = None
        self._draining = False
        self.last_drain_events = []
        self.max_slots = max_slots
        self.add_fails = add_fails
        self.stall_after = stall_after
        self.steps = 0
        self.flight_recorder = None

    def admission_check(self, prompt_len, max_new_tokens):
        if prompt_len + max_new_tokens > 10_000:
            raise RequestTooLargeError("scripted: never fits")

    def add_request(self, prompt, max_new_tokens, sampling=None,
                    eos_token_id=None, rid=None, deadline_s=None,
                    max_queue_wait_s=None):
        if self._draining:
            raise EngineDrainingError("draining")
        if self.add_fails > 0:
            self.add_fails -= 1
            raise QueueFullError("scripted queue full")
        r = FakeReq(rid, list(prompt))
        r.max_new = max_new_tokens
        if len(self.scheduler.running) < self.max_slots:
            slot = min(set(range(self.max_slots))
                       - set(self.scheduler.running))
            self.scheduler.running[slot] = r
        else:
            self.scheduler.waiting.append(r)
        return rid

    def step(self):
        self.steps += 1
        if self.stall_after is not None and self.steps > self.stall_after:
            raise SchedulerStalledError("scripted stall",
                                        {"step": self.steps})
        events = []
        while (self.scheduler.waiting
               and len(self.scheduler.running) < self.max_slots):
            slot = min(set(range(self.max_slots))
                       - set(self.scheduler.running))
            self.scheduler.running[slot] = self.scheduler.waiting.pop(0)
        for slot, r in sorted(self.scheduler.running.items()):
            tok = r.prompt[0] * 100 + r.produced
            r.produced += 1
            fin = r.produced >= r.max_new
            events.append({"rid": r.rid, "token": tok, "finished": fin,
                           "finish_reason": "length" if fin else None})
            if fin:
                del self.scheduler.running[slot]
        return events

    def drain(self, timeout_s=None):
        self._draining = True
        events = []
        for r in self.scheduler.waiting:
            events.append({"rid": r.rid, "token": None, "finished": True,
                           "finish_reason": "preempted"})
        self.scheduler.waiting.clear()
        while self.scheduler.running:
            events.extend(self.step())
        self.last_drain_events = events
        return {}

    def decode_program_count(self):
        return 1


def _expected(prompt, max_new):
    return [prompt[0] * 100 + i for i in range(max_new)]


def _submit_payload(rid, prompt, max_new, attempt=1):
    return {"attempt": attempt, "prompt": list(prompt),
            "max_new_tokens": max_new,
            "sampling": {"temperature": 1.0, "top_p": 1.0,
                         "do_sample": False, "seed": 0},
            "eos_token_id": None, "deadline_s": None,
            "max_queue_wait_s": None, "tenant": 0, "priority": 0,
            "ack": 0}


def _collect_tokens(events):
    seen: dict[str, list] = {}
    for ev in events:
        if ev.get("token") is not None:
            seen.setdefault(ev["rid"], []).append(ev["token"])
    return seen


# ---------------------------------------------------------------------------
# the shared deterministic jitter helper
# ---------------------------------------------------------------------------

class TestDeterministicJitter:
    def test_reproducible_and_bounded(self):
        for key in ("fleet-jitter:1:2", "fleet-hb:0", "x"):
            for bound in (2, 7, 100):
                v = deterministic_jitter(key, bound)
                assert v == deterministic_jitter(key, bound)
                assert 0 <= v < bound

    def test_degenerate_bounds(self):
        assert deterministic_jitter("k", 0) == 0
        assert deterministic_jitter("k", 1) == 0

    def test_fleet_breaker_delegates_with_historical_key(self):
        # the breaker's backoff jitter must keep its exact pre-refactor
        # hash key — chaos runs replay bit-identically across PRs
        import hashlib
        h = hashlib.sha256(b"fleet-jitter:1:2").digest()
        assert FleetRouter._jitter(1, 2, 8) \
            == int.from_bytes(h[:4], "big") % 8
        assert FleetRouter._jitter(3, 1, 1) == 0


# ---------------------------------------------------------------------------
# Message: wire format + digest gate
# ---------------------------------------------------------------------------

class TestMessage:
    def test_payload_round_trip_verifies(self):
        m = Message.make("SUBMIT", "router", "replica:0", epoch=2,
                         rid="r1", payload={"a": 1, "b": [2, 3]})
        assert m.verify()
        assert m.payload() == {"a": 1, "b": [2, 3]}
        assert m.path == "SUBMIT:r1"

    def test_numpy_scalars_serialize(self):
        m = Message.make("STEP_RESULTS", "replica:0", "router", payload={
            "events": [{"rid": "r", "token": np.int32(7),
                        "finished": False, "finish_reason": None}]})
        assert m.payload()["events"][0]["token"] == 7

    def test_flipped_byte_fails_verify(self):
        m = Message.make("STEP", "router", "replica:0",
                         payload={"router_step": 3, "ack": 0})
        flat = bytearray(m.body)
        flat[len(flat) // 2] ^= 0xFF
        m.body = bytes(flat)
        assert not m.verify()

    def test_corrupt_body_is_dropped_never_delivered(self, fault_free):
        t = LoopbackTransport()
        got = []
        t.bind("sink", got.append)
        m = Message.make("STEP", "router", "sink",
                         payload={"router_step": 0, "ack": 0})
        flat = bytearray(m.body)
        flat[0] ^= 0xFF
        m.body = bytes(flat)
        t.send(m)
        t.pump()
        assert got == []
        assert t.counters["corrupt_dropped"] == 1
        assert t.counters["received"] == 0


# ---------------------------------------------------------------------------
# ChaosTransport delivery semantics (endpoint-level units)
# ---------------------------------------------------------------------------

def _inbox_pair(**kw):
    t = ChaosTransport(**kw)
    t.bind("a")
    t.bind("b")
    return t


def _msg(i=0, src="a", dst="b"):
    return Message.make("STEP_RESULTS", src, dst, seq=i + 1,
                        payload={"i": i})


class TestChaosDelivery:
    def test_drop_everything(self, fault_free):
        t = _inbox_pair(seed=1, drop_p=1.0)
        t.send(_msg())
        t.pump()
        assert t.recv("b") == []
        assert t.counters["dropped"] == 1

    def test_duplicate_everything(self, fault_free):
        t = _inbox_pair(seed=1, dup_p=1.0)
        t.send(_msg())
        t.pump()
        got = t.recv("b")
        assert len(got) == 2
        assert got[0].payload() == got[1].payload()
        assert t.counters["duplicated"] == 1

    def test_delay_releases_on_tick(self, fault_free):
        t = _inbox_pair(seed=1, delay_p=1.0, max_delay_steps=3)
        t.tick(0)
        t.send(_msg())
        t.pump()
        assert t.recv("b") == []          # in flight, not lost
        assert t.counters["delayed"] == 1
        for step in range(1, 6):
            t.tick(step)
            t.pump()
        assert len(t.recv("b")) == 1      # released within max_delay_steps

    def test_corrupt_injected_always_caught(self, fault_free):
        t = _inbox_pair(seed=1, corrupt_p=1.0)
        for i in range(10):
            t.send(_msg(i))
        t.pump()
        assert t.recv("b") == []          # zero corrupt payloads consumed
        assert t.counters["corrupt_injected"] == 10
        assert t.counters["corrupt_dropped"] == 10

    def test_reorder_is_deterministic_permutation(self, fault_free):
        def run():
            t = _inbox_pair(seed=5, reorder=True)
            for i in range(8):
                t.send(_msg(i))
            t.pump()
            return [m.payload()["i"] for m in t.recv("b")]
        once, twice = run(), run()
        assert once == twice              # seeded -> replayable
        assert sorted(once) == list(range(8))
        assert once != list(range(8))     # actually permuted

    def test_same_seed_same_outcomes(self, fault_free):
        def run():
            t = _inbox_pair(seed=9, drop_p=0.3, dup_p=0.3, delay_p=0.3)
            for i in range(40):
                t.send(_msg(i))
            t.pump()
            return dict(t.counters)
        assert run() == run()

    def test_partition_holds_then_heals(self, fault_free):
        t = _inbox_pair(seed=1)
        t.partition("a", "b", two_way=True)
        t.send(_msg(0))
        t.pump()
        assert t.recv("b") == []
        assert t.counters["held"] == 1
        assert t.stats()["in_flight"] == 1    # held, not dropped
        t.heal()
        t.pump()
        assert len(t.recv("b")) == 1          # late, intact, delivered

    def test_one_way_partition_blocks_one_direction(self, fault_free):
        t = _inbox_pair(seed=1)
        t.partition("a", "b", two_way=False)
        t.send(_msg(0, src="a", dst="b"))
        t.send(_msg(1, src="b", dst="a"))
        t.pump()
        assert t.recv("b") == []              # a -> b blocked
        assert len(t.recv("a")) == 1          # b -> a flows

    def test_partition_window_expires_on_tick(self, fault_free):
        t = _inbox_pair(seed=1)
        t.partition("a", "b", start=0, until=3)
        t.tick(0)
        t.send(_msg(0))
        t.pump()
        assert t.recv("b") == []
        t.tick(3)                             # window closed: release
        t.pump()
        assert len(t.recv("b")) == 1

    def test_query_refused_across_partition(self, fault_free):
        t = ChaosTransport(seed=1)
        t.bind_query("replica:0", lambda kind, p: {"kind": kind})
        assert t.query("replica:0", "gauges", {}) == {"kind": "gauges"}
        t.partition("router", "replica:0")
        assert t.query("replica:0", "gauges", {}) is None


# ---------------------------------------------------------------------------
# EngineServer: idempotent command execution under redelivery
# ---------------------------------------------------------------------------

class TestEngineServer:
    def _rig(self):
        t = LoopbackTransport()
        t.bind("router")
        eng = FakeEngine()
        srv = EngineServer(0, eng, t)
        return t, eng, srv

    def test_submit_redelivery_places_once(self, fault_free):
        t, eng, _ = self._rig()
        m = Message.make("SUBMIT", "router", "replica:0", epoch=1,
                         rid="r1", payload=_submit_payload("r1", [3], 4))
        for _ in range(3):                    # at-least-once redelivery
            t.send(m)
            t.pump()
        replies = [r for r in t.recv("router")
                   if r.kind == "SUBMIT_REPLY"]
        assert len(replies) >= 3
        # every copy is the SAME stream batch — identical seq, so the
        # router-side dedup collapses them to one application
        assert len({r.seq for r in replies}) == 1
        assert replies[0].payload()["ok"] is True
        assert len(eng.scheduler.running) == 1    # placed exactly once

    def test_step_redelivery_steps_once(self, fault_free):
        t, eng, _ = self._rig()
        t.send(Message.make("SUBMIT", "router", "replica:0", epoch=1,
                            rid="r1",
                            payload=_submit_payload("r1", [3], 4)))
        t.pump()
        step = Message.make("STEP", "router", "replica:0", epoch=1,
                            payload={"router_step": 0, "ack": 0})
        for _ in range(3):
            t.send(step)
            t.pump()
        assert eng.steps == 1                 # duplicate STEP never re-steps
        results = [r for r in t.recv("router")
                   if r.kind == "STEP_RESULTS"]
        assert len({r.seq for r in results}) == 1   # same batch, resent

    def test_fence_refuses_stale_epoch(self, fault_free):
        t, eng, _ = self._rig()
        t.send(Message.make("FENCE", "router", "replica:0", epoch=1,
                            payload={}))
        t.pump()
        t.send(Message.make("STEP", "router", "replica:0", epoch=1,
                            payload={"router_step": 0, "ack": 0}))
        t.pump()
        assert eng.steps == 0                 # zombie-epoch work refused
        assert t.counters["fenced_dropped"] == 1
        # the CURRENT epoch still serves
        t.send(Message.make("SUBMIT", "router", "replica:0", epoch=2,
                            rid="r1",
                            payload=_submit_payload("r1", [3], 4)))
        t.send(Message.make("STEP", "router", "replica:0", epoch=2,
                            payload={"router_step": 1, "ack": 0}))
        t.pump()
        assert eng.steps == 1

    def test_ack_prunes_resend_buffer(self, fault_free):
        t, eng, srv = self._rig()
        t.send(Message.make("SUBMIT", "router", "replica:0", epoch=1,
                            rid="r1",
                            payload=_submit_payload("r1", [3], 4)))
        t.pump()
        assert len(srv._resend) == 1          # unacked SUBMIT_REPLY
        p = _submit_payload("r2", [4], 4, attempt=1)
        p["ack"] = 1                          # cumulative ack
        t.send(Message.make("SUBMIT", "router", "replica:0", epoch=1,
                            rid="r2", payload=p))
        t.pump()
        assert 1 not in srv._resend           # pruned by the ack


# ---------------------------------------------------------------------------
# loopback parity: the default wire is the pre-transport fleet, bitwise
# ---------------------------------------------------------------------------

class TestLoopbackParity:
    def test_default_transport_is_loopback(self, fault_free):
        router = FleetRouter([FakeEngine()])
        assert type(router.transport) is LoopbackTransport

    def test_streams_bitwise_and_lossless(self, fault_free):
        router = FleetRouter([FakeEngine(), FakeEngine()])
        rids = [router.submit([p], 4) for p in (3, 5, 7)]
        events = []
        while router.has_work():
            events.extend(router.step())
        for rid, p in zip(rids, (3, 5, 7)):
            assert router.request(rid).tokens == _expected([p], 4)
        seen = _collect_tokens(events)
        for rid in rids:
            assert seen[rid] == router.request(rid).tokens  # exactly-once
        tstats = router.transport.stats()
        assert tstats["sent"] > 0 and tstats["received"] > 0
        assert tstats["dropped"] == 0 and tstats["corrupt_dropped"] == 0
        fc = router.fleet_metrics.counters
        assert fc["duplicates_suppressed"] == 0
        assert fc["stale_epoch_discarded"] == 0
        assert fc["lease_expirations"] == 0

    def test_explicit_loopback_equals_default_step_for_step(self,
                                                            fault_free):
        def run(transport):
            router = FleetRouter([FakeEngine(), FakeEngine()],
                                 transport=transport)
            rids = [router.submit([p], 5) for p in (2, 4, 6, 8)]
            steps = []
            while router.has_work():
                steps.append(router.step())
            return rids, steps
        rids_a, steps_a = run(None)
        rids_b, steps_b = run(LoopbackTransport())
        assert rids_a == rids_b
        assert steps_a == steps_b             # identical per-step events

    def test_prometheus_carries_transport_series(self, fault_free):
        router = FleetRouter([FakeEngine(), FakeEngine()])
        router.submit([3], 3)
        router.run_to_completion(max_steps=30)
        page = render_fleet_prometheus(router)
        parsed = parse_prometheus(page)
        assert parsed["paddle_serving_fleet_transport_sent_total"] > 0
        assert parsed["paddle_serving_fleet_transport_dropped_total"] == 0
        assert "paddle_serving_fleet_duplicates_suppressed_total" in parsed
        assert "paddle_serving_fleet_stale_epoch_discarded_total" in parsed
        assert "paddle_serving_fleet_lease_expirations_total" in parsed
        assert "paddle_serving_fleet_heartbeat_rtt_p50_steps" in parsed
        assert "paddle_serving_fleet_heartbeat_rtt_p99_steps" in parsed
        assert parsed['paddle_serving_fleet_replica_epoch{replica="0"}'] \
            == 1


# ---------------------------------------------------------------------------
# the fleet over a hostile wire: exactly-once, bitwise, no hangs
# ---------------------------------------------------------------------------

class TestFleetUnderChaos:
    def _run_fleet(self, transport, prompts, max_new=5, n_replicas=2,
                   **router_kw):
        engines = [FakeEngine() for _ in range(n_replicas)]
        router = FleetRouter(engines, transport=transport, **router_kw)
        rids = [router.submit(list(p), max_new) for p in prompts]
        events = []
        guard = 0
        while router.has_work():
            events.extend(router.step())
            guard += 1
            assert guard < 2000, "router hang under chaos"
        return router, rids, events

    def _assert_exact(self, router, rids, events, prompts, max_new=5):
        seen = _collect_tokens(events)
        for rid, p in zip(rids, prompts):
            rec = router.request(rid)
            assert rec.finished and rec.finish_reason == "length"
            assert rec.tokens == _expected(list(p), max_new), rid
            assert seen.get(rid, []) == rec.tokens  # exactly-once

    def test_duplicates_and_reorder_collapse(self, fault_free):
        prompts = [[p] for p in (2, 3, 5, 7, 9)]
        t = ChaosTransport(seed=3, dup_p=0.6, reorder=True)
        router, rids, events = self._run_fleet(t, prompts)
        self._assert_exact(router, rids, events, prompts)
        assert t.counters["duplicated"] > 0
        assert router.fleet_metrics.counters["duplicates_suppressed"] > 0

    def test_drops_and_delays_retransmit_through(self, fault_free):
        prompts = [[p] for p in (2, 3, 5, 7)]
        t = ChaosTransport(seed=11, drop_p=0.15, delay_p=0.3,
                           max_delay_steps=3)
        router, rids, events = self._run_fleet(t, prompts)
        self._assert_exact(router, rids, events, prompts)
        assert t.counters["dropped"] > 0

    def test_corruption_always_caught_never_consumed(self, fault_free):
        prompts = [[p] for p in (2, 3, 5)]
        t = ChaosTransport(seed=17, corrupt_p=0.2)
        router, rids, events = self._run_fleet(t, prompts)
        self._assert_exact(router, rids, events, prompts)
        assert t.counters["corrupt_injected"] > 0
        # THE digest-gate invariant: every injected corruption was
        # caught at receive — zero corrupt payloads consumed
        assert t.counters["corrupt_dropped"] \
            == t.counters["corrupt_injected"]

    def test_acceptance_drops_dups_reorder_partition_kill(self,
                                                          fault_free):
        """ISSUE 16 acceptance combo: drops + duplicates + reorder + a
        healed partition + one replica kill — every client stream
        exactly-once and bitwise, no hangs, zero corrupt consumed."""
        prompts = [[p] for p in (2, 3, 5, 7, 9, 11, 13, 17)]
        t = ChaosTransport(seed=29, drop_p=0.08, dup_p=0.25,
                           delay_p=0.15, max_delay_steps=2,
                           corrupt_p=0.05, reorder=True)
        t.partition("router", "replica:2", two_way=True, start=4)
        engines = [FakeEngine() for _ in range(3)]
        router = FleetRouter(engines, transport=t, lease_steps=5)
        rids = [router.submit(list(p), 6) for p in prompts]
        events = []
        guard = 0
        while router.has_work():
            if guard == 6:
                router.kill_replica(1)        # the one replica kill
            events.extend(router.step())
            guard += 1
            assert guard < 2000, "router hang under chaos"
        t.heal()                              # the partition heals: any
        events.extend(router.step())          # zombie traffic arrives now
        events.extend(router.step())
        seen = _collect_tokens(events)
        for rid, p in zip(rids, prompts):
            rec = router.request(rid)
            assert rec.finished and rec.finish_reason == "length"
            assert rec.tokens == _expected(list(p), 6), rid
            assert seen.get(rid, []) == rec.tokens
        assert t.counters["corrupt_dropped"] \
            == t.counters["corrupt_injected"]
        st = router.stats()
        assert st["replicas_ejected"] == 2    # killed + partitioned
        fc = router.fleet_metrics.counters
        assert fc["failovers"] >= 1


class TestZombieFencing:
    def test_partitioned_replica_ejected_then_fenced(self, fault_free):
        """The epoch-fencing scenario end to end: a one-way partition
        silences replica 1's replies while it keeps receiving STEPs and
        producing tokens; its lease expires, the router ejects it and
        replays elsewhere; the partition heals and the zombie's held
        results arrive — every one counted stale and discarded, no
        token delivered twice, streams bitwise."""
        prompts = [[3], [5], [7], [9]]
        t = ChaosTransport(seed=0)
        engines = [FakeEngine(), FakeEngine()]
        router = FleetRouter(engines, transport=t, lease_steps=4)
        rids = [router.submit(list(p), 6) for p in prompts]
        events = []
        events.extend(router.step())          # placed on both replicas
        assert any(router.request(r).replica == 1 for r in rids)
        t.partition("replica:1", "router", two_way=False)  # mute replies
        guard = 0
        while router.has_work():
            events.extend(router.step())
            guard += 1
            assert guard < 200
        rep1 = router.stats()["replica_health"][1]
        assert rep1["state"] == "dead"
        assert rep1["dead_reason"] == "lease_expired"
        assert rep1["epoch"] == 2             # the fence moved
        fc = router.fleet_metrics.counters
        assert fc["lease_expirations"] == 1
        assert fc["failovers"] >= 1
        # the zombie DID produce while partitioned (STEPs still arrived)
        assert engines[1].steps > 0
        assert t.counters["held"] > 0
        before = fc["stale_epoch_discarded"]
        t.heal()                              # zombie replies arrive now
        events.extend(router.step())
        assert fc["stale_epoch_discarded"] > before
        # exactly-once + bitwise despite the zombie's double production
        seen = _collect_tokens(events)
        for rid, p in zip(rids, prompts):
            rec = router.request(rid)
            assert rec.tokens == _expected(list(p), 6)
            assert seen.get(rid, []) == rec.tokens    # no double emission

    def test_heal_before_lease_expiry_no_failover(self, fault_free):
        """A partition shorter than the lease: held replies release at
        the window end, apply normally (same epoch), and nothing is
        ejected or replayed — partitions cost latency, not work."""
        t = ChaosTransport(seed=0)
        t.partition("replica:1", "router", two_way=False, start=2,
                    until=4)
        router = FleetRouter([FakeEngine(), FakeEngine()], transport=t,
                             lease_steps=8)
        rids = [router.submit([p], 6) for p in (3, 5)]
        events = []
        guard = 0
        while router.has_work():
            events.extend(router.step())
            guard += 1
            assert guard < 200
        assert router.stats()["replicas_ejected"] == 0
        assert router.fleet_metrics.counters["failovers"] == 0
        seen = _collect_tokens(events)
        for rid, p in zip(rids, (3, 5)):
            assert router.request(rid).tokens == _expected([p], 6)
            assert seen[rid] == router.request(rid).tokens


# ---------------------------------------------------------------------------
# fleet.transport.send / fleet.transport.recv fault sites
# ---------------------------------------------------------------------------

class TestTransportFaultSites:
    def _run(self, plan, n=3, max_new=4):
        fault.activate(plan)
        router = FleetRouter([FakeEngine(), FakeEngine()])
        rids = [router.submit([p], max_new) for p in (3, 5, 7)[:n]]
        events = []
        guard = 0
        while router.has_work():
            events.extend(router.step())
            guard += 1
            assert guard < 500
        return router, rids, events

    def test_drop_action_on_results_recovers_by_resend(self, fault_free):
        plan = fault.FaultPlan([fault.FaultSpec(
            site="fleet.transport.send", action="drop",
            match=r"^STEP_RESULTS")])
        router, rids, events = self._run(plan)
        assert router.transport.counters["dropped"] == 1
        seen = _collect_tokens(events)
        for rid, p in zip(rids, (3, 5, 7)):
            assert router.request(rid).tokens == _expected([p], 4)
            assert seen[rid] == router.request(rid).tokens

    def test_dup_action_is_suppressed(self, fault_free):
        plan = fault.FaultPlan([fault.FaultSpec(
            site="fleet.transport.send", action="dup",
            match=r"^STEP_RESULTS")])
        router, rids, events = self._run(plan)
        assert router.transport.counters["duplicated"] == 1
        assert router.fleet_metrics.counters["duplicates_suppressed"] >= 1
        seen = _collect_tokens(events)
        for rid in rids:
            assert seen[rid] == router.request(rid).tokens

    def test_delay_action_arrives_late_and_exact(self, fault_free):
        plan = fault.FaultPlan([fault.FaultSpec(
            site="fleet.transport.send", action="delay", arg=2,
            match=r"^STEP_RESULTS")])
        router, rids, events = self._run(plan)
        assert router.transport.counters["delayed"] == 1
        seen = _collect_tokens(events)
        for rid, p in zip(rids, (3, 5, 7)):
            assert router.request(rid).tokens == _expected([p], 4)
            assert seen[rid] == router.request(rid).tokens

    def test_corrupt_action_caught_at_recv(self, fault_free):
        plan = fault.FaultPlan([fault.FaultSpec(
            site="fleet.transport.send", action="corrupt",
            match=r"^SUBMIT:fleet-req-0$")])
        router, rids, events = self._run(plan)
        t = router.transport.counters
        assert t["corrupt_injected"] == 1
        assert t["corrupt_dropped"] == 1      # digest gate caught it
        # the pinned submit retransmitted and the stream survived
        for rid, p in zip(rids, (3, 5, 7)):
            assert router.request(rid).tokens == _expected([p], 4)

    def test_recv_site_fires_with_kind_rid_path(self, fault_free):
        plan = fault.FaultPlan([fault.FaultSpec(
            site="fleet.transport.recv", action="drop",
            match=r"^HEARTBEAT_ACK")])
        fault.activate(plan)
        router = FleetRouter([FakeEngine()])
        router.submit([3], 2)
        router.run_to_completion(max_steps=50)
        assert router.transport.counters["dropped"] == 1
        assert router.request("fleet-req-0").tokens == _expected([3], 2)


# ---------------------------------------------------------------------------
# real-model acceptance: kill during a partition (slow/faults)
# ---------------------------------------------------------------------------

def _mk_engine(model, **kw):
    cfg = dict(num_pages=64, page_size=16, max_slots=4)
    cfg.update(kw)
    return ServingEngine(model, **cfg)


@pytest.mark.slow
class TestRealModelTransport:
    def test_loopback_fleet_matches_generate_bitwise(self, model,
                                                     fault_free):
        prompts = [RNG.integers(1, 500, size=int(n)).tolist()
                   for n in (5, 9, 7)]
        refs = [_reference(model, p, 6) for p in prompts]
        router = FleetRouter([_mk_engine(model), _mk_engine(model)])
        rids = [router.submit(p, 6) for p in prompts]
        out = router.run_to_completion(max_steps=200)
        for rid, ref in zip(rids, refs):
            assert out[rid] == ref
        assert router.transport.stats()["dropped"] == 0

    @pytest.mark.faults
    def test_kill_during_partition_sweep(self, model, fault_free):
        """ISSUE 16: the faults-marked kill-during-partition sweep —
        replica 2 partitioned two-way (lease expires -> eject), replica
        1 chaos-killed at step k while the partition is open, mild
        drop/dup/reorder chaos on every surviving message. For every
        kill point: each stream bitwise equals single-engine
        ``generate()``, exactly once; ``step_program_counts()`` stays
        pinned (no retrace) and ``audit_pool()`` is clean on the
        survivor."""
        prompts = [RNG.integers(1, 500, size=int(RNG.integers(4, 10)))
                   .tolist() for _ in range(6)]
        max_new = 6
        refs = [_reference(model, p, max_new) for p in prompts]
        for k in (2, 4, 6):
            fault.activate(fault.FaultPlan([
                fault.FaultSpec(site="fleet.replica_kill", action="raise",
                                step=k, match=r"^1$")]))
            t = ChaosTransport(seed=100 + k, drop_p=0.05, dup_p=0.2,
                               delay_p=0.1, max_delay_steps=2,
                               reorder=True)
            t.partition("router", "replica:2", two_way=True, start=1)
            engines = [_mk_engine(model) for _ in range(3)]
            router = FleetRouter(engines, transport=t, lease_steps=4)
            rids = [router.submit(p, max_new) for p in prompts]
            events = []
            guard = 0
            while router.has_work():
                events.extend(router.step())
                guard += 1
                assert guard < 1000, f"router hang (kill step {k})"
            t.heal()
            events.extend(router.step())      # flush zombie traffic
            seen = _collect_tokens(events)
            for rid, ref in zip(rids, refs):
                rec = router.request(rid)
                assert rec.finished
                assert rec.tokens == ref, f"kill step {k}, {rid}"
                assert seen.get(rid, []) == rec.tokens   # exactly-once
            st = router.stats()
            assert st["replicas_ejected"] == 2
            dead = {h["dead_reason"] for h in st["replica_health"]
                    if h["state"] == DEAD}
            assert dead == {"killed", "lease_expired"}
            for h in st["replica_health"]:
                if h["state"] != DEAD:
                    eng = router.engines[h["replica"]]
                    counts = eng.step_program_counts()
                    assert all(v == 1 for v in counts.values()), counts
                    assert eng.decode_program_count() == 1
                    eng.audit_pool()
            fault.deactivate()
