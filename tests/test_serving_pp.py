"""paddle_tpu.serving.parallel — pipeline-parallel (pp x mp) serving.

The PP contracts (SERVING.md "Pipeline-parallel serving"):

1. BITWISE ACROSS DEGREES — ``ServingEngine(pp=2, tp=2)`` emits
   streams bitwise identical to the tp-only engine and to
   ``model.generate()``, composed with prefix caching, int8 KV,
   speculation and chunked prefill: staging the decoder along the
   stacked-layer axis changes WHERE layers run, never WHAT they
   compute (stage handoff is a ppermute of exact activations; sampling
   stays replicated after the final-stage logits gather, so
   ``fold_in(key, token_index)`` is untouched).
2. TWO PROGRAMS, ANY DEGREE — the ``[max_slots]`` decode step and the
   ``[max_slots, chunk]`` mixed step each stay ONE ``jit(shard_map)``
   over the full pp x mp mesh; ``step_program_counts()`` stays
   ``{"decode": 1, "mixed": 1}`` under churn. The jaxpr audit pins the
   wire: per stage, ``2 * L/pp + 1`` mp-psums, ONE pp ring (static
   ppermute 1, trips ``waves + pp - 1``), ONE pp-psum (ring close),
   ONE logits all_gather.
3. PORTABLE SNAPSHOTS — the stacked pool's host payloads keep the
   per-layer k-then-v order, so a pp=2 snapshot restores into a tp-only
   engine (and vice versa) bitwise; meta records ``pp``.
4. TYPED REJECTION — a decoder that doesn't carve into equal stages
   (``num_hidden_layers % pp != 0``) raises :class:`TPConfigError` at
   construction, not a shape crash inside the compiled step.

The suite runs on CPU: tests/conftest.py forces
``--xla_force_host_platform_device_count=8``, so pp=2 x tp=2, pp=4 and
a 2-replica pp=2 x tp=2 fleet all fit. Chaos tests carry the
``faults`` marker; heavy compile matrices are ``slow``.
"""

from types import SimpleNamespace

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.distributed import fault
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM, llama_tiny
from paddle_tpu.observability import render_prometheus
from paddle_tpu.serving import (FleetRouter, ServingEngine, TPConfigError,
                                collective_counts, partition_devices,
                                validate_tp_config)

RNG = np.random.default_rng(43)


@pytest.fixture(scope="module")
def model():
    pt.seed(123)
    m = LlamaForCausalLM(llama_tiny(dtype="float32",
                                    mp_axis="mp", fsdp_axis=None))
    m.eval()
    return m


@pytest.fixture(scope="module")
def model_l4():
    """pp=4 needs num_hidden_layers % 4 == 0 (llama_tiny has 2)."""
    pt.seed(123)
    cfg = LlamaConfig(vocab_size=512, hidden_size=128,
                      intermediate_size=384, num_hidden_layers=4,
                      num_attention_heads=4, num_key_value_heads=2,
                      max_position_embeddings=512, dtype="float32",
                      mp_axis="mp", fsdp_axis=None)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture
def fault_free(monkeypatch):
    """No FaultPlan leaks out of a chaos test; no rank env leaks in."""
    fault.deactivate()
    monkeypatch.delenv("PADDLE_TRAINER_ID", raising=False)
    monkeypatch.delenv("PROCESS_ID", raising=False)
    monkeypatch.delenv("PADDLE_RESTART_EPOCH", raising=False)
    yield
    fault.deactivate()


def _reference(model, prompt, max_new, **kw):
    out = model.generate(jnp.asarray([prompt]), max_new_tokens=max_new, **kw)
    return np.asarray(out)[0, len(prompt):].tolist()


def _mk(model, tp=1, pp=1, **kw):
    cfg = dict(num_pages=64, page_size=8, max_slots=4)
    cfg.update(kw)
    return ServingEngine(model, tp=tp, pp=pp, **cfg)


def _prompts(n=3, lo=4, hi=14):
    return [RNG.integers(1, 500, size=int(RNG.integers(lo, hi))).tolist()
            for _ in range(n)]


def _serve(model, tp, pp, prompts, max_new=8, **kw):
    eng = _mk(model, tp=tp, pp=pp, **kw)
    rids = [eng.add_request(p, max_new, eos_token_id=None) for p in prompts]
    out = eng.run_to_completion(max_steps=400)
    assert eng.step_program_counts() == {"decode": 1, "mixed": 1}
    eng.audit_pool()
    return [out[r] for r in rids], eng


# ---------------------------------------------------------------------------
# typed construction-time rejection + 2-D device carving
# ---------------------------------------------------------------------------

class TestPPValidation:
    def test_layers_not_divisible(self, model, fault_free):
        with pytest.raises(TPConfigError, match="num_hidden_layers"):
            _mk(model, tp=1, pp=3)      # llama_tiny: L=2, 2 % 3 != 0

    def test_pp_zero_rejected(self):
        with pytest.raises(TPConfigError, match=">= 1"):
            validate_tp_config(SimpleNamespace(), 1, 0)

    def test_pp_one_skips_layer_check(self):
        validate_tp_config(SimpleNamespace(num_hidden_layers=3), 1, 1)

    def test_model_without_pp_parts_rejected(self, fault_free):
        from paddle_tpu.serving.parallel import TPContext
        bare = SimpleNamespace(
            config=SimpleNamespace(num_hidden_layers=2),
            spec_dict=lambda: {}, state_dict=lambda: {})
        with pytest.raises(TPConfigError, match="pp_parts"):
            TPContext(bare, 1, pp=2)

    def test_partition_devices_2d_disjoint(self):
        groups = partition_devices(2, 2, 2)      # 2 replicas of pp2 x tp2
        assert len(groups) == 2 and all(len(g) == 4 for g in groups)
        assert len({d.id for g in groups for d in g}) == 8

    def test_partition_devices_2d_too_few(self):
        with pytest.raises(TPConfigError, match="host_platform_device_count"):
            partition_devices(4, 2, 2)           # 16 > 8 fake devices

    def test_partition_devices_back_compat_2arg(self):
        """The original (n, tp) form still means n groups of tp."""
        groups = partition_devices(2, 2)
        assert all(len(g) == 2 for g in groups)

    def test_too_few_devices_for_engine(self, model, fault_free):
        import jax
        with pytest.raises(TPConfigError, match="host_platform_device_count"):
            # pp=2 x tp=2 needs 4 devices; hand the engine only 2
            _mk(model, tp=2, pp=2, tp_devices=jax.devices()[:2])


# ---------------------------------------------------------------------------
# bitwise parity across pp degrees x feature compositions
# ---------------------------------------------------------------------------

class TestPPParity:
    def test_pp2_tp2_matches_tp_only_and_generate(self, model, fault_free):
        prompts = _prompts()
        a, _ = _serve(model, 1, 1, prompts)
        b, _ = _serve(model, 2, 2, prompts)
        c, _ = _serve(model, 1, 2, prompts)
        assert a == b == c
        assert a[0] == _reference(model, prompts[0], 8, eos_token_id=None)

    def test_pp2_unwaved_bitwise(self, model, fault_free):
        """Microbatching is a schedule change, not a math change: waved
        and unwaved mixed steps emit identical streams."""
        prompts = _prompts(lo=10, hi=20)
        a, _ = _serve(model, 1, 1, prompts)
        b, eng = _serve(model, 2, 2, prompts, pp_microbatch=False)
        assert a == b
        assert eng._pp_waves == 1

    def test_pp2_prefix_reuse_bitwise(self, model, fault_free):
        base = RNG.integers(1, 500, size=16).tolist()
        prompts = [base + [7, 8], base + [9, 10, 11]]

        def sequential(tp, pp):
            eng = _mk(model, tp=tp, pp=pp)
            streams = []
            for p in prompts:         # 2nd admission sees 1st's pages
                rid = eng.add_request(p, 8, eos_token_id=None)
                streams.append(eng.run_to_completion(max_steps=200)[rid])
            return streams, eng

        a, _ = sequential(1, 1)
        b, eng = sequential(2, 2)
        assert a == b
        assert eng.pool.counters["prefix_hits"] >= 1
        assert eng.step_program_counts() == {"decode": 1, "mixed": 1}

    def test_pp2_int8_kv_bitwise(self, model, fault_free):
        prompts = _prompts()
        a, _ = _serve(model, 1, 1, prompts, kv_quant=True)
        b, eng = _serve(model, 2, 2, prompts, kv_quant=True)
        assert a == b
        assert eng.pool.stats()["pp_degree"] == 2

    @pytest.mark.slow
    def test_pp2_speculative_bitwise(self, model, fault_free):
        prompts = _prompts()
        a, _ = _serve(model, 1, 1, prompts, speculative=2)
        b, _ = _serve(model, 2, 2, prompts, speculative=2)
        assert a == b

    @pytest.mark.slow
    def test_pp2_chunked_prefill_bitwise(self, model, fault_free):
        prompts = _prompts(lo=10, hi=20)
        a, _ = _serve(model, 1, 1, prompts, chunked=True, prefill_chunk=4)
        b, _ = _serve(model, 2, 2, prompts, chunked=True, prefill_chunk=4)
        assert a == b

    @pytest.mark.slow
    def test_pp4_matches_unstaged(self, model_l4, fault_free):
        prompts = _prompts()
        a, _ = _serve(model_l4, 1, 1, prompts)
        b, _ = _serve(model_l4, 1, 4, prompts)
        assert a == b


# ---------------------------------------------------------------------------
# program counts, collectives, observability
# ---------------------------------------------------------------------------

class TestPPPrograms:
    def test_counts_pinned_over_churn_epochs(self, model, fault_free):
        """3 admission waves through one pp=2 x tp=2 engine: churn
        changes array values, never shapes — and under pp, never the
        stage layout."""
        eng = _mk(model, tp=2, pp=2)
        for epoch in range(3):
            rids = [eng.add_request(p, 6, eos_token_id=None)
                    for p in _prompts(n=4)]
            out = eng.run_to_completion(max_steps=400)
            assert all(len(out[r]) == 6 for r in rids)
            assert eng.step_program_counts() == {"decode": 1, "mixed": 1}, \
                f"retraced in epoch {epoch}"
        eng.audit_pool()

    def test_collective_budget_per_stage(self, model, fault_free):
        """Each stage runs ``2 * L/pp + 1`` mp-psums (two per local
        layer block plus the vocab-parallel embed), ONE pp ring close
        psum, ONE logits all_gather, and ONE static ppermute whose trip
        count is the ring length ``waves + pp - 1`` (== pp for decode's
        single wave)."""
        eng = _mk(model, tp=2, pp=2)
        L, pp, W = model.config.num_hidden_layers, 2, eng._pp_waves
        S, M = eng.max_slots, eng.max_pages_per_slot
        z = lambda *s: jnp.zeros(s, jnp.int32)         # noqa: E731
        o = lambda *s: jnp.ones(s, jnp.float32)        # noqa: E731
        decode_args = (eng._state, eng.pool.pools, z(S), z(S, M), z(S),
                       jnp.zeros((S,), bool), o(S), o(S),
                       jnp.ones((S,), bool), z(S), z(S))
        K = eng._chunk
        mixed_args = (eng._state, eng.pool.pools, z(S, K), z(S, M), z(S),
                      jnp.zeros((S,), bool), z(S), jnp.zeros((S,), bool),
                      o(S), o(S), jnp.ones((S,), bool), z(S), z(S))
        for waves, step, args in ((1, eng._decode_step, decode_args),
                                  (W, eng._mixed_step, mixed_args)):
            c = collective_counts(step._tp_inner, *args)
            assert c.get("psum[mp]", 0) == 2 * (L // pp) + 1, c
            assert c.get("psum[pp]", 0) == 1, c
            assert c.get("ppermute", 0) == 1, c
            assert c.get("ppermute_trips[pp]", 0) == waves + pp - 1, c
            assert c.get("all_gather", 0) == 1, c
            assert c.get("all_to_all", 0) == 0, c

    def test_pp_observability_surface(self, model, fault_free):
        eng = _mk(model, tp=2, pp=2)
        eng.add_request(_prompts(n=1)[0], 4, eos_token_id=None)
        eng.run_to_completion(max_steps=200)
        st = eng.pool.stats()
        assert st["pp_degree"] == 2
        assert st["pp_stage_layers"] == model.config.num_hidden_layers // 2
        assert st["tp_shard_kv_bytes_per_token"] \
            == eng.pool.kv_bytes_per_token() // 4      # tp2 x pp2
        s = eng.stats()
        assert s["pp"] == 2 and s["pp_waves"] == 2
        assert s["pipeline_bubble_frac"] == pytest.approx(1 / 3)
        ms = eng.metrics.summary()
        assert ms["pp_degree"] == 2 and ms["pp_waves"] == 2
        assert ms["pipeline_bubble_frac"] == pytest.approx(1 / 3)
        page = render_prometheus(ms, st, eng.tracer.counters)
        assert "paddle_serving_pp_degree 2" in page
        assert "paddle_serving_pool_pp_stage_layers" in page

    def test_bubble_frac_waved_below_unwaved(self, model, fault_free):
        """The whole point of microbatching: (pp-1)/(W+pp-1) < (pp-1)/pp."""
        waved = _mk(model, tp=1, pp=2)
        unwaved = _mk(model, tp=1, pp=2, pp_microbatch=False)
        assert waved.pipeline_bubble_frac() \
            < unwaved.pipeline_bubble_frac() == 0.5
        assert _mk(model).pipeline_bubble_frac() == 0.0

    def test_pp1_has_no_pp_machinery(self, model, fault_free):
        eng = _mk(model, tp=1, pp=1)
        assert eng._tp is None
        assert eng.pool.stats()["pp_degree"] == 1
        assert not eng.pool.stacked
        assert eng.metrics.summary()["pp_degree"] == 1
        assert eng.metrics.summary()["pipeline_bubble_frac"] == 0.0


# ---------------------------------------------------------------------------
# snapshot portability across pp degrees
# ---------------------------------------------------------------------------

class TestPPSnapshotPortability:
    def _partial(self, model, tmp_path, tp, pp, steps=6, **kw):
        prompts = [RNG.integers(1, 500, size=7).tolist(),
                   RNG.integers(1, 500, size=5).tolist()]
        eng = _mk(model, tp=tp, pp=pp, **kw)
        rids = [eng.add_request(p, 10, eos_token_id=None) for p in prompts]
        for _ in range(steps):
            eng.step()
        path = str(tmp_path / "snap")
        eng.save_snapshot(path)
        return eng, rids, path

    def test_pp2_snapshot_restores_into_tp1(self, model, tmp_path,
                                            fault_free):
        """The stacked pool's host payloads keep the per-layer k-then-v
        order — a pp=2 snapshot is just bytes an unstaged engine can
        re-place per layer."""
        eng, rids, path = self._partial(model, tmp_path, tp=1, pp=2)
        warm = _mk(model, tp=1, pp=1)
        assert warm.restore(path) == rids
        out = warm.run_to_completion(max_steps=100)
        cont = eng.run_to_completion(max_steps=100)
        for r in rids:
            assert out[r] == cont[r]
        assert warm.metrics.counters["snapshot_restore_corrupt"] == 0
        warm.audit_pool()
        eng.audit_pool()

    @pytest.mark.slow
    def test_tp1_snapshot_restores_into_pp2(self, model, tmp_path,
                                            fault_free):
        eng, rids, path = self._partial(model, tmp_path, tp=1, pp=1)
        warm = _mk(model, tp=1, pp=2)
        assert warm.restore(path) == rids
        out = warm.run_to_completion(max_steps=100)
        cont = eng.run_to_completion(max_steps=100)
        for r in rids:
            assert out[r] == cont[r]
        counts = warm.step_program_counts()
        assert counts["decode"] == 1 and counts["mixed"] <= 1
        warm.audit_pool()

    def test_snapshot_meta_records_pp(self, model, tmp_path, fault_free):
        from paddle_tpu.serving import load_engine_snapshot
        _, _, path = self._partial(model, tmp_path, tp=2, pp=2)
        _, meta = load_engine_snapshot(path)
        assert meta["pp"] == 2 and meta["tp"] == 2


# ---------------------------------------------------------------------------
# chaos: a fleet replica IS a pp x tp group
# ---------------------------------------------------------------------------

@pytest.mark.faults
@pytest.mark.slow
class TestPPFleetChaos:
    def test_kill_pp_replica_midstream_replays_bitwise(self, model,
                                                       fault_free):
        """2 replicas x (pp=2 x tp=2) on 8 disjoint devices: a
        permanent alloc storm pinned to replica 0 ejects the whole
        staged group mid-stream; its requests replay on the survivor
        bitwise (snapshot-seeded or from scratch — same tokens either
        way), the survivor's two programs stay pinned and its stacked
        pool audits clean."""
        groups = partition_devices(2, 2, 2)
        engines = [_mk(model, tp=2, pp=2, tp_devices=g) for g in groups]
        assert all(e.tp == 2 and e.pp == 2 for e in engines)
        router = FleetRouter(engines, max_queue_depth=64)
        prompts = _prompts(n=6, lo=4, hi=8)
        refs = [_reference(model, p, 6, eos_token_id=None) for p in prompts]
        fault.activate(fault.FaultPlan([
            fault.FaultSpec(site="serving.alloc", action="raise",
                            once=False, match=r"^0$"),
        ]))
        rids = [router.submit(p, 6, eos_token_id=None) for p in prompts]
        while router.has_work():
            router.step()
            assert router.stats()["steps"] < 2000, "router hang"
        for rid, ref in zip(rids, refs):
            rec = router.request(rid)
            assert rec.finished
            assert rec.finish_reason in ("stop", "length")
            assert rec.tokens == ref        # replay is bitwise
        st = router.stats()
        for h in st["replica_health"]:
            assert h["pp_degree"] == 2 and h["tp_degree"] == 2
            if h["state"] != "dead":
                eng = router.engines[h["replica"]]
                assert eng.step_program_counts() == {"decode": 1,
                                                     "mixed": 1}
                eng.audit_pool()
