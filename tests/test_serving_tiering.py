"""paddle_tpu.serving.tiering — host-RAM KV spill tier + traffic harness.

The tiering contracts (SERVING.md "KV tiering & traffic harness"):

1. BITWISE RESTORE — a page that round-trips HBM -> host -> HBM carries
   exactly the bytes it spilled with, for fp32, bf16 AND int8 (codes
   and scales together); engine streams with tiering on are bitwise
   identical to ``model.generate()`` even when every shared prefix was
   served through a restore.
2. NEVER WRONG KV — a corrupted host payload (bit rot or the
   ``serving.restore`` fault site's ``poison``) is detected by the
   blake2b re-verify and falls back to recompute; quarantined pages
   never spill and quarantine purges their host entries.
3. NO NEW PROGRAMS — restores are host-side ``device_put``s at
   admission time; ``decode_program_count() == 1`` holds through spill/
   restore churn exactly as without a tier.
4. DETERMINISTIC TRAFFIC — a :class:`Workload` is a value: same seed,
   same trace, so A/B arms (tier off vs on) see identical arrivals and
   their deltas are attributable to the tier alone.

Chaos tests (deterministic FaultPlan replays) carry the ``faults``
marker, same as the serving/fleet suites.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.distributed import fault
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.observability import render_prometheus
from paddle_tpu.serving import (FleetRouter, HostTier, KVCachePool,
                                ServingEngine, ServingMetrics, Workload,
                                WorkloadRequest, WorkloadSpec, make_workload)

RNG = np.random.default_rng(23)


@pytest.fixture(scope="module")
def model():
    pt.seed(123)
    m = LlamaForCausalLM(llama_tiny(dtype="float32",
                                    mp_axis=None, fsdp_axis=None))
    m.eval()
    return m


@pytest.fixture
def fault_free(monkeypatch):
    """No FaultPlan leaks out of a chaos test; no rank env leaks in."""
    fault.deactivate()
    monkeypatch.delenv("PADDLE_TRAINER_ID", raising=False)
    monkeypatch.delenv("PROCESS_ID", raising=False)
    monkeypatch.delenv("PADDLE_RESTART_EPOCH", raising=False)
    yield
    fault.deactivate()


def _reference(model, prompt, max_new, **kw):
    out = model.generate(jnp.asarray([prompt]), max_new_tokens=max_new, **kw)
    return np.asarray(out)[0, len(prompt):].tolist()


def _fill_pages(pool, pages, seed=0):
    """Write deterministic random content into ``pages`` of every layer
    (codes AND scales in quantized mode) so spill/restore has real bytes
    to round-trip."""
    rng = np.random.default_rng(seed)
    idx = jnp.asarray(pages)
    for li, (pk, pv) in enumerate(pool.pools):
        pair = []
        for arr in (pk, pv):
            if hasattr(arr, "q"):      # QuantizedKV
                q = rng.integers(-127, 128,
                                 size=(len(pages),) + arr.q.shape[1:])
                s = rng.random((len(pages),) + arr.scale.shape[1:]) + 0.5
                arr = type(arr)(
                    arr.q.at[idx].set(jnp.asarray(q, arr.q.dtype)),
                    arr.scale.at[idx].set(jnp.asarray(s, arr.scale.dtype)))
            else:
                v = rng.standard_normal((len(pages),) + arr.shape[1:])
                arr = arr.at[idx].set(jnp.asarray(v, arr.dtype))
            pair.append(arr)
        pool.pools[li] = tuple(pair)


def _payloads(pool, pages):
    return [pool._page_payload(p) for p in pages]


def _assert_payloads_equal(a, b):
    assert len(a) == len(b)
    for xs, ys in zip(a, b):
        assert len(xs) == len(ys)
        for x, y in zip(xs, ys):
            assert x.dtype == y.dtype and x.shape == y.shape
            assert np.array_equal(np.asarray(x), np.asarray(y))


def _mk_pool(dtype="float32", **kw):
    cfg = dict(num_layers=2, num_pages=6, page_size=4, num_kv_heads=2,
               head_dim=8, host_tier=HostTier())
    if dtype == "int8":
        cfg["quantized"] = True
    else:
        cfg["dtype"] = jnp.dtype(dtype)
    cfg.update(kw)
    return KVCachePool(**cfg)


def _cache_two_pages(pool, tokens, seed=1):
    """Alloc+fill+register+release two full pages of ``tokens`` so they
    sit refcount-0 in the HBM LRU, ready to be evicted (and spilled)."""
    pages = pool.alloc(2)
    _fill_pages(pool, pages, seed=seed)
    pool.register_prefix(tokens, pages)
    before = _payloads(pool, pages)
    pool.release(pages)
    return pages, before


# ---------------------------------------------------------------------------
# HostTier: the bounded host-RAM LRU itself (pure numpy, no model)
# ---------------------------------------------------------------------------

class TestHostTier:
    def _page(self, seed=0, n=64):
        rng = np.random.default_rng(seed)
        return [rng.standard_normal(n).astype(np.float32),
                rng.integers(-127, 128, n).astype(np.int8)]

    def test_put_fetch_roundtrip_bitwise(self):
        tier = HostTier(max_bytes=1 << 20)
        arrays = self._page(0)
        assert tier.put("float32", "full", b"k1", arrays)
        got = tier.fetch("float32", "full", b"k1")
        for a, b in zip(arrays, got):
            assert np.array_equal(a, b) and a.dtype == b.dtype
        assert tier.counters["host_hits"] == 1
        assert tier.pool_bytes == sum(a.nbytes for a in arrays)

    def test_miss_counts(self):
        tier = HostTier()
        assert tier.fetch("float32", "full", b"nope") is None
        assert tier.counters["host_misses"] == 1

    def test_lru_eviction_under_byte_budget(self):
        one = sum(a.nbytes for a in self._page(0))
        tier = HostTier(max_bytes=2 * one)
        tier.put("float32", "full", b"a", self._page(1))
        tier.put("float32", "full", b"b", self._page(2))
        tier.fetch("float32", "full", b"a")     # refresh a's recency
        tier.put("float32", "full", b"c", self._page(3))
        assert not tier.has("float32", "full", b"b")   # LRU victim
        assert tier.has("float32", "full", b"a")
        assert tier.has("float32", "full", b"c")
        assert tier.counters["host_evictions"] == 1
        assert tier.pool_bytes <= tier.max_bytes

    def test_oversized_payload_refused_not_flushed(self):
        tier = HostTier(max_bytes=128)
        tier.put("float32", "full", b"a",
                 [np.zeros(16, np.float32)])            # 64 bytes, fits
        big = [np.zeros(64, np.float32)]                # 256 > budget
        assert not tier.put("float32", "full", b"b", big)
        assert tier.counters["spill_dropped"] == 1
        assert tier.has("float32", "full", b"a")        # not flushed for it

    def test_corrupt_detected_dropped_counted(self):
        tier = HostTier()
        tier.put("float32", "full", b"k", self._page(4))
        tier.corrupt("float32", "full", b"k")
        assert tier.fetch("float32", "full", b"k") is None
        assert tier.counters["restore_corrupt_detected"] == 1
        assert not tier.has("float32", "full", b"k")    # entry dropped
        # bytes accounting survives the drop
        assert tier.pool_bytes == 0

    def test_dtype_tag_namespacing(self):
        tier = HostTier()
        tier.put("float32", "full", b"k", self._page(5))
        assert not tier.has("int8", "full", b"k")
        assert not tier.has("bfloat16", "full", b"k")
        assert tier.fetch("int8", "full", b"k") is None

    def test_discard_and_restore_charge(self):
        tier = HostTier(restore_budget_frac=0.25)
        tier.put("float32", "partial", b"k", self._page(6))
        assert tier.discard("float32", "partial", b"k")
        assert not tier.discard("float32", "partial", b"k")
        assert tier.pool_bytes == 0
        assert tier.restore_charge(16) == 4
        assert tier.restore_charge(1) == 1      # ceil
        assert tier.restore_charge(0) == 0

    def test_zero_stats_schema_matches_stats(self):
        tier = HostTier()
        tier.put("float32", "full", b"k", self._page(7))
        assert set(tier.stats()) == set(HostTier.zero_stats())
        assert all(v == 0 for v in HostTier.zero_stats().values())

    def test_bad_config_rejected(self):
        with pytest.raises(ValueError):
            HostTier(max_bytes=0)
        with pytest.raises(ValueError):
            HostTier(restore_budget_frac=-0.1)


# ---------------------------------------------------------------------------
# Pool-level spill -> evict -> match(chain) -> restore
# ---------------------------------------------------------------------------

class TestPoolSpillRestore:
    @pytest.mark.parametrize("dtype", ["float32", "bfloat16", "int8"])
    def test_spill_restore_roundtrip_bitwise(self, dtype):
        pool = _mk_pool(dtype)
        tokens = list(range(10, 18))                   # 2 full pages
        pages, before = _cache_two_pages(pool, tokens)
        hold = pool.alloc(5)                           # evicts+spills both
        assert pool.host_tier.counters["spilled_pages"] == 2
        m = pool.match_prefix(tokens)
        assert m.cached_tokens == 0                    # gone from HBM
        assert len(m.chain) == 2 and m.host_tokens == 8
        assert m.total_cached == 8 and m.hit
        pool.free(hold)
        got, restored_tok = pool.restore_chain(m)
        assert len(got) == 2 and restored_tok == 8
        _assert_payloads_equal(before, _payloads(pool, got))
        assert pool.host_tier.counters["restored_pages"] == 2
        # restored pages are registered: a fresh match resolves in HBM
        m2 = pool.match_prefix(tokens)
        assert m2.cached_tokens == 8 and not m2.chain
        pool.release(got)

    def test_partial_page_spills_and_restores_bitwise(self):
        pool = _mk_pool("float32")
        tokens = list(range(30, 36))                   # 1 full + 2 partial
        pages = pool.alloc(2)
        _fill_pages(pool, pages, seed=3)
        pool.register_prefix(tokens, pages)
        before = _payloads(pool, pages)
        pool.release(pages)
        hold = pool.alloc(5)
        m = pool.match_prefix(tokens)
        assert len(m.chain) == 1
        assert m.host_partial_len == 2 and m.host_partial_key is not None
        assert m.total_cached == 6
        pool.free(hold)
        chain_pages, tok = pool.restore_chain(m)
        assert tok == 4
        payload = pool.fetch_host_partial(m)
        assert payload is not None
        dst = pool.alloc(1)[0]
        pool.restore_partial_into(dst, payload)
        _assert_payloads_equal(before, _payloads(pool, chain_pages + [dst]))
        # the partial landed in a PRIVATE page — not re-registered
        assert dst not in pool._page_key

    def test_restore_race_hbm_wins(self):
        """A chain key that is HBM-resident again by restore time is
        acquired, not fetched from host."""
        pool = _mk_pool("float32")
        tokens = list(range(50, 58))
        pages, before = _cache_two_pages(pool, tokens)
        hold = pool.alloc(5)
        m = pool.match_prefix(tokens)
        pool.free(hold)
        first, _ = pool.restore_chain(m)       # re-registers both keys
        hits_before = pool.host_tier.counters["host_hits"]
        again, tok = pool.restore_chain(m)     # same chain, now resident
        assert again == first and tok == 0     # acquired, zero restored
        assert pool.host_tier.counters["host_hits"] == hits_before
        for p in first:
            assert pool.refcount(p) == 2
        pool.release(first)
        pool.release(again)

    def test_quarantine_never_spills_and_purges_host_entry(self):
        pool = _mk_pool("float32")
        tokens = list(range(70, 78))
        pages, _ = _cache_two_pages(pool, tokens)
        # (a) quarantined-while-cached content must not spill later
        pool.quarantine(pages)
        pool.free(pool.alloc(5))               # churn: nothing to spill
        assert pool.host_tier.counters["spilled_pages"] == 0
        assert pool.host_tier.num_entries == 0
        # (b) content both HBM-registered and host-resident: quarantine
        # purges the host copy too
        pages2, _ = _cache_two_pages(pool, tokens, seed=2)
        hold = pool.alloc(5)                   # spill both
        assert pool.host_tier.num_entries == 2
        pool.free(hold)
        m = pool.match_prefix(tokens)
        got, _ = pool.restore_chain(m)         # resident again, same keys
        pool.quarantine(got)
        assert pool.host_tier.num_entries == 0
        pool.release(got)

    def test_shared_quarantined_page_blocked_from_spilling(self):
        pool = _mk_pool("float32")
        tokens = list(range(90, 98))
        pages = pool.alloc(2)
        _fill_pages(pool, pages, seed=5)
        pool.register_prefix(tokens, pages)    # still held (refcount 1)
        pool.quarantine(pages)                 # shared -> scrub-on-zero
        pool.release(pages)                    # scrubbed + freed now
        pool.free(pool.alloc(5))
        assert pool.host_tier.counters["spilled_pages"] == 0

    def test_corrupt_restore_falls_back_to_recompute(self):
        pool = _mk_pool("float32")
        tokens = list(range(110, 118))
        pages, _ = _cache_two_pages(pool, tokens)
        hold = pool.alloc(5)
        m = pool.match_prefix(tokens)
        pool.free(hold)
        # rot the FIRST chain entry in host RAM
        pool.host_tier.corrupt(pool._tier_tag, "full", m.chain[0])
        got, tok = pool.restore_chain(m)
        assert got == [] and tok == 0          # stop at the bad link
        assert pool.host_tier.counters["restore_corrupt_detected"] == 1
        # nothing was registered; the caller recomputes from scratch
        assert pool.match_prefix(tokens).cached_tokens == 0

    def test_no_tier_match_is_unchanged(self):
        pool = _mk_pool("float32", host_tier=None)
        tokens = list(range(130, 138))
        pages = pool.alloc(2)
        _fill_pages(pool, pages, seed=7)
        pool.register_prefix(tokens, pages)
        pool.release(pages)
        m = pool.match_prefix(tokens)
        assert m.cached_tokens == 8 == m.total_cached and not m.chain
        assert pool.restore_charge(m) == 0
        assert pool.stats()["host_tier"] == 0
        assert pool.stats()["host_pool_bytes"] == 0    # schema-stable

    def test_pool_stats_carry_host_breakdown(self):
        pool = _mk_pool("float32")
        tokens = list(range(150, 158))
        _cache_two_pages(pool, tokens)
        pool.free(pool.alloc(5))
        s = pool.stats()
        assert s["host_tier"] == 1
        assert s["spilled_pages"] == 2 and s["host_pool_pages"] == 2
        assert s["host_pool_bytes"] > 0
        # ...and render straight into the Prometheus page
        page = render_prometheus(pool_stats=s)
        assert "paddle_serving_pool_host_pool_bytes" in page
        assert "paddle_serving_pool_spilled_pages 2" in page

    def test_host_tier_int_shorthand_sets_budget(self):
        pool = _mk_pool("float32", host_tier=1 << 16)
        assert pool.host_tier.max_bytes == 1 << 16
        assert _mk_pool("float32", host_tier=True).host_tier is not None


# ---------------------------------------------------------------------------
# Workload: the deterministic traffic generator
# ---------------------------------------------------------------------------

class TestWorkload:
    def test_same_seed_same_trace(self):
        a = make_workload(seed=5, n_requests=24, rate=1.0)
        b = make_workload(seed=5, n_requests=24, rate=1.0)
        assert [(r.rid, r.arrival_step, r.prompt, r.max_new_tokens,
                 r.tenant) for r in a] == \
               [(r.rid, r.arrival_step, r.prompt, r.max_new_tokens,
                 r.tenant) for r in b]
        c = make_workload(seed=6, n_requests=24, rate=1.0)
        assert [r.prompt for r in a] != [r.prompt for r in c]

    def test_bursty_arrivals_respect_the_square_wave(self):
        wl = make_workload(seed=1, n_requests=40, arrival="bursty",
                           rate=0.5, burst_on=4, burst_off=12,
                           burst_factor=6.0, idle_factor=0.0)
        for r in wl:
            assert (r.arrival_step % 16) < 4    # idle windows are silent

    def test_zipf_head_is_hottest(self):
        wl = make_workload(seed=2, n_requests=200, rate=4.0,
                           tenants=4, zipf_alpha=1.5)
        counts = wl.stats()["tenant_counts"]
        assert counts[0] == max(counts) and counts[0] > counts[-1]

    def test_prompts_are_system_prefix_plus_bounded_suffix(self):
        spec = WorkloadSpec(seed=3, n_requests=30, rate=2.0,
                            system_len=(8, 12),
                            prompt_mix=((0.7, 4, 6), (0.3, 10, 16)),
                            max_new=(2, 5), vocab_size=64)
        wl = make_workload(spec)
        assert len(wl.system_prompts) == spec.tenants
        for sp in wl.system_prompts:
            assert 8 <= len(sp) <= 12
        for r in wl:
            sp = wl.system_prompts[r.tenant]
            assert r.prompt[:len(sp)] == sp
            assert 4 <= len(r.prompt) - len(sp) <= 16
            assert 2 <= r.max_new_tokens <= 5
            assert all(0 <= t < 64 for t in r.prompt)

    def test_stats_and_due_are_pure(self):
        wl = make_workload(seed=4, n_requests=10, rate=1.0)
        s = wl.stats()
        assert s["n_requests"] == 10 == len(wl)
        assert sum(s["tenant_counts"]) == 10
        assert sum(len(wl.due(t)) for t in range(wl.horizon + 1)) == 10
        assert wl.due(0) == wl.due(0)           # no cursor side effects

    def test_bad_specs_rejected(self):
        with pytest.raises(ValueError):
            make_workload(arrival="weibull")
        with pytest.raises(ValueError):
            make_workload(tenants=0)
        with pytest.raises(TypeError):
            make_workload(WorkloadSpec(), seed=1)
        with pytest.raises(ValueError):         # rate too low to place
            make_workload(n_requests=2, rate=0.0)

    def test_replay_mechanics_on_scripted_target(self):
        """Arrival-step pacing, shed counting and the drain tripwire,
        without compiling anything."""
        from paddle_tpu.serving.errors import QueueFullError

        class Target:
            def __init__(self, reject=()):
                self.reject = set(reject)
                self.seen = []          # (step, rid)
                self.steps = 0
                self.pending = 0

            def add_request(self, prompt, max_new, eos_token_id=None,
                            rid=None):
                if rid in self.reject:
                    raise QueueFullError("full")
                self.seen.append((self.steps, rid))
                self.pending += 1
                return rid

            def step(self):
                self.steps += 1
                if self.pending and self.steps % 2 == 0:
                    self.pending -= 1

            def has_work(self):
                return self.pending > 0

        wl = make_workload(seed=7, n_requests=6, rate=1.0)
        tgt = Target()
        out = wl.replay(tgt)
        assert out["submitted"] == 6 and out["shed"] == 0
        assert out["rids"] == [r.rid for r in wl.requests]
        for (step, rid), r in zip(tgt.seen, wl.requests):
            assert step == r.arrival_step       # submitted when due
        shed_rid = wl.requests[0].rid
        out2 = wl.replay(Target(reject={shed_rid}))
        assert out2["shed"] == 1 and out2["submitted"] == 5
        with pytest.raises(RuntimeError):       # never drains -> tripwire
            stuck = Target()
            stuck.step = lambda: None           # pending never drains
            wl.replay(stuck, max_steps=5)

    def test_replay_on_real_engine_is_deterministic(self, model):
        wl = make_workload(seed=8, n_requests=3, rate=1.0, tenants=2,
                           system_len=(4, 6), prompt_mix=((1.0, 2, 5),),
                           max_new=(2, 4), vocab_size=128)
        outs = []
        for _ in range(2):
            eng = ServingEngine(model, num_pages=64, page_size=4,
                                max_slots=2)
            res = wl.replay(eng, max_steps=500)
            assert res["submitted"] == 3 and res["shed"] == 0
            outs.append(eng.run_to_completion())    # drained: just collects
        assert outs[0] == outs[1]


# ---------------------------------------------------------------------------
# Engine-level: tiering on, bitwise parity, one program
# ---------------------------------------------------------------------------

def _tenant_prompts(n_requests, system_len=24, suffix_len=6, tenants=2,
                    seed=31):
    """Alternating-tenant prompts sized so ~1.3 tenants fit in HBM:
    returning tenants must restore through the host tier."""
    rng = np.random.default_rng(seed)
    systems = [list(rng.integers(1, 500, system_len))
               for _ in range(tenants)]
    return [systems[i % tenants] + list(rng.integers(1, 500, suffix_len))
            for i in range(n_requests)]


class TestTieredEngine:
    def test_parity_with_real_restores_two_epochs(self, model, fault_free):
        """The acceptance run: serial alternating-tenant traffic through
        a pool that holds ~1.3 tenants, two epochs on ONE engine — every
        stream bitwise equals generate(), real restores happened, and
        the decode program count never moves."""
        prompts = _tenant_prompts(6)
        refs = [_reference(model, p, 6) for p in prompts]
        eng = ServingEngine(model, num_pages=14, page_size=4, max_slots=1,
                            prefill_token_budget=256, host_tier=HostTier())
        for epoch in range(2):
            for p, ref in zip(prompts, refs):
                rid = eng.add_request(p, 6)
                assert eng.run_to_completion(max_steps=100)[rid] == ref
            assert all(v == 1
                       for v in eng.step_program_counts().values()), epoch
        tier = eng.pool.host_tier
        assert tier.counters["restored_pages"] >= 12
        assert tier.counters["spilled_pages"] > 0
        assert eng.decode_program_count() == 1
        assert eng.stats()["host_tier"] is True
        # metrics surface the tier breakdown
        s = eng.metrics.summary()
        assert s["host_tier_enabled"] == 1
        assert s["prefill_restored_tokens"] > 0
        assert s["tier_host_hit_rate"] > 0
        assert s["spilled_bytes"] > 0 and s["restored_bytes"] > 0
        assert abs(s["tier_hbm_hit_rate"] + s["tier_host_hit_rate"]
                   + s["tier_miss_rate"] - 1.0) < 1e-9
        page = render_prometheus(s, eng.pool.stats())
        assert "paddle_serving_tier_host_hit_rate" in page
        assert "paddle_serving_spilled_bytes" in page

    def test_int8_tier_on_equals_tier_off_bitwise(self, model, fault_free):
        """Quantized KV: codes AND scales round-trip the host tier, so
        the tiered int8 engine matches the untiered one token-for-token
        while actually restoring pages."""
        prompts = _tenant_prompts(4, system_len=16, suffix_len=4)
        outs = []
        for tier in (None, HostTier()):
            eng = ServingEngine(model, num_pages=10, page_size=4,
                                max_slots=1, kv_quant=True, host_tier=tier)
            got = []
            for p in prompts:
                rid = eng.add_request(p, 4)
                got.append(eng.run_to_completion(max_steps=100)[rid])
            assert eng.decode_program_count() == 1
            outs.append(got)
        assert outs[0] == outs[1]
        assert eng.pool.host_tier.counters["restored_pages"] > 0
        assert eng.pool._tier_tag == "int8"

    def test_untiered_metrics_keep_tier_schema(self):
        m = ServingMetrics()
        s = m.summary()
        assert s["host_tier_enabled"] == 0
        assert s["spilled_bytes"] == 0 and s["tier_host_hit_rate"] == 0.0
        assert m.tier_hit_rates() == {"hbm": 0.0, "host": 0.0, "miss": 0.0}


# ---------------------------------------------------------------------------
# Chaos: the serving.spill / serving.restore fault sites
# ---------------------------------------------------------------------------

@pytest.mark.faults
class TestTieredChaos:
    def test_spill_storm_means_no_tier_not_wrong_tier(self, model,
                                                      fault_free):
        """Every spill dropped: hit-rate degrades to the untiered pool's
        but parity holds — a lost spill is a miss, never wrong KV."""
        fault.activate(fault.FaultPlan([
            fault.FaultSpec(site="serving.spill", action="raise",
                            once=False),
        ]))
        prompts = _tenant_prompts(4)
        refs = [_reference(model, p, 4) for p in prompts]
        eng = ServingEngine(model, num_pages=14, page_size=4, max_slots=1,
                            host_tier=HostTier())
        for p, ref in zip(prompts, refs):
            rid = eng.add_request(p, 4)
            assert eng.run_to_completion(max_steps=100)[rid] == ref
        tier = eng.pool.host_tier
        assert tier.num_entries == 0            # storm dropped everything
        assert tier.counters["spill_dropped"] > 0
        assert tier.counters["restored_pages"] == 0
        assert eng.decode_program_count() == 1
        eng.audit_pool()

    def test_restore_poison_detected_and_recomputed(self, model,
                                                    fault_free):
        """Every restore poisoned in host RAM: the digest re-verify
        catches each one and the scheduler recomputes — streams stay
        bitwise exact and wrong KV is never served."""
        fault.activate(fault.FaultPlan([
            fault.FaultSpec(site="serving.restore", action="poison",
                            once=False),
        ]))
        prompts = _tenant_prompts(4)
        refs = [_reference(model, p, 4) for p in prompts]
        eng = ServingEngine(model, num_pages=14, page_size=4, max_slots=1,
                            host_tier=HostTier())
        for p, ref in zip(prompts, refs):
            rid = eng.add_request(p, 4)
            assert eng.run_to_completion(max_steps=100)[rid] == ref
        tier = eng.pool.host_tier
        assert tier.counters["restore_corrupt_detected"] > 0
        assert tier.counters["restored_pages"] == 0
        assert eng.decode_program_count() == 1
        eng.audit_pool()

    def test_restore_fault_raise_falls_back(self, model, fault_free):
        """An injected restore failure (raise) on one chain key: those
        tokens recompute, counted as restore_failed, parity intact."""
        fault.activate(fault.FaultPlan([
            fault.FaultSpec(site="serving.restore", action="raise",
                            once=False),
        ]))
        prompts = _tenant_prompts(4)
        refs = [_reference(model, p, 4) for p in prompts]
        eng = ServingEngine(model, num_pages=14, page_size=4, max_slots=1,
                            host_tier=HostTier())
        for p, ref in zip(prompts, refs):
            rid = eng.add_request(p, 4)
            assert eng.run_to_completion(max_steps=100)[rid] == ref
        assert eng.pool.host_tier.counters["restore_failed"] > 0
        assert eng.pool.host_tier.counters["restored_pages"] == 0
        eng.audit_pool()

    def test_fleet_shared_tier_replica_kill_exact_or_classified(
            self, model, fault_free):
        """Two replicas share ONE HostTier (identical weights -> bitwise
        identical KV); a mid-run replica kill must leave every request
        bitwise exact or classified, with the tier active and no hang."""
        tier = HostTier()
        engines = [ServingEngine(model, num_pages=14, page_size=4,
                                 max_slots=1, prefill_token_budget=256,
                                 host_tier=tier) for _ in range(2)]
        router = FleetRouter(engines)
        prompts = _tenant_prompts(6)
        refs = [_reference(model, p, 4) for p in prompts]
        rids = [router.submit(p, 4) for p in prompts]
        for _ in range(3):
            router.step()
        victim = router.request(rids[0]).replica
        router.kill_replica(0 if victim is None else victim)
        out = router.run_to_completion(max_steps=600)   # hang tripwire
        classified = 0
        for rid, ref in zip(rids, refs):
            rec = router.request(rid)
            assert rec.finished
            if rec.finish_reason in ("stop", "length"):
                assert out[rid] == ref
            else:
                classified += 1
        assert classified == 0                  # failover replays exactly
        assert tier.counters["spilled_pages"] > 0
        for eng in engines:
            if eng.stats()["steps"]:
                eng.audit_pool()
