"""Text dataset zoo over fabricated official-layout archives (parity:
python/paddle/text/datasets/ + test/legacy_test/test_datasets.py)."""

import gzip
import io
import os
import tarfile
import zipfile

import numpy as np
import pytest

from paddle_tpu.text import (Conll05st, Imdb, Imikolov, Movielens,
                             UCIHousing, WMT14, WMT16)


def _add(tf, name, data: bytes):
    info = tarfile.TarInfo(name)
    info.size = len(data)
    tf.addfile(info, io.BytesIO(data))


def test_uci_housing_split_and_normalization(tmp_path):
    rng = np.random.default_rng(0)
    rows = rng.uniform(1, 10, (20, 14))
    p = tmp_path / "housing.data"
    p.write_text("\n".join(" ".join(f"{v:.4f}" for v in r) for r in rows))
    train = UCIHousing(data_file=str(p), mode="train")
    test = UCIHousing(data_file=str(p), mode="test")
    assert len(train) == 16 and len(test) == 4
    x, y = train[0]
    assert x.shape == (13,) and y.shape == (1,)
    # features are normalized: |x| bounded by ~1
    assert np.abs(x).max() <= 1.0 + 1e-6
    with pytest.raises(RuntimeError, match="egress"):
        UCIHousing()


def test_imdb_vocab_and_labels(tmp_path):
    p = tmp_path / "aclImdb.tar.gz"
    docs = {
        "aclImdb/train/pos/0.txt": b"great movie great fun",
        "aclImdb/train/neg/0.txt": b"bad movie, bad plot!",
        "aclImdb/test/pos/0.txt": b"great plot",
        "aclImdb/test/neg/0.txt": b"bad fun",
    }
    with tarfile.open(p, "w:gz") as tf:
        for name, data in docs.items():
            _add(tf, name, data)
    ds = Imdb(data_file=str(p), mode="train", cutoff=0)
    assert len(ds) == 2
    # freq order: bad(3) great(3) movie(2) fun(2) plot(2) -> ties by word
    w = ds.word_idx
    assert w["<unk>"] == len(w) - 1
    assert w["bad"] < w["movie"]  # higher freq first
    doc0, label0 = ds[0]
    assert label0[0] == 0  # pos first
    # punctuation stripped: 'movie,' == 'movie'
    ds_ids = {tuple(ds[i][0].tolist()) for i in range(2)}
    assert all(len(d) == 4 for d in ds_ids)


def test_imikolov_ngram_and_seq(tmp_path):
    p = tmp_path / "simple-examples.tgz"
    train = b"a b c\nb c d\n"
    valid = b"a b\n"
    with tarfile.open(p, "w:gz") as tf:
        _add(tf, "./simple-examples/data/ptb.train.txt", train)
        _add(tf, "./simple-examples/data/ptb.valid.txt", valid)
    ng = Imikolov(data_file=str(p), data_type="NGRAM", window_size=2,
                  mode="train", min_word_freq=0)
    # each line '<s> a b c <e>' yields 4 bigrams
    assert len(ng) == 8
    assert ng[0].shape == (2,)
    seq = Imikolov(data_file=str(p), data_type="SEQ", mode="valid",
                   min_word_freq=0)
    src, trg = seq[0]
    assert src[0] == seq.word_idx["<s>"]
    assert trg[-1] == seq.word_idx["<e>"]
    np.testing.assert_array_equal(src[1:], trg[:-1])


def test_movielens_features(tmp_path):
    p = tmp_path / "ml-1m.zip"
    with zipfile.ZipFile(p, "w") as zf:
        zf.writestr("ml-1m/movies.dat",
                    "1::Toy Story (1995)::Animation|Comedy\n"
                    "2::Heat (1995)::Action\n")
        zf.writestr("ml-1m/users.dat",
                    "1::M::25::7::55117\n2::F::35::3::55117\n")
        zf.writestr("ml-1m/ratings.dat",
                    "1::1::5::964982703\n2::2::3::964982224\n"
                    "1::2::4::964982931\n")
    train = Movielens(data_file=str(p), mode="train", test_ratio=0.0)
    assert len(train) == 3
    uid, gender, age, job, mid, cats, title, rating = train[0]
    assert uid[0] in (1, 2) and gender[0] in (0, 1)
    assert rating.dtype.kind == "f"
    assert float(rating[0]) in (3.0, 4.0, 5.0)
    # categories/title map through shared dicts
    assert set(np.asarray(cats).tolist()) <= set(
        train.categories_dict.values())
    test = Movielens(data_file=str(p), mode="test", test_ratio=0.0)
    assert len(test) == 0


def test_conll05st_bio_expansion_and_features(tmp_path):
    words = b"The\ncat\nsat\n\n"
    # one predicate column: (A0*) * (V*) -> B-A0 O B-V
    props = b"-\t(A0*)\n-\t*\nsat\t(V*)\n\n"
    p = tmp_path / "conll05st-tests.tar.gz"
    wbuf = gzip.compress(words)
    pbuf = gzip.compress(props)
    with tarfile.open(p, "w:gz") as tf:
        _add(tf, "conll05st-release/test.wsj/words/test.wsj.words.gz", wbuf)
        _add(tf, "conll05st-release/test.wsj/props/test.wsj.props.gz", pbuf)
    wd = tmp_path / "words.dict"
    wd.write_text("The\ncat\nsat\nbos\neos\n")
    vd = tmp_path / "verbs.dict"
    vd.write_text("sat\n")
    td = tmp_path / "targets.dict"
    td.write_text("A0\nV\n")
    ds = Conll05st(data_file=str(p), word_dict_file=str(wd),
                   verb_dict_file=str(vd), target_dict_file=str(td))
    assert len(ds) == 1
    (w, n2, n1, c0, p1, p2, pred, mark, labels) = ds[0]
    assert w.tolist() == [0, 1, 2]
    assert labels.tolist() == [ds.label_dict["B-A0"], ds.label_dict["O"],
                               ds.label_dict["B-V"]]
    assert mark.tolist() == [1, 1, 1]  # all within +-2 of the verb
    assert (pred == ds.predicate_dict["sat"]).all()
    # ctx windows: verb at index 2 -> p1/p2 fall off the end = 'eos'
    assert (p1 == ds.word_dict["eos"]).all()


def _wmt14_archive(tmp_path):
    p = tmp_path / "wmt14.tgz"
    with tarfile.open(p, "w:gz") as tf:
        _add(tf, "wmt14/src.dict", b"<s>\n<e>\n<unk>\nhello\nworld\n")
        _add(tf, "wmt14/trg.dict", b"<s>\n<e>\n<unk>\nbonjour\nmonde\n")
        _add(tf, "wmt14/train/train",
             b"hello world\tbonjour monde\nhello\tbonjour\n")
        _add(tf, "wmt14/test/test", b"world\tmonde\n")
    return p


def test_wmt14_ids_and_teacher_forcing(tmp_path):
    p = _wmt14_archive(tmp_path)
    ds = WMT14(data_file=str(p), mode="train", dict_size=5)
    assert len(ds) == 2
    src, trg, trg_next = ds[0]
    sd, td = ds.get_dict()
    assert src.tolist() == [sd["<s>"], sd["hello"], sd["world"], sd["<e>"]]
    assert trg.tolist() == [td["<s>"], td["bonjour"], td["monde"]]
    assert trg_next.tolist() == [td["bonjour"], td["monde"], td["<e>"]]
    rev, _ = ds.get_dict(reverse=True)
    assert rev[sd["hello"]] == "hello"
    test = WMT14(data_file=str(p), mode="test", dict_size=5)
    assert len(test) == 1


def test_wmt16_builds_vocab_from_train(tmp_path):
    p = tmp_path / "wmt16.tar.gz"
    with tarfile.open(p, "w:gz") as tf:
        _add(tf, "wmt16/train", b"good day\tguten tag\nday\ttag\n")
        _add(tf, "wmt16/val", b"good\tguten\n")
    ds = WMT16(data_file=str(p), mode="val", src_dict_size=10,
               trg_dict_size=10, lang="en")
    assert len(ds) == 1
    src, trg, trg_next = ds[0]
    d = ds.get_dict("en")
    assert src.tolist() == [0, d["good"], 1]  # <s> good <e>
    assert trg_next[-1] == 1  # <e>
    # de-side vocab came from column 1
    assert "guten" in ds.get_dict("de")
    # frequency order: 'day'(2) before 'good'(1) in the en dict
    assert d["day"] < d["good"]
