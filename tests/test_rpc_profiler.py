"""RPC agent + profiler scheduler/statistics (parity:
python/paddle/distributed/rpc tests; profiler scheduler windows,
profiler.py:346)."""

import time

import numpy as np
import pytest


def _add(a, b):
    return a + b


def _boom():
    raise ValueError("remote failure")


def test_rpc_sync_async_roundtrip():
    from paddle_tpu.distributed import rpc
    me = rpc.init_rpc("worker0",
                      workers=["worker0:127.0.0.1:29551",
                               "worker1:127.0.0.1:29552"])
    try:
        # second "worker" in the same process (separate server socket)
        import threading
        from paddle_tpu.distributed.rpc import _Handler, _Server
        srv = _Server(("127.0.0.1", 29552), _Handler)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            assert rpc.rpc_sync("worker1", _add, args=(2, 3)) == 5
            fut = rpc.rpc_async("worker1", _add, args=(np.ones(4), 1.0))
            np.testing.assert_allclose(fut.result(), 2 * np.ones(4))
            with pytest.raises(ValueError, match="remote failure"):
                rpc.rpc_sync("worker1", _boom)
            infos = rpc.get_all_worker_infos()
            assert {w.name for w in infos} == {"worker0", "worker1"}
            assert rpc.get_worker_info().name == "worker0"
        finally:
            srv.shutdown()
            srv.server_close()
    finally:
        rpc.shutdown()


def test_profiler_scheduler_state_machine():
    from paddle_tpu.profiler import ProfilerState, make_scheduler
    sched = make_scheduler(closed=2, ready=1, record=2, repeat=1,
                           skip_first=1)
    states = [sched(i) for i in range(8)]
    S = ProfilerState
    assert states[0] == S.CLOSED            # skip_first
    assert states[1:3] == [S.CLOSED, S.CLOSED]
    assert states[3] == S.READY
    assert states[4] == S.RECORD
    assert states[5] == S.RECORD_AND_RETURN
    assert states[6] == S.CLOSED            # repeat=1 exhausted
    assert states[7] == S.CLOSED


def test_profiler_event_statistics():
    import paddle_tpu.profiler as profiler
    prof = profiler.Profiler(timer_only=True)
    prof.start()
    for _ in range(3):
        with profiler.RecordEvent("my_op"):
            time.sleep(0.01)
        prof.step()
    stats = prof.event_stats()
    prof.stop()
    assert stats["my_op"]["calls"] == 3
    assert stats["my_op"]["avg_ms"] >= 8
    text = prof.summary()
    assert "my_op" in text and "avg step" in text


def test_profiler_trace_windows_timer_only():
    import paddle_tpu.profiler as profiler
    sched = profiler.make_scheduler(closed=1, ready=0, record=1)
    prof = profiler.Profiler(timer_only=True, scheduler=sched)
    prof.start()
    for _ in range(4):
        prof.step()
    prof.stop()
    assert prof._step_num == 4
