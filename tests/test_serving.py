"""paddle_tpu.serving — continuous batching over the paged KV pool.

The two contracts that define the subsystem (SERVING.md):

1. DETERMINISM — greedy requests fed through the engine (staggered
   arrivals, shared pool, preempt-and-recompute) produce tokens bitwise
   identical to a standalone per-request ``model.generate()`` (fp32 CPU).
2. NO RETRACE — the decode step is ONE compiled program for the
   engine's lifetime; requests joining/finishing/preempting never change
   its compiled-program count.
3. CLASSIFIED FAILURE — every failure mode is a typed exception at
   admission or a per-request finish_reason at a step boundary, never an
   engine-wide hang; quarantining a poisoned request leaves the
   survivors' token streams bitwise intact ("Serving failure modes",
   SERVING.md). Chaos tests (deterministic FaultPlan replays) carry the
   ``faults`` marker.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.distributed import fault
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.serving import (EngineDrainingError, KVCachePool,
                                PoolExhaustedError, QueueFullError, Request,
                                RequestTooLargeError, SamplingParams,
                                Scheduler, SchedulerStalledError,
                                ServingEngine, ServingError, ServingMetrics,
                                percentile)

RNG = np.random.default_rng(7)


@pytest.fixture(scope="module")
def model():
    pt.seed(123)
    m = LlamaForCausalLM(llama_tiny(dtype="float32",
                                    mp_axis=None, fsdp_axis=None))
    m.eval()
    return m


def _reference(model, prompt, max_new, **kw):
    out = model.generate(jnp.asarray([prompt]), max_new_tokens=max_new, **kw)
    return np.asarray(out)[0, len(prompt):].tolist()


# ---------------------------------------------------------------------------
# KV-cache pool
# ---------------------------------------------------------------------------

class TestKVCachePool:
    def test_shapes_and_reserved_scratch_page(self):
        pool = KVCachePool(num_layers=3, num_pages=8, page_size=4,
                           num_kv_heads=2, head_dim=16)
        assert len(pool.pools) == 3
        assert pool.pools[0][0].shape == (8, 4, 2, 16)
        assert pool.capacity == 7  # page 0 reserved
        got = pool.alloc(7)
        assert 0 not in got

    def test_alloc_all_or_nothing_and_accounting(self):
        pool = KVCachePool(1, 6, 4, 2, 8)
        a = pool.alloc(2)
        assert pool.num_in_use == 2 and pool.num_free == 3
        with pytest.raises(PoolExhaustedError):
            pool.alloc(4)  # only 3 free — must not tear off a partial grab
        assert pool.num_free == 3
        pool.free(a)
        assert pool.num_in_use == 0
        assert pool.utilization() == 0.0
        assert pool.stats()["peak_in_use"] == 2

    def test_free_rejects_scratch_double_and_bogus(self):
        pool = KVCachePool(1, 6, 4, 2, 8)
        pages = pool.alloc(1)
        pool.free(pages)
        with pytest.raises(ValueError, match="double free"):
            pool.free(pages)
        with pytest.raises(ValueError, match="not an allocatable"):
            pool.free([0])
        with pytest.raises(ValueError, match="not an allocatable"):
            pool.free([99])

    def test_pages_for(self):
        pool = KVCachePool(1, 6, 4, 2, 8)
        assert pool.pages_for(1) == 1
        assert pool.pages_for(4) == 1
        assert pool.pages_for(5) == 2
        assert pool.pages_for(0) == 1  # a slot always owns >= 1 page


# ---------------------------------------------------------------------------
# scheduler
# ---------------------------------------------------------------------------

class TestScheduler:
    def _pool(self, pages=16, ps=4):
        return KVCachePool(1, pages, ps, 2, 8)

    def test_fcfs_admission_respects_budget_and_slots(self):
        pool = self._pool()
        sched = Scheduler(max_slots=2, prefill_token_budget=8)
        for i, n in enumerate((4, 6, 3)):
            sched.add(Request(rid=f"r{i}", prompt=list(range(n)),
                              max_new_tokens=4))
        admitted = sched.admit(pool)
        # r0 (4 tokens) fits; r1 (6) exceeds the remaining budget (4) so
        # it waits for the next step — the budget bounds per-step prefill
        assert [r.rid for r in admitted] == ["r0"]
        assert sched.queue_depth == 2
        assert admitted[0].slot is not None and admitted[0].pages
        # next step: r1 goes first (FCFS), r2 again over the leftover budget
        assert [r.rid for r in sched.admit(pool)] == ["r1"]
        assert sched.admit(pool) == []  # both slots now occupied

    def test_no_queue_jumping_when_head_does_not_fit(self):
        pool = self._pool(pages=3, ps=4)  # capacity 2 pages
        sched = Scheduler(max_slots=2, prefill_token_budget=64)
        sched.add(Request(rid="big", prompt=list(range(12)),
                          max_new_tokens=1))  # needs 3 pages > capacity
        sched.add(Request(rid="small", prompt=[1], max_new_tokens=1))
        assert sched.admit(pool) == []  # strict FCFS: small must wait

    def test_preempt_youngest_and_requeue_order(self):
        pool = self._pool(pages=5, ps=4)  # capacity 4
        sched = Scheduler(max_slots=2)
        r0 = Request(rid="r0", prompt=list(range(8)), max_new_tokens=8)
        r1 = Request(rid="r1", prompt=list(range(8)), max_new_tokens=8)
        sched.add(r0)
        sched.add(r1)
        assert len(sched.admit(pool)) == 2  # 2 pages each
        r0.tokens, r1.tokens = [5], [6]
        # growing r0 to a 3rd page must evict r1 (youngest), not r0
        r0.context_len = r1.context_len = 8
        preempted = sched.ensure_decode_pages(pool)
        assert [r.rid for r in preempted] == ["r1"]
        assert r1.state == "preempted" and r1.pages == [] and r1.slot is None
        assert sched.waiting[0].rid == "r1"  # back at its arrival position
        assert len(r0.pages) == 3  # the oldest got its page

    def test_finish_releases_resources(self):
        pool = self._pool()
        sched = Scheduler(max_slots=1)
        r = Request(rid="r", prompt=[1, 2], max_new_tokens=2)
        sched.add(r)
        sched.admit(pool)
        sched.finish(r, pool, "length")
        assert r.done and r.finish_reason == "length"
        assert pool.num_in_use == 0 and not sched.running


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

class TestMetrics:
    def test_percentile(self):
        assert percentile([], 50) == 0.0
        assert percentile([3.0], 99) == 3.0
        assert percentile([1.0, 2.0, 3.0, 4.0], 50) == 2.5
        assert percentile([1.0, 2.0, 3.0, 4.0], 100) == 4.0

    def test_ttft_tpot_itl_with_virtual_clock(self):
        t = [0.0]
        m = ServingMetrics(clock=lambda: t[0])
        m.on_arrival("a")
        t[0] = 1.0
        m.on_token("a")           # TTFT = 1.0
        t[0] = 1.5
        m.on_token("a")           # ITL 0.5
        t[0] = 2.5
        m.on_token("a")           # ITL 1.0
        m.on_finish("a")
        m.on_step(queue_depth=2, pool_utilization=0.5)
        s = m.summary()
        assert s["ttft_p50_s"] == pytest.approx(1.0)
        assert s["tpot_mean_s"] == pytest.approx(0.75)  # (2.5-1.0)/2
        assert s["itl_p50_s"] == pytest.approx(0.75)
        assert s["tokens_generated"] == 3
        assert s["requests_finished"] == 1
        assert s["queue_depth_max"] == 2
        assert s["kv_util_peak"] == 0.5
        assert s["tokens_per_s"] == pytest.approx(3 / 2.5)


# ---------------------------------------------------------------------------
# the engine: determinism + no-retrace contracts
# ---------------------------------------------------------------------------

class TestServingEngine:
    def test_greedy_equivalence_staggered_arrivals(self, model):
        prompts = [list(RNG.integers(0, 512, n)) for n in (5, 9, 3, 12)]
        max_new = 8
        refs = [_reference(model, p, max_new) for p in prompts]
        eng = ServingEngine(model, num_pages=64, page_size=4, max_slots=4,
                            max_pages_per_slot=8)
        rids = [eng.add_request(prompts[0], max_new),
                eng.add_request(prompts[1], max_new)]
        eng.step()
        rids.append(eng.add_request(prompts[2], max_new))
        eng.step()
        rids.append(eng.add_request(prompts[3], max_new))
        res = eng.run_to_completion(max_steps=200)
        for rid, ref in zip(rids, refs):
            assert res[rid] == ref  # bitwise: same argmax stream
        assert eng.decode_program_count() == 1

    def test_greedy_equivalence_through_preemption(self, model):
        prompts = [list(RNG.integers(0, 512, n)) for n in (6, 7)]
        max_new = 10
        refs = [_reference(model, p, max_new) for p in prompts]
        # capacity 6 pages; the two requests need 4 + 5 at full length,
        # so decode growth must preempt-and-recompute
        eng = ServingEngine(model, num_pages=7, page_size=4, max_slots=2,
                            max_pages_per_slot=6)
        rids = [eng.add_request(p, max_new) for p in prompts]
        res = eng.run_to_completion(max_steps=500)
        assert eng.scheduler.num_preemptions > 0, \
            "config failed to exercise preemption"
        for rid, ref in zip(rids, refs):
            assert res[rid] == ref
        assert eng.decode_program_count() == 1
        assert eng.metrics.summary()["preemptions"] > 0

    def test_no_retrace_across_scheduling_epochs(self, model):
        """Join/leave churn across >= 3 drain epochs with varying prompt
        lengths, batch sizes and sampling params: the decode step must
        stay ONE compiled program."""
        eng = ServingEngine(model, num_pages=64, page_size=4, max_slots=4,
                            max_pages_per_slot=8)
        for epoch in range(3):
            lens = [3 + epoch, 5, 8][: 2 + epoch % 2]
            for i, n in enumerate(lens):
                sp = (SamplingParams(do_sample=True, top_p=0.8,
                                     temperature=0.7, seed=epoch * 10 + i)
                      if i % 2 else None)
                eng.add_request(list(RNG.integers(0, 512, n)),
                                max_new_tokens=4 + epoch, sampling=sp)
            eng.run_to_completion(max_steps=200)
            assert eng.decode_program_count() == 1, f"retraced in epoch {epoch}"
        assert eng.stats()["decode_programs"] == 1

    def test_eos_stops_request_early(self, model):
        prompt = list(RNG.integers(0, 512, 6))
        ref = _reference(model, prompt, 8)
        eos = ref[2]  # a token the greedy stream actually emits
        k = ref.index(eos)  # first occurrence is where decode stops
        eng = ServingEngine(model, num_pages=32, page_size=4, max_slots=2)
        rid = eng.add_request(prompt, 8, eos_token_id=eos)
        res = eng.run_to_completion(max_steps=100)
        assert res[rid] == ref[: k + 1]  # stops AT the eos token
        assert eng.request(rid).finish_reason == "stop"

    @pytest.mark.slow
    def test_sampled_stream_invariant_to_batch_composition(self, model):
        """fold_in(PRNGKey(seed), token_index) keying: a sampled request
        draws the same tokens alone as when sharing the engine."""
        prompt = list(RNG.integers(0, 512, 5))
        sp = SamplingParams(do_sample=True, top_p=0.9, temperature=0.8,
                            seed=42)
        eng1 = ServingEngine(model, num_pages=64, page_size=4, max_slots=4)
        r_alone = eng1.add_request(prompt, 6, sampling=sp)
        alone = eng1.run_to_completion(max_steps=100)[r_alone]
        eng2 = ServingEngine(model, num_pages=64, page_size=4, max_slots=4)
        eng2.add_request(list(RNG.integers(0, 512, 7)), 6)  # companion
        r_shared = eng2.add_request(prompt, 6, sampling=sp)
        shared = eng2.run_to_completion(max_steps=100)[r_shared]
        assert alone == shared

    def test_stream_yields_tokens_and_finish(self, model):
        eng = ServingEngine(model, num_pages=32, page_size=4, max_slots=2)
        rid = eng.add_request(list(RNG.integers(0, 512, 4)), 3)
        evs = list(eng.stream())
        mine = [e for e in evs if e["rid"] == rid]
        assert len(mine) == 3
        assert mine[-1]["finished"] and mine[-1]["finish_reason"] == "length"
        assert [e["token"] for e in mine] == eng.request(rid).tokens

    def test_request_too_large_rejected_upfront(self, model):
        eng = ServingEngine(model, num_pages=8, page_size=4, max_slots=2,
                            max_pages_per_slot=4)
        with pytest.raises(ValueError, match="pages"):
            eng.add_request(list(range(1, 30)), 8)  # > max_pages_per_slot
        with pytest.raises(ValueError, match="non-empty"):
            eng.add_request([], 4)

    def test_pool_drains_clean_after_completion(self, model):
        eng = ServingEngine(model, num_pages=32, page_size=4, max_slots=2)
        for n in (4, 6, 5):
            eng.add_request(list(RNG.integers(0, 512, n)), 4)
        eng.run_to_completion(max_steps=200)
        assert eng.pool.num_in_use == 0
        assert eng.scheduler.queue_depth == 0
        assert not eng.scheduler.running
        m = eng.metrics.summary()
        assert m["requests_finished"] == 3
        assert m["tokens_generated"] == 12


# ---------------------------------------------------------------------------
# the robustness layer: typed errors, classified outcomes, chaos replays
# ---------------------------------------------------------------------------

@pytest.fixture
def fault_free(monkeypatch):
    """Guarantee no FaultPlan leaks out of a chaos test — and no rank
    env leaked IN by an earlier launcher test skews the hash draws."""
    fault.deactivate()
    monkeypatch.delenv("PADDLE_TRAINER_ID", raising=False)
    monkeypatch.delenv("PROCESS_ID", raising=False)
    monkeypatch.delenv("PADDLE_RESTART_EPOCH", raising=False)
    yield
    fault.deactivate()


class TestServingRobustness:
    def test_queue_full_backpressure(self, model):
        eng = ServingEngine(model, num_pages=32, page_size=4, max_slots=1,
                            max_queue_depth=2)
        eng.add_request([1, 2, 3], 4)
        eng.step()                             # admits it into the only slot
        eng.add_request([4, 5], 4)             # waiting[0]
        eng.add_request([6, 7], 4)             # waiting[1] — queue now full
        with pytest.raises(QueueFullError, match="max_queue_depth=2"):
            eng.add_request([8, 9], 4, rid="overflow")
        assert "overflow" not in eng._requests  # rejected, never registered
        m = eng.metrics.summary()
        assert m["rejected_queue_full"] == 1 and m["rejected"] == 1
        # backpressure is not engine damage: the admitted three still run
        res = eng.run_to_completion(max_steps=200)
        assert all(len(t) == 4 for t in res.values())

    def test_request_too_large_typed_at_both_layers(self, model):
        # layer 1: per-slot cap (engine-level reject)
        eng = ServingEngine(model, num_pages=16, page_size=4, max_slots=2,
                            max_pages_per_slot=2)
        with pytest.raises(RequestTooLargeError, match="pages"):
            eng.add_request(list(range(1, 20)), 8)
        # layer 2: pool capacity (scheduler-level reject — the fix for
        # admit() spinning forever on an impossible queue head); the slot
        # cap is raised past the pool so THIS layer is the one that fires
        eng2 = ServingEngine(model, num_pages=4, page_size=4, max_slots=2,
                             max_pages_per_slot=20)
        with pytest.raises(RequestTooLargeError,
                           match=r"needs \d+ pages .* only 3 allocatable"):
            eng2.add_request(list(range(1, 30)), 8)
        # typed, but still a ValueError for pre-existing callers
        assert issubclass(RequestTooLargeError, ValueError)
        assert issubclass(RequestTooLargeError, ServingError)
        assert eng2.metrics.summary()["rejected_too_large"] == 1

    def test_scheduler_rejects_never_runnable_head(self):
        pool = KVCachePool(1, 4, 4, 2, 8)  # capacity 3
        sched = Scheduler(max_slots=2, max_queue_depth=1)
        with pytest.raises(RequestTooLargeError, match="could never run"):
            sched.add(Request(rid="huge", prompt=list(range(30)),
                              max_new_tokens=4), pool)
        sched.add(Request(rid="ok", prompt=[1], max_new_tokens=1), pool)
        with pytest.raises(QueueFullError):
            sched.add(Request(rid="ok2", prompt=[2], max_new_tokens=1), pool)

    def test_preempted_limit_starvation_guard(self, model):
        # capacity 6; both requests want 5 pages at full length, so decode
        # growth must preempt the youngest — with a cap of 0 the first
        # eviction becomes a classified terminal outcome
        eng = ServingEngine(model, num_pages=7, page_size=4, max_slots=2,
                            max_pages_per_slot=6, max_preemptions=0)
        prompts = [list(RNG.integers(0, 512, 8)), list(RNG.integers(0, 512, 8))]
        rids = [eng.add_request(p, 12) for p in prompts]
        evs = []
        while eng.scheduler.has_work():
            evs.extend(eng.step())
        survivor, victim = eng.request(rids[0]), eng.request(rids[1])
        assert survivor.finish_reason == "length"
        assert survivor.tokens == _reference(model, prompts[0], 12)
        assert victim.finish_reason == "preempted_limit"
        term = [e for e in evs if e["rid"] == rids[1] and e["finished"]]
        assert term == [{"rid": rids[1], "token": None, "finished": True,
                         "finish_reason": "preempted_limit"}]
        assert eng.metrics.summary()["preempted_limit"] == 1
        assert eng.pool.num_in_use == 0

    def test_deadline_and_queue_wait_timeouts_virtual_clock(self, model):
        t = [0.0]
        eng = ServingEngine(model, num_pages=32, page_size=4, max_slots=1,
                            clock=lambda: t[0])
        r0 = eng.add_request([1, 2, 3], 64, deadline_s=5.0)
        r1 = eng.add_request([4, 5, 6], 8, max_queue_wait_s=2.0)
        eng.step()   # r0 admitted + prefilled at t=0
        t[0] = 3.0
        eng.step()   # r1 has waited 3s >= 2s -> timeout, never admitted
        assert eng.request(r1).finish_reason == "timeout"
        assert eng.request(r1).tokens == []
        assert eng.request(r0).finish_reason is None  # within deadline
        t[0] = 6.0
        eng.step()   # r0 now past its 5s completion deadline
        assert eng.request(r0).finish_reason == "timeout"
        assert eng.request(r0).tokens  # partial output kept
        assert not eng.scheduler.has_work()
        m = eng.metrics.summary()
        assert m["timed_out"] == 2
        assert m["queue_wait_p99_s"] == 0.0  # only r0 was admitted, at t=0

    def test_scheduler_stall_raises_with_snapshot(self, model):
        eng = ServingEngine(model, num_pages=4, page_size=4, max_slots=2)
        # bypass add_request validation to plant a never-admittable head —
        # the stall detector is the backstop for exactly this class of bug
        req = Request(rid="huge", prompt=list(range(40)), max_new_tokens=4)
        eng.scheduler.add(req)
        eng._requests["huge"] = req
        with pytest.raises(SchedulerStalledError, match="zero-progress") as ei:
            eng.run_to_completion(max_steps=50)
        snap = ei.value.snapshot
        assert snap["head_rid"] == "huge"
        assert snap["head_needs_pages"] > snap["capacity"]
        assert snap["queue_depth"] == 1 and snap["running"] == 0
        assert snap["idle_steps"] == 3

    def test_drain_reports_outcomes_and_blocks_admission(self, model):
        eng = ServingEngine(model, num_pages=32, page_size=4, max_slots=2)
        rids = [eng.add_request(list(RNG.integers(0, 512, 4)), 16)
                for _ in range(4)]
        eng.step()
        eng.step()
        report = eng.drain(timeout_s=0.0)  # evict everything immediately
        assert set(report) == set(rids)
        for rid in rids:
            assert report[rid]["finish_reason"] == "preempted"
            assert report[rid]["retriable"] is True
            assert report[rid]["tokens"] == eng.request(rid).tokens
        assert {e["finish_reason"] for e in eng.last_drain_events} \
            == {"preempted"}
        with pytest.raises(EngineDrainingError):
            eng.add_request([1, 2], 4)
        m = eng.metrics.summary()
        assert m["drained"] == 4
        assert eng.pool.num_in_use == 0

    def test_sigterm_guard_drains_mid_stream(self, model):
        eng = ServingEngine(model, num_pages=32, page_size=4, max_slots=2)
        guard = eng.attach_preemption_guard()
        try:
            rids = [eng.add_request(list(RNG.integers(0, 512, 4)), 32)
                    for _ in range(3)]
            it = eng.stream()
            next(it)            # engine is mid-flight...
            guard.request()     # ...when the SIGTERM lands
            evs = list(it)      # stream drains instead of vanishing
        finally:
            guard.uninstall()
        assert eng._draining
        for rid in rids:
            assert eng.request(rid).finish_reason is not None
        # the waiting third request never held a slot: retriable eviction
        assert eng.request(rids[2]).finish_reason == "preempted"
        assert any(e["finish_reason"] == "preempted" for e in evs)
        with pytest.raises(EngineDrainingError):
            eng.add_request([7], 2)

    def test_watchdog_wraps_the_step_sync(self, model):
        from paddle_tpu.distributed.watchdog import CommWatchdog
        wd = CommWatchdog(timeout=600.0)
        eng = ServingEngine(model, num_pages=32, page_size=4, max_slots=2,
                            watchdog=wd, step_timeout_s=120.0)
        eng.add_request([1, 2, 3], 3)
        eng.run_to_completion(max_steps=50)
        recs = [r for r in wd.records if r.name == "serving.step"]
        assert recs, "device sync ran outside the watchdog"
        assert all(r.finished and not r.timed_out for r in recs)
        assert recs[0].meta["slots"] == 1

    def test_generate_detailed_maps_typed_errors(self, model):
        from paddle_tpu.inference import create_llm_predictor
        # all four prompts are enqueued BEFORE the first step, so the
        # bounded queue (depth 2) takes the first two admissible ones
        pred = create_llm_predictor(model, num_pages=16, page_size=4,
                                    max_slots=1, max_pages_per_slot=3,
                                    max_queue_depth=2)
        prompts = [[1, 2, 3],                 # runs
                   list(range(1, 40)),        # too large for a slot
                   [4, 5, 6],                 # fills the queue
                   [7, 8, 9]]                 # queue full
        out = pred.generate_detailed(prompts, max_new_tokens=4)
        assert out[0]["error"] is None
        assert out[0]["finish_reason"] == "length"
        assert out[0]["tokens"] == _reference(model, prompts[0], 4)
        assert out[1] == {"tokens": [], "finish_reason": "rejected",
                          "error": "too_large", "retryable": False}
        assert out[2]["error"] is None
        # queue_full is the retryable outcome: nothing was computed, the
        # same prompt succeeds once the queue drains (SERVING.md)
        assert out[3] == {"tokens": [], "finish_reason": "rejected",
                          "error": "queue_full", "retryable": True}


@pytest.mark.faults
class TestServingChaos:
    """Deterministic FaultPlan replays over the engine's fault sites —
    the same plan fires the same failure every run (RESILIENCE.md)."""

    def test_poison_quarantines_only_the_offending_slot(self, model,
                                                        fault_free):
        prompts = [list(RNG.integers(0, 512, n)) for n in (5, 7, 4)]
        refs = [_reference(model, p, 10) for p in prompts]
        fault.activate(fault.FaultPlan([
            fault.FaultSpec(site="serving.decode", action="poison",
                            step=3, match=r"^victim$"),
        ]))
        eng = ServingEngine(model, num_pages=64, page_size=4, max_slots=4)
        rids = [eng.add_request(prompts[0], 10, rid="ok-0"),
                eng.add_request(prompts[1], 10, rid="victim"),
                eng.add_request(prompts[2], 10, rid="ok-1")]
        res = eng.run_to_completion(max_steps=200)
        victim = eng.request("victim")
        assert victim.finish_reason == "nonfinite"
        # tokens emitted before the poison are valid: a strict prefix
        assert len(victim.tokens) < 10
        assert victim.tokens == refs[1][: len(victim.tokens)]
        # survivors never saw the NaN page: bitwise parity holds
        assert res["ok-0"] == refs[0] and res["ok-1"] == refs[2]
        assert eng.decode_program_count() == 1
        assert eng.metrics.summary()["quarantined"] == 1
        # quarantined pages were scrubbed before returning to the free
        # list — nothing non-finite survives anywhere in the pool
        for pk, pv in eng.pool.pools:
            assert bool(jnp.all(jnp.isfinite(pk.astype(jnp.float32))))
            assert bool(jnp.all(jnp.isfinite(pv.astype(jnp.float32))))
        eng.audit_pool()

    def test_injected_prefill_failure_is_classified(self, model, fault_free):
        fault.activate(fault.FaultPlan([
            fault.FaultSpec(site="serving.prefill", action="raise",
                            match=r"^doomed$"),
        ]))
        eng = ServingEngine(model, num_pages=32, page_size=4, max_slots=2)
        eng.add_request([1, 2, 3], 4, rid="doomed")
        ok = eng.add_request([4, 5, 6], 4)
        res = eng.run_to_completion(max_steps=100)
        assert eng.request("doomed").finish_reason == "injected"
        assert res["doomed"] == []
        assert len(res[ok]) == 4
        assert eng.metrics.summary()["injected"] == 1
        assert eng.pool.num_in_use == 0
        eng.audit_pool()

    def test_alloc_storm_preempts_but_stays_deterministic(self, model,
                                                          fault_free):
        prompts = [list(RNG.integers(0, 512, n)) for n in (6, 7)]
        refs = [_reference(model, p, 10) for p in prompts]
        # ~40% of page allocations report injected exhaustion; the hash
        # draw is keyed by (seed, rank, step, site) so the storm pattern
        # is identical every run
        fault.activate(fault.FaultPlan([
            fault.FaultSpec(site="serving.alloc", action="raise",
                            prob=0.4, once=False),
        ], seed=11))
        eng = ServingEngine(model, num_pages=8, page_size=4, max_slots=2,
                            max_pages_per_slot=6)
        rids = [eng.add_request(p, 10) for p in prompts]
        res = eng.run_to_completion(max_steps=500)
        # churn happened, yet recompute reproduced every stream bitwise
        assert eng.scheduler.num_preemptions > 0
        for rid, ref in zip(rids, refs):
            assert res[rid] == ref
        assert eng.decode_program_count() == 1
        eng.audit_pool()

    def test_acceptance_chaos_storm(self, model, fault_free):
        """ISSUE.md acceptance: NaN poison + pool-exhaustion storm +
        mid-stream SIGTERM drain. Every request must end classified,
        untouched survivors bitwise-match generate(), and the decode
        step must still be ONE compiled program."""
        prompts = [list(RNG.integers(0, 512, n))
                   for n in (5, 6, 4, 7, 5, 6)]
        refs = [_reference(model, p, 12) for p in prompts]
        fault.activate(fault.FaultPlan([
            # once=True + match: fires on c1's first decode step, whenever
            # the storm lets that be — no step pin to go stale against it
            fault.FaultSpec(site="serving.decode", action="poison",
                            match=r"^c1$"),
            fault.FaultSpec(site="serving.alloc", action="raise",
                            prob=0.3, once=False),
        ], seed=5))
        eng = ServingEngine(model, num_pages=16, page_size=4, max_slots=3,
                            max_pages_per_slot=8)
        guard = eng.attach_preemption_guard()
        try:
            rids = [eng.add_request(p, 12, rid=f"c{i}")
                    for i, p in enumerate(prompts)]
            evs = []
            for i, ev in enumerate(eng.stream()):
                evs.append(ev)
                if i == 11:
                    guard.request()  # SIGTERM mid-decode
        finally:
            guard.uninstall()
        seen_reasons = set()
        for rid, ref in zip(rids, refs):
            req = eng.request(rid)
            assert req.finish_reason is not None, f"{rid} left unclassified"
            seen_reasons.add(req.finish_reason)
            if req.finish_reason == "nonfinite":
                assert rid == "c1"
                assert req.tokens == ref[: len(req.tokens)]
            elif req.finish_reason == "length":
                assert req.tokens == ref  # survivors bitwise intact
            else:  # preempted by the drain: a valid, retriable prefix
                assert req.finish_reason == "preempted"
                assert req.tokens == ref[: len(req.tokens)]
        assert "nonfinite" in seen_reasons
        assert "preempted" in seen_reasons  # the drain actually evicted
        assert eng.decode_program_count() == 1
        assert eng.pool.num_in_use == 0
        m = eng.metrics.summary()
        assert m["quarantined"] == 1 and m["drained"] >= 1
        eng.audit_pool()


# ---------------------------------------------------------------------------
# automatic prefix caching (SERVING.md "Prefix caching")
# ---------------------------------------------------------------------------

class TestPrefixCachePool:
    def _pool(self, pages=10, ps=4, **kw):
        return KVCachePool(1, pages, ps, 2, 8, **kw)

    def test_release_of_registered_pages_caches_instead_of_freeing(self):
        pool = self._pool()
        pages = pool.alloc(2)
        pool.register_prefix(list(range(8)), pages)
        pool.release(pages)
        assert pool.num_cached == 2 and pool.num_in_use == 0
        assert pool.num_available == pool.capacity
        s = pool.stats()
        assert s["pinned"] == 0 and s["cached"] == 2 and s["free"] == 7
        # re-acquiring pins them again (off the eviction LRU)
        pool.acquire(pages)
        assert pool.num_cached == 0 and pool.num_in_use == 2
        assert pool.refcount(pages[0]) == 1

    def test_match_full_and_partial_pages_and_cap(self):
        pool = self._pool()
        toks = list(range(10))  # 2 full pages + a 2-token partial
        pages = pool.alloc(3)
        pool.register_prefix(toks, pages)
        m = pool.match_prefix(toks)
        assert m.full_pages == pages[:2]
        assert m.partial_page == pages[2] and m.partial_len == 2
        assert m.cached_tokens == 10 and m.hit
        # the partial index stores the EXACT content hash, so a cap that
        # truncates mid-partial misses it (q=1 was never registered)
        m2 = pool.match_prefix(toks, max_tokens=9)
        assert m2.full_pages == pages[:2] and m2.partial_page is None
        assert m2.cached_tokens == 8
        # divergent content stops the chained-hash walk at the split
        m3 = pool.match_prefix(toks[:4] + [999] * 6)
        assert m3.full_pages == pages[:1] and m3.cached_tokens == 4
        assert not pool.match_prefix([999] * 8).hit

    def test_register_first_writer_wins(self):
        pool = self._pool()
        a = pool.alloc(1)
        assert pool.register_prefix(list(range(4)), a) == 1
        b = pool.alloc(1)
        # same content under a different page: the index keeps page a
        assert pool.register_prefix(list(range(4)), b) == 0
        assert pool.match_prefix(list(range(4))).full_pages == a
        pool.release(b)  # unregistered -> straight back to the free list
        assert pool.num_cached == 0 and pool.num_free == 8

    def test_alloc_evicts_lru_oldest_and_scrubs(self):
        pool = self._pool(pages=6)  # capacity 5
        a = pool.alloc(2)
        pool.register_prefix(list(range(8)), a)
        pk, pv = pool.pools[0]
        pool.pools[0] = (pk.at[a[0]].set(1.0), pv)  # sentinel content
        pool.release(a)
        b = pool.alloc(2)
        pool.register_prefix(list(range(100, 108)), b)
        pool.release(b)
        assert pool.num_free == 1 and pool.num_cached == 4
        # a was released first -> LRU-oldest -> evicted to satisfy 3 > 1
        got = pool.alloc(3)
        assert pool.counters["prefix_evictions"] == 2
        assert not pool.match_prefix(list(range(8))).hit
        assert pool.match_prefix(list(range(100, 108))).hit  # b survived
        assert bool(jnp.all(pool.pools[0][0][a[0]] == 0))  # scrubbed
        pool.free(got)

    def test_acquire_release_refreshes_lru_recency(self):
        pool = self._pool(pages=6)  # capacity 5
        a = pool.alloc(2)
        pool.register_prefix(list(range(8)), a)
        pool.release(a)
        b = pool.alloc(2)
        pool.register_prefix(list(range(100, 108)), b)
        pool.release(b)
        pool.acquire(a)   # a touched -> most recent
        pool.release(a)
        pool.alloc(3)     # evicts the now-oldest b, not a
        assert pool.match_prefix(list(range(8))).hit
        assert not pool.match_prefix(list(range(100, 108))).hit

    def test_quarantine_scrubs_shared_pages_only_at_refcount_zero(self):
        pool = self._pool()
        shared = pool.alloc(1)       # holder 1 (the poisoned request)
        pool.acquire(shared)         # holder 2 (an innocent sharer)
        pool.register_prefix(list(range(4)), shared)
        pk, pv = pool.pools[0]
        pool.pools[0] = (pk.at[shared[0]].set(jnp.nan), pv)
        pool.quarantine(shared)
        # deregistered IMMEDIATELY: no future request can match it
        assert not pool.match_prefix(list(range(4))).hit
        # but the content survives while the sharer still reads it
        assert pool.refcount(shared[0]) == 2
        assert bool(jnp.isnan(pool.pools[0][0][shared[0]]).any())
        pool.release(shared)         # poisoned holder exits
        assert bool(jnp.isnan(pool.pools[0][0][shared[0]]).any())
        pool.release(shared)         # last holder exits -> scrub + free
        assert bool(jnp.all(jnp.isfinite(pool.pools[0][0])))
        assert pool.num_free == pool.capacity and pool.num_cached == 0

    def test_quarantine_of_cached_page_scrubs_immediately(self):
        pool = self._pool()
        a = pool.alloc(1)
        pool.register_prefix(list(range(4)), a)
        pool.release(a)              # cached, refcount 0
        pool.quarantine(a)
        assert pool.num_cached == 0 and pool.num_free == pool.capacity
        assert not pool.match_prefix(list(range(4))).hit

    def test_cache_disabled_pool_never_caches(self):
        pool = self._pool(cache_enabled=False)
        a = pool.alloc(2)
        assert pool.register_prefix(list(range(8)), a) == 0
        pool.release(a)
        assert pool.num_cached == 0 and pool.num_free == pool.capacity
        assert not pool.match_prefix(list(range(8))).hit

    def test_cow_into_copies_device_content(self):
        pool = self._pool()
        a, b = pool.alloc(2)
        pk, pv = pool.pools[0]
        pool.pools[0] = (pk.at[a].set(3.0), pv)
        pool.cow_into(a, b)
        assert bool(jnp.all(pool.pools[0][0][b] == 3.0))
        assert pool.counters["prefix_cow_copies"] == 1


class TestPrefixScheduler:
    def test_admission_charges_only_the_uncached_suffix(self):
        shared = list(range(100, 108))
        pool = KVCachePool(1, 32, 4, 2, 8)
        seed = pool.alloc(2)
        pool.register_prefix(shared, seed)
        pool.release(seed)
        sched = Scheduler(max_slots=2, prefill_token_budget=12)
        sched.add(Request(rid="r0", prompt=list(range(6)),
                          max_new_tokens=4))
        r1 = Request(rid="r1", prompt=shared + [1, 2, 3, 4],
                     max_new_tokens=4)
        sched.add(r1)
        # r0 takes 6 of the 12-token budget; r1 is 12 tokens but 8 are
        # cached, so its suffix (4) fits the remaining 6 — both admitted
        # in ONE call where an uncached r1 would have waited a step
        admitted = sched.admit(pool)
        assert [r.rid for r in admitted] == ["r0", "r1"]
        assert r1.cached_len == 8 and not r1.cached_partial
        assert r1.pages[:2] == seed
        assert pool.refcount(seed[0]) == 1  # mapped = pinned
        # control: same shape, cold pool -> the second request waits
        pool2 = KVCachePool(1, 32, 4, 2, 8)
        sched2 = Scheduler(max_slots=2, prefill_token_budget=12)
        sched2.add(Request(rid="c0", prompt=list(range(6)),
                           max_new_tokens=4))
        sched2.add(Request(rid="c1", prompt=shared + [1, 2, 3, 4],
                           max_new_tokens=4))
        assert [r.rid for r in sched2.admit(pool2)] == ["c0"]

    def test_add_accounts_cached_pages_against_capacity(self):
        shared = list(range(64))
        pool = KVCachePool(1, 21, 4, 2, 8)  # capacity 20
        seed = pool.alloc(16)
        pool.register_prefix(shared, seed)
        pool.release(seed)
        sched = Scheduler(max_slots=1)
        # 64 prompt + 16 decode = 20 pages: equals capacity, admissible
        # only because 16 prompt pages are already cached
        sched.add(Request(rid="ok", prompt=shared, max_new_tokens=16),
                  pool)
        cold = KVCachePool(1, 21, 4, 2, 8)
        with pytest.raises(RequestTooLargeError):
            sched.add(Request(rid="no", prompt=shared + [1] * 20,
                              max_new_tokens=16), cold)


class TestPrefixCacheEngine:
    def test_shared_prefix_staggered_hit_parity(self, model):
        shared = list(RNG.integers(0, 512, 11))
        prompts = [shared + list(RNG.integers(0, 512, n))
                   for n in (3, 5, 2)]
        max_new = 8
        refs = [_reference(model, p, max_new) for p in prompts]
        eng = ServingEngine(model, num_pages=64, page_size=4, max_slots=4,
                            max_pages_per_slot=16)
        rids = [eng.add_request(prompts[0], max_new)]
        eng.step()   # the first prefill registers the shared pages
        rids.append(eng.add_request(prompts[1], max_new))
        eng.step()
        rids.append(eng.add_request(prompts[2], max_new))
        res = eng.run_to_completion(max_steps=200)
        for rid, ref in zip(rids, refs):
            assert res[rid] == ref  # bitwise: cache hits change nothing
        m = eng.metrics.summary()
        assert m["prefix_hits"] >= 2
        assert m["cache_hit_rate"] > 0.3
        assert eng.decode_program_count() == 1
        # every prefill token flows through the ONE mixed program —
        # no suffix-bucket family, whatever the hit/suffix geometry
        assert eng.stats()["prefill_programs"] == 1

    def test_same_step_burst_shares_the_first_prefill(self, model):
        """Interleaved admission, unchunked arm: requests arriving in
        the SAME step as the prefix writer still hit — the legacy
        whole-prompt prefill registers inside the admission loop, before
        the next admission's prefix lookup. The chunked engine commits
        registration at the FINAL chunk instead (after this step's
        admissions), so a same-step burst only shares from the next
        arrival on — but the emitted streams must be bitwise identical
        either way."""
        shared = list(RNG.integers(0, 512, 9))
        prompts = [shared + list(RNG.integers(0, 512, n)) for n in (2, 4)]
        refs = [_reference(model, p, 6) for p in prompts]
        for chunked, min_hits in ((False, 1), (True, 0)):
            eng = ServingEngine(model, num_pages=64, page_size=4,
                                max_slots=4, max_pages_per_slot=16,
                                chunked=chunked)
            rids = [eng.add_request(p, 6) for p in prompts]
            res = eng.run_to_completion(max_steps=100)
            for rid, ref in zip(rids, refs):
                assert res[rid] == ref, f"chunked={chunked}"
            assert eng.metrics.summary()["prefix_hits"] >= min_hits

    def test_partial_page_cow_hit_then_divergence(self, model):
        """Multi-turn shape: follow-ups extend a finished request's full
        context (prompt + its reply), so the match runs THROUGH the
        frozen partial page — both hitters get COW copies and extend
        them divergently; the cached page itself is never written."""
        shared = list(RNG.integers(0, 512, 6))
        eng = ServingEngine(model, num_pages=64, page_size=4, max_slots=4,
                            max_pages_per_slot=16)
        r0 = eng.add_request(shared, 2)
        out0 = eng.run_to_completion(max_steps=50)[r0]
        assert out0 == _reference(model, shared, 2)
        # r0's release registered (shared + out0)[:7]: one full page and
        # a 3-token partial page
        hist = shared + out0
        prompts = [hist + list(RNG.integers(0, 512, n)) for n in (3, 2)]
        refs = [_reference(model, p, 6) for p in prompts]
        rids = [eng.add_request(p, 6) for p in prompts]
        res = eng.run_to_completion(max_steps=100)
        for rid, ref in zip(rids, refs):
            assert res[rid] == ref  # bitwise through the COW copies
        m = eng.metrics.summary()
        # the FIRST hitter partial-hits and COWs; the second full-hits
        # the page the first hitter's prefill completed and registered
        # (a partial page upgraded to a shared full page)
        assert m["prefix_hits"] >= 2
        assert m["prefix_partial_hits"] >= 1
        assert m["prefix_cow_copies"] >= 1
        # the original context replays bitwise too: its cached page was
        # never written in place by the diverging hitters
        r3 = eng.add_request(shared, 2)
        assert eng.run_to_completion(max_steps=50)[r3] == out0

    def test_parity_after_eviction_and_reprefill(self, model):
        pa = list(RNG.integers(0, 512, 8))
        ref = _reference(model, pa, 4)
        eng = ServingEngine(model, num_pages=9, page_size=4, max_slots=2,
                            max_pages_per_slot=8)
        ra = eng.add_request(pa, 4)
        assert eng.run_to_completion(max_steps=100)[ra] == ref
        # disjoint churn overruns the tiny pool's cache -> evictions
        for _ in range(4):
            eng.add_request(list(RNG.integers(0, 512, 8)), 4)
            eng.run_to_completion(max_steps=100)
        assert eng.pool.counters["prefix_evictions"] > 0
        # pa's pages may be gone; a re-run must re-prefill and match
        ra2 = eng.add_request(pa, 4)
        assert eng.run_to_completion(max_steps=100)[ra2] == ref
        assert eng.decode_program_count() == 1
        for pk, pv in eng.pool.pools:
            assert bool(jnp.all(jnp.isfinite(pk.astype(jnp.float32))))

    def test_parity_across_preemption_recompute_hits_cache(self, model):
        prompts = [list(RNG.integers(0, 512, n)) for n in (6, 7)]
        refs = [_reference(model, p, 10) for p in prompts]
        eng = ServingEngine(model, num_pages=7, page_size=4, max_slots=2,
                            max_pages_per_slot=6)
        rids = [eng.add_request(p, 10) for p in prompts]
        res = eng.run_to_completion(max_steps=500)
        assert eng.scheduler.num_preemptions > 0
        for rid, ref in zip(rids, refs):
            assert res[rid] == ref
        # the victim's pages were registered at preemption, so its
        # recompute mapped them back instead of re-prefilling everything
        assert eng.pool.counters["prefix_hits"] > 0
        assert eng.decode_program_count() == 1

    def test_prefix_cache_off_is_the_old_engine(self, model):
        shared = list(RNG.integers(0, 512, 11))
        prompts = [shared + list(RNG.integers(0, 512, n)) for n in (3, 5)]
        refs = [_reference(model, p, 6) for p in prompts]
        eng = ServingEngine(model, num_pages=64, page_size=4, max_slots=4,
                            max_pages_per_slot=16, prefix_cache=False)
        rids = [eng.add_request(p, 6) for p in prompts]
        res = eng.run_to_completion(max_steps=100)
        for rid, ref in zip(rids, refs):
            assert res[rid] == ref
        assert eng.stats()["prefix_cache"] is False
        m = eng.metrics.summary()
        assert m["cache_hit_rate"] == 0.0 and m["prefix_hits"] == 0
        assert eng.pool.num_cached == 0

    def test_summary_carries_prefix_counters(self, model):
        eng = ServingEngine(model, num_pages=32, page_size=4, max_slots=2)
        eng.add_request(list(RNG.integers(0, 512, 5)), 3)
        eng.run_to_completion(max_steps=50)
        m = eng.metrics.summary()
        for k in ("cache_hit_rate", "prefill_tokens",
                  "prefill_cached_tokens", "prefix_lookups", "prefix_hits",
                  "prefix_hit_pages", "prefix_partial_hits",
                  "prefix_evictions", "prefix_cow_copies",
                  "prefix_pages_registered"):
            assert k in m, k
        assert 0.0 <= m["cache_hit_rate"] <= 1.0


@pytest.mark.faults
class TestPrefixCacheChaos:
    def test_poison_never_scrubs_under_a_live_sharer(self, model,
                                                     fault_free):
        """A poisoned request sharing cached prefix pages with a live
        reader: quarantine deregisters the pages immediately (no future
        hit can map NaNs) but scrubs them only when the LAST reference
        drops — the sharer's stream stays bitwise intact, and the pool
        ends all-finite."""
        shared = list(RNG.integers(0, 512, 11))
        prompts = [shared + list(RNG.integers(0, 512, n)) for n in (3, 5)]
        refs = [_reference(model, p, 12) for p in prompts]
        fault.activate(fault.FaultPlan([
            fault.FaultSpec(site="serving.decode", action="poison",
                            step=4, match=r"^victim$"),
        ]))
        eng = ServingEngine(model, num_pages=64, page_size=4, max_slots=4,
                            max_pages_per_slot=16)
        eng.add_request(prompts[0], 12, rid="victim")
        eng.step()  # victim prefills + registers the shared pages
        eng.add_request(prompts[1], 12, rid="sharer")
        res = eng.run_to_completion(max_steps=200)
        victim = eng.request("victim")
        assert victim.finish_reason == "nonfinite"
        assert victim.tokens == refs[0][: len(victim.tokens)]
        # the sharer mapped the victim's prefix pages, held them through
        # the quarantine, and still matches the cold reference bitwise
        assert eng.metrics.summary()["prefix_hits"] >= 1
        assert res["sharer"] == refs[1]
        # a post-quarantine arrival must NOT hit the deregistered pages
        # (they may hold poison until the last release) — and must still
        # generate correctly via a fresh prefill
        hits_before = eng.pool.counters["prefix_hits"]
        r3 = eng.add_request(shared + [7], 4)
        out3 = eng.run_to_completion(max_steps=100)[r3]
        assert out3 == _reference(model, shared + [7], 4)
        assert eng.pool.counters["prefix_hits"] >= hits_before  # sharer's
        for pk, pv in eng.pool.pools:
            assert bool(jnp.all(jnp.isfinite(pk.astype(jnp.float32))))
            assert bool(jnp.all(jnp.isfinite(pv.astype(jnp.float32))))
        assert eng.decode_program_count() == 1
        eng.audit_pool()


# ---------------------------------------------------------------------------
# the Pallas block-table kernel (interpret mode on CPU)
# ---------------------------------------------------------------------------

class TestPagedAttentionKernel:
    def test_kernel_applicable_gate(self):
        from paddle_tpu.ops.pallas.paged_attention import kernel_applicable
        assert kernel_applicable((2, 1, 4, 128), (8, 8, 2, 128))
        assert not kernel_applicable((2, 2, 4, 128), (8, 8, 2, 128))  # s>1
        assert not kernel_applicable((2, 1, 4, 64), (8, 8, 2, 64))    # lanes
        assert not kernel_applicable((2, 1, 4, 128), (8, 6, 2, 128))  # page
        assert not kernel_applicable((2, 1, 3, 128), (8, 8, 2, 128))  # GQA

    def test_kernel_matches_xla_gather_path(self):
        from paddle_tpu.nn.functional.attention import _grouped_decode_attn
        from paddle_tpu.ops.pallas.paged_attention import (
            kernel_applicable, paged_attention_tpu)
        b, h, kvh, d, ps, M, npages = 3, 4, 2, 128, 8, 3, 8
        assert kernel_applicable((b, 1, h, d), (npages, ps, kvh, d))
        q = jnp.asarray(RNG.standard_normal((b, 1, h, d)), jnp.float32)
        pk = jnp.asarray(RNG.standard_normal((npages, ps, kvh, d)),
                         jnp.float32)
        pv = jnp.asarray(RNG.standard_normal((npages, ps, kvh, d)),
                         jnp.float32)
        tables = jnp.asarray(RNG.permutation(np.arange(1, npages))[: b * M]
                             .reshape(b, M) if b * M < npages else
                             RNG.integers(1, npages, (b, M)), jnp.int32)
        lens = jnp.asarray([5, ps * M - 1, ps + 3], jnp.int32)
        got = paged_attention_tpu(q, pk, pv, tables, lens)
        kg = pk[tables].reshape(b, M * ps, kvh, d)
        vg = pv[tables].reshape(b, M * ps, kvh, d)
        want = _grouped_decode_attn(q, kg, vg, lens, 1.0 / np.sqrt(d))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-6, atol=2e-6)


# ---------------------------------------------------------------------------
# front-end + model surface satellites
# ---------------------------------------------------------------------------

class TestFrontEnds:
    @pytest.mark.slow
    def test_llm_predictor_matches_generate(self, model):
        from paddle_tpu.inference import create_llm_predictor
        prompts = [list(RNG.integers(0, 512, n)) for n in (4, 7)]
        pred = create_llm_predictor(model, num_pages=32, page_size=4,
                                    max_slots=4)
        outs = pred.generate(prompts, max_new_tokens=5)
        for p, got in zip(prompts, outs):
            assert got == _reference(model, p, 5)
        assert pred.metrics_summary()["requests_finished"] == 2
        assert pred.stats()["decode_programs"] == 1

    def test_decode_cache_stats_public_surface(self):
        # fresh model: the module-scoped one's signature LRU may be at
        # capacity from the other tests' generate() calls, which would
        # turn the +1 assertion into an eviction-order puzzle
        pt.seed(3)
        model = LlamaForCausalLM(llama_tiny(dtype="float32",
                                            mp_axis=None, fsdp_axis=None))
        model.eval()
        stats = model.decode_cache_stats()
        assert set(stats) >= {"signatures", "capacity", "signature_keys"}
        before = stats["signatures"]
        model.generate(jnp.asarray([[1, 2, 3]]), max_new_tokens=2)
        model.generate(jnp.asarray([[4, 5, 6]]), max_new_tokens=2)  # same sig
        after = model.decode_cache_stats()
        assert after["signatures"] == before + 1
        assert after["capacity"] == 16
        assert len(after["signature_keys"]) == after["signatures"]

    def test_generate_eos_pins_tail_to_pad(self, model):
        prompt = list(RNG.integers(0, 512, 5))
        ref = _reference(model, prompt, 8)
        eos = ref[1]
        got = _reference(model, prompt, 8, eos_token_id=eos, pad_token_id=0)
        k = ref.index(eos)
        assert got[: k + 1] == ref[: k + 1]
        assert got[k + 1:] == [0] * (len(ref) - k - 1)
        # eager loop path pins identically (bitwise scan/eager parity)
        eager = _reference(model, prompt, 8, eos_token_id=eos,
                           pad_token_id=0, jit_loop=False)
        assert eager == got
