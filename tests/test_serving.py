"""paddle_tpu.serving — continuous batching over the paged KV pool.

The two contracts that define the subsystem (SERVING.md):

1. DETERMINISM — greedy requests fed through the engine (staggered
   arrivals, shared pool, preempt-and-recompute) produce tokens bitwise
   identical to a standalone per-request ``model.generate()`` (fp32 CPU).
2. NO RETRACE — the decode step is ONE compiled program for the
   engine's lifetime; requests joining/finishing/preempting never change
   its compiled-program count.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.serving import (KVCachePool, PoolExhaustedError, Request,
                                SamplingParams, Scheduler, ServingEngine,
                                ServingMetrics, percentile)

RNG = np.random.default_rng(7)


@pytest.fixture(scope="module")
def model():
    pt.seed(123)
    m = LlamaForCausalLM(llama_tiny(dtype="float32",
                                    mp_axis=None, fsdp_axis=None))
    m.eval()
    return m


def _reference(model, prompt, max_new, **kw):
    out = model.generate(jnp.asarray([prompt]), max_new_tokens=max_new, **kw)
    return np.asarray(out)[0, len(prompt):].tolist()


# ---------------------------------------------------------------------------
# KV-cache pool
# ---------------------------------------------------------------------------

class TestKVCachePool:
    def test_shapes_and_reserved_scratch_page(self):
        pool = KVCachePool(num_layers=3, num_pages=8, page_size=4,
                           num_kv_heads=2, head_dim=16)
        assert len(pool.pools) == 3
        assert pool.pools[0][0].shape == (8, 4, 2, 16)
        assert pool.capacity == 7  # page 0 reserved
        got = pool.alloc(7)
        assert 0 not in got

    def test_alloc_all_or_nothing_and_accounting(self):
        pool = KVCachePool(1, 6, 4, 2, 8)
        a = pool.alloc(2)
        assert pool.num_in_use == 2 and pool.num_free == 3
        with pytest.raises(PoolExhaustedError):
            pool.alloc(4)  # only 3 free — must not tear off a partial grab
        assert pool.num_free == 3
        pool.free(a)
        assert pool.num_in_use == 0
        assert pool.utilization() == 0.0
        assert pool.stats()["peak_in_use"] == 2

    def test_free_rejects_scratch_double_and_bogus(self):
        pool = KVCachePool(1, 6, 4, 2, 8)
        pages = pool.alloc(1)
        pool.free(pages)
        with pytest.raises(ValueError, match="double free"):
            pool.free(pages)
        with pytest.raises(ValueError, match="not an allocatable"):
            pool.free([0])
        with pytest.raises(ValueError, match="not an allocatable"):
            pool.free([99])

    def test_pages_for(self):
        pool = KVCachePool(1, 6, 4, 2, 8)
        assert pool.pages_for(1) == 1
        assert pool.pages_for(4) == 1
        assert pool.pages_for(5) == 2
        assert pool.pages_for(0) == 1  # a slot always owns >= 1 page


# ---------------------------------------------------------------------------
# scheduler
# ---------------------------------------------------------------------------

class TestScheduler:
    def _pool(self, pages=16, ps=4):
        return KVCachePool(1, pages, ps, 2, 8)

    def test_fcfs_admission_respects_budget_and_slots(self):
        pool = self._pool()
        sched = Scheduler(max_slots=2, prefill_token_budget=8)
        for i, n in enumerate((4, 6, 3)):
            sched.add(Request(rid=f"r{i}", prompt=list(range(n)),
                              max_new_tokens=4))
        admitted = sched.admit(pool)
        # r0 (4 tokens) fits; r1 (6) exceeds the remaining budget (4) so
        # it waits for the next step — the budget bounds per-step prefill
        assert [r.rid for r in admitted] == ["r0"]
        assert sched.queue_depth == 2
        assert admitted[0].slot is not None and admitted[0].pages
        # next step: r1 goes first (FCFS), r2 again over the leftover budget
        assert [r.rid for r in sched.admit(pool)] == ["r1"]
        assert sched.admit(pool) == []  # both slots now occupied

    def test_no_queue_jumping_when_head_does_not_fit(self):
        pool = self._pool(pages=3, ps=4)  # capacity 2 pages
        sched = Scheduler(max_slots=2, prefill_token_budget=64)
        sched.add(Request(rid="big", prompt=list(range(12)),
                          max_new_tokens=1))  # needs 3 pages > capacity
        sched.add(Request(rid="small", prompt=[1], max_new_tokens=1))
        assert sched.admit(pool) == []  # strict FCFS: small must wait

    def test_preempt_youngest_and_requeue_order(self):
        pool = self._pool(pages=5, ps=4)  # capacity 4
        sched = Scheduler(max_slots=2)
        r0 = Request(rid="r0", prompt=list(range(8)), max_new_tokens=8)
        r1 = Request(rid="r1", prompt=list(range(8)), max_new_tokens=8)
        sched.add(r0)
        sched.add(r1)
        assert len(sched.admit(pool)) == 2  # 2 pages each
        r0.tokens, r1.tokens = [5], [6]
        # growing r0 to a 3rd page must evict r1 (youngest), not r0
        r0.context_len = r1.context_len = 8
        preempted = sched.ensure_decode_pages(pool)
        assert [r.rid for r in preempted] == ["r1"]
        assert r1.state == "preempted" and r1.pages == [] and r1.slot is None
        assert sched.waiting[0].rid == "r1"  # back at its arrival position
        assert len(r0.pages) == 3  # the oldest got its page

    def test_finish_releases_resources(self):
        pool = self._pool()
        sched = Scheduler(max_slots=1)
        r = Request(rid="r", prompt=[1, 2], max_new_tokens=2)
        sched.add(r)
        sched.admit(pool)
        sched.finish(r, pool, "length")
        assert r.done and r.finish_reason == "length"
        assert pool.num_in_use == 0 and not sched.running


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

class TestMetrics:
    def test_percentile(self):
        assert percentile([], 50) == 0.0
        assert percentile([3.0], 99) == 3.0
        assert percentile([1.0, 2.0, 3.0, 4.0], 50) == 2.5
        assert percentile([1.0, 2.0, 3.0, 4.0], 100) == 4.0

    def test_ttft_tpot_itl_with_virtual_clock(self):
        t = [0.0]
        m = ServingMetrics(clock=lambda: t[0])
        m.on_arrival("a")
        t[0] = 1.0
        m.on_token("a")           # TTFT = 1.0
        t[0] = 1.5
        m.on_token("a")           # ITL 0.5
        t[0] = 2.5
        m.on_token("a")           # ITL 1.0
        m.on_finish("a")
        m.on_step(queue_depth=2, pool_utilization=0.5)
        s = m.summary()
        assert s["ttft_p50_s"] == pytest.approx(1.0)
        assert s["tpot_mean_s"] == pytest.approx(0.75)  # (2.5-1.0)/2
        assert s["itl_p50_s"] == pytest.approx(0.75)
        assert s["tokens_generated"] == 3
        assert s["requests_finished"] == 1
        assert s["queue_depth_max"] == 2
        assert s["kv_util_peak"] == 0.5
        assert s["tokens_per_s"] == pytest.approx(3 / 2.5)


# ---------------------------------------------------------------------------
# the engine: determinism + no-retrace contracts
# ---------------------------------------------------------------------------

class TestServingEngine:
    def test_greedy_equivalence_staggered_arrivals(self, model):
        prompts = [list(RNG.integers(0, 512, n)) for n in (5, 9, 3, 12)]
        max_new = 8
        refs = [_reference(model, p, max_new) for p in prompts]
        eng = ServingEngine(model, num_pages=64, page_size=4, max_slots=4,
                            max_pages_per_slot=8)
        rids = [eng.add_request(prompts[0], max_new),
                eng.add_request(prompts[1], max_new)]
        eng.step()
        rids.append(eng.add_request(prompts[2], max_new))
        eng.step()
        rids.append(eng.add_request(prompts[3], max_new))
        res = eng.run_to_completion(max_steps=200)
        for rid, ref in zip(rids, refs):
            assert res[rid] == ref  # bitwise: same argmax stream
        assert eng.decode_program_count() == 1

    def test_greedy_equivalence_through_preemption(self, model):
        prompts = [list(RNG.integers(0, 512, n)) for n in (6, 7)]
        max_new = 10
        refs = [_reference(model, p, max_new) for p in prompts]
        # capacity 6 pages; the two requests need 4 + 5 at full length,
        # so decode growth must preempt-and-recompute
        eng = ServingEngine(model, num_pages=7, page_size=4, max_slots=2,
                            max_pages_per_slot=6)
        rids = [eng.add_request(p, max_new) for p in prompts]
        res = eng.run_to_completion(max_steps=500)
        assert eng.scheduler.num_preemptions > 0, \
            "config failed to exercise preemption"
        for rid, ref in zip(rids, refs):
            assert res[rid] == ref
        assert eng.decode_program_count() == 1
        assert eng.metrics.summary()["preemptions"] > 0

    def test_no_retrace_across_scheduling_epochs(self, model):
        """Join/leave churn across >= 3 drain epochs with varying prompt
        lengths, batch sizes and sampling params: the decode step must
        stay ONE compiled program."""
        eng = ServingEngine(model, num_pages=64, page_size=4, max_slots=4,
                            max_pages_per_slot=8)
        for epoch in range(3):
            lens = [3 + epoch, 5, 8][: 2 + epoch % 2]
            for i, n in enumerate(lens):
                sp = (SamplingParams(do_sample=True, top_p=0.8,
                                     temperature=0.7, seed=epoch * 10 + i)
                      if i % 2 else None)
                eng.add_request(list(RNG.integers(0, 512, n)),
                                max_new_tokens=4 + epoch, sampling=sp)
            eng.run_to_completion(max_steps=200)
            assert eng.decode_program_count() == 1, f"retraced in epoch {epoch}"
        assert eng.stats()["decode_programs"] == 1

    def test_eos_stops_request_early(self, model):
        prompt = list(RNG.integers(0, 512, 6))
        ref = _reference(model, prompt, 8)
        eos = ref[2]  # a token the greedy stream actually emits
        k = ref.index(eos)  # first occurrence is where decode stops
        eng = ServingEngine(model, num_pages=32, page_size=4, max_slots=2)
        rid = eng.add_request(prompt, 8, eos_token_id=eos)
        res = eng.run_to_completion(max_steps=100)
        assert res[rid] == ref[: k + 1]  # stops AT the eos token
        assert eng.request(rid).finish_reason == "stop"

    @pytest.mark.slow
    def test_sampled_stream_invariant_to_batch_composition(self, model):
        """fold_in(PRNGKey(seed), token_index) keying: a sampled request
        draws the same tokens alone as when sharing the engine."""
        prompt = list(RNG.integers(0, 512, 5))
        sp = SamplingParams(do_sample=True, top_p=0.9, temperature=0.8,
                            seed=42)
        eng1 = ServingEngine(model, num_pages=64, page_size=4, max_slots=4)
        r_alone = eng1.add_request(prompt, 6, sampling=sp)
        alone = eng1.run_to_completion(max_steps=100)[r_alone]
        eng2 = ServingEngine(model, num_pages=64, page_size=4, max_slots=4)
        eng2.add_request(list(RNG.integers(0, 512, 7)), 6)  # companion
        r_shared = eng2.add_request(prompt, 6, sampling=sp)
        shared = eng2.run_to_completion(max_steps=100)[r_shared]
        assert alone == shared

    def test_stream_yields_tokens_and_finish(self, model):
        eng = ServingEngine(model, num_pages=32, page_size=4, max_slots=2)
        rid = eng.add_request(list(RNG.integers(0, 512, 4)), 3)
        evs = list(eng.stream())
        mine = [e for e in evs if e["rid"] == rid]
        assert len(mine) == 3
        assert mine[-1]["finished"] and mine[-1]["finish_reason"] == "length"
        assert [e["token"] for e in mine] == eng.request(rid).tokens

    def test_request_too_large_rejected_upfront(self, model):
        eng = ServingEngine(model, num_pages=8, page_size=4, max_slots=2,
                            max_pages_per_slot=4)
        with pytest.raises(ValueError, match="pages"):
            eng.add_request(list(range(1, 30)), 8)  # > max_pages_per_slot
        with pytest.raises(ValueError, match="non-empty"):
            eng.add_request([], 4)

    def test_pool_drains_clean_after_completion(self, model):
        eng = ServingEngine(model, num_pages=32, page_size=4, max_slots=2)
        for n in (4, 6, 5):
            eng.add_request(list(RNG.integers(0, 512, n)), 4)
        eng.run_to_completion(max_steps=200)
        assert eng.pool.num_in_use == 0
        assert eng.scheduler.queue_depth == 0
        assert not eng.scheduler.running
        m = eng.metrics.summary()
        assert m["requests_finished"] == 3
        assert m["tokens_generated"] == 12


# ---------------------------------------------------------------------------
# the Pallas block-table kernel (interpret mode on CPU)
# ---------------------------------------------------------------------------

class TestPagedAttentionKernel:
    def test_kernel_applicable_gate(self):
        from paddle_tpu.ops.pallas.paged_attention import kernel_applicable
        assert kernel_applicable((2, 1, 4, 128), (8, 8, 2, 128))
        assert not kernel_applicable((2, 2, 4, 128), (8, 8, 2, 128))  # s>1
        assert not kernel_applicable((2, 1, 4, 64), (8, 8, 2, 64))    # lanes
        assert not kernel_applicable((2, 1, 4, 128), (8, 6, 2, 128))  # page
        assert not kernel_applicable((2, 1, 3, 128), (8, 8, 2, 128))  # GQA

    def test_kernel_matches_xla_gather_path(self):
        from paddle_tpu.nn.functional.attention import _grouped_decode_attn
        from paddle_tpu.ops.pallas.paged_attention import (
            kernel_applicable, paged_attention_tpu)
        b, h, kvh, d, ps, M, npages = 3, 4, 2, 128, 8, 3, 8
        assert kernel_applicable((b, 1, h, d), (npages, ps, kvh, d))
        q = jnp.asarray(RNG.standard_normal((b, 1, h, d)), jnp.float32)
        pk = jnp.asarray(RNG.standard_normal((npages, ps, kvh, d)),
                         jnp.float32)
        pv = jnp.asarray(RNG.standard_normal((npages, ps, kvh, d)),
                         jnp.float32)
        tables = jnp.asarray(RNG.permutation(np.arange(1, npages))[: b * M]
                             .reshape(b, M) if b * M < npages else
                             RNG.integers(1, npages, (b, M)), jnp.int32)
        lens = jnp.asarray([5, ps * M - 1, ps + 3], jnp.int32)
        got = paged_attention_tpu(q, pk, pv, tables, lens)
        kg = pk[tables].reshape(b, M * ps, kvh, d)
        vg = pv[tables].reshape(b, M * ps, kvh, d)
        want = _grouped_decode_attn(q, kg, vg, lens, 1.0 / np.sqrt(d))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-6, atol=2e-6)


# ---------------------------------------------------------------------------
# front-end + model surface satellites
# ---------------------------------------------------------------------------

class TestFrontEnds:
    @pytest.mark.slow
    def test_llm_predictor_matches_generate(self, model):
        from paddle_tpu.inference import create_llm_predictor
        prompts = [list(RNG.integers(0, 512, n)) for n in (4, 7)]
        pred = create_llm_predictor(model, num_pages=32, page_size=4,
                                    max_slots=4)
        outs = pred.generate(prompts, max_new_tokens=5)
        for p, got in zip(prompts, outs):
            assert got == _reference(model, p, 5)
        assert pred.metrics_summary()["requests_finished"] == 2
        assert pred.stats()["decode_programs"] == 1

    def test_decode_cache_stats_public_surface(self, model):
        stats = model.decode_cache_stats()
        assert set(stats) >= {"signatures", "capacity", "signature_keys"}
        before = stats["signatures"]
        model.generate(jnp.asarray([[1, 2, 3]]), max_new_tokens=2)
        model.generate(jnp.asarray([[4, 5, 6]]), max_new_tokens=2)  # same sig
        after = model.decode_cache_stats()
        assert after["signatures"] == before + 1
        assert after["capacity"] == 16
        assert len(after["signature_keys"]) == after["signatures"]

    def test_generate_eos_pins_tail_to_pad(self, model):
        prompt = list(RNG.integers(0, 512, 5))
        ref = _reference(model, prompt, 8)
        eos = ref[1]
        got = _reference(model, prompt, 8, eos_token_id=eos, pad_token_id=0)
        k = ref.index(eos)
        assert got[: k + 1] == ref[: k + 1]
        assert got[k + 1:] == [0] * (len(ref) - k - 1)
        # eager loop path pins identically (bitwise scan/eager parity)
        eager = _reference(model, prompt, 8, eos_token_id=eos,
                           pad_token_id=0, jit_loop=False)
        assert eager == got
