"""Sorted MoE dispatch (grouped pack-GEMM and ragged_dot) vs the einsum oracle.

Parity target: the two dispatch modes implement the same routing semantics
(reference moe_layer.py:263 einsum path vs fusion/cutlass/moe_kernel.cu:647
grouped GEMM — same math, different data movement), so outputs, aux losses
and gradients must agree to fp tolerance.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.distributed.moe import (ExpertFFN, GShardGate, MoELayer,
                                        SwitchGate, moe_ragged_compute)


def _make(gate_cls, dispatch, d_model=16, d_hidden=32, E=4, seed=0):
    pt.seed(seed)
    gate = gate_cls(d_model, E)
    experts = ExpertFFN(E, d_model, d_hidden, ep_axis=None)
    return MoELayer(d_model, experts=experts, gate=gate, ep_axis=None,
                    dispatch=dispatch)


def _copy_weights(src: MoELayer, dst: MoELayer):
    dst.set_state_dict(src.state_dict())


@pytest.mark.parametrize("gate_cls", [GShardGate, SwitchGate])
@pytest.mark.parametrize("mode", ["ragged", "grouped"])
def test_ragged_matches_einsum(gate_cls, mode):
    T, D = 24, 16
    ein = _make(gate_cls, "einsum")
    rag = _make(gate_cls, mode)
    _copy_weights(ein, rag)
    ein.eval()  # deterministic routing (no second-expert rng / jitter)
    rag.eval()
    x = jnp.asarray(np.random.default_rng(0).standard_normal((2, T // 2, D)),
                    jnp.float32)
    ye = ein(x)
    yr = rag(x)
    np.testing.assert_allclose(np.asarray(yr), np.asarray(ye),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(float(rag.aux_loss), float(ein.aux_loss),
                               rtol=1e-6)


@pytest.mark.parametrize("gate_cls", [GShardGate, SwitchGate])
@pytest.mark.parametrize("mode", ["ragged", "grouped"])
def test_ragged_grads_match_einsum(gate_cls, mode):
    T, D = 24, 16
    ein = _make(gate_cls, "einsum")
    rag = _make(gate_cls, mode)
    _copy_weights(ein, rag)
    ein.eval()
    rag.eval()
    x = jnp.asarray(np.random.default_rng(1).standard_normal((T, D)),
                    jnp.float32)

    from paddle_tpu.nn.module import functional_call

    def loss(layer, params, x):
        out, _ = functional_call(layer, params, x, training=False)
        return (out.astype(jnp.float32) ** 2).sum()

    pe = ein.param_dict()
    pr = rag.param_dict()
    (le, ge), (lr, gr) = (jax.value_and_grad(
        lambda p, l=l: loss(l, p, x))(p) for l, p in ((ein, pe), (rag, pr)))
    np.testing.assert_allclose(float(lr), float(le), rtol=2e-5)
    for k in ge:
        np.testing.assert_allclose(np.asarray(gr[k]), np.asarray(ge[k]),
                                   rtol=5e-4, atol=5e-5, err_msg=k)


@pytest.mark.parametrize("mode", ["ragged", "grouped"])
def test_ragged_capacity_drops_match(mode):
    """Force capacity drops (tiny capacity_factor): dropped slots must carry
    zero weight on both paths — including the oracle's top-1-before-top-2
    slot priority, which the grouped path must reproduce exactly."""
    T, D, E = 32, 16, 4
    pt.seed(3)
    gate_e = GShardGate(D, E, capacity_factor=0.3, eval_capacity_factor=0.3)
    experts_e = ExpertFFN(E, D, 32, ep_axis=None)
    ein = MoELayer(D, experts=experts_e, gate=gate_e, ep_axis=None,
                   dispatch="einsum")
    pt.seed(3)
    gate_r = GShardGate(D, E, capacity_factor=0.3, eval_capacity_factor=0.3)
    experts_r = ExpertFFN(E, D, 32, ep_axis=None)
    rag = MoELayer(D, experts=experts_r, gate=gate_r, ep_axis=None,
                   dispatch=mode)
    _copy_weights(ein, rag)
    ein.eval()
    rag.eval()
    x = jnp.asarray(np.random.default_rng(2).standard_normal((T, D)),
                    jnp.float32)
    np.testing.assert_allclose(np.asarray(rag(x)), np.asarray(ein(x)),
                               rtol=2e-5, atol=2e-5)


def test_moe_ragged_compute_reference():
    """moe_ragged_compute against a per-token numpy loop."""
    rng = np.random.default_rng(4)
    T, D, H, E, K = 12, 8, 16, 3, 2
    x = rng.standard_normal((T, D)).astype(np.float32)
    idx = rng.integers(0, E, (T, K)).astype(np.int32)
    w = rng.random((T, K)).astype(np.float32)
    w_in = rng.standard_normal((E, D, H)).astype(np.float32) * 0.1
    w_gate = rng.standard_normal((E, D, H)).astype(np.float32) * 0.1
    w_out = rng.standard_normal((E, H, D)).astype(np.float32) * 0.1

    def silu(v):
        return v / (1 + np.exp(-v))

    ref = np.zeros((T, D), np.float32)
    for t in range(T):
        for k in range(K):
            e = idx[t, k]
            h = x[t] @ w_in[e]
            h = silu(x[t] @ w_gate[e]) * h
            ref[t] += w[t, k] * (h @ w_out[e])

    got = moe_ragged_compute(jnp.asarray(x), jnp.asarray(idx), jnp.asarray(w),
                             jnp.asarray(w_in), jnp.asarray(w_gate),
                             jnp.asarray(w_out), jax.nn.silu)
    np.testing.assert_allclose(np.asarray(got), ref, rtol=2e-4, atol=2e-4)


def test_qwen2_moe_ragged_default_trains():
    """Qwen2-MoE with the new default dispatch trains end-to-end and its
    loss matches the einsum dispatch config."""
    from paddle_tpu.models.qwen2_moe import Qwen2MoeForCausalLM, qwen2_moe_tiny

    losses = {}
    for disp in ("grouped", "ragged", "einsum"):
        cfg = qwen2_moe_tiny(mp_axis=None, fsdp_axis=None, ep_axis=None,
                             ep_dispatch=disp)
        pt.seed(0)
        m = Qwen2MoeForCausalLM(cfg)
        m.eval()
        ids = jnp.asarray(np.random.default_rng(0).integers(
            0, cfg.vocab_size, (2, 16)), jnp.int32)
        logits = m(ids)
        losses[disp] = float(m.loss(logits, ids))
    np.testing.assert_allclose(losses["ragged"], losses["einsum"], rtol=1e-4)
    np.testing.assert_allclose(losses["grouped"], losses["einsum"], rtol=1e-4)


def test_fcfs_cumsum_matches_jnp_cumsum():
    """The blocked tril-matmul cumsum must be integer-exact vs jnp.cumsum
    for every shape class: multiple-of-block, non-multiple (fallback),
    small (fallback), skewed masks (all tokens on one expert)."""
    import jax.numpy as jnp
    from paddle_tpu.distributed.moe import _fcfs_cumsum
    r = np.random.default_rng(0)
    for T, E in [(2048, 16), (4096, 8), (1000, 16), (64, 4)]:
        idx = r.integers(0, E, (T,))
        mask = np.eye(E, dtype=np.int32)[idx]
        got = np.asarray(_fcfs_cumsum(jnp.asarray(mask)))
        want = np.cumsum(mask, axis=0)
        np.testing.assert_array_equal(got, want, err_msg=f"{T}x{E}")
    # skew: one expert takes everything (max block sums)
    mask = np.zeros((4096, 16), np.int32)
    mask[:, 3] = 1
    got = np.asarray(_fcfs_cumsum(jnp.asarray(mask)))
    np.testing.assert_array_equal(got, np.cumsum(mask, axis=0))


class TestFusedRouting:
    """Fused Pallas top-2 routing (ops/pallas/moe_routing.py — the fused
    dispatch's routing front-end, selected via _top2_parts(impl="fused"))
    vs the XLA chain: identical decisions (indices, positions, keeps),
    matching weights/aux to fp32 tolerance, matching logits-gradients.
    Runs in interpret mode on CPU; T a multiple of the kernel's 1024-token
    block triggers the fused path (asserted, not assumed)."""

    @staticmethod
    def _engages(T, E):
        from paddle_tpu.distributed.moe import _kernel_path_ok
        from paddle_tpu.ops.pallas.moe_routing import fused_routing_applicable
        return fused_routing_applicable(T, E) and _kernel_path_ok()

    def _both(self, T=1024, E=16, seed=0, policy="random", cap=None):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.distributed.moe import _top2_parts
        r = np.random.default_rng(seed)
        logits = jnp.asarray(r.standard_normal((T, E)) * 2, jnp.float32)
        cap = cap if cap is not None else int(1.25 * T * 2 / E)
        key = jax.random.key(7)
        assert self._engages(T, E)  # kernel engages, not vacuous
        fused = _top2_parts(logits, cap, second_policy=policy, key=key,
                            impl="fused")
        ref = _top2_parts(logits, cap, second_policy=policy, key=key)
        return logits, cap, key, fused, ref

    @pytest.mark.parametrize("policy", ["random", "all"])
    def test_decisions_and_weights_match(self, policy):
        _, _, _, fused, ref = self._both(policy=policy)
        names = ["g1_idx", "g2_idx", "w1", "w2", "keep1", "keep2f",
                 "p1", "p2", "aux"]
        for name, a, b in zip(names, fused, ref):
            a, b = np.asarray(a), np.asarray(b)
            if a.dtype.kind in "ib":
                np.testing.assert_array_equal(a, b, err_msg=name)
            else:
                np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6,
                                           err_msg=name)

    def test_tight_capacity_drops_match(self):
        _, _, _, fused, ref = self._both(cap=8, seed=3)
        for a, b in zip(fused, ref):
            a, b = np.asarray(a), np.asarray(b)
            if a.dtype.kind in "ib":
                np.testing.assert_array_equal(a, b)
            else:
                np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)

    def test_gradients_match_xla_chain(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.distributed.moe import _top2_parts
        r = np.random.default_rng(1)
        T, E, cap = 1024, 8, 320
        logits = jnp.asarray(r.standard_normal((T, E)), jnp.float32)
        key = jax.random.key(3)
        assert self._engages(T, E)  # kernel engages, not vacuous
        cw1 = jnp.asarray(r.standard_normal((T,)), jnp.float32)
        cw2 = jnp.asarray(r.standard_normal((T,)), jnp.float32)

        def loss(lg, impl):
            out = _top2_parts(lg, cap, second_policy="random", key=key,
                              impl=impl)
            _, _, w1, w2, _, _, _, _, aux = out
            return jnp.sum(w1 * cw1) + jnp.sum(w2 * cw2) + 3.0 * aux

        g_fused = jax.grad(lambda lg: loss(lg, "fused"))(logits)
        g_ref = jax.grad(lambda lg: loss(lg, "xla"))(logits)
        np.testing.assert_allclose(np.asarray(g_fused), np.asarray(g_ref),
                                   rtol=1e-4, atol=1e-6)

    def test_moe_layer_parity_fused_vs_xla(self):
        """End-to-end: the fused dispatch (whose routing front-end is this
        kernel) matches the grouped layer routed by the XLA chain (same
        framework seed). D=128 so the dispatch kernel engages too."""
        import paddle_tpu as pt
        import jax.numpy as jnp
        from paddle_tpu.distributed.moe import MoELayer
        r = np.random.default_rng(2)
        x = jnp.asarray(r.standard_normal((1024, 128)), jnp.float32)
        assert self._engages(1024, 8)
        outs = []
        for disp in ("fused", "grouped"):
            pt.seed(11)
            layer = MoELayer(128, num_experts=8, d_hidden=64, dispatch=disp)
            outs.append(np.asarray(layer(x)))
        np.testing.assert_allclose(outs[0], outs[1], rtol=2e-5, atol=2e-6)
