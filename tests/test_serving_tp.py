"""paddle_tpu.serving.parallel — tensor-parallel paged serving.

The TP contracts (SERVING.md "Tensor-parallel serving"):

1. BITWISE ACROSS DEGREES — ``ServingEngine(tp=2)`` emits streams
   bitwise identical to ``tp=1`` and to ``model.generate()``, composed
   with prefix caching, int8 KV, speculation, chunked prefill and the
   host tier: sharding the kv-head dim and the Megatron weight layout
   changes WHERE math runs, never WHAT it computes (the one psum per
   block sums exact partial products; sampling sees all-gathered
   logits identical on every shard).
2. TWO PROGRAMS, ANY DEGREE — ``step_program_counts()`` stays
   ``{"decode": 1, "mixed": 1}`` over request churn at every tp; each
   step is ONE jitted shard_map program.
3. PORTABLE SNAPSHOTS — pool payloads device_get as GLOBAL arrays, so
   a tp=2 snapshot restores into a tp=1 engine (and vice versa)
   bitwise.
4. TYPED REJECTION — un-shardable configs (kv heads or vocab not
   divisible by tp) raise :class:`TPConfigError` at construction, not
   a shape crash inside the compiled step.

The suite runs on CPU: tests/conftest.py forces
``--xla_force_host_platform_device_count=8`` for the whole run, so
tp in {2, 4} and a 2-replica tp=2 fleet all fit. Chaos tests carry the
``faults`` marker; heavy compile matrices are ``slow``.
"""

from types import SimpleNamespace

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.distributed import fault
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM, llama_tiny
from paddle_tpu.observability import render_prometheus
from paddle_tpu.serving import (FleetRouter, ServingEngine, TPConfigError,
                                collective_counts, partition_devices,
                                validate_tp_config)

RNG = np.random.default_rng(41)


@pytest.fixture(scope="module")
def model():
    pt.seed(123)
    m = LlamaForCausalLM(llama_tiny(dtype="float32",
                                    mp_axis="mp", fsdp_axis=None))
    m.eval()
    return m


@pytest.fixture(scope="module")
def model_kvh4():
    """tp=4 needs num_key_value_heads % 4 == 0 (llama_tiny has 2)."""
    pt.seed(123)
    cfg = LlamaConfig(vocab_size=512, hidden_size=128,
                      intermediate_size=384, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=4,
                      max_position_embeddings=512, dtype="float32",
                      mp_axis="mp", fsdp_axis=None)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture
def fault_free(monkeypatch):
    """No FaultPlan leaks out of a chaos test; no rank env leaks in."""
    fault.deactivate()
    monkeypatch.delenv("PADDLE_TRAINER_ID", raising=False)
    monkeypatch.delenv("PROCESS_ID", raising=False)
    monkeypatch.delenv("PADDLE_RESTART_EPOCH", raising=False)
    yield
    fault.deactivate()


def _reference(model, prompt, max_new, **kw):
    out = model.generate(jnp.asarray([prompt]), max_new_tokens=max_new, **kw)
    return np.asarray(out)[0, len(prompt):].tolist()


def _mk(model, tp=1, **kw):
    cfg = dict(num_pages=64, page_size=8, max_slots=4)
    cfg.update(kw)
    return ServingEngine(model, tp=tp, **cfg)


def _prompts(n=3, lo=4, hi=14):
    return [RNG.integers(1, 500, size=int(RNG.integers(lo, hi))).tolist()
            for _ in range(n)]


def _serve(model, tp, prompts, max_new=8, **kw):
    eng = _mk(model, tp=tp, **kw)
    rids = [eng.add_request(p, max_new, eos_token_id=None) for p in prompts]
    out = eng.run_to_completion(max_steps=400)
    assert eng.step_program_counts() == {"decode": 1, "mixed": 1}
    eng.audit_pool()
    return [out[r] for r in rids], eng


# ---------------------------------------------------------------------------
# typed construction-time rejection
# ---------------------------------------------------------------------------

class TestTPValidation:
    def test_kv_heads_not_divisible(self, model, fault_free):
        with pytest.raises(TPConfigError, match="num_key_value_heads"):
            _mk(model, tp=4)            # llama_tiny: kvh=2, 2 % 4 != 0

    def test_vocab_not_divisible(self):
        cfg = SimpleNamespace(num_key_value_heads=2, num_attention_heads=2,
                              vocab_size=511, intermediate_size=384)
        with pytest.raises(TPConfigError, match="vocab_size"):
            validate_tp_config(cfg, 2)

    def test_tp_zero_rejected(self):
        with pytest.raises(TPConfigError, match=">= 1"):
            validate_tp_config(SimpleNamespace(), 0)

    def test_tp_one_skips_divisibility(self):
        validate_tp_config(SimpleNamespace(vocab_size=511), 1)

    def test_partition_devices_too_few(self):
        with pytest.raises(TPConfigError, match="host_platform_device_count"):
            partition_devices(8, 4)

    def test_partition_devices_disjoint(self):
        groups = partition_devices(2, 2)
        assert len(groups) == 2 and all(len(g) == 2 for g in groups)
        assert len({d.id for g in groups for d in g}) == 4

    def test_error_is_serving_error_and_value_error(self, model, fault_free):
        from paddle_tpu.serving import ServingError
        with pytest.raises(ServingError):
            _mk(model, tp=4)
        with pytest.raises(ValueError):
            _mk(model, tp=4)


# ---------------------------------------------------------------------------
# bitwise parity across tp degrees x feature compositions
# ---------------------------------------------------------------------------

class TestTPParity:
    def test_tp2_matches_tp1_and_generate(self, model, fault_free):
        prompts = _prompts()
        a, _ = _serve(model, 1, prompts)
        b, _ = _serve(model, 2, prompts)
        assert a == b
        assert a[0] == _reference(model, prompts[0], 8, eos_token_id=None)

    def test_tp2_prefix_reuse_bitwise(self, model, fault_free):
        """Two prompts sharing a long prefix: the second is admitted
        through the (sharded) prefix cache and still streams bitwise."""
        base = RNG.integers(1, 500, size=16).tolist()
        prompts = [base + [7, 8], base + [9, 10, 11]]

        def sequential(tp):
            eng = _mk(model, tp=tp)
            streams = []
            for p in prompts:         # 2nd admission sees 1st's pages
                rid = eng.add_request(p, 8, eos_token_id=None)
                streams.append(eng.run_to_completion(max_steps=200)[rid])
            return streams, eng

        a, _ = sequential(1)
        b, eng = sequential(2)
        assert a == b
        assert eng.pool.counters["prefix_hits"] >= 1
        assert eng.step_program_counts() == {"decode": 1, "mixed": 1}

    def test_tp2_int8_kv_bitwise(self, model, fault_free):
        prompts = _prompts()
        a, _ = _serve(model, 1, prompts, kv_quant=True)
        b, eng = _serve(model, 2, prompts, kv_quant=True)
        assert a == b
        assert eng.pool.stats()["tp_degree"] == 2

    @pytest.mark.slow
    def test_tp2_speculative_bitwise(self, model, fault_free):
        prompts = _prompts()
        a, _ = _serve(model, 1, prompts, speculative=2)
        b, _ = _serve(model, 2, prompts, speculative=2)
        assert a == b

    @pytest.mark.slow
    def test_tp2_chunked_prefill_bitwise(self, model, fault_free):
        prompts = _prompts(lo=10, hi=20)
        a, _ = _serve(model, 1, prompts, chunked=True, prefill_chunk=4)
        b, _ = _serve(model, 2, prompts, chunked=True, prefill_chunk=4)
        assert a == b

    @pytest.mark.slow
    def test_tp2_host_tier_bitwise(self, model, fault_free):
        prompts = _prompts()
        a, _ = _serve(model, 1, prompts, host_tier=True)
        b, _ = _serve(model, 2, prompts, host_tier=True)
        assert a == b

    @pytest.mark.slow
    def test_tp4_matches_tp1(self, model_kvh4, fault_free):
        prompts = _prompts()
        a, _ = _serve(model_kvh4, 1, prompts)
        b, _ = _serve(model_kvh4, 4, prompts)
        assert a == b


# ---------------------------------------------------------------------------
# program counts, collectives, observability
# ---------------------------------------------------------------------------

class TestTPPrograms:
    def test_counts_pinned_over_churn_epochs(self, model, fault_free):
        """3 admission waves through one tp=2 engine: churn changes
        array values, never shapes — and under TP, never shardings."""
        eng = _mk(model, tp=2)
        for epoch in range(3):
            rids = [eng.add_request(p, 6, eos_token_id=None)
                    for p in _prompts(n=4)]
            out = eng.run_to_completion(max_steps=400)
            assert all(len(out[r]) == 6 for r in rids)
            assert eng.step_program_counts() == {"decode": 1, "mixed": 1}, \
                f"retraced in epoch {epoch}"
        eng.audit_pool()

    def test_exactly_one_psum_per_block(self, model, fault_free):
        """The jaxpr of each step program carries 2 * num_layers + 1
        psums (one per attention block, one per MLP block, one for the
        vocab-parallel embedding) and exactly ONE all_gather (logits) —
        nothing ever gathers the KV pool."""
        eng = _mk(model, tp=2)
        L = model.config.num_hidden_layers
        S, M = eng.max_slots, eng.max_pages_per_slot
        z = lambda *s: jnp.zeros(s, jnp.int32)         # noqa: E731
        o = lambda *s: jnp.ones(s, jnp.float32)        # noqa: E731
        decode_args = (eng._state, eng.pool.pools, z(S), z(S, M), z(S),
                       jnp.zeros((S,), bool), o(S), o(S),
                       jnp.ones((S,), bool), z(S), z(S))
        K = eng._chunk
        mixed_args = (eng._state, eng.pool.pools, z(S, K), z(S, M), z(S),
                      jnp.zeros((S,), bool), z(S), jnp.zeros((S,), bool),
                      o(S), o(S), jnp.ones((S,), bool), z(S), z(S))
        for step, args in ((eng._decode_step, decode_args),
                           (eng._mixed_step, mixed_args)):
            c = collective_counts(step._tp_inner, *args)
            assert c.get("psum", 0) == 2 * L + 1, c
            assert c.get("all_gather", 0) == 1, c
            assert c.get("all_to_all", 0) == 0, c

    def test_tp_observability_surface(self, model, fault_free):
        eng = _mk(model, tp=2)
        eng.add_request(_prompts(n=1)[0], 4, eos_token_id=None)
        eng.run_to_completion(max_steps=200)
        st = eng.pool.stats()
        assert st["tp_degree"] == 2
        assert st["tp_shard_kv_bytes_per_token"] \
            == eng.pool.kv_bytes_per_token() // 2
        assert st["tp_shard_capacity_bytes"] > 0
        assert eng.metrics.summary()["tp_degree"] == 2
        assert eng.stats()["tp"] == 2
        page = render_prometheus(eng.metrics.summary(), st,
                                 eng.tracer.counters)
        assert "paddle_serving_tp_degree 2" in page
        assert "paddle_serving_pool_tp_shard_kv_bytes_per_token" in page

    def test_tp1_has_no_tp_machinery(self, model, fault_free):
        eng = _mk(model, tp=1)
        assert eng._tp is None
        assert eng.pool.stats()["tp_degree"] == 1
        assert eng.metrics.summary()["tp_degree"] == 1


# ---------------------------------------------------------------------------
# snapshot portability across tp degrees
# ---------------------------------------------------------------------------

class TestTPSnapshotPortability:
    def _partial(self, model, tmp_path, tp, steps=6, **kw):
        prompts = [RNG.integers(1, 500, size=7).tolist(),
                   RNG.integers(1, 500, size=5).tolist()]
        eng = _mk(model, tp=tp, **kw)
        rids = [eng.add_request(p, 10, eos_token_id=None) for p in prompts]
        for _ in range(steps):
            eng.step()
        path = str(tmp_path / "snap")
        eng.save_snapshot(path)
        return eng, rids, path

    def test_tp2_snapshot_restores_into_tp1(self, model, tmp_path,
                                            fault_free):
        """Page payloads device_get as GLOBAL arrays — a tp=2 snapshot
        is just bytes a tp=1 engine can re-place unsharded."""
        eng, rids, path = self._partial(model, tmp_path, tp=2)
        warm = _mk(model, tp=1)
        assert warm.restore(path) == rids
        out = warm.run_to_completion(max_steps=100)
        cont = eng.run_to_completion(max_steps=100)
        for r in rids:
            assert out[r] == cont[r]
        assert warm.metrics.counters["snapshot_restore_corrupt"] == 0
        warm.audit_pool()
        eng.audit_pool()

    @pytest.mark.slow
    def test_tp1_snapshot_restores_into_tp2(self, model, tmp_path,
                                            fault_free):
        eng, rids, path = self._partial(model, tmp_path, tp=1)
        warm = _mk(model, tp=2)
        assert warm.restore(path) == rids
        out = warm.run_to_completion(max_steps=100)
        cont = eng.run_to_completion(max_steps=100)
        for r in rids:
            assert out[r] == cont[r]
        # restore injects pages host-side, so the warm engine may go
        # straight to pure decode — mixed compiles 0 or 1 programs
        counts = warm.step_program_counts()
        assert counts["decode"] == 1 and counts["mixed"] <= 1
        warm.audit_pool()

    def test_snapshot_meta_records_tp(self, model, tmp_path, fault_free):
        from paddle_tpu.serving import load_engine_snapshot
        _, _, path = self._partial(model, tmp_path, tp=2)
        _, meta = load_engine_snapshot(path)
        assert meta["tp"] == 2


# ---------------------------------------------------------------------------
# chaos: a fleet replica IS a TP group
# ---------------------------------------------------------------------------

@pytest.mark.faults
@pytest.mark.slow
class TestTPFleetChaos:
    def test_alloc_storm_and_poison_on_tp2_fleet(self, model, fault_free):
        """2 replicas x tp=2 on 4 disjoint devices: a permanent alloc
        storm pinned to replica 0 ejects it (failover replay), and one
        NaN-poisoned request — corrupting ONE shard's kv-head slice —
        is quarantined fleet-wide because the o_proj psum mixes every
        shard's heads into the checked output. Survivor audits clean."""
        groups = partition_devices(2, 2)
        engines = [_mk(model, tp=2, tp_devices=g) for g in groups]
        assert all(e.tp == 2 for e in engines)
        router = FleetRouter(engines, max_queue_depth=64)
        # lengths stay off page_size multiples: the poison NaNs the
        # request's (private) LAST page, which must hold already-valid
        # rows — a fresh boundary page's only row is overwritten by the
        # next scatter and the rest is masked
        prompts = _prompts(n=8, lo=4, hi=8)
        refs = [_reference(model, p, 6, eos_token_id=None) for p in prompts]
        poisoned_rid = "fleet-req-3"
        fault.activate(fault.FaultPlan([
            fault.FaultSpec(site="serving.alloc", action="raise",
                            once=False, match=r"^0$"),
            fault.FaultSpec(site="serving.decode", action="poison",
                            match=rf"^{poisoned_rid}$"),
        ]))
        rids = [router.submit(p, 6, eos_token_id=None) for p in prompts]
        events = []
        while router.has_work():
            events.extend(router.step())
            assert router.stats()["steps"] < 2000, "router hang"
        classified = 0
        for rid, ref in zip(rids, refs):
            rec = router.request(rid)
            assert rec.finished
            if rec.finish_reason in ("stop", "length"):
                assert rec.tokens == ref
            else:
                classified += 1
        assert classified >= 1
        assert router.request(poisoned_rid).finish_reason in (
            "nonfinite", "injected")
        st = router.stats()
        for h in st["replica_health"]:
            assert h["tp_degree"] == 2      # blast radius = the TP group
            if h["state"] != "dead":
                eng = router.engines[h["replica"]]
                assert eng.step_program_counts() == {"decode": 1,
                                                     "mixed": 1}
                eng.audit_pool()
