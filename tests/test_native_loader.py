"""Native C++ input-pipeline fast path (parity: the reference's C++
DataFeed readers): the compiled path must agree exactly with the numpy
fallback, and packing must roundtrip the original sequences."""

import numpy as np
import pytest

from paddle_tpu.io.native_loader import (gather_rows, native_available,
                                         pack_sequences)

RNG = np.random.default_rng(0)


def _seqs(n=50, max_len=37):
    return [RNG.integers(1, 1000, RNG.integers(1, max_len)).astype(np.int32)
            for _ in range(n)]


def test_native_compiles():
    import shutil
    if shutil.which("g++") is None:
        pytest.skip("no host C++ toolchain — numpy fallback is the contract")
    assert native_available(), "host toolchain should build the fast path"


def test_pack_sequences_native_matches_numpy():
    seqs = _seqs()
    rows_n, cu_n = pack_sequences(seqs, 64)
    rows_p, cu_p = pack_sequences(seqs, 64, force_numpy=True)
    np.testing.assert_array_equal(rows_n, rows_p)
    np.testing.assert_array_equal(cu_n, cu_p)


def test_pack_sequences_roundtrip():
    seqs = _seqs()
    rows, cu = pack_sequences(seqs, 64, pad_id=0)
    recovered = []
    for r, c in zip(rows, cu):
        bounds = c[c >= 0]
        for a, b in zip(bounds[:-1], bounds[1:]):
            recovered.append(np.asarray(r[a:b]))
    assert len(recovered) == len(seqs)
    for got, want in zip(recovered, seqs):
        np.testing.assert_array_equal(got, want)
    # rows reasonably full (greedy packing actually packs)
    fill = sum(len(s) for s in seqs) / rows.size
    assert fill > 0.5


def test_pack_cu_seqlens_feed_varlen_flash():
    """The emitted per-row segment bounds are a valid cu_seqlens for the
    varlen flash kernel."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas.flash_attention import flash_attn_unpadded
    seqs = [RNG.integers(1, 50, l).astype(np.int32) for l in (12, 20, 9)]
    rows, cu = pack_sequences(seqs, 48)
    assert rows.shape[0] == 1
    bounds = cu[0][cu[0] >= 0]
    total = int(bounds[-1])
    h, d = 2, 16
    q = jnp.asarray(RNG.standard_normal((total, h, d)), jnp.float32)
    out = flash_attn_unpadded(q, q, q, bounds, bounds, causal=True)
    assert out.shape == (total, h, d)
    assert np.isfinite(np.asarray(out)).all()


def test_gather_rows_matches_numpy():
    corpus = RNG.integers(0, 100, (128, 16)).astype(np.int32)
    idx = RNG.integers(0, 128, 40)
    got = gather_rows(corpus, idx, 16)
    np.testing.assert_array_equal(got, corpus[idx])
    got1 = gather_rows(corpus, idx, 16, n_threads=1)
    np.testing.assert_array_equal(got1, corpus[idx])
