"""Test configuration: force an 8-device virtual CPU platform, so
sharding/parallel tests run anywhere (the driver's real TPU chip is reserved
for bench.py).

Note: this environment pins JAX_PLATFORMS=axon (TPU) via sitecustomize, so
the env var alone is not enough — jax.config must be updated after import
(before first backend use)."""

import os

_REAL_CHIP = os.environ.get("PADDLE_TPU_REAL_CHIP") == "1"

if not _REAL_CHIP:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

import sys  # noqa: E402

import jax  # noqa: E402
import pytest  # noqa: E402

if not _REAL_CHIP:
    jax.config.update("jax_platforms", "cpu")


@pytest.fixture(autouse=True)
def _reap_replica_processes():
    """Multi-host hygiene: no replica host process may outlive its
    test. Zero-cost unless the test imported serving.replica_host; a
    nonzero reap count means the test leaked — fail it loudly."""
    yield
    mod = sys.modules.get("paddle_tpu.serving.replica_host")
    if mod is not None:
        leaked = mod.reap_orphans()
        assert leaked == 0, (
            f"{leaked} replica host process(es) outlived the test "
            "and were SIGKILLed by the reaper")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from the tier-1 run "
        "(multi-second multiprocess gangs, big models)")
    config.addinivalue_line(
        "markers", "faults: deterministic fault-injection (chaos) tests — "
        "kill/restart/torn-checkpoint scenarios driven by "
        "paddle_tpu.distributed.fault")
