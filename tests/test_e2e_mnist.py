"""Walking-skeleton e2e: LeNet on (synthetic) MNIST with the jit TrainStep
(parity model: the reference's MNIST convergence tests; SURVEY §7 step 2)."""

import itertools

import numpy as np

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.io import DataLoader
from paddle_tpu.nn import functional as F
from paddle_tpu.vision.datasets import MNIST
from paddle_tpu.vision.models import LeNet


def test_lenet_mnist_loss_decreases(tmp_path):
    pt.seed(42)
    model = LeNet()
    opt = pt.optimizer.Adam(learning_rate=2e-3, parameters=model)
    step = pt.jit.TrainStep(model, opt, lambda out, y: F.cross_entropy(out, y))

    ds = MNIST(mode="train")
    dl = DataLoader(ds, batch_size=64, shuffle=True)
    losses = [float(step(x, y)) for x, y in itertools.islice(dl, 40)]
    first = np.mean(losses[:5])
    last = np.mean(losses[-5:])
    assert last < first * 0.8, f"no learning: {first} -> {last}"

    # eval accuracy beats chance
    model.eval()
    es = pt.jit.EvalStep(model)
    test = MNIST(mode="test")
    xs, ys = test.images[:512], test.labels[:512]
    logits = np.asarray(es(xs))
    acc = (logits.argmax(-1) == ys).mean()
    assert acc > 0.2, f"accuracy {acc}"

    # checkpoint roundtrip mid-training
    path = str(tmp_path / "ckpt.pdparams")
    pt.save({"model": model.state_dict(), "opt": step.state_dict()}, path)
    blob = pt.load(path)
    model2 = LeNet()
    model2.set_state_dict(blob["model"])
    logits2 = np.asarray(pt.jit.EvalStep(model2)(xs))
    np.testing.assert_allclose(logits, logits2, rtol=1e-5, atol=1e-5)


def test_trainstep_updates_bn_buffers():
    pt.seed(0)
    model = nn.Sequential(nn.Conv2D(1, 4, 3, padding=1), nn.BatchNorm2D(4),
                          nn.ReLU(), nn.Flatten(), nn.Linear(4 * 8 * 8, 2))
    opt = pt.optimizer.SGD(learning_rate=0.01, parameters=model)
    step = pt.jit.TrainStep(model, opt, lambda out, y: F.cross_entropy(out, y))
    x = np.random.default_rng(0).standard_normal((4, 1, 8, 8)).astype(np.float32)
    y = np.array([0, 1, 0, 1])
    before = np.asarray(model.state_dict()["1._mean"])
    step(x, y)
    after = np.asarray(model.state_dict()["1._mean"])
    assert not np.allclose(before, after)


def test_dataloader_batching_and_prefetch():
    from paddle_tpu.io import TensorDataset
    xs = np.arange(100, dtype=np.float32).reshape(100, 1)
    ys = np.arange(100)
    ds = TensorDataset([xs, ys])
    dl = DataLoader(ds, batch_size=32, drop_last=True)
    batches = list(dl)
    assert len(batches) == 3
    assert batches[0][0].shape == (32, 1)
    dl2 = DataLoader(ds, batch_size=32, shuffle=True, drop_last=False)
    assert len(list(dl2)) == 4
