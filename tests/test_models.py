"""Model zoo tests (parity model: reference llama decoder tests in
test/auto_parallel/hybrid_strategy/ + vision model tests)."""

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.models import llama as llama_mod
from paddle_tpu.models.bert import BertConfig, BertForSequenceClassification
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

RNG = np.random.default_rng(3)


def test_llama_forward_and_loss_decreases():
    pt.seed(0)
    cfg = llama_tiny()
    model = LlamaForCausalLM(cfg)
    opt = pt.optimizer.AdamW(learning_rate=1e-3, parameters=model)
    ids = RNG.integers(0, cfg.vocab_size, (2, 64))
    step = pt.jit.TrainStep(model, opt, lambda logits, labels: model.loss(logits, labels),
                            n_inputs=1)
    losses = [float(step(ids, ids)) for _ in range(15)]
    assert losses[-1] < losses[0], losses
    assert losses[0] < 1.2 * np.log(cfg.vocab_size)  # sane init


def test_llama_kv_cache_decode_matches_full():
    pt.seed(1)
    cfg = llama_tiny()
    model = LlamaForCausalLM(cfg)
    model.eval()
    ids = jnp.asarray(RNG.integers(0, cfg.vocab_size, (1, 12)))
    full = model(ids)
    # prefill + decode one-at-a-time through the cache
    caches = model.init_kv_caches(1, 32, dtype=jnp.float32)
    logits, caches = model(ids[:, :8], kv_caches=caches, position_offset=0)
    np.testing.assert_allclose(np.asarray(logits[0, -1]), np.asarray(full[0, 7]),
                               rtol=2e-2, atol=2e-3)
    for t in range(8, 12):
        logits, caches = model(ids[:, t:t + 1], kv_caches=caches, position_offset=t)
        np.testing.assert_allclose(np.asarray(logits[0, 0]), np.asarray(full[0, t]),
                                   rtol=2e-2, atol=2e-3)


def test_llama_gqa_shapes():
    cfg = llama_tiny()
    assert cfg.num_key_value_heads < cfg.num_attention_heads
    model = LlamaForCausalLM(cfg)
    out = model(jnp.asarray(RNG.integers(0, cfg.vocab_size, (2, 16))))
    assert out.shape == (2, 16, cfg.vocab_size)


def test_llama_tp_specs_cover_big_weights():
    cfg = llama_tiny()
    model = LlamaForCausalLM(cfg)
    specs = model.spec_dict()
    assert specs["model.layers.0.self_attn.q_proj.weight"] == (None, "mp")
    assert specs["model.layers.0.self_attn.o_proj.weight"] == ("mp", None)
    assert specs["model.layers.0.mlp.gate_proj.weight"] == (None, "mp")
    assert specs["model.layers.0.mlp.down_proj.weight"] == ("mp", None)
    assert specs["model.embed_tokens.weight"] == ("mp", None)


def test_gpt_trains():
    pt.seed(2)
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=128,
                    max_position_embeddings=64, hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0)
    model = GPTForCausalLM(cfg)
    opt = pt.optimizer.Adam(learning_rate=1e-3, parameters=model)
    ids = RNG.integers(0, 256, (2, 32))
    step = pt.jit.TrainStep(model, opt, lambda lg, lb: model.loss(lg, lb))
    losses = [float(step(ids, ids)) for _ in range(10)]
    assert losses[-1] < losses[0]


def test_bert_classification_forward():
    cfg = BertConfig(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, intermediate_size=128,
                     max_position_embeddings=64)
    model = BertForSequenceClassification(cfg, num_classes=3)
    model.eval()
    ids = jnp.asarray(RNG.integers(0, 128, (2, 16)))
    mask = jnp.ones((2, 16), jnp.int32)
    out = model(ids, attention_mask=mask)
    assert out.shape == (2, 3)
    # padding must not change the unmasked logits
    ids2 = jnp.concatenate([ids, jnp.zeros((2, 4), ids.dtype)], axis=1)
    mask2 = jnp.concatenate([mask, jnp.zeros((2, 4), jnp.int32)], axis=1)
    out2 = model(ids2, attention_mask=mask2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out2), rtol=2e-2,
                               atol=2e-3)


def test_resnet18_forward_and_train_shape():
    from paddle_tpu.vision.models import resnet18
    pt.seed(3)
    model = resnet18(num_classes=10)
    x = RNG.standard_normal((2, 3, 32, 32)).astype(np.float32)
    out = model(x)
    assert out.shape == (2, 10)
    model.eval()
    out2 = model(x)
    assert out2.shape == (2, 10)


def test_rope_rotation_property():
    # relative-position property: scores depend only on distance
    cfg = llama_tiny()
    cos, sin = llama_mod._rope_cache(cfg)
    d = cfg.head_dim
    q = jnp.asarray(RNG.standard_normal((1, 8, 1, d)), jnp.float32)
    k = jnp.asarray(RNG.standard_normal((1, 8, 1, d)), jnp.float32)
    qr = llama_mod.apply_rotary_pos_emb(q, cos, sin)
    kr = llama_mod.apply_rotary_pos_emb(k, cos, sin)
    # score(i, j) with both shifted by +2 must match
    pos = jnp.arange(8)[None, :] + 2
    qr2 = llama_mod.apply_rotary_pos_emb(q, cos, sin, jnp.broadcast_to(pos, (1, 8)))
    kr2 = llama_mod.apply_rotary_pos_emb(k, cos, sin, jnp.broadcast_to(pos, (1, 8)))
    s1 = jnp.einsum("bqhd,bkhd->bqk", qr, kr)
    s2 = jnp.einsum("bqhd,bkhd->bqk", qr2, kr2)
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), rtol=1e-3, atol=1e-3)
