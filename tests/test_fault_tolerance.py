"""Fault-tolerant runtime suite (RESILIENCE.md): atomic verified
checkpoints (commit protocol + SHA-256 shard verification), committed-only
resume discovery, deterministic fault injection (distributed/fault.py),
watchdog abort with post-mortem, preemption drain, and the chaos e2e:
SIGKILL a rank mid-step during an async save and require a bit-identical
resumed loss trajectory."""

import json
import os
import pickle
import signal
import subprocess
import sys
import textwrap
import time

import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RNG = np.random.default_rng(11)


def _cpu_env(**extra):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PADDLE_FAULT_PLAN", None)
    env.update(extra)
    return env


# --------------------------------------------------------------------------
# commit protocol + verification
# --------------------------------------------------------------------------

def test_save_commits_atomically(tmp_path):
    from paddle_tpu.distributed.checkpoint import (COMMIT_MARKER,
                                                   is_committed,
                                                   save_state_dict)
    path = str(tmp_path / "ck")
    w = jnp.asarray(RNG.standard_normal((4, 3)), jnp.float32)
    save_state_dict({"w": w}, path)
    assert is_committed(path)
    assert os.path.isfile(os.path.join(path, COMMIT_MARKER))
    assert os.path.isfile(os.path.join(path, "metadata.pkl"))
    # staging dir is renamed away, not left behind
    assert not os.path.exists(path + ".tmp")
    # overwriting a committed checkpoint re-commits and leaves no .old swap
    save_state_dict({"w": w * 2}, path)
    assert is_committed(path) and not os.path.exists(path + ".old")
    # checksums landed in the merged metadata
    with open(os.path.join(path, "metadata.pkl"), "rb") as f:
        meta = pickle.load(f)
    assert meta.checksums and all(len(d) == 64
                                  for d in meta.checksums.values())


def test_uncommitted_dir_is_rejected(tmp_path):
    from paddle_tpu.distributed.checkpoint import (CheckpointCorruptionError,
                                                   is_committed,
                                                   load_state_dict)
    torn = tmp_path / "step_5"
    torn.mkdir()
    (torn / "0.distcp.npz").write_bytes(b"partial")
    assert not is_committed(str(torn))
    with pytest.raises(CheckpointCorruptionError, match="never committed"):
        load_state_dict({"w": jnp.zeros((2,))}, str(torn))
    # a *.tmp staging dir is never committed even with a COMMIT inside
    stage = tmp_path / "step_6.tmp"
    stage.mkdir()
    (stage / "COMMIT").write_text("")
    assert not is_committed(str(stage))


def test_flipped_byte_fails_load_naming_shard(tmp_path):
    from paddle_tpu.distributed.checkpoint import (CheckpointCorruptionError,
                                                   save_state_dict,
                                                   load_state_dict)
    path = str(tmp_path / "ck")
    w = jnp.asarray(RNG.standard_normal((16, 8)), jnp.float32)
    save_state_dict({"w": w}, path)
    npz = os.path.join(path, "0.distcp.npz")
    blob = bytearray(open(npz, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(npz, "wb").write(bytes(blob))
    with pytest.raises(CheckpointCorruptionError, match=r"w\|0,0"):
        load_state_dict({"w": jnp.zeros((16, 8))}, path)


def test_tampered_checksum_detected(tmp_path):
    """Exercise the sha256-compare branch itself: the shard file is intact
    (zip CRC passes) but the recorded digest disagrees."""
    from paddle_tpu.distributed.checkpoint import (CheckpointCorruptionError,
                                                   save_state_dict,
                                                   load_state_dict)
    path = str(tmp_path / "ck")
    save_state_dict({"w": jnp.ones((4, 4))}, path)
    meta_path = os.path.join(path, "metadata.pkl")
    with open(meta_path, "rb") as f:
        meta = pickle.load(f)
    (pk,) = meta.checksums
    meta.checksums[pk] = "0" * 64
    with open(meta_path, "wb") as f:
        pickle.dump(meta, f)
    with pytest.raises(CheckpointCorruptionError, match="checksum"):
        load_state_dict({"w": jnp.zeros((4, 4))}, path)


def test_injected_torn_write_is_caught_on_load(tmp_path):
    """Arm the harness's own `torn` action on the shard write and require
    the verification layer to catch the damage."""
    from paddle_tpu.distributed import fault
    from paddle_tpu.distributed.checkpoint import (CheckpointCorruptionError,
                                                   save_state_dict,
                                                   load_state_dict,
                                                   is_committed)
    path = str(tmp_path / "ck")
    fault.activate(fault.FaultPlan([
        {"site": "ckpt.write_shard", "action": "torn"}]))
    try:
        save_state_dict({"w": jnp.asarray(RNG.standard_normal((32, 32)),
                                          jnp.float32)}, path)
    finally:
        fault.deactivate()
    # the save itself succeeded (commit happened) — only verification can
    # tell the shard bytes were torn after hashing
    assert is_committed(path)
    with pytest.raises(CheckpointCorruptionError):
        load_state_dict({"w": jnp.zeros((32, 32))}, path)


# --------------------------------------------------------------------------
# committed-only resume discovery
# --------------------------------------------------------------------------

def test_latest_checkpoint_edge_cases(tmp_path):
    from paddle_tpu.distributed.fleet.elastic import ElasticManager
    em = ElasticManager(checkpoint_dir=str(tmp_path))
    assert em.latest_checkpoint() is None  # empty dir
    assert ElasticManager(
        checkpoint_dir=str(tmp_path / "nope")).latest_checkpoint() is None

    # digit-bearing junk must never win: loss traces, notes, torn staging,
    # uncommitted dirs
    (tmp_path / "loss_e12345.txt").write_text("0 1.0\n")
    (tmp_path / "notes_v2").mkdir()
    (tmp_path / "step_99").mkdir()            # uncommitted: no COMMIT/meta
    torn = tmp_path / "step_50.tmp"
    torn.mkdir()
    (torn / "0.distcp.npz").write_bytes(b"x")
    assert em.latest_checkpoint() is None

    (tmp_path / "step_3").mkdir()
    (tmp_path / "step_3" / "COMMIT").write_text("")
    assert em.latest_checkpoint().endswith("step_3")
    # pre-protocol checkpoint (metadata.pkl only) still counts
    (tmp_path / "step_25").mkdir()
    (tmp_path / "step_25" / "metadata.pkl").write_bytes(b"\x80\x04N.")
    assert em.latest_checkpoint().endswith("step_25")

    # gc_torn removes staging leftovers and nothing else
    got = em.latest_checkpoint(gc_torn=True)
    assert got.endswith("step_25")
    assert not torn.exists()
    assert (tmp_path / "step_99").exists()


# --------------------------------------------------------------------------
# FaultPlan semantics
# --------------------------------------------------------------------------

def test_fault_plan_matching_and_once():
    from paddle_tpu.distributed.fault import FaultInjected, FaultPlan
    plan = FaultPlan([{"site": "train.step", "action": "raise",
                       "rank": 1, "step": 3}])
    plan.trip("train.step", rank=0, step=3)   # wrong rank
    plan.trip("train.step", rank=1, step=2)   # wrong step
    plan.trip("other.site", rank=1, step=3)   # wrong site
    with pytest.raises(FaultInjected):
        plan.trip("train.step", rank=1, step=3)
    plan.trip("train.step", rank=1, step=3)   # once=True: spent


def test_fault_plan_nth_and_match():
    from paddle_tpu.distributed.fault import FaultInjected, FaultPlan
    plan = FaultPlan([{"site": "ckpt.commit", "action": "raise", "nth": 3}])
    plan.trip("ckpt.commit", rank=0)
    plan.trip("ckpt.commit", rank=0)
    with pytest.raises(FaultInjected):
        plan.trip("ckpt.commit", rank=0)
    plan2 = FaultPlan([{"site": "ckpt.commit", "action": "raise",
                        "match": r"step_3$"}])
    plan2.trip("ckpt.commit", rank=0, path="/ck/step_30")
    with pytest.raises(FaultInjected):
        plan2.trip("ckpt.commit", rank=0, path="/ck/step_3")


def test_fault_plan_env_roundtrip_and_epoch_gate(monkeypatch):
    from paddle_tpu.distributed import fault
    plan = fault.FaultPlan([{"site": "s", "action": "raise", "epoch": 0}],
                           seed=7)
    again = fault.FaultPlan.from_json(plan.to_json())
    assert again.seed == 7 and again.specs[0].epoch == 0
    monkeypatch.setenv("PADDLE_RESTART_EPOCH", "1")
    again.trip("s", rank=0)  # epoch-gated: silent on the restarted life
    monkeypatch.setenv("PADDLE_RESTART_EPOCH", "0")
    with pytest.raises(fault.FaultInjected):
        again.trip("s", rank=0)


def test_fault_plan_prob_draw_is_deterministic():
    from paddle_tpu.distributed.fault import FaultPlan, FaultSpec
    spec = FaultSpec(site="s", action="raise", prob=0.5, once=False)
    a, b = FaultPlan([spec], seed=3), FaultPlan([spec], seed=3)
    draws_a = [a._draw(spec, r, s) for r in range(4) for s in range(16)]
    draws_b = [b._draw(spec, r, s) for r in range(4) for s in range(16)]
    assert draws_a == draws_b
    assert 0 < sum(draws_a) < len(draws_a)  # actually probabilistic


# --------------------------------------------------------------------------
# watchdog abort: exit code 17 + on-disk post-mortem
# --------------------------------------------------------------------------

def test_watchdog_kill_exits_17_with_diagnosis(tmp_path):
    from paddle_tpu.distributed.watchdog import EXIT_WATCHDOG_ABORT
    script = tmp_path / "hang.py"
    script.write_text(textwrap.dedent(f"""
        import sys, time
        sys.path.insert(0, {REPO!r})
        import jax; jax.config.update("jax_platforms", "cpu")
        from paddle_tpu.distributed.watchdog import CommWatchdog
        wd = CommWatchdog(timeout=0.3, action="kill",
                          diagnosis_dir={str(tmp_path)!r})
        with wd.task("stuck_allreduce", group="tp", shape=(4096,)):
            time.sleep(60)
    """))
    proc = subprocess.run([sys.executable, str(script)],
                          env=_cpu_env(PADDLE_TRAINER_ID="3"),
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == EXIT_WATCHDOG_ABORT, (proc.stdout, proc.stderr)
    dump = tmp_path / "watchdog_diagnosis.rank3.json"
    assert dump.exists()
    diag = json.loads(dump.read_text())
    assert diag["rank"] == 3
    (hung,) = [t for t in diag["tasks"] if t["timed_out"]]
    assert hung["name"] == "stuck_allreduce" and not hung["finished"]


# --------------------------------------------------------------------------
# preemption: SIGTERM → drain async save → final checkpoint → exit 143
# --------------------------------------------------------------------------

def test_preemption_guard_drains_and_checkpoints(tmp_path):
    from paddle_tpu.distributed.fleet.preempt import EXIT_PREEMPTED
    ready = tmp_path / "ready"
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(f"""
        import os, sys, time
        sys.path.insert(0, {REPO!r})
        import jax; jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        from paddle_tpu.distributed import PreemptionGuard
        from paddle_tpu.distributed.checkpoint import save_state_dict
        guard = PreemptionGuard()
        state = {{"w": jnp.arange(8.0)}}
        # an in-flight async save the guard must drain before the final one
        save_state_dict(state, os.path.join({str(tmp_path)!r}, "step_4"),
                        async_save=True)
        open({str(ready)!r}, "w").write("ok")
        for _ in range(1200):
            time.sleep(0.05)
            guard.check(save_fn=lambda: save_state_dict(
                state, os.path.join({str(tmp_path)!r}, "final")))
        sys.exit(9)  # never preempted
    """))
    proc = subprocess.Popen([sys.executable, str(script)], env=_cpu_env(),
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True)
    deadline = time.monotonic() + 90
    while not ready.exists():
        assert time.monotonic() < deadline, proc.communicate(timeout=5)
        assert proc.poll() is None, proc.communicate(timeout=5)
        time.sleep(0.05)
    proc.send_signal(signal.SIGTERM)
    out, err = proc.communicate(timeout=90)
    assert proc.returncode == EXIT_PREEMPTED, (out, err)
    from paddle_tpu.distributed.checkpoint import is_committed
    assert is_committed(str(tmp_path / "step_4"))   # drained, not torn
    assert is_committed(str(tmp_path / "final"))    # final sync checkpoint


# --------------------------------------------------------------------------
# chaos e2e: SIGKILL mid-step during an async save; resume bit-identical
# --------------------------------------------------------------------------

_CHAOS_WORKER = """
    import os, sys
    sys.path.insert(0, {repo!r})
    import jax; jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu import nn
    from paddle_tpu.distributed.fleet.elastic import ElasticManager
    from paddle_tpu.distributed.checkpoint import (load_state_dict,
                                                   save_state_dict)

    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    epoch = int(os.environ.get("PADDLE_RESTART_EPOCH", "0"))
    ckpt_dir = os.environ["CHAOS_CKPT_DIR"]
    log_dir = os.environ["CHAOS_LOG_DIR"]

    pt.seed(0)
    rng = np.random.default_rng(0)
    X = rng.standard_normal((32, 16)).astype("float32")
    Y = (X @ rng.standard_normal((16, 1)).astype("float32")).ravel()
    model = nn.Sequential(nn.Linear(16, 32), nn.Tanh(), nn.Linear(32, 1))
    opt = pt.optimizer.SGD(learning_rate=0.05, parameters=model)
    step = pt.jit.TrainStep(model, opt,
                            lambda out, y: ((out.ravel() - y) ** 2).mean(),
                            n_inputs=1)
    em = ElasticManager(checkpoint_dir=ckpt_dir)
    start = 0
    latest = em.latest_checkpoint(gc_torn=(rank == 0))
    if latest:
        model.set_state_dict(load_state_dict(dict(model.state_dict()),
                                             latest))
        start = int(latest.rsplit("_", 1)[1]) + 1
        with open(os.path.join(log_dir, f"resume_e{{epoch}}.r{{rank}}"),
                  "w") as f:
            f.write(os.path.basename(latest))
    step._host_step = start  # RNG/lr streams continue from the true step
    handles = {{}}
    for i in range(start, 8):
        if i - 2 in handles:  # commit horizon: step i-2 must be durable
            handles.pop(i - 2).result(timeout=120)
        loss = float(step(X, Y))
        with open(os.path.join(log_dir,
                               f"loss_e{{epoch}}.r{{rank}}.txt"), "a") as f:
            f.write(f"{{i}} {{loss!r}}\\n")
        if rank == 0:
            handles[i] = save_state_dict(
                dict(model.state_dict()),
                os.path.join(ckpt_dir, f"step_{{i}}"),
                async_save=True, async_timeout=120)
    for h in handles.values():
        h.result(timeout=120)
"""


def _read_losses(path):
    return {int(a): float(b) for a, b in
            (ln.split() for ln in path.read_text().splitlines())}


def test_chaos_sigkill_mid_async_save_resumes_bit_identical(tmp_path):
    """The capstone: at epoch 0 rank 0's commit of step_3 hangs (torn
    staging guaranteed) and the next train step SIGKILLs the rank. The
    launcher must classify the death, gang-restart, and the restarted gang
    must resume from step_2 — the newest COMMITTED checkpoint — with every
    recomputed loss bit-identical to a run that never saw a fault."""
    script = tmp_path / "train.py"
    script.write_text(textwrap.dedent(_CHAOS_WORKER.format(repo=REPO)))

    # --- reference: same worker, no launcher, no faults
    ref_ckpt, ref_log = tmp_path / "ref_ck", tmp_path / "ref_log"
    ref_ckpt.mkdir(), ref_log.mkdir()
    proc = subprocess.run(
        [sys.executable, str(script)],
        env=_cpu_env(CHAOS_CKPT_DIR=str(ref_ckpt), CHAOS_LOG_DIR=str(ref_log),
                     PADDLE_TRAINER_ID="0", PADDLE_RESTART_EPOCH="0"),
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    ref = _read_losses(ref_log / "loss_e0.r0.txt")
    assert sorted(ref) == list(range(8))

    # --- faulted gang: hang step_3's commit, SIGKILL rank 0 at step 4
    ckpt, log = tmp_path / "ck", tmp_path / "log"
    ckpt.mkdir(), log.mkdir()
    plan = {"seed": 0, "specs": [
        {"site": "ckpt.commit", "action": "hang", "arg": 120.0,
         "rank": 0, "epoch": 0, "match": r"step_3$"},
        {"site": "train.step", "action": "kill",
         "rank": 0, "step": 4, "epoch": 0},
    ]}
    code = textwrap.dedent(f"""
        import sys; sys.path.insert(0, {REPO!r})
        from paddle_tpu.distributed.launch.main import launch
        sys.exit(launch(["--nproc_per_node", "2", "--max_restarts", "2",
                         {str(script)!r}]))
    """)
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env=_cpu_env(CHAOS_CKPT_DIR=str(ckpt), CHAOS_LOG_DIR=str(log),
                     PADDLE_FAULT_PLAN=json.dumps(plan)),
        capture_output=True, text=True, timeout=540)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "killed-by-SIGKILL" in proc.stderr     # exit classification
    assert "gang restart 1/2" in proc.stderr

    # resumed from the newest COMMITTED checkpoint: step_3 was torn
    assert (log / "resume_e1.r0").read_text() == "step_2"
    # the torn staging dir was GC'd on the restart path
    assert not (ckpt / "step_3.tmp").exists()

    e0 = _read_losses(log / "loss_e0.r0.txt")
    e1 = _read_losses(log / "loss_e1.r0.txt")
    assert sorted(e0) == [0, 1, 2, 3]      # killed inside step 4
    assert sorted(e1) == [3, 4, 5, 6, 7]   # resumed after step_2
    # bit-identical: overlap step AND the whole union against the
    # unfaulted reference (repr round-trips float64 exactly)
    assert e1[3] == e0[3]
    merged = {**e0, **e1}
    assert merged == ref, (merged, ref)
    # every surviving checkpoint is committed; rank 1's epoch-0 life also
    # ran to completion writing its own trajectory
    from paddle_tpu.distributed.checkpoint import is_committed
    for i in range(3, 8):
        assert is_committed(str(ckpt / f"step_{i}"))
    assert _read_losses(log / "loss_e0.r1.txt") == ref
