"""Round-3 tensor-API tail: the scripted name diff must be clean, the
inplace alias policy behaves, and sampling decode works end-to-end."""

import subprocess
import sys
import os

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt


@pytest.mark.skipif(
    not os.path.isdir("/root/reference"),
    reason="the /root/reference Paddle source mount is absent — "
           "tools/api_diff.py compares against its tensor/__init__.py, "
           "so the scripted name diff cannot run in this environment")
def test_api_diff_clean():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run([sys.executable, os.path.join(repo, "tools", "api_diff.py")],
                          capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "MISSING: none" in proc.stdout


def test_inplace_aliases_compute_and_chain():
    x = jnp.asarray([0.5, -0.5])
    np.testing.assert_allclose(np.asarray(pt.tanh_(x)), np.tanh([0.5, -0.5]),
                               rtol=1e-6)
    # chaining contract preserved; input (immutable) unchanged
    y = pt.add_(pt.abs_(x), jnp.ones(2))
    np.testing.assert_allclose(np.asarray(y), [1.5, 1.5])
    np.testing.assert_allclose(np.asarray(x), [0.5, -0.5])
    # random in-place fills: statistical behavior
    g = pt.geometric_(jnp.zeros(20000), 0.25)
    assert abs(float(jnp.mean(g)) - 4.0) < 0.3  # mean = 1/p
    n = pt.normal_(jnp.zeros(20000), mean=2.0, std=0.5)
    assert abs(float(jnp.mean(n)) - 2.0) < 0.05
    import paddle_tpu.ops.inplace as ip
    assert len(ip.__all__) >= 90  # the full `_` surface


def test_tensor_array_helpers():
    arr = pt.create_array()
    arr = pt.array_write(jnp.ones((2, 2)), 0, arr)
    arr = pt.array_write(jnp.zeros((2, 2)), 1, arr)
    assert int(pt.array_length(arr)) == 2
    np.testing.assert_array_equal(np.asarray(pt.array_read(arr, 1)),
                                  np.zeros((2, 2)))


def test_top_p_sampling_nucleus_bound():
    probs = jnp.asarray([[0.6, 0.25, 0.1, 0.05]] * 64)
    v, i = pt.top_p_sampling(probs, jnp.full((64,), 0.8), seed=11)
    assert np.asarray(i).max() <= 1  # nucleus is {0, 1}
    # greedy when ps <= 0
    v, i = pt.top_p_sampling(probs, jnp.zeros((64,)))
    assert np.asarray(i).max() == 0


def test_generate_with_sampling_decode():
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    pt.seed(0)
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64,
                      mp_axis=None, fsdp_axis=None)
    m = LlamaForCausalLM(cfg)
    m.eval()
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 8)))
    out_greedy = m.generate(ids, max_new_tokens=4)
    assert out_greedy.shape == (2, 12)
    out_s1 = m.generate(ids, max_new_tokens=4, do_sample=True, top_p=0.9, seed=7)
    out_s2 = m.generate(ids, max_new_tokens=4, do_sample=True, top_p=0.9, seed=7)
    np.testing.assert_array_equal(np.asarray(out_s1), np.asarray(out_s2))


def test_misc_new_ops_behave():
    # svd_lowrank captures dominant subspace of a low-rank matrix
    rs = np.random.default_rng(3)
    base = rs.standard_normal((40, 3)).astype("float32") @ \
        rs.standard_normal((3, 20)).astype("float32")
    U, S, V = pt.svd_lowrank(jnp.asarray(base), q=5, niter=3)
    recon = np.asarray(U) @ np.diag(np.asarray(S)) @ np.asarray(V).T
    assert np.max(np.abs(recon - base)) < 1e-3
    # cond of identity is 1
    assert abs(float(pt.cond(jnp.eye(4))) - 1.0) < 1e-5
    # broadcast_shape
    assert pt.broadcast_shape((2, 1, 3), (4, 3)) == [2, 4, 3]
    # frexp roundtrip
    m, e = pt.frexp(jnp.asarray([3.0, -0.75, 0.0]))
    np.testing.assert_allclose(np.asarray(m) * 2.0 ** np.asarray(e),
                               [3.0, -0.75, 0.0])
