"""1F1B pipeline schedule tests (parity: the reference's PP integration tests,
test/collective/fleet/hybrid_parallel_pp_*.py — loss/grad equality between the
pipelined and single-device runs; spec SURVEY §B.1)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu.core import mesh as mesh_lib
from paddle_tpu.distributed.pipeline import pipeline_train_1f1b
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.llama_pipe import LlamaForCausalLMPipe
from paddle_tpu.nn.module import functional_call


def _toy_setup():
    rng = np.random.default_rng(0)
    L, H, I, O, M, mb = 8, 16, 8, 4, 6, 4
    sp = {"w": jnp.asarray(rng.standard_normal((L, H, H)), jnp.float32) * 0.1,
          "b": jnp.asarray(rng.standard_normal((L, H)), jnp.float32) * 0.1}
    ex = {"emb": jnp.asarray(rng.standard_normal((I, H)), jnp.float32) * 0.3,
          "head": jnp.asarray(rng.standard_normal((H, O)), jnp.float32) * 0.3}
    micros = {"x": jnp.asarray(rng.standard_normal((M, mb, I)), jnp.float32),
              "y": jnp.asarray(rng.standard_normal((M, mb, O)), jnp.float32)}

    def first_fn(ex, mi):
        return mi["x"] @ ex["emb"]

    def layer_apply(sl, h):
        return jnp.tanh(h @ sl["w"] + sl["b"])

    def last_fn(ex, h, mi):
        logits = h @ ex["head"]
        return jnp.sum((logits - mi["y"]) ** 2), jnp.float32(logits.size)

    def ref_loss(sp, ex):
        num = 0.0
        den = 0.0
        for m in range(M):
            mi = jax.tree.map(lambda a: a[m], micros)
            h = first_fn(ex, mi)
            for l in range(L):
                h = layer_apply(jax.tree.map(lambda a: a[l], sp), h)
            n, d = last_fn(ex, h, mi)
            num += n
            den += d
        return num / den

    return sp, ex, micros, first_fn, layer_apply, last_fn, ref_loss


@pytest.mark.parametrize("pp,vpp", [(2, 1), (4, 1), (2, 2), (4, 2), (2, 4)])
def test_1f1b_matches_single_device(pp, vpp):
    """Plain 1F1B (vpp=1) and interleaved VPP (vpp>1, the
    PipelineParallelWithInterleave parity) must both reproduce the
    single-device loss and gradients exactly."""
    sp, ex, micros, first_fn, layer_apply, last_fn, ref_loss = _toy_setup()
    if sp["w"].shape[0] % (pp * vpp):
        pytest.skip("layers not divisible")
    ref_l, (ref_gsp, ref_gex) = jax.value_and_grad(
        ref_loss, argnums=(0, 1))(sp, ex)
    mesh = Mesh(np.array(jax.devices()).reshape(8 // pp, pp), ("dp", "pp"))
    with mesh_lib.use_mesh(mesh):
        spd = jax.device_put(sp, NamedSharding(mesh, P("pp")))
        loss, gsp, gex = jax.jit(lambda a, b, c: pipeline_train_1f1b(
            a, b, c, first_fn, layer_apply, last_fn, axis="pp", vpp=vpp))(
                spd, ex, micros)
    assert abs(float(loss) - float(ref_l)) < 1e-5
    for k in gsp:
        np.testing.assert_allclose(gsp[k], ref_gsp[k], atol=1e-5)
    for k in gex:
        np.testing.assert_allclose(gex[k], ref_gex[k], atol=1e-5)


def test_1f1b_degenerate_single_stage():
    """pp absent => plain grad accumulation, same math."""
    sp, ex, micros, first_fn, layer_apply, last_fn, ref_loss = _toy_setup()
    ref_l = ref_loss(sp, ex)
    loss, gsp, gex = pipeline_train_1f1b(
        sp, ex, micros, first_fn, layer_apply, last_fn, mesh=None)
    assert abs(float(loss) - float(ref_l)) < 1e-5


def _llama_pair(sep_axis):
    pt.seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=32, intermediate_size=96,
                      num_hidden_layers=4, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64,
                      mp_axis=None, fsdp_axis=None, pp_axis="pp",
                      sep_axis=sep_axis)
    ref = LlamaForCausalLM(cfg)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 128, (4, 32)))

    def ref_loss(p):
        out, _ = functional_call(ref, {**ref.buffer_dict(), **p}, ids,
                                 training=True)
        return ref.loss(out, ids)

    rl, rg = jax.value_and_grad(ref_loss)(ref.param_dict())
    return cfg, ref, ids, rl, rg


@pytest.mark.parametrize("sep_axis", [None, "sep"])
def test_llama_pipe_matches_reference(sep_axis):
    cfg, ref, ids, rl, rg = _llama_pair(sep_axis)
    mesh = Mesh(np.array(jax.devices()).reshape(2, 2, 2), ("dp", "pp", "sep"))
    with mesh_lib.use_mesh(mesh):
        pipe = LlamaForCausalLMPipe.from_unstacked(ref, num_micro=2)
        state = {}
        for k, v in pipe.param_dict().items():
            spec = pipe.spec_dict().get(k)
            pspec = P(*[a if a in mesh.axis_names else None
                        for a in (spec or ())])
            state[k] = jax.device_put(v, NamedSharding(mesh, pspec))
        pipe.set_state_dict(state)
        loss, grads = jax.jit(
            lambda p, b: pipe.pipeline_loss_and_grads(p, b, ids, ids))(
                pipe.param_dict(), pipe.buffer_dict())
    assert abs(float(loss) - float(rl)) < 3e-4
    np.testing.assert_allclose(grads["embed_tokens.weight"],
                               rg["model.embed_tokens.weight"], atol=1e-3)
    np.testing.assert_allclose(grads["norm.weight"],
                               rg["model.norm.weight"], atol=5e-3)
    for path in ["self_attn.q_proj.weight", "mlp.down_proj.weight"]:
        stacked_ref = np.stack(
            [np.asarray(rg[f"model.layers.{i}.{path}"])
             for i in range(cfg.num_hidden_layers)])
        got = grads["stage__" + path.replace(".", "__")]
        np.testing.assert_allclose(got, stacked_ref, atol=1e-3)


def test_llama_pipe_vpp_matches_reference():
    """Interleaved VPP on the flagship: pp=2 x vpp=2 virtual stages."""
    cfg, ref, ids, rl, rg = _llama_pair(None)
    mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("dp", "pp"))
    with mesh_lib.use_mesh(mesh):
        pipe = LlamaForCausalLMPipe.from_unstacked(ref, num_micro=2, vpp=2)
        loss, grads = jax.jit(
            lambda p, b: pipe.pipeline_loss_and_grads(p, b, ids, ids))(
                pipe.param_dict(), pipe.buffer_dict())
    assert abs(float(loss) - float(rl)) < 3e-4
    np.testing.assert_allclose(grads["embed_tokens.weight"],
                               rg["model.embed_tokens.weight"], atol=1e-3)
    stacked_ref = np.stack(
        [np.asarray(rg[f"model.layers.{i}.self_attn.q_proj.weight"])
         for i in range(cfg.num_hidden_layers)])
    np.testing.assert_allclose(grads["stage__self_attn__q_proj__weight"],
                               stacked_ref, atol=1e-3)


def test_llama_pipe_tied_embeddings_shared_grad():
    """Tied embedding = the reference's shared-embedding PP machinery
    (pp_layers.py:257): grad must be the SUM of the stage-0 (lookup) and
    last-stage (logits) contributions."""
    pt.seed(1)
    cfg = LlamaConfig(vocab_size=128, hidden_size=32, intermediate_size=96,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=4, max_position_embeddings=64,
                      mp_axis=None, fsdp_axis=None, pp_axis="pp",
                      tie_word_embeddings=True)
    ref = LlamaForCausalLM(cfg)
    ids = jnp.asarray(np.random.default_rng(1).integers(0, 128, (4, 16)))

    def ref_loss(p):
        out, _ = functional_call(ref, {**ref.buffer_dict(), **p}, ids,
                                 training=True)
        return ref.loss(out, ids)

    rl, rg = jax.value_and_grad(ref_loss)(ref.param_dict())
    mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("dp", "pp"))
    with mesh_lib.use_mesh(mesh):
        pipe = LlamaForCausalLMPipe.from_unstacked(ref, num_micro=2)
        loss, grads = jax.jit(
            lambda p, b: pipe.pipeline_loss_and_grads(p, b, ids, ids))(
                pipe.param_dict(), pipe.buffer_dict())
    assert abs(float(loss) - float(rl)) < 3e-4
    np.testing.assert_allclose(grads["embed_tokens.weight"],
                               rg["model.embed_tokens.weight"], atol=1e-3)


def test_pipeline_train_step_converges():
    """PipelineTrainStep drives the loss down on a toy corpus."""
    from paddle_tpu.distributed.fleet.meta_parallel import apply_hybrid_shardings
    pt.seed(2)
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=96,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=4, max_position_embeddings=32,
                      mp_axis=None, fsdp_axis=None, pp_axis="pp")
    mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("dp", "pp"))
    with mesh_lib.use_mesh(mesh):
        pipe = LlamaForCausalLMPipe(cfg, num_micro=2)
        pipe = apply_hybrid_shardings(pipe, mesh)
        opt = pt.optimizer.AdamW(learning_rate=5e-3, parameters=pipe)
        step = pt.jit.PipelineTrainStep(pipe, opt)
        ids = np.random.default_rng(3).integers(0, 64, (8, 16))
        losses = [float(step(ids, ids)) for _ in range(8)]
    assert losses[-1] < losses[0] - 0.5, losses


def test_pipe_to_unstacked_roundtrip():
    """Weights trained in the pipe layout must load into the plain model
    and produce identical logits (deploy path after PP training)."""
    cfg, ref, ids, rl, rg = _llama_pair(None)
    mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("dp", "pp"))
    with mesh_lib.use_mesh(mesh):
        pipe = LlamaForCausalLMPipe.from_unstacked(ref, num_micro=2)
        back = pipe.to_unstacked_state_dict()
    fresh = LlamaForCausalLM(cfg)
    fresh.set_state_dict(back)
    fresh.eval()
    ref.eval()
    np.testing.assert_allclose(np.asarray(fresh(ids)), np.asarray(ref(ids)),
                               rtol=1e-5, atol=1e-6)
