"""Vision zoo tail tests (parity: python/paddle/vision/models/
{densenet,googlenet,inceptionv3,mobilenetv3,shufflenetv2}.py +
test/legacy_test/test_vision_models.py)."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.vision import models

RNG = np.random.default_rng(7)


def _n_params(model):
    return sum(int(np.prod(p.shape)) for p in model.parameters())


@pytest.mark.parametrize("factory,size,n_params", [
    (models.densenet121, 64, 7_978_856),
    (models.mobilenet_v3_small, 64, 2_542_856),
    (models.mobilenet_v3_large, 64, 5_483_032),
    (models.shufflenet_v2_x0_25, 64, 603_688),
    (models.shufflenet_v2_x1_0, 64, 2_278_604),
    (models.inception_v3, 80, 23_834_568),
])
def test_zoo_forward_shape_and_param_count(factory, size, n_params):
    pt.seed(11)
    model = factory()
    model.eval()
    x = RNG.standard_normal((2, 3, size, size)).astype(np.float32)
    out = model(x)
    assert out.shape == (2, 1000)
    assert np.isfinite(np.asarray(out)).all()
    assert _n_params(model) == n_params


def test_densenet_variants_channel_arithmetic():
    # growth-rate bookkeeping: final feature width must match the spec
    for layers, want in [(121, 1024), (169, 1664), (201, 1920)]:
        model = models.DenseNet(layers=layers, num_classes=0, with_pool=True)
        assert model.out_channels == want


def test_googlenet_returns_three_heads():
    pt.seed(5)
    model = models.googlenet(num_classes=10)
    model.eval()
    x = RNG.standard_normal((1, 3, 224, 224)).astype(np.float32)
    main, aux1, aux2 = model(x)
    assert main.shape == (1, 10)
    assert aux1.shape == (1, 10)
    assert aux2.shape == (1, 10)


def test_shufflenet_channel_shuffle_mixes_branches():
    # after one stride-1 unit, the passthrough half must interleave with
    # the transformed half (shuffle property), not stay contiguous
    from paddle_tpu.vision.models.shufflenetv2 import InvertedResidual
    pt.seed(1)
    unit = InvertedResidual(8, "relu")
    unit.eval()
    x = np.zeros((1, 8, 4, 4), np.float32)
    x[:, :4] = 1.0  # mark the passthrough half
    out = np.asarray(unit(x))
    passthrough = (out == 1.0).all(axis=(0, 2, 3))
    # shuffle with groups=2 interleaves: out channels 0,2,4,6 from keep-half
    assert passthrough[[0, 2, 4, 6]].all()


def test_mobilenetv3_scale_halves_width():
    m_full = models.MobileNetV3Small(scale=1.0, num_classes=0,
                                     with_pool=False)
    m_half = models.MobileNetV3Small(scale=0.5, num_classes=0,
                                     with_pool=False)
    assert _n_params(m_half) < _n_params(m_full)


def test_zoo_trains_one_step():
    pt.seed(2)
    model = models.shufflenet_v2_x0_25(num_classes=10)
    opt = pt.optimizer.SGD(learning_rate=0.1, parameters=model)
    loss_fn = pt.nn.CrossEntropyLoss()
    step = pt.jit.TrainStep(model, opt, loss_fn, n_inputs=1)
    x = RNG.standard_normal((2, 3, 64, 64)).astype(np.float32)
    y = np.array([1, 3])
    l0 = float(step(x, y))
    l1 = float(step(x, y))
    assert np.isfinite(l0) and np.isfinite(l1)
