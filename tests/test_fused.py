"""Fused kernel zoo contracts (parity: the incubate fused-layer surface,
SURVEY §A.5): every fused op must match its naive composition, including
gradients where applicable; decode attention must match full attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.incubate.nn import functional as FF
from paddle_tpu.incubate import nn as inn
import paddle_tpu.nn.functional as F

RNG = np.random.default_rng(0)


def _naive_rms(x, w, eps=1e-6):
    xf = x.astype(np.float32)
    return (xf / np.sqrt((xf ** 2).mean(-1, keepdims=True) + eps)) * w


def test_fused_rms_norm_matches_naive():
    x = RNG.standard_normal((6, 256)).astype(np.float32)
    w = RNG.standard_normal(256).astype(np.float32)
    got = np.asarray(FF.fused_rms_norm(x, w))
    np.testing.assert_allclose(got, _naive_rms(x, w), rtol=1e-5, atol=1e-5)
    # residual variant returns (out, residual_out)
    r = RNG.standard_normal((6, 256)).astype(np.float32)
    out, res = FF.fused_rms_norm(x, w, residual=r)
    np.testing.assert_allclose(np.asarray(res), x + r, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out), _naive_rms(x + r, w),
                               rtol=1e-5, atol=1e-5)


def test_fused_rms_norm_grads():
    x = jnp.asarray(RNG.standard_normal((6, 256)), jnp.float32)
    w = jnp.asarray(RNG.standard_normal(256), jnp.float32)

    def fused(x, w):
        return jnp.sum(jnp.sin(FF.fused_rms_norm(x, w)))

    def naive(x, w):
        xf = x.astype(jnp.float32)
        y = xf * jax.lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True) + 1e-6) * w
        return jnp.sum(jnp.sin(y))

    g1 = jax.grad(fused, argnums=(0, 1))(x, w)
    g2 = jax.grad(naive, argnums=(0, 1))(x, w)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_fused_layer_norm_matches_naive():
    x = RNG.standard_normal((6, 256)).astype(np.float32)
    w = RNG.standard_normal(256).astype(np.float32)
    b = RNG.standard_normal(256).astype(np.float32)
    got = np.asarray(FF.fused_layer_norm(x, w, b))
    want = np.asarray(F.layer_norm(x, 256, w, b))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    gx = jax.grad(lambda x: jnp.sum(jnp.sin(
        FF.fused_layer_norm(x, jnp.asarray(w), jnp.asarray(b)))))(
            jnp.asarray(x))
    gx_ref = jax.grad(lambda x: jnp.sum(jnp.sin(
        F.layer_norm(x, 256, jnp.asarray(w), jnp.asarray(b)))))(
            jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_ref),
                               rtol=1e-4, atol=1e-5)


def test_fused_rope_matches_model_rope():
    from paddle_tpu.models.llama import apply_rotary_pos_emb, _rope_cache, LlamaConfig
    cfg = LlamaConfig(hidden_size=64, num_attention_heads=4,
                      max_position_embeddings=128)
    cos, sin = _rope_cache(cfg)
    q = jnp.asarray(RNG.standard_normal((2, 16, 4, 16)), jnp.float32)
    k = jnp.asarray(RNG.standard_normal((2, 16, 4, 16)), jnp.float32)
    qr, kr, _ = FF.fused_rotary_position_embedding(q, k, sin=sin, cos=cos)
    np.testing.assert_allclose(np.asarray(qr),
                               np.asarray(apply_rotary_pos_emb(q, cos, sin)),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(kr),
                               np.asarray(apply_rotary_pos_emb(k, cos, sin)),
                               rtol=1e-5, atol=1e-6)


def test_swiglu():
    x = jnp.asarray(RNG.standard_normal((4, 8)), jnp.float32)
    y = jnp.asarray(RNG.standard_normal((4, 8)), jnp.float32)
    np.testing.assert_allclose(np.asarray(FF.swiglu(x, y)),
                               np.asarray(F.silu(x) * y), rtol=1e-6)
    xy = jnp.concatenate([x, y], -1)
    np.testing.assert_allclose(np.asarray(FF.swiglu(xy)),
                               np.asarray(F.silu(x) * y), rtol=1e-6)


def test_fused_linear():
    x = RNG.standard_normal((4, 8)).astype(np.float32)
    w = RNG.standard_normal((8, 5)).astype(np.float32)
    b = RNG.standard_normal(5).astype(np.float32)
    with pt.core.flags.flag_guard(matmul_precision="highest"):
        np.testing.assert_allclose(np.asarray(FF.fused_linear(x, w, b)),
                                   x @ w + b, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(FF.fused_linear(x, w.T, b, transpose_weight=True)),
            x @ w + b, rtol=1e-5, atol=1e-5)


def test_fused_dropout_add():
    x = jnp.ones((64, 64))
    y = jnp.full((64, 64), 2.0)
    out = FF.fused_dropout_add(x, y, p=0.5, training=True,
                               key=jax.random.key(0))
    kept = np.asarray(out) != 2.0
    assert 0.3 < kept.mean() < 0.7
    np.testing.assert_allclose(np.asarray(out)[kept], 4.0)
    np.testing.assert_allclose(
        np.asarray(FF.fused_dropout_add(x, y, p=0.5, training=False)), 3.0)


def test_masked_mha_decode_matches_full_attention():
    b, S, h, kvh, d = 2, 16, 4, 2, 8
    keys = jnp.asarray(RNG.standard_normal((b, S, kvh, d)), jnp.float32)
    vals = jnp.asarray(RNG.standard_normal((b, S, kvh, d)), jnp.float32)
    n_ctx = 5  # tokens already in cache
    cache_k = jnp.zeros((b, S, kvh, d)).at[:, :n_ctx].set(keys[:, :n_ctx])
    cache_v = jnp.zeros((b, S, kvh, d)).at[:, :n_ctx].set(vals[:, :n_ctx])
    q = jnp.asarray(RNG.standard_normal((b, 1, h, d)), jnp.float32)
    k_new = keys[:, n_ctx:n_ctx + 1]
    v_new = vals[:, n_ctx:n_ctx + 1]
    seq_lens = jnp.full((b,), n_ctx, jnp.int32)
    out, ck, cv = FF.masked_multihead_attention(q, k_new, v_new, cache_k,
                                                cache_v, seq_lens)
    # reference: full attention of q over the first n_ctx+1 k/v
    kf = jnp.repeat(keys[:, :n_ctx + 1], h // kvh, axis=2)
    vf = jnp.repeat(vals[:, :n_ctx + 1], h // kvh, axis=2)
    from paddle_tpu.nn.functional.attention import _xla_attention
    ref = _xla_attention(q, kf, vf, is_causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ck[:, n_ctx]),
                               np.asarray(k_new[:, 0]))


def test_block_mha_matches_masked_mha():
    """Paged KV (block pool + tables) must equal the contiguous cache."""
    b, h, kvh, d, bs = 2, 4, 2, 8, 4
    max_blocks = 4
    S = bs * max_blocks
    nb = b * max_blocks
    pool_k = jnp.zeros((nb, bs, kvh, d))
    pool_v = jnp.zeros((nb, bs, kvh, d))
    # sequence i owns interleaved pages (exercises non-contiguous tables)
    tables = jnp.asarray(
        np.stack([np.arange(max_blocks) * b + i for i in range(b)]), jnp.int32)
    keys = jnp.asarray(RNG.standard_normal((b, S, kvh, d)), jnp.float32)
    vals = jnp.asarray(RNG.standard_normal((b, S, kvh, d)), jnp.float32)
    n_ctx = 6
    # scatter context into the pools page by page
    for i in range(b):
        for t in range(n_ctx):
            pool_k = pool_k.at[tables[i, t // bs], t % bs].set(keys[i, t])
            pool_v = pool_v.at[tables[i, t // bs], t % bs].set(vals[i, t])
    q = jnp.asarray(RNG.standard_normal((b, 1, h, d)), jnp.float32)
    k_new = keys[:, n_ctx:n_ctx + 1]
    v_new = vals[:, n_ctx:n_ctx + 1]
    seq_lens = jnp.full((b,), n_ctx, jnp.int32)
    out, pk, pv = FF.block_multihead_attention(q, pool_k, pool_v, tables,
                                               seq_lens, k_new, v_new)
    cache_k = jnp.zeros((b, S, kvh, d)).at[:, :n_ctx].set(keys[:, :n_ctx])
    cache_v = jnp.zeros((b, S, kvh, d)).at[:, :n_ctx].set(vals[:, :n_ctx])
    ref, _, _ = FF.masked_multihead_attention(q, k_new, v_new, cache_k,
                                              cache_v, seq_lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_varlen_flash_matches_loop():
    from paddle_tpu.ops.pallas.flash_attention import flash_attn_unpadded
    from paddle_tpu.nn.functional.attention import _xla_attention
    lens = [48, 96, 32]
    cu = np.concatenate([[0], np.cumsum(lens)])
    T, h, d = int(cu[-1]), 4, 32
    q = jnp.asarray(RNG.standard_normal((T, h, d)), jnp.float32)
    k = jnp.asarray(RNG.standard_normal((T, h, d)), jnp.float32)
    v = jnp.asarray(RNG.standard_normal((T, h, d)), jnp.float32)
    for causal in (False, True):
        out = flash_attn_unpadded(q, k, v, cu, cu, causal=causal)
        ref = jnp.concatenate([
            _xla_attention(q[s:e][None], k[s:e][None], v[s:e][None],
                           is_causal=causal)[0]
            for s, e in zip(cu[:-1], cu[1:])], axis=0)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)
        gk = jax.grad(lambda k: jnp.sum(jnp.sin(
            flash_attn_unpadded(q, k, v, cu, cu, causal=causal))))(k)
        gk_ref = jax.grad(lambda k: jnp.sum(jnp.sin(jnp.concatenate([
            _xla_attention(q[s:e][None], k[s:e][None], v[s:e][None],
                           is_causal=causal)[0]
            for s, e in zip(cu[:-1], cu[1:])], axis=0))))(k)
        np.testing.assert_allclose(np.asarray(gk), np.asarray(gk_ref),
                                   rtol=1e-3, atol=1e-4)


def test_fused_multi_transformer_decode_matches_prefill():
    """Token-by-token decode through caches must reproduce the no-cache
    forward logits position by position."""
    pt.seed(3)
    m = inn.FusedMultiTransformer(embed_dim=32, num_heads=4,
                                  dim_feedforward=64, num_layers=2,
                                  num_key_value_heads=2)
    m.eval()
    x = jnp.asarray(RNG.standard_normal((2, 8, 32)), jnp.float32)
    full = m(x)
    caches = m.init_caches(2, 8, dtype=jnp.float32)
    outs = []
    for t in range(8):
        o, caches = m(x[:, t:t + 1], caches=caches,
                      seq_lens=jnp.full((2,), t, jnp.int32))
        outs.append(o)
    stepwise = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(stepwise), np.asarray(full),
                               rtol=1e-3, atol=1e-4)


def test_fused_encoder_layer_runs_and_trains():
    pt.seed(4)
    layer = inn.FusedTransformerEncoderLayer(32, 4, 64, dropout_rate=0.0)
    x = jnp.asarray(RNG.standard_normal((2, 8, 32)), jnp.float32)
    y = layer(x)
    assert y.shape == x.shape
    lin = inn.FusedLinear(32, 8)
    assert lin(x).shape == (2, 8, 8)
    bdrln = inn.FusedBiasDropoutResidualLayerNorm(32, dropout_rate=0.0)
    assert bdrln(x, x).shape == x.shape


def test_llama_generate_greedy_consistent():
    """generate() (prefill + fused decode steps) must equal the argmax chain
    computed with full forwards at every step."""
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    pt.seed(5)
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=96,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64,
                      mp_axis=None, fsdp_axis=None)
    model = LlamaForCausalLM(cfg)
    model.eval()
    ids = jnp.asarray(RNG.integers(0, 64, (2, 5)))
    out = model.generate(ids, max_new_tokens=6)
    assert out.shape == (2, 11)
    # reference: recompute with full forward each step
    cur = ids
    for _ in range(6):
        logits = model(cur)
        nxt = jnp.argmax(logits[:, -1], axis=-1)
        cur = jnp.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(cur))


def test_llama_generate_scan_matches_eager_loop():
    """The one-program lax.scan decode (jit_loop=True, default) must produce
    the same tokens as the per-token eager loop, greedy AND sampled (same
    seed -> same nucleus draws)."""
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    pt.seed(9)
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=96,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64,
                      mp_axis=None, fsdp_axis=None)
    model = LlamaForCausalLM(cfg)
    model.eval()
    ids = jnp.asarray(RNG.integers(0, 64, (2, 5)))
    a = model.generate(ids, max_new_tokens=7, jit_loop=True)
    b = model.generate(ids, max_new_tokens=7, jit_loop=False)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    s1 = model.generate(ids, max_new_tokens=7, do_sample=True, top_p=0.9,
                        seed=3, jit_loop=True)
    s2 = model.generate(ids, max_new_tokens=7, do_sample=True, top_p=0.9,
                        seed=3, jit_loop=False)
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))
