"""Family tails from VERDICT r2 item 8: MultivariateNormal, geometric
reindex/sampling, audio backends + datasets (parity:
distribution/multivariate_normal.py, geometric/reindex.py,
geometric/sampling/neighbors.py, audio/backends/wave_backend.py,
audio/datasets/)."""

import csv
import os

import numpy as np
import pytest
import scipy.stats

import paddle_tpu as pt
from paddle_tpu import audio, geometric
from paddle_tpu.distribution import MultivariateNormal, kl_divergence

RNG = np.random.default_rng(0)


# ---------------- MultivariateNormal ----------------

def _random_spd(k, rng):
    a = rng.standard_normal((k, k))
    return a @ a.T + k * np.eye(k)


def test_mvn_log_prob_entropy_match_scipy():
    k = 4
    cov = _random_spd(k, RNG)
    loc = RNG.standard_normal(k)
    rv = MultivariateNormal(loc=loc.astype(np.float32),
                            covariance_matrix=cov.astype(np.float32))
    ref = scipy.stats.multivariate_normal(loc, cov)
    x = RNG.standard_normal((5, k)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(rv.log_prob(x)), ref.logpdf(x),
                               rtol=1e-4)
    np.testing.assert_allclose(float(rv.entropy()), ref.entropy(), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(rv.covariance_matrix), cov,
                               rtol=1e-4)


def test_mvn_three_parameterizations_agree():
    k = 3
    cov = _random_spd(k, RNG).astype(np.float32)
    loc = np.zeros(k, np.float32)
    a = MultivariateNormal(loc, covariance_matrix=cov)
    b = MultivariateNormal(loc, scale_tril=np.linalg.cholesky(cov))
    c = MultivariateNormal(loc, precision_matrix=np.linalg.inv(cov))
    x = RNG.standard_normal((4, k)).astype(np.float32)
    for other in (b, c):
        np.testing.assert_allclose(np.asarray(a.log_prob(x)),
                                   np.asarray(other.log_prob(x)), rtol=1e-3,
                                   atol=1e-4)


def test_mvn_sample_moments_and_kl():
    k = 2
    cov = np.array([[2.0, 1.0], [1.0, 2.0]], np.float32)
    rv = MultivariateNormal(np.array([2.0, 5.0], np.float32),
                            covariance_matrix=cov)
    pt.seed(0)
    s = np.asarray(rv.sample((8000,)))
    assert s.shape == (8000, 2)
    np.testing.assert_allclose(s.mean(0), [2.0, 5.0], atol=0.1)
    np.testing.assert_allclose(np.cov(s.T), cov, atol=0.2)
    # KL(p, p) == 0; KL vs shifted mean = 0.5 m^T Sigma^-1 m
    assert abs(float(kl_divergence(rv, rv))) < 1e-5
    rv2 = MultivariateNormal(np.array([3.0, 5.0], np.float32),
                             covariance_matrix=cov)
    m = np.array([1.0, 0.0])
    want = 0.5 * m @ np.linalg.inv(cov) @ m
    np.testing.assert_allclose(float(kl_divergence(rv, rv2)), want,
                               rtol=1e-4)


def test_mvn_rejects_bad_args():
    with pytest.raises(ValueError):
        MultivariateNormal([0.0, 0.0])
    with pytest.raises(ValueError):
        MultivariateNormal([0.0, 0.0], covariance_matrix=np.eye(2),
                           scale_tril=np.eye(2))


# ---------------- geometric graph preprocessing ----------------

def test_reindex_graph_reference_example():
    # the exact example documented at geometric/reindex.py reindex_graph
    src, dst, nodes = geometric.reindex_graph([0, 1, 2], [8, 9, 0, 4, 7, 6, 7],
                                              [2, 3, 2])
    assert src.tolist() == [3, 4, 0, 5, 6, 7, 6]
    assert dst.tolist() == [0, 0, 1, 1, 1, 2, 2]
    assert nodes.tolist() == [0, 1, 2, 8, 9, 4, 7, 6]


def test_reindex_graph_rejects_duplicates_and_bad_count():
    with pytest.raises(ValueError):
        geometric.reindex_graph([0, 0], [1, 2], [1, 1])
    with pytest.raises(ValueError):
        geometric.reindex_graph([0, 1], [1, 2, 3], [1, 1])


ROW = np.array([3, 7, 0, 9, 1, 4, 2, 9, 3, 9, 1, 9, 7])
COLPTR = np.array([0, 2, 4, 5, 6, 7, 9, 11, 11, 13, 13])


def test_sample_neighbors_counts_and_membership():
    pt.seed(4)
    out, cnt = geometric.sample_neighbors(ROW, COLPTR, [0, 8, 1, 2],
                                          sample_size=2)
    assert cnt.tolist() == [2, 2, 2, 1]  # node 2 has a single neighbor
    # every sampled neighbor must come from the node's CSC slice
    off = 0
    for node, c in zip([0, 8, 1, 2], cnt.tolist()):
        allowed = set(ROW[COLPTR[node]:COLPTR[node + 1]].tolist())
        assert set(out[off:off + c].tolist()) <= allowed
        off += c
    # sample_size=-1 returns everything
    out_all, cnt_all = geometric.sample_neighbors(ROW, COLPTR, [0, 1],
                                                  sample_size=-1)
    assert cnt_all.tolist() == [2, 2]


def test_sample_neighbors_eids_track_picks():
    pt.seed(9)
    eids = np.arange(len(ROW)) + 100
    out, cnt, oe = geometric.sample_neighbors(ROW, COLPTR, [0, 6],
                                              sample_size=1, eids=eids,
                                              return_eids=True)
    # each returned eid must point at the returned neighbor
    for nb, e in zip(out.tolist(), oe.tolist()):
        assert ROW[e - 100] == nb
    with pytest.raises(ValueError):
        geometric.sample_neighbors(ROW, COLPTR, [0], return_eids=True)


def test_weighted_sample_neighbors_respects_weights():
    # one neighbor has overwhelming weight -> it is (almost) always picked
    pt.seed(1)
    row = np.array([0, 1, 2, 3])
    colptr = np.array([0, 4])
    w = np.array([1e-6, 1e-6, 1e6, 1e-6])
    hits = 0
    for _ in range(20):
        out, cnt = geometric.weighted_sample_neighbors(row, colptr, w, [0],
                                                       sample_size=1)
        hits += int(out[0] == 2)
    assert hits >= 19
    # sample_size=0 returns nothing (uniform and weighted agree)
    out0, cnt0 = geometric.weighted_sample_neighbors(row, colptr, w, [0],
                                                     sample_size=0)
    assert len(out0) == 0 and cnt0.tolist() == [0]
    out0u, cnt0u = geometric.sample_neighbors(row, colptr, [0], sample_size=0)
    assert len(out0u) == 0 and cnt0u.tolist() == [0]


# ---------------- audio backends + datasets ----------------

def _write_wav(path, sr=16000, seconds=0.05, channels=1, freq=440.0):
    t = np.arange(int(sr * seconds)) / sr
    wav = 0.4 * np.sin(2 * np.pi * freq * t).astype(np.float32)
    wav = np.tile(wav[None, :], (channels, 1))
    audio.save(str(path), wav, sr)
    return wav


def test_wave_backend_save_load_info_roundtrip(tmp_path):
    p = tmp_path / "t.wav"
    wav = _write_wav(p, channels=2)
    meta = audio.info(str(p))
    assert (meta.sample_rate, meta.num_channels) == (16000, 2)
    assert meta.bits_per_sample == 16
    got, sr = audio.load(str(p))
    assert sr == 16000
    assert got.shape == wav.shape
    np.testing.assert_allclose(np.asarray(got), wav, atol=2 / 2 ** 15)
    # frame windowing + channels_last
    got2, _ = audio.load(str(p), frame_offset=10, num_frames=20,
                         channels_first=False)
    assert got2.shape == (20, 2)
    np.testing.assert_allclose(np.asarray(got2), np.asarray(got).T[10:30],
                               atol=1e-7)


def test_backend_selection():
    assert audio.backends.get_current_backend() == "wave_backend"
    assert "wave_backend" in audio.backends.list_available_backends()
    with pytest.raises(NotImplementedError):
        audio.backends.set_backend("nonexistent")


def test_esc50_local_meta_and_features(tmp_path):
    # fabricate a tiny local ESC-50 layout
    root = tmp_path
    audio_dir = root / "ESC-50-master" / "audio"
    meta_dir = root / "ESC-50-master" / "meta"
    os.makedirs(audio_dir)
    os.makedirs(meta_dir)
    rows = [("a.wav", 1, 0), ("b.wav", 1, 3), ("c.wav", 2, 7)]
    with open(meta_dir / "esc50.csv", "w", newline="") as f:
        wr = csv.writer(f)
        wr.writerow(["filename", "fold", "target"])
        for fn, fold, tgt in rows:
            wr.writerow([fn, fold, tgt])
            _write_wav(audio_dir / fn, seconds=0.1)
    train = audio.datasets.ESC50(mode="train", split=1, data_dir=str(root))
    dev = audio.datasets.ESC50(mode="dev", split=1, data_dir=str(root))
    assert len(train) == 1 and len(dev) == 2  # fold 1 held out of train
    x, y = train[0]
    assert int(y) == 7 and x.ndim == 1
    feat = audio.datasets.ESC50(mode="dev", split=1, data_dir=str(root),
                                feat_type="mfcc", n_mfcc=13, n_fft=256)
    fx, fy = feat[0]
    assert fx.shape[0] == 13 and int(fy) == 0


def test_esc50_without_data_dir_names_the_archive():
    with pytest.raises(RuntimeError, match="ESC-50"):
        audio.datasets.ESC50(data_dir=None)


# ---------------- hub + utils tails ----------------

def test_hub_local_repo_protocol(tmp_path):
    (tmp_path / "hubconf.py").write_text(
        "dependencies = ['numpy']\n"
        "def tiny_model(width=4):\n"
        "    'builds a tiny model'\n"
        "    return {'width': width}\n")
    import paddle_tpu.hub as hub
    assert hub.list(str(tmp_path), source="local") == ["tiny_model"]
    assert "tiny" in hub.help(str(tmp_path), "tiny_model", source="local")
    got = hub.load(str(tmp_path), "tiny_model", source="local", width=8)
    assert got == {"width": 8}
    with pytest.raises(RuntimeError, match="egress"):
        hub.load("o/repo", "m", source="github")
    with pytest.raises(RuntimeError, match="available"):
        hub.load(str(tmp_path), "nope", source="local")


def test_hub_missing_dependency_named(tmp_path):
    (tmp_path / "hubconf.py").write_text(
        "dependencies = ['not_a_real_pkg_xyz']\n"
        "def m():\n    return 1\n")
    import paddle_tpu.hub as hub
    with pytest.raises(RuntimeError, match="not_a_real_pkg_xyz"):
        hub.list(str(tmp_path), source="local")


def test_dlpack_roundtrip_with_torch():
    import torch
    import jax.numpy as jnp
    from paddle_tpu.utils import dlpack
    t = torch.arange(6, dtype=torch.float32).reshape(2, 3)
    arr = dlpack.from_dlpack(t)  # torch -> jax via __dlpack__
    np.testing.assert_allclose(np.asarray(arr), t.numpy())
    cap = dlpack.to_dlpack(jnp.asarray([1.0, 2.0]))
    back = torch.utils.dlpack.from_dlpack(cap)
    np.testing.assert_allclose(back.numpy(), [1.0, 2.0])


def test_unique_name_generate_and_guard():
    from paddle_tpu.utils import unique_name
    with unique_name.guard():
        a = unique_name.generate("fc")
        b = unique_name.generate("fc")
        c = unique_name.generate("conv")
    assert (a, b, c) == ("fc_0", "fc_1", "conv_0")
    with unique_name.guard():
        assert unique_name.generate("fc") == "fc_0"  # fresh namespace


def test_deprecated_and_try_import():
    from paddle_tpu.utils import deprecated, try_import
    import warnings

    @deprecated(update_to="new_fn", since="2.0")
    def old_fn():
        return 42

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert old_fn() == 42
    assert any("new_fn" in str(x.message) for x in w)
    assert try_import("math") is not None
    with pytest.raises(ImportError):
        try_import("definitely_not_installed_xyz")


# ---------------- sparse tail ----------------

def test_sparse_unary_tail_and_coalesce():
    import paddle_tpu.sparse as S
    import jax.numpy as jnp
    x = S.sparse_coo_tensor([[0, 1], [1, 2]], [0.5, -0.25], (2, 3))
    for name in ("asin", "atan", "sinh", "tan", "expm1", "log1p",
                 "rad2deg", "deg2rad"):
        out = getattr(S, name)(x)
        ref = getattr(np, {"asin": "arcsin", "atan": "arctan"}.get(name, name))
        np.testing.assert_allclose(np.asarray(out.data),
                                   ref(np.array([0.5, -0.25])), rtol=1e-5)
    dup = S.sparse_coo_tensor([[0, 0], [1, 1]], [1.0, 2.0], (2, 2))
    co = S.coalesce(dup)
    assert int(S.nnz(co)) <= 2
    np.testing.assert_allclose(np.asarray(S.to_dense(co)),
                               [[0, 3], [0, 0]])
    assert S.is_same_shape(x, S.reshape(x, (3, 2))) is False
    v = S.mv(x, np.ones(3, np.float32))
    np.testing.assert_allclose(np.asarray(v), [0.5, -0.25])
    out = S.addmm(np.ones((2, 2), np.float32), x,
                  np.ones((3, 2), np.float32), beta=2.0, alpha=3.0)
    np.testing.assert_allclose(np.asarray(out),
                               2.0 + 3.0 * np.array([[0.5, 0.5],
                                                     [-0.25, -0.25]]))


def test_sparse_nn_softmax_and_batchnorm():
    import paddle_tpu.sparse as S
    rows = [[0, 0, 1], [0, 2, 1]]
    x = S.sparse_coo_tensor(rows, [1.0, 2.0, 3.0], (2, 3))
    sm = S.nn.Softmax()(x)
    d = np.asarray(S.to_dense(sm))
    # row sums over STORED entries are 1; implicit zeros stay zero
    np.testing.assert_allclose(d[0].sum(), 1.0, rtol=1e-6)
    np.testing.assert_allclose(d[1], [0, 1.0, 0], atol=1e-6)
    bn = S.nn.BatchNorm(4)
    vals = RNG.standard_normal((6, 4)).astype(np.float32) * 3 + 1
    xx = S.sparse_coo_tensor([[0, 1, 2, 3, 4, 5]], vals, (8, 4))
    out = bn(xx)
    od = np.asarray(out.data)
    np.testing.assert_allclose(od.mean(0), 0.0, atol=1e-4)
    np.testing.assert_allclose(od.std(0), 1.0, atol=1e-2)


def test_sparse_subm_conv_preserves_pattern():
    import paddle_tpu.sparse as S
    import paddle_tpu as pt
    pt.seed(0)
    dense = np.zeros((1, 4, 4, 4, 2), np.float32)
    dense[0, 1, 1, 1] = [1.0, 2.0]
    dense[0, 3, 2, 0] = [3.0, 1.0]
    x = S.to_sparse_coo(dense)
    conv = S.nn.SubmConv3D(2, 5, 3)
    out = conv(x)
    od = np.asarray(S.to_dense(out))
    active = np.abs(od).sum(-1) > 0
    want = np.abs(dense).sum(-1) > 0
    np.testing.assert_array_equal(active, want)  # no sparsity dilation
    # plain Conv3D dilates
    conv2 = S.nn.Conv3D(2, 5, 3, padding=1)
    out2 = np.asarray(S.to_dense(conv2(x)))
    assert (np.abs(out2).sum(-1) > 0).sum() > want.sum()
    # pool runs and keeps shape contract
    pooled = S.nn.MaxPool3D(2)(x)
    assert pooled.shape == (1, 2, 2, 2, 2)


def test_sparse_subm_conv_masks_by_coordinates_not_values():
    # an active site with MIXED stored values (one channel zeroed by
    # relu) must survive and stay the ONLY active output site; masking
    # is by coordinate set, so neighbors never activate (no dilation)
    import paddle_tpu.sparse as S
    import paddle_tpu as pt
    pt.seed(1)
    dense = np.zeros((1, 3, 3, 3, 2), np.float32)
    dense[0, 1, 1, 1] = [-5.0, 2.0]  # relu keeps channel 1 only
    xs = S.relu(S.to_sparse_coo(dense))
    conv = S.nn.SubmConv3D(2, 3, 3)
    out = conv(xs)
    od = np.asarray(S.to_dense(out))
    assert np.abs(od[0, 1, 1, 1]).sum() > 0
    assert (np.abs(od).sum((0, 4)) > 0).sum() == 1  # only that site


def test_sparse_batchnorm_guards():
    import paddle_tpu.sparse as S
    import pytest as _pytest
    bn = S.nn.BatchNorm(2)
    with _pytest.raises(ValueError):
        S.nn.BatchNorm(2, data_format="NCDHW")
    with _pytest.raises(ValueError):
        bn(S.to_sparse_csr(np.eye(2, dtype=np.float32)))
    # dense >2D input: stats stay (C,)-shaped
    out = bn(np.ones((2, 3, 3, 3, 2), np.float32))
    assert bn._mean.shape == (2,)
    assert out.shape == (2, 3, 3, 3, 2)
