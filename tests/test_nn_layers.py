"""Layer behavior tests (parity model: test/legacy_test per-layer tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.nn import functional as F

RNG = np.random.default_rng(1)


def test_linear_matches_manual():
    m = nn.Linear(6, 4)
    x = RNG.standard_normal((3, 6)).astype(np.float32)
    got = np.asarray(m(x))
    want = x @ np.asarray(m.weight) + np.asarray(m.bias)
    # default matmul precision is reduced (MXU-class); assert within bf16 error
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)


def test_conv2d_matches_scipy_style():
    m = nn.Conv2D(2, 3, 3, padding=1)
    x = RNG.standard_normal((1, 2, 5, 5)).astype(np.float32)
    out = np.asarray(m(x))
    assert out.shape == (1, 3, 5, 5)
    # naive direct convolution check at one output position
    w = np.asarray(m.weight)
    b = np.asarray(m.bias)
    xp = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
    want = (xp[0, :, 1:4, 1:4] * w[1]).sum() + b[1]
    np.testing.assert_allclose(out[0, 1, 1, 1], want, rtol=2e-2, atol=2e-2)


def test_conv_transpose_shape_inverts_conv():
    x = RNG.standard_normal((2, 4, 8, 8)).astype(np.float32)
    down = nn.Conv2D(4, 8, 3, stride=2, padding=1)
    up = nn.Conv2DTranspose(8, 4, 3, stride=2, padding=1, output_padding=1)
    y = down(x)
    z = up(y)
    assert y.shape == (2, 8, 4, 4)
    assert z.shape == (2, 4, 8, 8)


def test_batchnorm_stats_update_and_eval():
    m = nn.BatchNorm2D(3, momentum=0.5)
    x = RNG.standard_normal((8, 3, 4, 4)).astype(np.float32) * 2 + 1
    m.train()
    y = m(x)
    # normalized output: near zero mean, unit var per channel
    ym = np.asarray(y).mean(axis=(0, 2, 3))
    np.testing.assert_allclose(ym, 0, atol=1e-5)
    new_mean = np.asarray(m._mean)
    assert not np.allclose(new_mean, 0)  # stats moved
    m.eval()
    y2 = m(x)
    assert y2.shape == x.shape


def test_layernorm_and_rmsnorm():
    x = RNG.standard_normal((4, 10)).astype(np.float32)
    ln = nn.LayerNorm(10)
    y = np.asarray(ln(x))
    np.testing.assert_allclose(y.mean(-1), 0, atol=1e-5)
    np.testing.assert_allclose(y.std(-1), 1, atol=1e-2)
    rn = nn.RMSNorm(10)
    y2 = np.asarray(rn(x))
    want = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(y2, want, rtol=1e-4, atol=1e-5)


def test_dropout_train_eval_and_determinism_under_key():
    x = np.ones((1000,), np.float32)
    d = nn.Dropout(0.5)
    d.train()
    y = np.asarray(d(x))
    assert 0.3 < (y == 0).mean() < 0.7
    assert np.allclose(y[y != 0], 2.0)  # upscale_in_train
    d.eval()
    np.testing.assert_allclose(np.asarray(d(x)), x)


def test_embedding_padding_idx():
    e = nn.Embedding(10, 4, padding_idx=0)
    out = np.asarray(e(np.array([[0, 1], [2, 0]])))
    np.testing.assert_allclose(out[0, 0], 0)
    np.testing.assert_allclose(out[1, 1], 0)
    assert not np.allclose(out[0, 1], 0)


def test_pooling():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    mp = np.asarray(F.max_pool2d(x, 2, 2))
    np.testing.assert_allclose(mp[0, 0], [[5, 7], [13, 15]])
    ap = np.asarray(F.avg_pool2d(x, 2, 2))
    np.testing.assert_allclose(ap[0, 0], [[2.5, 4.5], [10.5, 12.5]])
    aap = np.asarray(F.adaptive_avg_pool2d(x, 1))
    np.testing.assert_allclose(aap[0, 0, 0, 0], 7.5)


def test_activations_shapes_and_values():
    x = np.array([-2.0, -0.5, 0.0, 0.5, 2.0], np.float32)
    np.testing.assert_allclose(np.asarray(F.relu(x)), np.maximum(x, 0))
    np.testing.assert_allclose(np.asarray(F.hardswish(x)),
                               x * np.clip(x + 3, 0, 6) / 6, rtol=1e-6)
    s = np.asarray(F.softmax(x))
    np.testing.assert_allclose(s.sum(), 1.0, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(F.glu(np.concatenate([x, x]))),
                               x * (1 / (1 + np.exp(-x))), rtol=1e-5)


def test_losses():
    logits = RNG.standard_normal((6, 5)).astype(np.float32)
    labels = RNG.integers(0, 5, 6)
    ce = float(F.cross_entropy(logits, labels))
    # manual
    p = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    want = -np.log(p[np.arange(6), labels]).mean()
    np.testing.assert_allclose(ce, want, rtol=1e-4)
    # ignore_index
    labels2 = labels.copy()
    labels2[0] = -100
    ce2 = float(F.cross_entropy(logits, labels2))
    want2 = -np.log(p[np.arange(1, 6), labels[1:]]).mean()
    np.testing.assert_allclose(ce2, want2, rtol=1e-4)
    # bce with logits stability
    big = np.array([100.0, -100.0], np.float32)
    tgt = np.array([1.0, 0.0], np.float32)
    assert float(F.binary_cross_entropy_with_logits(big, tgt)) < 1e-6
    # mse/l1/smooth
    a, b = np.ones((3,), np.float32), np.zeros((3,), np.float32)
    assert float(F.mse_loss(a, b)) == 1.0
    assert float(F.l1_loss(a, b)) == 1.0
    np.testing.assert_allclose(float(F.smooth_l1_loss(a, b)), 0.5)


def test_ctc_loss_simple():
    # T=4, B=1, C=3 with uniform logits: loss = -log P(path)
    T, B, C, L = 4, 1, 3, 2
    logp = np.log(np.full((T, B, C), 1.0 / C, np.float32))
    labels = np.array([[1, 2]], np.int32)
    loss = float(F.ctc_loss(logp, labels, np.array([T]), np.array([L]),
                            reduction="none")[0])
    # brute force over all paths of length 4 collapsing to [1,2]
    import itertools
    total = 0.0
    for path in itertools.product(range(C), repeat=T):
        collapsed = []
        prev = None
        for s in path:
            if s != prev and s != 0:
                collapsed.append(s)
            prev = s
        if collapsed == [1, 2]:
            total += (1.0 / C) ** T
    np.testing.assert_allclose(loss, -np.log(total), rtol=1e-4)


def test_attention_matches_reference():
    q = RNG.standard_normal((2, 8, 4, 16)).astype(np.float32)
    k = RNG.standard_normal((2, 8, 4, 16)).astype(np.float32)
    v = RNG.standard_normal((2, 8, 4, 16)).astype(np.float32)
    out = np.asarray(F.scaled_dot_product_attention(q, k, v))
    # manual for head 0, batch 0
    s = (q[0, :, 0] @ k[0, :, 0].T) / np.sqrt(16)
    p = np.exp(s) / np.exp(s).sum(-1, keepdims=True)
    want = p @ v[0, :, 0]
    np.testing.assert_allclose(out[0, :, 0], want, rtol=2e-2, atol=2e-2)
    # causal
    outc = np.asarray(F.scaled_dot_product_attention(q, k, v, is_causal=True))
    sc = np.where(np.tril(np.ones((8, 8))) > 0, s, -np.inf)
    pc = np.exp(sc) / np.exp(sc).sum(-1, keepdims=True)
    np.testing.assert_allclose(outc[0, :, 0], pc @ v[0, :, 0], rtol=2e-2, atol=2e-2)


def test_multihead_attention_and_cache():
    m = nn.MultiHeadAttention(32, 4)
    x = RNG.standard_normal((2, 6, 32)).astype(np.float32)
    y = m(x)
    assert y.shape == (2, 6, 32)
    cache = m.gen_cache(x[:, :0])
    step_outs = []
    for t in range(3):
        o, cache = m(x[:, t:t + 1], x[:, t:t + 1], x[:, t:t + 1], None, cache)
        step_outs.append(o)
    full = m(x[:, :3], attn_mask=None)  # full attention differs (causality)
    assert cache.k.shape[1] == 3


def test_transformer_encoder():
    layer = nn.TransformerEncoderLayer(d_model=32, nhead=4, dim_feedforward=64)
    enc = nn.TransformerEncoder(layer, 2)
    x = RNG.standard_normal((2, 5, 32)).astype(np.float32)
    enc.eval()
    assert enc(x).shape == (2, 5, 32)


def test_state_dict_roundtrip_and_save_load(tmp_path):
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    sd = m.state_dict()
    assert set(sd) == {"0.weight", "0.bias", "2.weight", "2.bias"}
    path = str(tmp_path / "model.pdparams")
    pt.save(sd, path)
    loaded = pt.load(path)
    m2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    m2.set_state_dict(loaded)
    x = RNG.standard_normal((3, 4)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(m(x)), np.asarray(m2(x)), rtol=1e-6)


def test_functional_call_purity():
    m = nn.BatchNorm1D(4, data_format="NCL")
    x = RNG.standard_normal((8, 4, 3)).astype(np.float32)
    state = m.state_dict(include_non_persistable_buffer=True)
    before = {k: np.asarray(v) for k, v in m.buffer_dict().items()}
    out, new_buffers = nn.functional_call(m, state, x, training=True)
    # module unchanged (purity), new stats returned
    for k, v in m.buffer_dict().items():
        np.testing.assert_allclose(np.asarray(v), before[k])
    assert any(not np.allclose(np.asarray(new_buffers[k]), before[k])
               for k in new_buffers)


def test_jit_of_functional_call_works():
    m = nn.Linear(4, 4)

    @jax.jit
    def f(state, x):
        out, _ = nn.functional_call(m, state, x)
        return out.sum()

    x = jnp.ones((2, 4))
    v1 = f(m.state_dict(), x)
    v2 = f(m.state_dict(), x)
    assert np.isfinite(float(v1)) and float(v1) == float(v2)


def test_grad_clip():
    grads = {"a": jnp.ones((10,)) * 3, "b": jnp.ones((5,)) * 4}
    clipped = nn.ClipGradByGlobalNorm(1.0)(grads)
    n = float(nn.clip.global_norm(clipped))
    np.testing.assert_allclose(n, 1.0, rtol=1e-5)
    cv = nn.ClipGradByValue(0.5)(grads)
    assert float(jnp.max(cv["b"])) == 0.5


def test_lazy_guard_abstract_init_and_aot_lower():
    """paddle.LazyGuard parity (fluid/lazy_init.py): layers built inside the
    guard carry ShapeDtypeStruct params (zero memory), usable for
    eval_shape and AOT .lower().compile() memory/sharding planning; outside
    the guard behavior is unchanged."""
    import jax
    import paddle_tpu as pt
    from paddle_tpu import nn
    from paddle_tpu.nn.module import functional_call

    with pt.LazyGuard():
        m = nn.Sequential(nn.Linear(16, 64), nn.ReLU(), nn.Linear(64, 4))
    params = m.param_dict()
    assert params and all(isinstance(v, jax.ShapeDtypeStruct)
                          for v in params.values()), {
                              k: type(v) for k, v in params.items()}
    assert params["0.weight"].shape == (16, 64)
    assert params["0.weight"].dtype == jnp.float32

    # abstract end-to-end: eval_shape through functional_call (rngs
    # passed explicitly -- the functional-core convention under transforms)
    x = jax.ShapeDtypeStruct((2, 16), jnp.float32)
    key = jax.random.key(0)
    out, _ = jax.eval_shape(
        lambda p, x: functional_call(m, p, x, rngs=key, training=False),
        params, x)
    assert out.shape == (2, 4)

    # AOT: lower + compile with abstract params, no materialization
    compiled = jax.jit(
        lambda p, x: functional_call(m, p, x, rngs=key, training=False)[0]
    ).lower(params, x).compile()
    assert compiled is not None

    # guard exited: construction is concrete again
    m2 = nn.Linear(4, 4)
    assert isinstance(m2.weight, jax.Array)

    # optimizer state planning over abstract params
    opt = pt.optimizer.AdamW(learning_rate=1e-3, parameters=m)
    st = jax.eval_shape(opt.init_state, params)
    assert st["moment1"]["0.weight"].shape == (16, 64)


def test_lazy_guard_embedding_padding_idx():
    """Embedding with padding_idx must construct under LazyGuard (the
    padding-row zeroing is a concrete-weight transform)."""
    import jax
    import paddle_tpu as pt
    from paddle_tpu import nn
    with pt.LazyGuard():
        e = nn.Embedding(100, 16, padding_idx=0)
    assert isinstance(e.weight, jax.ShapeDtypeStruct)
    assert e.weight.shape == (100, 16)
    e2 = nn.Embedding(10, 4, padding_idx=0)  # concrete: row 0 zeroed
    assert float(jnp.abs(e2.weight[0]).sum()) == 0.0
