"""Optimizer + LR scheduler tests (parity model: test/legacy_test/test_adam_op.py
style numeric checks against the published update rules)."""

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, optimizer as opt_mod
from paddle_tpu.optimizer import lr as lr_mod


def _quad_params():
    return {"w": jnp.asarray(np.array([3.0, -2.0], np.float32))}


def _quad_grads(params):
    return {"w": 2 * params["w"]}  # grad of ||w||^2


def _run(opt, steps=50):
    params = _quad_params()
    state = opt.init_state(params)
    for _ in range(steps):
        params, state = opt.update(params, _quad_grads(params), state)
    return float(jnp.sum(params["w"] ** 2))


@pytest.mark.parametrize("cls,kw", [
    (opt_mod.SGD, dict(learning_rate=0.1)),
    (opt_mod.Momentum, dict(learning_rate=0.05, momentum=0.9)),
    (opt_mod.Adam, dict(learning_rate=0.2)),
    (opt_mod.AdamW, dict(learning_rate=0.2, weight_decay=0.01)),
    (opt_mod.Adamax, dict(learning_rate=0.2)),
    (opt_mod.Adagrad, dict(learning_rate=0.5)),
    (opt_mod.Adadelta, dict(learning_rate=5.0)),
    (opt_mod.RMSProp, dict(learning_rate=0.05)),
    (opt_mod.Lamb, dict(learning_rate=0.05)),
    (opt_mod.NAdam, dict(learning_rate=0.2)),
    (opt_mod.RAdam, dict(learning_rate=0.2)),
    (opt_mod.Rprop, dict(learning_rate=0.1)),
])
def test_optimizers_minimize_quadratic(cls, kw):
    final = _run(cls(**kw), steps=300)
    assert final < 0.5, f"{cls.__name__} failed to minimize: {final}"


def test_adam_matches_reference_formula():
    lr, b1, b2, eps = 0.1, 0.9, 0.999, 1e-8
    opt = opt_mod.Adam(learning_rate=lr, beta1=b1, beta2=b2, epsilon=eps)
    params = {"w": jnp.asarray([1.0])}
    g = {"w": jnp.asarray([0.5])}
    state = opt.init_state(params)
    p2, state = opt.update(params, g, state)
    m = (1 - b1) * 0.5
    v = (1 - b2) * 0.25
    mhat = m / (1 - b1)
    vhat = v / (1 - b2)
    want = 1.0 - lr * mhat / (np.sqrt(vhat) + eps)
    np.testing.assert_allclose(float(p2["w"][0]), want, rtol=1e-6)


def test_adamw_decoupled_decay():
    opt = opt_mod.AdamW(learning_rate=0.1, weight_decay=0.5)
    params = {"w": jnp.asarray([1.0])}
    g = {"w": jnp.asarray([0.0])}
    state = opt.init_state(params)
    p2, _ = opt.update(params, g, state)
    np.testing.assert_allclose(float(p2["w"][0]), 1.0 * (1 - 0.1 * 0.5), rtol=1e-6)


def test_multi_precision_master_weights():
    opt = opt_mod.SGD(learning_rate=0.1, multi_precision=True)
    params = {"w": jnp.asarray([1.0], jnp.bfloat16)}
    state = opt.init_state(params)
    assert state["master"]["w"].dtype == jnp.float32
    small = {"w": jnp.asarray([1e-3], jnp.float32)}
    for _ in range(10):
        params, state = opt.update(params, small, state)
    # master accumulated 10 * 1e-4 updates even though each is below bf16 ulp
    np.testing.assert_allclose(float(state["master"]["w"][0]), 1.0 - 1e-3, rtol=1e-4)


def test_grad_clip_in_optimizer():
    opt = opt_mod.SGD(learning_rate=1.0, grad_clip=nn.ClipGradByGlobalNorm(0.1))
    params = {"w": jnp.asarray([0.0])}
    state = opt.init_state(params)
    p2, _ = opt.update(params, {"w": jnp.asarray([100.0])}, state)
    np.testing.assert_allclose(float(p2["w"][0]), -0.1, rtol=1e-4)


def test_lr_schedulers():
    s = lr_mod.StepDecay(0.1, step_size=10, gamma=0.5)
    assert np.isclose(float(s.lr_at(0)), 0.1)
    assert np.isclose(float(s.lr_at(10)), 0.05)
    assert np.isclose(float(s.lr_at(25)), 0.025)
    c = lr_mod.CosineAnnealingDecay(1.0, T_max=100)
    assert np.isclose(float(c.lr_at(0)), 1.0)
    assert np.isclose(float(c.lr_at(100)), 0.0, atol=1e-6)
    w = lr_mod.LinearWarmup(0.1, warmup_steps=10, start_lr=0.0, end_lr=0.1)
    assert np.isclose(float(w.lr_at(5)), 0.05)
    assert np.isclose(float(w.lr_at(50)), 0.1)
    n = lr_mod.NoamDecay(d_model=512, warmup_steps=100)
    assert float(n.lr_at(50)) < float(n.lr_at(100))
    p = lr_mod.PiecewiseDecay([10, 20], [1.0, 0.5, 0.1])
    assert np.isclose(float(p.lr_at(5)), 1.0) and np.isclose(
        float(p.lr_at(15)), 0.5) and np.isclose(float(p.lr_at(25)), 0.1)
    # paddle-style stateful stepping
    s2 = lr_mod.ExponentialDecay(0.1, gamma=0.9)
    s2.step()
    assert np.isclose(s2.get_lr(), 0.09)


def test_reduce_on_plateau():
    r = lr_mod.ReduceOnPlateau(0.1, patience=1, factor=0.5)
    r.step(1.0)
    r.step(1.0)  # bad 1
    r.step(1.0)  # bad 2 -> reduce
    assert np.isclose(r.last_lr, 0.05)


def test_optimizer_state_dict_roundtrip():
    m = nn.Linear(3, 3)
    opt = opt_mod.Adam(learning_rate=0.1, parameters=m)
    grads = {k: jnp.ones_like(v) for k, v in m.param_dict().items()}
    opt.step(grads)
    sd = opt.state_dict()
    opt2 = opt_mod.Adam(learning_rate=0.1, parameters=m)
    opt2.set_state_dict(sd)
    assert int(opt2._eager_state["step"]) == 1
