"""nn functional/layer tail (parity: nn/functional/{vision,extension,
distance,loss,pooling}.py + nn/layer equivalents)."""

import numpy as np
import pytest
import scipy.spatial.distance

import paddle_tpu as pt
import paddle_tpu.nn.functional as F
from paddle_tpu import nn

RNG = np.random.default_rng(21)


def test_affine_grid_identity_and_grid_sample_roundtrip():
    import jax.numpy as jnp
    x = RNG.standard_normal((2, 3, 5, 7)).astype(np.float32)
    theta = np.tile(np.array([[[1.0, 0, 0], [0, 1.0, 0]]], np.float32),
                    (2, 1, 1))
    grid = F.affine_grid(theta, (2, 3, 5, 7), align_corners=True)
    out = F.grid_sample(x, grid, align_corners=True)
    np.testing.assert_allclose(np.asarray(out), x, atol=1e-5)
    # translation by one output pixel in x
    theta_t = theta.copy()
    theta_t[:, 0, 2] = 2.0 / (7 - 1)
    out_t = np.asarray(F.grid_sample(x, F.affine_grid(
        theta_t, (2, 3, 5, 7)), padding_mode="zeros"))
    np.testing.assert_allclose(out_t[..., :-1], x[..., 1:], atol=1e-4)


def test_grid_sample_is_differentiable():
    import jax
    import jax.numpy as jnp
    x = jnp.asarray(RNG.standard_normal((1, 2, 4, 4)), jnp.float32)
    grid = jnp.asarray(RNG.uniform(-1, 1, (1, 3, 3, 2)), jnp.float32)
    g = jax.grad(lambda x_: F.grid_sample(x_, grid).sum())(x)
    assert np.isfinite(np.asarray(g)).all()


def test_sequence_mask_and_temporal_shift():
    m = F.sequence_mask(np.array([1, 3, 2]), maxlen=4)
    np.testing.assert_array_equal(np.asarray(m),
                                  [[1, 0, 0, 0], [1, 1, 1, 0],
                                   [1, 1, 0, 0]])
    x = np.arange(2 * 2 * 4 * 1 * 1, dtype=np.float32) \
        .reshape(4, 4, 1, 1)  # N*T=4 (N=2, T=2), C=4
    out = np.asarray(F.temporal_shift(x, seg_num=2, shift_ratio=0.25))
    assert out.shape == x.shape
    # channel 0 shifts backward: position t gets t+1's value; last t -> 0
    assert out[0, 0, 0, 0] == x[1, 0, 0, 0]
    assert out[1, 0, 0, 0] == 0.0
    # channel 1 shifts forward: first t -> 0
    assert out[0, 1, 0, 0] == 0.0
    assert out[1, 1, 0, 0] == x[0, 1, 0, 0]
    # remaining channels stay
    np.testing.assert_array_equal(out[:, 2:], x[:, 2:])


def test_gather_tree_backtrace():
    # [T=3, batch=1, beam=2]
    ids = np.array([[[1, 2]], [[3, 4]], [[5, 6]]])
    parents = np.array([[[0, 0]], [[0, 0]], [[1, 0]]])
    out = np.asarray(F.gather_tree(ids, parents))
    # beam 0 at t=2 came from parent beam 1 at t=1, which came from beam 0
    np.testing.assert_array_equal(out[:, 0, 0], [1, 4, 5])
    np.testing.assert_array_equal(out[:, 0, 1], [1, 3, 6])


def test_pairwise_distance_and_pdist_match_scipy():
    x = RNG.standard_normal((4, 6)).astype(np.float32)
    y = RNG.standard_normal((4, 6)).astype(np.float32)
    d = np.asarray(F.pairwise_distance(x, y, p=2.0, epsilon=0.0))
    np.testing.assert_allclose(d, np.linalg.norm(x - y, axis=-1), rtol=1e-5)
    pd = np.asarray(F.pdist(x))
    np.testing.assert_allclose(pd, scipy.spatial.distance.pdist(x),
                               rtol=1e-5)
    layer = nn.PairwiseDistance(p=1.0, epsilon=0.0)
    np.testing.assert_allclose(np.asarray(layer(x, y)),
                               np.abs(x - y).sum(-1), rtol=1e-5)


def test_hsigmoid_loss_default_tree_decreases():
    import jax
    import jax.numpy as jnp
    pt.seed(0)
    n_cls, dim = 6, 8
    layer = nn.HSigmoidLoss(dim, n_cls)
    x = jnp.asarray(RNG.standard_normal((16, dim)), jnp.float32)
    y = np.array([i % n_cls for i in range(16)])[:, None]
    loss0 = float(np.asarray(layer(x, y)).mean())
    assert np.isfinite(loss0) and loss0 > 0

    w = layer.weight
    def loss_fn(w_):
        return F.hsigmoid_loss(x, y, n_cls, w_, layer.bias).mean()
    g = jax.grad(loss_fn)(w)
    w2 = w - 0.5 * g
    assert float(loss_fn(w2)) < loss0


def test_hsigmoid_custom_path():
    x = RNG.standard_normal((2, 4)).astype(np.float32)
    w = RNG.standard_normal((3, 4)).astype(np.float32)
    table = np.array([[0, 1, -1], [0, 2, -1]])  # padded with -1
    code = np.array([[1, 0, 0], [0, 1, 0]])
    out = np.asarray(F.hsigmoid_loss(x, np.array([[0], [1]]), 3, w,
                                     path_table=table, path_code=code))
    assert out.shape == (2, 1) and np.isfinite(out).all()
    # manual: sum over valid nodes of softplus(pre) - bit*pre
    pre = x @ w.T
    want0 = (np.logaddexp(0, pre[0, 0]) - pre[0, 0]
             + np.logaddexp(0, pre[0, 1]))
    np.testing.assert_allclose(out[0, 0], want0, rtol=1e-5)


def test_margin_cross_entropy_reduces_to_ce_at_zero_margin():
    import jax
    logits = RNG.uniform(-1, 1, (4, 5)).astype(np.float32)
    labels = np.array([0, 2, 4, 1])
    plain = F.margin_cross_entropy(logits, labels, margin1=1.0, margin2=0.0,
                                   margin3=0.0, scale=1.0)
    ref = -np.log(np.exp(logits)[np.arange(4), labels]
                  / np.exp(logits).sum(-1)).mean()
    np.testing.assert_allclose(float(plain), ref, rtol=1e-4)
    # a positive margin raises the loss (harder positives)
    hard = F.margin_cross_entropy(logits, labels, margin2=0.5, scale=1.0)
    assert float(hard) > float(plain)
    loss, sm = F.margin_cross_entropy(logits, labels, return_softmax=True)
    np.testing.assert_allclose(np.asarray(sm).sum(-1), 1.0, rtol=1e-5)


def test_edit_distance():
    a = np.array([[1, 2, 3, 0], [1, 2, 3, 4]])
    b = np.array([[1, 3, 3, 0], [1, 2, 3, 4]])
    d, n = F.edit_distance(a, b, normalized=False,
                           input_length=[3, 4], label_length=[3, 4])
    np.testing.assert_allclose(d[:, 0], [1.0, 0.0])
    assert n[0] == 2
    dn, _ = F.edit_distance(a, b, normalized=True,
                            input_length=[3, 4], label_length=[3, 4])
    np.testing.assert_allclose(dn[:, 0], [1 / 3, 0.0])
    # ignored tokens removed before comparison
    d2, _ = F.edit_distance(a, b, normalized=False, ignored_tokens=[3],
                            input_length=[3, 4], label_length=[3, 4])
    np.testing.assert_allclose(d2[:, 0], [1.0, 0.0])


def test_fractional_max_pool_shapes_and_determinism():
    x = RNG.standard_normal((2, 3, 9, 9)).astype(np.float32)
    o1 = np.asarray(F.fractional_max_pool2d(x, 4, random_u=0.3))
    o2 = np.asarray(F.fractional_max_pool2d(x, 4, random_u=0.3))
    assert o1.shape == (2, 3, 4, 4)
    np.testing.assert_array_equal(o1, o2)  # deterministic with fixed u
    # every output is a max of some input window => subset of input values
    assert np.isin(o1, x).all()
    x3 = RNG.standard_normal((1, 2, 6, 6, 6)).astype(np.float32)
    o3 = np.asarray(F.fractional_max_pool3d(x3, (2, 3, 2), random_u=0.7))
    assert o3.shape == (1, 2, 2, 3, 2)
    layer = nn.FractionalMaxPool2D(4, random_u=0.5)
    assert np.asarray(layer(x)).shape == (2, 3, 4, 4)
    with pytest.raises(ValueError):
        F.fractional_max_pool2d(x, 4, random_u=1.5)
    # return_mask raises loudly (no index materialization on XLA) instead
    # of returning (out, None) that fails later inside max_unpool*
    with pytest.raises(NotImplementedError):
        F.fractional_max_pool2d(x, 4, random_u=0.3, return_mask=True)
    with pytest.raises(NotImplementedError):
        F.fractional_max_pool3d(x3, (2, 3, 2), random_u=0.7,
                                return_mask=True)


def test_max_unpool_1d_3d_roundtrip():
    import jax.numpy as jnp
    x1 = jnp.asarray(RNG.standard_normal((2, 3, 8)), jnp.float32)
    pooled, idx = F.max_pool1d(x1, 2, stride=2, return_mask=True)
    restored = np.asarray(F.max_unpool1d(pooled, idx, 2, stride=2))
    assert restored.shape == x1.shape
    # every pooled max lands back at its TRUE argmax position
    assert np.count_nonzero(restored) == pooled.size
    nz = restored != 0
    np.testing.assert_allclose(restored[nz], np.asarray(x1)[nz])
    x2 = jnp.asarray(RNG.standard_normal((1, 2, 6, 6)), jnp.float32)
    p2, i2 = F.max_pool2d(x2, 2, stride=2, return_mask=True)
    r2 = np.asarray(F.max_unpool2d(p2, i2, 2, stride=2))
    nz2 = r2 != 0
    np.testing.assert_allclose(r2[nz2], np.asarray(x2)[nz2])
    x3 = jnp.asarray(RNG.standard_normal((1, 2, 4, 4, 4)), jnp.float32)
    p3, i3 = F.max_pool3d(x3, 2, stride=2, return_mask=True)
    r3 = np.asarray(F.max_unpool3d(p3, i3, 2, stride=2))
    assert r3.shape == x3.shape
    nz3 = r3 != 0
    np.testing.assert_allclose(r3[nz3], np.asarray(x3)[nz3])


def test_softmax2d_and_unflatten_layers():
    x = RNG.standard_normal((2, 3, 4, 5)).astype(np.float32)
    out = np.asarray(nn.Softmax2D()(x))
    np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-5)
    u = nn.Unflatten(1, (2, 3))
    y = RNG.standard_normal((4, 6, 5)).astype(np.float32)
    assert np.asarray(u(y)).shape == (4, 2, 3, 5)


def test_sparse_attention_matches_masked_dense():
    b, h, sq, d = 1, 2, 4, 8
    q = RNG.standard_normal((b, h, sq, d)).astype(np.float32)
    k = RNG.standard_normal((b, h, sq, d)).astype(np.float32)
    v = RNG.standard_normal((b, h, sq, d)).astype(np.float32)
    # CSR: each row attends to itself and column 0
    offs = np.tile(np.array([0, 2, 4, 6, 8]), (b, h, 1))
    cols = np.tile(np.array([0, 0, 0, 1, 0, 2, 0, 3]), (b, h, 1))
    out = np.asarray(F.sparse_attention(q, k, v, offs, cols))
    assert out.shape == (b, h, sq, d)
    # dense reference with the same mask
    mask = np.full((sq, sq), -np.inf)
    for r in range(sq):
        mask[r, [0, r]] = 0
    import jax
    s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d) + mask
    ref = np.einsum("bhqk,bhkd->bhqd",
                    np.asarray(jax.nn.softmax(s, axis=-1)), v)
    np.testing.assert_allclose(out, ref, atol=2e-3, rtol=2e-3)


def test_flash_attention_with_sparse_mask_expands_rows():
    b, s, h, d = 1, 6, 2, 8
    q = RNG.standard_normal((b, s, h, d)).astype(np.float32)
    k = RNG.standard_normal((b, s, h, d)).astype(np.float32)
    v = RNG.standard_normal((b, s, h, d)).astype(np.float32)
    # column j masked for rows >= start[j]; start=s means never masked
    start = np.full((b, h, s), s, np.int32)
    out_plain = np.asarray(F.flash_attention_with_sparse_mask(
        q, k, v, start))
    ref = np.asarray(F.scaled_dot_product_attention(q, k, v,
                                                    is_causal=True))
    np.testing.assert_allclose(out_plain, ref, atol=2e-3, rtol=2e-3)
    # masking col 0 from row 2 on changes rows >= 2 only
    start2 = start.copy()
    start2[..., 0] = 2
    out_m = np.asarray(F.flash_attention_with_sparse_mask(q, k, v, start2))
    np.testing.assert_allclose(out_m[:, :2], ref[:, :2], atol=2e-3)
    assert np.abs(out_m[:, 2:] - ref[:, 2:]).max() > 1e-4


def test_return_mask_ceil_mode_and_channel_last():
    import jax.numpy as jnp
    x = jnp.asarray(RNG.standard_normal((1, 1, 5, 5)), jnp.float32)
    out, mask = F.max_pool2d(x, 2, stride=2, ceil_mode=True,
                             return_mask=True)
    assert out.shape == mask.shape == (1, 1, 3, 3)
    r = np.asarray(F.max_unpool2d(out, mask, 2, stride=2,
                                  output_size=(5, 5)))
    nz = r != 0
    np.testing.assert_allclose(r[nz], np.asarray(x)[nz])
    # channel-last layout
    xl = jnp.moveaxis(x, 1, -1)
    out_l, mask_l = F.max_pool2d(xl, 2, stride=2, data_format="NHWC",
                                 return_mask=True)
    np.testing.assert_array_equal(
        np.asarray(mask_l)[..., 0],
        np.asarray(F.max_pool2d(x, 2, stride=2, return_mask=True)[1])[:, 0])


def test_fractional_pool_follows_framework_seed():
    x = RNG.standard_normal((1, 2, 9, 9)).astype(np.float32)
    pt.seed(123)
    a = np.asarray(F.fractional_max_pool2d(x, 4))
    pt.seed(123)
    b = np.asarray(F.fractional_max_pool2d(x, 4))
    np.testing.assert_array_equal(a, b)


def test_sparse_even_kernel_and_ceil_pool():
    import paddle_tpu.sparse as S
    dense = np.zeros((1, 4, 4, 4, 2), np.float32)
    dense[0, 1, 2, 3] = [1.0, -1.0]
    x = S.to_sparse_coo(dense)
    out = S.nn.SubmConv3D(2, 5, 2)(x)  # even kernel must work
    od = np.asarray(S.to_dense(out))
    assert od.shape == (1, 4, 4, 4, 5)
    assert (np.abs(od).sum((0, 4)) > 0).sum() == 1  # pattern preserved
    pooled = S.nn.MaxPool3D(2, ceil_mode=True)(
        S.to_sparse_coo(np.ones((1, 5, 5, 5, 1), np.float32)))
    assert pooled.shape == (1, 3, 3, 3, 1)
    with pytest.raises(NotImplementedError):
        S.nn.MaxPool3D(2, return_mask=True)
