"""Failure detection + elastic relaunch (parity: comm_task_manager.cc
watchdog + fleet/elastic/manager.py gang restart; verdict done-bar: kill a
worker and observe relaunch)."""

import os
import subprocess
import sys
import textwrap
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_watchdog_flags_hung_task():
    from paddle_tpu.distributed.watchdog import CommWatchdog
    wd = CommWatchdog(timeout=0.2, action="log")
    with wd.task("fast_op"):
        pass
    assert not wd.timed_out_tasks()
    with wd.task("slow_allreduce", shape=(1024,)):
        time.sleep(0.5)
    bad = wd.timed_out_tasks()
    assert len(bad) == 1 and bad[0].name == "slow_allreduce"
    assert bad[0].meta["shape"] == (1024,)


def test_watchdog_raise_mode():
    from paddle_tpu.distributed.watchdog import CommWatchdog
    wd = CommWatchdog(timeout=0.1, action="raise")
    with pytest.raises(TimeoutError):
        with wd.task("hung"):
            time.sleep(0.3)


def test_elastic_gang_restart(tmp_path):
    """Worker 1 dies on the first run; the launcher must gang-restart and
    the job succeeds on the retry (PADDLE_RESTART_EPOCH visible)."""
    script = tmp_path / "worker.py"
    marker = tmp_path / "attempted"
    script.write_text(textwrap.dedent(f"""
        import os, sys
        rank = int(os.environ["PADDLE_TRAINER_ID"])
        epoch = int(os.environ["PADDLE_RESTART_EPOCH"])
        # first attempt: rank 1 crashes
        if epoch == 0 and rank == 1:
            sys.exit(3)
        open({str(marker)!r} + f".r{{epoch}}.{{rank}}", "w").write("ok")
    """))
    code = textwrap.dedent(f"""
        import sys; sys.path.insert(0, {REPO!r})
        from paddle_tpu.distributed.launch.main import launch
        sys.exit(launch(["--nproc_per_node", "2", "--max_restarts", "2",
                         {str(script)!r}]))
    """)
    proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, timeout=120)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "gang restart 1/2" in proc.stderr
    # retry ran both ranks with the bumped restart epoch
    assert (tmp_path / "attempted.r1.0").exists()
    assert (tmp_path / "attempted.r1.1").exists()


def test_elastic_exhausted_restarts_fails(tmp_path):
    script = tmp_path / "always_fail.py"
    script.write_text("import sys; sys.exit(5)\n")
    code = textwrap.dedent(f"""
        import sys; sys.path.insert(0, {REPO!r})
        from paddle_tpu.distributed.launch.main import launch
        sys.exit(launch(["--nproc_per_node", "2", "--max_restarts", "1",
                         {str(script)!r}]))
    """)
    proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, timeout=120)
    assert proc.returncode == 5


def test_elastic_manager_checkpoint_discovery(tmp_path):
    from paddle_tpu.distributed.fleet.elastic import ElasticManager
    for name in ("step_10", "step_200", "step_30"):
        (tmp_path / name).mkdir()
        # discovery only returns COMMITTED checkpoints
        (tmp_path / name / "COMMIT").write_text("")
    em = ElasticManager(checkpoint_dir=str(tmp_path))
    assert em.latest_checkpoint().endswith("step_200")
    assert not em.is_restart


def test_ps_deprioritization_note():
    from paddle_tpu.distributed import ps
    assert "deliberately" in ps.__doc__ or "NOT rebuilt" in ps.__doc__
    with pytest.raises(NotImplementedError):
        ps.DistributedTranspiler()
