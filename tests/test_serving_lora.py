"""paddle_tpu.serving.lora — multi-tenant LoRA serving over one base
model.

The contracts (SERVING.md "Multi-tenant LoRA serving"):

1. TWO PROGRAMS, EVER — the adapter table is an array VALUE like a
   block table; arbitrary adapter churn (loads, evictions, slot reuse)
   keeps ``step_program_counts() == {"decode": 1, "mixed": 1}``.
2. MERGED-WEIGHT PARITY — a stream served through the paged pool is
   bitwise identical to ``model.generate()`` with that adapter folded
   into the base weights; a base request through a LoRA engine is
   bitwise identical to the plain base model (slot 0 = exact zeros).
3. NAMESPACED PREFIXES — prefix-cache identity includes the adapter
   digest: the same prompt under two adapters NEVER cross-hits, and
   adapter A's second request still hits its own entries.
4. PAGED POOL — content-hash identity, refcounted slots, LRU eviction
   of refcount-0 residents, blake2b-digest-verified host spill/restore
   that round-trips bit-exact.
5. FAULTS TYPED — a corrupted adapter fetch is caught by the digest
   re-verify and fails the request with ``adapter_unavailable`` (never
   silent base-model fallback); a killed replica's failover replay is
   bitwise with the same adapter bound.

Chaos tests (deterministic FaultPlan replays) carry the ``faults``
marker, same as the serving/fleet suites.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.distributed import fault
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.observability import parse_prometheus, render_prometheus
from paddle_tpu.serving import FleetRouter, HostTier, ServingEngine
from paddle_tpu.serving.lora import (AdapterExhaustedError, AdapterPool,
                                     AdapterUnavailableError, LoRAAdapter,
                                     llama_lora_targets)

RNG = np.random.default_rng(41)


@pytest.fixture(scope="module")
def model():
    pt.seed(123)
    m = LlamaForCausalLM(llama_tiny(dtype="float32",
                                    mp_axis=None, fsdp_axis=None))
    m.eval()
    return m


@pytest.fixture
def fault_free(monkeypatch):
    """No FaultPlan leaks out of a chaos test; no rank env leaks in."""
    fault.deactivate()
    monkeypatch.delenv("PADDLE_TRAINER_ID", raising=False)
    monkeypatch.delenv("PROCESS_ID", raising=False)
    monkeypatch.delenv("PADDLE_RESTART_EPOCH", raising=False)
    yield
    fault.deactivate()


def _adapter(model, seed, rank=4, scale=0.2, name=None):
    """A test adapter with deltas large enough that different adapters
    produce visibly different greedy streams on the tiny model."""
    return LoRAAdapter.random(name or f"tenant-{seed}", model.config,
                              rank=rank, seed=seed, scale=scale)


def _merged_ref(model, adapter, prompt, max_new):
    """Reference arm: fold the adapter into the base weights, generate,
    restore the base weights bit-exact."""
    state = model.state_dict()
    try:
        model.set_state_dict(adapter.merged_into(state))
        out = model.generate(jnp.asarray([prompt]), max_new_tokens=max_new)
    finally:
        model.set_state_dict(state)
    return np.asarray(out)[0, len(prompt):].tolist()


def _base_ref(model, prompt, max_new):
    out = model.generate(jnp.asarray([prompt]), max_new_tokens=max_new)
    return np.asarray(out)[0, len(prompt):].tolist()


def _mk_engine(model, lora=None, **kw):
    cfg = dict(num_pages=64, page_size=8, max_slots=4,
               lora=lora if lora is not None
               else {"max_live": 4, "max_rank": 8})
    cfg.update(kw)
    return ServingEngine(model, **cfg)


def _payloads_equal(a, b):
    assert len(a) == len(b)
    for x, y in zip(a, b):
        assert x.dtype == y.dtype and x.shape == y.shape
        assert np.array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# AdapterPool: identity, refcounts, LRU, spill/restore
# ---------------------------------------------------------------------------

class TestAdapterPool:
    def _pool(self, model, **kw):
        cfg = dict(max_live=3, max_rank=8)
        cfg.update(kw)
        return AdapterPool(model.config, **cfg)

    def test_register_resolve_content_identity(self, model):
        pool = self._pool(model)
        a = _adapter(model, 1)
        h = pool.register(a)
        assert h == a.digest.hex()
        # re-register identical content: same digest, no duplicate
        assert pool.register(a) == h and pool.stats()["registered"] == 1
        # resolve by name, hex, bytes and the adapter object itself
        for ref in (a.name, h, a.digest, a):
            assert pool.resolve(ref) == a.digest
        with pytest.raises(AdapterUnavailableError):
            pool.resolve("never-registered")

    def test_acquire_refcount_release_lru_hit(self, model):
        pool = self._pool(model)
        a = _adapter(model, 1)
        pool.register(a)
        assert pool.acquire(b"") == 0          # identity adapter
        s1 = pool.acquire(a.digest)
        assert s1 != 0 and pool.num_live == 1
        assert pool.acquire(a.digest) == s1    # second pin: same slot
        pool.release(s1)
        assert pool.num_live == 1              # still pinned once
        pool.release(s1)
        assert pool.num_live == 0 and pool.num_cached == 1
        # refcount-0 resident: the next acquire is a free LRU hit
        before = pool.counters["adapter_loads"]
        assert pool.acquire(a.digest) == s1
        assert pool.counters["adapter_loads"] == before
        assert pool.counters["adapter_hits"] >= 2

    def test_exhausted_when_all_slots_pinned(self, model):
        pool = self._pool(model, max_live=3)   # capacity 2
        ads = [_adapter(model, i) for i in range(3)]
        for a in ads:
            pool.register(a)
        pool.acquire(ads[0].digest)
        pool.acquire(ads[1].digest)
        with pytest.raises(AdapterExhaustedError):
            pool.acquire(ads[2].digest)

    def test_lru_evict_spill_restore_roundtrip(self, model):
        pool = self._pool(model, max_live=3)   # capacity 2
        ads = [_adapter(model, i) for i in range(3)]
        keys = [a.digest for a in ads]
        for a in ads:
            pool.register(a)
        s0 = pool.acquire(keys[0])
        pool.release(s0)
        s1 = pool.acquire(keys[1])
        pool.release(s1)
        # drop adapter 0's host copy so eviction MUST spill it back
        assert pool.host_tier.discard("lora", "full", keys[0])
        s2 = pool.acquire(keys[2])             # miss -> evict LRU (= 0)
        assert s2 == s0 and not pool.resident(keys[0])
        assert pool.counters["adapter_evictions"] == 1
        assert pool.counters["adapter_spills"] == 1
        assert pool.host_tier.has("lora", "full", keys[0])
        # restore: digest-verified, bit-exact vs the original payload
        pool.release(s2)
        s0b = pool.acquire(keys[0])
        _payloads_equal(pool._slot_payload(s0b, keys[0]), ads[0].payload())
        st = pool.stats()
        assert st["adapter_loads"] == 4 and st["lora_bytes_streamed"] > 0

    def test_corrupt_host_payload_detected_never_served(self, model):
        pool = self._pool(model)
        a = _adapter(model, 5)
        pool.register(a)
        pool.host_tier.corrupt("lora", "full", a.digest)
        with pytest.raises(AdapterUnavailableError):
            pool.acquire(a.digest)
        assert pool.counters["adapter_restore_corrupt"] == 1
        assert pool.counters["adapter_unavailable"] == 1

    def test_rank_above_pool_max_rejected(self, model):
        pool = self._pool(model, max_rank=4)
        a = _adapter(model, 7, rank=8)
        pool.register(a)
        with pytest.raises(AdapterUnavailableError):
            pool.acquire(a.digest)

    def test_stats_schema_matches_zero_stats(self, model):
        pool = self._pool(model)
        assert set(pool.stats()) == set(AdapterPool.zero_stats())


# ---------------------------------------------------------------------------
# engine: merged-weight parity + the two-program contract
# ---------------------------------------------------------------------------

class TestEngineParity:
    def test_streams_match_merged_generate(self, model, fault_free):
        """Base + two adapters interleaved in one batch: every stream
        equals generate() with that adapter folded into the weights,
        and the engine still owns exactly two compiled programs."""
        a1, a2 = _adapter(model, 1), _adapter(model, 2)
        prompts = [RNG.integers(1, 500, size=int(n)).tolist()
                   for n in (6, 9, 7)]
        refs = [_merged_ref(model, a1, prompts[0], 8),
                _merged_ref(model, a2, prompts[1], 8),
                _base_ref(model, prompts[2], 8)]
        assert refs[0] != refs[1] != refs[2]   # adapters actually differ
        eng = _mk_engine(model)
        h1, h2 = eng.register_adapter(a1), eng.register_adapter(a2)
        rids = [eng.add_request(prompts[0], 8, adapter=h1),
                eng.add_request(prompts[1], 8, adapter=a2.name),
                eng.add_request(prompts[2], 8)]
        out = eng.run_to_completion(max_steps=100)
        for rid, ref in zip(rids, refs):
            assert out[rid] == ref
        assert eng.step_program_counts() == {"decode": 1, "mixed": 1}
        st = eng.stats()["lora"]
        assert st["adapter_loads"] == 2 and st["pinned"] == 0

    def test_base_engine_programs_unchanged(self, model, fault_free):
        """An engine built WITHOUT lora= never threads the extra step
        arguments: same two programs, and adapter= submissions are
        refused typed at add time."""
        eng = _mk_engine(model, lora=False)
        assert eng.adapters is None
        rid = eng.add_request([5, 6, 7], 4)
        out = eng.run_to_completion(max_steps=50)
        assert out[rid] == _base_ref(model, [5, 6, 7], 4)
        assert eng.step_program_counts() == {"decode": 1, "mixed": 1}
        with pytest.raises(AdapterUnavailableError):
            eng.add_request([1, 2], 4, adapter="deadbeef")

    def test_churn_three_epochs_programs_pinned(self, model, fault_free):
        """More adapters than slots, three epochs of rotation: loads,
        LRU evictions and slot reuse are all array-value churn — the
        program counts never move and parity holds every epoch."""
        n_adapters, max_new = 5, 6
        ads = [_adapter(model, i) for i in range(n_adapters)]
        prompts = [RNG.integers(1, 500, size=int(RNG.integers(5, 10)))
                   .tolist() for _ in range(n_adapters)]
        refs = [_merged_ref(model, a, p, max_new)
                for a, p in zip(ads, prompts)]
        eng = _mk_engine(model, lora={"max_live": 3, "max_rank": 8},
                         max_slots=2)
        hexes = [eng.register_adapter(a) for a in ads]
        for epoch in range(3):
            rids = [eng.add_request(prompts[i], max_new, adapter=hexes[i])
                    for i in range(n_adapters)]
            out = eng.run_to_completion(max_steps=400)
            for i, rid in enumerate(rids):
                assert out[rid] == refs[i], f"epoch {epoch} adapter {i}"
            assert eng.step_program_counts() == {"decode": 1, "mixed": 1}
        st = eng.stats()["lora"]
        # 5 adapters through 2 cache-able slots: evictions + reloads
        assert st["adapter_evictions"] > 0
        assert st["adapter_loads"] > n_adapters
        assert st["registered"] == n_adapters and st["pinned"] == 0

    def test_snapshot_restore_rebinds_adapter(self, model, tmp_path,
                                              fault_free):
        """A drained engine's snapshot carries the adapter digest; the
        warm engine re-resolves it and the continuation is bitwise one
        life. A warm engine WITHOUT the lora pool refuses typed."""
        a = _adapter(model, 3)
        prompt = RNG.integers(1, 500, size=7).tolist()
        ref = _merged_ref(model, a, prompt, 10)
        eng = _mk_engine(model)
        h = eng.register_adapter(a)
        rid = eng.add_request(prompt, 10, adapter=h)
        for _ in range(3):
            eng.step()
        partial = list(eng.request(rid).tokens)
        assert 0 < len(partial) < 10
        path = str(tmp_path / "lora_snap")
        eng.drain(snapshot_path=path)
        warm = _mk_engine(model)
        warm.register_adapter(a)
        assert warm.restore(path) == [rid]
        out = warm.run_to_completion(max_steps=100)
        assert out[rid] == ref and out[rid][:len(partial)] == partial
        # the warm life admits via plain prefill (no chunk ran): mixed
        # may legitimately still be uncompiled — but never >1 of either
        counts = warm.step_program_counts()
        assert counts["decode"] == 1 and counts["mixed"] <= 1
        # an engine with no adapter pool cannot silently resume as base
        bare = _mk_engine(model, lora=False)
        with pytest.raises(AdapterUnavailableError):
            bare.restore(path)


# ---------------------------------------------------------------------------
# prefix-cache namespacing
# ---------------------------------------------------------------------------

class TestPrefixNamespacing:
    def test_same_prompt_two_adapters_never_cross_hit(self, model,
                                                      fault_free):
        """The planted collision: an identical prompt under adapter A,
        then adapter B — B must MISS A's cached pages (its KV is
        different math) and still decode its own bitwise stream; A's
        second run hits its own namespace."""
        a, b = _adapter(model, 11), _adapter(model, 12)
        prompt = RNG.integers(1, 500, size=16).tolist()  # 2 full pages
        ref_a = _merged_ref(model, a, prompt, 6)
        ref_b = _merged_ref(model, b, prompt, 6)
        assert ref_a != ref_b
        eng = _mk_engine(model)
        ha, hb = eng.register_adapter(a), eng.register_adapter(b)
        r1 = eng.add_request(prompt, 6, adapter=ha)
        out = eng.run_to_completion(max_steps=60)
        assert out[r1] == ref_a
        hits0 = eng.pool.counters["prefix_hits"]
        r2 = eng.add_request(prompt, 6, adapter=hb)
        out = eng.run_to_completion(max_steps=60)
        assert out[r2] == ref_b                       # not A's KV
        assert eng.pool.counters["prefix_hits"] == hits0   # planted miss
        r3 = eng.add_request(prompt, 6, adapter=ha)
        out = eng.run_to_completion(max_steps=60)
        assert out[r3] == ref_a
        assert eng.pool.counters["prefix_hits"] == hits0 + 1  # own hit

    def test_base_namespace_distinct_from_adapters(self, model,
                                                   fault_free):
        """The empty namespace (base model) is itself isolated from
        every adapter namespace."""
        a = _adapter(model, 13)
        prompt = RNG.integers(1, 500, size=16).tolist()
        eng = _mk_engine(model)
        ha = eng.register_adapter(a)
        r1 = eng.add_request(prompt, 4)
        eng.run_to_completion(max_steps=40)
        hits0 = eng.pool.counters["prefix_hits"]
        r2 = eng.add_request(prompt, 4, adapter=ha)
        out = eng.run_to_completion(max_steps=40)
        assert eng.pool.counters["prefix_hits"] == hits0
        assert out[r2] == _merged_ref(model, a, prompt, 4)


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------

class TestLoraObservability:
    def test_metrics_summary_and_prometheus_family(self, model,
                                                   fault_free):
        eng = _mk_engine(model)
        a = _adapter(model, 21)
        rid = eng.add_request([3, 4, 5], 4,
                              adapter=eng.register_adapter(a))
        eng.run_to_completion(max_steps=40)
        s = eng.metrics.summary()
        assert s["lora_enabled"] == 1
        assert s["lora_adapter_loads"] == 1
        assert s["lora_registered"] == 1
        assert s["lora_bytes_streamed"] > 0   # not double-prefixed
        page = render_prometheus(s)
        series = parse_prometheus(page)
        assert series["paddle_serving_lora_enabled"] == 1.0
        assert series["paddle_serving_lora_adapter_loads"] == 1.0
        # a base engine still exports the schema-stable zero family
        s0 = _mk_engine(model, lora=False).metrics.summary()
        assert s0["lora_enabled"] == 0 and s0["lora_adapter_loads"] == 0


# ---------------------------------------------------------------------------
# chaos: corrupted fetch + failover replay (deterministic FaultPlans)
# ---------------------------------------------------------------------------

@pytest.mark.faults
class TestLoraChaos:
    def test_corrupt_fetch_fails_typed_never_base(self, model,
                                                  fault_free):
        """serving.lora_fetch poison corrupts the host payload; the
        digest re-verify catches it and the request finishes
        ``adapter_unavailable`` — co-scheduled base and healthy-adapter
        streams are untouched."""
        bad, good = _adapter(model, 31), _adapter(model, 32)
        eng = _mk_engine(model)
        hb, hg = eng.register_adapter(bad), eng.register_adapter(good)
        fault.activate(fault.FaultPlan([
            fault.FaultSpec(site="serving.lora_fetch", action="poison",
                            match=rf"^{hb}$"),
        ]))
        p_bad = RNG.integers(1, 500, size=6).tolist()
        p_good = RNG.integers(1, 500, size=7).tolist()
        p_base = RNG.integers(1, 500, size=5).tolist()
        r_bad = eng.add_request(p_bad, 6, adapter=hb)
        r_good = eng.add_request(p_good, 6, adapter=hg)
        r_base = eng.add_request(p_base, 6)
        events = []
        while eng.scheduler.has_work():
            events.extend(eng.step())
        assert eng.request(r_bad).finish_reason == "adapter_unavailable"
        assert eng.request(r_bad).tokens == []     # never base tokens
        term = [e for e in events if e["rid"] == r_bad and e["finished"]]
        assert term == [{"rid": r_bad, "token": None, "finished": True,
                         "finish_reason": "adapter_unavailable"}]
        st = eng.stats()["lora"]
        assert st["adapter_restore_corrupt"] == 1
        assert st["adapter_unavailable"] == 1
        fault.deactivate()
        assert eng.request(r_good).tokens == \
            _merged_ref(model, good, p_good, 6)
        assert eng.request(r_base).tokens == _base_ref(model, p_base, 6)
        assert eng.step_program_counts() == {"decode": 1, "mixed": 1}

    def test_fleet_kill_replays_bitwise_with_same_adapter(self, model,
                                                          fault_free):
        """Kill the replica serving an adapter-bound stream mid-decode:
        the failover replay re-resolves the SAME adapter on the
        survivor and the client stream is bitwise the merged-weight
        reference — exactly-once, never base-model tokens."""
        a = _adapter(model, 33)
        prompt = RNG.integers(1, 500, size=8).tolist()
        max_new = 8
        ref = _merged_ref(model, a, prompt, max_new)
        engines = [_mk_engine(model) for _ in range(2)]
        for e in engines:
            h = e.register_adapter(a)
        router = FleetRouter(engines)
        rid = router.submit(prompt, max_new, adapter=h)
        guard = 0
        while router.request(rid).emitted < 2:
            router.step()
            guard += 1
            assert guard < 50
        victim = router.request(rid).replica
        router.kill_replica(0 if victim is None else victim)
        out = router.run_to_completion(max_steps=200)
        assert out[rid] == ref
        assert router.request(rid).finish_reason == "length"
        for e in engines:
            if not e._draining:
                assert e.step_program_counts() == \
                    {"decode": 1, "mixed": 1}

    def test_adapter_affinity_prefers_resident_replica(self, model,
                                                       fault_free):
        """Placement: with no prefix cached anywhere, the replica whose
        pool already holds the adapter wins the affinity query."""
        a = _adapter(model, 34)
        engines = [_mk_engine(model) for _ in range(2)]
        hexes = [e.register_adapter(a) for e in engines]
        # preload the adapter on replica 1 only
        engines[1].adapters.release(
            engines[1].adapters.acquire(a.digest))
        router = FleetRouter(engines)
        rid = router.submit(RNG.integers(1, 500, size=6).tolist(), 4,
                            adapter=hexes[0])
        router.step()
        assert router.request(rid).replica == 1
        router.run_to_completion(max_steps=50)
        assert router.request(rid).finish_reason == "length"
