"""REAL multi-process distributed bootstrap (VERDICT r3 missing #4).

Parity target: the reference's multi-process distributed tests spawn real
trainer subprocesses and compare loss sequences
(test/legacy_test/test_dist_base.py:952, spawns at :1271/:1351). Here the
gang goes through the actual production path: paddle_tpu.distributed.launch
spawns 2 workers -> each calls init_parallel_env() ->
jax.distributed.initialize (distributed/parallel.py:46, CPU backend, 2
local devices per process) -> a DP train step over a 4-way global mesh
whose mean-loss gradient is a cross-process psum -> distributed checkpoint
save/load on the real jax.process_count()>1 branch -> loss parity with a
single-process run of the same model/data.
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent("""
    import json, os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")  # axon pin -> cpu
    out_dir = sys.argv[1]

    import numpy as np
    import paddle_tpu as pt
    import paddle_tpu.distributed as dist

    # the production bootstrap: env (set by launch) -> jax.distributed.initialize
    dist.init_parallel_env()
    assert jax.process_count() == 2, jax.process_count()
    rank = jax.process_index()
    assert dist.get_world_size() == 2 and dist.get_rank() == rank
    assert len(jax.devices()) == 4, jax.devices()          # 2 procs x 2 local
    assert len(jax.local_devices()) == 2

    import jax.numpy as jnp
    from functools import partial
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu import nn
    from paddle_tpu.core import mesh as mesh_lib
    from paddle_tpu.nn.module import functional_call
    import paddle_tpu.nn.functional as F

    mesh = mesh_lib.make_mesh({"dp": 4})
    pt.seed(0)
    model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    rep = NamedSharding(mesh, P())
    params = {k: jax.device_put(v, rep) for k, v in model.param_dict().items()}

    r = np.random.default_rng(0)
    X = r.standard_normal((32, 16)).astype("float32")
    Y = r.integers(0, 4, (32,)).astype("int32")
    dsh = NamedSharding(mesh, P("dp"))
    # each process contributes its local rows of the GLOBAL dp-sharded batch
    Xg = jax.make_array_from_process_local_data(dsh, X[rank * 16:(rank + 1) * 16])
    Yg = jax.make_array_from_process_local_data(dsh, Y[rank * 16:(rank + 1) * 16])

    def loss_fn(p, x, y):
        out, _ = functional_call(model, p, x, training=True)
        return F.cross_entropy(out, y)   # mean over the GLOBAL batch -> psum

    @partial(jax.jit, donate_argnums=0)
    def step(p, x, y):
        l, g = jax.value_and_grad(loss_fn)(p, x, y)
        return jax.tree.map(lambda a, b: a - 0.1 * b, p, g), l

    losses = []
    for _ in range(5):
        params, l = step(params, Xg, Yg)
        losses.append(float(l))

    # distributed checkpoint on the REAL multi-process branch
    from paddle_tpu.distributed.checkpoint import load_state_dict, save_state_dict
    ck = os.path.join(out_dir, "ckpt")
    save_state_dict(params, ck)
    template = {k: jax.device_put(jnp.zeros(v.shape, jnp.float32), rep)
                for k, v in params.items()}
    template = load_state_dict(template, ck)
    for k in params:
        a = np.asarray(jax.device_get(params[k].addressable_shards[0].data))
        b = np.asarray(jax.device_get(template[k].addressable_shards[0].data))
        np.testing.assert_allclose(a, b, rtol=0, atol=0, err_msg=k)

    with open(os.path.join(out_dir, f"result.{rank}.json"), "w") as f:
        json.dump({"losses": losses, "world": jax.process_count()}, f)
""")


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_launch_two_process_dp_parity(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    out = tmp_path / "out"
    out.mkdir()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # workers get their own XLA_FLAGS from launch --devices; scrub the test
    # process's 8-device forcing so each worker sees exactly 2
    env.pop("XLA_FLAGS", None)
    port = _free_port()
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--master", f"127.0.0.1:{port}",
         "--devices", "2", "--log_dir", str(tmp_path / "logs"),
         str(worker), str(out)],
        env=env, capture_output=True, text=True, timeout=570)
    logs = ""
    logdir = tmp_path / "logs"
    if logdir.exists():
        for f in sorted(logdir.iterdir()):
            logs += f"\n--- {f.name} ---\n" + f.read_text()[-3000:]
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:],
                                  logs)

    results = {}
    for rank in (0, 1):
        with open(out / f"result.{rank}.json") as f:
            results[rank] = json.load(f)
    assert results[0]["world"] == results[1]["world"] == 2
    # both ranks observed the same (global) loss sequence
    np.testing.assert_allclose(results[0]["losses"], results[1]["losses"],
                               rtol=1e-6)

    # single-process reference: same model, same global batch, same SGD
    import jax
    import jax.numpy as jnp
    from functools import partial

    import paddle_tpu as pt
    import paddle_tpu.nn.functional as F
    from paddle_tpu import nn
    from paddle_tpu.nn.module import functional_call

    pt.seed(0)
    model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    params = model.param_dict()
    r = np.random.default_rng(0)
    X = jnp.asarray(r.standard_normal((32, 16)).astype("float32"))
    Y = jnp.asarray(r.integers(0, 4, (32,)).astype("int32"))

    def loss_fn(p, x, y):
        outp, _ = functional_call(model, p, x, training=True)
        return F.cross_entropy(outp, y)

    @partial(jax.jit, donate_argnums=0)
    def step(p, x, y):
        l, g = jax.value_and_grad(loss_fn)(p, x, y)
        return jax.tree.map(lambda a, b: a - 0.1 * b, p, g), l

    ref = []
    for _ in range(5):
        params, l = step(params, X, Y)
        ref.append(float(l))
    np.testing.assert_allclose(results[0]["losses"], ref, rtol=2e-5,
                               err_msg="multi-process DP diverged from "
                                       "single-process reference")
