"""REAL multi-process distributed bootstrap (VERDICT r3 missing #4).

Parity target: the reference's multi-process distributed tests spawn real
trainer subprocesses and compare loss sequences
(test/legacy_test/test_dist_base.py:952, spawns at :1271/:1351). Here the
gang goes through the actual production path: paddle_tpu.distributed.launch
spawns 2 workers -> each calls init_parallel_env() ->
jax.distributed.initialize (distributed/parallel.py:46, CPU backend, 2
local devices per process) -> a DP train step over a 4-way global mesh
whose mean-loss gradient is a cross-process psum -> distributed checkpoint
save/load on the real jax.process_count()>1 branch -> loss parity with a
single-process run of the same model/data.
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent("""
    import json, os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")  # axon pin -> cpu
    out_dir = sys.argv[1]

    import numpy as np
    import paddle_tpu as pt
    import paddle_tpu.distributed as dist

    # the production bootstrap: env (set by launch) -> jax.distributed.initialize
    dist.init_parallel_env()
    assert jax.process_count() == 2, jax.process_count()
    rank = jax.process_index()
    assert dist.get_world_size() == 2 and dist.get_rank() == rank
    assert len(jax.devices()) == 4, jax.devices()          # 2 procs x 2 local
    assert len(jax.local_devices()) == 2

    import jax.numpy as jnp
    from functools import partial
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu import nn
    from paddle_tpu.core import mesh as mesh_lib
    from paddle_tpu.nn.module import functional_call
    import paddle_tpu.nn.functional as F

    mesh = mesh_lib.make_mesh({"dp": 4})
    pt.seed(0)
    model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    rep = NamedSharding(mesh, P())
    params = {k: jax.device_put(v, rep) for k, v in model.param_dict().items()}

    r = np.random.default_rng(0)
    X = r.standard_normal((32, 16)).astype("float32")
    Y = r.integers(0, 4, (32,)).astype("int32")
    dsh = NamedSharding(mesh, P("dp"))
    # each process contributes its local rows of the GLOBAL dp-sharded batch
    Xg = jax.make_array_from_process_local_data(dsh, X[rank * 16:(rank + 1) * 16])
    Yg = jax.make_array_from_process_local_data(dsh, Y[rank * 16:(rank + 1) * 16])

    def loss_fn(p, x, y):
        out, _ = functional_call(model, p, x, training=True)
        return F.cross_entropy(out, y)   # mean over the GLOBAL batch -> psum

    @partial(jax.jit, donate_argnums=0)
    def step(p, x, y):
        l, g = jax.value_and_grad(loss_fn)(p, x, y)
        return jax.tree.map(lambda a, b: a - 0.1 * b, p, g), l

    losses = []
    for _ in range(5):
        params, l = step(params, Xg, Yg)
        losses.append(float(l))

    # distributed checkpoint on the REAL multi-process branch
    from paddle_tpu.distributed.checkpoint import load_state_dict, save_state_dict
    ck = os.path.join(out_dir, "ckpt")
    save_state_dict(params, ck)
    template = {k: jax.device_put(jnp.zeros(v.shape, jnp.float32), rep)
                for k, v in params.items()}
    template = load_state_dict(template, ck)
    for k in params:
        a = np.asarray(jax.device_get(params[k].addressable_shards[0].data))
        b = np.asarray(jax.device_get(template[k].addressable_shards[0].data))
        np.testing.assert_allclose(a, b, rtol=0, atol=0, err_msg=k)

    with open(os.path.join(out_dir, f"result.{rank}.json"), "w") as f:
        json.dump({"losses": losses, "world": jax.process_count()}, f)
""")


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_launch_two_process_dp_parity(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    out = tmp_path / "out"
    out.mkdir()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # workers get their own XLA_FLAGS from launch --devices; scrub the test
    # process's 8-device forcing so each worker sees exactly 2
    env.pop("XLA_FLAGS", None)
    port = _free_port()
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--master", f"127.0.0.1:{port}",
         "--devices", "2", "--log_dir", str(tmp_path / "logs"),
         str(worker), str(out)],
        env=env, capture_output=True, text=True, timeout=570)
    logs = ""
    logdir = tmp_path / "logs"
    if logdir.exists():
        for f in sorted(logdir.iterdir()):
            logs += f"\n--- {f.name} ---\n" + f.read_text()[-3000:]
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:],
                                  logs)

    results = {}
    for rank in (0, 1):
        with open(out / f"result.{rank}.json") as f:
            results[rank] = json.load(f)
    assert results[0]["world"] == results[1]["world"] == 2
    # both ranks observed the same (global) loss sequence
    np.testing.assert_allclose(results[0]["losses"], results[1]["losses"],
                               rtol=1e-6)

    # single-process reference: same model, same global batch, same SGD
    import jax
    import jax.numpy as jnp
    from functools import partial

    import paddle_tpu as pt
    import paddle_tpu.nn.functional as F
    from paddle_tpu import nn
    from paddle_tpu.nn.module import functional_call

    pt.seed(0)
    model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    params = model.param_dict()
    r = np.random.default_rng(0)
    X = jnp.asarray(r.standard_normal((32, 16)).astype("float32"))
    Y = jnp.asarray(r.integers(0, 4, (32,)).astype("int32"))

    def loss_fn(p, x, y):
        outp, _ = functional_call(model, p, x, training=True)
        return F.cross_entropy(outp, y)

    @partial(jax.jit, donate_argnums=0)
    def step(p, x, y):
        l, g = jax.value_and_grad(loss_fn)(p, x, y)
        return jax.tree.map(lambda a, b: a - 0.1 * b, p, g), l

    ref = []
    for _ in range(5):
        params, l = step(params, X, Y)
        ref.append(float(l))
    np.testing.assert_allclose(results[0]["losses"], ref, rtol=2e-5,
                               err_msg="multi-process DP diverged from "
                                       "single-process reference")


WORKER_TP_ASYNC = textwrap.dedent("""
    import json, os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")  # axon pin -> cpu
    out_dir = sys.argv[1]

    import numpy as np
    import paddle_tpu as pt
    import paddle_tpu.distributed as dist

    dist.init_parallel_env()
    rank = jax.process_index()
    assert jax.process_count() == 2 and len(jax.devices()) == 4

    import jax.numpy as jnp
    from functools import partial
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu import nn
    from paddle_tpu.core import mesh as mesh_lib
    from paddle_tpu.nn.module import functional_call
    import paddle_tpu.nn.functional as F

    # --- TP crossing the process boundary (VERDICT r4 missing #4) ---
    # mp as the LEADING mesh axis pairs one device from EACH process into
    # every mp group, so the Column->Row parallel allreduce is a real
    # cross-process collective (parity: hybrid_parallel_mp_layers.py).
    mesh = mesh_lib.make_mesh({"mp": 2, "dp": 2})
    groups = [set(d.process_index for d in mesh.devices[:, j])
              for j in range(2)]
    assert all(g == {0, 1} for g in groups), groups

    pt.seed(0)
    class TPMLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(16, 32, weight_spec=(None, "mp"))
            self.fc2 = nn.Linear(32, 4, weight_spec=("mp", None))
        def forward(self, x):
            return self.fc2(F.relu(self.fc1(x)))

    model = TPMLP()
    specs = model.spec_dict()
    # every process holds the full weight on host; make_array_from_callback
    # hands each addressable device its slice (process_local_data would
    # misread the full array as one process's SHARD for mp-sharded dims)
    params = {}
    for k, v in model.param_dict().items():
        sh = NamedSharding(mesh, P(*(specs.get(k) or ())))
        arr = np.asarray(v)
        params[k] = jax.make_array_from_callback(
            arr.shape, sh, lambda idx, arr=arr: arr[idx])

    r = np.random.default_rng(0)
    X = r.standard_normal((32, 16)).astype("float32")
    Y = r.integers(0, 4, (32,)).astype("int32")
    dsh = NamedSharding(mesh, P("dp"))
    # every process addresses devices in BOTH dp groups (dp is the trailing
    # axis), so the process-local view is the full global batch
    Xg = jax.make_array_from_process_local_data(dsh, X)
    Yg = jax.make_array_from_process_local_data(dsh, Y)

    def loss_fn(p, x, y):
        out, _ = functional_call(model, p, x, training=True)
        return F.cross_entropy(out, y)

    @partial(jax.jit, donate_argnums=0)
    def step(p, x, y):
        l, g = jax.value_and_grad(loss_fn)(p, x, y)
        return jax.tree.map(lambda a, b: a - 0.1 * b, p, g), l

    losses = []
    with mesh_lib.use_mesh(mesh):
        for _ in range(5):
            params, l = step(params, Xg, Yg)
            losses.append(float(l))

    # --- ASYNC distributed checkpoint on the real gang (VERDICT r4 weak
    # #4): coordinator-merge through done-marker files across processes,
    # plus a second round to the same path (in-flight guard + seq bump) ---
    from paddle_tpu.distributed.checkpoint import (load_state_dict,
                                                   save_state_dict)
    ck = os.path.join(out_dir, "ckpt_async")
    h1 = save_state_dict(params, ck, async_save=True)
    h1.result(timeout=120)
    assert os.path.exists(os.path.join(ck, "metadata.pkl"))
    params2 = jax.tree.map(lambda a: a + 1.0, params)
    h2 = save_state_dict(params2, ck, async_save=True)  # round 2, same path
    h2.result(timeout=120)
    rep = NamedSharding(mesh, P())
    template = {k: jax.make_array_from_process_local_data(
                    rep, np.zeros(v.shape, np.float32))
                for k, v in params.items()}
    loaded = load_state_dict(template, ck)
    # loaded is replicated (full array on every device); params2 is
    # TP-sharded -- compare each addressable shard against its slice of
    # the loaded full array (round-2 values must have won)
    for k in params2:
        full = np.asarray(jax.device_get(loaded[k].addressable_shards[0].data))
        for sh in params2[k].addressable_shards:
            np.testing.assert_array_equal(
                np.asarray(jax.device_get(sh.data)), full[sh.index],
                err_msg=k)

    # --- PP crossing the process boundary: staged layers over a leading
    # pp axis (each 1F1B ppermute hop crosses processes) ---
    from paddle_tpu.distributed.pipeline import PipelineStagedLayers
    mesh_pp = mesh_lib.make_mesh({"pp": 2, "dp": 2})
    with mesh_lib.use_mesh(mesh_pp):
        pt.seed(1)
        class PPModel(nn.Layer):
            def __init__(self):
                super().__init__()
                self.embed = nn.Linear(16, 32)
                self.middle = PipelineStagedLayers(
                    [nn.Linear(32, 32) for _ in range(4)],
                    num_micro=2, axis="pp")
                self.head = nn.Linear(32, 4)
            def forward(self, x):
                return self.head(F.relu(self.middle(self.embed(x))))
        ppm = PPModel()
        opt = pt.optimizer.Adam(learning_rate=1e-3, parameters=ppm)
        stepp = pt.jit.TrainStep(ppm, opt,
                                 lambda o, t: F.cross_entropy(o, t))
        xpp = np.random.default_rng(1).standard_normal((8, 16)).astype(
            "float32")
        ypp = np.random.default_rng(2).integers(0, 4, 8)
        lpp = [float(stepp(xpp, ypp)) for _ in range(2)]
        assert all(np.isfinite(v) for v in lpp), lpp

    with open(os.path.join(out_dir, f"result.{rank}.json"), "w") as f:
        json.dump({"losses": losses, "pp_losses": lpp}, f)
""")


def test_launch_two_process_tp_pp_async_ckpt(tmp_path):
    """TP allreduce + 1F1B pp hops crossing a real process boundary, and
    the ASYNC checkpoint coordinator-merge on real ranks (VERDICT r4
    missing #4 / weak #4 — retires the monkeypatched coverage as the only
    coverage)."""
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER_TP_ASYNC)
    out = tmp_path / "out"
    out.mkdir()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    port = _free_port()
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--master", f"127.0.0.1:{port}",
         "--devices", "2", "--log_dir", str(tmp_path / "logs"),
         str(worker), str(out)],
        env=env, capture_output=True, text=True, timeout=570)
    logs = ""
    logdir = tmp_path / "logs"
    if logdir.exists():
        for f in sorted(logdir.iterdir()):
            logs += f"\n--- {f.name} ---\n" + f.read_text()[-3000:]
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:],
                                  logs)
    results = {}
    for rank in (0, 1):
        with open(out / f"result.{rank}.json") as f:
            results[rank] = json.load(f)
    np.testing.assert_allclose(results[0]["losses"], results[1]["losses"],
                               rtol=1e-6)
    np.testing.assert_allclose(results[0]["pp_losses"],
                               results[1]["pp_losses"], rtol=1e-6)

    # single-process dense reference for the TP MLP (same seed/init/data)
    import jax
    from functools import partial

    import paddle_tpu as pt
    import paddle_tpu.nn.functional as F
    from paddle_tpu import nn
    from paddle_tpu.nn.module import functional_call

    pt.seed(0)

    class TPMLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(16, 32, weight_spec=(None, "mp"))
            self.fc2 = nn.Linear(32, 4, weight_spec=("mp", None))

        def forward(self, x):
            return self.fc2(F.relu(self.fc1(x)))

    model = TPMLP()
    params = model.param_dict()
    r = np.random.default_rng(0)
    X = np.asarray(r.standard_normal((32, 16)).astype("float32"))
    Y = np.asarray(r.integers(0, 4, (32,)).astype("int32"))

    def loss_fn(p, x, y):
        outp, _ = functional_call(model, p, x, training=True)
        return F.cross_entropy(outp, y)

    @partial(jax.jit, donate_argnums=0)
    def step(p, x, y):
        l, g = jax.value_and_grad(loss_fn)(p, x, y)
        return jax.tree.map(lambda a, b: a - 0.1 * b, p, g), l

    ref = []
    for _ in range(5):
        params, l = step(params, X, Y)
        ref.append(float(l))
    np.testing.assert_allclose(results[0]["losses"], ref, rtol=2e-5,
                               err_msg="cross-process TP diverged from "
                                       "single-process dense reference")
