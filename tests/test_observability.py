"""paddle_tpu.observability — tracing, flight recorder, SLO export.

The contracts (OBSERVABILITY.md):

1. ZERO-COST OFF — the NULL_TRACER hot path records nothing and
   allocates nothing; tracing ON must not perturb the engine either:
   token streams stay bitwise identical to ``model.generate()`` and the
   decode step stays ONE compiled program.
2. LOADABLE TRACES — ``chrome_trace()`` emits Chrome trace-event JSON
   (every event has ph/ts/pid/tid, durations carry dur, instants carry
   scope) with one thread per track so requests render as rows.
3. STATE AT DEATH — the FlightRecorder is a bounded ring over the event
   stream, auto-dumped to rank-annotated JSON (ONE schema) when the
   engine hits a terminal condition; a stall snapshot points at the
   file.
"""

import json
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.distributed import fault
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.observability import (NULL_TRACER, FlightRecorder,
                                      MetricsServer, Tracer, parse_prometheus)
from paddle_tpu.observability.recorder import SCHEMA
from paddle_tpu.serving import (SchedulerStalledError, ServingEngine,
                                ServingMetrics)

import jax.numpy as jnp

RNG = np.random.default_rng(11)


@pytest.fixture(scope="module")
def model():
    pt.seed(123)
    m = LlamaForCausalLM(llama_tiny(dtype="float32",
                                    mp_axis=None, fsdp_axis=None))
    m.eval()
    return m


def _reference(model, prompt, max_new):
    out = model.generate(jnp.asarray([prompt]), max_new_tokens=max_new)
    return np.asarray(out)[0, len(prompt):].tolist()


@pytest.fixture
def fault_free(monkeypatch):
    """No FaultPlan leaks out of a chaos test, no rank env leaks in."""
    fault.deactivate()
    monkeypatch.delenv("PADDLE_TRAINER_ID", raising=False)
    monkeypatch.delenv("PROCESS_ID", raising=False)
    monkeypatch.delenv("PADDLE_RESTART_EPOCH", raising=False)
    yield
    fault.deactivate()


def _vclock():
    t = [0.0]
    return t, (lambda: t[0])


# ---------------------------------------------------------------------------
# tracer: virtual-clock timelines, zero-cost off
# ---------------------------------------------------------------------------

class TestTracer:
    def test_span_records_measured_duration(self):
        t, clock = _vclock()
        tr = Tracer(clock=clock)
        with tr.span("decode_dispatch", slots=2):
            t[0] = 0.5
        (ev,) = tr.events
        assert ev["ph"] == "X" and ev["name"] == "decode_dispatch"
        assert ev["ts"] == 0.0 and ev["dur"] == 0.5
        assert ev["track"] == "engine" and ev["args"] == {"slots": 2}

    def test_lifecycle_timeline_on_a_request_track(self):
        t, clock = _vclock()
        tr = Tracer(clock=clock)
        tr.begin("queued", track="r-0", prompt=3)
        t[0] = 1.0
        tr.instant("admit", track="r-0", slot=0)
        tr.end("queued", track="r-0")
        t[0] = 2.5
        tr.instant("finish", track="r-0", reason="stop")
        assert [(e["ph"], e["name"], e["ts"]) for e in tr.events] == [
            ("B", "queued", 0.0), ("i", "admit", 1.0),
            ("E", "queued", 1.0), ("i", "finish", 2.5)]
        assert all(e["track"] == "r-0" for e in tr.events)

    def test_bump_accumulates_and_records_counter_events(self):
        tr = Tracer(clock=lambda: 0.0)
        tr.bump("compiles")
        tr.bump("compiles", 2)
        tr.bump("tokens", track="engine")
        assert tr.counters == {"compiles": 3, "tokens": 1}
        c0, c1, _ = tr.events
        assert c0["ph"] == "C" and c0["args"] == {"compiles": 1}
        assert c1["args"] == {"compiles": 3}

    def test_disabled_tracer_is_a_noop(self):
        tr = Tracer(enabled=False)
        with tr.span("x"):
            pass
        tr.begin("b")
        tr.end("b")
        tr.instant("i")
        tr.bump("c")
        assert tr.events == [] and tr.counters == {}
        # the null span context is shared — no per-call allocation
        assert NULL_TRACER.span("a") is NULL_TRACER.span("b")
        assert NULL_TRACER.events == []

    def test_sink_subscription_is_idempotent(self):
        tr = Tracer(clock=lambda: 0.0)
        seen = []
        tr.add_sink(seen.append)
        tr.add_sink(seen.append)  # engine re-attach must not double-record
        tr.instant("once")
        assert len(seen) == 1


# ---------------------------------------------------------------------------
# Chrome trace export
# ---------------------------------------------------------------------------

class TestChromeTrace:
    def _traced(self):
        t, clock = _vclock()
        tr = Tracer(clock=clock)
        with tr.span("step", steps=1):
            t[0] = 0.001
        tr.begin("queued", track="r-0")
        tr.end("queued", track="r-0")
        tr.instant("quarantine", track="pool", pages=1)
        tr.bump("compiles")
        return tr

    def test_every_event_carries_the_required_schema_keys(self):
        tr = self._traced()
        doc = json.loads(json.dumps(tr.chrome_trace()))  # round-trips
        events = doc["traceEvents"]
        assert events, "empty trace"
        for ev in events:
            assert {"name", "ph", "ts", "pid", "tid"} <= set(ev), ev
            if ev["ph"] == "X":
                assert "dur" in ev, ev
            if ev["ph"] == "i":
                assert ev["s"] == "t", ev
        # timestamps are scaled to microseconds at dump time
        span = next(e for e in events if e["ph"] == "X")
        assert span["dur"] == pytest.approx(1000.0)  # 0.001 s

    def test_tracks_become_named_threads(self):
        doc = self._traced().chrome_trace()
        names = {e["args"]["name"]: e["tid"] for e in doc["traceEvents"]
                 if e["ph"] == "M" and e["name"] == "thread_name"}
        assert set(names) == {"engine", "r-0", "pool"}
        assert names["engine"] == 0  # engine registered first: row 0
        assert len(set(names.values())) == 3  # one distinct row per track
        by_tid = {names["r-0"]: "r-0", names["pool"]: "pool"}
        for ev in doc["traceEvents"]:
            if ev["ph"] in ("B", "E"):
                assert by_tid[ev["tid"]] == "r-0"

    def test_dump_is_atomic_and_loadable(self, tmp_path):
        path = str(tmp_path / "traces" / "serve.trace.json")
        out = self._traced().dump_chrome_trace(path)
        assert out == path
        with open(path) as f:
            doc = json.load(f)
        assert doc["displayTimeUnit"] == "ms"
        assert not (tmp_path / "traces" / "serve.trace.json.tmp").exists()


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

class TestFlightRecorder:
    def test_ring_keeps_only_the_last_capacity_events(self):
        tr = Tracer(clock=lambda: 0.0)
        rec = FlightRecorder(capacity=8, tracer=tr)
        for i in range(20):
            tr.instant(f"e{i}")
        assert len(rec) == 8
        names = [e["name"] for e in rec.events()]
        assert names == [f"e{i}" for i in range(12, 20)]  # oldest dropped
        assert sum(rec.histogram().values()) == 8

    def test_capacity_must_be_positive(self):
        with pytest.raises(ValueError, match="capacity"):
            FlightRecorder(capacity=0)

    def test_dump_writes_rank_annotated_schema(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_TRAINER_ID", "3")
        tr = Tracer(clock=lambda: 0.0)
        rec = FlightRecorder(capacity=16, tracer=tr,
                             dump_dir=str(tmp_path))
        tr.instant("stall", queue=2)
        path = rec.dump("scheduler stalled!", snapshot={"idle_steps": 3})
        assert path.endswith("flight_recorder.rank3.scheduler_stalled_.json")
        with open(path) as f:
            payload = json.load(f)
        assert payload["schema"] == SCHEMA
        assert payload["rank"] == 3
        assert payload["reason"] == "scheduler stalled!"
        assert payload["snapshot"] == {"idle_steps": 3}
        assert payload["n_events"] == 1
        assert payload["histogram"] == {"stall": 1}
        assert payload["events"][0]["name"] == "stall"
        assert rec.last_dump_path == path and rec.dumps == 1


# ---------------------------------------------------------------------------
# engine integration: tracing must not perturb serving
# ---------------------------------------------------------------------------

class TestEngineTracing:
    def test_tracing_off_by_default(self, model):
        eng = ServingEngine(model, num_pages=16, page_size=4, max_slots=2)
        assert eng.tracer is NULL_TRACER
        assert eng.stats()["tracing"] is False

    def test_tracing_on_bitwise_parity_single_decode_program(self, model):
        prompts = [list(RNG.integers(0, 512, n)) for n in (5, 9, 3)]
        max_new = 8
        refs = [_reference(model, p, max_new) for p in prompts]
        tr = Tracer()
        eng = ServingEngine(model, num_pages=64, page_size=4, max_slots=4,
                            max_pages_per_slot=8, tracer=tr)
        assert eng.stats()["tracing"] is True
        rids = [eng.add_request(prompts[0], max_new),
                eng.add_request(prompts[1], max_new)]
        eng.step()
        rids.append(eng.add_request(prompts[2], max_new))
        res = eng.run_to_completion(max_steps=200)
        for rid, ref in zip(rids, refs):
            assert res[rid] == ref  # bitwise: tracing observes, not alters
        assert eng.decode_program_count() == 1
        assert "decode_retraces" not in tr.counters
        # the step phases, lifecycle events and compile markers all
        # landed (chunked default: prompts stream through the mixed
        # program, so chunk instants replace prefill_dispatch spans)
        names = {e["name"] for e in tr.events}
        assert {"deadline_sweep", "admission", "mixed_dispatch",
                "chunk", "decode_dispatch", "device_sync", "sample_emit",
                "queued", "running", "admit", "finish",
                "compile"} <= names, names
        assert tr.counters["tokens"] == sum(len(r) for r in refs)
        assert tr.counters["finishes"] == 3
        assert tr.counters["compiles"] >= 2  # mixed program + decode
        # every request track's B/E durations are balanced — the Chrome
        # B/E stack per tid corrupts if the scheduler mislays one side
        for rid in rids:
            evs = [e for e in tr.events if e["track"] == rid]
            for phase in ("queued", "running"):
                b = sum(1 for e in evs
                        if e["name"] == phase and e["ph"] == "B")
                e_ = sum(1 for e in evs
                         if e["name"] == phase and e["ph"] == "E")
                assert b == e_ > 0, (rid, phase, b, e_)

    @pytest.mark.faults
    def test_stall_dumps_the_flight_recorder(self, model, tmp_path,
                                             fault_free):
        # every pool alloc fails -> zero admission progress -> the stall
        # backstop fires; the snapshot must point at the dump file
        fault.activate(fault.FaultPlan([
            fault.FaultSpec(site="serving.alloc", action="raise",
                            prob=1.0, once=False)]))
        tr = Tracer()
        rec = FlightRecorder(capacity=64, tracer=tr,
                             dump_dir=str(tmp_path))
        eng = ServingEngine(model, num_pages=32, page_size=4, max_slots=2,
                            tracer=tr, flight_recorder=rec)
        eng.add_request([1, 2, 3], 4)
        with pytest.raises(SchedulerStalledError) as ei:
            eng.run_to_completion(max_steps=50)
        path = ei.value.snapshot["flight_recorder"]
        assert path == rec.last_dump_path
        with open(path) as f:
            payload = json.load(f)
        assert payload["schema"] == SCHEMA
        assert payload["reason"] == "scheduler_stalled"
        assert payload["histogram"]["admit_rollback"] >= 1
        assert payload["snapshot"]["idle_steps"] >= 1
        eng.audit_pool()

    def test_drain_dumps_outcomes(self, model, tmp_path):
        tr = Tracer()
        rec = FlightRecorder(capacity=64, tracer=tr,
                             dump_dir=str(tmp_path))
        eng = ServingEngine(model, num_pages=32, page_size=4, max_slots=2,
                            tracer=tr, flight_recorder=rec)
        rid = eng.add_request(list(RNG.integers(0, 512, 4)), 16)
        eng.step()
        eng.step()
        eng.drain(timeout_s=0.0)
        with open(rec.last_dump_path) as f:
            payload = json.load(f)
        assert payload["reason"] == "drain"
        assert payload["snapshot"]["outcomes"] == {rid: "preempted"}

    def test_metrics_server_scrapes_a_live_engine(self, model):
        tr = Tracer()
        eng = ServingEngine(model, num_pages=32, page_size=4, max_slots=2,
                            tracer=tr)
        eng.add_request(list(RNG.integers(0, 512, 5)), 6)
        eng.run_to_completion(max_steps=100)
        srv = MetricsServer(engine=eng)
        port = srv.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
                body = r.read().decode()
            metrics = parse_prometheus(body)
            assert metrics["paddle_serving_requests_finished"] == 1
            assert metrics["paddle_serving_tokens_generated"] == 6
            assert "paddle_serving_goodput_at_slo" in metrics
            assert "paddle_serving_pool_peak_in_use" in metrics
            assert metrics["paddle_serving_trace_tokens_total"] == 6
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
                health = json.loads(r.read().decode())
            assert health["status"] == "ok"
            assert health["running"] == 0
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# goodput under SLO
# ---------------------------------------------------------------------------

class TestGoodput:
    def _metrics(self):
        t, clock = _vclock()
        m = ServingMetrics(clock=clock)
        # r-good: ttft 0.5s, itl gaps 0.1s, normal finish
        m.on_arrival("r-good")
        t[0] = 0.5
        m.on_token("r-good")
        t[0] = 0.6
        m.on_token("r-good")
        t[0] = 0.7
        m.on_token("r-good")
        m.on_finish("r-good", "stop")
        # r-slow: normal finish but ttft 3s blows the SLO
        m.on_arrival("r-slow")
        t[0] = 3.0
        m.on_token("r-slow")
        m.on_finish("r-slow", "length")
        # r-dead: fast but abnormal finish — never good
        m.on_arrival("r-dead")
        t[0] = 3.1
        m.on_token("r-dead")
        t[0] = 4.0
        m.on_finish("r-dead", "nonfinite")
        return m  # wall = 4.0s

    def test_goodput_counts_only_slo_meeting_normal_finishes(self):
        m = self._metrics()
        # unconstrained: both normal finishes count, the abnormal never
        assert m.goodput_at_slo() == pytest.approx(2 / 4.0)
        # TTFT SLO of 1s drops r-slow
        assert m.goodput_at_slo(ttft_p99_s=1.0) == pytest.approx(1 / 4.0)
        # ITL SLO below r-good's 0.1s gaps drops it too
        assert m.goodput_at_slo(ttft_p99_s=1.0,
                                itl_p99_s=0.05) == 0.0
        assert m.goodput_at_slo(ttft_p99_s=1.0,
                                itl_p99_s=0.2) == pytest.approx(1 / 4.0)

    def test_summary_carries_goodput_at_the_configured_slo(self):
        m = self._metrics()
        s = m.summary()
        assert s["goodput_at_slo"] == pytest.approx(2 / 4.0)  # no SLO set
        m.set_slo(ttft_p99_s=1.0, itl_p99_s=0.25)
        assert m.summary()["goodput_at_slo"] == pytest.approx(1 / 4.0)
