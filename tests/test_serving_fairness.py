"""SLO-aware overload control (SERVING.md "Overload control & tenant
fairness"; RESILIENCE.md "Overload playbook").

The overload-control contracts:

1. FAIRNESS NEVER CHANGES A STREAM — the weighted virtual-token-counter
   queue (Sheng et al., OSDI'24) reorders admission ACROSS tenants only
   (FCFS within a tenant), and per-request determinism (seed + token
   index) makes every finished stream bitwise identical to
   ``generate()`` and to the FCFS arm, whatever the interleaving.
2. QUOTAS SHED AT THE DOOR — per-tenant live-slot caps skip (the
   request waits, nothing is lost) while queued-token caps shed with a
   typed retryable :class:`AdmissionShedError` carrying a deterministic
   ``retry_after_s``; an infeasible deadline is shed BEFORE it burns
   pool pages.
3. BROWNOUT IS HOST-SIDE ONLY — the ladder (budget shrink -> drafter
   off -> lowest-priority shed) moves scalars and queue membership,
   never compiled shapes: ``step_program_counts()`` stays
   ``{"decode": 1, "mixed": 1}`` across every transition, and
   hysteresis walks it back down as load clears.
4. FAILOVER COMPOSES — a replica killed mid-flood replays onto the
   survivor under the SURVIVOR's quotas, and client streams stay
   bitwise and exactly-once.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.distributed import fault
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.serving import (AdmissionShedError, BrownoutConfig,
                                FleetRouter, ServingEngine,
                                overload_workload)
from paddle_tpu.serving.errors import ServingError

RNG = np.random.default_rng(47)

P_A = RNG.integers(0, 512, 6).tolist()
P_B = RNG.integers(0, 512, 9).tolist()
P_C = RNG.integers(0, 512, 13).tolist()
MAX_NEW = 6


@pytest.fixture(scope="module")
def model():
    pt.seed(123)
    m = LlamaForCausalLM(llama_tiny(dtype="float32",
                                    mp_axis=None, fsdp_axis=None))
    m.eval()
    return m


@pytest.fixture(scope="module")
def refs(model):
    return {id_: _reference(model, p, MAX_NEW)
            for id_, p in (("a", P_A), ("b", P_B), ("c", P_C))}


@pytest.fixture
def fault_free():
    fault.deactivate()
    yield
    fault.deactivate()


def _reference(model, prompt, max_new):
    out = model.generate(jnp.asarray([prompt]), max_new_tokens=max_new)
    return np.asarray(out)[0, len(prompt):].tolist()


def _engine(model, **kw):
    kw.setdefault("num_pages", 64)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_slots", 4)
    kw.setdefault("max_pages_per_slot", 16)
    return ServingEngine(model, **kw)


class _StepClock:
    """Virtual clock frozen WITHIN a step and advanced one unit per
    step by the driver: TTFT/deadlines become exact step counts, so
    latency assertions are deterministic on any host."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class _TickClock:
    """Advances a tiny epsilon on EVERY read: now() is monotone inside
    a step, so the step-duration EMA (and with it ``retry_after_s``)
    becomes deterministic and nonzero after the first step."""

    def __init__(self, eps: float = 0.001):
        self.t = 0.0
        self.eps = eps

    def __call__(self):
        self.t += self.eps
        return self.t


def _drive(wl, eng, clock, max_steps=800):
    """Replay a workload on one engine, advancing the virtual clock by
    one unit per engine step; typed rejections count as shed."""
    i, step, shed = 0, 0, 0
    reqs = wl.requests
    while i < len(reqs) or eng.scheduler.has_work():
        while i < len(reqs) and reqs[i].arrival_step <= step:
            r = reqs[i]
            i += 1
            try:
                eng.add_request(r.prompt, r.max_new_tokens, rid=r.rid,
                                tenant=r.tenant, priority=r.priority,
                                deadline_s=r.deadline_s)
            except ServingError:
                shed += 1
        eng.step()
        clock.t += 1.0
        step += 1
        assert step < max_steps, "workload did not drain"
    return shed


# ---------------------------------------------------------------------------
# fair scheduling (weighted virtual token counters)
# ---------------------------------------------------------------------------

class TestFairScheduling:
    def test_fair_streams_bitwise_identical_to_generate(self, model, refs,
                                                        fault_free):
        """Contract 1: tenancy, weights and priorities change WHO runs
        next, never WHAT a request decodes."""
        eng = _engine(model, fair_scheduling=True,
                      tenant_weights={0: 1.0, 1: 3.0})
        rids = [eng.add_request(p, MAX_NEW, tenant=t, priority=t)
                for p, t in ((P_A, 0), (P_B, 1), (P_C, 2))]
        res = eng.run_to_completion(max_steps=300)
        for rid, ref in zip(rids, (refs["a"], refs["b"], refs["c"])):
            assert res[rid] == ref
        assert eng.step_program_counts() == {"decode": 1, "mixed": 1}

    def test_cold_tenant_jumps_hot_backlog(self, model, fault_free):
        """A late cold-tenant arrival is served ahead of the hot
        tenant's backlog (its counter was lifted to the backlogged
        minimum, the hot tenant's keeps charging), while FCFS within
        the hot tenant is preserved."""
        eng = _engine(model, max_slots=1, fair_scheduling=True)
        hot = [eng.add_request(P_A, 2, tenant=0) for _ in range(3)]
        order, seen = [], set()

        def poll():
            for r in eng.scheduler.running.values():
                if r.rid not in seen:
                    seen.add(r.rid)
                    order.append(r.rid)

        eng.step()
        poll()
        assert order == [hot[0]]
        cold = eng.add_request(P_B, 2, tenant=1)
        guard = 0
        while eng.scheduler.has_work():
            eng.step()
            poll()
            guard += 1
            assert guard < 200
        assert order.index(cold) < order.index(hot[2])
        assert order.index(hot[0]) < order.index(hot[1]) \
            < order.index(hot[2])          # FCFS within the hot tenant

    def test_fcfs_unchanged_when_fairness_off(self, model, fault_free):
        eng = _engine(model, max_slots=1)
        rids = [eng.add_request(p, 2, tenant=t)
                for p, t in ((P_A, 0), (P_B, 0), (P_C, 1))]
        order, seen = [], set()
        guard = 0
        while eng.scheduler.has_work():
            eng.step()
            for r in eng.scheduler.running.values():
                if r.rid not in seen:
                    seen.add(r.rid)
                    order.append(r.rid)
            guard += 1
            assert guard < 200
        assert order == rids


# ---------------------------------------------------------------------------
# admission quotas + infeasibility shedding
# ---------------------------------------------------------------------------

class TestAdmissionQuotas:
    def test_live_slot_cap_skips_never_sheds(self, model, fault_free):
        """tenant_max_live holds a tenant to N concurrent slots: excess
        requests WAIT (no error) and everything still finishes."""
        eng = _engine(model, fair_scheduling=True, tenant_max_live=1)
        rids = [eng.add_request(P_A, 4, tenant=0),
                eng.add_request(P_B, 4, tenant=0),
                eng.add_request(P_C, 4, tenant=1)]
        guard = 0
        while eng.scheduler.has_work():
            eng.step()
            per: dict = {}
            for r in eng.scheduler.running.values():
                per[r.tenant] = per.get(r.tenant, 0) + 1
            assert all(v <= 1 for v in per.values())
            guard += 1
            assert guard < 200
        for rid in rids:
            assert eng.request(rid).finish_reason in ("stop", "length")
        assert eng.metrics.counters["rejected_quota"] == 0

    def test_queued_token_quota_sheds_with_retry_hint(self, model,
                                                      fault_free):
        clock = _TickClock()
        eng = _engine(model, clock=clock, max_slots=1,
                      tenant_max_queued_tokens=48)
        need = len(P_C) + 8                     # 21 service tokens each
        eng.add_request(P_C, 8, tenant=0)
        eng.add_request(P_C, 8, tenant=0, rid="q2")
        # held 42 + 21 > 48 -> shed; cold engine -> honest 0.0 hint
        with pytest.raises(AdmissionShedError) as ei:
            eng.add_request(P_C, 8, tenant=0, rid="q3")
        assert ei.value.kind == "tenant_quota"
        assert ei.value.tenant == 0
        assert ei.value.retryable is True
        assert ei.value.retry_after_s == 0.0
        # another tenant is untouched by tenant 0's quota
        eng.add_request(P_A, 4, tenant=1)
        # after timed steps the hint becomes a positive drain estimate
        eng.step()
        eng.step()
        eng.add_request(P_C, 8, tenant=0, rid="q4")
        with pytest.raises(AdmissionShedError) as ei2:
            eng.add_request(P_C, 8, tenant=0, rid="q5")
        assert ei2.value.retry_after_s > 0.0
        assert eng.metrics.counters["rejected_quota"] == 2
        assert eng.metrics.counters["shed"] == 0   # admission shed, not
        #                                            a queued-request kill
        del need

    def test_infeasible_deadline_shed(self, model, fault_free):
        clock = _TickClock()
        eng = _engine(model, clock=clock, shed_infeasible=True)
        # cold engine: no step-duration data -> the gate never fires
        r1 = eng.add_request(P_A, 4, deadline_s=1e6)
        eng.step()
        eng.step()
        # now the EMA exists: a deadline the backlog can't meet is shed
        # at the door instead of burning pages on a guaranteed timeout
        with pytest.raises(AdmissionShedError) as ei:
            eng.add_request(P_C, 32, deadline_s=1e-9, rid="doomed")
        assert ei.value.kind == "deadline_infeasible"
        assert eng.metrics.counters["rejected_infeasible"] == 1
        # a generous deadline still admits
        r2 = eng.add_request(P_B, 4, deadline_s=1e6)
        guard = 0
        while eng.scheduler.has_work():
            eng.step()
            guard += 1
            assert guard < 200
        for rid in (r1, r2):
            assert eng.request(rid).finish_reason in ("stop", "length")


# ---------------------------------------------------------------------------
# brownout ladder
# ---------------------------------------------------------------------------

class TestBrownout:
    def test_config_validation(self):
        with pytest.raises(ValueError):
            BrownoutConfig(high_queue=2, low_queue=4)
        with pytest.raises(ValueError):
            BrownoutConfig(budget_frac=0.0)
        with pytest.raises(ValueError):
            BrownoutConfig(dwell_steps=0)

    def test_level1_shrinks_budget_host_side(self, model, fault_free):
        eng = _engine(model, prefill_token_budget=64,
                      brownout=BrownoutConfig(budget_frac=0.5))
        assert eng._effective_prefill_budget() == 64
        eng._brownout_level = 1
        assert eng._effective_prefill_budget() == 32
        eng._brownout_level = 0

    def test_ladder_walks_up_and_down_zero_recompiles(self, model,
                                                      fault_free):
        """Contract 3: a burst pushes the ladder up (through the
        drafter-off level), the drain walks it back to 0, and the two
        compiled programs never retrace."""
        clock = _StepClock()
        eng = _engine(model, clock=clock, num_pages=96,
                      max_pages_per_slot=24, speculative=2,
                      brownout=BrownoutConfig(high_queue=3, low_queue=1,
                                              dwell_steps=1))
        rids = [eng.add_request(p, 4, tenant=0, priority=1)
                for p in (P_A, P_B, P_C) * 3]
        levels = set()
        guard = 0
        while eng.scheduler.has_work():
            eng.step()
            clock.t += 1.0
            levels.add(eng.brownout_level)
            guard += 1
            assert guard < 300
        assert max(levels) >= 2                 # ladder actually climbed
        assert eng.brownout_level == 0          # ... and fully released
        ms = eng.metrics.summary()
        assert ms["brownout_transitions"] >= 2
        assert ms["brownout_level1_steps"] > 0
        assert eng.step_program_counts() == {"decode": 1, "mixed": 1}
        for rid in rids:
            assert eng.request(rid).finish_reason in ("stop", "length",
                                                      "shed")
        eng.audit_pool()

    def test_level3_sheds_lowest_priority_first(self, model, fault_free):
        """Level 3 takes the LOWEST-priority queued requests (youngest
        first within a class); high-priority work rides out the
        brownout untouched."""
        clock = _StepClock()
        eng = _engine(model, clock=clock, max_slots=1,
                      brownout=BrownoutConfig(high_queue=2, low_queue=0,
                                              dwell_steps=1))
        lows = [eng.add_request(P_A, 2, tenant=0, priority=0)
                for _ in range(4)]
        highs = [eng.add_request(P_B, 2, tenant=1, priority=5)
                 for _ in range(2)]
        guard = 0
        while eng.scheduler.has_work():
            eng.step()
            clock.t += 1.0
            guard += 1
            assert guard < 200
        shed = [rid for rid in lows + highs
                if eng.request(rid).finish_reason == "shed"]
        assert shed                              # level 3 engaged
        assert set(shed) <= set(lows)            # only priority-0 victims
        for rid in highs:
            assert eng.request(rid).finish_reason in ("stop", "length")
        assert eng.metrics.counters["shed"] == len(shed)
        assert eng.metrics.shed_by_priority().get(0, 0) == len(shed)
        assert eng.step_program_counts() == {"decode": 1, "mixed": 1}


# ---------------------------------------------------------------------------
# chaos: fault sites + failover composition
# ---------------------------------------------------------------------------

class TestChaos:
    def test_admission_fault_site_raises_typed(self, model, fault_free):
        fault.activate(fault.FaultPlan([
            fault.FaultSpec(site="serving.admission", action="raise",
                            match=r"^boom$"),
        ]))
        eng = _engine(model)
        with pytest.raises(fault.FaultInjected):
            eng.add_request(P_A, 2, rid="boom")
        # the fault fired BEFORE any state change: same rid re-admits
        fault.deactivate()
        rid = eng.add_request(P_A, 2, rid="boom")
        assert rid == "boom"

    def test_brownout_fault_site_fires_on_transition(self, model,
                                                     fault_free):
        fault.activate(fault.FaultPlan([
            fault.FaultSpec(site="serving.brownout", action="raise",
                            match=r"^0->1$"),
        ]))
        eng = _engine(model, max_slots=1,
                      brownout=BrownoutConfig(high_queue=2, low_queue=0,
                                              dwell_steps=1))
        for _ in range(5):
            eng.add_request(P_A, 2)
        with pytest.raises(fault.FaultInjected):
            for _ in range(10):
                eng.step()
        # the level was committed before the injected crash: the
        # controller state stays consistent and the engine drains
        assert eng.brownout_level == 1
        fault.deactivate()
        guard = 0
        while eng.scheduler.has_work():
            eng.step()
            guard += 1
            assert guard < 200
        eng.audit_pool()

    @pytest.mark.slow
    def test_kill_mid_flood_survivor_quota_holds_replay_bitwise(
            self, model, fault_free):
        """Contract 4: replica killed mid-flood; the survivor's
        queued-token quota gates the failover replay (rejections are
        breaker data points, the records stay queued), and every
        delivered stream is bitwise the no-failure run."""
        prompts = [(P_A, 4), (P_B, 4), (P_C, 4)] * 3
        # no-failure reference: one engine, same prompts
        ref_eng = _engine(model, num_pages=96, max_pages_per_slot=24)
        ref_rids = [ref_eng.add_request(p, n, rid=f"r-{i}")
                    for i, (p, n) in enumerate(prompts)]
        ref = ref_eng.run_to_completion(max_steps=400)

        engines = [_engine(model, num_pages=96, max_pages_per_slot=24,
                           max_slots=2, fair_scheduling=True,
                           tenant_max_queued_tokens=40)
                   for _ in range(2)]
        router = FleetRouter(engines)
        rids = [router.submit(p, n, rid=f"r-{i}", tenant=0, priority=1)
                for i, (p, n) in enumerate(prompts)]
        # run until both replicas hold work, then kill one
        guard = 0
        while not all(e.scheduler.has_work() for e in engines):
            router.step()
            guard += 1
            assert guard < 100
        victim = 0
        router.kill_replica(victim)
        out = router.run_to_completion(max_steps=800)
        survivor = engines[1 - victim]
        finished = [rid for rid in rids
                    if router.request(rid).finish_reason in ("stop",
                                                             "length")]
        assert len(finished) >= len(rids) - 2    # flood largely served
        for i, rid in enumerate(rids):
            if rid in finished:
                assert out[rid] == ref[ref_rids[i]]   # bitwise replay
        # the survivor's quota actually gated the replay wave
        assert survivor.metrics.counters["rejected_quota"] > 0
        assert all(v <= 1
                   for v in survivor.step_program_counts().values())
        survivor.audit_pool()

    def test_shed_events_carry_retry_after(self, fault_free):
        """Router shed events and FleetOverloadedError both carry the
        drain-rate hint clients back off on (RESILIENCE.md)."""
        from tests.test_serving_fleet import FakeEngine
        router = FleetRouter([FakeEngine(max_slots=1, max_queue_depth=1)],
                             max_queue_depth=2, shed_patience=1)
        router.submit([1], 4, tenant=0)
        router.submit([2], 4, tenant=0)
        with pytest.raises(Exception) as ei:
            router.submit([3], 4, tenant=1, priority=2)
        assert hasattr(ei.value, "retry_after_s")
        assert ei.value.retryable is True


# ---------------------------------------------------------------------------
# acceptance: seeded hot-tenant overload A/B (FCFS vs fair+brownout)
# ---------------------------------------------------------------------------

class TestOverloadAcceptance:
    def _arm(self, model, wl, fair, slo_ttft):
        clock = _StepClock()
        kw = dict(clock=clock, num_pages=96, max_pages_per_slot=24)
        if fair:
            kw.update(fair_scheduling=True,
                      brownout=BrownoutConfig(high_queue=5, low_queue=2,
                                              dwell_steps=2))
        eng = _engine(model, **kw)
        eng.metrics.set_slo(ttft_p99_s=slo_ttft)
        _drive(wl, eng, clock)
        return eng

    @pytest.mark.slow
    def test_fair_brownout_bounds_cold_p99_and_improves_goodput(
            self, model, fault_free):
        """THE acceptance criterion: on the seeded hot-tenant trace the
        fairness+brownout arm bounds every cold tenant's p99 TTFT, beats
        FCFS on aggregate goodput_at_slo, keeps finished streams bitwise
        identical across arms (scheduling is invisible in the tokens),
        and never moves a compiled program."""
        wl = overload_workload(seed=11, n_requests=24, zipf_alpha=1.6,
                               max_new=(4, 8))
        tenants = {r.tenant for r in wl.requests}
        assert 0 in tenants and len(tenants) >= 3   # hot + cold classes
        slo = 14.0                                  # steps, virtual clock
        fcfs = self._arm(model, wl, fair=False, slo_ttft=slo)
        fairb = self._arm(model, wl, fair=True, slo_ttft=slo)
        pt_fcfs = fcfs.metrics.per_tenant()
        pt_fair = fairb.metrics.per_tenant()
        for t in sorted(tenants - {0}):
            # no cold-tenant starvation: p99 TTFT bounded by the SLO
            # and no worse than the FCFS arm
            assert pt_fair[t]["ttft_p99_s"] <= slo, f"tenant {t}"
            assert (pt_fair[t]["ttft_p99_s"]
                    <= pt_fcfs[t]["ttft_p99_s"]), f"tenant {t}"
        assert any(pt_fair[t]["ttft_p99_s"] < pt_fcfs[t]["ttft_p99_s"]
                   for t in tenants - {0})
        g_fcfs = fcfs.metrics.summary()["goodput_at_slo"]
        g_fair = fairb.metrics.summary()["goodput_at_slo"]
        assert g_fair > g_fcfs
        # bitwise across arms: a request finished normally in both
        # decoded the same stream regardless of interleaving
        both = [r.rid for r in wl.requests
                if (fcfs.request(r.rid).finish_reason in ("stop", "length")
                    if r.rid in fcfs._requests else False)
                and (fairb.request(r.rid).finish_reason in ("stop",
                                                            "length")
                     if r.rid in fairb._requests else False)]
        assert both
        for rid in both:
            assert (list(fairb.request(rid).tokens)
                    == list(fcfs.request(rid).tokens))
        # O(1) programs across every brownout transition
        assert fairb.step_program_counts() == {"decode": 1, "mixed": 1}
        assert fairb.metrics.summary()["brownout_transitions"] >= 2
        assert fairb.brownout_level == 0
        fcfs.audit_pool()
        fairb.audit_pool()
