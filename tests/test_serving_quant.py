"""paddle_tpu.quantization.serving — int8 KV cache + int8 weight
streaming for the paged serving engine (SERVING.md "Quantized KV &
weights").

The contracts under test:

1. FORMAT — QuantizedKV roundtrip error is bounded by scale/2 per
   element, exact zeros stay exact (masked-garbage-is-zero survives
   quantization), and the codes/scales pair is a jax pytree that rides
   jit carries.
2. ONE PROGRAM — the int8 engine keeps the fp engine's design contract:
   decode stays ONE compiled program under churn, and its greedy tokens
   are bitwise identical to ``generate(kv_dtype="int8")`` (both arms
   quantize at cache-write and dequantize in the SAME shared GQA core).
3. COMPOSITION — prefix caching (hash roots namespaced per storage
   format, COW copies carry scales), preempt-and-recompute, and the NaN
   quarantine (poison-by-scale: int8 codes cannot hold a NaN, so the
   fp32 scale row carries the sentinel; the scrub must zero codes AND
   scales) all hold with the quantized pool.
4. WEIGHT STREAMING — quantize_for_serving swaps decode matmuls to
   int8 + per-channel scales with the dequant fused into the matmul
   epilogue, cutting serving_state_bytes roughly in half.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.distributed import fault
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.quantization import (Int8ServingLinear, QuantizedKV,
                                     kv_dequantize, kv_quantize,
                                     quantize_for_serving,
                                     serving_state_bytes)
from paddle_tpu.serving import KVCachePool, ServingEngine, ServingMetrics

RNG = np.random.default_rng(11)


@pytest.fixture(scope="module")
def model():
    pt.seed(123)
    m = LlamaForCausalLM(llama_tiny(dtype="float32",
                                    mp_axis=None, fsdp_axis=None))
    m.eval()
    return m


@pytest.fixture
def fault_free(monkeypatch):
    fault.deactivate()
    monkeypatch.delenv("PADDLE_TRAINER_ID", raising=False)
    monkeypatch.delenv("PROCESS_ID", raising=False)
    monkeypatch.delenv("PADDLE_RESTART_EPOCH", raising=False)
    yield
    fault.deactivate()


def _reference(model, prompt, max_new, **kw):
    out = model.generate(jnp.asarray([prompt]), max_new_tokens=max_new, **kw)
    return np.asarray(out)[0, len(prompt):].tolist()


# ---------------------------------------------------------------------------
# the QuantizedKV format
# ---------------------------------------------------------------------------

class TestQuantizedKV:
    def test_roundtrip_error_bounded_by_half_scale(self):
        x = jnp.asarray(RNG.standard_normal((4, 16, 2, 32)), jnp.float32)
        c = kv_quantize(x)
        assert c.q.dtype == jnp.int8
        assert c.scale.shape == (4, 16, 2)
        back = kv_dequantize(c)
        err = np.abs(np.asarray(back) - np.asarray(x))
        bound = np.asarray(c.scale)[..., None] / 2.0 + 1e-7
        assert (err <= bound).all()

    def test_zero_rows_roundtrip_exactly(self):
        # the paged pool's unwritten positions are zeros the attention
        # mask relies on — quantization must keep them EXACT zeros
        # (scale 0 -> guarded divide -> dequant exact 0)
        x = jnp.zeros((2, 4, 1, 8), jnp.float32)
        c = kv_quantize(x)
        assert not np.asarray(c.q).any()
        assert not np.asarray(c.scale).any()
        assert not np.asarray(kv_dequantize(c)).any()

    def test_codes_clipped_and_scale_is_absmax_over_127(self):
        x = jnp.asarray([[[[-3.0, 0.5, 127.0]]]], jnp.float32)
        c = kv_quantize(x)
        np.testing.assert_allclose(np.asarray(c.scale), [[[1.0]]])
        assert np.abs(np.asarray(c.q)).max() <= 127

    def test_pytree_rides_jit(self):
        x = jnp.asarray(RNG.standard_normal((2, 4, 1, 8)), jnp.float32)
        c = kv_quantize(x)
        leaves = jax.tree_util.tree_leaves(c)
        assert len(leaves) == 2

        @jax.jit
        def f(c):
            return kv_dequantize(c) * 2.0

        np.testing.assert_allclose(np.asarray(f(c)),
                                   2.0 * np.asarray(kv_dequantize(c)))

    def test_shape_dtype_nbytes_delegate_to_codes(self):
        c = kv_quantize(jnp.ones((2, 4, 3, 8), jnp.float32))
        assert c.shape == (2, 4, 3, 8)
        assert c.ndim == 4
        assert c.dtype == jnp.int8
        assert c.nbytes == 2 * 4 * 3 * 8 + 2 * 4 * 3 * 4

    def test_write_order_invariance(self):
        # prefill-write and decode-append must quantize a row bitwise
        # identically: per-row absmax is order-exact, so quantizing a
        # block equals quantizing its rows one at a time
        x = jnp.asarray(RNG.standard_normal((1, 8, 2, 16)), jnp.float32)
        whole = kv_quantize(x)
        rows = [kv_quantize(x[:, i]) for i in range(8)]
        for i, r in enumerate(rows):
            assert np.array_equal(np.asarray(whole.q[:, i]),
                                  np.asarray(r.q))
            assert np.array_equal(np.asarray(whole.scale[:, i]),
                                  np.asarray(r.scale))


# ---------------------------------------------------------------------------
# the quantized pool
# ---------------------------------------------------------------------------

class TestQuantizedPool:
    def test_quantized_pool_layout_and_bytes(self):
        pool = KVCachePool(num_layers=2, num_pages=8, page_size=4,
                           num_kv_heads=2, head_dim=16, quantized=True)
        pk, pv = pool.pools[0]
        assert isinstance(pk, QuantizedKV) and isinstance(pv, QuantizedKV)
        assert pk.q.shape == (8, 4, 2, 16) and pk.q.dtype == jnp.int8
        assert pk.scale.shape == (8, 4, 2)
        assert pool.stats()["kv_quant"] == 1
        # per token: 2 arms * 2 layers * (kvh*d codes + kvh*4 scale)
        assert pool.kv_bytes_per_token() == 2 * 2 * (2 * 16 + 2 * 4)
        fp = KVCachePool(2, 8, 4, 2, 16, dtype=jnp.bfloat16)
        assert fp.kv_bytes_per_token() == 2 * 2 * (2 * 16 * 2)
        assert fp.stats()["kv_quant"] == 0

    def test_hash_roots_namespaced_per_format(self):
        # the SAME tokens must never alias across storage formats: an
        # fp-written page answering an int8 lookup (or vice versa) would
        # feed one engine the other's bytes
        from paddle_tpu.serving.kv_cache import _HASH_ROOT, _HASH_ROOT_INT8
        assert _HASH_ROOT != _HASH_ROOT_INT8
        fp = KVCachePool(1, 8, 4, 2, 8, cache_enabled=True)
        q = KVCachePool(1, 8, 4, 2, 8, cache_enabled=True, quantized=True)
        toks = np.arange(8, dtype=np.int64)
        pages = fp.alloc(2)
        fp.register_prefix(toks, pages)
        assert fp.match_prefix(toks).cached_tokens == 8
        assert not q.match_prefix(toks).hit  # different root: no hit
        qpages = q.alloc(2)
        q.register_prefix(toks, qpages)
        assert q.match_prefix(toks).cached_tokens == 8

    def test_scrub_zeroes_codes_and_scales(self):
        pool = KVCachePool(1, 8, 4, 2, 8, quantized=True)
        pages = pool.alloc(1)
        page = pages[0]
        pk, pv = pool.pools[0]
        pool.pools[0] = (
            QuantizedKV(pk.q.at[page].set(7),
                        pk.scale.at[page].set(jnp.nan)),
            pv)
        pool.scrub(pages)
        pool.free(pages)
        pk, _ = pool.pools[0]
        assert not np.asarray(pk.q[page]).any()
        assert np.isfinite(np.asarray(pk.scale[page])).all()
        assert not np.asarray(pk.scale[page]).any()

    def test_cow_copies_codes_and_scales(self):
        pool = KVCachePool(1, 8, 4, 2, 8, quantized=True)
        src, dst = pool.alloc(2)
        pk, pv = pool.pools[0]
        pool.pools[0] = (
            QuantizedKV(pk.q.at[src].set(5),
                        pk.scale.at[src].set(0.25)),
            pv)
        pool.cow_into(src, dst)
        pk, _ = pool.pools[0]
        assert (np.asarray(pk.q[dst]) == 5).all()
        np.testing.assert_allclose(np.asarray(pk.scale[dst]), 0.25)


# ---------------------------------------------------------------------------
# the int8 engine: parity, one-program, composition with PRs 3-6
# ---------------------------------------------------------------------------

class TestInt8Engine:
    def test_engine_matches_int8_generate_bitwise(self, model):
        """The engine's int8 tokens == generate(kv_dtype="int8") — both
        arms quantize at cache-write and dequantize in the one shared
        GQA core, so their streams agree bitwise, not just closely. A
        second epoch of join/leave churn must not mint a second decode
        program (the tentpole's one-program contract; 3-epoch version in
        test_no_retrace_across_epochs_int8)."""
        prompts = [list(RNG.integers(0, 512, n)) for n in (5, 9)]
        refs = [_reference(model, p, 6, kv_dtype="int8") for p in prompts]
        eng = ServingEngine(model, num_pages=64, page_size=4, max_slots=4,
                            kv_quant=True)
        rids = [eng.add_request(p, 6) for p in prompts]
        res = eng.run_to_completion(max_steps=200)
        for rid, ref in zip(rids, refs):
            assert res[rid] == ref
        assert eng.decode_program_count() == 1
        assert eng.stats()["kv_quant"] is True
        r2 = eng.add_request(prompts[0], 6)
        assert eng.run_to_completion(max_steps=100)[r2] == refs[0]
        assert eng.decode_program_count() == 1

    def test_kv_dtype_int8_is_an_alias_for_kv_quant(self, model):
        # constructor-level wiring only — programs compile lazily, so
        # this stays cheap; the decode path itself runs in the bitwise
        # parity test above
        eng = ServingEngine(model, num_pages=32, page_size=4, max_slots=2,
                            kv_dtype="int8")
        assert eng.kv_quant and eng.pool.quantized
        assert eng.metrics.kv_quant_enabled == 1

    @pytest.mark.slow
    def test_no_retrace_across_epochs_int8(self, model):
        eng = ServingEngine(model, num_pages=64, page_size=4, max_slots=4,
                            kv_quant=True)
        for epoch in range(3):
            for n in [3 + epoch, 5, 8][: 2 + epoch % 2]:
                eng.add_request(list(RNG.integers(0, 512, n)), 4 + epoch)
            eng.run_to_completion(max_steps=200)
            assert eng.decode_program_count() == 1, f"retraced epoch {epoch}"

    @pytest.mark.slow
    def test_greedy_agreement_vs_fp_cache(self, model):
        """Bounded-error acceptance: >=99% of greedy tokens agree with
        the fp cache across the trace (on the tiny model the streams
        happen to agree exactly; the harness in tools/profile_serving.py
        --kv-int8 scores the decisive-margin rate on bigger traces)."""
        prompts = [list(RNG.integers(0, 512, n)) for n in (6, 11, 4, 9)]
        refs = [_reference(model, p, 10) for p in prompts]
        eng = ServingEngine(model, num_pages=64, page_size=4, max_slots=4,
                            kv_quant=True)
        rids = [eng.add_request(p, 10) for p in prompts]
        res = eng.run_to_completion(max_steps=200)
        agree = sum(int(a == b) for rid, ref in zip(rids, refs)
                    for a, b in zip(res[rid], ref))
        total = sum(len(r) for r in refs)
        assert agree / total >= 0.99

    @pytest.mark.slow
    def test_prefix_hit_parity_int8(self, model):
        """Shared-prefix requests on the int8 pool: followers map cached
        int8 pages (codes + scales move together) and stay bitwise equal
        to the cold int8 reference. (The storage-format namespacing that
        makes this safe is covered fast by
        TestQuantizedPool::test_hash_roots_namespaced_per_format.)"""
        shared = list(RNG.integers(0, 512, 12))
        prompts = [shared + list(RNG.integers(0, 512, n)) for n in (4, 6)]
        refs = [_reference(model, p, 6, kv_dtype="int8") for p in prompts]
        eng = ServingEngine(model, num_pages=64, page_size=4, max_slots=4,
                            max_pages_per_slot=16, kv_quant=True)
        r0 = eng.add_request(prompts[0], 6)
        eng.step()
        r1 = eng.add_request(prompts[1], 6)
        res = eng.run_to_completion(max_steps=100)
        assert res[r0] == refs[0]
        assert res[r1] == refs[1]
        assert eng.metrics.summary()["prefix_hits"] >= 1

    @pytest.mark.slow
    def test_partial_page_cow_int8(self, model):
        """COW through a frozen partial int8 page: the copy carries the
        scale rows, the diverging extensions stay bitwise correct, and
        the cached page itself replays untouched. (The scale-copy
        mechanism itself is covered fast by
        TestQuantizedPool::test_cow_copies_codes_and_scales.)"""
        shared = list(RNG.integers(0, 512, 6))
        eng = ServingEngine(model, num_pages=64, page_size=4, max_slots=4,
                            max_pages_per_slot=16, kv_quant=True)
        r0 = eng.add_request(shared, 2)
        out0 = eng.run_to_completion(max_steps=50)[r0]
        assert out0 == _reference(model, shared, 2, kv_dtype="int8")
        hist = shared + out0
        prompts = [hist + list(RNG.integers(0, 512, n)) for n in (3, 2)]
        refs = [_reference(model, p, 6, kv_dtype="int8") for p in prompts]
        rids = [eng.add_request(p, 6) for p in prompts]
        res = eng.run_to_completion(max_steps=100)
        for rid, ref in zip(rids, refs):
            assert res[rid] == ref
        assert eng.metrics.summary()["prefix_cow_copies"] >= 1
        r3 = eng.add_request(shared, 2)
        assert eng.run_to_completion(max_steps=50)[r3] == out0

    @pytest.mark.slow
    def test_parity_through_preemption_int8(self, model):
        # int8 preempt-and-recompute parity also runs fast via
        # TestInt8Chaos::test_alloc_storm_preempts_int8_deterministic
        prompts = [list(RNG.integers(0, 512, n)) for n in (6, 7)]
        refs = [_reference(model, p, 8, kv_dtype="int8") for p in prompts]
        eng = ServingEngine(model, num_pages=7, page_size=4, max_slots=2,
                            max_pages_per_slot=6, kv_quant=True)
        rids = [eng.add_request(p, 8) for p in prompts]
        res = eng.run_to_completion(max_steps=500)
        assert eng.scheduler.num_preemptions > 0, \
            "config failed to exercise preemption"
        for rid, ref in zip(rids, refs):
            assert res[rid] == ref
        assert eng.decode_program_count() == 1

    def test_metrics_and_prometheus_gauges(self):
        # gauge logic lives entirely in ServingMetrics — no engine
        # needed (the engine-side feed of on_kv_quant_scale is covered
        # by test_llm_predictor_quant_flags / the trace-instant test)
        mx = ServingMetrics()
        mx.set_kv_quant(True)
        mx.on_kv_quant_scale(0.25)
        mx.on_kv_quant_scale(0.125)   # gauge is a running max
        m = mx.summary()
        assert m["kv_quant_enabled"] == 1
        assert m["kv_quant_scale_max"] == 0.25
        assert m["kv_quant_err_bound"] == 0.125
        from paddle_tpu.observability import render_prometheus
        text = render_prometheus(m)
        assert "paddle_serving_kv_quant_enabled 1" in text
        assert "paddle_serving_kv_quant_err_bound" in text
        # fp metrics keep the schema, gauges at zero
        m2 = ServingMetrics().summary()
        assert m2["kv_quant_enabled"] == 0
        assert m2["kv_quant_err_bound"] == 0.0

    @pytest.mark.slow
    def test_kv_quantize_trace_instant(self, model):
        from paddle_tpu.observability import Tracer
        tracer = Tracer()
        eng = ServingEngine(model, num_pages=32, page_size=4, max_slots=2,
                            kv_quant=True, tracer=tracer)
        eng.add_request(list(RNG.integers(0, 512, 5)), 3)
        eng.run_to_completion(max_steps=50)
        names = {ev.get("name") for ev in tracer.events}
        assert "kv_quantize" in names


@pytest.mark.faults
class TestInt8Chaos:
    @pytest.mark.slow
    def test_poison_by_scale_quarantines_and_scrubs(self, model,
                                                    fault_free):
        """int8 codes cannot hold a NaN, so the poison lands in the fp32
        scale row and propagates through dequant to the nonfinite logit
        sentinel: the victim is quarantined, survivors' int8 streams
        stay bitwise intact, and the scrub zeroes codes AND scales.
        (The scrub mechanics run fast in
        TestQuantizedPool::test_scrub_zeroes_codes_and_scales.)"""
        prompts = [list(RNG.integers(0, 512, n)) for n in (5, 7, 4)]
        refs = [_reference(model, p, 8, kv_dtype="int8") for p in prompts]
        fault.activate(fault.FaultPlan([
            fault.FaultSpec(site="serving.decode", action="poison",
                            step=3, match=r"^victim$"),
        ]))
        eng = ServingEngine(model, num_pages=64, page_size=4, max_slots=4,
                            kv_quant=True)
        res_ids = [eng.add_request(prompts[0], 8, rid="ok-0"),
                   eng.add_request(prompts[1], 8, rid="victim"),
                   eng.add_request(prompts[2], 8, rid="ok-1")]
        del res_ids
        res = eng.run_to_completion(max_steps=200)
        victim = eng.request("victim")
        assert victim.finish_reason == "nonfinite"
        assert len(victim.tokens) < 8
        assert victim.tokens == refs[1][: len(victim.tokens)]
        assert res["ok-0"] == refs[0] and res["ok-1"] == refs[2]
        assert eng.metrics.summary()["quarantined"] == 1
        assert eng.decode_program_count() == 1
        # nothing non-finite survives: every scale row is finite again
        # and the quarantined pages' codes are zeroed
        for pk, pv in eng.pool.pools:
            assert np.isfinite(np.asarray(pk.scale)).all()
            assert np.isfinite(np.asarray(pv.scale)).all()
        eng.audit_pool()

    @pytest.mark.slow
    def test_alloc_storm_preempts_int8_deterministic(self, model,
                                                     fault_free):
        prompts = [list(RNG.integers(0, 512, n)) for n in (6, 7)]
        refs = [_reference(model, p, 10, kv_dtype="int8") for p in prompts]
        fault.activate(fault.FaultPlan([
            fault.FaultSpec(site="serving.alloc", action="raise",
                            prob=0.4, once=False),
        ], seed=11))
        eng = ServingEngine(model, num_pages=8, page_size=4, max_slots=2,
                            max_pages_per_slot=6, kv_quant=True)
        rids = [eng.add_request(p, 10) for p in prompts]
        res = eng.run_to_completion(max_steps=500)
        assert eng.scheduler.num_preemptions > 0
        for rid, ref in zip(rids, refs):
            assert res[rid] == ref
        assert eng.decode_program_count() == 1
        eng.audit_pool()


# ---------------------------------------------------------------------------
# contiguous generate() int8 arm + the Pallas kernel int8 mode
# ---------------------------------------------------------------------------

class TestContiguousInt8:
    @pytest.mark.slow
    def test_generate_int8_scan_equals_eager_loop(self, model):
        prompt = list(RNG.integers(0, 512, 7))
        scan = _reference(model, prompt, 6, kv_dtype="int8")
        eager = _reference(model, prompt, 6, kv_dtype="int8",
                           jit_loop=False)
        assert scan == eager

    def test_init_kv_caches_int8_layout(self, model):
        caches = model.init_kv_caches(2, 16, dtype="int8")
        ck, cv = caches[0]
        assert isinstance(ck, QuantizedKV)
        assert ck.q.dtype == jnp.int8
        assert ck.scale.dtype == jnp.float32
        assert ck.q.shape[:2] == (2, 16)
        assert ck.scale.shape == ck.q.shape[:3]


class TestPagedKernelInt8:
    def test_kernel_int8_matches_xla_gather_path(self):
        """The Pallas block-table kernel's quant mode (scales ride the
        same index map as their pages, dequant inside the page loop)
        against the XLA gather + shared-core reference on the SAME
        QuantizedKV pool — identical inputs, so only kernel math can
        differ (fp32 accumulation both sides)."""
        from paddle_tpu.nn.functional.attention import _grouped_decode_attn
        from paddle_tpu.ops.pallas.paged_attention import (
            kernel_applicable, paged_attention_tpu)
        b, h, kvh, d, ps, M, npages = 3, 4, 2, 128, 8, 3, 8
        assert kernel_applicable((b, 1, h, d), (npages, ps, kvh, d))
        q = jnp.asarray(RNG.standard_normal((b, 1, h, d)), jnp.float32)
        pk = kv_quantize(jnp.asarray(
            RNG.standard_normal((npages, ps, kvh, d)), jnp.float32))
        pv = kv_quantize(jnp.asarray(
            RNG.standard_normal((npages, ps, kvh, d)), jnp.float32))
        tables = jnp.asarray(RNG.integers(1, npages, (b, M)), jnp.int32)
        lens = jnp.asarray([5, ps * M - 1, ps + 3], jnp.int32)
        got = paged_attention_tpu(q, pk.q, pv.q, tables, lens,
                                  k_scale=pk.scale, v_scale=pv.scale)
        kg = QuantizedKV(pk.q[tables].reshape(b, M * ps, kvh, d),
                         pk.scale[tables].reshape(b, M * ps, kvh))
        vg = QuantizedKV(pv.q[tables].reshape(b, M * ps, kvh, d),
                         pv.scale[tables].reshape(b, M * ps, kvh))
        want = _grouped_decode_attn(q, kg, vg, lens, 1.0 / np.sqrt(d))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-6, atol=2e-6)

    def test_paged_attention_decode_routes_quantized(self):
        """The dispatcher accepts a QuantizedKV pool and agrees with
        manual dequantize-then-attend."""
        from paddle_tpu.nn.functional.attention import (
            _grouped_decode_attn, paged_attention_decode)
        b, h, kvh, d, ps, M, npages = 2, 4, 2, 16, 4, 3, 8
        q = jnp.asarray(RNG.standard_normal((b, 1, h, d)), jnp.float32)
        pk = kv_quantize(jnp.asarray(
            RNG.standard_normal((npages, ps, kvh, d)), jnp.float32))
        pv = kv_quantize(jnp.asarray(
            RNG.standard_normal((npages, ps, kvh, d)), jnp.float32))
        tables = jnp.asarray(RNG.integers(1, npages, (b, M)), jnp.int32)
        lens = jnp.asarray([3, ps * M - 1], jnp.int32)
        got = paged_attention_decode(q, pk, pv, tables, lens)
        kg = kv_dequantize(QuantizedKV(
            pk.q[tables].reshape(b, M * ps, kvh, d),
            pk.scale[tables].reshape(b, M * ps, kvh)))
        vg = kv_dequantize(QuantizedKV(
            pv.q[tables].reshape(b, M * ps, kvh, d),
            pv.scale[tables].reshape(b, M * ps, kvh)))
        want = _grouped_decode_attn(q, kg, vg, lens, 1.0 / np.sqrt(d))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# int8 weight streaming
# ---------------------------------------------------------------------------

class TestWeightStreaming:
    def test_int8_linear_matches_dequant_reference(self):
        from paddle_tpu import nn
        from paddle_tpu.quantization import _dequantize_weight
        pt.seed(5)
        lin = nn.Linear(32, 48)
        lin.eval()
        qlin = Int8ServingLinear.from_linear(lin)
        x = jnp.asarray(RNG.standard_normal((4, 32)), jnp.float32)
        got = qlin(x)
        wref = _dequantize_weight(qlin.weight_q, qlin.weight_scale,
                                  dtype=jnp.float32)
        want = x @ wref + lin.bias
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)
        # and close to the fp layer (absmax int8, per-channel)
        np.testing.assert_allclose(np.asarray(got), np.asarray(lin(x)),
                                   rtol=0.1, atol=0.1)

    def test_quantize_for_serving_swaps_and_shrinks(self, model):
        fp_bytes = serving_state_bytes(model)
        qm = quantize_for_serving(model)
        q_bytes = serving_state_bytes(qm)
        assert fp_bytes / q_bytes > 1.8  # embeddings stay fp; matmuls ~4x
        n_q = sum(1 for _, s in qm.named_sublayers()
                  if isinstance(s, Int8ServingLinear))
        assert n_q == 4 * len(qm.model.layers) + 3 * len(qm.model.layers)
        # the source model is untouched (deepcopy semantics)
        assert not any(isinstance(s, Int8ServingLinear)
                       for _, s in model.named_sublayers())

    @pytest.mark.slow
    def test_quantized_model_generate_close_to_fp(self, model):
        """Bounded-error check between two DIFFERENT models (fp vs int8
        weights): greedy streams are autoregressive, so one near-tie
        argmax flip cascades — score the divergence-free PREFIX, not
        per-token agreement after the fork."""
        prompt = list(RNG.integers(0, 512, 8))
        ref = _reference(model, prompt, 8)
        qm = quantize_for_serving(model)
        got = _reference(qm, prompt, 8)
        div = next((i for i, (a, b) in enumerate(zip(ref, got))
                    if a != b), len(ref))
        assert div >= len(ref) // 2, (ref, got)

    @pytest.mark.slow
    def test_full_int8_engine_weights_and_kv(self, model):
        """Both halves at once: int8 weight streaming + int8 KV through
        the serving engine — the deployment configuration."""
        prompts = [list(RNG.integers(0, 512, n)) for n in (5, 8)]
        qm = quantize_for_serving(model)
        refs = [_reference(qm, p, 6, kv_dtype="int8") for p in prompts]
        eng = ServingEngine(qm, num_pages=64, page_size=4, max_slots=4,
                            kv_quant=True)
        rids = [eng.add_request(p, 6) for p in prompts]
        res = eng.run_to_completion(max_steps=100)
        for rid, ref in zip(rids, refs):
            assert res[rid] == ref
        assert eng.decode_program_count() == 1

    @pytest.mark.slow
    def test_llm_predictor_quant_flags(self, model):
        from paddle_tpu.inference import create_llm_predictor
        prompts = [list(RNG.integers(0, 512, n)) for n in (4, 7)]
        pred = create_llm_predictor(model, num_pages=32, page_size=4,
                                    max_slots=4, kv_quant=True,
                                    weight_quant=True)
        assert pred.engine.kv_quant
        assert any(isinstance(s, Int8ServingLinear)
                   for _, s in pred.model.named_sublayers())
        outs = pred.generate(prompts, max_new_tokens=4)
        assert all(len(o) == 4 for o in outs)
        assert pred.metrics_summary()["kv_quant_enabled"] == 1
