"""Distribution API contracts vs scipy (parity:
test/distribution/test_distribution_*.py — log_prob/moments/KL against
scipy.stats) and fft/signal contracts vs numpy/scipy."""

import numpy as np
import pytest
import scipy.stats as st

import paddle_tpu as pt
from paddle_tpu import distribution as D

RNG = np.random.default_rng(0)


def _lp(dist, scipy_logpdf, xs, atol=1e-4):
    got = np.asarray(dist.log_prob(xs))
    np.testing.assert_allclose(got, scipy_logpdf(xs), rtol=1e-4, atol=atol)


def test_normal_contract():
    d = D.Normal(1.5, 2.0)
    xs = RNG.standard_normal(64).astype(np.float32) * 2
    _lp(d, lambda x: st.norm.logpdf(x, 1.5, 2.0), xs)
    np.testing.assert_allclose(float(d.entropy()), st.norm.entropy(1.5, 2.0),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(d.cdf(xs)),
                               st.norm.cdf(xs, 1.5, 2.0), atol=1e-5)
    s = d.sample((4000,), key=pt.core.rng.next_key())
    assert abs(float(np.mean(np.asarray(s))) - 1.5) < 0.2
    assert abs(float(np.std(np.asarray(s))) - 2.0) < 0.2


def test_uniform_beta_gamma_contract():
    xs = RNG.uniform(0.05, 0.95, 32).astype(np.float32)
    _lp(D.Uniform(0.0, 1.0), lambda x: st.uniform.logpdf(x), xs)
    _lp(D.Beta(2.0, 3.0), lambda x: st.beta.logpdf(x, 2, 3), xs)
    g = D.Gamma(2.0, 3.0)  # rate parametrization
    xg = RNG.gamma(2.0, 1 / 3.0, 32).astype(np.float32) + 0.05
    _lp(g, lambda x: st.gamma.logpdf(x, 2.0, scale=1 / 3.0), xg)
    np.testing.assert_allclose(float(g.mean), 2 / 3, rtol=1e-6)
    np.testing.assert_allclose(float(g.variance), 2 / 9, rtol=1e-6)


def test_discrete_contracts():
    b = D.Bernoulli(probs=0.3)
    for v in (0.0, 1.0):
        np.testing.assert_allclose(float(b.log_prob(v)),
                                   st.bernoulli.logpmf(v, 0.3), rtol=1e-5)
    c = D.Categorical(probs=np.array([0.2, 0.3, 0.5], np.float32))
    np.testing.assert_allclose(float(c.log_prob(2)), np.log(0.5), rtol=1e-5)
    np.testing.assert_allclose(
        float(c.entropy()), st.entropy([0.2, 0.3, 0.5]), rtol=1e-5)
    p = D.Poisson(4.0)
    _lp(p, lambda x: st.poisson.logpmf(x, 4.0), np.arange(8, dtype=np.float32))
    bn = D.Binomial(10.0, 0.3)
    _lp(bn, lambda x: st.binom.logpmf(x, 10, 0.3),
        np.arange(10, dtype=np.float32))
    geom = D.Geometric(0.25)
    np.testing.assert_allclose(float(geom.log_prob(3.0)),
                               st.geom.logpmf(4, 0.25), rtol=1e-5)


def test_more_logpdfs():
    xs = RNG.standard_normal(32).astype(np.float32)
    _lp(D.Laplace(0.5, 1.5), lambda x: st.laplace.logpdf(x, 0.5, 1.5), xs)
    _lp(D.Cauchy(0.0, 2.0), lambda x: st.cauchy.logpdf(x, 0, 2), xs)
    _lp(D.Gumbel(1.0, 2.0), lambda x: st.gumbel_r.logpdf(x, 1, 2), xs)
    _lp(D.StudentT(5.0), lambda x: st.t.logpdf(x, 5), xs)
    xp = np.abs(xs) + 0.1
    _lp(D.LogNormal(0.0, 1.0), lambda x: st.lognorm.logpdf(x, 1.0), xp)
    _lp(D.Exponential(2.0), lambda x: st.expon.logpdf(x, scale=0.5), xp)


def test_dirichlet_multinomial():
    conc = np.array([1.0, 2.0, 3.0], np.float32)
    d = D.Dirichlet(conc)
    x = np.array([0.2, 0.3, 0.5], np.float32)
    np.testing.assert_allclose(float(d.log_prob(x)),
                               st.dirichlet.logpdf(x, conc), rtol=1e-4)
    m = D.Multinomial(8, np.array([0.2, 0.3, 0.5], np.float32))
    v = np.array([2.0, 2.0, 4.0], np.float32)
    np.testing.assert_allclose(float(m.log_prob(v)),
                               st.multinomial.logpmf(v, 8, [0.2, 0.3, 0.5]),
                               rtol=1e-4)
    s = m.sample(key=pt.core.rng.next_key())
    assert float(np.sum(np.asarray(s))) == 8.0


def test_kl_divergence_registry():
    p = D.Normal(0.0, 1.0)
    q = D.Normal(1.0, 2.0)
    want = (np.log(2.0) + (1 + 1) / (2 * 4) - 0.5)
    np.testing.assert_allclose(float(D.kl_divergence(p, q)), want, rtol=1e-5)
    c1 = D.Categorical(probs=np.array([0.5, 0.5], np.float32))
    c2 = D.Categorical(probs=np.array([0.9, 0.1], np.float32))
    want = 0.5 * np.log(0.5 / 0.9) + 0.5 * np.log(0.5 / 0.1)
    np.testing.assert_allclose(float(D.kl_divergence(c1, c2)), want,
                               rtol=1e-5)
    with pytest.raises(NotImplementedError):
        D.kl_divergence(p, c1)


def test_transformed_distribution():
    base = D.Normal(0.0, 1.0)
    ln = D.TransformedDistribution(base, [D.ExpTransform()])
    xs = np.abs(RNG.standard_normal(16)).astype(np.float32) + 0.1
    np.testing.assert_allclose(np.asarray(ln.log_prob(xs)),
                               st.lognorm.logpdf(xs, 1.0), rtol=1e-4,
                               atol=1e-5)
    aff = D.TransformedDistribution(base, [D.AffineTransform(2.0, 3.0)])
    np.testing.assert_allclose(np.asarray(aff.log_prob(xs)),
                               st.norm.logpdf(xs, 2.0, 3.0), rtol=1e-4,
                               atol=1e-5)


def test_independent():
    base = D.Normal(np.zeros((4, 3), np.float32), np.ones((4, 3), np.float32))
    ind = D.Independent(base, 1)
    assert ind.batch_shape == (4,) and ind.event_shape == (3,)
    x = RNG.standard_normal((4, 3)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(ind.log_prob(x)),
                               st.norm.logpdf(x).sum(-1), rtol=1e-4)


# ---------------- fft / signal ----------------

def test_fft_contract():
    from paddle_tpu import fft
    x = RNG.standard_normal(64).astype(np.float32)
    np.testing.assert_allclose(np.asarray(fft.fft(x)), np.fft.fft(x),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(fft.rfft(x)), np.fft.rfft(x),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(fft.irfft(fft.rfft(x))), x,
                               rtol=1e-4, atol=1e-5)
    x2 = RNG.standard_normal((8, 16)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(fft.fft2(x2)), np.fft.fft2(x2),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(fft.fftfreq(10, 0.1)),
                               np.fft.fftfreq(10, 0.1), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(fft.fftshift(x)), np.fft.fftshift(x))


def test_stft_istft_roundtrip():
    from paddle_tpu import signal
    x = RNG.standard_normal((2, 512)).astype(np.float32)
    n_fft, hop = 64, 16
    w = np.hanning(n_fft).astype(np.float32)
    spec = signal.stft(x, n_fft, hop_length=hop, window=w)
    assert spec.shape == (2, n_fft // 2 + 1, (512) // hop + 1)
    rec = signal.istft(spec, n_fft, hop_length=hop, window=w, length=512)
    # interior must roundtrip (edges lose energy to the window taper)
    np.testing.assert_allclose(np.asarray(rec)[:, 64:-64], x[:, 64:-64],
                               rtol=1e-3, atol=1e-3)
    # scipy cross-check of one frame column
    import scipy.signal as ss
    f, t, want = ss.stft(x[0], nperseg=n_fft, noverlap=n_fft - hop,
                         window=w, boundary="zeros", padded=True)
    # scipy scales by win.sum(); compare shapes only plus a scaled column
    assert want.shape[0] == spec.shape[1]


def test_frame_overlap_add():
    from paddle_tpu import signal
    x = np.arange(32, dtype=np.float32)
    fr = signal.frame(x, 8, 4)
    assert fr.shape == (8, 7)
    np.testing.assert_allclose(np.asarray(fr[:, 0]), x[:8])
    np.testing.assert_allclose(np.asarray(fr[:, 1]), x[4:12])
    ones = np.ones((8, 7), np.float32)
    ov = signal.overlap_add(ones, 4)
    assert ov.shape == (32,)
    assert float(np.asarray(ov).sum()) == 56.0
