"""LBFGS / LookAhead / ModelAverage / ASP tests (VERDICT r2 item 7;
parity: optimizer/lbfgs.py:315, incubate/optimizer/lookahead.py:27,
modelaverage.py:31, incubate/asp/)."""

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.incubate import asp
from paddle_tpu.incubate.optimizer import LookAhead, ModelAverage


class _Point(nn.Layer):
    def __init__(self, init):
        super().__init__()
        self.xy = nn.Parameter(jnp.asarray(init, jnp.float32))


def test_lbfgs_rosenbrock_converges():
    m = _Point([-1.2, 1.0])
    opt = pt.optimizer.LBFGS(parameters=m, line_search_fn="strong_wolfe",
                             max_iter=30)

    def rosen(params):
        x, y = params["xy"][0], params["xy"][1]
        return (1 - x) ** 2 + 100 * (y - x * x) ** 2

    for _ in range(6):
        loss = opt.step(rosen)
    assert float(loss) < 1e-8
    np.testing.assert_allclose(np.asarray(m.xy), [1.0, 1.0], atol=1e-4)


def test_lbfgs_quadratic_fast_and_no_linesearch():
    m = _Point([5.0, -3.0])
    opt = pt.optimizer.LBFGS(parameters=m, learning_rate=0.5, max_iter=50)
    loss = opt.step(lambda p: jnp.sum(p["xy"] ** 2))
    assert float(loss) < 1e-6


def test_lbfgs_validates_line_search_name():
    import pytest
    with pytest.raises(ValueError):
        pt.optimizer.LBFGS(parameters=_Point([0.0]), line_search_fn="bogus")


def test_lookahead_sync_formula():
    lin = nn.Linear(4, 1)
    inner = pt.optimizer.SGD(learning_rate=0.1, parameters=lin)
    la = LookAhead(inner, alpha=0.5, k=2)
    params = lin.param_dict(trainable_only=True)
    st = la.init_state(params)
    g = {k: jnp.ones_like(v) for k, v in params.items()}
    p1, st = la.update(params, g, st)     # fast step, no sync
    np.testing.assert_allclose(np.asarray(p1["weight"]),
                               np.asarray(params["weight"]) - 0.1, rtol=1e-5)
    p2, st = la.update(p1, g, st)         # sync: slow = p0 + 0.5*((p0-0.2)-p0)
    np.testing.assert_allclose(np.asarray(p2["weight"]),
                               np.asarray(params["weight"]) - 0.1, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(st["slow"]["weight"]),
                               np.asarray(p2["weight"]), rtol=1e-6)


def test_lookahead_trains_under_trainstep():
    import paddle_tpu.nn.functional as F
    pt.seed(0)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    la = LookAhead(pt.optimizer.Adam(learning_rate=1e-2, parameters=model),
                   alpha=0.5, k=3)
    step = pt.jit.TrainStep(model, la, lambda o, y: F.mse_loss(o, y))
    rs = np.random.default_rng(0)
    x = rs.standard_normal((32, 8)).astype("float32")
    y = rs.standard_normal((32, 1)).astype("float32")
    losses = [float(step(x, y)) for _ in range(12)]
    assert losses[-1] < losses[0]


def test_model_average_window_and_restore():
    lin = nn.Linear(4, 1)
    ma = ModelAverage(0.15, parameters=lin, max_average_window=100)
    w0 = np.asarray(lin.weight).copy()
    ma.accumulate()
    lin.weight = jnp.asarray(w0 + 1.0)
    ma.accumulate()
    with ma.apply():
        np.testing.assert_allclose(np.asarray(lin.weight), w0 + 0.5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(lin.weight), w0 + 1.0, rtol=1e-5)
    # restart when exceeding max window
    ma2 = ModelAverage(0.15, parameters=lin, max_average_window=1)
    ma2.accumulate()
    ma2.accumulate()  # restart: sum == current params, count == 1
    assert int(ma2._eager_state["num_accumulates"]) == 1


def test_asp_2_4_masks():
    rs = np.random.default_rng(0)
    w = jnp.asarray(rs.standard_normal((16, 16)).astype("float32"))
    mask = asp.create_mask(w)
    assert asp.check_mask(w * mask)
    assert abs(asp.calculate_density(w * mask) - 0.5) < 1e-6
    # kept entries are the 2 largest |w| of each group of 4
    groups = np.abs(np.asarray(w)).reshape(16, 4, 4)
    kept = np.asarray(mask).reshape(16, 4, 4)
    for r in range(16):
        for g in range(4):
            top2 = set(np.argsort(-groups[r, g])[:2])
            assert set(np.nonzero(kept[r, g])[0]) == top2

    lin = nn.Linear(8, 8)
    masks = asp.prune_model(lin)
    assert "weight" in masks and asp.check_mask(lin.weight)
    # bias (1-D) untouched
    assert "bias" not in masks
    # post-update enforcement
    params = lin.param_dict(trainable_only=True)
    params = {k: v + 1.0 for k, v in params.items()}  # densify
    enforced = asp.apply_masks(params, masks)
    assert asp.check_mask(enforced["weight"])
