"""bf16 gradient contract matrix (VERDICT r3 weak #7; parity model:
OpTest check_grad run across its dtype matrix, test/legacy_test/
op_test.py:2958 — bf16 grads checked against user-defined fp32 grads).

Every case computes jax.grad of sum(square(op(..))) twice — once with fp32
inputs (the reference analytic gradient) and once with the SAME values cast
to bf16 — and compares.

Tolerance model (documented): bf16 carries an 8-bit mantissa (~2 decimal
digits). A single rounding on the input or the cotangent gives ~0.4%
relative error; accumulation (matmul/conv/reduction backward) and
cancellation widen it. The matrix therefore allows per-element
rtol=8% with an absolute floor of 10% of the gradient's max magnitude
(atol = 0.10 * max|g32| + 1e-3). Ops whose fp32 gradients are themselves
ill-conditioned at random inputs (poles, branch points) are excluded with a
reason rather than loosened further.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.nn.functional as F
from paddle_tpu.core.registry import all_ops

RNG = np.random.default_rng(7)


def _grad_pair(fn, xs, argnums=None):
    """(fp32 grads, bf16 grads) of sum(square(fn(*xs))) w.r.t. the float
    inputs."""
    if argnums is None:
        argnums = tuple(i for i, x in enumerate(xs)
                        if np.asarray(x).dtype == np.float32)
    assert argnums, "no float inputs to differentiate"

    def scalar(*args):
        out = fn(*args)
        tot = jnp.float32(0)
        for leaf in jax.tree.leaves(out):
            if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact):
                tot = tot + jnp.sum(jnp.square(jnp.asarray(leaf)
                                               .astype(jnp.float32)))
        return tot

    g32 = jax.grad(scalar, argnums)(*[jnp.asarray(x) for x in xs])
    xs16 = [jnp.asarray(x, jnp.bfloat16)
            if np.asarray(x).dtype == np.float32 else jnp.asarray(x)
            for x in xs]
    g16 = jax.grad(scalar, argnums)(*xs16)
    return g32, g16


def _assert_bf16_close(g32, g16, rtol=0.08, afrac=0.10, name=""):
    for a, b in zip(jax.tree.leaves(g32), jax.tree.leaves(g16)):
        a = np.asarray(a, np.float32)
        b = np.asarray(b, np.float32)
        atol = afrac * max(np.abs(a).max(), 0.0) + 1e-3
        np.testing.assert_allclose(b, a, rtol=rtol, atol=atol,
                                   err_msg=f"bf16 grad mismatch: {name}")


def _check(fn, xs, argnums=None, name=""):
    g32, g16 = _grad_pair(fn, xs, argnums)
    _assert_bf16_close(g32, g16, name=name)


# ---------------- registry-driven elementwise/contract matrix -----------

# excluded with reasons: poles/branch points where the fp32 gradient itself
# explodes at random inputs (tan near pi/2; reciprocal-family 1/x^2 near 0;
# expm1/exp square loss overflows bf16 range; digamma/lgamma poles at
# non-positive ints; erfinv pole at +-1)
_EXCLUDE = {
    "tan": "pole at pi/2",
    "reciprocal": "1/x^2 amplifies bf16 input rounding unboundedly near 0",
    "rsqrt": "x^-1.5 near 0",
    "digamma": "poles at non-positive integers",
    "lgamma": "poles at non-positive integers",
    "polygamma": "poles",
    "erfinv": "derivative pole at |x| -> 1",
    "atanh": "pole at |x| -> 1 under the +0.5 input shift",
    "acosh": "branch point at 1",
    "bitwise_left_shift": "integer op (grad_ref marks the fp32-cast check)",
    "bitwise_right_shift": "integer op",
    "float_power": "x^y with random base/exponent: log(x) grad term is "
                   "ill-conditioned near 0 even in fp32",
}

_DOMAIN_SHIFT = {
    "sqrt": lambda x: np.abs(x) + 0.5,
    "log": lambda x: np.abs(x) + 0.5,
    "log2": lambda x: np.abs(x) + 0.5,
    "log10": lambda x: np.abs(x) + 0.5,
    "log1p": lambda x: np.abs(x) + 0.5,
    "asin": lambda x: np.clip(x, -0.8, 0.8),
    "acos": lambda x: np.clip(x, -0.8, 0.8),
}


def _registry_cases():
    cases = []
    for name, info in sorted(all_ops().items()):
        if not info.grad_ref or name in _EXCLUDE:
            continue
        if info.category != "elementwise":
            continue
        cases.append((name, info))
    return cases


REG_CASES = _registry_cases()


def _registry_inputs(name, info):
    if info.make_inputs is not None:
        import zlib
        rng = np.random.default_rng(zlib.crc32(name.encode()))
        xs = list(info.make_inputs(rng))
    else:
        import inspect
        sig = inspect.signature(info.fn)
        n = sum(1 for p in sig.parameters.values()
                if p.default is inspect.Parameter.empty and p.kind in (
                    p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)) or 1
        shapes = (info.test_shapes or ((4, 8),))
        if len(shapes) == 1:
            shapes = shapes * n
        xs = [RNG.standard_normal(s).astype(np.float32) + 0.5
              for s in shapes]
    fix = _DOMAIN_SHIFT.get(name)
    if fix is not None:
        xs = [fix(x) if np.asarray(x).dtype == np.float32 else x for x in xs]
    return xs


@pytest.mark.parametrize("name,info", REG_CASES,
                         ids=[c[0] for c in REG_CASES])
def test_grad_bfloat16_elementwise(name, info):
    xs = _registry_inputs(name, info)
    if not any(np.asarray(x).dtype == np.float32 for x in xs):
        pytest.skip("integer op")
    _check(info.fn_call or info.fn, xs, name=name)


# ---------------- hot-family matrix (matmul/conv/norm/softmax/attention/
# loss — the training-path ops VERDICT r3 names) ----------------

def _f32(*shape):
    return RNG.standard_normal(shape).astype(np.float32)


HOT_CASES = {
    "matmul": lambda: (pt.matmul, [_f32(4, 8), _f32(8, 5)]),
    "matmul_batched": lambda: (pt.matmul, [_f32(2, 4, 8), _f32(2, 8, 5)]),
    "linear": lambda: (F.linear, [_f32(6, 8), _f32(8, 5), _f32(5)]),
    "conv2d": lambda: (
        lambda x, w, b: F.conv2d(x, w, b, padding=1),
        [_f32(2, 3, 8, 8), _f32(4, 3, 3, 3) * 0.2, _f32(4)]),
    "conv2d_stride2": lambda: (
        lambda x, w: F.conv2d(x, w, stride=2),
        [_f32(2, 4, 9, 9), _f32(8, 4, 3, 3) * 0.2]),
    "conv2d_grouped": lambda: (
        lambda x, w: F.conv2d(x, w, groups=2, padding=1),
        [_f32(2, 4, 8, 8), _f32(6, 2, 3, 3) * 0.2]),
    "conv2d_transpose": lambda: (
        lambda x, w: F.conv2d_transpose(x, w, stride=2),
        [_f32(2, 4, 5, 5), _f32(4, 3, 3, 3) * 0.2]),
    "conv1d": lambda: (
        lambda x, w: F.conv1d(x, w, padding=1),
        [_f32(2, 3, 16), _f32(5, 3, 3) * 0.2]),
    "conv3d": lambda: (
        lambda x, w: F.conv3d(x, w),
        [_f32(1, 2, 5, 5, 5), _f32(3, 2, 2, 2, 2) * 0.2]),
    "layer_norm": lambda: (
        lambda x, w, b: F.layer_norm(x, 16, w, b),
        [_f32(6, 16), _f32(16), _f32(16)]),
    "rms_norm": lambda: (
        lambda x, w: F.rms_norm(x, w), [_f32(6, 128), _f32(128)]),
    # batch norm: check d(w)/d(b) only — d(x) of a pure normalizer under a
    # sum-square loss is near-zero cancellation residue (the loss is almost
    # invariant to x), meaningless to compare at bf16 resolution
    "batch_norm_train": lambda: (
        lambda x, w, b: F.batch_norm(x, jnp.zeros(4), jnp.ones(4), w, b,
                                     training=True)[0],
        [_f32(8, 4, 6, 6), _f32(4), _f32(4)], (1, 2)),
    "group_norm": lambda: (
        lambda x, w, b: F.group_norm(x, 2, weight=w, bias=b, epsilon=1e-5),
        [_f32(4, 4, 5, 5), _f32(4), _f32(4)]),
    "softmax": lambda: (lambda x: F.softmax(x, axis=-1), [_f32(6, 12)]),
    "log_softmax": lambda: (lambda x: F.log_softmax(x, axis=-1),
                            [_f32(6, 12)]),
    "cross_entropy": lambda: (
        lambda x, y: F.cross_entropy(x, y),
        [_f32(16, 12), RNG.integers(0, 12, 16).astype(np.int32)]),
    "cross_entropy_ignore": lambda: (
        lambda x, y: F.cross_entropy(x, y, ignore_index=0),
        [_f32(16, 12), RNG.integers(0, 12, 16).astype(np.int32)]),
    "softmax_with_cross_entropy": lambda: (
        lambda x, y: F.softmax_with_cross_entropy(x, y[:, None]),
        [_f32(16, 12), RNG.integers(0, 12, 16).astype(np.int64)]),
    "nll_loss": lambda: (
        lambda x, y: F.nll_loss(F.log_softmax(x, -1), y),
        [_f32(16, 12), RNG.integers(0, 12, 16).astype(np.int32)]),
    "mse_loss": lambda: (F.mse_loss, [_f32(8, 4), _f32(8, 4)]),
    "l1_loss": lambda: (F.l1_loss, [_f32(8, 4), _f32(8, 4) + 0.3]),
    "smooth_l1_loss": lambda: (F.smooth_l1_loss, [_f32(8, 4), _f32(8, 4)]),
    "kl_div": lambda: (
        lambda x, y: F.kl_div(F.log_softmax(x, -1), F.softmax(y, -1)),
        [_f32(8, 6), _f32(8, 6)]),
    "bce_with_logits": lambda: (
        F.binary_cross_entropy_with_logits,
        [_f32(8, 4), (RNG.random((8, 4)) > 0.5).astype(np.float32)]),
    "attention_sdpa": lambda: (
        lambda q, k, v: F.scaled_dot_product_attention(q, k, v),
        [_f32(2, 16, 4, 8) * 0.5, _f32(2, 16, 4, 8) * 0.5,
         _f32(2, 16, 4, 8) * 0.5]),
    "attention_causal": lambda: (
        lambda q, k, v: F.scaled_dot_product_attention(q, k, v,
                                                       is_causal=True),
        [_f32(2, 16, 4, 8) * 0.5, _f32(2, 16, 4, 8) * 0.5,
         _f32(2, 16, 4, 8) * 0.5]),
    "embedding": lambda: (
        lambda ids, w: F.embedding(ids, w),
        [RNG.integers(0, 20, (4, 6)).astype(np.int32), _f32(20, 8)]),
    "gelu": lambda: (F.gelu, [_f32(6, 16)]),
    "gelu_tanh": lambda: (lambda x: F.gelu(x, approximate=True),
                          [_f32(6, 16)]),
    "silu": lambda: (F.silu, [_f32(6, 16)]),
    "swiglu": lambda: (lambda a, b: F.silu(a) * b,
                       [_f32(6, 16), _f32(6, 16)]),
    "mean_reduce": lambda: (lambda x: pt.mean(x, axis=1), [_f32(5, 9)]),
    "sum_reduce": lambda: (lambda x: pt.sum(x, axis=0), [_f32(5, 9)]),
    "max_pool2d": lambda: (
        lambda x: F.max_pool2d(x, 2, 2), [_f32(2, 3, 8, 8)]),
    "avg_pool2d": lambda: (
        lambda x: F.avg_pool2d(x, 2, 2), [_f32(2, 3, 8, 8)]),
    "adaptive_avg_pool2d": lambda: (
        lambda x: F.adaptive_avg_pool2d(x, (2, 2)), [_f32(2, 3, 8, 8)]),
}


@pytest.mark.parametrize("name", sorted(HOT_CASES),
                         ids=sorted(HOT_CASES))
def test_grad_bfloat16_hot(name):
    case = HOT_CASES[name]()
    fn, xs = case[0], case[1]
    argnums = case[2] if len(case) > 2 else None
    _check(fn, xs, argnums=argnums, name=name)


def test_matrix_size():
    """The VERDICT r3 bar: >= 50 differentiable ops under bf16 grad
    contract."""
    assert len(REG_CASES) + len(HOT_CASES) >= 50, (
        len(REG_CASES), len(HOT_CASES))
