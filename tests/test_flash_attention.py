"""Pallas flash-attention contract tests (parity: the reference FA2 contract,
SURVEY §B.7) — run in interpret mode on CPU."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas.flash_attention import (
    flash_attention, flash_attention_with_lse)

RNG = np.random.default_rng(7)


def ref_attn(q, k, v, causal):
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / math.sqrt(d)
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        s = jnp.where(jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq), s, -jnp.inf)
    p = jax.nn.softmax(s, -1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)


@pytest.mark.parametrize("b,s,h,d,causal", [
    (2, 256, 2, 64, False),
    (2, 256, 2, 64, True),
    (1, 128, 4, 128, True),
    (1, 384, 1, 64, True),  # seq not a multiple of 256 -> bk fallback
])
def test_forward_matches_reference(b, s, h, d, causal):
    q = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    out = flash_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_attn(q, k, v, causal)),
                               rtol=1e-4, atol=1e-4)


def test_gradients_match_reference():
    b, s, h, d = 1, 256, 2, 64
    q = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    f1 = lambda q, k, v: jnp.sum(jnp.sin(flash_attention(q, k, v, causal=True)))
    f2 = lambda q, k, v: jnp.sum(jnp.sin(ref_attn(q, k, v, True)))
    g1 = jax.grad(f1, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f2, argnums=(0, 1, 2))(q, k, v)
    for a, b_, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-3,
                                   atol=1e-3, err_msg=f"d{name}")


def test_lse_contract():
    b, s, h, d = 1, 128, 2, 64
    q = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    out, lse = flash_attention_with_lse(q, q, q, causal=True)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, q) / math.sqrt(d)
    scores = jnp.where(jnp.tril(jnp.ones((s, s), bool)), scores, -jnp.inf)
    want = jax.scipy.special.logsumexp(scores, -1)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(want), rtol=1e-5,
                               atol=1e-5)
    assert lse.shape == (b, h, s)


def test_bf16_inputs():
    b, s, h, d = 1, 256, 2, 64
    q = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.bfloat16)
    out = flash_attention(q, q, q, causal=True)
    assert out.dtype == jnp.bfloat16
    want = ref_attn(q.astype(jnp.float32), q.astype(jnp.float32),
                    q.astype(jnp.float32), True)
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(want),
                               rtol=5e-2, atol=5e-2)


@pytest.mark.parametrize("sq,sk", [(128, 256), (64, 192), (256, 128)])
def test_causal_bottom_right_alignment(sq, sk):
    """seq_q != seq_k causal must match the FA2 bottom-right convention
    (the XLA reference path: tril with k=sk-sq)."""
    b, h, d = 1, 2, 64
    q = jnp.asarray(RNG.standard_normal((b, sq, h, d)), jnp.float32)
    k = jnp.asarray(RNG.standard_normal((b, sk, h, d)), jnp.float32)
    v = jnp.asarray(RNG.standard_normal((b, sk, h, d)), jnp.float32)
    out = flash_attention(q, k, v, causal=True)
    live = max(sq - sk, 0)  # rows < sq-sk are fully masked: ref gives NaN,
    # flash gives zeros (the safer defined behavior)
    np.testing.assert_allclose(np.asarray(out)[:, live:],
                               np.asarray(ref_attn(q, k, v, True))[:, live:],
                               rtol=1e-4, atol=1e-4)
    if live:
        assert np.all(np.asarray(out)[:, :live] == 0)
    if sq <= sk:  # grads too (sq > sk has fully-masked rows: NaN in the ref)
        g1 = jax.grad(lambda q, k, v: jnp.sum(
            jnp.sin(flash_attention(q, k, v, causal=True))), (0, 1, 2))(q, k, v)
        g2 = jax.grad(lambda q, k, v: jnp.sum(
            jnp.sin(ref_attn(q, k, v, True))), (0, 1, 2))(q, k, v)
        for a, b_, name in zip(g1, g2, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-3,
                                       atol=1e-3, err_msg=f"d{name}")


@pytest.mark.parametrize("causal", [False, True])
def test_ragged_seq_padding(causal):
    """seq not a multiple of the minimum block (8): forward masks padded keys
    in-kernel, backward pads to block multiples (was: silently wrong grads)."""
    b, s, h, d = 1, 130, 2, 64
    q = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    out = flash_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(ref_attn(q, k, v, causal)),
                               rtol=1e-4, atol=1e-4)
    g1 = jax.grad(lambda q, k, v: jnp.sum(
        jnp.sin(flash_attention(q, k, v, causal=causal))), (0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda q, k, v: jnp.sum(
        jnp.sin(ref_attn(q, k, v, causal))), (0, 1, 2))(q, k, v)
    for a, b_, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-3,
                                   atol=1e-3, err_msg=f"d{name}")


def test_jit_and_vmap_compose():
    b, s, h, d = 1, 128, 1, 64
    q = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    jit_out = jax.jit(lambda q: flash_attention(q, q, q, causal=True))(q)
    np.testing.assert_allclose(np.asarray(jit_out),
                               np.asarray(flash_attention(q, q, q, causal=True)),
                               rtol=1e-5, atol=1e-6)


def test_flash_spmd_rule_matches_xla():
    """SPMD rule parity (spmd_rules/flash_attention.cc): under an active
    mesh the flash kernel runs in a shard_map over the dp/mp axes and must
    match XLA attention, values and grads."""
    from jax.sharding import Mesh
    from paddle_tpu.core import mesh as mesh_lib
    from paddle_tpu.nn.functional.attention import (_flash_sharded,
                                                    _xla_attention)
    q = jnp.asarray(RNG.standard_normal((4, 256, 4, 32)), jnp.float32)
    k = jnp.asarray(RNG.standard_normal((4, 256, 4, 32)), jnp.float32)
    v = jnp.asarray(RNG.standard_normal((4, 256, 4, 32)), jnp.float32)
    mesh = Mesh(np.array(jax.devices()).reshape(2, 2, 2), ("dp", "pp", "mp"))
    ref = _xla_attention(q, k, v, is_causal=True)
    with mesh_lib.use_mesh(mesh):
        out = _flash_sharded(q, k, v, True)
        g = jax.grad(lambda q: jnp.sum(jnp.sin(
            _flash_sharded(q, k, v, True))))(q)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)
    g_ref = jax.grad(lambda q: jnp.sum(jnp.sin(
        _xla_attention(q, k, v, is_causal=True))))(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               rtol=1e-3, atol=1e-4)


def test_flash_spmd_rule_indivisible_falls_back():
    from jax.sharding import Mesh
    from paddle_tpu.core import mesh as mesh_lib
    from paddle_tpu.nn.functional.attention import _flash_sharded
    q = jnp.asarray(RNG.standard_normal((3, 128, 3, 32)), jnp.float32)
    mesh = Mesh(np.array(jax.devices()).reshape(2, 2, 2), ("dp", "pp", "mp"))
    with mesh_lib.use_mesh(mesh):
        assert _flash_sharded(q, q, q, True) is None  # caller routes to XLA


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attn_mask_in_kernel(causal):
    """In-kernel additive attn_mask (reference flash attn_mask attr):
    padding-style bool mask, ragged seq, values and grads vs XLA. Rows kept
    non-degenerate (a fully-masked row is NaN in the reference softmax but
    defined-zero in the kernel — documented divergence)."""
    from paddle_tpu.nn.functional.attention import _xla_attention
    b, s, h, d = 2, 192, 4, 32
    q = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    valid = RNG.uniform(size=(b, 1, 1, s)) > 0.3
    valid[..., 0] = True
    mask = np.broadcast_to(valid, (b, 1, s, s))
    out = flash_attention(q, k, v, causal=causal, attn_mask=mask)
    ref = _xla_attention(q, k, v, attn_mask=jnp.asarray(mask),
                         is_causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=2e-5)
    for argnum, name in ((0, "dq"), (1, "dk"), (2, "dv")):
        g = jax.grad(lambda *a: jnp.sum(jnp.sin(flash_attention(
            *a, causal=causal, attn_mask=mask))), argnum)(q, k, v)
        g_ref = jax.grad(lambda *a: jnp.sum(jnp.sin(_xla_attention(
            *a, attn_mask=jnp.asarray(mask), is_causal=causal))),
            argnum)(q, k, v)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                                   rtol=1e-3, atol=2e-4, err_msg=name)


def _padding_mask(b, sq, sk, lens):
    m = np.zeros((b, sq, sk), bool)
    for i, L in enumerate(lens):
        m[i, :, :L] = True
    return jnp.asarray(m)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_spmd_rule_masked_matches_xla(causal):
    """VERDICT r4 missing #2: masked flash keeps the Pallas kernel under a
    dp x mp mesh (parity: spmd_rules/flash_attention.h:25 — attn_mask is a
    first-class rule input). Per-batch padding mask, batch-sharded inside
    the shard_map; values and q-grads vs the XLA oracle."""
    from jax.sharding import Mesh
    from paddle_tpu.core import mesh as mesh_lib
    from paddle_tpu.nn.functional.attention import (_flash_sharded,
                                                    _normalize_kernel_mask,
                                                    _xla_attention)
    b, s, h, d = 4, 192, 4, 32
    q = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    mask3 = _padding_mask(b, s, s, [s, 150, 100, 64])
    m = _normalize_kernel_mask(mask3, b, h, s, s)
    assert m is not None and m.shape == (b, 1, s, s)
    ref = _xla_attention(q, k, v, attn_mask=mask3, is_causal=causal)
    mesh = Mesh(np.array(jax.devices()).reshape(2, 2, 2), ("dp", "pp", "mp"))
    with mesh_lib.use_mesh(mesh):
        out = _flash_sharded(q, k, v, causal, mask=m)
        assert out is not None
        g = jax.grad(lambda q: jnp.sum(jnp.sin(
            _flash_sharded(q, k, v, causal, mask=m))))(q)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)
    g_ref = jax.grad(lambda q: jnp.sum(jnp.sin(
        _xla_attention(q, k, v, attn_mask=mask3, is_causal=causal))))(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               rtol=1e-3, atol=1e-4)


def test_flash_spmd_rule_per_head_mask_sharded():
    """A full [b, h, sq, sk] additive mask shards its head dim over mp
    alongside q's heads."""
    from jax.sharding import Mesh
    from paddle_tpu.core import mesh as mesh_lib
    from paddle_tpu.nn.functional.attention import (_flash_sharded,
                                                    _xla_attention)
    b, s, h, d = 2, 128, 4, 32
    q = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    bias = jnp.asarray(RNG.standard_normal((b, h, s, s)) * 0.5, jnp.float32)
    ref = _xla_attention(q, k, v, attn_mask=bias)
    mesh = Mesh(np.array(jax.devices()).reshape(2, 2, 2), ("dp", "pp", "mp"))
    with mesh_lib.use_mesh(mesh):
        out = _flash_sharded(q, k, v, False, mask=bias)
    assert out is not None
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_flash_spmd_rule_custom_axis_names():
    """Axis names come from the flash_batch_axes/flash_head_axes flags, not
    hardcoded dp/mp (VERDICT r4 weak #2): a ('data','model') mesh keeps the
    kernel once the flags name its axes."""
    import paddle_tpu as pt
    from jax.sharding import Mesh
    from paddle_tpu.core import mesh as mesh_lib
    from paddle_tpu.core.flags import flag_guard
    from paddle_tpu.nn.functional.attention import (_flash_sharded,
                                                    _xla_attention)
    b, s, h, d = 4, 128, 4, 32
    q = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("data", "model"))
    ref = _xla_attention(q, q, q, is_causal=True)
    with flag_guard(flash_batch_axes="data", flash_head_axes="model"), \
            mesh_lib.use_mesh(mesh):
        out = _flash_sharded(q, q, q, True)
    assert out is not None
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_flash_spmd_rule_warns_on_unrecognized_mesh():
    """A sized mesh whose axes match neither flag loses the kernel — with a
    diagnostic (was: silent XLA fallback)."""
    import warnings
    from jax.sharding import Mesh
    from paddle_tpu.core import mesh as mesh_lib
    from paddle_tpu.nn.functional import attention as attn_mod
    q = jnp.asarray(RNG.standard_normal((4, 128, 4, 32)), jnp.float32)
    mesh = Mesh(np.array(jax.devices()).reshape(8), ("replicas",))
    attn_mod._warned_mesh_sigs.clear()
    with mesh_lib.use_mesh(mesh):
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            assert attn_mod._flash_sharded(q, q, q, True) is None
        assert any("flash_batch_axes" in str(x.message) for x in w), \
            [str(x.message) for x in w]
        # once per mesh signature
        with warnings.catch_warnings(record=True) as w2:
            warnings.simplefilter("always")
            assert attn_mod._flash_sharded(q, q, q, True) is None
        assert not w2


def test_sdpa_masked_keeps_kernel_under_mesh(monkeypatch):
    """BERT-style padded-batch attention under a mesh routes through the
    sharded flash rule (VERDICT r4: 'BERT-with-padding-mask keeping the
    kernel under a mesh'). Backend gate forced so the routing logic is
    exercised on the CPU mesh (kernel runs interpreted)."""
    from jax.sharding import Mesh
    from paddle_tpu.core import mesh as mesh_lib
    from paddle_tpu.nn.functional import attention as attn_mod
    b, s, h, d = 4, 256, 4, 32
    q = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    mask3 = _padding_mask(b, s, s, [s, 200, 128, 96])
    monkeypatch.setattr(attn_mod, "_flash_backend_ok", lambda: True)
    calls = []
    orig = attn_mod._flash_sharded
    monkeypatch.setattr(
        attn_mod, "_flash_sharded",
        lambda *a, **kw: calls.append(kw) or orig(*a, **kw))
    mesh = Mesh(np.array(jax.devices()).reshape(2, 2, 2), ("dp", "pp", "mp"))
    ref = attn_mod._xla_attention(q, q, q, attn_mask=mask3)
    with mesh_lib.use_mesh(mesh):
        out = attn_mod.scaled_dot_product_attention(q, q, q, attn_mask=mask3)
    assert calls and calls[0]["mask"] is not None
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_float_tracer_mask_keeps_gradient(monkeypatch):
    """A float additive mask being differentiated (a tracer, e.g. learned
    ALiBi) must NOT route into the kernel (whose mask is stop_gradient'd) —
    the XLA path keeps the bias gradient alive. Bool masks carry no
    gradient and stay on the kernel."""
    from paddle_tpu.nn.functional import attention as attn_mod
    monkeypatch.setattr(attn_mod, "_flash_backend_ok", lambda: True)
    b, s, h, d = 1, 256, 2, 32
    q = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    bias = jnp.asarray(RNG.standard_normal((s, s)) * 0.1, jnp.float32)

    def loss(bias):
        out = attn_mod.scaled_dot_product_attention(q, q, q, attn_mask=bias)
        return jnp.sum(jnp.sin(out))

    g = jax.grad(loss)(bias)  # bias is a tracer inside grad
    assert float(jnp.max(jnp.abs(g))) > 0.0  # grad flows (XLA path)

    # concrete float bias still allowed on the kernel (eager, no grads)
    calls = []
    orig = attn_mod._flash_sharded
    monkeypatch.setattr(attn_mod, "_flash_sharded",
                        lambda *a, **kw: calls.append(1) or orig(*a, **kw))
    out = attn_mod.scaled_dot_product_attention(q, q, q, attn_mask=bias)
    assert calls and np.isfinite(np.asarray(out)).all()


def test_fully_masked_row_stays_finite():
    """A batch row whose bool mask excludes every key (all-padding dummy
    rows in fixed-size serving batches) must produce FINITE output on the
    XLA path (uniform softmax), not NaN."""
    from paddle_tpu.nn.functional.attention import _xla_attention
    b, s, h, d = 2, 8, 2, 16
    q = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    mask = np.ones((b, s, s), bool)
    mask[1] = False  # row 1 fully padded
    out = _xla_attention(q, q, q, attn_mask=jnp.asarray(mask))
    assert np.isfinite(np.asarray(out)).all()
