"""Pallas flash-attention contract tests (parity: the reference FA2 contract,
SURVEY §B.7) — run in interpret mode on CPU."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas.flash_attention import (
    flash_attention, flash_attention_with_lse)

RNG = np.random.default_rng(7)


def ref_attn(q, k, v, causal):
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / math.sqrt(d)
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        s = jnp.where(jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq), s, -jnp.inf)
    p = jax.nn.softmax(s, -1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)


@pytest.mark.parametrize("b,s,h,d,causal", [
    (2, 256, 2, 64, False),
    (2, 256, 2, 64, True),
    (1, 128, 4, 128, True),
    (1, 384, 1, 64, True),  # seq not a multiple of 256 -> bk fallback
])
def test_forward_matches_reference(b, s, h, d, causal):
    q = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    out = flash_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_attn(q, k, v, causal)),
                               rtol=1e-4, atol=1e-4)


def test_gradients_match_reference():
    b, s, h, d = 1, 256, 2, 64
    q = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    f1 = lambda q, k, v: jnp.sum(jnp.sin(flash_attention(q, k, v, causal=True)))
    f2 = lambda q, k, v: jnp.sum(jnp.sin(ref_attn(q, k, v, True)))
    g1 = jax.grad(f1, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f2, argnums=(0, 1, 2))(q, k, v)
    for a, b_, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-3,
                                   atol=1e-3, err_msg=f"d{name}")


def test_lse_contract():
    b, s, h, d = 1, 128, 2, 64
    q = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    out, lse = flash_attention_with_lse(q, q, q, causal=True)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, q) / math.sqrt(d)
    scores = jnp.where(jnp.tril(jnp.ones((s, s), bool)), scores, -jnp.inf)
    want = jax.scipy.special.logsumexp(scores, -1)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(want), rtol=1e-5,
                               atol=1e-5)
    assert lse.shape == (b, h, s)


def test_bf16_inputs():
    b, s, h, d = 1, 256, 2, 64
    q = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.bfloat16)
    out = flash_attention(q, q, q, causal=True)
    assert out.dtype == jnp.bfloat16
    want = ref_attn(q.astype(jnp.float32), q.astype(jnp.float32),
                    q.astype(jnp.float32), True)
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(want),
                               rtol=5e-2, atol=5e-2)


@pytest.mark.parametrize("sq,sk", [(128, 256), (64, 192), (256, 128)])
def test_causal_bottom_right_alignment(sq, sk):
    """seq_q != seq_k causal must match the FA2 bottom-right convention
    (the XLA reference path: tril with k=sk-sq)."""
    b, h, d = 1, 2, 64
    q = jnp.asarray(RNG.standard_normal((b, sq, h, d)), jnp.float32)
    k = jnp.asarray(RNG.standard_normal((b, sk, h, d)), jnp.float32)
    v = jnp.asarray(RNG.standard_normal((b, sk, h, d)), jnp.float32)
    out = flash_attention(q, k, v, causal=True)
    live = max(sq - sk, 0)  # rows < sq-sk are fully masked: ref gives NaN,
    # flash gives zeros (the safer defined behavior)
    np.testing.assert_allclose(np.asarray(out)[:, live:],
                               np.asarray(ref_attn(q, k, v, True))[:, live:],
                               rtol=1e-4, atol=1e-4)
    if live:
        assert np.all(np.asarray(out)[:, :live] == 0)
    if sq <= sk:  # grads too (sq > sk has fully-masked rows: NaN in the ref)
        g1 = jax.grad(lambda q, k, v: jnp.sum(
            jnp.sin(flash_attention(q, k, v, causal=True))), (0, 1, 2))(q, k, v)
        g2 = jax.grad(lambda q, k, v: jnp.sum(
            jnp.sin(ref_attn(q, k, v, True))), (0, 1, 2))(q, k, v)
        for a, b_, name in zip(g1, g2, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-3,
                                       atol=1e-3, err_msg=f"d{name}")


@pytest.mark.parametrize("causal", [False, True])
def test_ragged_seq_padding(causal):
    """seq not a multiple of the minimum block (8): forward masks padded keys
    in-kernel, backward pads to block multiples (was: silently wrong grads)."""
    b, s, h, d = 1, 130, 2, 64
    q = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    out = flash_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(ref_attn(q, k, v, causal)),
                               rtol=1e-4, atol=1e-4)
    g1 = jax.grad(lambda q, k, v: jnp.sum(
        jnp.sin(flash_attention(q, k, v, causal=causal))), (0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda q, k, v: jnp.sum(
        jnp.sin(ref_attn(q, k, v, causal))), (0, 1, 2))(q, k, v)
    for a, b_, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-3,
                                   atol=1e-3, err_msg=f"d{name}")


def test_jit_and_vmap_compose():
    b, s, h, d = 1, 128, 1, 64
    q = jnp.asarray(RNG.standard_normal((b, s, h, d)), jnp.float32)
    jit_out = jax.jit(lambda q: flash_attention(q, q, q, causal=True))(q)
    np.testing.assert_allclose(np.asarray(jit_out),
                               np.asarray(flash_attention(q, q, q, causal=True)),
                               rtol=1e-5, atol=1e-6)
