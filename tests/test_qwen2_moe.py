"""Qwen2-MoE flagship (parity: the expert-parallel model family, BASELINE
config 5 — routed experts + shared expert, aux loss joins the objective,
trains under the hybrid mesh)."""

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt
from paddle_tpu.models.qwen2_moe import (Qwen2MoeConfig, Qwen2MoeForCausalLM,
                                         qwen2_moe_tiny)

RNG = np.random.default_rng(0)


def test_forward_shapes_and_aux_loss():
    pt.seed(0)
    cfg = qwen2_moe_tiny(mp_axis=None, fsdp_axis=None, ep_axis=None)
    model = Qwen2MoeForCausalLM(cfg)
    ids = jnp.asarray(RNG.integers(0, cfg.vocab_size, (2, 16)))
    logits = model(ids)
    assert logits.shape == (2, 16, cfg.vocab_size)
    aux = float(model.aux_loss())
    assert np.isfinite(aux) and aux > 0  # router balance loss accumulated
    loss = model.loss(logits, ids)
    assert np.isfinite(float(loss))


def test_dense_interleave():
    """decoder_sparse_step=2: alternate dense/sparse layers."""
    pt.seed(1)
    cfg = qwen2_moe_tiny(mp_axis=None, fsdp_axis=None, ep_axis=None,
                         decoder_sparse_step=2)
    model = Qwen2MoeForCausalLM(cfg)
    sparse_flags = [l.is_sparse for l in model.layers]
    assert sparse_flags == [False, True]


def test_trains_and_loss_decreases():
    pt.seed(2)
    cfg = qwen2_moe_tiny(mp_axis=None, fsdp_axis=None, ep_axis=None)
    model = Qwen2MoeForCausalLM(cfg)
    opt = pt.optimizer.AdamW(learning_rate=5e-3, parameters=model)
    step = pt.jit.TrainStep(model, opt,
                            lambda logits, labels: model.loss(logits, labels))
    ids = RNG.integers(0, cfg.vocab_size, (4, 16))
    losses = [float(step(ids, ids)) for _ in range(8)]
    assert losses[-1] < losses[0] - 0.3, losses


def test_trains_on_hybrid_mesh_with_expert_sharding():
    """Expert weights sharded on the mp axis (the EP mapping): one step on
    a dp x mp mesh must run and produce a finite loss."""
    from jax.sharding import Mesh
    from paddle_tpu.core import mesh as mesh_lib
    from paddle_tpu.distributed.fleet.meta_parallel import apply_hybrid_shardings
    pt.seed(3)
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("dp", "mp"))
    with mesh_lib.use_mesh(mesh):
        cfg = qwen2_moe_tiny(fsdp_axis=None)   # mp + ep active
        model = Qwen2MoeForCausalLM(cfg)
        model = apply_hybrid_shardings(model, mesh)
        opt = pt.optimizer.AdamW(learning_rate=1e-3, parameters=model)
        step = pt.jit.TrainStep(model, opt,
                                lambda lg, lb: model.loss(lg, lb))
        ids = RNG.integers(0, cfg.vocab_size, (4, 16))
        loss = float(step(ids, ids))
        assert np.isfinite(loss)
