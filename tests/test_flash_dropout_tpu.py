"""In-kernel flash dropout tests — TPU-ONLY (pltpu.prng_* has no CPU
interpret lowering; VERDICT r2 item 4). The whole module skips on the CPU
mesh; the bench driver environment has a real chip, and
tools/run_tpu_checks.py executes this file there.

Checks (parity contract flash_attn_kernel.cu:250):
  - statistical: dropout is unbiased (E[out] == no-dropout out) and actually
    drops (outputs differ);
  - determinism: same (seed, offset) -> bitwise-identical out AND grads;
    different seed -> different out;
  - gradient: FD check through the kernel with a fixed seed (the mask is
    deterministic, so finite differences are valid).
"""

import os

import numpy as np
import pytest

# This file must NOT import the CPU-forcing conftest behavior: it runs under
# tools/run_tpu_checks.py with the real backend. Under the normal suite the
# conftest pins CPU and everything here skips.
import jax

if jax.default_backend() != "tpu":
    pytest.skip("in-kernel flash dropout is TPU-only", allow_module_level=True)

import jax.numpy as jnp

from paddle_tpu.ops.pallas.flash_attention import flash_attention


def _qkv(b=1, s=512, h=4, d=64, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.standard_normal((b, s, h, d)) * 0.3,
                             jnp.float32)
    return mk(), mk(), mk()


def test_dropout_unbiased_and_active():
    q, k, v = _qkv()
    base = flash_attention(q, k, v, causal=True)
    dropped = flash_attention(q, k, v, causal=True, dropout_p=0.2,
                              fixed_seed_offset=(7, 0))
    diff = float(jnp.mean(jnp.abs(dropped - base)))
    assert diff > 1e-4  # dropout actually happened
    # unbiasedness: the average over independent seeds converges to the
    # no-dropout output (each mask is unbiased after the 1/(1-p) rescale)
    acc = jnp.zeros_like(base)
    n_seeds = 8
    for s_ in range(n_seeds):
        acc = acc + flash_attention(q, k, v, causal=True, dropout_p=0.2,
                                    fixed_seed_offset=(100 + s_, s_))
    rel_one = diff / max(float(jnp.mean(jnp.abs(base))), 1e-9)
    rel_avg = (float(jnp.mean(jnp.abs(acc / n_seeds - base)))
               / max(float(jnp.mean(jnp.abs(base))), 1e-9))
    assert rel_avg < rel_one / 2, (rel_one, rel_avg)  # ~1/sqrt(8) shrink
    assert rel_avg < 0.25, rel_avg


def test_dropout_deterministic_replay():
    q, k, v = _qkv(seed=1)
    f = lambda seed: flash_attention(q, k, v, causal=True, dropout_p=0.3,
                                     fixed_seed_offset=seed)
    o1 = f((123, 4))
    o2 = f((123, 4))
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    o3 = f((124, 4))
    assert float(jnp.max(jnp.abs(o1 - o3))) > 1e-4

    g = lambda seed: jax.grad(lambda q_: jnp.sum(
        flash_attention(q_, k, v, causal=True, dropout_p=0.3,
                        fixed_seed_offset=seed)))(q)
    g1, g2 = g((123, 4)), g((123, 4))
    np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))


def test_dropout_grads_match_finite_differences():
    # small shapes; fixed seed makes the dropped network a deterministic
    # function, so central differences apply
    q, k, v = _qkv(b=1, s=256, h=1, d=64, seed=2)
    seed = (55, 1)

    def loss(q_, k_, v_):
        out = flash_attention(q_, k_, v_, causal=True, dropout_p=0.25,
                              fixed_seed_offset=seed)
        return jnp.sum(out * jnp.cos(jnp.arange(out.size).reshape(out.shape)
                                     * 0.01))

    gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    rng = np.random.default_rng(0)
    eps = 1e-2
    for name, x, gx in (("q", q, gq), ("k", k, gk), ("v", v, gv)):
        flat = np.asarray(x).ravel()
        for _ in range(4):
            idx = rng.integers(0, flat.size)
            e = np.zeros_like(flat)
            e[idx] = eps
            xp = jnp.asarray((flat + e).reshape(x.shape))
            xm = jnp.asarray((flat - e).reshape(x.shape))
            args_p = {"q": (xp, k, v), "k": (q, xp, v), "v": (q, k, xp)}[name]
            args_m = {"q": (xm, k, v), "k": (q, xm, v), "v": (q, k, xm)}[name]
            num = (float(loss(*args_p)) - float(loss(*args_m))) / (2 * eps)
            ana = float(np.asarray(gx).ravel()[idx])
            assert abs(num - ana) < 5e-2 + 0.1 * abs(num), (name, num, ana)


def test_dropout_composes_with_attn_mask_in_kernel():
    """mask + dropout ride the SAME tiled kernel (round-4: the r3 wrapper
    forbade the combination although the kernels were fully plumbed)."""
    q, k, v = _qkv(s=256)
    rng = np.random.default_rng(3)
    mask = jnp.asarray(
        np.where(rng.random((256, 256)) < 0.15, -1e30, 0.0), jnp.float32)

    base = flash_attention(q, k, v, attn_mask=mask)  # bias-only reference

    # fixed seed: bitwise-deterministic out AND grads through the combined
    # path; different seed differs
    def loss(qq, kk, vv, seed):
        out = flash_attention(qq, kk, vv, attn_mask=mask, dropout_p=0.3,
                              fixed_seed_offset=seed)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    g1 = jax.grad(loss, argnums=(0, 1, 2))(q, k, v, (7, 9))
    g2 = jax.grad(loss, argnums=(0, 1, 2))(q, k, v, (7, 9))
    for a, b in zip(g1, g2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert np.isfinite(np.asarray(a, np.float32)).all()
    o1 = flash_attention(q, k, v, attn_mask=mask, dropout_p=0.3,
                         fixed_seed_offset=(7, 9))
    o3 = flash_attention(q, k, v, attn_mask=mask, dropout_p=0.3,
                         fixed_seed_offset=(8, 9))
    assert np.abs(np.asarray(o1) - np.asarray(o3)).max() > 0

    # unbiasedness under the mask: mean over seeds approaches the
    # no-dropout masked output
    acc = np.zeros_like(np.asarray(base), np.float32)
    n = 24
    for s in range(n):
        acc += np.asarray(flash_attention(
            q, k, v, attn_mask=mask, dropout_p=0.3,
            fixed_seed_offset=(s, 0)), np.float32)
    err = np.abs(acc / n - np.asarray(base, np.float32)).mean()
    scale = np.abs(np.asarray(base)).mean()
    assert err < 0.25 * scale, (err, scale)


def test_sdpa_routes_dropout_through_kernel(monkeypatch):
    # s must be >= _FLASH_MIN_SEQ or sdpa silently stays on the XLA path
    import paddle_tpu.nn.functional as F
    from paddle_tpu.nn.functional import attention as attn_mod
    assert 1024 >= attn_mod._flash_min_seq()
    q, k, v = _qkv(s=1024)

    # prove the route: the kernel entry must actually be hit for the
    # training call
    calls = {}
    real_fa = flash_attention

    def spy(*a, **kw):
        calls["dropout_p"] = kw.get("dropout_p", 0.0)
        return real_fa(*a, **kw)

    import paddle_tpu.ops.pallas.flash_attention as fa_mod
    monkeypatch.setattr(fa_mod, "flash_attention", spy)
    out = F.scaled_dot_product_attention(q, k, v, dropout_p=0.1,
                                         is_causal=True, training=True)
    assert out.shape == q.shape
    assert calls.get("dropout_p") == 0.1  # in-kernel route taken
    out_eval = F.scaled_dot_product_attention(q, k, v, dropout_p=0.1,
                                              is_causal=True, training=False)
    base = real_fa(q, k, v, causal=True)
    # kernel runs bf16-class compute on TPU — compare at matching tolerance
    np.testing.assert_allclose(np.asarray(out_eval), np.asarray(base),
                               rtol=2e-2, atol=5e-3)


def test_sharded_dropout_determinism_and_decorrelation():
    """The shard_map dropout rule (VERDICT r4 missing #2): same
    (seed, offset) -> bitwise-identical output through the sharded fn;
    the per-shard offset fold means shard i draws the direct kernel's
    (seed, offset + i) stream — verified on the 1-device mesh where the
    fold contributes axis_index=0 (exactness) and by checking the
    offset+1 stream differs (what shard 1 of a 2-way mesh would draw)."""
    from jax.sharding import Mesh
    from paddle_tpu.nn.functional.attention import _flash_sharded_fn

    q, k, v = _qkv(b=2, s=512, h=4, d=64, seed=3)
    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1), ("dp",))
    fn = _flash_sharded_fn(mesh, ("dp",), (), True, None, 0.2)
    seed = jnp.asarray([11, 5], jnp.int32)
    a = fn(q, k, v, seed)
    b_ = fn(q, k, v, seed)
    assert np.array_equal(np.asarray(a), np.asarray(b_))
    # matches the direct kernel at the same five-tuple base
    direct = flash_attention(q, k, v, causal=True, dropout_p=0.2,
                             fixed_seed_offset=(11, 5))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(direct))
    # a neighbouring shard's stream (offset+1) is a different mask
    other = flash_attention(q, k, v, causal=True, dropout_p=0.2,
                            fixed_seed_offset=(11, 6))
    assert not np.array_equal(np.asarray(a), np.asarray(other))


def test_sdpa_dropout_under_mesh_keeps_kernel():
    """scaled_dot_product_attention with dropout under an active (1-device)
    mesh must not fall back to XLA: the sharded rule now covers dropout."""
    from jax.sharding import Mesh
    from paddle_tpu.core import mesh as mesh_lib
    from paddle_tpu.nn.functional import attention as attn_mod

    q, k, v = _qkv(b=2, s=512, h=4, d=64, seed=4)
    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1), ("dp",))
    calls = []
    orig = attn_mod._flash_sharded

    import unittest.mock as mock
    with mock.patch.object(
            attn_mod, "_flash_sharded",
            side_effect=lambda *a, **kw: calls.append(kw) or orig(*a, **kw)):
        with mesh_lib.use_mesh(mesh):
            out = attn_mod.scaled_dot_product_attention(
                q, k, v, dropout_p=0.1, is_causal=True, training=True)
    assert calls and calls[0].get("dropout_p") == 0.1
    assert np.isfinite(np.asarray(out)).all()
